#!/usr/bin/env bash
# Executable CI pipeline for NDS-TPU (invoked stage-by-stage by
# cicd/ci.yml, runnable locally: `bash cicd/run_ci.sh all`).
#
# Stages:
#   native     - build the C++ data generator and self-check one tiny table
#   resilience - fast smoke of the fault-injection/retry/deadline layer
#   static     - static analysis BEFORE anything executes: the engine-
#                discipline lint (python -m nds_tpu.analysis — frozen plan
#                IR, locked cross-thread writes, lock-order deadlock
#                detection, device-lane purity, typed-error and counter
#                discipline) and the plan-IR verifier sweep (every bundled
#                template through per-pass verification + seeded-corruption
#                mutation tests, tests/test_plan_verify.py)
#   planner    - planner/streaming tier-1: late-materialization legality/
#                differential, capacity-ladder, shared-scan morsel fusion,
#                narrow-lane packed-upload, and observability-layer tests
#                (fast, CPU backend): these rewrites change plans/execution
#                (and the physical upload layout) for every
#                dimension-grouped aggregate and every streamed query, so
#                their SQLite-oracle exactness and bit-identity gates run
#                early and cheaply; the obs suite gates here because the
#                tracer/metrics hooks thread through the same session/
#                streaming paths, and the EXPLAIN ANALYZE suite
#                (tests/test_profile.py: profiled-vs-normal bit-identity,
#                exact per-node rows, cardinality audit, device-memory
#                watermarks) for the same reason
#   encoded    - encoded execution tier-1 (fast differentials): the
#                dictionary/RLE pack/unpack property round trip, streamed
#                on/off bit-identity + numpy-oracle differentials,
#                code-space filter/join/group-by evidence (decode-site
#                counts), verifier "encoding" findings, the sharded
#                (mesh_shards=2) encoded round trip, and the encoding-
#                stats sources (arrow/parquet/view/warehouse-manifest);
#                the SF0.01 SQLite-oracle slice carries the slow marker
#                and runs in the full `test` stage
#   kernels    - Pallas kernel suite in INTERPRET mode (JAX_PLATFORMS=cpu
#                exercises the real kernel bodies of
#                engine/jax_backend/pallas_kernels.py): kernel-vs-XLA
#                bit-identity properties + session-level on/off/oracle
#                differentials; the SF0.01 NDS-query sweeps carry the slow
#                marker and run in the full `test` stage instead, keeping
#                this stage inside the tier-1 time budget
#   mesh       - sharded morsel execution (EngineConfig.mesh_shards) on
#                8 forced virtual CPU devices: sharded-vs-single-chip
#                bit-identity differentials, skewed-morsel edge, pallas-
#                inside-shard_map dispatch, collective accounting
#                (tests/test_mesh_morsels.py); the GSPMD-compile-heavy
#                SF0.01 oracle sweep keeps the slow marker and runs in
#                the full `test` stage so this stage stays in budget
#   service    - concurrent query service (nds_tpu/service): admission
#                control + typed rejection, per-tenant deadlines,
#                batched-dispatch bit-identity vs serial, cross-client
#                program adoption with flat compile counts, concurrent-
#                client and live-config-toggle races, service-backed
#                throughput streams (tests/test_service.py); plus the
#                service-grade observability suite (tests/
#                test_obs_service.py): histogram quantile-error/merge
#                properties, span parent-linkage across the service's
#                thread hops, flight-recorder ring overflow and fault-
#                triggered dumps; the 100-client open-loop run carries
#                the slow marker and runs in the full `test` stage
#   cache      - semantic result cache tier-1: exact-tier hit/miss/
#                generation/TTL semantics, the subsumption proof battery
#                (accepts + adversarial rejects), the IVM differential
#                fast slice (3 LF_*/DF_* functions at SF0.001, cached-
#                updated vs cold-recompute bit-identical), and the
#                service admission wiring (tests/test_result_cache.py);
#                the full 11-function sweep carries the slow marker and
#                runs in the full `test` stage
#   chaos      - chaos-hardened serving: circuit breaker / retry budget /
#                program quarantine / lane watchdog under REAL injected
#                faults, a seeded ~8-client campaign against the live
#                service (0 untyped failures, 0 hash mismatches, flight
#                dump per firing), and the crash-resumable scored
#                lifecycle's checkpoint/resume/score machinery
#                (tests/test_chaos.py + tests/test_lifecycle.py); the
#                100-client campaign and the real SF0.001 kill+resume /
#                chaos lifecycle runs carry the slow marker and run in
#                the full `test` stage
#   adaptive   - adaptive execution tier-1 (tests/test_adaptive.py):
#                feedback-store observation/right-sizing semantics, the
#                q9-class capacity right-size with response-hash identity
#                across sightings, under-observed ceiling-hint overflow
#                re-recording (never mis-answering), the drift sentinel,
#                query-log <-> feedback-store replay equivalence,
#                crash-consistent persistence round trip, the
#                system.plan_feedback surface, and the off-by-default
#                strict-zero counter pins
#   txn        - transactional warehouse tier-1: crash-consistent
#                manifest writes (8-reader torn-read hunt), atomic
#                multi-table commits + rollback + recovery over the
#                _snapshots log, snapshot-pinned reads (read-your-writes
#                writer vs pinned readers, AS OF time travel, rollback
#                CLI, result-cache snapshot keys, system.snapshots), and
#                the seeded chaos-mid-DML campaign through a live
#                QueryService (tests/test_txn.py); the SIGKILL-between-
#                table-commits subprocess run carries the slow marker
#                and runs in the full `test` stage
#   metrics_gate - diff the deterministic gate workload's COUNT-shaped
#                engine counters (compiles, cache hits, morsels, batch
#                sizes...) against cicd/metrics_baseline.json with
#                generous ratio bounds; wall-time metrics are report-
#                only (this host's timing flakes). Catches cache-key /
#                batching / re-trace regressions every bit-identity test
#                is blind to (scripts/metrics_gate.py --update refreshes
#                the baseline after intentional behavior changes)
#   test       - full pytest suite on an 8-virtual-device CPU mesh
#   bench      - quick bench slice (SF 0.01) to catch perf regressions early
#   all        - every stage in order
set -euo pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}"
export NDS_TPU_JIT_PLANS=1
# CI default: verify the fully rewritten plan of every planned statement
# (engine/verify.py). Bench runs measure with verification off; the static
# stage exercises the stricter per-pass mode through the template sweep.
export NDS_TPU_VERIFY_PLANS="${NDS_TPU_VERIFY_PLANS:-final}"

stage_native() {
    make -C "$REPO/native/datagen"
    local out
    out="$(mktemp -d)"
    "$REPO/native/bin/ndsdgen" -scale 0.01 -dir "$out" -table date_dim \
        -parallel 1 -child 1
    # self-check: date_dim is fixed-size (73049 rows) at every SF
    local rows
    rows="$(wc -l < "$out/date_dim.dat")"
    rm -rf "$out"
    [ "$rows" -eq 73049 ] || {
        echo "native self-check failed: date_dim rows=$rows" >&2; exit 1; }
    echo "native OK"
}

stage_resilience() {
    # fast smoke of the resilience layer: supervised streams, per-query
    # deadlines, resume-from-log, and the engine fault registry — these
    # guard the multi-hour runs, so they gate early and cheaply
    (cd "$REPO" && python -m pytest tests/test_resilience.py -q)
}

stage_static() {
    # catch rewrite bugs before they execute: the six-family engine lint
    # (frozen plan IR, cross-thread locking, lock-order deadlock detection,
    # device-lane purity, typed-error + counter discipline — machine-
    # readable findings for the CI log), then sweep every bundled query
    # template through per-pass plan verification
    (cd "$REPO" && python -m nds_tpu.analysis --json nds_tpu)
    (cd "$REPO" && python -m pytest tests/test_plan_verify.py \
        tests/test_lint_engine.py -q)
}

stage_planner() {
    # test_profile.py gates here too: EXPLAIN ANALYZE profiled-vs-normal
    # bit-identity (in-core/streamed/encoded/sharded), per-node row
    # exactness, the cardinality audit, device-memory watermarks, and the
    # metrics-glossary completeness check — the profiling hooks thread
    # through the same planner/session/streaming paths this stage owns
    (cd "$REPO" && python -m pytest tests/test_late_materialization.py \
        tests/test_capacity_ladder.py tests/test_shared_scan.py \
        tests/test_streaming.py tests/test_narrow_lanes.py \
        tests/test_obs.py tests/test_profile.py -q)
}

stage_encoded() {
    # encoded execution: every streamed scan group's dictionary/RLE wire
    # layout must stay bit-identical to the plain narrow-lane path, with
    # joins/group-bys provably running on codes (decode-site counts) and
    # encoding specs proven against recorded stats before a morsel ships
    (cd "$REPO" && python -m pytest tests/test_encoded_exec.py \
        -q -m 'not slow')
}

stage_kernels() {
    # Pallas interpret-mode suite: the real kernel code paths (tiled
    # bitonic sort, fused group-by partials, VMEM-staged gather) proven
    # bit-identical to the XLA lowering before anything measures them
    (cd "$REPO" && python -m pytest tests/test_pallas_kernels.py \
        -q -m 'not slow')
}

stage_mesh() {
    # sharded morsel execution: every streamed scan group dispatched over
    # the virtual 8-device mesh must stay bit-identical to the single-chip
    # path at every shard count (the conftest forces the device count)
    (cd "$REPO" && python -m pytest tests/test_mesh_morsels.py \
        -q -m 'not slow')
}

stage_service() {
    # concurrent query service: every response a client receives must be
    # bit-identical to a fresh single-caller session running the same SQL
    # — through batched dispatches, the serial lane, deadline-expired
    # neighbors, and live config toggles; the service-observability suite
    # (histograms, trace propagation, flight recorder) gates here because
    # its hooks thread through the same service stages, and the
    # system-tables + query-log suite (tests/test_system_tables.py:
    # frozen schemas, ring<->JSONL equivalence, atomic snapshot cuts,
    # the service's system.* admission bypass with strict-zero counter
    # pins, rotation/retention, slo_report + metrics_server CLIs) for
    # the same reason
    (cd "$REPO" && python -m pytest tests/test_service.py \
        tests/test_obs_service.py tests/test_system_tables.py \
        -q -m 'not slow')
}

stage_cache() {
    # semantic result cache: every tier must be bit-identical to
    # recompute — exact hits, re-filtered coarser aggregates after a
    # containment proof, and partials updated in place across LF_*/DF_*
    # maintenance deltas (counts-based pins; wall times never gate here)
    (cd "$REPO" && python -m pytest tests/test_result_cache.py \
        -q -m 'not slow')
}

stage_chaos() {
    # resilience as a verified property of the WHOLE stack: typed
    # degradation, bit-stable completions, and self-healing (breaker,
    # retry budget, quarantine, watchdog) under armed fault points with
    # concurrent clients in flight, plus lifecycle resume determinism
    (cd "$REPO" && python -m pytest tests/test_chaos.py \
        tests/test_lifecycle.py -q -m 'not slow')
}

stage_frontdoor() {
    # cross-process distributed serving: the Arrow-IPC wire protocol
    # (frame codec bounds, typed-error reconstruction, real OS-process
    # round trips, engine-kill + connection-drop chaos), weighted-fair
    # scheduling with morsel-boundary preemption (bit-identity preserved
    # mid-preemption), in-flight dedup, the cross-process result-cache
    # snapshot/invalidation handshake, and the off-mode strict-zero pins.
    # The integration half of the file is marked slow to keep it out of
    # the tier-1 selection; THIS stage is where it runs, so no marker
    # filter here.
    (cd "$REPO" && python -m pytest tests/test_frontdoor.py -q)
}

stage_adaptive() {
    # adaptive execution: observed actuals may right-size capacity
    # schedules and flip planner decisions, but every adapted response
    # must stay bit-identical to the unadapted one, an under-observed
    # hint must cost a re-record (never a wrong answer), and the default
    # (off) path must move zero feedback counters
    (cd "$REPO" && python -m pytest tests/test_adaptive.py -q -m 'not slow')
}

stage_txn() {
    # the transactional warehouse's headline invariant, verified: no
    # torn manifest, no cross-table blend of two warehouse versions, and
    # every kill window (fault-aborted commits, dead-writer recovery)
    # lands on exactly the pre- or post-commit snapshot
    (cd "$REPO" && python -m pytest tests/test_txn.py -q -m 'not slow')
}

stage_metrics_gate() {
    # count-shaped counter diff vs the checked-in baseline: compiles,
    # cache hits, morsel/batch counts must stay in band on the fixed
    # workload (wall-time metrics report-only — CI hosts flake)
    (cd "$REPO" && python scripts/metrics_gate.py)
}

stage_test() {
    (cd "$REPO" && python -m pytest tests/ -q --durations=15)
}

stage_bench() {
    local d
    d="$(mktemp -d)"
    # bench measures raw engine time: plan verification off
    (cd "$REPO" && NDS_TPU_BENCH_DIR="$d" NDS_TPU_BENCH_SF=0.01 \
        NDS_TPU_VERIFY_PLANS=off \
        NDS_TPU_BENCH_QUERIES=query3,query7 python bench.py)
    rm -rf "$d"
}

# run one stage with wall-time accounting: every CI line ends with a
# "stage <name>: <seconds>s" marker, so slow stages are attributable from
# any runner's log without extra tooling
run_stage() {
    local name="$1"
    local t0=$SECONDS
    "stage_${name}"
    echo "stage ${name}: $((SECONDS - t0))s"
}

case "${1:-all}" in
    native|resilience|static|planner|encoded|kernels|mesh|service|cache|chaos|frontdoor|adaptive|txn|metrics_gate|test|bench)
        run_stage "$1" ;;
    all)
        total0=$SECONDS
        for s in native resilience static planner encoded kernels mesh \
                 service cache chaos frontdoor adaptive txn metrics_gate \
                 test bench; do
            run_stage "$s"
        done
        echo "stage all: $((SECONDS - total0))s" ;;
    --list)     echo "native resilience static planner encoded kernels mesh service cache chaos frontdoor adaptive txn metrics_gate test bench all" ;;
    *) echo "usage: run_ci.sh [native|resilience|static|planner|encoded|kernels|mesh|service|cache|chaos|frontdoor|adaptive|txn|metrics_gate|test|bench|all|--list]" >&2
       exit 2 ;;
esac
