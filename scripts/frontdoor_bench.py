#!/usr/bin/env python
"""Distributed-serving bench -> FRONTDOOR_r01.json (the PR acceptance
artifact): interactive p99 under weighted-fair + morsel-boundary
preemption vs the FIFO baseline, measured across REAL OS process
boundaries.

The shape (all against the chaos demo dataset — fact/dim in-core at
``out_of_core_min_rows=30_000``, sfact parquet streamed):

1. **Serial baseline** — a fresh in-process Session hashes every
   distinct workload statement (the canonical engine-table hash the
   server ships per response); every wire response in every phase must
   match bit-for-bit.
2. **In-process reference** — the same mixed workload through
   ``QueryService.submit`` directly (threads, no wire): the QPS
   ceiling the front door is compared against.
3. **FIFO phase** — one engine process behind the Arrow-IPC front
   door, scheduler flags off. Two WORKER PROCESSES (spawned copies of
   this script with ``--worker``) run 50 client threads each: the
   ``interactive`` tenant paces short in-core lookups while the
   ``batch`` tenant saturates the device lane with streamed scans —
   the convoy: every interactive arrival queues behind every
   already-queued scan.
4. **Fair phase** — identical workload, identical engine config, the
   server restarted with ``--fair_queue --tenant_weights
   interactive=4,batch=1 --preemption``: per-tenant weighted deficit
   queues + streamed queries yielding the lane between scan groups.
5. Both phases read per-tenant latency from ``system.query_log`` OVER
   THE WIRE (the server runs ``--query_log``) — the engine reports its
   own p99, the bench never trusts client clocks for the headline.
6. **Chaos round** — ``nds_tpu.chaos.run_topology_campaign``:
   connection drops, one engine-process kill mid-query (exit 86), a
   replacement server, and the stale-cache invariant (a snapshot
   warmed from the dead epoch must validate False, re-fetch, and still
   hash-identical).

Workers synchronize on a stdin GO line after connecting all sockets,
so measured wall excludes interpreter/import/connect cost; each server
is warmed (every distinct statement, tenant ``warmup``) before the
measured window, so the phases compare scheduling, not compilation.

Usage:
  python scripts/frontdoor_bench.py                  # full acceptance run
  python scripts/frontdoor_bench.py --quick          # small smoke shape
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: identical engine shape for baseline/in-process/servers: fact (20k
#: rows) stays in-core (batched dispatch), sfact (60k rows) streams in
#: 4096-row morsels — the preemption yield points
ENGINE_KW = dict(chunk_rows=4096, out_of_core_min_rows=30_000)
TENANT_WEIGHTS = "interactive=4,batch=1"


def build_workload(seed: int, n_interactive: int, n_batch: int,
                   q_interactive: int, q_batch: int) -> dict:
    """Seeded per-thread query lists for both tenants (the same lists
    replay against FIFO, fair, and the in-process reference)."""
    import random

    from nds_tpu.chaos import demo_pool

    pool = demo_pool()
    incore = [p for p in pool if p[0].startswith("incore")]
    streamed = [p for p in pool if p[0].startswith("streamed")]
    rng = random.Random(seed)
    return {
        "interactive": {
            str(i): [list(incore[rng.randrange(len(incore))])
                     for _ in range(q_interactive)]
            for i in range(n_interactive)},
        "batch": {
            str(i): [list(streamed[rng.randrange(len(streamed))])
                     for _ in range(q_batch)]
            for i in range(n_batch)},
    }


def distinct_sqls(workload: dict) -> list:
    out = []
    for threads in workload.values():
        for queries in threads.values():
            for _label, sql in queries:
                if sql not in out:
                    out.append(sql)
    return out


# -- worker process mode ----------------------------------------------------

def run_worker(cfg_path: str) -> int:
    """One OS client process: N threads, one FlightClient socket each,
    replaying this worker's query lists against the server and checking
    every response hash against the serial baseline. Prints WORKERREADY
    once every socket is connected, blocks on a stdin GO line, then
    prints one WORKERRESULT json line."""
    from nds_tpu.obs.metrics import exact_quantile
    from nds_tpu.service.frontdoor import FlightClient

    with open(cfg_path) as f:
        cfg = json.load(f)
    tenant = cfg["tenant"]
    baseline = cfg["baseline"]
    pace_s = float(cfg.get("pace_s") or 0.0)
    clients = {tid: FlightClient("127.0.0.1", cfg["port"], retries=3)
               for tid in cfg["threads"]}
    for c in clients.values():
        c.ping()
    print("WORKERREADY", flush=True)
    sys.stdin.readline()          # the GO barrier

    lock = threading.Lock()
    state = {"completed": 0, "checked": 0, "mismatches": 0,
             "failed": {}, "untyped": [], "lat_ms": []}

    def client(tid: str, queries: list) -> None:
        c = clients[tid]
        for label, sql in queries:
            t0 = time.perf_counter()
            try:
                _table, hdr = c.query(sql, tenant=tenant, label=label,
                                      want_hash=True)
            except Exception as e:
                from nds_tpu.chaos import is_typed
                with lock:
                    if is_typed(e):
                        name = type(e).__name__
                        state["failed"][name] = \
                            state["failed"].get(name, 0) + 1
                    else:
                        state["untyped"].append(
                            f"{label}: {type(e).__name__}: {e}")
                continue
            ms = (time.perf_counter() - t0) * 1000.0
            with lock:
                state["completed"] += 1
                state["lat_ms"].append(ms)
                if sql in baseline:
                    state["checked"] += 1
                    if hdr.get("result_hash") != baseline[sql]:
                        state["mismatches"] += 1
            if pace_s:
                time.sleep(pace_s)
        c.close()

    threads = [threading.Thread(target=client, args=(tid, qs),
                                name=f"bench-{tenant}-{tid}", daemon=True)
               for tid, qs in cfg["threads"].items()]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    lat = sorted(state["lat_ms"])
    print("WORKERRESULT " + json.dumps({
        "tenant": tenant, "threads": len(threads),
        "wall_s": round(time.perf_counter() - t0, 3),
        "completed": state["completed"], "checked": state["checked"],
        "mismatches": state["mismatches"], "failed": state["failed"],
        "untyped": state["untyped"][:10],
        "untyped_count": len(state["untyped"]),
        "client_p50_ms": round(exact_quantile(lat, 0.50), 2) if lat else 0,
        "client_p99_ms": round(exact_quantile(lat, 0.99), 2) if lat else 0,
    }), flush=True)
    return 0


# -- parent orchestration ---------------------------------------------------

def _warm(port: int, sqls: list) -> None:
    """Compile every distinct statement before the measured window
    (tenant 'warmup' rows are excluded from the per-tenant log stats)."""
    from nds_tpu.service.frontdoor import FlightClient

    c = FlightClient("127.0.0.1", port)
    for sql in sqls:
        for _ in range(2):
            c.sql(sql, tenant="warmup", label="warmup")
    c.close()


def _log_stats(port: int) -> dict:
    """Per-tenant latency FROM THE ENGINE: SQL over system.query_log
    through the same wire the workload used."""
    from nds_tpu.obs.metrics import exact_quantile
    from nds_tpu.service.frontdoor import FlightClient

    c = FlightClient("127.0.0.1", port)
    rows = c.sql("SELECT tenant, status, wall_ms, queue_ms, exec_ms, "
                 "preempted FROM system.query_log",
                 tenant="bench", label="log_read").to_pylist()
    c.close()
    out = {}
    for tenant in ("interactive", "batch"):
        mine = [r for r in rows if r["tenant"] == tenant]
        lat = sorted(r["wall_ms"] for r in mine
                     if r["wall_ms"] is not None)
        qs = [r["queue_ms"] or 0.0 for r in mine]
        if not mine:
            continue
        out[tenant] = {
            "count": len(mine),
            "errors": sum(1 for r in mine if r["status"] != "ok"),
            "p50_ms": round(exact_quantile(lat, 0.50), 2) if lat else 0,
            "p95_ms": round(exact_quantile(lat, 0.95), 2) if lat else 0,
            "p99_ms": round(exact_quantile(lat, 0.99), 2) if lat else 0,
            "mean_queue_ms": round(sum(qs) / len(qs), 2) if qs else 0,
            "preempted": sum(int(r["preempted"] or 0) for r in mine),
        }
    return out


def run_wire_phase(name: str, server_flags: list, workload: dict,
                   baseline: dict, pace: dict, tmp: str) -> dict:
    """Spawn one engine server + one worker PROCESS per tenant, release
    them together, and report engine-side + client-side stats."""
    from nds_tpu.chaos import _spawn_frontdoor

    base = ["--demo", "--query_log",
            "--chunk_rows", str(ENGINE_KW["chunk_rows"]),
            "--out_of_core_min_rows",
            str(ENGINE_KW["out_of_core_min_rows"])]
    proc, info = _spawn_frontdoor(base + server_flags)
    port = info["port"]
    workers = []
    try:
        _warm(port, distinct_sqls(workload))
        for tenant, threads in workload.items():
            cfg_path = os.path.join(tmp, f"{name}_{tenant}.json")
            with open(cfg_path, "w") as f:
                json.dump({"port": port, "tenant": tenant,
                           "threads": threads, "baseline": baseline,
                           "pace_s": pace.get(tenant, 0.0)}, f)
            w = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__),
                 "--worker", cfg_path],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
            workers.append((tenant, w))
        for _tenant, w in workers:          # all sockets connected?
            line = w.stdout.readline()
            if not line.startswith("WORKERREADY"):
                raise RuntimeError(f"worker failed to start: {line!r}")
        t0 = time.perf_counter()
        for _tenant, w in workers:          # the GO barrier
            w.stdin.write("GO\n")
            w.stdin.flush()
        results = {}
        for tenant, w in workers:
            line = w.stdout.readline()
            while line and not line.startswith("WORKERRESULT "):
                line = w.stdout.readline()
            if not line:
                raise RuntimeError(f"worker {tenant} died without result")
            results[tenant] = json.loads(line.split(" ", 1)[1])
        wall = time.perf_counter() - t0
        engine = _log_stats(port)
    finally:
        for _tenant, w in workers:
            try:
                w.stdin.close()
                w.wait(timeout=30)
            except Exception:
                w.kill()
        try:
            proc.stdin.close()
            proc.wait(timeout=30)
        except Exception:
            proc.kill()
    completed = sum(r["completed"] for r in results.values())
    return {"phase": name, "server": info, "wall_s": round(wall, 3),
            "completed": completed,
            "qps": round(completed / wall, 2) if wall else 0.0,
            "engine_log": engine, "workers": results}


def run_inproc_reference(workload: dict, pace: dict, tmp: str) -> dict:
    """The same mixed workload through QueryService.submit in ONE
    process (fair + preemption armed): the no-wire QPS reference."""
    from nds_tpu.chaos import build_demo_session
    from nds_tpu.service import QueryService, ServiceConfig

    session = build_demo_session(os.path.join(tmp, "inproc"), **ENGINE_KW)
    weights = dict(p.split("=") for p in TENANT_WEIGHTS.split(","))
    svc = QueryService(session, ServiceConfig(
        fair_queue=True,
        tenant_weights={k: float(v) for k, v in weights.items()},
        preemption=True, preempt_max=4))
    svc.start()
    try:
        for sql in distinct_sqls(workload):
            for _ in range(2):
                svc.submit(sql, tenant="warmup").result(timeout=300)
        lock = threading.Lock()
        state = {"completed": 0, "failed": 0}

        def client(tenant: str, queries: list) -> None:
            pace_s = pace.get(tenant, 0.0)
            for label, sql in queries:
                try:
                    svc.submit(sql, tenant=tenant,
                               label=label).result(timeout=300)
                except Exception:
                    with lock:
                        state["failed"] += 1
                    continue
                with lock:
                    state["completed"] += 1
                if pace_s:
                    time.sleep(pace_s)

        threads = [threading.Thread(target=client, args=(tenant, qs),
                                    daemon=True)
                   for tenant, per_thread in workload.items()
                   for qs in per_thread.values()]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
    finally:
        svc.close()
    return {"clients": len(threads), "wall_s": round(wall, 3),
            "completed": state["completed"], "failed": state["failed"],
            "qps": round(state["completed"] / wall, 2) if wall else 0.0}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="frontdoor_bench.py", description=(
        "mixed-traffic front-door bench: FIFO vs weighted-fair + "
        "preemption across OS process boundaries -> FRONTDOOR_r01.json"))
    p.add_argument("--worker", default=None, metavar="CFG_JSON",
                   help=argparse.SUPPRESS)   # internal: client process
    p.add_argument("--seed", type=int, default=0xC0FFEE)
    p.add_argument("--interactive_clients", type=int, default=50)
    p.add_argument("--batch_clients", type=int, default=50)
    p.add_argument("--interactive_queries", type=int, default=6,
                   help="paced in-core lookups per interactive thread")
    p.add_argument("--batch_queries", type=int, default=4,
                   help="back-to-back streamed scans per batch thread")
    p.add_argument("--pace_s", type=float, default=0.05,
                   help="interactive think time between queries")
    p.add_argument("--quick", action="store_true",
                   help="small smoke shape (8+8 clients, no chaos)")
    p.add_argument("--skip_chaos", action="store_true")
    p.add_argument("--out", default=os.path.join(REPO,
                                                 "FRONTDOOR_r01.json"))
    a = p.parse_args(argv)
    if a.worker:
        return run_worker(a.worker)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if a.quick:
        a.interactive_clients = a.batch_clients = 8
        a.interactive_queries, a.batch_queries = 3, 2
        a.skip_chaos = True

    from nds_tpu.chaos import (TOPOLOGY_POINTS, CampaignSpec,
                               build_demo_session, result_hash,
                               run_topology_campaign)

    tmp = tempfile.mkdtemp(prefix="frontdoor_bench_")
    workload = build_workload(a.seed, a.interactive_clients,
                              a.batch_clients, a.interactive_queries,
                              a.batch_queries)
    pace = {"interactive": a.pace_s, "batch": 0.0}

    # 1. serial baseline hashes (fresh session, same engine shape)
    t0 = time.perf_counter()
    base_session = build_demo_session(os.path.join(tmp, "baseline"),
                                      **ENGINE_KW)
    baseline = {sql: result_hash(base_session.sql(sql))
                for sql in distinct_sqls(workload)}
    baseline_s = round(time.perf_counter() - t0, 3)
    print(f"frontdoor_bench: serial baseline hashed "
          f"{len(baseline)} statements in {baseline_s}s", file=sys.stderr)

    # 2. in-process QPS reference
    inproc = run_inproc_reference(workload, pace, tmp)
    print(f"frontdoor_bench: in-process reference "
          f"{inproc['qps']} qps", file=sys.stderr)

    # 3/4. the wire phases: FIFO baseline, then fair + preemption
    fifo = run_wire_phase("fifo", [], workload, baseline, pace, tmp)
    print(f"frontdoor_bench: fifo phase {fifo['qps']} qps, interactive "
          f"p99 {fifo['engine_log']['interactive']['p99_ms']} ms",
          file=sys.stderr)
    fair = run_wire_phase(
        "fair", ["--fair_queue", "--tenant_weights", TENANT_WEIGHTS,
                 "--preemption", "--preempt_max", "4"],
        workload, baseline, pace, tmp)
    print(f"frontdoor_bench: fair phase {fair['qps']} qps, interactive "
          f"p99 {fair['engine_log']['interactive']['p99_ms']} ms",
          file=sys.stderr)

    # 6. chaos over the topology: drop + engine kill + recovery
    chaos = None
    if not a.skip_chaos:
        spec = CampaignSpec(seed=a.seed, clients=8, queries_per_client=6,
                            points=TOPOLOGY_POINTS, probability=0.35,
                            times_per_point=2)
        chaos = run_topology_campaign(spec, os.path.join(tmp, "chaos"))
        print(f"frontdoor_bench: chaos invariants "
              f"{chaos['invariants']}", file=sys.stderr)

    p99_fifo = fifo["engine_log"]["interactive"]["p99_ms"]
    p99_fair = fair["engine_log"]["interactive"]["p99_ms"]
    mism = sum(r["mismatches"] for ph in (fifo, fair)
               for r in ph["workers"].values())
    checked = sum(r["checked"] for ph in (fifo, fair)
                  for r in ph["workers"].values())
    record = {
        "schema_version": 1,
        "config": {
            "seed": a.seed, "engine": dict(ENGINE_KW),
            "tenant_weights": TENANT_WEIGHTS,
            "interactive_clients": a.interactive_clients,
            "batch_clients": a.batch_clients,
            "clients_total": a.interactive_clients + a.batch_clients,
            "client_processes": 2,
            "interactive_queries": a.interactive_queries,
            "batch_queries": a.batch_queries, "pace_s": a.pace_s},
        "serial_baseline": {"statements": len(baseline),
                            "wall_s": baseline_s},
        "inproc": inproc,
        "phases": {"fifo": fifo, "fair": fair},
        "comparison": {
            "interactive_p99_fifo_ms": p99_fifo,
            "interactive_p99_fair_ms": p99_fair,
            "interactive_p99_speedup": round(p99_fifo / p99_fair, 2)
            if p99_fair else None,
            "preemptions":
                fair["engine_log"]["batch"]["preempted"],
            "wire_qps_vs_inproc": round(fair["qps"] / inproc["qps"], 3)
            if inproc["qps"] else None},
        "hash_identity": {"checked": checked, "mismatches": mism},
        "chaos": chaos,
        "invariants": {
            "interactive_p99_improved": p99_fair < p99_fifo,
            "all_hashes_identical": mism == 0 and checked > 0,
            "preemption_observed":
                fair["engine_log"]["batch"]["preempted"] > 0,
            "multiprocess": True,
            **({f"chaos_{k}": v
                for k, v in chaos["invariants"].items()} if chaos
               else {}),
        },
    }
    with open(a.out, "w") as f:
        json.dump(record, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps({"out": a.out, "comparison": record["comparison"],
                      "invariants": record["invariants"]},
                     indent=2, sort_keys=True))
    ok = all(record["invariants"].values())
    print(f"frontdoor_bench: {'OK' if ok else 'INVARIANT FAILURES'}",
          file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
