#!/usr/bin/env python
"""One-command scored lifecycle run (nds_tpu/lifecycle).

Runs the reference's full deliverable — datagen -> load -> stream gen ->
power -> throughput x2 -> maintenance x2 -> geometric-mean score — with
per-phase checkpointing in <report_dir>/lifecycle_state.json. A crash
(or injected fault) mid-run resumes from the last completed phase with
--resume; the power phase resumes at query granularity through its
flushed partial time log, and the score is always recomputed from the
phase time logs, so a resumed run's score inputs are identical to an
uninterrupted run's.

Usage:
  python scripts/run_lifecycle.py --sf 0.01 --report_dir ./lifecycle_sf001
  python scripts/run_lifecycle.py --sf 0.01 --report_dir ./lifecycle_sf001 \
      --resume                      # continue after a crash/kill
  python scripts/run_lifecycle.py --sf 0.01 --chaos ...
      # maintenance runs CONCURRENTLY with service-mode query streams
      # under an armed fault campaign; flight dumps land per firing in
      # <report_dir>/flight_round{1,2}
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="run_lifecycle.py", description=(
        "single-command scored NDS lifecycle with per-phase "
        "checkpointing and an optional chaos mode"))
    p.add_argument("--sf", type=float, default=0.01,
                   help="scale factor (0.01 = the CI-sized scored run)")
    p.add_argument("--report_dir", default="./lifecycle_report")
    p.add_argument("--streams", type=int, default=3,
                   help="stream count (odd >= 3; stream 0 = power)")
    p.add_argument("--resume", action="store_true",
                   help="continue a crashed/killed run from its "
                        "lifecycle_state.json checkpoint")
    p.add_argument("--sub_queries", default=None,
                   help="comma-separated query subset for every stream")
    p.add_argument("--warmup", type=int, default=0)
    p.add_argument("--backend", default=None, choices=["jax", "numpy"])
    p.add_argument("--decimal", default=None, choices=["f64", "i64"])
    p.add_argument("--use_decimal", action="store_true",
                   help="load the warehouse with decimal columns")
    p.add_argument("--datagen_parallel", type=int, default=2)
    p.add_argument("--throughput_mode", default="thread",
                   choices=["process", "thread", "service"])
    p.add_argument("--stream_timeout", type=float, default=None)
    p.add_argument("--phase_attempts", type=int, default=1,
                   help="attempts per phase (retries count into the "
                        "lifecycle_phase_retries metric)")
    p.add_argument("--rngseed", type=int, default=None,
                   help="stream-generation seed (default: load end stamp)")
    p.add_argument("--chaos", action="store_true",
                   help="run maintenance concurrently with service-mode "
                        "query streams under an armed fault campaign")
    p.add_argument("--chaos_points", default=None,
                   help="comma list of fault points for --chaos (default "
                        "device.put,jax.compile,jax.execute,query.run)")
    p.add_argument("--chaos_times", type=int, default=2,
                   help="firings cap per armed chaos spec")
    p.add_argument("--query_log", nargs="?", const="", default=None,
                   metavar="PATH",
                   help="enable the durable query log: one flat JSONL "
                        "row per completed statement across every phase "
                        "(bare --query_log defaults to "
                        "<report_dir>/query_log.jsonl); "
                        "scripts/slo_report.py reads it offline")
    p.add_argument("--json", default=None, metavar="PATH",
                   help="also write the final {times, metric} block here")
    a = p.parse_args(argv)

    from nds_tpu.lifecycle import LifecycleConfig, LifecycleRunner

    kwargs = dict(
        scale_factor=a.sf, num_streams=a.streams, report_dir=a.report_dir,
        datagen_parallel=a.datagen_parallel, use_decimal=a.use_decimal,
        decimal=a.decimal, backend=a.backend,
        sub_queries=a.sub_queries.split(",") if a.sub_queries else None,
        warmup=a.warmup, rngseed=a.rngseed,
        throughput_mode=a.throughput_mode, stream_timeout=a.stream_timeout,
        phase_attempts=a.phase_attempts, chaos=a.chaos,
        chaos_times_per_point=a.chaos_times,
        query_log=(a.query_log if a.query_log is not None and a.query_log
                   else (os.path.join(a.report_dir, "query_log.jsonl")
                         if a.query_log is not None else "")))
    if a.chaos_points:
        kwargs["chaos_points"] = tuple(
            x.strip() for x in a.chaos_points.split(",") if x.strip())
    out = LifecycleRunner(LifecycleConfig(**kwargs)).run(resume=a.resume)
    if a.json:
        os.makedirs(os.path.dirname(a.json) or ".", exist_ok=True)
        with open(a.json, "w") as f:
            json.dump(out, f, indent=2, sort_keys=True)
    print(json.dumps(out, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
