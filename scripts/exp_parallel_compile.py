"""Does the axon compile tunnel parallelize concurrent compile RPCs?

Compiles 4 unique never-cached programs serially, then 4 more in 4 threads.
If threaded wall ~= serial wall / 4, parallel compile pre-warming works.
"""
import os
import time
from concurrent.futures import ThreadPoolExecutor

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)

SALT = int(time.time())  # defeat the persistent cache


def make(i):
    k = SALT * 100 + i

    def f(x):
        y = x * k + jnp.sin(x) * (k % 7)
        for j in range(3):
            y = y @ jnp.eye(64, dtype=x.dtype) * (k + j)
        return y.sum()
    return jax.jit(f)


x = jnp.ones((64, 64), jnp.float32)

t0 = time.time()
for i in range(4):
    make(i).lower(x).compile()
serial = time.time() - t0
print(f"serial 4 compiles: {serial:.1f}s")

t0 = time.time()
with ThreadPoolExecutor(4) as ex:
    list(ex.map(lambda i: make(i).lower(x).compile(), range(10, 14)))
par = time.time() - t0
print(f"threaded 4 compiles: {par:.1f}s  speedup {serial/max(par,1e-9):.2f}x")
