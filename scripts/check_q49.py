"""Round-5 check: q49 device ranks must equal the numpy oracle exactly
(no carve-out) now that rank order keys are exact rationals."""
import glob
import os
import sys
import time

from nds_tpu.config import EngineConfig, apply_decimal, enable_x64

enable_x64()
from nds_tpu.engine.session import Session
from nds_tpu.streams import instantiate
from nds_tpu.warehouse import Warehouse


def run(backend: str):
    s = Session(EngineConfig(decimal_physical="i64"))
    Warehouse(".bench_data/sf1_wh").register_all(s)
    sql = [q for q in instantiate(49, 0, 778).split(";") if q.strip()][0]
    t0 = time.time()
    res = s.sql(sql, backend=backend)
    print(f"{backend}: {time.time()-t0:.1f}s, {len(res.columns[0].data)} rows",
          flush=True)
    if backend == "jax" and s.last_fallbacks:
        print("FALLBACKS:", s.last_fallbacks)
        sys.exit(2)
    return res


a = run("numpy")
b = run("jax")
ok = True
for i, (ca, cb) in enumerate(zip(a.columns, b.columns)):
    import numpy as np
    da, db = np.asarray(ca.data), np.asarray(cb.data)
    if ca.dtype == "float":
        same = np.allclose(da, db, rtol=1e-7, atol=1e-9)
    else:
        same = np.array_equal(da, db)
    print(f"col {i} ({ca.dtype}): {'OK' if same else 'MISMATCH'}")
    if not same:
        ok = False
        bad = np.nonzero(da != db)[0][:5]
        print("  rows", bad, da[bad], db[bad])
print("Q49 EXACT PASS" if ok else "Q49 FAIL")
sys.exit(0 if ok else 1)
