#!/usr/bin/env python
"""Per-tenant SLO report over a saved query log — computed BY the engine.

Reads one or more query-log JSONL files (``--query_log`` on power/bench/
run_lifecycle, ``ServiceConfig``-driven service runs, or a rotated set
``log.jsonl.1 log.jsonl.2 log.jsonl``), replays the rows into the
process query-log ring, and computes the report by running SQL over
``system.query_log`` through the engine's own host-only introspection
path — the PyTond move ("on the shoulders of databases"): the analysis
runs INSIDE the engine the log came from, so this script exercises
exactly the operator surface a live ``/query?sql=`` scrape hits.

Reported per tenant (and overall):

- request count, error count/classes, exact p50/p95/p99 wall latency
  (exact — the log holds every row, unlike the ~12%-bounded histogram
  quantiles a live registry serves);
- SLO attainment: fraction of ok-status rows completing within
  ``--slo_ms``, against ``--target`` (e.g. 0.99 = "99% of requests under
  500 ms");
- multi-window burn rates: for each ``--windows`` span ending at the
  log's last row, ``(bad fraction in window) / (1 - target)`` — the
  standard error-budget burn multiple (1.0 = burning exactly the
  budget; >>1 = paging territory; the 5m/1h pair is the classic
  fast+slow multiwindow alert input);
- fairness: queue-wait share (sum queue_ms / sum wall_ms — how much of
  a tenant's perceived latency was spent WAITING for the lane),
  preemption count (sum of the ``preempted`` column: interactive
  tickets served inside this tenant's streamed morsel-boundary yields),
  and weight attainment — (tenant's share of total exec_ms) / (tenant's
  share of total weight, via ``--weights a=4,b=1``; default weight 1).
  Attainment ≈ 1.0 means the weighted-fair scheduler delivered the
  configured share; a saturating tenant >> its weight share under FIFO
  is exactly the convoy the fair queue removes.

Usage:
  python scripts/slo_report.py run/query_log.jsonl
  python scripts/slo_report.py log.jsonl --slo_ms 500 --target 0.99 \
      --windows 300,3600 --json slo.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from nds_tpu.obs.metrics import exact_quantile          # noqa: E402
from nds_tpu.obs.query_log import QUERY_LOG, read_jsonl  # noqa: E402


def _fetch(session, sql: str) -> list[dict]:
    """Run one system.* statement and return its rows as dicts — the
    same host-only path the live scrape endpoint serves."""
    from nds_tpu.engine.arrow_bridge import to_arrow
    return to_arrow(session.system_query(sql, label="slo_report")
                    ).to_pylist()


def _sql_count(session, where: str = "") -> dict[str, int]:
    """{tenant: count} via engine SQL (tenant NULL folds to '')."""
    rows = _fetch(session, "SELECT tenant, COUNT(*) AS n "
                           f"FROM system.query_log {where} "
                           "GROUP BY tenant")
    return {(r["tenant"] or ""): r["n"] for r in rows}


def build_report(session, slo_ms: float, target: float,
                 windows: list[float],
                 weights: dict[str, float] | None = None) -> dict:
    weights = weights or {}
    total = _sql_count(session)
    ok = _sql_count(session, "WHERE status = 'ok'")
    good = _sql_count(session,
                      f"WHERE status = 'ok' AND wall_ms <= {slo_ms}")
    # exact percentiles need the raw samples; fetch them through the same
    # SQL surface (one pass, grouped host-side)
    raw = _fetch(session, "SELECT tenant, status, wall_ms, queue_ms, "
                          "exec_ms, preempted, ts "
                          "FROM system.query_log")
    by_tenant: dict[str, list[float]] = {}
    for r in raw:
        if r["wall_ms"] is not None:
            by_tenant.setdefault(r["tenant"] or "", []).append(
                r["wall_ms"])
    t_end = max((r["ts"] for r in raw if r["ts"] is not None), default=0.0)

    def slice_rows(tenant, since):
        return [r for r in raw
                if (r["tenant"] or "") == tenant
                and (r["ts"] or 0) >= since]

    # fairness inputs: per-tenant sums over the whole log
    q_sum: dict[str, float] = {}
    w_sum: dict[str, float] = {}
    e_sum: dict[str, float] = {}
    p_sum: dict[str, int] = {}
    for r in raw:
        t = r["tenant"] or ""
        q_sum[t] = q_sum.get(t, 0.0) + (r["queue_ms"] or 0.0)
        w_sum[t] = w_sum.get(t, 0.0) + (r["wall_ms"] or 0.0)
        e_sum[t] = e_sum.get(t, 0.0) + (r["exec_ms"] or 0.0)
        p_sum[t] = p_sum.get(t, 0) + int(r["preempted"] or 0)
    exec_total = sum(e_sum.values())
    weight_total = sum(float(weights.get(t, 1.0)) for t in total) or 1.0

    tenants = sorted(total)
    out_rows = []
    budget = max(1e-9, 1.0 - target)
    for tenant in tenants + ["(all)"]:
        if tenant == "(all)":
            n = sum(total.values())
            n_ok = sum(ok.values())
            n_good = sum(good.values())
            lat = sorted(x for v in by_tenant.values() for x in v)
            qs, ws = sum(q_sum.values()), sum(w_sum.values())
            preempt = sum(p_sum.values())
            attain_w = None                  # share-of-total is trivially 1
        else:
            n = total.get(tenant, 0)
            n_ok = ok.get(tenant, 0)
            n_good = good.get(tenant, 0)
            lat = sorted(by_tenant.get(tenant, []))
            qs, ws = q_sum.get(tenant, 0.0), w_sum.get(tenant, 0.0)
            preempt = p_sum.get(tenant, 0)
            wshare = float(weights.get(tenant, 1.0)) / weight_total
            eshare = (e_sum.get(tenant, 0.0) / exec_total
                      if exec_total > 0 else 0.0)
            attain_w = round(eshare / wshare, 3) if wshare > 0 else None
        if not n:
            continue
        attain = n_good / n
        row = {"tenant": tenant, "count": n, "errors": n - n_ok,
               "p50_ms": round(exact_quantile(lat, 0.50), 2),
               "p95_ms": round(exact_quantile(lat, 0.95), 2),
               "p99_ms": round(exact_quantile(lat, 0.99), 2),
               "attainment": round(attain, 5),
               "met": attain >= target,
               "queue_share": round(qs / ws, 4) if ws > 0 else 0.0,
               "preempted": preempt,
               "weight_attainment": attain_w,
               "burn": {}}
        for w in windows:
            if tenant == "(all)":
                win = [r for r in raw if (r["ts"] or 0) >= t_end - w]
            else:
                win = slice_rows(tenant, t_end - w)
            bad = sum(1 for r in win
                      if r["status"] != "ok"
                      or (r["wall_ms"] or 0) > slo_ms)
            row["burn"][_wname(w)] = \
                round((bad / len(win)) / budget, 3) if win else 0.0
        out_rows.append(row)
    return {"slo_ms": slo_ms, "target": target,
            "windows_s": list(windows), "rows": out_rows}


def _wname(w: float) -> str:
    if w % 3600 == 0:
        return f"{int(w // 3600)}h"
    if w % 60 == 0:
        return f"{int(w // 60)}m"
    return f"{int(w)}s"


def print_report(rep: dict) -> None:
    wnames = [_wname(w) for w in rep["windows_s"]]
    head = (f"{'tenant':<16} {'count':>7} {'errors':>7} {'p50':>9} "
            f"{'p95':>9} {'p99':>9} {'attain':>8} {'met':>4} "
            f"{'q_share':>8} {'preempt':>8} {'w_attain':>9}"
            + "".join(f" {('burn_' + n):>9}" for n in wnames))
    print(f"SLO: {rep['target']:.2%} of requests <= {rep['slo_ms']} ms "
          "(burn = bad-fraction / error-budget; 1.0 = budget-rate; "
          "q_share = queue wait / wall; w_attain = exec share / "
          "weight share)")
    print(head)
    print("-" * len(head))
    for r in rep["rows"]:
        wa = r.get("weight_attainment")
        print(f"{r['tenant'] or '(none)':<16} {r['count']:>7} "
              f"{r['errors']:>7} {r['p50_ms']:>9.1f} {r['p95_ms']:>9.1f} "
              f"{r['p99_ms']:>9.1f} {r['attainment']:>8.4f} "
              f"{'yes' if r['met'] else 'NO':>4} "
              f"{r['queue_share']:>8.4f} {r['preempted']:>8} "
              f"{(f'{wa:.3f}' if wa is not None else '-'):>9}"
              + "".join(f" {r['burn'][n]:>9.2f}" for n in wnames))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="slo_report.py", description=(
        "per-tenant SLO attainment + multi-window burn rates computed "
        "by running SQL over a saved query log (system.query_log)"))
    p.add_argument("log", nargs="+",
                   help="query-log JSONL file(s); pass a rotated set in "
                        "filename order (lexicographic = chronological)")
    p.add_argument("--slo_ms", type=float, default=1000.0,
                   help="latency SLO threshold in ms (default 1000)")
    p.add_argument("--target", type=float, default=0.99,
                   help="attainment target in [0,1] (default 0.99)")
    p.add_argument("--windows", default="300,3600",
                   help="comma list of burn-rate window spans in seconds "
                        "(default 300,3600 = the classic 5m+1h pair)")
    p.add_argument("--weights", default="", metavar="T=W,...",
                   help="tenant weights for the weight-attainment column "
                        "(e.g. interactive=4,batch=1; unlisted tenants "
                        "weigh 1.0 — matches ServiceConfig.tenant_weights)")
    p.add_argument("--json", default=None, metavar="PATH",
                   help="also write the report JSON here")
    a = p.parse_args(argv)

    weights: dict[str, float] = {}
    for part in a.weights.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            print(f"slo_report: bad --weights entry {part!r} "
                  "(want tenant=weight)", file=sys.stderr)
            return 2
        k, _, v = part.partition("=")
        try:
            weights[k.strip()] = float(v)
        except ValueError:
            print(f"slo_report: bad --weights value {part!r}",
                  file=sys.stderr)
            return 2

    rows = []
    for path in a.log:
        try:
            rows.extend(read_jsonl(path))
        except (OSError, json.JSONDecodeError) as e:
            print(f"slo_report: {path}: {e}", file=sys.stderr)
            return 2
    if not rows:
        print("slo_report: no rows in the given log(s)", file=sys.stderr)
        return 2
    # replay the saved rows into the ring, then let the ENGINE do the
    # analysis over system.query_log (host-only, no device, no jax init)
    QUERY_LOG.configure(enabled=True, capacity=len(rows), clear=True)
    QUERY_LOG.load_rows(rows)
    from nds_tpu.config import EngineConfig
    from nds_tpu.engine import Session
    session = Session(EngineConfig(use_jax=False))
    windows = [float(x) for x in a.windows.split(",") if x.strip()]
    rep = build_report(session, a.slo_ms, a.target, windows,
                       weights=weights)
    if weights:
        rep["weights"] = dict(weights)
    rep["source"] = [os.path.basename(x) for x in a.log]
    rep["rows_read"] = len(rows)
    print_report(rep)
    if a.json:
        os.makedirs(os.path.dirname(a.json) or ".", exist_ok=True)
        with open(a.json, "w") as f:
            json.dump(rep, f, indent=2, sort_keys=True)
        print(f"slo_report: wrote {a.json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
