"""Why is the in-program gather 425ms when standalone is ~0? Probe variants."""
import time
import numpy as np
import jax
import jax.numpy as jnp

jax.config.update("jax_enable_x64", True)

NI, NS = 1 << 24, 1 << 20
rng = np.random.default_rng(0)
idx_np = rng.integers(0, NS, NI)
idx32 = jnp.asarray(idx_np, jnp.int32)
idx64 = jnp.asarray(idx_np, jnp.int64)
src32 = jnp.asarray(rng.integers(0, 1 << 30, NS), jnp.int32)
src64 = jnp.asarray(rng.integers(0, 1 << 60, NS), jnp.int64)


def bench(name, fn, *args):
    f = jax.jit(fn)
    jax.block_until_ready(f(*args))
    t0 = time.perf_counter()
    for _ in range(3):
        jax.block_until_ready(f(*args))
    dt = (time.perf_counter() - t0) / 3 * 1000
    print(f"{name:28s} {dt:8.2f} ms", flush=True)


bench("i32src_i32idx", lambda s, i: s[i], src32, idx32)
bench("i32src_i32idx_sum", lambda s, i: s[i].sum(), src32, idx32)
bench("i64src_i32idx", lambda s, i: s[i], src64, idx32)
bench("i32src_i64idx", lambda s, i: s[i], src32, idx64)
bench("clip_then_gather", lambda s, i: s[jnp.clip(i, 0, NS - 1)], src32, idx32)
bench("where_gather", lambda s, i: jnp.where(i < NS, s[jnp.clip(i, 0, NS-1)], 0), src32, idx32)
# gather fused with producer of indices (cummax — the expand_join shape)
from jax import lax
bench("cummax_gather", lambda s, i: s[lax.cummax(i)], src32, idx32)
# take with explicit mode
bench("take_fill", lambda s, i: jnp.take(s, i, mode="fill"), src32, idx32)
bench("take_clip", lambda s, i: jnp.take(s, i, mode="clip"), src32, idx32)
