"""When do 16M gathers become slow? Scale program complexity."""
import time
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

jax.config.update("jax_enable_x64", True)
NI, NS = 1 << 24, 1 << 20
rng = np.random.default_rng(0)
idx = jnp.asarray(rng.integers(0, NS, NI), jnp.int32)
srcs = [jnp.asarray(rng.integers(0, 1 << 30, NS), jnp.int32) for _ in range(10)]


def bench(name, fn, *args):
    f = jax.jit(fn)
    jax.block_until_ready(f(*args))
    t0 = time.perf_counter()
    for _ in range(3):
        jax.block_until_ready(f(*args))
    print(f"{name:24s} {(time.perf_counter()-t0)/3*1000:8.1f} ms", flush=True)


bench("gather1", lambda s, i: s[i].sum(), srcs[0], idx)
bench("gather10_parallel", lambda i, *ss: sum(s[i].sum() for s in ss), idx, *srcs)


def chained(s, i):
    out = jnp.zeros((), jnp.int64)
    for k in range(10):
        g = s[(i + k) % NS]          # different idx each time
        out = out + g.sum()
    return out
bench("gather10_chained", chained, srcs[0], idx)


def sort_then_gather(s, i):
    key, pos = lax.sort((i, jnp.arange(NI, dtype=jnp.int32)), num_keys=1)
    g = s[key]
    h = s[pos]
    return g.sum() + h.sum()
bench("sort_then_gather", sort_then_gather, srcs[0], idx)


def join_like(s, i):
    # mimic expand_join: scatter-max + cummax -> gather chain
    starts = jnp.cumsum(jnp.ones(NI, jnp.int32)) - 1
    marker = jnp.zeros(NI + 1, jnp.int32).at[starts].max(jnp.arange(NI, dtype=jnp.int32))
    left = lax.cummax(marker[:NI])
    g1 = s[jnp.clip(i[left], 0, NS - 1)]
    g2 = s[jnp.clip(left % NS, 0, NS - 1)]
    return g1.sum() + g2.sum()
bench("join_like", join_like, srcs[0], idx)
