#!/usr/bin/env python
"""Summarize NDS-TPU observability artifacts on the terminal.

Accepts any of the formats the obs layer emits and prints the aggregate
view a Perfetto session would start from:

- Chrome trace-event JSON (``bench.py --trace`` / ``power --trace`` /
  ``service_bench.py --trace``): per-span-name rollup (count / total /
  mean / max ms) plus the slowest individual spans with their attributes;
  traces containing ``service/*`` spans additionally get a per-tenant
  rollup and a slowest-ticket listing (the ``service/ticket`` root spans
  opened at admission);
- JSONL event logs (one event per line, same rollup);
- flight-recorder JSONL dumps (``obs.flight``): per-event-type counts,
  per-tenant rollup, and the slowest completed tickets;
- bench JSON lines (the ``bench.py`` stdout object): the per-program
  device-time table, per-query attribution fractions, the engine metrics
  snapshot, and (schema >= 3) histogram quantile tables.

Usage:  python scripts/trace_report.py ARTIFACT [--top N]

Stdlib plus the dependency-free ``nds_tpu.obs.metrics`` (histogram
quantile math); safe to point at artifacts from any round
(schema_version tolerant — unknown keys are ignored).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_events(path: str) -> list[dict] | None:
    """Trace events from a Chrome trace file or JSONL log; None when the
    file is some other JSON artifact (e.g. a bench summary)."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        events = []
        for line in text.splitlines():
            line = line.strip()
            if line:
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError as e:
                    raise ValueError(
                        f"{path}: neither JSON nor JSONL "
                        f"({e})") from None
        return events
    if isinstance(doc, dict) and "traceEvents" in doc:
        return doc["traceEvents"]
    if isinstance(doc, list):
        return doc
    return None


def rollup(events: list[dict]) -> list[dict]:
    """Per-span-name aggregate over complete (ph == "X") events."""
    agg: dict[str, dict] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        row = agg.setdefault(e["name"], {"name": e["name"], "count": 0,
                                         "total_ms": 0.0, "max_ms": 0.0})
        ms = e.get("dur", 0) / 1000.0
        row["count"] += 1
        row["total_ms"] += ms
        row["max_ms"] = max(row["max_ms"], ms)
    out = sorted(agg.values(), key=lambda r: r["total_ms"], reverse=True)
    for r in out:
        r["mean_ms"] = r["total_ms"] / r["count"] if r["count"] else 0.0
    return out


def print_rollup(rows: list[dict]) -> None:
    head = (f"{'span':<24} {'count':>7} {'total_ms':>11} {'mean_ms':>9} "
            f"{'max_ms':>9}")
    print(head)
    print("-" * len(head))
    for r in rows:
        print(f"{r['name'][:24]:<24} {r['count']:>7} {r['total_ms']:>11.1f} "
              f"{r['mean_ms']:>9.2f} {r['max_ms']:>9.1f}")


def print_slowest(events: list[dict], top: int) -> None:
    spans = sorted((e for e in events if e.get("ph") == "X"),
                   key=lambda e: e.get("dur", 0), reverse=True)[:top]
    print(f"\nslowest {len(spans)} spans:")
    for e in spans:
        args = e.get("args", {})
        label = args.get("label") or args.get("table") or ""
        detail = f" [{label}]" if label else ""
        print(f"  {e.get('dur', 0) / 1000.0:>9.1f} ms  "
              f"{e['name']}{detail}  {args}")


def print_service_view(events: list[dict], top: int) -> None:
    """Service-trace extras: per-tenant rollup over the ``service/ticket``
    root spans and the slowest tickets (label, latency, batch company)."""
    tickets = [e for e in events
               if e.get("ph") == "X" and e.get("name") == "service/ticket"]
    if not tickets:
        return
    tenants: dict[str, dict] = {}
    for e in tickets:
        t = (e.get("args") or {}).get("tenant", "?")
        row = tenants.setdefault(t, {"count": 0, "total_ms": 0.0,
                                     "max_ms": 0.0, "errors": 0})
        ms = e.get("dur", 0) / 1000.0
        row["count"] += 1
        row["total_ms"] += ms
        row["max_ms"] = max(row["max_ms"], ms)
        if (e.get("args") or {}).get("error"):
            row["errors"] += 1
    print(f"\nservice tickets by tenant ({len(tickets)} tickets):")
    head = (f"{'tenant':<16} {'tickets':>8} {'mean_ms':>9} {'max_ms':>9} "
            f"{'errors':>7}")
    print(head)
    print("-" * len(head))
    for t, r in sorted(tenants.items(), key=lambda kv: -kv[1]["max_ms"]):
        print(f"{t[:16]:<16} {r['count']:>8} "
              f"{r['total_ms'] / r['count']:>9.1f} {r['max_ms']:>9.1f} "
              f"{r['errors']:>7}")
    slow = sorted(tickets, key=lambda e: e.get("dur", 0),
                  reverse=True)[:top]
    print(f"\nslowest {len(slow)} tickets:")
    for e in slow:
        args = e.get("args", {})
        print(f"  {e.get('dur', 0) / 1000.0:>9.1f} ms  "
              f"{args.get('label', '?')}  tenant={args.get('tenant', '?')}"
              f"{'  ERROR=' + args['error'] if args.get('error') else ''}")


def is_flight_log(events: list[dict]) -> bool:
    """Flight-recorder dumps are JSONL like trace event logs but carry
    ``event``/``t_ms`` instead of Chrome's ``ph``/``ts``."""
    return bool(events) and all(
        isinstance(e, dict) and "event" in e and "ph" not in e
        for e in events)


def print_flight(events: list[dict], top: int) -> None:
    """Flight-recorder dump: event-type counts, per-tenant rollup, and
    the slowest completed tickets."""
    kinds: dict[str, int] = {}
    tenants: dict[str, dict] = {}
    for e in events:
        kinds[e["event"]] = kinds.get(e["event"], 0) + 1
        t = e.get("tenant")
        if t is None:
            continue
        row = tenants.setdefault(t, {"complete": 0, "reject": 0,
                                     "expire": 0, "error": 0,
                                     "total_ms": 0.0, "max_ms": 0.0})
        k = e["event"]
        if k in row:
            row[k] += 1
        if k == "complete" and e.get("latency_ms") is not None:
            row["total_ms"] += e["latency_ms"]
            row["max_ms"] = max(row["max_ms"], e["latency_ms"])
    span_s = (events[-1]["t_ms"] - events[0]["t_ms"]) / 1000.0 \
        if len(events) > 1 else 0.0
    print(f"flight recorder: {len(events)} events over {span_s:.1f}s")
    for k in sorted(kinds, key=lambda k: -kinds[k]):
        print(f"  {k:<10} {kinds[k]}")
    if tenants:
        head = (f"\n{'tenant':<16} {'complete':>9} {'reject':>7} "
                f"{'expire':>7} {'error':>6} {'mean_ms':>9} {'max_ms':>9}")
        print(head)
        print("-" * (len(head) - 1))
        for t, r in sorted(tenants.items(),
                           key=lambda kv: -kv[1]["max_ms"]):
            mean = r["total_ms"] / r["complete"] if r["complete"] else 0.0
            print(f"{t[:16]:<16} {r['complete']:>9} {r['reject']:>7} "
                  f"{r['expire']:>7} {r['error']:>6} {mean:>9.1f} "
                  f"{r['max_ms']:>9.1f}")
    # self-healing / lifecycle vocabulary (chaos-hardened serving): the
    # old event set prints exactly as before — this block only appears
    # when the new events are present in the dump
    trips: dict[str, int] = {}
    probes: dict[str, int] = {}
    quarantines = []
    phases = []
    for e in events:
        if e["event"] == "trip":
            r = e.get("reason", "?")
            trips[r] = trips.get(r, 0) + 1
        elif e["event"] == "probe":
            key = f"{e.get('error_class', '?')}" + \
                ("/closed" if e.get("outcome") == "closed" else "")
            probes[key] = probes.get(key, 0) + 1
        elif e["event"] == "quarantine":
            quarantines.append(e)
        elif e["event"] == "lifecycle_phase":
            phases.append(e)
    if trips or probes or quarantines:
        print("\nself-healing:")
        for r in sorted(trips, key=lambda r: -trips[r]):
            print(f"  trip {r:<24} x{trips[r]}")
        for k in sorted(probes):
            print(f"  probe {k:<23} x{probes[k]}")
        for e in quarantines:
            print(f"  quarantine fp={e.get('fp', '?')} "
                  f"strikes={e.get('strikes', '?')} "
                  f"reason={e.get('reason', '?')}")
    if phases:
        print("\nlifecycle phases:")
        for e in phases:
            extra = f" ({e['elapsed_s']}s)" if e.get("elapsed_s") else ""
            print(f"  {e['t_ms']:>10.1f} ms  {e.get('phase', '?'):<18} "
                  f"{e.get('status', '?')}{extra}")
    done = sorted((e for e in events if e["event"] == "complete"
                   and e.get("latency_ms") is not None),
                  key=lambda e: -e["latency_ms"])[:top]
    print(f"\nslowest {len(done)} tickets:")
    for e in done:
        extra = f"  batched_with={e['batched_with']}" \
            if e.get("batched_with") else ""
        print(f"  {e['latency_ms']:>9.1f} ms  {e.get('label', '?')}  "
              f"tenant={e.get('tenant', '?')}{extra}")


def print_bench(doc: dict, top: int) -> None:
    print(f"bench: {doc.get('metric')} = {doc.get('value')} "
          f"{doc.get('unit', '')} (vs_baseline {doc.get('vs_baseline')})")
    programs = doc.get("device_time_programs") or []
    if programs:
        print("\ntop programs by device time:")
        head = (f"{'program':<40} {'runs':>5} {'total_ms':>10} "
                f"{'mean_ms':>9} {'roofline':>9}")
        print(head)
        print("-" * len(head))
        for r in programs[:top]:
            rf = r.get("roofline_frac")
            print(f"{r['program'][:40]:<40} {r['runs']:>5} "
                  f"{r['device_ms']:>10.1f} {r['mean_ms']:>9.2f} "
                  f"{(f'{rf:.4f}' if rf is not None else '-'):>9}")
    attribution = doc.get("attribution_frac") or {}
    if attribution:
        print("\ndevice-time attribution (fraction of timed wall):")
        for q, frac in attribution.items():
            print(f"  {q:<12} {frac:.1%}")
    metrics = doc.get("metrics") or {}
    if metrics:
        print("\nengine metrics:")
        for name, v in metrics.items():
            if v:
                print(f"  {name:<24} {v}")
    spans = doc.get("spans") or {}
    if spans:
        rows = [{"name": n, **r,
                 "mean_ms": r["total_ms"] / r["count"] if r["count"] else 0.0}
                for n, r in spans.items()]
        rows.sort(key=lambda r: r["total_ms"], reverse=True)
        print()
        print_rollup(rows)
    hists = doc.get("histograms") or {}
    if hists:
        sys.path.insert(0, REPO)
        from nds_tpu.obs.metrics import quantile_from_snapshot
        print("\nhistograms (count / p50 / p95 / p99 / max ms):")
        for key, snap in sorted(hists.items()):
            qs = [quantile_from_snapshot(snap, p)
                  for p in (0.5, 0.95, 0.99)]
            qtxt = " ".join(f"{q:>9.1f}" if q is not None else f"{'-':>9}"
                            for q in qs)
            print(f"  {key[:48]:<48} {snap['count']:>7} {qtxt} "
                  f"{snap['max'] if snap['max'] is not None else 0:>9.1f}")


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="trace_report.py")
    p.add_argument("artifact", help="Chrome trace / JSONL event log / "
                                    "bench JSON")
    p.add_argument("--top", type=int, default=10,
                   help="rows in the slowest-spans / top-programs tables")
    a = p.parse_args(argv)
    try:
        events = load_events(a.artifact)
        if events is not None and is_flight_log(events):
            print_flight(events, a.top)
            return 0
        if events is not None and events and \
                all(isinstance(e, dict) and "ph" in e for e in events):
            print_rollup(rollup(events))
            print_slowest(events, a.top)
            print_service_view(events, a.top)
            return 0
        with open(a.artifact) as f:
            doc = json.load(f)
    except (ValueError, OSError) as e:
        print(f"trace_report: {e}", file=sys.stderr)
        return 2
    if isinstance(doc, dict):
        print_bench(doc, a.top)
        return 0
    print(f"unrecognized artifact format: {a.artifact}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
