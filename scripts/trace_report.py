#!/usr/bin/env python
"""Summarize NDS-TPU observability artifacts on the terminal.

Accepts any of the formats the obs layer emits and prints the aggregate
view a Perfetto session would start from:

- Chrome trace-event JSON (``bench.py --trace`` / ``power --trace``):
  per-span-name rollup (count / total / mean / max ms) plus the slowest
  individual spans with their attributes;
- JSONL event logs (one event per line, same rollup);
- bench JSON lines (the ``bench.py`` stdout object): the per-program
  device-time table, per-query attribution fractions, and the engine
  metrics snapshot.

Usage:  python scripts/trace_report.py ARTIFACT [--top N]

Pure stdlib; safe to point at artifacts from any round (schema_version
tolerant — unknown keys are ignored).
"""
from __future__ import annotations

import argparse
import json
import sys


def load_events(path: str) -> list[dict] | None:
    """Trace events from a Chrome trace file or JSONL log; None when the
    file is some other JSON artifact (e.g. a bench summary)."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        events = []
        for line in text.splitlines():
            line = line.strip()
            if line:
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError as e:
                    raise ValueError(
                        f"{path}: neither JSON nor JSONL "
                        f"({e})") from None
        return events
    if isinstance(doc, dict) and "traceEvents" in doc:
        return doc["traceEvents"]
    if isinstance(doc, list):
        return doc
    return None


def rollup(events: list[dict]) -> list[dict]:
    """Per-span-name aggregate over complete (ph == "X") events."""
    agg: dict[str, dict] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        row = agg.setdefault(e["name"], {"name": e["name"], "count": 0,
                                         "total_ms": 0.0, "max_ms": 0.0})
        ms = e.get("dur", 0) / 1000.0
        row["count"] += 1
        row["total_ms"] += ms
        row["max_ms"] = max(row["max_ms"], ms)
    out = sorted(agg.values(), key=lambda r: r["total_ms"], reverse=True)
    for r in out:
        r["mean_ms"] = r["total_ms"] / r["count"] if r["count"] else 0.0
    return out


def print_rollup(rows: list[dict]) -> None:
    head = (f"{'span':<24} {'count':>7} {'total_ms':>11} {'mean_ms':>9} "
            f"{'max_ms':>9}")
    print(head)
    print("-" * len(head))
    for r in rows:
        print(f"{r['name'][:24]:<24} {r['count']:>7} {r['total_ms']:>11.1f} "
              f"{r['mean_ms']:>9.2f} {r['max_ms']:>9.1f}")


def print_slowest(events: list[dict], top: int) -> None:
    spans = sorted((e for e in events if e.get("ph") == "X"),
                   key=lambda e: e.get("dur", 0), reverse=True)[:top]
    print(f"\nslowest {len(spans)} spans:")
    for e in spans:
        args = e.get("args", {})
        label = args.get("label") or args.get("table") or ""
        detail = f" [{label}]" if label else ""
        print(f"  {e.get('dur', 0) / 1000.0:>9.1f} ms  "
              f"{e['name']}{detail}  {args}")


def print_bench(doc: dict, top: int) -> None:
    print(f"bench: {doc.get('metric')} = {doc.get('value')} "
          f"{doc.get('unit', '')} (vs_baseline {doc.get('vs_baseline')})")
    programs = doc.get("device_time_programs") or []
    if programs:
        print("\ntop programs by device time:")
        head = (f"{'program':<40} {'runs':>5} {'total_ms':>10} "
                f"{'mean_ms':>9} {'roofline':>9}")
        print(head)
        print("-" * len(head))
        for r in programs[:top]:
            rf = r.get("roofline_frac")
            print(f"{r['program'][:40]:<40} {r['runs']:>5} "
                  f"{r['device_ms']:>10.1f} {r['mean_ms']:>9.2f} "
                  f"{(f'{rf:.4f}' if rf is not None else '-'):>9}")
    attribution = doc.get("attribution_frac") or {}
    if attribution:
        print("\ndevice-time attribution (fraction of timed wall):")
        for q, frac in attribution.items():
            print(f"  {q:<12} {frac:.1%}")
    metrics = doc.get("metrics") or {}
    if metrics:
        print("\nengine metrics:")
        for name, v in metrics.items():
            if v:
                print(f"  {name:<24} {v}")
    spans = doc.get("spans") or {}
    if spans:
        rows = [{"name": n, **r,
                 "mean_ms": r["total_ms"] / r["count"] if r["count"] else 0.0}
                for n, r in spans.items()]
        rows.sort(key=lambda r: r["total_ms"], reverse=True)
        print()
        print_rollup(rows)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="trace_report.py")
    p.add_argument("artifact", help="Chrome trace / JSONL event log / "
                                    "bench JSON")
    p.add_argument("--top", type=int, default=10,
                   help="rows in the slowest-spans / top-programs tables")
    a = p.parse_args(argv)
    try:
        events = load_events(a.artifact)
        if events is not None and events and \
                all(isinstance(e, dict) and "ph" in e for e in events):
            print_rollup(rollup(events))
            print_slowest(events, a.top)
            return 0
        with open(a.artifact) as f:
            doc = json.load(f)
    except (ValueError, OSError) as e:
        print(f"trace_report: {e}", file=sys.stderr)
        return 2
    if isinstance(doc, dict):
        print_bench(doc, a.top)
        return 0
    print(f"unrecognized artifact format: {a.artifact}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
