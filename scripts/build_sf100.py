"""Disk-frugal SF100 warehouse builder: generate -> parquet -> delete, per chunk.

The SF100 ladder step (BASELINE.md step 2: q1-q10 at SF100) needs a ~50 GB
raw dataset on a host with less free disk than raw+parquet combined, so the
whole-dataset datagen->transcode pipeline (nds_tpu.datagen + nds_tpu.transcode,
reference nds/nds_gen_data.py -> nds/nds_transcode.py) is replaced here by a
chunk loop: one generator chunk (a few million rows) is produced, transcoded
into an appended warehouse parquet file, and its raw CSV deleted before the
next chunk starts. Peak raw footprint is one chunk (~500 MB) instead of the
full table.

Resumable: per-table chunk progress persists in <root>/_build_state.json, so
an interrupted multi-hour build continues where it stopped.

Inventory is excluded by default (399M rows at SF100, needed by no query in
the q1-q10 ladder step); pass --with_inventory for the full set.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from nds_tpu.datagen import check_build              # noqa: E402
from nds_tpu.schema import get_schemas               # noqa: E402
from nds_tpu.transcode import load_csv               # noqa: E402
from nds_tpu.warehouse import Warehouse              # noqa: E402

# SF1 row counts (generator's own sizing model) used only to pick a chunk
# fan-out that lands ~CHUNK_ROWS rows per generated file
SF1_ROWS = {
    "store_sales": 2_880_000, "store_returns": 288_000,
    "catalog_sales": 1_440_000, "catalog_returns": 144_000,
    "web_sales": 720_000, "web_returns": 72_000,
    "inventory": 11_745_000, "customer": 100_000,
    "customer_address": 50_000, "customer_demographics": 1_920_800,
}
CHUNK_ROWS = 4_000_000

SMALL_TABLES = [
    "call_center", "catalog_page", "date_dim", "household_demographics",
    "income_band", "item", "promotion", "reason", "ship_mode", "store",
    "time_dim", "warehouse", "web_page", "web_site",
]
MEDIUM_TABLES = ["customer", "customer_address", "customer_demographics"]
FACT_TABLES = ["store_returns", "catalog_returns", "web_returns",
               "web_sales", "catalog_sales", "store_sales"]


def _parallel_for(table: str, scale: float) -> int:
    rows = SF1_ROWS.get(table, 0) * scale
    return max(1, int(round(rows / CHUNK_ROWS))) if rows else 1


def _gen_chunk(binary: str, work: str, table: str, scale: float,
               parallel: int, child: int) -> str:
    os.makedirs(work, exist_ok=True)
    subprocess.run([binary, "-scale", str(scale), "-dir", work,
                    "-parallel", str(parallel), "-child", str(child),
                    "-table", table], check=True)
    name = (f"{table}_{child}_{parallel}.dat" if parallel > 1
            else f"{table}.dat")
    return os.path.join(work, name)


def build(root: str, scale: float, tables: list[str],
          use_decimal: bool = True) -> None:
    binary = check_build()
    wh = Warehouse(root)
    state_path = os.path.join(root, "_build_state.json")
    state: dict = {}
    if os.path.exists(state_path):
        with open(state_path) as f:
            state = json.load(f)
    # chunk counts derive from (scale, CHUNK_ROWS): a state written under
    # different build params must not be resumed into this chunking
    params = {"scale": scale, "chunk_rows": CHUNK_ROWS,
              "use_decimal": use_decimal}
    if state.get("_params", params) != params:
        raise SystemExit(
            f"{state_path} was written by a build with params "
            f"{state['_params']} != {params}; use a fresh --root")
    state["_params"] = params

    def save_state():
        tmp = state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f, indent=1)
        os.replace(tmp, state_path)

    schemas = dict(get_schemas(use_decimal))
    work = os.path.join(root, "_raw_chunk_")
    for table in tables:
        parallel = _parallel_for(table, scale)
        wt = wh.table(table)
        cur_version = len(wt._load())
        if table not in state:
            if cur_version:
                raise SystemExit(
                    f"table {table!r} already has {cur_version} snapshot(s) "
                    f"in {root} but no build state — it was not produced by "
                    f"this script's chunk loop; use a fresh --root or "
                    f"--tables without it")
            # register BEFORE the first insert: a crash between chunk 1's
            # commit and its checkpoint must land in the reconcile below,
            # not in the foreign-snapshot guard above
            state[table] = {"chunk": 0, "version": 0}
            save_state()
        st = state[table]
        # crash-between-insert-and-save reconcile: every non-empty chunk
        # commits exactly one snapshot, so a manifest ahead of the recorded
        # version means those chunks landed but were not checkpointed —
        # roll the chunk counter forward instead of re-inserting them
        if cur_version > st["version"]:
            st["chunk"] += cur_version - st["version"]
            st["version"] = cur_version
            state[table] = st
            save_state()
        done = st["chunk"]
        if done >= parallel:
            print(f"[skip] {table}: complete ({parallel} chunks)", flush=True)
            continue
        sch = schemas[table].arrow_schema(use_decimal=use_decimal)
        for child in range(done + 1, parallel + 1):
            path = _gen_chunk(binary, work, table, scale, parallel, child)
            if os.path.getsize(path) > 0:
                t = load_csv(path, sch)
                if wt.exists():
                    wt.insert(t, partition=False)
                else:
                    wt.create(t, partition=False)
                rows = t.num_rows
            else:
                rows = 0
            os.remove(path)
            state[table] = {"chunk": child, "version": len(wt._load())}
            save_state()
            print(f"[{table}] chunk {child}/{parallel}: {rows} rows",
                  flush=True)
    shutil.rmtree(work, ignore_errors=True)
    print("SF%s warehouse complete at %s" % (scale, root), flush=True)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="build_sf100")
    p.add_argument("--root", default=os.path.join(REPO, ".bench_data",
                                                  "sf100_wh"))
    p.add_argument("--scale", type=float, default=100.0)
    p.add_argument("--tables", default=None,
                   help="comma-separated subset (default: dims+facts)")
    p.add_argument("--with_inventory", action="store_true")
    p.add_argument("--no_decimal", action="store_true")
    a = p.parse_args(argv)
    tables = (a.tables.split(",") if a.tables else
              SMALL_TABLES + MEDIUM_TABLES + FACT_TABLES +
              (["inventory"] if a.with_inventory else []))
    build(a.root, a.scale, tables, use_decimal=not a.no_decimal)
    return 0


if __name__ == "__main__":
    sys.exit(main())
