"""Microbench the sort-join kernel pieces at q95 scale on the TPU.

Pieces (each its own jit; timed warm over 3 reps):
  sort2      - lax.sort of (i32,i32), n
  sort4      - lax.sort of 4 i32 operands, n
  segsum     - segment_sum scatter, n data -> n segments (probe_counts path)
  scatmax    - .at[idx].max scatter with a shared dump slot (expand_join path)
  scatmax_u  - same but all-unique indices + unique_indices=True
  cummax     - lax.cummax over n
"""
import time
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

jax.config.update("jax_enable_x64", True)
N = 1 << 21

rng = np.random.default_rng(0)
key = jnp.asarray(rng.integers(0, N, N), jnp.int32)
iota = jnp.arange(N, dtype=jnp.int32)
ones = jnp.ones(N, jnp.int32)
starts = jnp.asarray(np.sort(rng.choice(2 * N, N, replace=False)), jnp.int32)
has = jnp.asarray(rng.random(N) < 0.7)


def sort2(k, i):
    return lax.sort((k, i), num_keys=1, is_stable=True)[1]

def sort4(k, i):
    return lax.sort((k, i, k, i), num_keys=2, is_stable=True)[1]

def segsum(d, g):
    return jax.ops.segment_sum(d, g, num_segments=N)

def scatmax(st, h, i):
    idx = jnp.where(h, st, 2 * N)
    m = jnp.zeros(2 * N + 1, jnp.int32).at[idx].max(i)
    return lax.cummax(m[:2 * N])

def scatmax_u(st, h, i):
    idx = jnp.where(h, st, 2 * N + i)      # all unique
    m = jnp.zeros(2 * N + N, jnp.int32).at[idx].max(i, unique_indices=True)
    return lax.cummax(m[:2 * N])

def cummax_(i):
    return lax.cummax(i)


CASES = [("sort2", sort2, (key, iota)), ("sort4", sort4, (key, iota)),
         ("segsum", segsum, (ones, key)), ("scatmax", scatmax, (starts, has, iota)),
         ("scatmax_u", scatmax_u, (starts, has, iota)), ("cummax", cummax_, (iota,))]

for name, fn, args in CASES:
    f = jax.jit(fn)
    jax.block_until_ready(f(*args))
    t0 = time.perf_counter()
    for _ in range(3):
        r = jax.block_until_ready(f(*args))
    dt = (time.perf_counter() - t0) / 3 * 1000
    print(f"{name:10s} {dt:8.1f} ms", flush=True)
