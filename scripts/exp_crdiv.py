"""Device probe 2: which ops are exact under TPU f64 emulation?

  - int64 // and %
  - int64 -> f64 cast (values < 2^53)
  - f64 multiply by power of two (gathered from host-constant table)
  - emulated f64 division error rate vs host
"""
import time
import numpy as np
import jax
import jax.numpy as jnp

jax.config.update("jax_enable_x64", True)

rng = np.random.default_rng(0)
ints = rng.integers(1, 1 << 52, size=4096)
dens = rng.integers(1, 1 << 40, size=4096)
ints[:4] = [2, 4, 200, 400]
dens[:4] = [3, 6, 300, 600]
a64 = ints.astype(np.float64)
b64 = dens.astype(np.float64)
exps = rng.integers(-300, 300, size=4096)
mant = rng.integers(1 << 52, 1 << 53, size=4096)

POW2 = 2.0 ** np.arange(-340, 341)


def probe(ia, ib, e, m):
    q = ia.astype(jnp.float64) / ib.astype(jnp.float64)
    qi = ia // ib
    ri = ia % ib
    cast = ia.astype(jnp.float64)
    p2 = jnp.asarray(POW2)[e + 340]
    scaled = m.astype(jnp.float64) * p2
    desc = ia.astype(jnp.float64) / 100.0
    return q, qi, ri, cast, scaled, desc


t0 = time.time()
out = jax.block_until_ready(jax.jit(probe)(
    jnp.asarray(ints), jnp.asarray(dens), jnp.asarray(exps), jnp.asarray(mant)))
print(f"compile+run: {time.time()-t0:.1f}s on {jax.devices()[0].platform}")
q, qi, ri, cast, scaled, desc = [np.asarray(x) for x in out]

print("int // exact:", np.array_equal(qi, ints // dens),
      "% exact:", np.array_equal(ri, ints % dens))
print("int->f64 cast exact:", np.array_equal(cast, a64))
hs = mant.astype(np.float64) * POW2[exps + 340]
print("m * 2^e exact:", np.array_equal(scaled, hs))
hq = a64 / b64
print("div mismatch:", (q != hq).sum(), "of", len(q))
print("tie pairs device equal:", q[0] == q[1], q[2] == q[3],
      "host equal:", hq[0] == hq[1], hq[2] == hq[3])
hd = a64 / 100.0
print("desc(/100) mismatch:", (desc != hd).sum())
