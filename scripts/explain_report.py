#!/usr/bin/env python
"""EXPLAIN ANALYZE renderer: annotated plan trees from profile artifacts.

The profiling layer (nds_tpu/obs/profile.py) serializes every profiled
execution as a PlanProfile JSON — ``power --explain`` writes one per
query under ``<json_summary_folder>/explain/``, tests and notebooks call
``Session.explain_analyze(...).to_dict()`` directly, and the service
exposes ``QueryService.explain_analyze`` live. This tool re-renders any
of those offline:

- a profile dump (``{"profile_version": 1, "nodes": {...}, ...}``) or a
  directory of them: the annotated tree (per-node self wall + time%,
  rows est->act, output bytes), the cardinality-audit findings, and the
  device-memory watermark line;
- a power JSON summary (``powerRunReport``): the per-query
  ``node_stats`` actual-row tables and memory watermarks the normal
  (unprofiled) runs recorded for free;
- a bench JSON: its ``memory`` block.

Pure stdlib + nds_tpu.obs.profile (no jax import on the render path).

Usage:
  python scripts/explain_report.py summary/explain/query9.json
  python scripts/explain_report.py summary/explain/          # every query
  python scripts/explain_report.py summary/power_*.json      # node_stats
  python scripts/explain_report.py BENCH_r05.json            # memory block
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from nds_tpu.obs.profile import PlanProfile  # noqa: E402


def _expand(paths: list[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, "*.json"))))
        else:
            out.append(p)
    return out


def _fmt_mem(block: dict) -> str:
    def mb(k):
        v = block.get(k)
        return f"{v / (1 << 20):.1f}MB" if v is not None else "-"
    line = (f"memory: live {mb('device_live_bytes')}, "
            f"peak {mb('device_peak_bytes')}")
    if block.get("budget_bytes"):
        line += (f", headroom {mb('headroom_bytes')} of "
                 f"{mb('budget_bytes')} budget")
    return line


def render_power_summary(doc: dict, path: str) -> None:
    """Per-query node_stats tables from a power JSON summary: the actual
    row counts the normal compiled/streamed runs attribute for free
    (ExecStats.node_stats; exact per-node coverage needs --explain)."""
    stats = doc.get("execStats") or []
    name = doc.get("appName") or os.path.basename(path)
    for st in stats:
        rows = st.get("node_stats")
        print(f"{name}: mode={st.get('mode', '?')}", end="")
        for k in ("mem_peak_bytes", "mem_live_bytes"):
            if st.get(k) is not None:
                print(f" {k.replace('mem_', '')}="
                      f"{st[k] / (1 << 20):.1f}MB", end="")
        print()
        if not rows:
            print("  (no node_stats recorded — run with --explain for "
                  "full per-node coverage)")
            continue
        for lbl, n in sorted(rows.items(), key=lambda kv: -kv[1]):
            print(f"  {lbl:<28} rows {n}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="explain_report.py", description=(
        "render EXPLAIN ANALYZE profiles (annotated plan tree + "
        "cardinality audit + memory watermarks) from profile dumps, "
        "power summaries, or bench JSON"))
    p.add_argument("artifacts", nargs="+",
                   help="profile JSON(s), a directory of them (power "
                        "--explain writes <summary>/explain/), power "
                        "JSON summaries, or a bench JSON")
    p.add_argument("--findings", type=int, default=8,
                   help="cardinality-audit findings shown per profile")
    a = p.parse_args(argv)
    paths = _expand(a.artifacts)
    if not paths:
        print("explain_report: no artifacts found", file=sys.stderr)
        return 2
    rc = 0
    for i, path in enumerate(paths):
        if i:
            print()
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"explain_report: {path}: {e}", file=sys.stderr)
            rc = 2
            continue
        if not isinstance(doc, dict):
            print(f"explain_report: {path}: not a JSON object",
                  file=sys.stderr)
            rc = 2
            continue
        if "nodes" in doc and ("profile_version" in doc or "root" in doc):
            print(PlanProfile.from_dict(doc).render(
                top_findings=a.findings))
        elif "execStats" in doc:
            render_power_summary(doc, path)
        elif "memory" in doc:
            print(f"{os.path.basename(path)}: {_fmt_mem(doc['memory'])}")
        else:
            print(f"explain_report: {path}: no profile, execStats, or "
                  "memory block", file=sys.stderr)
            rc = 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
