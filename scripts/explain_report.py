#!/usr/bin/env python
"""EXPLAIN ANALYZE renderer: annotated plan trees from profile artifacts.

The profiling layer (nds_tpu/obs/profile.py) serializes every profiled
execution as a PlanProfile JSON — ``power --explain`` writes one per
query under ``<json_summary_folder>/explain/``, tests and notebooks call
``Session.explain_analyze(...).to_dict()`` directly, and the service
exposes ``QueryService.explain_analyze`` live. This tool re-renders any
of those offline:

- a profile dump (``{"profile_version": 1, "nodes": {...}, ...}``) or a
  directory of them: the annotated tree (per-node self wall + time%,
  rows est->act, output bytes), the cardinality-audit findings, and the
  device-memory watermark line;
- a power JSON summary (``powerRunReport``): the per-query
  ``node_stats`` actual-row tables and memory watermarks the normal
  (unprofiled) runs recorded for free;
- a bench JSON: its ``memory`` block.

Pure stdlib + nds_tpu.obs.profile (no jax import on the render path).

``--audit`` flips the tool from per-artifact rendering to a CROSS-RUN
rollup: every artifact's per-node actuals (profile dumps' est->act
pairs, power summaries' and query-log JSONLs' ``node_stats`` maps) merge
into one table ranked by capacity overprovision — the bucket-drift
factor between what a schedule provisioned (the static estimate, or the
``--chunk_rows`` morsel bucket for streamed nodes) and the LARGEST
actual any run observed. The top of that list is the feedback store's
shopping list (``EngineConfig.adaptive_plans`` closes the same loop
online).

Usage:
  python scripts/explain_report.py summary/explain/query9.json
  python scripts/explain_report.py summary/explain/          # every query
  python scripts/explain_report.py summary/power_*.json      # node_stats
  python scripts/explain_report.py BENCH_r05.json            # memory block
  python scripts/explain_report.py --audit summary/explain/ qlog.jsonl \
      --chunk_rows 262144                                    # rollup
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from nds_tpu.obs.profile import PlanProfile  # noqa: E402


def _expand(paths: list[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, "*.json"))))
        else:
            out.append(p)
    return out


def _fmt_mem(block: dict) -> str:
    def mb(k):
        v = block.get(k)
        return f"{v / (1 << 20):.1f}MB" if v is not None else "-"
    line = (f"memory: live {mb('device_live_bytes')}, "
            f"peak {mb('device_peak_bytes')}")
    if block.get("budget_bytes"):
        line += (f", headroom {mb('headroom_bytes')} of "
                 f"{mb('budget_bytes')} budget")
    return line


def render_power_summary(doc: dict, path: str) -> None:
    """Per-query node_stats tables from a power JSON summary: the actual
    row counts the normal compiled/streamed runs attribute for free
    (ExecStats.node_stats; exact per-node coverage needs --explain)."""
    stats = doc.get("execStats") or []
    name = doc.get("appName") or os.path.basename(path)
    for st in stats:
        rows = st.get("node_stats")
        print(f"{name}: mode={st.get('mode', '?')}", end="")
        for k in ("mem_peak_bytes", "mem_live_bytes"):
            if st.get(k) is not None:
                print(f" {k.replace('mem_', '')}="
                      f"{st[k] / (1 << 20):.1f}MB", end="")
        print()
        if not rows:
            print("  (no node_stats recorded — run with --explain for "
                  "full per-node coverage)")
            continue
        for lbl, n in sorted(rows.items(), key=lambda kv: -kv[1]):
            print(f"  {lbl:<28} rows {n}")


# the engine's capacity ladder (jax_backend/device.bucket), mirrored so
# the audit stays importable without jax on the render path
_CAP_LADDER_MIN = 4 << 20


def _bucket(n, minimum: int = 8) -> int:
    c = max(int(n), minimum)
    p = 1 << (c - 1).bit_length()
    if p > _CAP_LADDER_MIN:
        mid = 3 * (p >> 2)
        if c <= mid:
            return mid
    return p


def _audit_collect(doc, path: str, rollup: dict) -> None:
    """Merge one artifact's per-node observations into the rollup:
    {(template, node): {"est": static estimate or None, "act": max
    actual, "runs": sightings}}. Profile dumps carry est->act pairs;
    power summaries and query-log rows carry actuals only."""
    def feed(template, node, est, act):
        if act is None:
            return
        key = (template or "?", node)
        e = rollup.setdefault(key, {"est": None, "act": 0, "runs": 0})
        e["act"] = max(e["act"], int(act))
        e["runs"] += 1
        if est is not None:
            e["est"] = int(est)

    if isinstance(doc, dict) and "nodes" in doc and \
            ("profile_version" in doc or "root" in doc):
        label = doc.get("label") or \
            os.path.splitext(os.path.basename(path))[0]
        for node, ns in doc["nodes"].items():
            feed(label, node, ns.get("est_rows"), ns.get("rows"))
        return
    if isinstance(doc, dict) and "execStats" in doc:
        app = (doc.get("env") or {}).get("appName") or \
            doc.get("appName") or os.path.basename(path)
        for i, st in enumerate(doc["execStats"]):
            label = st.get("label") or \
                (app if len(doc["execStats"]) == 1 else f"{app}#{i}")
            for node, act in (st.get("node_stats") or {}).items():
                feed(label, node, None, act)
        return
    if isinstance(doc, list):          # query-log JSONL rows
        for r in doc:
            ns = r.get("node_stats")
            if isinstance(ns, str):
                try:
                    ns = json.loads(ns)
                except json.JSONDecodeError:
                    continue
            for node, act in (ns or {}).items():
                feed(r.get("label") or r.get("template"), node, None, act)


def render_audit(rollup: dict, chunk_rows, top: int) -> None:
    """The ranked overprovision table: per (template, node), the bucket
    the schedule provisioned (static estimate, or the --chunk_rows
    morsel bucket when only actuals are known) vs the bucket the worst
    observed actual needs — factor = provisioned/needed. Scans are
    skipped in the chunk_rows fallback (the morsel IS the scan)."""
    findings = []
    for (template, node), e in rollup.items():
        est = e["est"]
        if est is None:
            if not chunk_rows or node.startswith("ScanNode"):
                continue
            est = int(chunk_rows)
        prov, need = _bucket(est), _bucket(e["act"])
        if prov > need:
            findings.append((prov / need, template, node, est, e))
    findings.sort(key=lambda f: (-f[0], f[1], f[2]))
    if not findings:
        print("audit: no overprovisioned nodes found")
        return
    print(f"audit: {len(findings)} overprovisioned node(s) across "
          f"{len({t for _, t, *_ in findings})} template(s) "
          "(provisioned bucket / needed bucket)")
    print(f"{'factor':>9}  {'template':<16} {'node':<28} "
          f"{'prov':>10} {'actual':>10} {'runs':>5}")
    for factor, template, node, est, e in findings[:top]:
        print(f"{factor:>8.0f}x  {template:<16} {node:<28} "
              f"{_bucket(est):>10} {e['act']:>10} {e['runs']:>5}")
    if len(findings) > top:
        print(f"... {len(findings) - top} more (raise --findings)")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="explain_report.py", description=(
        "render EXPLAIN ANALYZE profiles (annotated plan tree + "
        "cardinality audit + memory watermarks) from profile dumps, "
        "power summaries, or bench JSON"))
    p.add_argument("artifacts", nargs="+",
                   help="profile JSON(s), a directory of them (power "
                        "--explain writes <summary>/explain/), power "
                        "JSON summaries, or a bench JSON")
    p.add_argument("--findings", type=int, default=8,
                   help="cardinality-audit findings shown per profile "
                        "(with --audit: rollup rows shown)")
    p.add_argument("--audit", action="store_true",
                   help="cross-run rollup instead of per-artifact "
                        "rendering: merge every artifact's per-node "
                        "actuals and print the ranked overprovision "
                        "list (bucket-drift factor, worst first)")
    p.add_argument("--chunk_rows", type=int, default=0,
                   help="with --audit: the streamed morsel bound the "
                        "run provisioned capacity buckets from — lets "
                        "actuals-only sources (node_stats maps, query "
                        "logs) estimate the ladder gap on streamed "
                        "non-scan nodes")
    a = p.parse_args(argv)
    paths = _expand(a.artifacts)
    if not paths:
        print("explain_report: no artifacts found", file=sys.stderr)
        return 2
    rc = 0
    rollup: dict = {}
    for i, path in enumerate(paths):
        if not a.audit and i:
            print()
        try:
            with open(path) as f:
                if path.endswith(".jsonl"):
                    doc = [json.loads(line) for line in f if line.strip()]
                else:
                    doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"explain_report: {path}: {e}", file=sys.stderr)
            rc = 2
            continue
        if a.audit:
            _audit_collect(doc, path, rollup)
            continue
        if not isinstance(doc, dict):
            print(f"explain_report: {path}: not a JSON object",
                  file=sys.stderr)
            rc = 2
            continue
        if "nodes" in doc and ("profile_version" in doc or "root" in doc):
            print(PlanProfile.from_dict(doc).render(
                top_findings=a.findings))
        elif "execStats" in doc:
            render_power_summary(doc, path)
        elif "memory" in doc:
            print(f"{os.path.basename(path)}: {_fmt_mem(doc['memory'])}")
        else:
            print(f"explain_report: {path}: no profile, execStats, or "
                  "memory block", file=sys.stderr)
            rc = 2
    if a.audit:
        render_audit(rollup, a.chunk_rows, max(a.findings, 1))
    return rc


if __name__ == "__main__":
    sys.exit(main())
