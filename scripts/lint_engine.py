#!/usr/bin/env python
"""Engine-discipline lint: AST-based custom checks for nds_tpu/.

Two rule families, both guarding invariants the runtime cannot check:

ENG001 — frozen plan IR. Plan nodes and bound expressions (engine/plan.py
  dataclasses) are treated as immutable everywhere: rewrite passes rebuild
  copy-on-write (`dataclasses.replace`), because plans are DAGs — a node
  reachable from several parents (shared CTE subtrees, segment-cache slots)
  that is mutated in place silently shifts positional bindings for every
  other consumer (the `_exact_rational_keys` shared-CTE widening hazard,
  ADVICE r5). The rule flags attribute assignments, augmented assignments,
  subscript stores, and mutating container calls (`append`/`extend`/...)
  whose target is a plan-IR field, EXCEPT:
    - on objects constructed in the same function (builder-style
      initialization of a node you provably own);
    - `self.<field>` inside classes that are not plan-IR classes (their
      namesake attributes are unrelated);
    - lines carrying the pragma  `# lint: frozen-exempt (<reason>)`
      (the whitelisted copy-on-write builders / sanctioned fresh-root
      annotations).

ENG002 — cross-thread writes take the lock. Functions handed to worker
  threads (threading.Thread(target=...), pool.submit/map) run concurrently
  with the session; an attribute write to shared state from such a function
  races unless it happens under a lock (the race class PR 2's per-program
  lock fixed by hand in CompiledQuery). Functions that are ENTERED
  concurrently without being a literal thread target — the session entry
  points the query service's client threads and planner workers call
  (Session.sql, column_stats, column_enc_stats, load_table) — opt into the
  same rule with a def-line pragma  `# lint: thread-entry (<reason>)`,
  so the lint (not review) enforces their locking discipline. The rule
  flags attribute writes inside thread-target/thread-entry functions (and
  their nested closures) unless:
    - lexically inside a `with <...lock...>:` block (any context-manager
      expression whose dotted name ends in "lock", e.g. `self._lock`,
      `_SHARED_LOCK` — the declared lock-protected set);
    - the target object was created inside the function (thread-local);
    - the line carries  `# lint: lock-exempt (<reason>)`.

Pure stdlib; runs standalone:  python scripts/lint_engine.py nds_tpu
Exit status 1 when findings exist. tests/test_lint_engine.py pins both the
clean run over the real tree and the regression behavior (a reintroduced
in-place PlanNode mutation and an unlocked cross-thread write are flagged).
"""
from __future__ import annotations

import ast
import re
import sys
from dataclasses import dataclass

# Plan-IR dataclass fields whose names are distinctive enough to identify a
# plan node / bound expression at a write site (engine/plan.py; keep in
# sync when the IR grows fields). Deliberately excludes names too generic
# to attribute (table, plan, index, dtype, name, value, op, args, extra,
# func, arg, kind, label, key, n, all, distinct, asc, left, right).
PLAN_FIELDS = frozenset({
    "out_names", "out_dtypes", "child", "predicate", "exprs",
    "left_keys", "right_keys", "residual", "null_aware", "late_mat",
    "group_exprs", "aggs", "rollup", "rollup_levels", "funcs", "keys",
    "columns", "partition_by", "order_by", "nulls_first", "cte_segments",
})

# classes whose OWN attributes legitimately carry plan-field names: the IR
# dataclasses themselves (self-writes inside them are still flagged)
IR_CLASSES = frozenset({
    "PlanNode", "ScanNode", "FilterNode", "ProjectNode", "JoinNode",
    "AggregateNode", "WindowNode", "SortNode", "LimitNode", "DistinctNode",
    "SetOpNode", "MaterializedNode", "VirtualScanNode", "BExpr", "BCol",
    "BLit", "BCall", "BParam", "BScalarSubquery", "AggSpec", "SortKey",
    "WindowFunc",
})

MUTATOR_METHODS = frozenset({
    "append", "extend", "insert", "pop", "remove", "clear", "sort",
    "reverse", "update", "setdefault",
})

_FROZEN_EXEMPT = re.compile(r"#\s*lint:\s*frozen-exempt")
_LOCK_EXEMPT = re.compile(r"#\s*lint:\s*lock-exempt")
#: def-line pragma declaring a function concurrently entered (service
#: client threads / planner workers) — ENG002 applies as if it were a
#: thread target, so its shared-state writes must sit under a lock
_THREAD_ENTRY = re.compile(r"#\s*lint:\s*thread-entry")


@dataclass
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} {self.message}")


def _dotted(node) -> str:
    """Best-effort dotted name of an expression ('self._lock', '')."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _root_name(node) -> str:
    """Leftmost Name of an attribute/subscript chain ('' when complex)."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else ""


def _is_lock_ctx(withitem: ast.withitem) -> bool:
    d = _dotted(withitem.context_expr)
    return d.lower().endswith("lock")


class _FunctionInfo:
    """Per-function facts shared by both rules."""

    def __init__(self, fn: ast.AST):
        self.fn = fn
        # local names bound from a direct ClassName(...) constructor call:
        # attribute writes through them are builder-style initialization
        self.owned: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    isinstance(node.value.func, ast.Name) and \
                    node.value.func.id[:1].isupper():
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.owned.add(t.id)


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, src: str, engine_scope: bool):
        self.path = path
        self.lines = src.splitlines()
        self.engine_scope = engine_scope   # rule ENG001 applies here
        self.findings: list[Finding] = []
        self._class_stack: list[str] = []
        self._fn_stack: list[_FunctionInfo] = []
        # thread-target function names collected in a pre-pass
        self.thread_targets: set[str] = set()
        # stack of "inside a thread-target function" markers
        self._thread_depth = 0
        self._lock_depth = 0

    # -- helpers -------------------------------------------------------------
    def _exempt(self, lineno: int, pattern: re.Pattern) -> bool:
        if 1 <= lineno <= len(self.lines):
            return bool(pattern.search(self.lines[lineno - 1]))
        return False

    def _add(self, node, rule: str, message: str) -> None:
        self.findings.append(Finding(self.path, node.lineno,
                                     node.col_offset, rule, message))

    def _owned(self, root: str) -> bool:
        return any(root in fi.owned for fi in self._fn_stack)

    def _in_ir_class(self) -> bool:
        return bool(self._class_stack) and \
            self._class_stack[-1] in IR_CLASSES

    # -- pre-pass: thread targets ---------------------------------------------
    def collect_thread_targets(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            cands: list[ast.expr] = []
            if isinstance(node.func, ast.Attribute):
                if node.func.attr == "Thread" or \
                        _dotted(node.func).endswith("threading.Thread"):
                    cands += [k.value for k in node.keywords
                              if k.arg == "target"]
                elif node.func.attr in ("submit", "map") and node.args:
                    # pool.submit(fn, ...) / pool.map(fn, it): first arg
                    cands.append(node.args[0])
            elif isinstance(node.func, ast.Name) and \
                    node.func.id == "Thread":
                cands += [k.value for k in node.keywords
                          if k.arg == "target"]
            for c in cands:
                if isinstance(c, ast.Name):
                    self.thread_targets.add(c.id)
                elif isinstance(c, ast.Attribute):
                    self.thread_targets.add(c.attr)

    # -- traversal -------------------------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    def _thread_entry_pragma(self, node) -> bool:
        """Does the def (header lines, up to the first body statement)
        carry the `# lint: thread-entry` pragma?"""
        end = node.body[0].lineno if node.body else node.lineno
        return any(_THREAD_ENTRY.search(self.lines[ln - 1])
                   for ln in range(node.lineno, min(end, len(self.lines)) + 1)
                   if 1 <= ln <= len(self.lines))

    def _visit_fn(self, node) -> None:
        entered_thread = node.name in self.thread_targets \
            or self._thread_entry_pragma(node)
        self._fn_stack.append(_FunctionInfo(node))
        if entered_thread:
            self._thread_depth += 1
        self.generic_visit(node)
        if entered_thread:
            self._thread_depth -= 1
        self._fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_With(self, node: ast.With) -> None:
        locked = any(_is_lock_ctx(i) for i in node.items)
        if locked:
            self._lock_depth += 1
        self.generic_visit(node)
        if locked:
            self._lock_depth -= 1

    # -- write sites ------------------------------------------------------------
    def _check_store(self, target, stmt) -> None:
        # unwrap subscript stores: node.out_names[0] = x mutates out_names
        sub = target
        while isinstance(sub, ast.Subscript):
            sub = sub.value
        if isinstance(sub, ast.Attribute):
            self._check_attr_write(sub, stmt,
                                   subscript=sub is not target)
        # plain Name / Tuple targets mutate no object attribute

    def _check_attr_write(self, attr: ast.Attribute, stmt,
                          subscript: bool = False) -> None:
        root = _root_name(attr.value)
        # ENG001: frozen plan IR
        if self.engine_scope and attr.attr in PLAN_FIELDS \
                and not self._exempt(stmt.lineno, _FROZEN_EXEMPT):
            allowed = (root == "self" and not self._in_ir_class()) or \
                (root != "self" and self._owned(root))
            if not allowed:
                how = "subscript store into" if subscript else \
                    "in-place assignment to"
                self._add(stmt, "ENG001",
                          f"{how} plan-IR field "
                          f"'{_dotted(attr) or attr.attr}': plan nodes and "
                          "bound expressions are frozen — rebuild "
                          "copy-on-write (dataclasses.replace), or mark a "
                          "sanctioned builder with "
                          "'# lint: frozen-exempt (<reason>)'")
        # ENG002: unlocked write from a thread-target function
        if self._thread_depth > 0 and self._lock_depth == 0 \
                and not self._exempt(stmt.lineno, _LOCK_EXEMPT):
            if root and root != "self" and self._owned(root):
                return          # thread-local object, not shared state
            self._add(stmt, "ENG002",
                      f"attribute write '{_dotted(attr) or attr.attr}' in "
                      "a thread-target function outside any lock: shared "
                      "session/streaming state must be written under its "
                      "lock ('with <lock>:'), or mark thread-local state "
                      "with '# lint: lock-exempt (<reason>)'")

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._check_store(t, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_store(node.target, node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._check_store(node.target, node)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        # mutating container calls on plan-IR fields:
        # node.out_names.append(x)
        f = node.func
        if self.engine_scope and isinstance(f, ast.Attribute) and \
                f.attr in MUTATOR_METHODS and \
                isinstance(f.value, ast.Attribute) and \
                f.value.attr in PLAN_FIELDS and \
                not self._exempt(node.lineno, _FROZEN_EXEMPT):
            root = _root_name(f.value.value)
            allowed = (root == "self" and not self._in_ir_class()) or \
                (root != "self" and self._owned(root))
            if not allowed:
                self._add(node, "ENG001",
                          f"mutating call '{_dotted(f)}()' on a plan-IR "
                          "field: plan nodes are frozen — rebuild the list "
                          "copy-on-write")
        self.generic_visit(node)


def lint_source(path: str, src: str,
                engine_scope: bool | None = None) -> list[Finding]:
    """Lint one file's source; engine_scope controls ENG001 (defaults to
    'is this file under an engine/ directory or plan-IR heavy module')."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, 0, "ENG000",
                        f"syntax error: {e.msg}")]
    if engine_scope is None:
        engine_scope = True      # plan IR may be touched from anywhere
    linter = _Linter(path, src, engine_scope)
    linter.collect_thread_targets(tree)
    linter.visit(tree)
    return sorted(linter.findings, key=lambda f: (f.path, f.line, f.col))


def lint_paths(paths: list[str]) -> list[Finding]:
    import os
    findings: list[Finding] = []
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for base, _dirs, names in os.walk(p):
                if "__pycache__" in base:
                    continue
                files += [os.path.join(base, n) for n in sorted(names)
                          if n.endswith(".py")]
        else:
            files.append(p)
    for f in files:
        with open(f, encoding="utf-8") as fh:
            findings += lint_source(f, fh.read())
    return findings


def main(argv: list[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if not args:
        print("usage: lint_engine.py <path>...", file=sys.stderr)
        return 2
    findings = lint_paths(args)
    for f in findings:
        print(f)
    if findings:
        print(f"{len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
