#!/usr/bin/env python
"""Back-compat shim: the engine-discipline lint lives in nds_tpu.analysis.

The linter grew from two per-file rules into six whole-program families
(frozen plan IR, cross-thread locking, lock-order deadlock detection,
device-lane purity, typed-error discipline, counter discipline — see
``nds_tpu/analysis/__init__.py``). This file keeps the historical CLI
and import surface alive:

    python scripts/lint_engine.py nds_tpu          # same exit codes
    spec_from_file_location("lint_engine", ...)    # tests load it so

Everything re-exported here is the package's implementation; nothing is
duplicated.
"""
from __future__ import annotations

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from nds_tpu.analysis import Finding, lint_paths, lint_source, main  # noqa: E402,F401,I001
from nds_tpu.analysis.engine_rules import (  # noqa: E402,F401
    IR_CLASSES, MUTATOR_METHODS, PLAN_FIELDS)

if __name__ == "__main__":
    sys.exit(main())
