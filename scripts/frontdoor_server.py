#!/usr/bin/env python
"""Standalone engine process behind the Arrow-IPC front door.

Builds a Session (the chaos demo dataset with ``--demo``, or parquet
registrations via ``--table name=path``), wraps it in a QueryService
configured from the CLI flags, binds a FrontDoorServer on the requested
port (0 = ephemeral), then prints ONE machine-readable line on stdout::

    FRONTDOOR {"host": "127.0.0.1", "port": 43215, "pid": 12345, ...}

and serves until stdin reaches EOF or SIGTERM arrives.  Parent
processes (tests, the topology chaos campaign, frontdoor_bench) spawn
this script, read the FRONTDOOR line to learn the bound port, and close
the child's stdin to shut it down cleanly.

``--allow_chaos`` enables the wire ``chaos`` op so a parent can arm
FaultRegistry points (``frontdoor.drop``, ``frontdoor.kill``, ...)
inside THIS process remotely — required by the topology campaign, off
by default (a production front door must not accept fault injection).

Usage:
  python scripts/frontdoor_server.py --demo
  python scripts/frontdoor_server.py --demo --fair_queue \
      --tenant_weights interactive=4,batch=1 --preemption --query_log
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def parse_weights(text: str) -> dict:
    """``a=2,b=1`` -> {"a": 2.0, "b": 1.0} (the --tenant_weights grammar)."""
    out = {}
    for part in (text or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, _, w = part.partition("=")
        out[name.strip()] = float(w) if w else 1.0
    return out


def build_session(args, work_dir: str):
    log_kwargs = {}
    if args.query_log:
        log_kwargs = {"query_log": True,
                      "query_log_path": os.path.join(work_dir,
                                                     "query_log.jsonl")}
    if args.demo:
        from nds_tpu.chaos import build_demo_session
        return build_demo_session(
            work_dir, chunk_rows=args.chunk_rows,
            out_of_core_min_rows=args.out_of_core_min_rows, **log_kwargs)
    from nds_tpu.config import EngineConfig
    from nds_tpu.engine import Session
    session = Session(EngineConfig(
        chunk_rows=args.chunk_rows,
        out_of_core_min_rows=args.out_of_core_min_rows, **log_kwargs))
    for spec in args.table or []:
        name, _, path = spec.partition("=")
        if not name or not path:
            raise SystemExit(f"bad --table spec: {spec!r} (want name=path)")
        session.register_parquet(name, path)
    return session


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="frontdoor_server.py", description=(
        "one engine process serving the Arrow-IPC front door"))
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="0 binds an ephemeral port (printed on stdout)")
    p.add_argument("--demo", action="store_true",
                   help="serve the chaos demo dataset (fact/dim/sfact)")
    p.add_argument("--table", action="append", default=[],
                   metavar="NAME=PATH", help="register a parquet table")
    p.add_argument("--allow_chaos", action="store_true",
                   help="accept the wire 'chaos' op (fault injection)")
    p.add_argument("--fair_queue", action="store_true")
    p.add_argument("--tenant_weights", default="",
                   help="per-tenant weights, e.g. interactive=4,batch=1")
    p.add_argument("--preemption", action="store_true")
    p.add_argument("--preempt_max", type=int, default=2)
    p.add_argument("--inflight_dedup", action="store_true")
    p.add_argument("--result_cache", action="store_true")
    p.add_argument("--query_log", action="store_true",
                   help="durable query log + system tables (the bench "
                        "reads p99 from system.query_log over the wire)")
    p.add_argument("--max_pending", type=int, default=512)
    p.add_argument("--dispatch_timeout_s", type=float, default=0.0)
    p.add_argument("--chunk_rows", type=int, default=8192)
    p.add_argument("--out_of_core_min_rows", type=int, default=10_000)
    args = p.parse_args(argv)

    from nds_tpu.service import FrontDoorServer, QueryService, ServiceConfig

    work_dir = tempfile.mkdtemp(prefix="frontdoor_")
    session = build_session(args, work_dir)
    rc_cfg = None
    if args.result_cache:
        from nds_tpu.engine.result_cache import ResultCacheConfig
        rc_cfg = ResultCacheConfig()
    cfg = ServiceConfig(max_pending=args.max_pending,
                        dispatch_timeout_s=args.dispatch_timeout_s,
                        fair_queue=args.fair_queue,
                        tenant_weights=parse_weights(args.tenant_weights),
                        preemption=args.preemption,
                        preempt_max=args.preempt_max,
                        inflight_dedup=args.inflight_dedup,
                        result_cache=rc_cfg)
    svc = QueryService(session, cfg)
    svc.start()
    server = FrontDoorServer(svc, host=args.host, port=args.port,
                             allow_chaos=args.allow_chaos)
    server.start()
    print("FRONTDOOR " + json.dumps({
        "host": args.host, "port": server.port, "pid": os.getpid(),
        "epoch": server.epoch, "fair_queue": args.fair_queue,
        "preemption": args.preemption}), flush=True)

    stop = {"done": False}

    def _term(_sig, _frm):
        stop["done"] = True

    signal.signal(signal.SIGTERM, _term)
    try:
        # serve until the parent closes our stdin (the clean-shutdown
        # handshake) or SIGTERM flips the flag
        while not stop["done"]:
            line = sys.stdin.readline()
            if not line:
                break
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
        svc.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
