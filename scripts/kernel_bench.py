"""Per-kernel XLA-vs-Pallas microbenchmark (promotes scripts/exp_gather.py).

Times the three ISSUE-7 kernel families — tiled segmented sort, fused
group-by partial aggregation, batched multi-column gather — against their
generic XLA lowerings over a rows x dtype grid, with FETCH-BASED timings
(obs.device_time.measure_ms: the completion barrier is a device_get on
tunneled platforms, so standalone numbers don't read ~0 ms — the PERF.md
measurement caveat, fixed at the source). Every timed run reports into the
PR-6 per-program registry under a "kernel/<name>:<impl>" label, so the
microbench table carries the same per-program roofline fractions as the
engine's bench JSON.

Stdlib argparse only; run under a TPU for compiled Mosaic numbers or under
JAX_PLATFORMS=cpu for interpret-mode (code-path) numbers:

    python scripts/kernel_bench.py --rows 65536,262144 --dtypes int32,int64
    python scripts/kernel_bench.py --kernels gather --json
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="kernel_bench.py",
        description="XLA vs Pallas microbench per relational kernel "
                    "(fetch-based timings, per-program roofline table)")
    p.add_argument("--kernels", default="sort,groupby,gather",
                   help="comma subset of sort,groupby,gather")
    p.add_argument("--rows", default="65536,262144",
                   help="comma list of row counts")
    p.add_argument("--dtypes", default="int32,int64",
                   help="comma list of payload dtypes (int32,int64)")
    p.add_argument("--segments", type=int, default=1024,
                   help="group count for the groupby kernel")
    p.add_argument("--src_rows", type=int, default=1 << 18,
                   help="gather source-table rows (VMEM-staged)")
    p.add_argument("--gather_cols", type=int, default=4,
                   help="columns gathered per index vector")
    p.add_argument("--iters", type=int, default=5)
    p.add_argument("--warmup", type=int, default=2)
    p.add_argument("--bw_gbps", type=float, default=float(os.environ.get(
        "NDS_TPU_BENCH_BW_GBPS", "100")))
    p.add_argument("--no_x64", action="store_true",
                   help="keep 32-bit jax types (default enables x64, the "
                        "engine's measured configuration)")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON line per measurement instead of the "
                        "fixed-width table")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = _parse_args(argv)
    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
    import jax
    import jax.numpy as jnp
    import numpy as np

    if not args.no_x64:
        jax.config.update("jax_enable_x64", True)
    from nds_tpu.engine.jax_backend import pallas_kernels as pk
    from nds_tpu.obs.device_time import (PROGRAMS, format_table, measure_ms)

    mode, reason = pk.probe()
    if mode == "off":
        print(f"pallas unavailable: {reason} (XLA rows still measured)",
              file=sys.stderr)
    pk.set_active(pk.parse_ops(args.kernels) if mode != "off"
                  else frozenset())
    kernels = [k.strip() for k in args.kernels.split(",") if k.strip()]
    rows_grid = [int(r) for r in args.rows.split(",") if r]
    dtypes = [d.strip() for d in args.dtypes.split(",") if d.strip()]
    rng = np.random.default_rng(778)
    records: list[dict] = []

    def run_pair(name: str, n: int, dt: str, xla_fn, pallas_fn,
                 bytes_accessed: float, args_):
        for impl, fn in (("xla", xla_fn), ("pallas", pallas_fn)):
            if fn is None:
                continue
            label = f"kernel/{name}:{impl}"
            jfn = jax.jit(fn)
            ms = measure_ms(jfn, *args_, iters=args.iters,
                            warmup=args.warmup, label=label)
            PROGRAMS.record_cost(label, {"flops": 0.0,
                                         "bytes accessed": bytes_accessed})
            records.append({"kernel": name, "impl": impl, "rows": n,
                            "dtype": dt, "best_ms": round(ms, 3),
                            "mode": mode if impl == "pallas" else "xla"})

    for dt in dtypes:
        jdt = jnp.dtype(dt)
        for n in rows_grid:
            key = jnp.asarray(rng.integers(0, 1 << 30, n), jdt)
            iota = jnp.arange(n, dtype=jnp.int32)
            if "sort" in kernels:
                from jax import lax
                run_pair(
                    f"sort[{dt},{n}]", n, dt,
                    lambda k, i: lax.sort((k, i), num_keys=1,
                                          is_stable=True),
                    (lambda k, i: pk.sort_pairs(k, i))
                    if mode != "off" else None,
                    # one read + one write of both operands per merge pass
                    2.0 * (key.nbytes + iota.nbytes) *
                    max(1, n.bit_length() - 1),
                    (key, iota))
            if "groupby" in kernels:
                S = args.segments
                gid = jnp.asarray(rng.integers(0, S, n), jnp.int32)
                data = jnp.asarray(rng.integers(0, 1000, n), jdt)

                def xla_gb(g, d, S=S):
                    return (jax.ops.segment_sum(d, g, num_segments=S),
                            jax.ops.segment_min(d, g, num_segments=S),
                            jax.ops.segment_max(d, g, num_segments=S))

                def pallas_gb(g, d, S=S):
                    return tuple(pk.seg_reduce_multi(
                        [(d, "sum"), (d, "min"), (d, "max")], g, S))

                run_pair(f"groupby[{dt},{n},S={S}]", n, dt, xla_gb,
                         pallas_gb if mode != "off" else None,
                         float(gid.nbytes + 3 * data.nbytes), (gid, data))
            if "gather" in kernels:
                srcs = [jnp.asarray(rng.integers(0, 1 << 30, args.src_rows),
                                    jdt) for _ in range(args.gather_cols)]
                idx = jnp.asarray(rng.integers(0, args.src_rows, n),
                                  jnp.int32)

                def xla_ga(i, *ss):
                    return tuple(s[i] for s in ss)

                def pallas_ga(i, *ss):
                    return tuple(pk.take_many(list(ss), i))

                run_pair(f"gather[{dt},{n}x{args.gather_cols}]", n, dt,
                         xla_ga, pallas_ga if mode != "off" else None,
                         float(idx.nbytes +
                               sum(s.nbytes for s in srcs) +
                               args.gather_cols * n * jdt.itemsize),
                         (idx, *srcs))

    if args.json:
        for r in records:
            print(json.dumps(r))
    else:
        print(f"pallas mode: {mode}" + (f" ({reason})" if reason else ""))
        print(format_table(PROGRAMS.table(bw_gbps=args.bw_gbps)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
