"""SF100 result validation (VERDICT r4 #8): check completed SF100 queries
against an independently computed answer.

The numpy oracle at SF100 would take hours on this 1-core host, so the
check is a DuckDB-free, pyarrow-compute-based recomputation per query of
the aggregate invariants the query's answer must satisfy — for the
simple-aggregate queries — plus, where feasible, an exact recomputation
over the pruned column set. Each check reads the same warehouse snapshot
the chip run read.

Usage: python scripts/validate_sf100.py <outputs_dir> [query3 ...]
Writes results_r5/sf100_validation.md.
"""
import os
import sys

import numpy as np
import pyarrow.parquet as pq

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from nds_tpu.config import EngineConfig, enable_x64  # noqa: E402

enable_x64()

from nds_tpu.engine.session import Session            # noqa: E402
from nds_tpu.streams import instantiate               # noqa: E402
from nds_tpu.warehouse import Warehouse               # noqa: E402

WH = ".bench_data/sf100_wh"


def chip_result(outputs: str, qname: str):
    d = os.path.join(outputs, qname)
    files = [os.path.join(d, f) for f in sorted(os.listdir(d))
             if f.endswith(".parquet")]
    import pyarrow as pa
    return pa.concat_tables([pq.read_table(f) for f in files])


def oracle_rows(qnum: int, sample_frac: float | None = None):
    """Numpy-oracle recomputation. For single-fact aggregate queries the
    pruned column set keeps this within host memory at SF100."""
    s = Session(EngineConfig(decimal_physical="i64", use_jax=False,
                             out_of_core=False))
    Warehouse(WH).register_all(s)
    sql = [q for q in instantiate(qnum, 0, 778).split(";") if q.strip()][0]
    return s.sql(sql, backend="numpy")


def compare(chip, oracle) -> tuple[bool, str]:
    import pyarrow as pa
    from nds_tpu.engine import arrow_bridge
    otbl = arrow_bridge.to_arrow(oracle)
    if chip.num_rows != otbl.num_rows:
        return False, f"row count {chip.num_rows} vs {otbl.num_rows}"
    bad = 0
    for i in range(chip.num_columns):
        a = chip.column(i).to_pylist()
        b = otbl.column(i).to_pylist()
        for x, y in zip(a, b):
            if x is None or y is None:
                if x is not y:
                    bad += 1
                continue
            if isinstance(x, float) or isinstance(y, float):
                fx, fy = float(x), float(y)
                if abs(fx - fy) > 1e-4 * max(1.0, abs(fx), abs(fy)):
                    bad += 1
            elif str(x) != str(y):
                bad += 1
    return bad == 0, f"{bad} differing cells" if bad else "exact"


def main():
    outputs = sys.argv[1]
    queries = sys.argv[2:] or sorted(os.listdir(outputs))
    lines = ["# SF100 validation (chip outputs vs 1-core numpy oracle)",
             "", f"outputs: {outputs}", ""]
    for qname in queries:
        qnum = int(qname.replace("query", "").split("_")[0])
        try:
            chip = chip_result(outputs, qname)
            oracle = oracle_rows(qnum)
            ok, detail = compare(chip, oracle)
            status = "Pass" if ok else "FAIL"
        except MemoryError:
            status, detail = "Skipped", "oracle exceeds host memory"
        except Exception as e:  # noqa: BLE001
            status, detail = "Error", f"{type(e).__name__}: {e}"[:200]
        print(f"{qname}: {status} ({detail})", flush=True)
        lines.append(f"- {qname}: **{status}** ({detail})")
    os.makedirs("results_r5", exist_ok=True)
    with open("results_r5/sf100_validation.md", "w") as f:
        f.write("\n".join(lines) + "\n")


if __name__ == "__main__":
    main()
