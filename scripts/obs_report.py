#!/usr/bin/env python
"""Service-SLO report: histogram quantile tables + flight-recorder view.

``trace_report.py`` summarizes spans and programs; this tool reads the
DISTRIBUTION side of the observability layer — the histogram snapshots
(``obs.metrics.Histogram``) that bench / service_bench / power embed
under a ``histograms`` key, and flight-recorder JSONL dumps
(``obs.flight``) — and prints the SLO tables an operator reads first:

- per-family quantile tables (count / mean / p50 / p95 / p99 / max ms)
  with one row per labeled series, slowest p99 first;
- the per-tenant SLO view of ``service_latency_ms`` and the top-K slow
  templates (``--family`` / ``--by`` select others);
- flight-recorder dumps: event-type counts, per-tenant outcomes, and the
  slowest completed tickets (delegates to trace_report's renderer so the
  two tools agree).

Artifacts accepted (auto-detected): a bench/service-bench/power JSON
carrying ``histograms`` (or a raw ``MetricsRegistry.export_json()``
dump), or a flight-recorder JSONL. ``--prometheus`` re-renders a JSON
artifact's histograms + counters in Prometheus text exposition format
(the live-process form of the same text comes from
``METRICS.export_prometheus()``).

``--compare BENCH_r*.json`` reads SEVERAL bench rounds (in argument
order) and prints the cross-round perf trajectory: per-round wall /
upload volume / count-shaped counters (compiles, decode sites, host
decode wall) and the per-query best-latency table, with regressions vs
the previous round highlighted — the bench history finally has a reader.
A bench/power artifact carrying EXPLAIN ANALYZE ``profiles`` renders
their annotated trees too (scripts/explain_report.py is the dedicated
renderer).

Usage:
  python scripts/obs_report.py SERVICE_r01.json
  python scripts/obs_report.py flight_fault_*.jsonl
  python scripts/obs_report.py bench.json --family query_latency_ms
  python scripts/obs_report.py bench.json --prometheus > metrics.prom
  python scripts/obs_report.py --compare BENCH_r01.json BENCH_r05.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from nds_tpu.obs.metrics import quantile_from_snapshot  # noqa: E402

QS = (0.5, 0.95, 0.99)


def load(path: str):
    """(kind, payload): kind is "hists" ({series: snapshot} + metrics) or
    "flight" (event list)."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        events = [json.loads(ln) for ln in text.splitlines() if ln.strip()]
        if events and all("event" in e for e in events):
            return "flight", events
        raise ValueError(f"{path}: not a JSON artifact or flight JSONL")
    if isinstance(doc, dict):
        if "event" in doc and "t_ms" in doc:
            return "flight", [doc]      # a one-event JSONL dump
        hists = doc.get("histograms")
        if hists is None and "runs" in doc:
            # a service-bench record without embedded snapshots still has
            # its per-run SLO rows; surface those
            return "service_runs", doc
        if hists is not None:
            return "hists", doc
    raise ValueError(f"{path}: no 'histograms' key (re-run the producer "
                     "on this branch, or pass a flight JSONL)")


def rows_for_family(hists: dict, family: str) -> list[dict]:
    rows = []
    for key, snap in hists.items():
        if snap.get("name", key) != family:
            continue
        row = {"series": key, "labels": snap.get("labels", {}),
               "count": snap["count"],
               "mean": snap["sum"] / snap["count"] if snap["count"] else 0,
               "max": snap.get("max") or 0}
        for p in QS:
            q = quantile_from_snapshot(snap, p)
            row[f"p{int(p * 100)}"] = q if q is not None else 0
        rows.append(row)
    rows.sort(key=lambda r: (bool(r["labels"]), -r["p99"]))
    return rows


def print_family(hists: dict, family: str, by: str, top: int) -> None:
    rows = rows_for_family(hists, family)
    if not rows:
        return
    print(f"\n{family} (count / mean / p50 / p95 / p99 / max ms):")
    head = (f"{'series':<52} {'count':>7} {'mean':>9} {'p50':>9} "
            f"{'p95':>9} {'p99':>9} {'max':>9}")
    print(head)
    print("-" * len(head))
    shown = 0
    for r in rows:
        if r["labels"] and shown >= top:
            continue
        tag = ",".join(f"{k}={v}" for k, v in sorted(r["labels"].items())) \
            or "(all)"
        print(f"{tag[:52]:<52} {r['count']:>7} {r['mean']:>9.1f} "
              f"{r['p50']:>9.1f} {r['p95']:>9.1f} {r['p99']:>9.1f} "
              f"{r['max']:>9.1f}")
        shown += bool(r["labels"])
    if by:
        # rollup by one label dimension (merge counts; quantiles cannot
        # merge without the buckets, so roll the bucket lists up)
        from nds_tpu.obs.metrics import merge_snapshots
        groups: dict[str, dict] = {}
        for key, snap in hists.items():
            if snap.get("name") != family or by not in \
                    snap.get("labels", {}):
                continue
            g = snap["labels"][by]
            groups[g] = merge_snapshots(groups[g], snap) if g in groups \
                else dict(snap)
        if groups:
            print(f"\n{family} by {by}:")
            for g, snap in sorted(
                    groups.items(),
                    key=lambda kv: -(quantile_from_snapshot(kv[1], 0.99)
                                     or 0))[:top]:
                qs = {p: quantile_from_snapshot(snap, p) or 0 for p in QS}
                print(f"  {g[:24]:<24} n={snap['count']:<7} "
                      f"p50={qs[0.5]:>8.1f} p95={qs[0.95]:>8.1f} "
                      f"p99={qs[0.99]:>8.1f}")


#: cross-round counters worth trending (count-shaped + the two honest
#: volume/wall numbers); regressions highlight when a round moves past
#: REGRESS_RATIO of the previous round's value
COMPARE_METRICS = ("compiles", "program_cache_misses", "replay_mismatches",
                   "host_fallbacks", "morsels", "decode_sites",
                   "bytes_uploaded", "host_decode_ms")
REGRESS_RATIO = 1.2


def _per_query_best(doc: dict) -> dict:
    """{template: best (min) latency ms} from a bench JSON's
    query_latency_ms histogram series (exact min rides every snapshot)."""
    out = {}
    for _key, snap in (doc.get("histograms") or {}).items():
        if snap.get("name") != "query_latency_ms":
            continue
        tpl = snap.get("labels", {}).get("template")
        if tpl and snap.get("min") is not None:
            out[tpl] = snap["min"]
    return out


def print_compare(paths: list, docs: list) -> list[str]:
    """Cross-round perf trajectory over several bench JSONs (argument
    order = round order): headline wall + upload volume, the trended
    counters, and per-query best latencies — each cell flagged when it
    regressed more than REGRESS_RATIO vs the PREVIOUS round. Returns
    the flagged row labels (``"compiles@BENCH_r05"``) so ``--gate`` can
    fail CI on them."""
    names = [os.path.basename(p).replace(".json", "") for p in paths]
    width = max(12, max(len(n) for n in names) + 1)
    flagged: list[str] = []

    def row(label, vals, fmt="{:.1f}", flag_up=True):
        cells = []
        prev = None
        for i, v in enumerate(vals):
            if v is None:
                cells.append(f"{'-':>{width}}")
                prev = None
                continue
            txt = fmt.format(v)
            if prev is not None and prev > 0 and \
                    (v / prev >= REGRESS_RATIO if flag_up
                     else v / prev <= 1 / REGRESS_RATIO):
                txt += "!"
                flagged.append(f"{label}@{names[i]}")
            cells.append(f"{txt:>{width}}")
            prev = v
        print(f"{label:<26}" + "".join(cells))

    print("cross-round perf trajectory ('!' = regressed >"
          f"{REGRESS_RATIO - 1:.0%} vs previous round):")
    print(f"{'round':<26}" + "".join(f"{n[:width - 1]:>{width}}"
                                     for n in names))
    row("wall_ms (slice total)", [d.get("value") for d in docs])
    row("upload_gb", [d.get("upload_gb") for d in docs], "{:.3f}")
    row("rows_per_s", [d.get("rows_per_s") for d in docs], "{:.0f}",
        flag_up=False)
    for m in COMPARE_METRICS:
        vals = [(d.get("metrics") or {}).get(m) for d in docs]
        if any(v for v in vals):
            row(m, vals, "{:.0f}")
    templates = sorted({t for d in docs for t in _per_query_best(d)})
    if templates:
        print("\nper-query best latency (ms):")
        for t in templates:
            row(t, [_per_query_best(d).get(t) for d in docs])
    return flagged


def gate_flags(flagged: list[str], allow: list[str]) -> list[str]:
    """--gate verdict: flags not waived by --allow. A waiver matches the
    bare row label ("compiles", "query3") or the exact flag cell
    ("compiles@BENCH_r05") — waive the known intentional change, keep
    gating everything else."""
    allowed = {a.strip() for a in allow if a.strip()}
    return [f for f in flagged
            if f not in allowed and f.split("@", 1)[0] not in allowed]


def print_profiles(doc: dict, top: int) -> bool:
    """Render EXPLAIN ANALYZE profiles embedded in an artifact (a
    ``profiles`` list or dict of PlanProfile.to_dict() payloads)."""
    profs = doc.get("profiles")
    if not profs:
        return False
    from nds_tpu.obs.profile import PlanProfile
    items = profs.values() if isinstance(profs, dict) else profs
    for p in items:
        print(PlanProfile.from_dict(p).render(top_findings=top))
        print()
    return True


def print_prometheus(doc: dict) -> None:
    """Prometheus text exposition of an artifact's metrics + histograms
    (offline twin of METRICS.export_prometheus())."""
    for name, v in (doc.get("metrics") or {}).items():
        print(f"{name}_total {v}")
    for _key, snap in (doc.get("histograms") or {}).items():
        base = ",".join(f'{k}="{v}"' for k, v in
                        sorted(snap.get("labels", {}).items()))
        sep = "," if base else ""
        cum = 0
        for le, n in snap.get("buckets", ()):
            cum += n
            letxt = f"{le:.6g}" if le is not None else "+Inf"
            print(f'{snap["name"]}_bucket{{{base}{sep}le="{letxt}"}} {cum}')
        lab = f"{{{base}}}" if base else ""
        print(f"{snap['name']}_sum{lab} {snap['sum']}")
        print(f"{snap['name']}_count{lab} {snap['count']}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="obs_report.py", description=(
        "histogram/SLO + flight-recorder summarizer for NDS-TPU "
        "observability artifacts"))
    p.add_argument("artifact", nargs="+",
                   help="JSON with a 'histograms' block "
                        "(bench/service_bench/export_json) or a "
                        "flight-recorder JSONL dump; several bench "
                        "JSONs with --compare")
    p.add_argument("--compare", action="store_true",
                   help="cross-round perf-trajectory table over several "
                        "bench JSONs (argument order = round order): "
                        "per-query wall, bytes uploaded, decode/compile "
                        "counters, regressions vs the previous round "
                        "highlighted")
    p.add_argument("--gate", action="store_true",
                   help="with --compare: exit 1 when any '!'-flagged "
                        ">20%% regression is present (the cross-round "
                        "reader can FAIL CI instead of only printing "
                        "flags); waive known-intentional rows with "
                        "--allow")
    p.add_argument("--allow", default="",
                   help="comma list of waived rows for --gate: a bare "
                        "row label ('compiles', 'query3') waives it in "
                        "every round, 'label@ROUND' one specific cell")
    p.add_argument("--family", default=None,
                   help="histogram family to print (default: every "
                        "family present, service_latency_ms first)")
    p.add_argument("--by", default="tenant",
                   help="label dimension for the rollup table "
                        "(tenant|template; '' disables)")
    p.add_argument("--top", type=int, default=12,
                   help="labeled rows / rollup groups per table")
    p.add_argument("--prometheus", action="store_true",
                   help="emit the artifact's metrics + histograms in "
                        "Prometheus text exposition format instead of "
                        "tables")
    a = p.parse_args(argv)
    if a.compare or len(a.artifact) > 1:
        docs = []
        for path in a.artifact:
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, json.JSONDecodeError) as e:
                print(f"obs_report: {path}: {e}", file=sys.stderr)
                return 2
            if not isinstance(doc, dict):
                print(f"obs_report: {path}: not a JSON object",
                      file=sys.stderr)
                return 2
            # driver-recorded rounds wrap the bench JSON under "parsed"
            if isinstance(doc.get("parsed"), dict):
                doc = doc["parsed"]
            docs.append(doc)
        flagged = print_compare(a.artifact, docs)
        if a.gate:
            offending = gate_flags(flagged, a.allow.split(","))
            if offending:
                for f in offending:
                    print(f"obs_report: GATE regression {f}",
                          file=sys.stderr)
                print(f"obs_report: GATE FAIL ({len(offending)} "
                      "regressions; waive intentional ones with "
                      "--allow)", file=sys.stderr)
                return 1
            print("obs_report: GATE OK "
                  f"({len(flagged)} flags, all waived)" if flagged
                  else "obs_report: GATE OK (no regressions)",
                  file=sys.stderr)
        return 0
    try:
        kind, payload = load(a.artifact[0])
    except (ValueError, OSError) as e:
        print(f"obs_report: {e}", file=sys.stderr)
        return 2
    if kind == "flight":
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import trace_report
        trace_report.print_flight(payload, a.top)
        return 0
    if kind == "service_runs":
        for run in payload.get("runs", []):
            print(f"clients={run.get('clients')}: qps={run.get('qps')} "
                  f"p50={run.get('p50_ms')} p99={run.get('p99_ms')}")
            for row in run.get("per_tenant_slo", [])[:a.top]:
                print(f"  {row.get('tenant'):<12} "
                      f"template={row.get('template')} "
                      f"n={row.get('count')} p50={row.get('p50_ms')} "
                      f"p95={row.get('p95_ms')} p99={row.get('p99_ms')}")
        return 0
    hists = payload["histograms"]
    if a.prometheus:
        print_prometheus(payload)
        return 0
    if print_profiles(payload, a.top):
        print()
    families = [a.family] if a.family else sorted(
        {s.get("name", k) for k, s in hists.items()},
        key=lambda n: (n != "service_latency_ms", n))
    if not hists:
        print("no histogram series recorded in this artifact")
        return 0
    for fam in families:
        print_family(hists, fam, a.by, a.top)
    return 0


if __name__ == "__main__":
    sys.exit(main())
