"""Sq=4 throughput-concurrency measurement on one chip (VERDICT r3 #4).

Methodology (matches the round-3 2-stream measurement in PERF.md): one
process, one session per stream, every (stream, query) pre-run to the
compiled steady state, then (a) the 4 streams run back-to-back serially,
(b) the 4 streams run concurrently on 4 threads sharing the chip.
Concurrency efficiency = serial_total / concurrent_elapsed (2.0 means two
chips' worth of work in one chip's wall-clock; 4.0 is the ceiling).

The reference's throughput test is N full Spark apps via xargs -P
(nds/nds-throughput) arbitrated by the cluster scheduler; here N sessions
multiplex one TPU via XLA async dispatch, so one stream's host phases
overlap another's device work.
"""
from __future__ import annotations

import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

QUERIES = os.environ.get("T4_QUERIES",
                         "query1,query2,query3,query4,query5,query6,"
                         "query7,query8,query9,query10").split(",")
WH = os.environ.get("T4_WAREHOUSE", ".bench_data/sf1_wh")
STREAMS = os.environ.get("T4_STREAMS", ".bench_data/sf1_streams5")


def main() -> int:
    from nds_tpu.config import EngineConfig, apply_decimal, \
        maybe_enable_compile_cache
    maybe_enable_compile_cache()
    cfg0 = EngineConfig()
    apply_decimal(cfg0, "i64")

    from nds_tpu.engine import Session
    from nds_tpu.power import gen_sql_from_stream, setup_tables

    sessions = []
    plans: list[list[tuple[str, str]]] = []
    for sid in (1, 2, 3, 4):
        cfg = EngineConfig(decimal_physical="i64")
        s = Session(cfg)
        setup_tables(s, WH, "parquet")
        qd = gen_sql_from_stream(
            open(os.path.join(STREAMS, f"query_{sid}.sql")).read())
        work = [(n, sql) for n, sql in qd.items()
                if n in QUERIES or n.rsplit("_part", 1)[0] in QUERIES]
        sessions.append(s)
        plans.append(work)

    def run_stream(i: int) -> float:
        t0 = time.perf_counter()
        s = sessions[i]
        for name, sql in plans[i]:
            for stmt in [x for x in sql.split(";") if x.strip()]:
                s.sql(stmt, backend="jax")
        return time.perf_counter() - t0

    # steady state: two pre-runs per stream (record+compile, then warm)
    for r in range(2):
        for i in range(4):
            dt = run_stream(i)
            print(f"warm{r} stream{i + 1}: {dt:.2f}s", flush=True)

    serial = [run_stream(i) for i in range(4)]
    print("serial per-stream s:", [round(x, 2) for x in serial], flush=True)

    t0 = time.perf_counter()
    with ThreadPoolExecutor(max_workers=4) as pool:
        list(pool.map(run_stream, range(4)))
    concurrent = time.perf_counter() - t0

    eff = sum(serial) / concurrent
    print(f"serial_total={sum(serial):.2f}s concurrent={concurrent:.2f}s "
          f"efficiency={eff:.2f}x", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
