#!/usr/bin/env python
"""Open-loop query-service benchmark -> SERVICE_r*.json.

Measures the concurrent query service (nds_tpu/service) the way ROADMAP
item 4 demands it be measured: sustained QPS and tail latency under N
CONCURRENT CLIENTS against the serial one-query-at-a-time baseline on the
same host — not stream-elapsed. The workload is dashboard-shaped
interactive analytics over the SF0.01 NDS warehouse: T parameterized
templates, each with a shared pool of literal instantiations, clients
drawing from the pool (cross-client text repeats and compatible
parameterized plans are the NORM, exactly the shape the shared plan/
program cache and compatible-plan batching exist for).

Phases:
  1. serial baseline — a fresh single-caller Session runs the whole
     workload one query at a time (after per-template warmup), recording
     wall, per-query latency, and a result hash per distinct text;
  2. per clients count C — a fresh Session + QueryService, per-template
     warmup (record + compile + publish), a short surge at concurrency C
     to warm batched program shapes, then the measured window: C client
     threads each submit-and-wait through their query lists. Every
     response hashes against the serial baseline (bit-identity is part of
     the record), latency decomposes into queue_wait + execute via
     ExecStats.queue_wait_ms, and batching shows up as batched_with.

Latency percentiles (p50/p99, queue-wait) come from the REGISTRY
histograms (obs.metrics — the same per-tenant/per-template SLO source a
live operator reads), cut to the measured window via snapshot diffs; a
``percentile_check`` block cross-checks them against exact per-ticket
latencies from the flight recorder within the histogram's documented
bucket-error bound, and ``per_tenant_slo`` records the slowest tenants.
``--trace`` exports one Chrome trace per client count showing every
ticket's parent-linked admission->plan->dispatch->materialize spans.

Writes one JSON record (default SERVICE_r01.json) and prints it to
stdout. Diagnostics go to stderr.

Usage:
  python scripts/service_bench.py                      # 10 and 100 clients
  python scripts/service_bench.py --clients 10,100,1000 --total_queries 1000
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: dashboard-shaped parameterized templates over the NDS warehouse. Every
#: hoistable literal varies per instantiation, so instantiations of one
#: template parameterize to ONE plan fingerprint (compatible plans).
#: pool size per template: dashboard workloads repeat a SMALL set of
#: distinct texts across many users — in-window dedup (one batched row
#: serving every parameter-identical query) is the compute lever
TEMPLATES = {
    "store_qty": (
        "SELECT ss_store_sk, COUNT(*) AS n, SUM(ss_quantity) AS q "
        "FROM store_sales WHERE ss_quantity BETWEEN {a} AND {b} "
        "GROUP BY ss_store_sk ORDER BY ss_store_sk"),
    "year_sales": (
        "SELECT d_year, COUNT(*) AS n, SUM(ss_quantity) AS q "
        "FROM store_sales JOIN date_dim ON ss_sold_date_sk = d_date_sk "
        "WHERE ss_quantity < {a} GROUP BY d_year ORDER BY d_year"),
    "category_rev": (
        "SELECT i_category, COUNT(*) AS n, "
        "SUM(ss_ext_sales_price) AS rev "
        "FROM store_sales JOIN item ON ss_item_sk = i_item_sk "
        "WHERE ss_quantity BETWEEN {a} AND {b} "
        "GROUP BY i_category ORDER BY i_category"),
}
POOL_PER_TEMPLATE = 8


def build_pool() -> list[tuple[str, str]]:
    """[(label, sql)]: the shared instantiation pool clients draw from.

    Parameter ranges stay away from degenerate selectivities (an empty
    filter flips data-dependent EXACT schedule decisions, which correctly
    marks the template's shared entry volatile and disables sharing — the
    engine's contract, but not the dashboard shape this bench models)."""
    pool = []
    for name, tpl in TEMPLATES.items():
        for i in range(POOL_PER_TEMPLATE):
            pool.append((f"{name}#{i}",
                         tpl.format(a=20 + i, b=60 + 2 * i)))
    return pool


def warm_texts() -> list[tuple[str, str]]:
    """One COVERING instantiation per template: parameters chosen so its
    filter contains every pool member's (a = pool minimum, b = pool
    maximum). The capacity schedule recorded from it dominates the whole
    pool — cap checks are <=, so no pool member can ReplayMismatch a
    program warmed this way (the cap-merge loop would converge to the
    same schedule, this just skips the thrash)."""
    a_min = 20
    a_max = 20 + (POOL_PER_TEMPLATE - 1)
    b_max = 60 + 2 * (POOL_PER_TEMPLATE - 1)
    cover = {  # widest filter per template shape
        "store_qty": dict(a=a_min, b=b_max),
        "year_sales": dict(a=a_max, b=b_max),     # "< a": max a covers
        "category_rev": dict(a=a_min, b=b_max),
    }
    return [(f"warm-{name}", tpl.format(**cover[name]))
            for name, tpl in TEMPLATES.items()]


def result_hash(table) -> str:
    return hashlib.sha1(
        repr(table.to_pylist()).encode()).hexdigest()[:16]


def hist_window(before: dict, after: dict, name: str) -> dict | None:
    """The measured window's snapshot of one registry histogram series:
    after minus before (bucket counts are monotonic)."""
    from nds_tpu.obs.metrics import diff_snapshot
    if name not in after:
        return None
    return diff_snapshot(after[name], before.get(name, {}))


def _hq(snap: dict | None, p: float) -> float:
    """Histogram quantile of a window snapshot, rounded for the record."""
    from nds_tpu.obs.metrics import quantile_from_snapshot
    q = quantile_from_snapshot(snap, p) if snap else None
    return round(q, 2) if q is not None else 0.0


def _percentile_check(lat_hist: dict | None, exact_lat: list) -> dict:
    """The acceptance cross-check: registry-histogram percentiles vs the
    exact per-ticket service latencies (flight-recorder complete events),
    with the histogram's DOCUMENTED error bound (a factor of
    sqrt(BUCKET_RATIO) ≈ 1.123) recorded beside the observed ratios."""
    from nds_tpu.obs.metrics import (BUCKET_RATIO, exact_quantile,
                                     quantile_from_snapshot)
    out = {"bound_factor": round(BUCKET_RATIO ** 0.5, 4),
           "samples": len(exact_lat)}
    for p in (0.50, 0.95, 0.99):
        exact = exact_quantile(exact_lat, p)
        hist = quantile_from_snapshot(lat_hist, p) if lat_hist else None
        key = f"p{int(p * 100)}"
        out[f"exact_{key}_ms"] = round(exact, 2)
        out[f"hist_{key}_ms"] = round(hist, 2) if hist is not None else None
        if hist and exact:
            out[f"{key}_ratio"] = round(hist / exact, 4)
            out[f"{key}_within_bound"] = \
                1 / (BUCKET_RATIO ** 0.5) <= hist / exact \
                <= BUCKET_RATIO ** 0.5
    return out


def _tenant_slo(h_before: dict, h_after: dict, top: int = 8) -> list:
    """Per-tenant window SLO rows (slowest p99 first): the live-registry
    per-tenant view the acceptance criterion asks for, cut to the
    measured window via snapshot diffs."""
    from nds_tpu.obs.metrics import quantile_from_snapshot
    rows = []
    for key, snap in h_after.items():
        if snap["name"] != "service_latency_ms" or "labels" not in snap:
            continue
        win = hist_window(h_before, h_after, key)
        if not win or not win["count"]:
            continue
        rows.append({
            "tenant": snap["labels"].get("tenant"),
            "template": snap["labels"].get("template"),
            "count": win["count"],
            "p50_ms": _hq(win, 0.50), "p95_ms": _hq(win, 0.95),
            "p99_ms": _hq(win, 0.99)})
    rows.sort(key=lambda r: r["p99_ms"], reverse=True)
    return rows[:top]


def make_session(wh_dir: str):
    from nds_tpu.config import EngineConfig
    from nds_tpu.engine import Session
    from nds_tpu.power import setup_tables

    decimal = os.environ.get("NDS_TPU_BENCH_DECIMAL", "i64")
    if decimal == "i64":
        from nds_tpu.config import enable_x64
        enable_x64()
    session = Session(EngineConfig(decimal_physical=decimal))
    setup_tables(session, wh_dir, "parquet")
    return session


def workload_for(pool, clients: int, per_client: int,
                 zipf: float = 0.0):
    """Deterministic per-client query lists drawn from the shared pool.

    zipf > 0 skews the draw: pool position is popularity rank and member
    i is picked with probability ∝ (i+1)^-zipf — the template × parameter
    mix real dashboard traffic has (a few hot texts dominate), which is
    exactly the shape the semantic result cache exists for. 0 = uniform
    (the pre-r03 workload)."""
    import numpy as np
    n = len(pool)
    p = None
    if zipf > 0:
        w = np.arange(1, n + 1, dtype=float) ** (-zipf)
        p = w / w.sum()
    out = []
    for cid in range(clients):
        rng = np.random.default_rng(1000 + cid)
        picks = rng.choice(n, size=per_client, p=p) if p is not None \
            else rng.integers(0, n, per_client)
        out.append([pool[int(i)] for i in picks])
    return out


def run_serial(wh_dir: str, pool, lists, log) -> dict:
    """The baseline the service must beat: same total workload, one query
    at a time on a fresh single-caller Session."""
    from nds_tpu.engine.jax_backend.executor import clear_shared_programs
    from nds_tpu.obs.metrics import exact_quantile

    clear_shared_programs()
    session = make_session(wh_dir)
    for label, sql in warm_texts():
        session.sql(sql, label=label)
        session.sql(sql, label=label)
    hashes: dict[str, str] = {}
    lat: list[float] = []
    t0 = time.perf_counter()
    for qlist in lists:
        for label, sql in qlist:
            q0 = time.perf_counter()
            res = session.sql(sql, label=label)
            lat.append((time.perf_counter() - q0) * 1000.0)
            if sql not in hashes:
                hashes[sql] = result_hash(res)
    wall = time.perf_counter() - t0
    lat.sort()
    total = sum(len(x) for x in lists)
    rec = {"queries": total, "wall_s": round(wall, 3),
           "qps": round(total / wall, 1),
           "p50_ms": round(exact_quantile(lat, 0.50), 2),
           "p99_ms": round(exact_quantile(lat, 0.99), 2)}
    log(f"serial: {total} queries in {wall:.2f}s = {rec['qps']} QPS, "
        f"p50 {rec['p50_ms']} ms, p99 {rec['p99_ms']} ms")
    rec["_hashes"] = hashes
    return rec


def _system_poll_check(svc, h_before, h_after) -> dict:
    """The acceptance cross-check for system-table polling: per-tenant
    p50/p95/p99 computed from SQL over ``system.query_log`` (exact — the
    log holds every completion) vs the live registry histograms
    (``METRICS.percentiles``' source), within the documented ~12% bucket
    bound. The SQL fetch itself rides the system bypass — the check IS
    a system poll."""
    from nds_tpu.engine.arrow_bridge import to_arrow
    from nds_tpu.obs.metrics import (BUCKET_RATIO, exact_quantile,
                                     merge_snapshots,
                                     quantile_from_snapshot)
    bound = BUCKET_RATIO ** 0.5
    rows = to_arrow(svc.sql(
        "SELECT tenant, wall_ms FROM system.query_log "
        "WHERE status = 'ok' AND source = 'service'")).to_pylist()
    by_tenant: dict[str, list[float]] = {}
    for r in rows:
        if r["tenant"] and r["wall_ms"] is not None:
            by_tenant.setdefault(r["tenant"], []).append(r["wall_ms"])
    per = []
    n_ok = 0
    for tenant, lat in sorted(by_tenant.items()):
        merged = None
        for key, snap in h_after.items():
            if snap["name"] != "service_latency_ms" or \
                    snap.get("labels", {}).get("tenant") != tenant:
                continue
            win = hist_window(h_before, h_after, key)
            if win and win["count"]:
                merged = win if merged is None \
                    else merge_snapshots(merged, win)
        if merged is None or not merged["count"]:
            continue
        lat.sort()
        row = {"tenant": tenant, "n": len(lat),
               "hist_n": merged["count"]}
        ok = True
        for p in (0.50, 0.95, 0.99):
            e = exact_quantile(lat, p)
            h = quantile_from_snapshot(merged, p)
            key_p = f"p{int(p * 100)}"
            row[f"sql_{key_p}"] = round(e, 2)
            row[f"hist_{key_p}"] = round(h, 2) if h is not None else None
            if h and e:
                r = h / e
                row[f"{key_p}_ratio"] = round(r, 4)
                ok = ok and (1 / bound - 1e-9 <= r <= bound + 1e-9)
        row["within_bound"] = ok and len(lat) == merged["count"]
        n_ok += row["within_bound"]
        per.append(row)
    return {"bound_factor": round(bound, 4),
            "tenants": len(per),
            "tenants_within_bound": n_ok,
            "all_within_bound": n_ok == len(per) and len(per) > 0,
            "rows": per}


def run_service(wh_dir: str, pool, clients: int, lists,
                serial_hashes: dict, record_queries: int, log,
                trace_dir: str | None = None,
                flight_dump: str | None = None,
                cache: bool = False,
                pollers: int = 0,
                query_log: str | None = None) -> dict:
    from nds_tpu.engine.jax_backend.executor import clear_shared_programs
    from nds_tpu.obs.flight import FLIGHT
    from nds_tpu.obs.metrics import METRICS
    from nds_tpu.obs.query_log import QUERY_LOG
    from nds_tpu.obs.trace import TRACER
    from nds_tpu.service import (QueryService, ResultCacheConfig,
                                 ServiceConfig)

    clear_shared_programs()
    session = make_session(wh_dir)
    cfg = ServiceConfig(max_pending=256, max_batch=64,
                        batch_linger_ms=5.0,
                        result_cache=ResultCacheConfig(subsumption=True)
                        if cache else None)
    svc = QueryService(session, cfg).start()
    try:
        for label, sql in warm_texts():
            svc.sql(sql, label=label)
            svc.sql(sql, label=label)
        if cache:
            # steady-state dashboard model: one pass over the pool
            # populates the result cache (each text executes once), so
            # the measured window is pure REPEAT traffic — the shape the
            # acceptance pins with counts: zero planner samples, zero
            # device dispatches, every completion a cache hit
            for label, sql in pool:
                svc.sql(sql, label=f"prewarm-{label}")
        # batch-shape warmup: the measured window's batched dispatches pad
        # to capacity-ladder buckets of their UNIQUE row counts — compile
        # every bucket up to max_batch now (held bursts of b distinct
        # instantiations -> cap bucket(b); a duplicate pair -> cap 1) so
        # compiles stay flat while the clock runs. With the result cache
        # armed this is SKIPPED: repeats answer at admission (they never
        # park at the lane, so held tickets would stall the hold loop) and
        # only the ~pool-size cold texts ever dispatch
        sizes = [] if cache else [1]
        b = 2
        while not cache and b <= min(cfg.max_batch,
                                     POOL_PER_TEMPLATE - 1):
            sizes.append(b)
            b = 2 * b - 1          # 2,3,5,9,17,33: caps 2,4,8,16,32,64
        for ti in range(len(TEMPLATES) if sizes else 0):
            base = ti * POOL_PER_TEMPLATE
            for bsize in sizes:
                with svc.hold_dispatch():
                    if bsize == 1:   # duplicate pair dedups to one row
                        picks = [pool[base], pool[base]]
                    else:
                        picks = [pool[base + j] for j in range(bsize)]
                    tickets = [svc.submit(sql, label=f"shape-{label}")
                               for label, sql in picks]
                    deadline = time.time() + 60
                    while time.time() < deadline:
                        with svc._cv:
                            if len(svc._ready) >= len(tickets):
                                break
                        time.sleep(0.005)
                for t in tickets:
                    t.result(timeout=600)

        per_query: list[dict] = []
        mismatches: list[str] = []
        errors: list[str] = []
        rejection_retries = [0]
        lock = threading.Lock()

        def client(cid, qlist):
            """OPEN-LOOP client: submits its whole list up front (arrival
            independent of completion — queue depth is the service's
            problem, shed via typed AdmissionRejected which the client
            retries with backoff, the intended overload protocol), then
            collects every result."""
            from nds_tpu.resilience import AdmissionRejected
            rows = []
            submitted = []
            for label, sql in qlist:
                q0 = time.perf_counter()
                backoff = 0.05
                while True:
                    try:
                        t = svc.submit(sql, label=label, tenant=f"c{cid}")
                        break
                    except AdmissionRejected:
                        with lock:
                            rejection_retries[0] += 1
                        time.sleep(backoff)
                        backoff = min(1.0, backoff * 2)
                submitted.append((label, sql, q0, t))
            for label, sql, q0, ticket in submitted:
                try:
                    res = ticket.result(timeout=600)
                except Exception as e:
                    with lock:
                        errors.append(f"{label}: {type(e).__name__}: {e}")
                    continue
                ms = (time.perf_counter() - q0) * 1000.0
                st = ticket.stats
                rows.append({
                    "label": label, "client": cid,
                    "latency_ms": round(ms, 2),
                    "queue_wait_ms": st.queue_wait_ms if st else None,
                    "batched_with": st.batched_with if st else None,
                    "mode": st.mode if st else None,
                })
                if result_hash(res) != serial_hashes.get(sql):
                    with lock:
                        mismatches.append(label)
            with lock:
                per_query.extend(rows)

        # the measured window's observability state: the flight recorder
        # rides along (sized to hold the whole window) and the histogram
        # cut isolates the window from warmup via snapshot diffs
        # ~4 ring events per query (admit/plan/complete + shared batch
        # rows) — size so the window's completes all survive eviction
        FLIGHT.configure(enabled=True,
                         capacity=4 * sum(len(x) for x in lists) + 512,
                         clear=True)
        if trace_dir:
            TRACER.configure(enabled=True)
        if pollers or query_log:
            # the durable query log covers exactly the measured window:
            # ring sized to hold every completion (the SQL-vs-histogram
            # cross-check needs the full sample set), JSONL opt-in
            QUERY_LOG.configure(
                enabled=True,
                capacity=sum(len(x) for x in lists) + 256,
                path=query_log, clear=True)
        poll_stats = {"polls": 0, "errors": 0, "last_rows": 0}
        poll_stop = threading.Event()

        def poller(pid):
            """Concurrent operator: SQL over system.query_log +
            system.histograms WHILE the workload runs — through the
            service's admission bypass (svc.submit), as a live operator
            would."""
            polls = [
                ("SELECT tenant, COUNT(*) AS n FROM system.query_log "
                 "GROUP BY tenant"),
                ("SELECT series, total_count FROM system.histograms "
                 "WHERE name = 'service_latency_ms'"),
                ("SELECT name, value FROM system.metrics "
                 "WHERE name = 'service_queue_depth'"),
            ]
            i = pid
            while not poll_stop.is_set():
                try:
                    res = svc.sql(polls[i % len(polls)],
                                  label=f"poll{pid}")
                    with lock:
                        poll_stats["polls"] += 1
                        poll_stats["last_rows"] = res.num_rows
                except Exception:
                    with lock:
                        poll_stats["errors"] += 1
                i += 1
                # operator cadence, not a tight loop: this 1-core host
                # shares the poll's host-side CPU with the workload, so
                # the poll RATE is the wall-clock knob (the zero-device-
                # work/zero-compile pins hold at any rate)
                time.sleep(0.5)

        before = METRICS.snapshot()
        h_before = METRICS.histograms()
        threads = [threading.Thread(target=client, args=(cid, ql))
                   for cid, ql in enumerate(lists)]
        poll_threads = [threading.Thread(target=poller, args=(i,))
                        for i in range(pollers)]
        t0 = time.perf_counter()
        for t in threads + poll_threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        poll_stop.set()
        for t in poll_threads:
            t.join()
        delta = METRICS.delta(before)
        h_after = METRICS.histograms()
        system_poll = None
        if pollers:
            system_poll = _system_poll_check(svc, h_before, h_after)
            system_poll["polls"] = poll_stats["polls"]
            system_poll["poll_errors"] = poll_stats["errors"]
        if query_log:
            QUERY_LOG.flush()
    finally:
        svc.close()

    trace_file = None
    if trace_dir:
        trace_file = TRACER.write_chrome_trace(os.path.join(
            trace_dir, f"service_trace_c{clients}.json"))
        TRACER.configure(enabled=False)
        log(f"trace: {trace_file} (open in ui.perfetto.dev)")
    flight_file = None
    if flight_dump:
        flight_file = FLIGHT.dump_jsonl(
            flight_dump.replace(".jsonl", f"_c{clients}.jsonl"))
    # service-side latency percentiles now come from the REGISTRY
    # histograms (the per-tenant/per-template SLO source every consumer
    # shares) — cross-checked below against exact per-ticket latencies
    # from the flight recorder's complete events, within the documented
    # bucket error bound
    lat_hist = hist_window(h_before, h_after, "service_latency_ms")
    wait_hist = hist_window(h_before, h_after, "service_queue_wait_ms")
    exact_lat = sorted(e["latency_ms"] for e in FLIGHT.events()
                       if e["event"] == "complete")
    FLIGHT.configure(enabled=False)
    batched = [r for r in per_query if (r["batched_with"] or 0) > 0]
    total = sum(len(x) for x in lists)
    rec = {
        "clients": clients,
        "result_cache": cache,
        "queries": total,
        "completed": len(per_query),
        "errors": errors[:10],
        "wall_s": round(wall, 3),
        "qps": round(len(per_query) / wall, 1) if wall else 0.0,
        "p50_ms": _hq(lat_hist, 0.50),
        "p99_ms": _hq(lat_hist, 0.99),
        "queue_wait_p50_ms": _hq(wait_hist, 0.50),
        "queue_wait_p99_ms": _hq(wait_hist, 0.99),
        "percentile_check": _percentile_check(lat_hist, exact_lat),
        "per_tenant_slo": _tenant_slo(h_before, h_after, top=8),
        # the raw window snapshots: any quantile is recomputable offline
        # (obs_report / quantile_from_snapshot), and shard-level records
        # merge via merge_snapshots
        "latency_hist": lat_hist,
        "queue_wait_hist": wait_hist,
        "batched_frac": round(len(batched) / max(1, len(per_query)), 3),
        "admission_rejection_retries": rejection_retries[0],
        # engine-counter delta over the MEASURED window (warmup excluded):
        # compiles ~0 proves the shared cache keeps programs flat; batches
        # and adoption quantify how the queries were actually served
        "metrics_delta": {k: delta[k] for k in sorted(delta)
                          if k.split("_")[0] in
                          ("service", "compiles", "program", "programs",
                           "queries", "replay", "result", "system",
                           "query")},
        "results_identical_to_serial": not mismatches,
        "result_mismatches": mismatches[:10],
        # the per-query block (capped): latency decomposed into wait vs
        # execute, plus who rode a shared batched dispatch
        "queries_sample": per_query[:record_queries],
    }
    if cache:
        # the acceptance pins, COUNTS ONLY (single-core host wall times
        # flake; they stay report-only): repeat-template tickets complete
        # with zero planner/device work, and every response hashed
        # identical to the uncached serial baseline
        texts = {sql for ql in lists for _l, sql in ql}
        executed = int(delta.get("queries_run", 0))
        hits = int(delta.get("result_cache_hits", 0)
                   + delta.get("result_cache_subsumption_hits", 0))
        plan_win = hist_window(h_before, h_after, "service_plan_ms")
        plan_n = int(plan_win["count"]) if plan_win else 0
        rec["cache_assertions"] = {
            "distinct_texts": len(texts),
            "executed_queries": executed,
            "cache_hits": hits,
            "plan_stage_samples": plan_n,
            # the pool was pre-warmed, so the window is all repeats:
            # ZERO planner samples and ZERO device dispatches, pinned by
            # counts (service_plan_ms count / queries_run / batches)
            "repeat_tickets_zero_planner_work": plan_n == 0,
            "repeat_tickets_zero_device_work":
                executed == 0 and not delta.get("service_batches")
                and not delta.get("compiles"),
            # every completion was a cache hit
            "hits_cover_all_repeats": hits == len(per_query),
            "hash_identical_to_uncached_baseline": not mismatches,
        }
    if system_poll is not None:
        # the acceptance block: per-tenant SQL-exact vs registry-
        # histogram percentiles within the documented bound, plus how
        # many concurrent polls rode the window
        rec["system_poll"] = system_poll
    if query_log:
        rec["query_log"] = query_log
    if trace_file:
        rec["trace_file"] = trace_file
    if flight_file:
        rec["flight_file"] = flight_file
    log(f"clients={clients}{' cache' if cache else ''}: "
        f"{rec['qps']} QPS ({total} queries in "
        f"{wall:.2f}s), p50 {rec['p50_ms']} ms, p99 {rec['p99_ms']} ms, "
        f"batched {rec['batched_frac']:.0%}, "
        f"compiles {delta.get('compiles', 0)}, "
        f"cache_hits {delta.get('result_cache_hits', 0)}, "
        f"identical={rec['results_identical_to_serial']}")
    return rec


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="service_bench.py", description=(
        "open-loop query-service bench: sustained QPS + p50/p99 latency "
        "at N concurrent clients vs the serial baseline"))
    p.add_argument("--clients", default="10,100",
                   help="comma list of concurrent-client counts")
    p.add_argument("--total_queries", type=int, default=1000,
                   help="total workload per measured run (split evenly "
                        "across clients, so every client count measures "
                        "the same amount of work)")
    p.add_argument("--record_queries", type=int, default=200,
                   help="per-query rows kept in the JSON (cap)")
    p.add_argument("--zipf", type=float, default=0.0,
                   help="Zipf skew over the template x parameter pool "
                        "(pool position = popularity rank, pick prob "
                        "~ rank^-S); 0 = uniform")
    p.add_argument("--cache", choices=["off", "on", "both"],
                   default="off",
                   help="arm the semantic result cache for the measured "
                        "runs; 'both' measures each client count "
                        "uncached THEN cached (the SERVICE_r03 shape: "
                        "counts-based zero-work assertions + hash "
                        "identity vs the uncached baseline)")
    p.add_argument("--trace", action="store_true",
                   help="span-trace each measured window; writes one "
                        "Chrome trace-event file per client count "
                        "(service_trace_cN.json beside --out) showing the "
                        "parent-linked admission->plan->dispatch->"
                        "materialize spans of every ticket")
    p.add_argument("--flight", action="store_true",
                   help="also dump each measured window's flight-recorder "
                        "ring as service_flight_cN.jsonl beside --out "
                        "(the ring records regardless — it feeds the "
                        "exact-percentile cross-check)")
    p.add_argument("--poll_system", type=int, default=0, metavar="N",
                   help="run N concurrent system-table poller threads "
                        "(SQL over system.query_log / system.histograms "
                        "/ system.metrics through the service's "
                        "admission bypass) DURING each measured window; "
                        "each client count then runs PAIRED — unpolled "
                        "baseline, then polled — and the record carries "
                        "the per-tenant SQL-vs-histogram percentile "
                        "cross-check plus a zero-added-work comparison "
                        "(compiles/dispatch counters equal, responses "
                        "hash-identical in both runs)")
    p.add_argument("--query_log", default=None, metavar="PATH",
                   help="enable the durable query log for the measured "
                        "windows and write the JSONL here (per client "
                        "count: PATH gains a _cN suffix) — "
                        "scripts/slo_report.py reproduces the SLO "
                        "numbers offline from it")
    p.add_argument("--out", default=os.path.join(REPO, "SERVICE_r01.json"))
    p.add_argument("--sf", default=os.environ.get("NDS_TPU_BENCH_SF",
                                                  "0.01"))
    a = p.parse_args(argv)

    os.environ["NDS_TPU_BENCH_SF"] = a.sf
    import bench  # noqa: E402  (repo root; reads NDS_TPU_BENCH_* at import)
    from nds_tpu.config import enable_compile_cache
    enable_compile_cache(os.path.join(
        os.path.expanduser("~"), ".cache",
        f"nds_tpu_xla_{bench._host_cache_tag()}"))

    def log(msg):
        print(msg, file=sys.stderr, flush=True)

    wh_dir, _stream = bench.ensure_data()
    pool = build_pool()
    counts = [int(x) for x in a.clients.split(",") if x.strip()]

    def lists_for(clients):
        per_client = max(1, -(-a.total_queries // clients))
        return workload_for(pool, clients, per_client, zipf=a.zipf)

    # the serial baseline runs the same total workload one query at a
    # time; every client count re-runs ~the same total, so QPS compares
    # equal sustained work, not unequal totals
    serial = run_serial(wh_dir, pool, lists_for(max(counts)), log)
    hashes = serial.pop("_hashes")
    out_dir = os.path.dirname(os.path.abspath(a.out))
    runs = []
    cache_modes = {"off": [False], "on": [True],
                   "both": [False, True]}[a.cache]
    #: counters whose window delta must be EQUAL between the unpolled
    #: baseline and the polled run — system polls must add zero compile/
    #: device/replay work (system_queries itself is the only expected
    #: mover). Batch COMPOSITION counters (service_batches,
    #: program_cache_misses) are reported beside but not pinned: under
    #: open-loop admission the drain windows are thread-timing-dependent
    #: run to run (batch_linger_ms=0 serves whatever is queued), polls
    #: or no polls
    PIN = ("compiles", "queries_run", "replay_mismatches")
    INFO = ("service_batches", "service_batched_queries",
            "program_cache_misses")
    for c in counts:
        for cached in cache_modes:
            passes = [0, a.poll_system] if a.poll_system else [0]
            pair = []
            for pollers in passes:
                ql = None
                if a.query_log and (pollers or not a.poll_system):
                    ql = a.query_log.replace(".jsonl", f"_c{c}.jsonl")
                rec = run_service(
                    wh_dir, pool, c, lists_for(c), hashes,
                    a.record_queries, log,
                    trace_dir=out_dir if a.trace else None,
                    flight_dump=os.path.join(out_dir,
                                             "service_flight.jsonl")
                    if a.flight else None,
                    cache=cached, pollers=pollers, query_log=ql)
                rec["speedup_vs_serial_qps"] = round(
                    rec["qps"] / serial["qps"], 2) if serial["qps"] \
                    else None
                rec["polled"] = bool(pollers)
                pair.append(rec)
                runs.append(rec)
            if len(pair) == 2:
                base, polled = pair
                bd, pd = base["metrics_delta"], polled["metrics_delta"]
                polled["system_poll_overhead"] = {
                    # the acceptance pins, COUNTS ONLY: the polled window
                    # compiled nothing extra, dispatched the same query
                    # count, replayed nothing wrong — polls added
                    # system_queries and NOTHING on those axes
                    "pinned_counters": {k: {"baseline": bd.get(k, 0),
                                            "polled": pd.get(k, 0)}
                                        for k in PIN},
                    "pins_equal": all(bd.get(k, 0) == pd.get(k, 0)
                                      for k in PIN),
                    "batching_composition": {
                        k: {"baseline": bd.get(k, 0),
                            "polled": pd.get(k, 0)} for k in INFO},
                    "system_queries_polled": pd.get("system_queries", 0),
                    "both_hash_identical_to_serial":
                        base["results_identical_to_serial"]
                        and polled["results_identical_to_serial"],
                }
                log(f"clients={c} polled-vs-unpolled pins equal: "
                    f"{polled['system_poll_overhead']['pins_equal']} "
                    f"(system_queries="
                    f"{pd.get('system_queries', 0)})")

    import platform
    out = {
        "schema_version": 3,
        "kind": "service_open_loop",
        "sf": a.sf,
        "templates": {k: v for k, v in TEMPLATES.items()},
        "pool_per_template": POOL_PER_TEMPLATE,
        "total_queries": a.total_queries,
        "zipf": a.zipf,
        "cache_mode": a.cache,
        "platform": {"python": platform.python_version(),
                     "machine": platform.machine(),
                     "jax_platform": "cpu"},
        "note": ("CPU host: the 'device' executes on the same cores, so "
                 "QPS gains come from batching + pipelining + shared "
                 "programs, not accelerator parallelism — TPU runs gain "
                 "the device/host overlap on top"),
        "serial": serial,
        "runs": runs,
    }
    with open(a.out, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    log(f"record: {a.out}")
    print(json.dumps({k: v for k, v in out.items()
                      if k not in ("templates", "runs")} |
                     {"runs": [{k: v for k, v in r.items()
                                if k != "queries_sample"}
                               for r in runs]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
