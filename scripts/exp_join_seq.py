"""Reproduce the executor's sort-join sequence at q72 scale on TPU.

dense_rank over combined keys -> build sort -> probe counts -> expand at
16M cap -> gather k columns. All via nds_tpu kernels, one jit.
"""
import time
import numpy as np
import jax
import jax.numpy as jnp

from nds_tpu.config import enable_x64
enable_x64()
from nds_tpu.engine.jax_backend import kernels

LCAP = 1 << 21      # probe side (cs-ish)
RCAP = 1 << 21      # build side (inv-ish slice)
CAP_OUT = 1 << 24   # recorded expansion cap
NCOLS = 6

rng = np.random.default_rng(0)
lkey = jnp.asarray(rng.integers(0, 200_000, LCAP), jnp.int64)
rkey = jnp.asarray(rng.integers(0, 200_000, RCAP), jnp.int64)
lalive = jnp.ones(LCAP, bool)
ralive = jnp.ones(RCAP, bool)
lcols = [jnp.asarray(rng.integers(0, 1 << 40, LCAP), jnp.int64)
         for _ in range(NCOLS)]
rcols = [jnp.asarray(rng.integers(0, 1 << 40, RCAP), jnp.int64)
         for _ in range(NCOLS)]


def join(lk, rk, la, ra, lcs, rcs):
    gid, _ = kernels.dense_rank([jnp.concatenate([lk, rk])],
                                [jnp.ones(LCAP + RCAP, bool)],
                                jnp.concatenate([la, ra]))
    lgid, rgid = gid[:LCAP], gid[LCAP:]
    sorted_gid, perm = kernels.build_side(rgid, ra)
    lo, cnt = kernels.probe_counts_by_gid(sorted_gid,
                                          ra[perm], lgid, la,
                                          LCAP + RCAP)
    left, bpos, alive = kernels.expand_join(lo, cnt, la, CAP_OUT)
    outs = [c[left] for c in lcs]
    bsafe = jnp.clip(bpos, 0, RCAP - 1)
    outs += [c[perm][bsafe] for c in rcs]
    acc = jnp.zeros((), jnp.int64)
    for o in outs:
        acc = acc + jnp.where(alive, o, 0).sum()
    return acc


f = jax.jit(join)
t0 = time.perf_counter()
r = jax.block_until_ready(f(lkey, rkey, lalive, ralive, lcols, rcols))
print(f"compile+first: {time.perf_counter()-t0:.1f}s")
t0 = time.perf_counter()
for _ in range(3):
    jax.block_until_ready(f(lkey, rkey, lalive, ralive, lcols, rcols))
print(f"steady: {(time.perf_counter()-t0)/3*1000:.1f} ms")
