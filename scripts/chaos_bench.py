#!/usr/bin/env python
"""Chaos campaign harness -> CHAOS_r*.json.

Drives a seeded nds_tpu/chaos campaign — N concurrent clients against a
live QueryService with the self-healing machinery armed (circuit
breaker, retry budget, program quarantine, optional lane watchdog) —
while the campaign's scheduled waves arm the FaultRegistry points,
then records the three-phase evidence (baseline / armed / recovery) and
the campaign invariants:

- 0 untyped exceptions (every failure a client saw was classifiable),
- 0 hash mismatches vs the fault-free baseline on completed responses,
- a flight-recorder dump per firing and per circuit trip,
- post-disarm QPS within 20% of the pre-arm baseline.

The workload is the self-contained demo dataset (chaos.build_demo_session):
a parameterized in-core template exercising the batched-dispatch path and
a parquet-backed streamed scan exercising the morsel/staging path, so
arrow.read / device.put fire per morsel and jax.execute per dispatch;
the campaign itself fires query.run per submission and stream.spawn per
client start, the same semantics the power/throughput runners give those
points.

``--mode txn`` swaps in the TRANSACTIONAL campaign
(chaos.run_txn_campaign): a live two-table warehouse, a writer thread
committing atomic cross-table transactions while the clients read, and
the ``manifest.write``/``txn.commit``/``txn.between_tables`` points
killing commits mid-flight. Its verdict adds the snapshot-isolation
invariants: every completed response hash-identical to SOME published
warehouse version replayed whole, zero torn-manifest reads, at least
one transaction landed.

Usage:
  python scripts/chaos_bench.py                          # 100 clients
  python scripts/chaos_bench.py --mode txn               # txn campaign
  python scripts/chaos_bench.py --clients 8 --queries 6 --out /tmp/c.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="chaos_bench.py", description=(
        "seeded chaos campaign against the live query service"))
    p.add_argument("--mode", default="service", choices=["service", "txn"],
                   help="service: the classic campaign; txn: chaos "
                        "mid-DML over a live warehouse")
    p.add_argument("--clients", type=int, default=100)
    p.add_argument("--queries", type=int, default=8,
                   help="queries per client per phase")
    p.add_argument("--seed", type=lambda s: int(s, 0), default=0xC0FFEE)
    p.add_argument("--times", type=int, default=2,
                   help="firings cap per armed spec")
    p.add_argument("--probability", type=float, default=1.0)
    p.add_argument("--points", default=None,
                   help="comma list of fault points (default: all "
                        "registered; txn mode defaults to the commit-"
                        "path points)")
    p.add_argument("--dml_rounds", type=int, default=0,
                   help="txn mode: writer transactions attempted during "
                        "the armed phase; 0 (default) auto-scales past "
                        "the armed points' total firing budget so at "
                        "least one commit lands")
    p.add_argument("--watchdog", type=float, default=0.0,
                   help="device-lane watchdog budget in seconds (0 = off)")
    p.add_argument("--dump_dir", default=None,
                   help="flight-dump directory (default: a temp dir, "
                        "paths recorded in the JSON)")
    p.add_argument("--out", default=None,
                   help="output JSON (default: CHAOS_r01.json, or "
                        "CHAOS_TXN_r01.json in txn mode)")
    a = p.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from nds_tpu.chaos import (TXN_POINTS, CampaignSpec, ChaosCampaign,
                               build_demo_session, demo_pool,
                               run_txn_campaign)

    dump_dir = a.dump_dir or tempfile.mkdtemp(prefix="chaos_flight_")
    work_dir = tempfile.mkdtemp(prefix="chaos_data_")
    out = a.out or os.path.join(
        REPO, "CHAOS_TXN_r01.json" if a.mode == "txn" else "CHAOS_r01.json")
    spec_kw = dict(seed=a.seed, clients=a.clients,
                   queries_per_client=a.queries, times_per_point=a.times,
                   probability=a.probability,
                   dispatch_timeout_s=a.watchdog, dump_dir=dump_dir)
    if a.points:
        spec_kw["points"] = tuple(
            x.strip() for x in a.points.split(",") if x.strip())
    elif a.mode == "txn":
        # the commit path is the campaign's subject; "raise" aborts are
        # what exercise rollback + recovery (a delayed commit still lands)
        spec_kw["points"] = TXN_POINTS
        spec_kw["actions"] = ("raise",)
    spec = CampaignSpec(**spec_kw)
    if a.mode == "txn":
        record = run_txn_campaign(spec, work_dir, dml_rounds=a.dml_rounds)
    else:
        session = build_demo_session(work_dir)
        record = ChaosCampaign(spec, demo_pool()).run(session)
    record["harness"] = {"dump_dir": dump_dir, "work_dir": work_dir}
    with open(out, "w") as f:
        json.dump(record, f, indent=2, sort_keys=True)
        f.write("\n")
    brief = {"out": out, "invariants": record["invariants"]}
    if a.mode == "txn":
        brief.update(dml=record["dml"],
                     warehouse_versions=record["warehouse_versions"],
                     txn_metrics=record["txn_metrics"])
    else:
        brief.update(firings=record["firings"],
                     flight_dumps=record["flight_dumps"],
                     recovery_qps_ratio=record["recovery_qps_ratio"])
    print(json.dumps(brief, indent=2, sort_keys=True))
    ok = all(record["invariants"].values())
    print(f"chaos_bench: {'OK' if ok else 'INVARIANT FAILURES'}",
          file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
