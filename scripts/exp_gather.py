"""Gather microbench: 16M random indices into a 1M-entry i32 table.

1. XLA gather (src[idx])
2. XLA gather, sorted indices
3. Pallas kernel: src in VMEM, vector dynamic indexing
"""
import functools
import time
import numpy as np
import jax
import jax.numpy as jnp

jax.config.update("jax_enable_x64", True)

NI, NS = 1 << 24, 1 << 20
rng = np.random.default_rng(0)
idx = jnp.asarray(rng.integers(0, NS, NI), jnp.int32)
idx_sorted = jnp.sort(idx)
src = jnp.asarray(rng.integers(0, 1 << 30, NS), jnp.int32)


def bench(name, fn, *args):
    try:
        f = jax.jit(fn)
        jax.block_until_ready(f(*args))
        t0 = time.perf_counter()
        for _ in range(3):
            r = jax.block_until_ready(f(*args))
        dt = (time.perf_counter() - t0) / 3 * 1000
        print(f"{name:24s} {dt:8.1f} ms", flush=True)
        return r
    except Exception as e:
        print(f"{name:24s} FAILED: {type(e).__name__}: {str(e)[:120]}")
        return None


r1 = bench("xla_gather", lambda s, i: s[i], src, idx)
bench("xla_gather_sorted", lambda s, i: s[i], src, idx_sorted)

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLK = 1 << 13


def pk(src_ref, idx_ref, out_ref):
    out_ref[:] = src_ref[idx_ref[:]]


def pallas_gather(s, i):
    return pl.pallas_call(
        pk,
        grid=(NI // BLK,),
        in_specs=[pl.BlockSpec((NS,), lambda b: (0,),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((BLK,), lambda b: (b,))],
        out_specs=pl.BlockSpec((BLK,), lambda b: (b,)),
        out_shape=jax.ShapeDtypeStruct((NI,), jnp.int32),
    )(s, i)


r3 = bench("pallas_vmem_gather", pallas_gather, src, idx)
if r1 is not None and r3 is not None:
    print("pallas correct:", bool(jnp.array_equal(r1, r3)))


def pk_take(src_ref, idx_ref, out_ref):
    out_ref[:] = jnp.take(src_ref[:], idx_ref[:], axis=0)


def pallas_take(s, i):
    return pl.pallas_call(
        pk_take,
        grid=(NI // BLK,),
        in_specs=[pl.BlockSpec((NS,), lambda b: (0,),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((BLK,), lambda b: (b,))],
        out_specs=pl.BlockSpec((BLK,), lambda b: (b,)),
        out_shape=jax.ShapeDtypeStruct((NI,), jnp.int32),
    )(s, i)


r4 = bench("pallas_take", pallas_take, src, idx)
if r1 is not None and r4 is not None:
    print("pallas_take correct:", bool(jnp.array_equal(r1, r4)))
