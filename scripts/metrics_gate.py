#!/usr/bin/env python
"""CI metrics gate: diff a deterministic workload's counter deltas
against a checked-in baseline.

Timing on this class of host flakes (a loaded 1-core runner can double
any wall number), so CI cannot gate on milliseconds — but it CAN gate on
COUNT-shaped metrics, which are functions of the engine's decisions, not
of the scheduler: how many programs compiled, how many cache hits served
replays, how many morsels streamed, how many queries a batched dispatch
absorbed. A regression that breaks a cache key, defeats batching, or
re-traces every morsel moves these counts by integer factors while every
test still passes bit-identical — exactly the failure class PR 9 found
by hand (the PackedTable aux-hash bug re-traced EVERY morsel; compiles
would have exploded in this gate).

Mechanics:

1. run a fixed synthetic workload (in-core record/compile/replay x3,
   a streamed low-cardinality scan x2, and a held 4-ticket service batch)
   on a fresh in-process engine;
2. take the registry counter snapshot; keep COUNT-shaped metrics only —
   ``*_ms`` wall metrics and ``*_bytes``-free size metrics are
   REPORT-ONLY (printed, never gated);
3. diff against ``cicd/metrics_baseline.json``: strict-zero metrics
   (replay_mismatches, host_fallbacks, ...) must stay exactly 0; every
   other gated counter passes within a generous ratio band
   (x0.5 .. x2.0, or an absolute slack of +-2 for small counts);
4. exit nonzero on any violation, printing the offending rows.

Refresh the baseline after an intentional behavior change:

  python scripts/metrics_gate.py --update
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BASELINE = os.path.join(REPO, "cicd", "metrics_baseline.json")

#: metrics that must be EXACTLY ZERO on the gate workload: any movement
#: is a behavior regression (a replay invalidated, an operator falling
#: back to host, a staging thread failing), never noise
STRICT_ZERO = (
    "replay_mismatches", "host_fallbacks", "query_failures",
    "prefetch_errors", "fault_point_firings", "service_rejected",
    "service_deadline_expired", "stream_restarts",
    # chaos-hardened serving: a CLEAN workload must never trip a breaker
    # or quarantine a program — movement here means the self-healing
    # machinery fired on healthy traffic
    "circuit_trips", "quarantined_programs",
    # semantic result cache: the gate workload runs with the cache OFF,
    # so a hit here means some layer armed it (or served a cached
    # result) without being asked — a behavior regression, never noise
    "result_cache_hits",
    # EXPLAIN ANALYZE: the gate workload runs with profiling OFF, so any
    # profiled query, audit finding, or histogram-series fold here means
    # the disabled path grew profiling work (the zero-cost contract)
    "profiled_queries", "cardinality_misestimates",
    "histogram_series_overflow",
    # system tables + durable query log: the gate workload runs with the
    # log DISABLED and issues no system.* statement, so any row, file
    # rotation, or served introspection query here means the disabled
    # path grew work (one branch per statement is the whole budget)
    "system_queries", "query_log_rows", "query_log_rotations",
    # transactional warehouse: the gate workload is query-only (no
    # warehouse attached, no DML), so a commit, rollback, or recovery
    # sweep here means the read path started opening transactions — the
    # pinning-disabled/bit-identical contract broke
    "txn_commits", "txn_rollbacks", "txn_recoveries",
    # adaptive execution: the gate workload runs with adaptive_plans OFF
    # (the default), so a feedback hit, profile refresh, or feedback-
    # driven re-record here means the disabled path built a store or
    # consulted one — the bit-identical off contract broke
    "feedback_hits", "feedback_refreshes", "adaptive_replans",
    # distributed serving front door: the gate workload is in-process
    # (no FrontDoorServer, fair_queue/preemption/inflight_dedup all at
    # their off defaults), so any wire request, preemption, dedup share,
    # cache snapshot export, or client-side cache hit here means the
    # disabled path grew serving work — the bit-identical off contract
    "frontdoor_requests", "frontdoor_errors", "service_preemptions",
    "service_inflight_dedup", "result_cache_snapshots",
    "frontdoor_client_cache_hits",
)

#: report-only name suffixes: wall-clock and byte-volume metrics flake
#: with host load / layout evolution — printed for the log, never gated
REPORT_ONLY_SUFFIXES = ("_ms", "_bytes", "bytes_uploaded")

RATIO_LO, RATIO_HI = 0.5, 2.0
ABS_SLACK = 2


def run_workload() -> dict:
    """The fixed workload; returns the registry snapshot AFTER it.

    Deterministic by construction: fixed rng seeds, fixed query texts,
    and the service batch accumulates under hold_dispatch so batching
    does not depend on thread timing."""
    import time

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from nds_tpu.config import EngineConfig
    from nds_tpu.engine import Session
    from nds_tpu.obs.metrics import METRICS
    from nds_tpu.service import QueryService, ServiceConfig

    import tempfile

    rng = np.random.default_rng(41)
    n_fact, n_dim = 20_000, 50
    fact = pa.table({
        "fk": pa.array(rng.integers(0, n_dim, n_fact), type=pa.int64()),
        "qty": pa.array(rng.integers(1, 100, n_fact), type=pa.int64()),
    })
    dim = pa.table({"dk": pa.array(np.arange(n_dim), type=pa.int64()),
                    "grp": pa.array((np.arange(n_dim) % 7)
                                    .astype(np.int64))})

    # 1. in-core record -> compile+run -> compiled replay
    s = Session(EngineConfig())
    s.register_arrow("fact", fact)
    s.register_arrow("dim", dim)
    tpl = ("SELECT grp, COUNT(*) AS n, SUM(qty) AS tq FROM fact "
           "JOIN dim ON fk = dk WHERE qty BETWEEN {a} AND {b} "
           "GROUP BY grp ORDER BY grp")
    for _ in range(3):
        s.sql(tpl.format(a=5, b=60), label="gate_incore")

    # 2. streamed morsel scan (low-cardinality column: the encoded path
    #    participates, so decode/dict counters gate too)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "sfact.parquet")
        pq.write_table(pa.table({
            "k": pa.array(rng.integers(0, 9, 60_000), type=pa.int32()),
            "v": pa.array(rng.integers(0, 1000, 60_000), type=pa.int64()),
        }), path, row_group_size=8192)
        s2 = Session(EngineConfig(chunk_rows=8192,
                                  out_of_core_min_rows=10_000))
        s2.register_parquet("sfact", path)
        for _ in range(2):
            s2.sql("SELECT k, COUNT(*) AS n, SUM(v) AS sv FROM sfact "
                   "GROUP BY k ORDER BY k", label="gate_stream")

    # 3. service: warm then one held batch of 4 compatible tickets
    with QueryService(s, ServiceConfig(max_batch=8)) as svc:
        svc.sql(tpl.format(a=5, b=60), label="gate_warm")
        svc.sql(tpl.format(a=5, b=60), label="gate_warm")
        with svc.hold_dispatch():
            tickets = [svc.submit(tpl.format(a=5 + i, b=60 + i),
                                  label=f"gate_b{i}", tenant="gate")
                       for i in range(4)]
            t0 = time.time()
            while time.time() - t0 < 30:
                with svc._cv:
                    if len(svc._ready) >= len(tickets):
                        break
                time.sleep(0.005)
        for t in tickets:
            t.result(timeout=120)
    return METRICS.snapshot()


def gated_view(snapshot: dict) -> tuple[dict, dict]:
    """(gated, report_only) split of a snapshot."""
    gated, report = {}, {}
    for name, v in snapshot.items():
        if any(name.endswith(sfx) for sfx in REPORT_ONLY_SUFFIXES):
            report[name] = v
        else:
            gated[name] = v
    return gated, report


def compare(baseline: dict, now: dict) -> list[str]:
    """Violation messages (empty = gate passes)."""
    out = []
    for name in STRICT_ZERO:
        if now.get(name, 0) != 0:
            out.append(f"STRICT-ZERO {name}: {now[name]} (must be 0)")
    for name, base in sorted(baseline.items()):
        if name in STRICT_ZERO:
            continue
        cur = now.get(name)
        if cur is None:
            out.append(f"MISSING {name}: baseline {base}, not in snapshot")
            continue
        if abs(cur - base) <= ABS_SLACK:
            continue
        if base > 0 and RATIO_LO <= cur / base <= RATIO_HI:
            continue
        out.append(f"OUT-OF-BAND {name}: {cur} vs baseline {base} "
                   f"(band x{RATIO_LO}-x{RATIO_HI}, slack +-{ABS_SLACK})")
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="metrics_gate.py", description=(
        "run the deterministic gate workload and diff count-shaped "
        "engine counters against the checked-in baseline"))
    p.add_argument("--baseline", default=BASELINE)
    p.add_argument("--update", action="store_true",
                   help="write the current counts as the new baseline "
                        "instead of gating")
    a = p.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    snapshot = run_workload()
    gated, report = gated_view(snapshot)
    if a.update:
        os.makedirs(os.path.dirname(a.baseline), exist_ok=True)
        with open(a.baseline, "w") as f:
            json.dump({"workload_version": 1, "gated": gated,
                       "report_only": report}, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"metrics_gate: baseline updated -> {a.baseline}")
        return 0
    try:
        with open(a.baseline) as f:
            doc = json.load(f)
    except OSError as e:
        print(f"metrics_gate: no baseline ({e}); run with --update first",
              file=sys.stderr)
        return 2
    violations = compare(doc["gated"], gated)
    print(json.dumps({"gated": gated, "report_only": report,
                      "violations": violations}, sort_keys=True))
    if violations:
        for v in violations:
            print(f"metrics_gate: {v}", file=sys.stderr)
        print(f"metrics_gate: FAIL ({len(violations)} violations)",
              file=sys.stderr)
        return 1
    print(f"metrics_gate: OK ({len(doc['gated'])} baseline metrics, "
          f"{len(gated)} observed)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
