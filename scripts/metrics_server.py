#!/usr/bin/env python
"""Standalone scrape endpoint over the system tables.

Serves ``/metrics`` (Prometheus text exposition), ``/healthz``, and
``/query?sql=SELECT...`` (system.* tables only) from a stdlib
``http.server`` — the same :class:`nds_tpu.obs.scrape.MetricsServer` a
live service starts via ``ServiceConfig.metrics_port``, runnable on its
own for two operator workflows:

- **post-mortem**: point it at a saved query-log JSONL (``--query_log``)
  and query the run's statement rows over the wire exactly as if the
  producing process were still alive;
- **sidecar demo / smoke**: bind an ephemeral port (``--port 0``), let a
  scraper or curl hit it, ctrl-C to stop.

The first stdout line is ``serving on http://HOST:PORT`` (flushed), so
harnesses that spawn this script can read the bound ephemeral port.

Usage:
  python scripts/metrics_server.py --port 9090
  python scripts/metrics_server.py --port 0 --query_log run/query_log.jsonl
  curl "http://127.0.0.1:9090/query?sql=SELECT+tenant,wall_ms+FROM+system.query_log"
"""
from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="metrics_server.py", description=(
        "serve /metrics, /healthz, and /query?sql= (system.* tables) "
        "over HTTP from this process's observability registries"))
    p.add_argument("--port", type=int, default=8900,
                   help="bind port (0 = OS-assigned ephemeral; the bound "
                        "port prints on the first stdout line)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--query_log", default=None, metavar="PATH",
                   help="replay a saved query-log JSONL into the ring so "
                        "system.query_log serves the offline run's rows")
    a = p.parse_args(argv)

    from nds_tpu.config import EngineConfig
    from nds_tpu.engine import Session
    from nds_tpu.obs.query_log import QUERY_LOG, read_jsonl
    from nds_tpu.obs.scrape import MetricsServer

    if a.query_log:
        rows = read_jsonl(a.query_log)
        QUERY_LOG.configure(enabled=True, capacity=max(1, len(rows)),
                            clear=True)
        n = QUERY_LOG.load_rows(rows)
        print(f"loaded {n} query-log rows from {a.query_log}",
              file=sys.stderr)
    # host-only session: /query plans against the system catalog and the
    # host executor — no jax initialization, no device
    session = Session(EngineConfig(use_jax=False))
    srv = MetricsServer(session=session, port=a.port, host=a.host).start()
    print(f"serving on {srv.url}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
