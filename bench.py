"""Benchmark entry point: one JSON line for the driver.

Round-2 benchmark: the REAL NDS workload (BASELINE.md ladder steps 1-2
shape) — native datagen at SF1, transcode to a Parquet warehouse, template
-substituted query stream, then a timed power-run subset on the device
(JAX/TPU) backend vs the numpy host oracle (the CPU-vs-accelerator frame of
reference nds/nds_validate.py; per-query timing mirrors
nds/nds_power.py:281-299).

Methodology: each query runs three times on the device backend — (1) eager
record pass (capacity schedule, host CPU), (2) whole-plan XLA compile +
first device run, (3+) steady-state compiled device runs. The TIMED number
is the best compiled run: the framework's contract is that a query stream
compiles once and re-runs (throughput test, repeated streams), matching the
reference's accelerated-plan steady state. Queries that fall back to the
host oracle FAIL the bench (reference runs every op on the accelerator).

Artifacts (data, warehouse, stream) are cached under .bench_data/ across
rounds; delete the directory to force regeneration.

Prints: {"metric", "value", "unit", "vs_baseline"} — value is the power-run
subset wall (ms) on the device path; vs_baseline > 1 means the device path
beats the host oracle. Everything else (per-query diagnostics) goes to
stderr through the nds_tpu.obs.log channel (NDS_TPU_VERBOSITY / -q).

--trace: enable the obs span tracer for the whole run and write a Chrome
trace-event file (opens in Perfetto / chrome://tracing) plus a JSONL event
log next to the bench data; the JSON line gains the per-span aggregate,
the per-program device-time table with per-program roofline fractions,
and the engine metrics snapshot.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

# XLA:CPU AOT results deserialized from a persistent cache written on a
# DIFFERENT machine spam a multi-KB machine-feature-mismatch warning per
# load (cpu_aot_loader.cc), burying the bench output. Two-part fix, set
# BEFORE jax/XLA load: scope the compile cache per host feature set (see
# _host_cache_tag) so mismatched AOT entries are never loaded, and default
# the C++ log level to errors-only so residual loader chatter stays out of
# the JSON tail (export TF_CPP_MIN_LOG_LEVEL=0 to re-enable).
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

REPO = os.path.dirname(os.path.abspath(__file__))
BENCH_DIR = os.environ.get("NDS_TPU_BENCH_DIR",
                           os.path.join(REPO, ".bench_data"))
SCALE = os.environ.get("NDS_TPU_BENCH_SF", "1")
# default subset: a spread of plan shapes (correlated-subquery CTE, star
# join+group, multi-dim join, scalar-subquery battery, semi/anti) whose
# record+compile cost fits the driver's bench budget
QUERIES = os.environ.get(
    "NDS_TPU_BENCH_QUERIES",
    "query1,query3,query7,query9,query10").split(",")
RNGSEED = 778  # fixed: cross-round comparability
TIMED_RUNS = 3


def ensure_data() -> tuple[str, str]:
    data_dir = os.path.join(BENCH_DIR, f"sf{SCALE}")
    wh_dir = os.path.join(BENCH_DIR, f"sf{SCALE}_wh")
    stream_dir = os.path.join(BENCH_DIR, f"sf{SCALE}_streams")
    # marker v2: the measured configuration is exact decimal (decN), so the
    # warehouse must carry DECIMAL parquet columns (--use_decimal)
    marker = os.path.join(BENCH_DIR, f"sf{SCALE}.ready.dec")
    if not os.path.exists(marker):
        os.makedirs(BENCH_DIR, exist_ok=True)
        if not os.path.exists(os.path.join(BENCH_DIR, f"sf{SCALE}.ready")):
            subprocess.run([sys.executable, "-m", "nds_tpu.datagen", "local",
                            data_dir, "--scale", SCALE, "--parallel", "8",
                            "--overwrite"], check=True, cwd=REPO)
        import shutil
        shutil.rmtree(wh_dir, ignore_errors=True)
        subprocess.run([sys.executable, "-m", "nds_tpu.transcode", data_dir,
                        wh_dir, os.path.join(BENCH_DIR, "load_report.txt"),
                        "--no_partition", "--use_decimal"],
                       check=True, cwd=REPO)
        subprocess.run([sys.executable, "-m", "nds_tpu.streams", stream_dir,
                        "--streams", "1", "--rngseed", str(RNGSEED)],
                       check=True, cwd=REPO)
        for m in (marker, os.path.join(BENCH_DIR, f"sf{SCALE}.ready")):
            with open(m, "w") as f:
                f.write("ok")
    return wh_dir, os.path.join(stream_dir, "query_0.sql")


def _host_cache_tag() -> str:
    """Stable per-host tag for the CPU compile-cache directory: caches from
    hosts with different CPU feature sets never mix, so the XLA:CPU AOT
    loader never sees (and never warns about) foreign-machine binaries."""
    import hashlib
    import platform

    probe = f"{platform.machine()}|{platform.processor()}"
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    probe += "|" + " ".join(sorted(line.split()[2:]))
                    break
    except OSError:
        pass
    return hashlib.sha1(probe.encode()).hexdigest()[:10]


def _parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="bench.py",
        description="timed NDS bench slice (one JSON line on stdout)")
    p.add_argument("--trace", action="store_true",
                   help="enable span tracing; writes a Chrome trace-event "
                        "file (Perfetto) + JSONL event log under the bench "
                        "data dir and embeds the span aggregate in the JSON")
    p.add_argument("--trace_dir", default=None,
                   help="directory for trace artifacts (default: bench "
                        "data dir)")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress per-query diagnostic lines (verbosity 0)")
    return p.parse_args(argv)


def main(argv=None) -> None:
    args = _parse_args(argv)
    from nds_tpu.config import EngineConfig, enable_compile_cache, enable_x64
    enable_compile_cache(os.path.join(
        os.path.expanduser("~"), ".cache",
        f"nds_tpu_xla_{_host_cache_tag()}"))

    from nds_tpu.engine import Session
    from nds_tpu.obs import log as obs_log
    from nds_tpu.obs.device_time import PROGRAMS
    from nds_tpu.obs.metrics import METRICS
    from nds_tpu.obs.trace import TRACER
    from nds_tpu.power import gen_sql_from_stream, setup_tables

    log = obs_log.configure(0 if args.quiet else None)
    if args.trace:
        TRACER.configure(enabled=True)

    wh_dir, stream_path = ensure_data()
    # measured configuration: EXACT scaled-int64 decimals (round-3 verdict
    # item 4; reference runs DecimalType, nds/nds_schema.py:43-47). f64
    # remains available via NDS_TPU_BENCH_DECIMAL=f64.
    decimal = os.environ.get("NDS_TPU_BENCH_DECIMAL", "i64")
    if decimal == "i64":
        enable_x64()
    config = EngineConfig(decimal_physical=decimal)
    # A/B knobs for the upload-volume acceptance runs: NDS_TPU_BENCH_NARROW
    # =0 restores the wide int64 morsel layout, NDS_TPU_BENCH_OOC_MIN_ROWS
    # lowers the streaming threshold so the small bench slice streams
    # (bytes_uploaded is 0 for device-resident in-core queries)
    config.narrow_lanes = os.environ.get(
        "NDS_TPU_BENCH_NARROW", "1").lower() not in ("0", "false", "no")
    ooc_min = os.environ.get("NDS_TPU_BENCH_OOC_MIN_ROWS")
    if ooc_min:
        config.out_of_core_min_rows = int(ooc_min)
    # A/B knob for the Pallas kernel swap (ISSUE 7): comma subset of
    # sort,groupby,gather — bit-identical results, per-op kernel choice
    pallas_env = os.environ.get("NDS_TPU_BENCH_PALLAS", "")
    if pallas_env:
        config.pallas_ops = tuple(
            x.strip() for x in pallas_env.split(",") if x.strip())
    session = Session(config)
    setup_tables(session, wh_dir, "parquet")
    with open(stream_path) as f:
        query_dict = gen_sql_from_stream(f.read())
    units = [k for k in query_dict
             if k in QUERIES or k.rsplit("_part", 1)[0] in QUERIES]
    if not units:
        log.error(f"FATAL: no stream query matches NDS_TPU_BENCH_QUERIES="
                  f"{','.join(QUERIES)!r}")
        sys.exit(1)

    jax_ms: dict[str, float] = {}
    np_ms: dict[str, float] = {}
    upload_bytes: dict[str, int] = {}
    exec_modes: dict[str, str] = {}
    fallback_reasons: dict[str, list] = {}
    attribution: dict[str, float] = {}
    for name in units:
        sql = query_dict[name]
        # untimed oracle warm run: the first execution pays the lazy parquet
        # load of every touched table — IO both backends share via the
        # session cache, so it must not be billed to either side. The timed
        # number is best-of like the device side (symmetric methodology).
        session.sql(sql, backend="numpy", label=name)
        best_np = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            session.sql(sql, backend="numpy", label=name)
            best_np = min(best_np, time.perf_counter() - t0)
        np_ms[name] = best_np * 1000

        session.sql(sql, backend="jax", label=name)  # record (host) pass
        session.sql(sql, backend="jax", label=name)  # compile + device run
        if session.last_fallbacks:
            # the per-operator REASON (last_exec_stats.fallback_reasons)
            # makes the remaining host-bound queries enumerable per run
            reasons = session.last_exec_stats.get(
                "fallback_reasons", session.last_fallbacks)
            log.error(f"FATAL: {name} fell back to host: {reasons}")
            sys.exit(1)
        best = float("inf")
        wall_s = 0.0
        prog_ms0 = PROGRAMS.total_ms()
        for _ in range(TIMED_RUNS):
            t0 = time.perf_counter()
            session.sql(sql, backend="jax", label=name)
            run_s = time.perf_counter() - t0
            wall_s += run_s
            best = min(best, run_s)
        jax_ms[name] = best * 1000
        # fraction of the timed window the per-program device-time
        # attribution explains (>=0.9 expected: everything outside
        # CompiledQuery dispatch is python glue)
        attribution[name] = round(
            (PROGRAMS.total_ms() - prog_ms0) / (wall_s * 1000), 3) \
            if wall_s > 0 else 0.0
        # streamed queries re-upload their morsels every run; in-core
        # queries upload nothing in steady state (device-resident scans)
        upload_bytes[name] = session.last_exec_stats.get("bytes_uploaded", 0)
        exec_modes[name] = session.last_exec_stats.get("mode", "in-core")
        if session.last_exec_stats.get("fallback_reasons"):
            fallback_reasons[name] = \
                list(session.last_exec_stats["fallback_reasons"])
        log.info(f"{name}: device {jax_ms[name]:.1f} ms, "
                 f"oracle {np_ms[name]:.1f} ms, mode {exec_modes[name]}, "
                 f"upload {upload_bytes[name] / 1e6:.2f} MB, "
                 f"attribution {attribution[name]:.0%}")

    total_jax = sum(jax_ms.values())
    total_np = sum(np_ms.values())
    rows_scanned, bytes_scanned = scan_volume(session,
                                              [query_dict[u] for u in units])
    device_s = total_jax / 1000.0
    bw_gbps = float(os.environ.get("NDS_TPU_BENCH_BW_GBPS", "100"))
    bw = bw_gbps * 1e9
    qtag = "+".join(u.replace("query", "q") for u in units)
    # per-program device-time attribution: the sorted top-programs table
    # (per-program roofline fractions from cost_analysis bytes) replaces
    # the single global roofline_frac as the kernel-work shopping list
    device_time_programs = PROGRAMS.table(bw_gbps=bw_gbps, top=15)
    out = {
        "schema_version": 2,
        "metric": f"nds_power_{qtag}_sf{SCALE}_ms",
        "value": round(total_jax, 1),
        "unit": "ms",
        "vs_baseline": round(total_np / total_jax, 3),
        # absolute per-chip metrics (round-2 verdict: the oracle varies
        # +/-30% on the shared host; these track progress independently)
        "rows_per_s": round(rows_scanned / device_s),
        "scan_gb": round(bytes_scanned / 1e9, 3),
        # per-run H2D upload volume (streamed morsel buffers, summed over
        # the timed subset): the cost shared-scan fusion divides by the
        # branch count (and narrow lanes divide again) — 0 when every
        # query runs in-core device-resident
        "upload_gb": round(sum(upload_bytes.values()) / 1e9, 3),
        "roofline_frac": round(bytes_scanned / bw / device_s, 4),
        # which queries stream vs run in-core, and why any fell back to
        # the host — the per-run enumeration of non-device work
        "exec_modes": exec_modes,
        "fallback_reasons": fallback_reasons,
        # the Pallas kernel configuration this run measured (ops enabled,
        # platform mode, and the degradation reason when the XLA lowering
        # served despite the flag)
        "pallas": _pallas_summary(config, session),
        # fraction of each query's timed wall the per-program device times
        # explain (acceptance: >= 0.9)
        "attribution_frac": attribution,
        "device_time_programs": device_time_programs,
        # uniform engine counters (obs.metrics): every layer writes through
        # one registry, every report reads the same names
        "metrics": METRICS.snapshot(),
    }
    if args.trace:
        from nds_tpu.obs.device_time import format_table
        trace_dir = args.trace_dir or BENCH_DIR
        out["trace_file"] = TRACER.write_chrome_trace(
            os.path.join(trace_dir, f"bench_trace_sf{SCALE}.json"))
        out["trace_events"] = TRACER.write_jsonl(
            os.path.join(trace_dir, f"bench_trace_sf{SCALE}.jsonl"))
        # aggregated per-span table: the compact per-query view the trace
        # file expands on (open trace_file in ui.perfetto.dev)
        out["spans"] = TRACER.aggregate()
        log.info("trace: %s (open in ui.perfetto.dev)", out["trace_file"])
        log.info("top programs by device time:\n%s",
                 format_table(device_time_programs))
    print(json.dumps(out))


def _pallas_summary(config, session) -> dict:
    """The run's kernel configuration for the bench JSON: which op
    families rode Pallas, the platform mode (tpu/interpret/off), and the
    recorded fallback reason if the XLA lowering served anyway."""
    from nds_tpu.engine.jax_backend import pallas_kernels as pk
    mode, reason = pk.probe()
    out = {"ops": sorted(pk.parse_ops(config.pallas_ops)), "mode": mode}
    fb = session.last_exec_stats.get("pallas_fallback_reason") or \
        (reason if (config.pallas_ops and mode == "off") else None)
    if fb:
        out["fallback_reason"] = fb
    return out


def scan_volume(session, sqls: list[str]) -> tuple[int, int]:
    """(rows, bytes) the timed queries scan, SUMMED PER QUERY: each compiled
    query re-reads its resident scan columns from HBM, so per-query bytes
    add across the subset (columns deduped within one query only — a lower
    bound of HBM traffic, giving a host-load-independent roofline
    fraction)."""
    import jax

    from nds_tpu.sql import parse_sql
    from nds_tpu.engine.planner import Planner
    from nds_tpu.engine.plan import ScanNode, iter_plan_nodes

    x64 = jax.config.read("jax_enable_x64")
    wide = 8 if x64 else 4
    size = {"int": wide, "float": wide, "bool": 1, "date": 4, "str": 4}
    rows = 0
    total_bytes = 0
    for sql in sqls:
        tables: set[str] = set()
        cols: dict[tuple[str, str], int] = {}
        for stmt in (x for x in sql.split(";") if x.strip()):
            plan = Planner(session._catalog()).plan_query(parse_sql(stmt))
            for node in iter_plan_nodes(plan):
                if not isinstance(node, ScanNode):
                    continue
                tables.add(node.table)
                n = session._est_rows.get(node.table, 0)
                for c, d in zip(node.columns, node.out_dtypes):
                    cols[(node.table, c)] = n * size.get(d, wide)
        rows += sum(session._est_rows.get(t, 0) for t in tables)
        total_bytes += sum(cols.values())
    return rows, total_bytes


if __name__ == "__main__":
    sys.exit(main())
