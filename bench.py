"""Benchmark entry point: one JSON line for the driver.

Round-2 benchmark: the REAL NDS workload (BASELINE.md ladder steps 1-2
shape) — native datagen at SF1, transcode to a Parquet warehouse, template
-substituted query stream, then a timed power-run subset on the device
(JAX/TPU) backend vs the numpy host oracle (the CPU-vs-accelerator frame of
reference nds/nds_validate.py; per-query timing mirrors
nds/nds_power.py:281-299).

Methodology: each query runs three times on the device backend — (1) eager
record pass (capacity schedule, host CPU), (2) whole-plan XLA compile +
first device run, (3+) steady-state compiled device runs. The TIMED number
is the best compiled run: the framework's contract is that a query stream
compiles once and re-runs (throughput test, repeated streams), matching the
reference's accelerated-plan steady state. Queries that fall back to the
host oracle FAIL the bench (reference runs every op on the accelerator).

Artifacts (data, warehouse, stream) are cached under .bench_data/ across
rounds; delete the directory to force regeneration.

Prints: {"metric", "value", "unit", "vs_baseline"} — value is the power-run
subset wall (ms) on the device path; vs_baseline > 1 means the device path
beats the host oracle. Everything else (per-query diagnostics) goes to
stderr through the nds_tpu.obs.log channel (NDS_TPU_VERBOSITY / -q).

--trace: enable the obs span tracer for the whole run and write a Chrome
trace-event file (opens in Perfetto / chrome://tracing) plus a JSONL event
log next to the bench data; the JSON line gains the per-span aggregate,
the per-program device-time table with per-program roofline fractions,
and the engine metrics snapshot.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

# XLA:CPU AOT results deserialized from a persistent cache written on a
# DIFFERENT machine spam a multi-KB machine-feature-mismatch warning per
# load (cpu_aot_loader.cc), burying the bench output. Two-part fix, set
# BEFORE jax/XLA load: scope the compile cache per host feature set (see
# _host_cache_tag) so mismatched AOT entries are never loaded, and default
# the C++ log level to errors-only so residual loader chatter stays out of
# the JSON tail (export TF_CPP_MIN_LOG_LEVEL=0 to re-enable).
#
# The flag is read at XLA's C++ static init — i.e. when jaxlib's shared
# library LOADS, which an interpreter-start sitecustomize that imports jax
# does before this module ever runs. Track both conditions so main() can
# re-exec once into a fresh interpreter with the env actually in place
# (_maybe_reexec): that is what finally covers the AOT-load path and keeps
# the captured bench tail clean.
_JAX_PRELOADED = "jax" in sys.modules or "jaxlib" in sys.modules
_TF_LOG_PRESET = "TF_CPP_MIN_LOG_LEVEL" in os.environ
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

REPO = os.path.dirname(os.path.abspath(__file__))
BENCH_DIR = os.environ.get("NDS_TPU_BENCH_DIR",
                           os.path.join(REPO, ".bench_data"))
SCALE = os.environ.get("NDS_TPU_BENCH_SF", "1")
# default subset: a spread of plan shapes (correlated-subquery CTE, star
# join+group, multi-dim join, scalar-subquery battery, semi/anti) whose
# record+compile cost fits the driver's bench budget
QUERIES = os.environ.get(
    "NDS_TPU_BENCH_QUERIES",
    "query1,query3,query7,query9,query10").split(",")
RNGSEED = 778  # fixed: cross-round comparability
TIMED_RUNS = 3


def ensure_data() -> tuple[str, str]:
    data_dir = os.path.join(BENCH_DIR, f"sf{SCALE}")
    wh_dir = os.path.join(BENCH_DIR, f"sf{SCALE}_wh")
    stream_dir = os.path.join(BENCH_DIR, f"sf{SCALE}_streams")
    # marker v2: the measured configuration is exact decimal (decN), so the
    # warehouse must carry DECIMAL parquet columns (--use_decimal)
    marker = os.path.join(BENCH_DIR, f"sf{SCALE}.ready.dec")
    if not os.path.exists(marker):
        os.makedirs(BENCH_DIR, exist_ok=True)
        if not os.path.exists(os.path.join(BENCH_DIR, f"sf{SCALE}.ready")):
            subprocess.run([sys.executable, "-m", "nds_tpu.datagen", "local",
                            data_dir, "--scale", SCALE, "--parallel", "8",
                            "--overwrite"], check=True, cwd=REPO)
        import shutil
        shutil.rmtree(wh_dir, ignore_errors=True)
        subprocess.run([sys.executable, "-m", "nds_tpu.transcode", data_dir,
                        wh_dir, os.path.join(BENCH_DIR, "load_report.txt"),
                        "--no_partition", "--use_decimal"],
                       check=True, cwd=REPO)
        subprocess.run([sys.executable, "-m", "nds_tpu.streams", stream_dir,
                        "--streams", "1", "--rngseed", str(RNGSEED)],
                       check=True, cwd=REPO)
        for m in (marker, os.path.join(BENCH_DIR, f"sf{SCALE}.ready")):
            with open(m, "w") as f:
                f.write("ok")
    return wh_dir, os.path.join(stream_dir, "query_0.sql")


def _host_cache_tag() -> str:
    """Stable per-host tag for the CPU compile-cache directory: caches from
    hosts with different CPU feature sets never mix, so the XLA:CPU AOT
    loader never sees (and never warns about) foreign-machine binaries."""
    import hashlib
    import platform

    probe = f"{platform.machine()}|{platform.processor()}"
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    probe += "|" + " ".join(sorted(line.split()[2:]))
                    break
    except OSError:
        pass
    return hashlib.sha1(probe.encode()).hexdigest()[:10]


def _parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="bench.py",
        description="timed NDS bench slice (one JSON line on stdout)")
    p.add_argument("--trace", action="store_true",
                   help="enable span tracing; writes a Chrome trace-event "
                        "file (Perfetto) + JSONL event log under the bench "
                        "data dir and embeds the span aggregate in the JSON")
    p.add_argument("--trace_dir", default=None,
                   help="directory for trace artifacts (default: bench "
                        "data dir)")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress per-query diagnostic lines (verbosity 0)")
    p.add_argument("--mesh_shards", default=None, metavar="N[,N...]",
                   help="multi-chip sharded morsel execution scaling run: "
                        "comma list of replica counts (e.g. 1,2,4,8). "
                        "After the main single-chip measurement, the slice "
                        "re-runs once per count with streamed scan groups "
                        "dispatched over that many mesh replicas "
                        "(EngineConfig.mesh_shards) and the JSON gains a "
                        "per-count \"mesh_scaling\" table (wall, rows/s, "
                        "collective bytes/ms). On a CPU host the device "
                        "count is forced virtually (re-exec with "
                        "XLA_FLAGS=--xla_force_host_platform_device_count)")
    p.add_argument("--mesh_record", default=None, metavar="PATH",
                   help="also write the mesh scaling table as a standalone "
                        "MULTICHIP_r*.json-style record to PATH")
    p.add_argument("--no_encoded", action="store_true",
                   help="disable encoded execution (dictionary/RLE wire "
                        "encodings, EngineConfig.encoded_exec) for A/B "
                        "upload-volume runs; equivalent to "
                        "NDS_TPU_BENCH_ENCODED=0")
    p.add_argument("--query_log", default=None, metavar="PATH",
                   help="enable the durable query log (obs/query_log.py) "
                        "and append one flat JSONL row per completed "
                        "statement here — the bench run's self-describing "
                        "artifact for scripts/slo_report.py")
    p.add_argument("--adaptive", action="store_true",
                   help="enable adaptive execution (EngineConfig."
                        "adaptive_plans, engine/feedback.py): the first "
                        "sighting of each query observes actuals, later "
                        "sightings right-size capacity schedules from "
                        "them; the JSON gains an \"adaptive\" block "
                        "(feedback counters, per-query capacity-cell and "
                        "mem-peak deltas, result-hash identity). "
                        "Equivalent to NDS_TPU_BENCH_ADAPTIVE=1")
    return p.parse_args(argv)


def _mesh_counts(args) -> list[int]:
    if not args.mesh_shards:
        return []
    return [int(x) for x in str(args.mesh_shards).split(",") if x.strip()]


def _maybe_reexec(args, argv) -> None:
    """Make the process environment actually effective for this run.

    Two knobs are read before bench.py gets a chance to set them when an
    interpreter-start sitecustomize imports jax: TF_CPP_MIN_LOG_LEVEL
    (XLA C++ static init — the cpu_aot_loader machine-feature spam) and
    XLA_FLAGS' virtual device count (backend init). When either matters
    and jax is already loaded, exec once into a fresh interpreter with the
    env in place; without a preloaded jax, setting os.environ here is
    early enough and no exec happens."""
    counts = _mesh_counts(args)
    want = max(counts, default=0)
    flags = os.environ.get("XLA_FLAGS", "")
    force_devices = (
        want > 1
        and os.environ.get("JAX_PLATFORMS", "cpu").split(",")[0] == "cpu"
        and "xla_force_host_platform_device_count" not in flags)
    if force_devices:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={want}"
        ).strip()
        os.environ["JAX_PLATFORMS"] = "cpu"
    if not _JAX_PRELOADED or os.environ.get("NDS_TPU_BENCH_ENV_READY"):
        return
    if not force_devices and _TF_LOG_PRESET:
        return      # the stale interpreter already has everything right
    env = dict(os.environ, NDS_TPU_BENCH_ENV_READY="1")
    os.execve(sys.executable,
              [sys.executable, os.path.abspath(__file__)] +
              (list(argv) if argv is not None else sys.argv[1:]), env)


def main(argv=None) -> None:
    args = _parse_args(argv)
    _maybe_reexec(args, argv)
    from nds_tpu.config import EngineConfig, enable_compile_cache, enable_x64
    enable_compile_cache(os.path.join(
        os.path.expanduser("~"), ".cache",
        f"nds_tpu_xla_{_host_cache_tag()}"))

    from nds_tpu.engine import Session
    from nds_tpu.obs import log as obs_log
    from nds_tpu.obs.device_time import PROGRAMS
    from nds_tpu.obs.metrics import METRICS
    from nds_tpu.obs.trace import TRACER
    from nds_tpu.power import gen_sql_from_stream, setup_tables

    log = obs_log.configure(0 if args.quiet else None)
    if args.trace:
        TRACER.configure(enabled=True)

    wh_dir, stream_path = ensure_data()
    # measured configuration: EXACT scaled-int64 decimals (round-3 verdict
    # item 4; reference runs DecimalType, nds/nds_schema.py:43-47). f64
    # remains available via NDS_TPU_BENCH_DECIMAL=f64.
    decimal = os.environ.get("NDS_TPU_BENCH_DECIMAL", "i64")
    if decimal == "i64":
        enable_x64()
    config = EngineConfig(decimal_physical=decimal)
    # A/B knobs for the upload-volume acceptance runs: NDS_TPU_BENCH_NARROW
    # =0 restores the wide int64 morsel layout, NDS_TPU_BENCH_OOC_MIN_ROWS
    # lowers the streaming threshold so the small bench slice streams
    # (bytes_uploaded is 0 for device-resident in-core queries)
    config.narrow_lanes = os.environ.get(
        "NDS_TPU_BENCH_NARROW", "1").lower() not in ("0", "false", "no")
    # NDS_TPU_BENCH_ENCODED=0 / --no_encoded: plain narrow-lane layout
    # (encoded execution off) for the dictionary/RLE A/B acceptance runs
    config.encoded_exec = not args.no_encoded and os.environ.get(
        "NDS_TPU_BENCH_ENCODED", "1").lower() not in ("0", "false", "no")
    ooc_min = os.environ.get("NDS_TPU_BENCH_OOC_MIN_ROWS")
    if ooc_min:
        config.out_of_core_min_rows = int(ooc_min)
    # A/B knob for the Pallas kernel swap (ISSUE 7): comma subset of
    # sort,groupby,gather — bit-identical results, per-op kernel choice
    pallas_env = os.environ.get("NDS_TPU_BENCH_PALLAS", "")
    if pallas_env:
        config.pallas_ops = tuple(
            x.strip() for x in pallas_env.split(",") if x.strip())
    if args.query_log:
        config.query_log = True
        config.query_log_path = args.query_log
    # --adaptive / NDS_TPU_BENCH_ADAPTIVE=1: feedback-driven plans; the
    # first sighting of each query observes (morsel-bound schedules),
    # later sightings replay right-sized ones — the A/B evidence rides
    # in the JSON "adaptive" block
    adaptive = args.adaptive or os.environ.get(
        "NDS_TPU_BENCH_ADAPTIVE", "").lower() in ("1", "true", "yes", "on")
    if adaptive:
        config.adaptive_plans = True
    session = Session(config)
    setup_tables(session, wh_dir, "parquet")
    with open(stream_path) as f:
        query_dict = gen_sql_from_stream(f.read())
    units = [k for k in query_dict
             if k in QUERIES or k.rsplit("_part", 1)[0] in QUERIES]
    if not units:
        log.error(f"FATAL: no stream query matches NDS_TPU_BENCH_QUERIES="
                  f"{','.join(QUERIES)!r}")
        sys.exit(1)

    jax_ms: dict[str, float] = {}
    np_ms: dict[str, float] = {}
    upload_bytes: dict[str, int] = {}
    exec_modes: dict[str, str] = {}
    fallback_reasons: dict[str, list] = {}
    attribution: dict[str, float] = {}
    encodings: dict[str, dict] = {}
    adaptive_evidence: dict[str, dict] = {}
    for name in units:
        sql = query_dict[name]
        # untimed oracle warm run: the first execution pays the lazy parquet
        # load of every touched table — IO both backends share via the
        # session cache, so it must not be billed to either side. The timed
        # number is best-of like the device side (symmetric methodology).
        session.sql(sql, backend="numpy", label=name)
        best_np = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            session.sql(sql, backend="numpy", label=name)
            best_np = min(best_np, time.perf_counter() - t0)
        np_ms[name] = best_np * 1000

        t_first = session.sql(sql, backend="jax", label=name)  # record pass
        if adaptive:
            # the first sighting ran UNADAPTED (morsel-bound schedules,
            # nothing observed yet): its stats and content hash are the
            # A/B "before" side; the next sighting re-plans from the
            # observations it just recorded
            from nds_tpu.chaos import result_hash
            adaptive_evidence[name] = {
                "mem_peak_bytes_before":
                    session.last_exec_stats.get("mem_peak_bytes", 0),
                "bytes_uploaded_before":
                    session.last_exec_stats.get("bytes_uploaded", 0),
                "hash_before": result_hash(t_first)}
        session.sql(sql, backend="jax", label=name)  # compile + device run
        if session.last_fallbacks:
            # the per-operator REASON (last_exec_stats.fallback_reasons)
            # makes the remaining host-bound queries enumerable per run
            reasons = session.last_exec_stats.get(
                "fallback_reasons", session.last_fallbacks)
            log.error(f"FATAL: {name} fell back to host: {reasons}")
            sys.exit(1)
        best = float("inf")
        wall_s = 0.0
        prog_ms0 = PROGRAMS.total_ms()
        for _ in range(TIMED_RUNS):
            t0 = time.perf_counter()
            t_last = session.sql(sql, backend="jax", label=name)
            run_s = time.perf_counter() - t0
            wall_s += run_s
            best = min(best, run_s)
            # per-template latency distribution: the same histogram family
            # the service records into, so one registry view ranks slow
            # templates across bench, power, and service runs
            METRICS.histogram("query_latency_ms",
                              template=name).observe(run_s * 1000.0)
        jax_ms[name] = best * 1000
        # fraction of the timed window the per-program device-time
        # attribution explains (>=0.9 expected: everything outside
        # CompiledQuery dispatch is python glue)
        attribution[name] = round(
            (PROGRAMS.total_ms() - prog_ms0) / (wall_s * 1000), 3) \
            if wall_s > 0 else 0.0
        # streamed queries re-upload their morsels every run; in-core
        # queries upload nothing in steady state (device-resident scans)
        upload_bytes[name] = session.last_exec_stats.get("bytes_uploaded", 0)
        exec_modes[name] = session.last_exec_stats.get("mode", "in-core")
        if session.last_exec_stats.get("enc_spec") is not None:
            # the encoded-execution evidence block: which encoding each
            # streamed column rode, the bytes the encodings removed vs the
            # plain narrow-lane layout, and how often values actually
            # materialized (decode sites; steady-state replays decode 0)
            st = session.last_exec_stats
            encodings[name] = {
                "spec": st["enc_spec"],
                "bytes_saved": st.get("enc_bytes_saved", 0),
                "bytes_uploaded": st.get("bytes_uploaded", 0),
                "decode_sites": st.get("decode_sites", 0),
                "decode_rows": st.get("decode_rows", 0),
                "host_decode_ms": st.get("host_decode_ms"),
            }
        if session.last_exec_stats.get("fallback_reasons"):
            fallback_reasons[name] = \
                list(session.last_exec_stats["fallback_reasons"])
        if adaptive:
            # "after" side: the timed runs replayed the ADAPTED programs
            # (observed-maximum capacity buckets). The response must be
            # hash-identical to the unadapted first sighting — right-
            # sizing is a provisioning change, never a result change
            from nds_tpu.chaos import result_hash
            ev = adaptive_evidence[name]
            ev["mem_peak_bytes_after"] = \
                session.last_exec_stats.get("mem_peak_bytes", 0)
            ev["bytes_uploaded_after"] = \
                session.last_exec_stats.get("bytes_uploaded", 0)
            ev["hash_identical"] = \
                result_hash(t_last) == ev.pop("hash_before")
        log.info(f"{name}: device {jax_ms[name]:.1f} ms, "
                 f"oracle {np_ms[name]:.1f} ms, mode {exec_modes[name]}, "
                 f"upload {upload_bytes[name] / 1e6:.2f} MB, "
                 f"attribution {attribution[name]:.0%}")

    total_jax = sum(jax_ms.values())
    total_np = sum(np_ms.values())
    rows_scanned, bytes_scanned = scan_volume(session,
                                              [query_dict[u] for u in units])
    device_s = total_jax / 1000.0
    bw_gbps = float(os.environ.get("NDS_TPU_BENCH_BW_GBPS", "100"))
    bw = bw_gbps * 1e9
    qtag = "+".join(u.replace("query", "q") for u in units)
    mesh_counts = _mesh_counts(args)
    mesh_scaling = None
    if mesh_counts:
        mesh_scaling = _run_mesh_scaling(mesh_counts, wh_dir, query_dict,
                                         units, decimal, rows_scanned, log)
        if args.mesh_record:
            _write_mesh_record(args.mesh_record, mesh_scaling, units)
            log.info("mesh scaling record: %s", args.mesh_record)
    # per-program device-time attribution: the sorted top-programs table
    # (per-program roofline fractions from cost_analysis bytes) replaces
    # the single global roofline_frac as the kernel-work shopping list;
    # mesh scaling runs add their per-shard-count morsel/gather programs
    # (labels "<q>/morsel:<table>@mesh<n>" / "<q>/gather:<table>@mesh<n>"),
    # so the table widens to keep them visible
    device_time_programs = PROGRAMS.table(
        bw_gbps=bw_gbps, top=15 + (8 * len(mesh_counts) if mesh_counts
                                   else 0))
    out = {
        "schema_version": 3,
        "metric": f"nds_power_{qtag}_sf{SCALE}_ms",
        "value": round(total_jax, 1),
        "unit": "ms",
        "vs_baseline": round(total_np / total_jax, 3),
        # absolute per-chip metrics (round-2 verdict: the oracle varies
        # +/-30% on the shared host; these track progress independently)
        "rows_per_s": round(rows_scanned / device_s),
        "scan_gb": round(bytes_scanned / 1e9, 3),
        # per-run H2D upload volume (streamed morsel buffers, summed over
        # the timed subset): the cost shared-scan fusion divides by the
        # branch count (and narrow lanes divide again) — 0 when every
        # query runs in-core device-resident
        "upload_gb": round(sum(upload_bytes.values()) / 1e9, 3),
        "roofline_frac": round(bytes_scanned / bw / device_s, 4),
        # which queries stream vs run in-core, and why any fell back to
        # the host — the per-run enumeration of non-device work
        "exec_modes": exec_modes,
        "fallback_reasons": fallback_reasons,
        # encoded execution (EngineConfig.encoded_exec / --no_encoded):
        # per-query chosen encoding specs + bytes saved + decode counts;
        # {} when off or nothing streams
        "encoded": bool(config.encoded_exec),
        "encodings": encodings,
        # the Pallas kernel configuration this run measured (ops enabled,
        # platform mode, and the degradation reason when the XLA lowering
        # served despite the flag)
        "pallas": _pallas_summary(config, session),
        # fraction of each query's timed wall the per-program device times
        # explain (acceptance: >= 0.9)
        "attribution_frac": attribution,
        "device_time_programs": device_time_programs,
        # uniform engine counters (obs.metrics): every layer writes through
        # one registry, every report reads the same names
        "metrics": METRICS.snapshot(),
        # histogram snapshots (count/sum/min/max + sparse log buckets):
        # scripts/obs_report.py renders quantile tables from this block
        "histograms": METRICS.histograms(),
        # device-memory watermarks (obs/profile.DEVICE_MEM): tracked
        # upload/codebook live set, its process peak, and the headroom to
        # the HBM scan budget — the "how close did this run get to the
        # ceiling" answer per bench round
        "memory": _memory_block(config),
    }
    if mesh_scaling is not None:
        # per-shard-count scaling of the same slice (sharded morsel
        # execution, EngineConfig.mesh_shards): wall, rows/s, collective
        # volume/time, and which queries actually streamed/sharded
        out["mesh_scaling"] = mesh_scaling
    if adaptive:
        # adaptive-execution A/B evidence: the feedback counters, the
        # capacity cells the store's right-sizing removed per template
        # (morsel-bound inflation vs adapted schedule), and the per-query
        # before/after mem-peak + upload volume with hash identity
        from nds_tpu.obs.metrics import (ADAPTIVE_REPLANS, FEEDBACK_HITS,
                                         FEEDBACK_REFRESHES)
        if session._feedback is not None:
            session._feedback.flush()
        out["adaptive"] = {
            "enabled": True,
            "feedback_hits": FEEDBACK_HITS.value,
            "feedback_refreshes": FEEDBACK_REFRESHES.value,
            "adaptive_replans": ADAPTIVE_REPLANS.value,
            "applied": dict(session._feedback.applied)
            if session._feedback is not None else {},
            "queries": adaptive_evidence,
        }
    if args.query_log:
        from nds_tpu.obs.query_log import QUERY_LOG
        QUERY_LOG.flush()
        out["query_log"] = args.query_log
    if args.trace:
        from nds_tpu.obs.device_time import format_table
        trace_dir = args.trace_dir or BENCH_DIR
        out["trace_file"] = TRACER.write_chrome_trace(
            os.path.join(trace_dir, f"bench_trace_sf{SCALE}.json"))
        out["trace_events"] = TRACER.write_jsonl(
            os.path.join(trace_dir, f"bench_trace_sf{SCALE}.jsonl"))
        # aggregated per-span table: the compact per-query view the trace
        # file expands on (open trace_file in ui.perfetto.dev)
        out["spans"] = TRACER.aggregate()
        log.info("trace: %s (open in ui.perfetto.dev)", out["trace_file"])
        log.info("top programs by device time:\n%s",
                 format_table(device_time_programs))
    print(json.dumps(out))


def _memory_block(config) -> dict:
    """The bench JSON ``memory`` block (obs/profile.memory_block against
    this run's configured HBM scan budget)."""
    from nds_tpu.obs.profile import memory_block
    return memory_block(int(config.scan_budget_gb * (1 << 30))
                        if config.scan_budget_gb > 0 else None)


def _run_mesh_scaling(counts, wh_dir, query_dict, units, decimal,
                      rows_scanned, log) -> list:
    """Re-run the timed slice once per shard count with sharded morsel
    execution on (mesh_shards=n; n<=1 = the single-chip baseline row) and
    collect the per-count scaling record: wall (best compiled run per
    query, summed), rows/s, per-device collective ingress bytes and the
    measured partial-gather wall, plus which queries streamed/sharded.

    The streaming threshold drops (NDS_TPU_BENCH_MESH_OOC_MIN_ROWS,
    default 20000) so fact-scan queries actually stream at bench SFs —
    only out-of-core scan groups shard; queries whose plans are not
    streaming-eligible run in-core single-chip and the per-query mode in
    the record says so. NDS_TPU_BENCH_MESH_CHUNK_ROWS sizes the morsel
    (default: the engine default, right for SF1+; small-SF records set it
    near the table size so padded morsel/partial capacities — ONE
    compiled program serves every morsel, so every capacity inflates to
    the chunk bound — do not dwarf the data)."""
    from nds_tpu.config import EngineConfig
    from nds_tpu.engine import Session
    from nds_tpu.power import setup_tables

    import hashlib

    ooc = int(os.environ.get("NDS_TPU_BENCH_MESH_OOC_MIN_ROWS", "20000"))
    chunk = os.environ.get("NDS_TPU_BENCH_MESH_CHUNK_ROWS")
    rows = []
    result_fp: dict = {}      # query -> first count's result fingerprint
    for n in counts:
        config = EngineConfig(decimal_physical=decimal,
                              mesh_shards=n if n > 1 else 0)
        config.out_of_core_min_rows = ooc
        if chunk:
            config.chunk_rows = int(chunk)
        session = Session(config)
        setup_tables(session, wh_dir, "parquet")
        per_query = {}
        modes = {}
        coll_bytes = 0
        coll_ms = 0.0
        sharded_q = 0
        identical = True
        for name in units:
            sql = query_dict[name]
            session.sql(sql, backend="jax", label=name)   # record pass
            session.sql(sql, backend="jax", label=name)   # compile + run
            best = float("inf")
            result = None
            for _ in range(TIMED_RUNS):
                t0 = time.perf_counter()
                result = session.sql(sql, backend="jax", label=name)
                best = min(best, time.perf_counter() - t0)
            st = session.last_exec_stats
            per_query[name] = round(best * 1000, 1)
            modes[name] = st.get("mode", "in-core")
            if st.get("mesh_shards"):
                sharded_q += 1
                coll_bytes += int(st.get("collective_bytes") or 0)
                coll_ms += float(st.get("collective_ms") or 0.0)
            # bit-identity across shard counts is part of the record: the
            # exact-decimal configuration merges integer partials order-
            # independently, so any drift is a sharding bug, not noise
            fp = hashlib.sha1(repr(sorted(
                map(repr, result.to_pylist()))).encode()).hexdigest()[:16]
            if result_fp.setdefault(name, fp) != fp:
                identical = False
                log.error("mesh_shards=%d: %s result drifted from "
                          "mesh_shards=%d", n, name, counts[0])
        wall_ms = round(sum(per_query.values()), 1)
        rows.append({
            "results_identical_to_first_count": identical,
            "mesh_shards": n,
            "wall_ms": wall_ms,
            "rows_per_s": round(rows_scanned / (wall_ms / 1000.0))
            if wall_ms else 0,
            "sharded_queries": sharded_q,
            "streamed_queries": sum(1 for m in modes.values()
                                    if m == "streaming"),
            # per-device ingress of the per-morsel partial all_gathers
            # (ring model) summed over the timed per-query best runs
            "collective_bytes": coll_bytes,
            "collective_ms": round(coll_ms, 1),
            "per_query_ms": per_query,
            "exec_modes": modes,
        })
        log.info("mesh_shards=%d: wall %.1f ms, %d/%d queries sharded, "
                 "collective %.2f MB / %.1f ms", n, wall_ms, sharded_q,
                 len(units), coll_bytes / 1e6, coll_ms)
    return rows


def _write_mesh_record(path: str, mesh_scaling: list, units: list) -> None:
    """Standalone MULTICHIP_r*.json-style record: the dryrun pass/fail bit
    grows into a real per-shard-count scaling table. Virtual CPU devices
    share one host, so these rows measure sharded-execution OVERHEAD and
    bit-exact correctness, not speedup — real scaling numbers wait for a
    TPU slice (the note rides in the record)."""
    import platform

    rec = {
        "schema_version": 2,
        "kind": "mesh_scaling",
        "sf": SCALE,
        "queries": list(units),
        "ooc_min_rows": int(os.environ.get(
            "NDS_TPU_BENCH_MESH_OOC_MIN_ROWS", "20000")),
        "platform": {"python": platform.python_version(),
                     "machine": platform.machine()},
        "virtual_devices": "xla_force_host_platform_device_count" in
                           os.environ.get("XLA_FLAGS", ""),
        "note": ("virtual CPU devices share one host: this table proves "
                 "bit-exact sharded execution and measures its overhead; "
                 "speedup claims require a real TPU slice"),
        "scaling": mesh_scaling,
    }
    with open(path, "w") as f:
        json.dump(rec, f, indent=2)
        f.write("\n")


def _pallas_summary(config, session) -> dict:
    """The run's kernel configuration for the bench JSON: which op
    families rode Pallas, the platform mode (tpu/interpret/off), and the
    recorded fallback reason if the XLA lowering served anyway."""
    from nds_tpu.engine.jax_backend import pallas_kernels as pk
    mode, reason = pk.probe()
    out = {"ops": sorted(pk.parse_ops(config.pallas_ops)), "mode": mode}
    fb = session.last_exec_stats.get("pallas_fallback_reason") or \
        (reason if (config.pallas_ops and mode == "off") else None)
    if fb:
        out["fallback_reason"] = fb
    return out


def scan_volume(session, sqls: list[str]) -> tuple[int, int]:
    """(rows, bytes) the timed queries scan, SUMMED PER QUERY: each compiled
    query re-reads its resident scan columns from HBM, so per-query bytes
    add across the subset (columns deduped within one query only — a lower
    bound of HBM traffic, giving a host-load-independent roofline
    fraction)."""
    import jax

    from nds_tpu.sql import parse_sql
    from nds_tpu.engine.planner import Planner
    from nds_tpu.engine.plan import ScanNode, iter_plan_nodes

    x64 = jax.config.read("jax_enable_x64")
    wide = 8 if x64 else 4
    size = {"int": wide, "float": wide, "bool": 1, "date": 4, "str": 4}
    rows = 0
    total_bytes = 0
    for sql in sqls:
        tables: set[str] = set()
        cols: dict[tuple[str, str], int] = {}
        for stmt in (x for x in sql.split(";") if x.strip()):
            plan = Planner(session._catalog()).plan_query(parse_sql(stmt))
            for node in iter_plan_nodes(plan):
                if not isinstance(node, ScanNode):
                    continue
                tables.add(node.table)
                n = session._est_rows.get(node.table, 0)
                for c, d in zip(node.columns, node.out_dtypes):
                    cols[(node.table, c)] = n * size.get(d, wide)
        rows += sum(session._est_rows.get(t, 0) for t in tables)
        total_bytes += sum(cols.values())
    return rows, total_bytes


if __name__ == "__main__":
    sys.exit(main())
