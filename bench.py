"""Benchmark entry point: one JSON line for the driver.

Current benchmark (round 1): a star-schema aggregate query (NDS power-run
shape: fact x dimension join -> group -> agg; reference nds/nds_power.py
times 103 such units per stream) over synthetic deterministic data, run on
the default JAX platform (the real TPU chip under the driver) through the
engine's JAX backend. Baseline = the same query through the numpy oracle
backend on host CPU — the reference's CPU-vs-accelerator frame
(nds/nds_validate.py compares exactly these two roles).

Prints: {"metric", "value", "unit", "vs_baseline"} — vs_baseline > 1 means
the device path beats the host-oracle path.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


N_FACT = 2_000_000
N_DIM = 20_000
REPEATS = 5

QUERY = """
SELECT d.grp, COUNT(*) AS cnt, SUM(f.qty) AS total_qty,
       AVG(f.price) AS avg_price, MAX(f.price) AS max_price
FROM fact f JOIN dim d ON f.fk = d.dk
WHERE f.day BETWEEN 30 AND 120 AND f.qty > 5
GROUP BY d.grp
ORDER BY d.grp
"""


def build_session():
    import pyarrow as pa

    from nds_tpu.engine import Session

    rng = np.random.default_rng(42)
    fact = pa.table({
        "fk": pa.array(rng.integers(0, N_DIM + 500, N_FACT), type=pa.int32()),
        "qty": pa.array(rng.integers(1, 100, N_FACT), type=pa.int32()),
        "price": pa.array(np.round(rng.uniform(0.5, 999.0, N_FACT), 2)
                          .astype(np.float32)),
        "day": pa.array(rng.integers(0, 365, N_FACT), type=pa.int32()),
    })
    dim = pa.table({
        "dk": pa.array(np.arange(N_DIM), type=pa.int32()),
        "grp": pa.array((np.arange(N_DIM) % 100).astype(np.int32)),
    })
    s = Session()
    s.register_arrow("fact", fact)
    s.register_arrow("dim", dim)
    return s


def timed(fn, repeats: int) -> float:
    fn()  # warmup (compile + caches)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> None:
    s = build_session()
    t_jax = timed(lambda: s.sql(QUERY, backend="jax"), REPEATS)
    t_oracle = timed(lambda: s.sql(QUERY, backend="numpy"), 3)
    rows_per_sec = N_FACT / t_jax
    print(json.dumps({
        "metric": "star_agg_query_rows_per_sec",
        "value": round(rows_per_sec, 1),
        "unit": "rows/s",
        "vs_baseline": round(t_oracle / t_jax, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
