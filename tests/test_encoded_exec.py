"""Encoded execution end-to-end (round 13).

The narrow-lane machinery generalized from width to ENCODING
(device.plan_encodings): low-cardinality int/date/decimal columns upload as
dictionary CODES on u8/u16 lanes plus a once-per-group host codebook, and
clustered columns upload as (value, run-length) pairs expanded on device.
Execution stays on codes where legality allows — equality/IN filters remap
literals through the sorted codebook at trace time, join and group keys
factorize codes directly, sorts ride the order-preserving dictionary — and
device.decode_col materializes values only at arithmetic/aggregate/output
sites. Exactness is pinned by a property round trip over dtypes x
encodings x validity patterns, on/off bit-identity differentials on
streamed shapes (plus a numpy oracle and a slow-marked SF0.01 SQLite
slice), verifier "encoding" findings, and a sharded (mesh_shards=2)
encoded round trip."""
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from nds_tpu.config import EngineConfig
from nds_tpu.engine import Session, arrow_bridge
from nds_tpu.engine.column import Column, Table
from nds_tpu.engine.jax_backend.device import (
    EncodingOverflowError, device_bytes, enc_lane_bytes, lane_bytes,
    pack_table, plan_encodings, plan_lanes, to_device, to_host,
    unpack_table)

N_FACT, N_DIM = 50_000, 300
CHUNK = 4_096


def _col(dtype, data, valid=None, dictionary=None):
    return Column.from_values(dtype, np.asarray(data), valid, dictionary)


def _validity(pattern, n, rng):
    if pattern == "none_null":
        return None
    if pattern == "all_null":
        return np.zeros(n, dtype=bool)
    return rng.random(n) < 0.7


# ---------------------------------------------------------------------------
# pack/unpack round trip: dtypes x encodings x validity patterns
# ---------------------------------------------------------------------------

# (name, dtype, value domain, expected enc kind)
_DICT_CASES = [
    ("int_wide_lowcard", "int", np.arange(0, 3_000_000, 9973), "dict"),
    ("dec2_lowcard", "dec2", np.arange(-500_000, 500_000, 7919), "dict"),
    ("date_lowcard", "date", np.arange(2_450_000, 2_453_000, 7), "dict"),
    ("int_single_value", "int", np.asarray([1_234_567]), "dict"),
    # max cardinality for a u8 code lane: exactly 256 distinct values
    ("int_u8_boundary", "int", np.arange(0, 256_000, 1000), "dict"),
]


@pytest.mark.parametrize("pattern", ["none_null", "mixed", "all_null"])
@pytest.mark.parametrize("name,dtype,domain,kind", _DICT_CASES,
                         ids=[c[0] for c in _DICT_CASES])
def test_roundtrip_dict(name, dtype, domain, kind, pattern):
    rng = np.random.default_rng(hash((name, pattern)) % 2 ** 31)
    n = 700
    data = rng.choice(domain, n)
    valid = _validity(pattern, n, rng)
    t = Table([name], [_col(dtype, data, valid)])
    lanes = plan_lanes([dtype], [(int(domain.min()), int(domain.max()))])
    st = arrow_bridge.column_enc_stat_values(
        np.asarray(t.columns[0].data), t.columns[0].validity)
    st["runs"] = None    # isolate the dict candidate (degenerate shapes —
    #                      single value, all-null — would prefer rle)
    planned = plan_encodings([dtype], lanes, [st], 1024)
    assert planned is not None
    encs, wire_lanes, books = planned
    assert encs[0][0] == kind
    packed = pack_table(t, capacity=1024, lanes=wire_lanes, encs=encs,
                        codebooks=books)
    dt = unpack_table(packed)
    assert (dt.cols[0].codebook is not None) == (kind == "dict")
    got = to_host(dt)
    want = to_host(to_device(t, capacity=1024))
    np.testing.assert_array_equal(np.asarray(got.columns[0].data),
                                  np.asarray(want.columns[0].data))
    np.testing.assert_array_equal(got.columns[0].validity,
                                  want.columns[0].validity)


@pytest.mark.parametrize("pattern", ["none_null", "mixed", "all_null"])
@pytest.mark.parametrize("shape", ["sorted_runs", "single_run", "run_len_1"])
def test_roundtrip_rle(shape, pattern):
    rng = np.random.default_rng(hash((shape, pattern)) % 2 ** 31)
    n = 700
    if shape == "sorted_runs":
        data = np.sort(rng.integers(0, 40, n)) * 1_000_003
    elif shape == "single_run":
        data = np.full(n, 77)
    else:  # run_len_1: every row its own run (worst case, still exact)
        data = np.arange(n) * 3 + 1
    valid = _validity(pattern, n, rng)
    t = Table(["r"], [_col("int", data, valid)])
    lanes = plan_lanes(["int"], [(int(data.min()), int(data.max()))])
    st = arrow_bridge.column_enc_stat_values(
        np.asarray(t.columns[0].data), t.columns[0].validity)
    st["distinct"] = None          # force the rle candidate
    encs = (("rle", st["runs"]),)
    packed = pack_table(t, capacity=1024, lanes=lanes, encs=encs,
                        codebooks=(None,))
    got = to_host(unpack_table(packed))
    want = to_host(to_device(t, capacity=1024))
    np.testing.assert_array_equal(np.asarray(got.columns[0].data),
                                  np.asarray(want.columns[0].data))
    np.testing.assert_array_equal(got.columns[0].validity,
                                  want.columns[0].validity)


def test_encoding_overflow_rejects():
    """Data violating the declared encoding spec must fail LOUDLY: a value
    outside the dictionary or more runs than planned would otherwise ship
    a silently wrong morsel."""
    book = np.asarray([10, 20, 30], dtype=np.int32)
    bad = Table(["x"], [_col("int", np.asarray([10, 25]))])
    with pytest.raises(EncodingOverflowError):
        pack_table(bad, capacity=8, lanes=("u8",), encs=(("dict", 3),),
                   codebooks=(book,))
    # nulls ride code 0 without being dictionary members
    nullish = Table(["x"], [_col("int", np.asarray([10, 99]),
                                 np.asarray([True, False]))])
    assert pack_table(nullish, capacity=8, lanes=("u8",),
                      encs=(("dict", 3),), codebooks=(book,)) is not None
    alternating = Table(["x"], [_col("int", np.arange(100) % 7)])
    with pytest.raises(EncodingOverflowError):
        pack_table(alternating, capacity=128, lanes=("u8",),
                   encs=(("rle", 4),), codebooks=(None,))


def test_plan_encodings_selection():
    """Selection policy: dict only when the code lane is strictly narrower
    than the value lane, rle only on a >= 2x data-section win, plain
    otherwise; no stats -> None (all plain, always safe)."""
    # wide-range low-cardinality int: i32 value lane -> u16 codes
    st = {"distinct": np.arange(0, 3_000_000, 9973), "runs": None}
    encs, wlanes, books = plan_encodings(["int"], ("u32",), [st], 4096)
    assert encs[0][0] == "dict" and wlanes == ("u16",)
    assert books[0].dtype == np.int32
    # u8-range column: codes cannot beat the u8 value lane -> plain
    assert plan_encodings(["int"], ("u8",),
                          [{"distinct": np.arange(200), "runs": None}],
                          4096) is None
    # clustered column: few runs -> rle on the value lane
    encs, wlanes, _ = plan_encodings(["int"], ("u32",),
                                     [{"distinct": None, "runs": 50}], 4096)
    assert encs[0][0] == "rle" and wlanes == ("u32",)
    # run-length-1 data: run count ~ rows -> no win -> plain
    assert plan_encodings(["int"], ("u32",),
                          [{"distinct": None, "runs": 4096}], 4096) is None
    assert plan_encodings(["int"], ("u32",), [None], 4096) is None
    # bytes accounting covers the encoded sections
    encs, wlanes, books = plan_encodings(["int"], ("u32",), [st], 4096)
    p = pack_table(Table(["x"], [_col("int", st["distinct"][:100])]),
                   capacity=4096, lanes=wlanes, encs=encs, codebooks=books)
    assert device_bytes(p) == enc_lane_bytes(wlanes, 4096, encs) \
        < lane_bytes(("u32",), 4096)


# ---------------------------------------------------------------------------
# streamed differentials: encoded on vs off bit-identical, fewer bytes,
# joins/group-bys demonstrably on codes
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def bench_shape(tmp_path_factory):
    """An NDS-fact shape stressing every encoding: a wide-range
    low-cardinality join key (dict), a scaled-decimal-like price (dict), a
    date-clustered column (rle), a quantity already on u8 (plain), and a
    float payload."""
    tmp = tmp_path_factory.mktemp("encoded_exec")
    rng = np.random.default_rng(29)
    days = np.sort(rng.integers(2_450_000, 2_450_200, N_FACT))
    fk_domain = np.arange(0, 3_000_000, 9973)       # 301 wide-spread keys
    price_domain = np.arange(100, 3_000_000, 7919)  # 379 distinct prices
    qty = rng.integers(1, 100, N_FACT).astype(object)
    qty[rng.random(N_FACT) < 0.05] = None
    fact = pa.table({
        "fk": pa.array(rng.choice(fk_domain, N_FACT), type=pa.int64()),
        "qty": pa.array(list(qty), type=pa.int32()),
        "price": pa.array(rng.choice(price_domain, N_FACT),
                          type=pa.int64()),
        "day": pa.array(days, type=pa.int64()),
        "f": pa.array(np.round(rng.uniform(0, 10, N_FACT), 3)),
    })
    path = os.path.join(str(tmp), "fact.parquet")
    pq.write_table(fact, path, row_group_size=8192)
    dim = pa.table({"dk": pa.array(fk_domain, type=pa.int64()),
                    "grp": pa.array((np.arange(len(fk_domain)) % 13)
                                    .astype(np.int32))})
    return {"fact_path": path, "dim": dim}


Q_BENCH = """
SELECT d.grp, SUM(f.qty) AS s, COUNT(*) AS c, MIN(f.price) AS mp,
       MAX(f.day) AS md, SUM(f.f) AS sf
FROM fact f JOIN dim d ON f.fk = d.dk
WHERE f.day < 2450150 AND f.price > 5000
GROUP BY d.grp ORDER BY d.grp
"""


def _session(data, encoded, **kw):
    cfg = EngineConfig(out_of_core=True, chunk_rows=CHUNK,
                       out_of_core_min_rows=10_000, encoded_exec=encoded,
                       **kw)
    s = Session(cfg)
    s.register_parquet("fact", data["fact_path"])
    s.register_arrow("dim", data["dim"])
    return s


def rows_of(t):
    return [tuple(r) for r in t.to_pylist()]


def test_encoded_off_bit_identical_and_bytes(bench_shape):
    """Acceptance: default (encoded) vs --no_encoded_exec results are
    BIT-IDENTICAL while bytes_uploaded drops >= 1.5x, with per-pass plan
    verification (incl. encoding/stats legality) green in both modes."""
    s_on = _session(bench_shape, True, verify_plans="per-pass")
    on = rows_of(s_on.sql(Q_BENCH, backend="jax"))
    st_on = dict(s_on.last_exec_stats)
    s_off = _session(bench_shape, False, verify_plans="per-pass")
    off = rows_of(s_off.sql(Q_BENCH, backend="jax"))
    st_off = dict(s_off.last_exec_stats)
    assert st_on["mode"] == st_off["mode"] == "streaming"
    assert on == off
    assert st_on["encoded_exec"] and not st_off["encoded_exec"]
    assert st_on["bytes_uploaded"] * 1.5 <= st_off["bytes_uploaded"]
    spec = st_on["enc_spec"]["fact"]
    assert spec["fk"].startswith("dict[") and spec["price"].startswith(
        "dict[")
    assert spec["day"].startswith("rle[")
    assert spec["qty"] == "plain" and spec["f"] == "plain"
    # dict columns ride their CODE lane on the wire
    assert st_on["lane_spec"]["fact"]["fk"] == "u16"
    assert st_on["enc_bytes_saved"] > 0
    assert st_off.get("enc_spec") is None
    # host-side morsel decode wall is now measurable per streamed table
    assert st_on["host_decode_ms"]["fact"] > 0
    # numpy oracle (float tolerance on the f64 sum only)
    oracle = rows_of(_session(bench_shape, True)
                     .sql(Q_BENCH, backend="numpy"))
    assert len(on) == len(oracle)
    for a, b in zip(on, oracle):
        assert a[:5] == b[:5]
        assert abs(a[5] - b[5]) <= 1e-6 * max(1.0, abs(b[5]))


def test_join_and_groupby_run_on_codes(bench_shape):
    """The decode-site evidence: the dict-encoded join key never
    materializes values at morsel scale — only the aggregate ARGUMENTS
    decode (qty/price sums at morsel capacity), so decode_rows stays a
    small multiple of the morsel cap instead of sites x morsels x cap,
    and a full replay run decodes NOTHING."""
    s = _session(bench_shape, True)
    s.sql(Q_BENCH, backend="jax")
    st1 = dict(s.last_exec_stats)
    assert st1["decode_sites"] > 0
    # record + one jit trace: each decodes the agg args once; the fk join
    # key and day filter contribute no morsel-scale decode, so the total
    # stays bounded by (2 passes) x (agg-arg sites) x cap + group-sized
    # output decodes, far under morsels x cap
    assert st1["decode_rows"] <= 6 * CHUNK
    assert st1["morsels"] * CHUNK > 6 * CHUNK
    s.sql(Q_BENCH, backend="jax")
    st2 = dict(s.last_exec_stats)
    assert st2["decode_sites"] == 0 and st2["decode_rows"] == 0
    assert st2["re_records"] == 0


def test_filter_literal_remap(bench_shape):
    """Equality/range/IN filters on dict-encoded columns remap literals
    into code space at trace time — including literals ABSENT from the
    dictionary (eq -> empty, ne -> all valid rows, range -> boundary)."""
    s_on = _session(bench_shape, True)
    s_off = _session(bench_shape, False)
    queries = [
        # 9973*7 is in the fk dictionary; 9974 is not
        "SELECT COUNT(*) c FROM fact WHERE fk = 69811",
        "SELECT COUNT(*) c FROM fact WHERE fk = 9974",
        "SELECT COUNT(*) c FROM fact WHERE fk <> 9974",
        "SELECT COUNT(*) c FROM fact WHERE price > 5000 AND price <= 100000",
        "SELECT COUNT(*) c FROM fact WHERE fk IN (69811, 9974, 19946)",
        "SELECT COUNT(*) c, SUM(qty) s FROM fact WHERE day >= 2450100",
    ]
    for q in queries:
        on = rows_of(s_on.sql(q, backend="jax"))
        off = rows_of(s_off.sql(q, backend="jax"))
        oracle = rows_of(s_on.sql(q, backend="numpy"))
        assert on == off == oracle, q


def test_sort_rides_order_preserving_dictionary(bench_shape):
    """ORDER BY an encoded column: the sorted codebook makes code order ==
    value order, so the streamed sort result matches the plain path."""
    q = ("SELECT price, COUNT(*) c FROM fact WHERE day < 2450100 "
         "GROUP BY price ORDER BY price DESC LIMIT 50")
    on = rows_of(_session(bench_shape, True).sql(q, backend="jax"))
    off = rows_of(_session(bench_shape, False).sql(q, backend="jax"))
    assert on == off and len(on) == 50


def test_live_toggle_invalidates_stream_cache(bench_shape):
    """encoded_exec is part of the stream-cache config fingerprint: a live
    toggle must re-derive groups/encodings/programs, not replay stale."""
    s = _session(bench_shape, True)
    a = rows_of(s.sql(Q_BENCH, backend="jax"))
    assert s.last_exec_stats["enc_spec"]
    s.config.encoded_exec = False
    b = rows_of(s.sql(Q_BENCH, backend="jax"))
    assert s.last_exec_stats.get("enc_spec") is None
    assert a == b


def test_dict_upload_cache_counts_hits(bench_shape):
    """The per-group device codebook uploads once; every later decode site
    / morsel re-record reuses it (obs/metrics dict_uploads_saved)."""
    from nds_tpu.obs.metrics import METRICS
    before = METRICS.snapshot()
    s = _session(bench_shape, True)
    s.sql(Q_BENCH, backend="jax")
    after = METRICS.snapshot()
    assert after.get("dict_uploads_saved", 0) > \
        before.get("dict_uploads_saved", 0)
    assert after.get("decode_sites", 0) > before.get("decode_sites", 0)


def test_sharded_encoded_roundtrip(bench_shape):
    """mesh_shards=2: the encoded morsel payload lands row-sharded (equal
    per-replica packed blocks, codebooks shared) and stays bit-identical
    to the single-chip encoded path AND to the plain path. Integer/decimal
    partials only — float partial sums are order-sensitive across shard
    counts (the documented PR-8 restriction), so the differential query
    keeps the exact-integer shape."""
    q = ("SELECT d.grp, SUM(f.qty) s, COUNT(*) c, MIN(f.price) mp, "
         "MAX(f.day) md FROM fact f JOIN dim d ON f.fk = d.dk "
         "WHERE f.day < 2450150 GROUP BY d.grp ORDER BY d.grp")
    single = rows_of(_session(bench_shape, True).sql(q, backend="jax"))
    plain = rows_of(_session(bench_shape, False).sql(q, backend="jax"))
    s = _session(bench_shape, True, mesh_shards=2)
    sharded = rows_of(s.sql(q, backend="jax"))
    st = dict(s.last_exec_stats)
    assert st["sharded_groups"] == 1 and st["mesh_shards"] == 2
    assert st["enc_spec"]["fact"]["fk"].startswith("dict[")
    assert sharded == single == plain


# ---------------------------------------------------------------------------
# fast multi-shape differential battery (the plan-sweep complement: every
# streaming shape the planner emits — union channels, semi-join build
# sides, scalar subqueries — on/off bit-identical)
# ---------------------------------------------------------------------------

_SHAPES = [
    ("scalar_subquery",
     "SELECT COUNT(*) c FROM fact WHERE price > "
     "(SELECT AVG(price) FROM fact)"),
    ("semi_join",
     "SELECT COUNT(*) c FROM dim d WHERE d.dk IN "
     "(SELECT f.fk FROM fact f WHERE f.day < 2450100)"),
    ("case_over_encoded",
     "SELECT SUM(CASE WHEN price > 100000 THEN qty ELSE 0 END) s, "
     "MIN(day) md FROM fact"),
    ("group_by_encoded_key",
     "SELECT price, COUNT(*) c FROM fact GROUP BY price "
     "ORDER BY c DESC, price LIMIT 20"),
    ("arith_on_encoded",
     "SELECT SUM(price * qty) s, AVG(price) a FROM fact "
     "WHERE day BETWEEN 2450050 AND 2450150"),
]


@pytest.mark.parametrize("name,q", _SHAPES, ids=[s[0] for s in _SHAPES])
def test_shape_differentials(bench_shape, name, q):
    on = rows_of(_session(bench_shape, True).sql(q, backend="jax"))
    off = rows_of(_session(bench_shape, False).sql(q, backend="jax"))
    assert on == off, name


# ---------------------------------------------------------------------------
# verifier: encoding metadata legality ("encoding" findings)
# ---------------------------------------------------------------------------

def test_verifier_encoding_findings():
    from nds_tpu.engine.plan import ScanNode
    from nds_tpu.engine.verify import (check_scan_encodings, verify_plan)

    scan = ScanNode("__morsel__", ["a", "b"], lanes=("u8", "u16"),
                    encodings=(("dict", 100), ("rle", 40)),
                    out_names=["a", "b"], out_dtypes=["int", "int"])
    ok = check_scan_encodings(scan, {
        "a": {"distinct": np.arange(100), "runs": None},
        "b": {"distinct": None, "runs": 40}})
    assert ok == []
    # stats that do not cover the declared spec
    bad = check_scan_encodings(scan, {
        "a": {"distinct": np.arange(150), "runs": None},
        "b": {"distinct": None, "runs": 99}})
    assert len(bad) == 2 and all(f.kind == "encoding" for f in bad)
    # a spec with NO stats proving it is itself a finding
    unproven = check_scan_encodings(scan, {})
    assert len(unproven) == 2
    assert "no distinct-value stats" in unproven[0].message
    # static dtype/lane legality (verify_plan path): cardinality past the
    # code lane, dict on float, rle on the bit-packed bool lane
    illegal = ScanNode(
        "__morsel__", ["x", "y", "z"], lanes=("u8", "f64", "b1"),
        encodings=(("dict", 300), ("dict", 4), ("rle", 5)),
        out_names=["x", "y", "z"], out_dtypes=["int", "float", "bool"])
    findings = verify_plan(illegal)
    msgs = [f.message for f in findings if f.kind == "encoding"]
    assert any("overflows code lane" in m for m in msgs)
    assert any("illegal for dtype 'float'" in m for m in msgs)
    assert any("bit-packed bool lane" in m for m in msgs)


def test_verify_groups_rejects_lying_enc_stats(bench_shape):
    """Session-level: per-pass verification proves each group's encoding
    spec against the SAME stats source the planner used."""
    from nds_tpu.engine import streaming
    from nds_tpu.engine.verify import PlanVerifyError

    s = _session(bench_shape, True, verify_plans="per-pass")
    s.sql(Q_BENCH, backend="jax")
    ent = s._stream_cache[Q_BENCH]
    g = ent["groups"][0]
    assert g.encodings is not None
    shrunk = tuple(("dict", 2) if isinstance(e, tuple) and e[0] == "dict"
                   else e for e in g.encodings)
    streaming.set_group_encodings(g, shrunk, g.lanes, g.codebooks)
    with pytest.raises(PlanVerifyError) as exc:
        streaming.verify_groups(ent["groups"],
                                enc_stats=s.column_enc_stats)
    assert "encoded_exec" in str(exc.value)


# ---------------------------------------------------------------------------
# encoding-stats sources: arrow tables, parquet column reads, engine
# views, warehouse manifests
# ---------------------------------------------------------------------------

def test_enc_stats_sources(tmp_path):
    import decimal
    t = pa.table({
        "i": pa.array([5, 5, None, 900_000, 5], type=pa.int64()),
        "d": pa.array([10_957, 10_957, 10_958, 10_958, 10_958],
                      type=pa.date32()),
        "dec": pa.array([decimal.Decimal("1.25")] * 5,
                        type=pa.decimal128(10, 2)),
        "s": pa.array(["x"] * 5),
    })
    path = os.path.join(str(tmp_path), "t.parquet")
    pq.write_table(t, path)
    s = Session(EngineConfig(decimal_physical="i64"))
    s.register_arrow("mem", t)
    s.register_parquet("disk", path)
    for name in ("mem", "disk"):
        st = s.column_enc_stats(name, ["i", "d", "dec", "s"])
        assert list(st["i"]["distinct"]) == [5, 900_000]
        assert st["i"]["runs"] == 4          # 5,5,0(null),900000,5
        assert st["d"]["runs"] == 2
        assert list(st["dec"]["distinct"]) == [125]
        assert "s" not in st
    # re-registration invalidates the per-column cache
    s.register_arrow("mem", t.slice(0, 2))
    assert list(s.column_enc_stats("mem", ["i"])["i"]["distinct"]) == [5]
    # engine-view registrations compute from the materialized table
    view = s.sql("SELECT i FROM mem", backend="numpy")
    s.register_view("v", view)
    assert s.column_enc_stats("v", ["i"])["i"]["runs"] >= 1


def test_warehouse_manifest_enc_stats(tmp_path):
    from nds_tpu.warehouse import Warehouse

    wh = Warehouse(str(tmp_path))
    t1 = pa.table({"k": pa.array([7, 7, 7, 9], type=pa.int64()),
                   "hi": pa.array(np.arange(4) * 99991, type=pa.int64())})
    t2 = pa.table({"k": pa.array([9, 11], type=pa.int64()),
                   "hi": pa.array([5, 6], type=pa.int64())})
    wt = wh.table("demo")
    wt.create(t1, partition=False)
    wt.insert(t2, partition=False)
    rec = wt.enc_stats()
    assert len(rec) == 2
    agg = wt.column_enc_stats(wt.current_files())
    assert list(agg["k"]["distinct"]) == [7, 9, 11]
    assert agg["k"]["runs"] == 2 + 2     # per-file runs SUM (window bound)
    s = Session(EngineConfig(decimal_physical="i64"))
    wh.register_all(s)
    st = s.column_enc_stats("demo", ["k"])
    assert list(st["k"]["distinct"]) == [7, 9, 11]


# ---------------------------------------------------------------------------
# satellite: parquet dictionary pass-through (staging-thread hot loop)
# ---------------------------------------------------------------------------

def test_parquet_dictionary_passthrough(tmp_path):
    """String columns dictionary-encoded in the parquet chunks register
    with ParquetReadOptions(dictionary_columns=...): batches arrive as
    dictionary arrays and from_arrow_column passes codes through without
    re-running dictionary_encode()."""
    vals = [f"cat{i % 40}" for i in range(5000)]
    t = pa.table({"s": pa.array(vals),
                  "i": pa.array(np.arange(5000), type=pa.int64())})
    path = os.path.join(str(tmp_path), "dict.parquet")
    pq.write_table(t, path, use_dictionary=True, row_group_size=1024)
    assert arrow_bridge.parquet_dictionary_columns([path]) == ["s"]
    s = Session(EngineConfig())
    s.register_parquet("t", path)
    batch = next(iter(s._batch_sources["t"](["s"])))
    arr = batch.column(0) if hasattr(batch, "column") else batch["s"]
    assert pa.types.is_dictionary(
        arr.type if not isinstance(arr, pa.ChunkedArray) else arr.type)
    got = s.sql("SELECT s, COUNT(*) c FROM t GROUP BY s ORDER BY s",
                backend="jax")
    assert len(rows_of(got)) == 40
    # a column with dictionary disabled must NOT be forced through it
    path2 = os.path.join(str(tmp_path), "plain.parquet")
    pq.write_table(t, path2, use_dictionary=False)
    assert arrow_bridge.parquet_dictionary_columns([path2]) == []


# ---------------------------------------------------------------------------
# slow: whole-template-sweep on/off bit-identity (streamed tiny SF) and the
# SF0.01 SQLite-oracle slice (full CI test stage; tier-1 runs the fast
# differentials above)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def sweep_sessions(tmp_path_factory):
    """Tiny-SF sessions with the streaming threshold dropped so fact scans
    actually ride the packed (encoded) morsel path — the on/off pair for
    the full template sweep."""
    from nds_tpu import datagen
    from nds_tpu.power import setup_tables
    data = str(tmp_path_factory.mktemp("enc_sweep") / "d")
    datagen.generate_data_local(data, 0.001, parallel=2, overwrite=True)
    out = {}
    for encoded in (True, False):
        s = Session(EngineConfig(encoded_exec=encoded,
                                 out_of_core_min_rows=1000,
                                 chunk_rows=4096))
        setup_tables(s, data, "csv")
        out[encoded] = s
    return out


def _template_numbers():
    from nds_tpu import streams
    return streams.available_templates()


@pytest.mark.slow
@pytest.mark.parametrize("number", _template_numbers())
def test_template_sweep_on_off_identity(sweep_sessions, number):
    """EVERY bundled template, streamed, encoded on vs off: results must be
    BIT-IDENTICAL (same rows, same order) — the template-sweep complement
    of the fast shape differentials above. Each side runs twice and the
    COMPILED steady-state results compare: cross-session program adoption
    would otherwise pit one side's eager record pass against the other's
    compiled replay, whose float expressions differ by ULPs for reasons
    independent of encoding (pre-existing, q78-class round() columns)."""
    from nds_tpu import streams
    sql = streams.instantiate(number, stream=0, rngseed=31415)
    parts = (streams.split_special_query(f"query{number}", sql)
             if number in streams.SPECIAL_TEMPLATES
             else [(f"query{number}", sql)])
    for name, part_sql in parts:
        for s in (sweep_sessions[True], sweep_sessions[False]):
            s.sql(part_sql, backend="jax", label=name)   # record/compile
        on = rows_of(sweep_sessions[True].sql(part_sql, backend="jax",
                                              label=name))
        off = rows_of(sweep_sessions[False].sql(part_sql, backend="jax",
                                                label=name))
        assert on == off, f"{name}: encoded on/off differ"

@pytest.fixture(scope="module")
def nds_env(tmp_path_factory):
    from nds_tpu import datagen
    from nds_tpu.power import setup_tables
    from sqlite_oracle import load_database
    data = str(tmp_path_factory.mktemp("encoded_nds") / "d")
    datagen.generate_data_local(data, 0.01, parallel=4, overwrite=True)
    conn = load_database(data)

    def mk(encoded):
        # stream the fact scans at SF0.01 so the encoded packed path is
        # actually exercised (the bench A/B uses the same knobs)
        s = Session(EngineConfig(encoded_exec=encoded,
                                 out_of_core_min_rows=20_000,
                                 chunk_rows=1 << 15))
        setup_tables(s, data, "csv")
        return s
    return mk, conn


@pytest.mark.slow
@pytest.mark.parametrize("number", [9, 22, 67, 95])
def test_nds_query_encoded_sqlite_differential(nds_env, number):
    from nds_tpu import streams, validate
    from sqlite_oracle import normalize_rows, sort_rows, to_sqlite_sql
    mk, conn = nds_env
    sql = streams.instantiate(number, stream=0, rngseed=778)
    name = f"query{number}"
    expected = conn.execute(to_sqlite_sql(sql)).fetchall()
    rows = {}
    for label, encoded in (("off", False), ("on", True)):
        s = mk(encoded)
        t = s.sql(sql, backend="jax", label=name)
        at = arrow_bridge.to_arrow(t)
        rows[label] = [tuple(r[c] for c in at.column_names)
                       for r in at.to_pylist()]
        names = list(t.names)
    assert rows["on"] == rows["off"], f"{name}: encoded on/off differ"
    rows_e = sort_rows(normalize_rows(expected))
    rows_a = sort_rows(normalize_rows(rows["on"]))
    assert len(rows_e) == len(rows_a), name
    for re_, ra_ in zip(rows_e, rows_a):
        assert validate.row_equal(re_, ra_, name, names), \
            f"{name}: sqlite {re_} != engine {ra_}"
