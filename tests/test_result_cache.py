"""Semantic result cache (nds_tpu/engine/result_cache.py): exact-tier
hit/miss/invalidation semantics, the subsumption proof battery (accepts
AND adversarial rejects), incremental view maintenance from LF_*/DF_*
deltas, and the query-service wiring.

The contract under test is the cache's acceptance bar: every answer a
tier serves must be BIT-IDENTICAL to recomputing the same SQL on the
current data — through exact hits, re-filtered coarser aggregates, and
partials updated in place across maintenance rounds. Counters (not wall
times — this host's timing flakes) pin that repeat loads do zero planner
and device work.
"""
import os
import shutil
import subprocess
import sys
import time

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.config import EngineConfig
from nds_tpu.engine import ResultCache, ResultCacheConfig, Session
from nds_tpu.obs.metrics import METRICS

N_FACT = 20_000


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(11)
    fact = pa.table({
        "g": pa.array(rng.integers(0, 40, N_FACT), type=pa.int64()),
        "v": pa.array(rng.integers(1, 100, N_FACT), type=pa.int64()),
        "f": pa.array(np.round(rng.uniform(0, 10, N_FACT), 3)),
    })
    other = pa.table({"x": pa.array(np.arange(10), type=pa.int64())})
    return {"fact": fact, "other": other}


def make_session(data, **cfg_kw):
    s = Session(EngineConfig(**cfg_kw))
    s.register_arrow("fact", data["fact"])
    s.register_arrow("other", data["other"])
    return s


def cache_for(session, **kw) -> ResultCache:
    cache = ResultCache(session, ResultCacheConfig(**kw))
    session.attach_result_cache(cache)
    return cache


Q = ("SELECT g, COUNT(*) AS n, SUM(v) AS tv FROM fact "
     "WHERE g BETWEEN {a} AND {b} GROUP BY g ORDER BY g")


# -- exact tier ---------------------------------------------------------------

def test_exact_hit_is_bit_identical_and_counted(data):
    s = make_session(data)
    cache = cache_for(s)
    sql = Q.format(a=3, b=35)
    before = METRICS.snapshot()
    r1 = cache.run(sql)
    r2 = cache.run(sql)
    d = METRICS.delta(before)
    assert r2 is r1                       # one shared read-only Table
    assert d.get("result_cache_misses") == 1
    assert d.get("result_cache_hits") == 1
    assert r1.to_pylist() == make_session(data).sql(sql).to_pylist()


def test_ttl_expires_entries(data):
    s = make_session(data)
    cache = cache_for(s, ttl_s=0.2)
    sql = Q.format(a=5, b=30)
    cache.run(sql)
    assert len(cache) == 1
    time.sleep(0.5)
    before = METRICS.snapshot()
    cache.run(sql)
    d = METRICS.delta(before)
    assert d.get("result_cache_misses") == 1
    assert d.get("result_cache_invalidations") == 1


def test_generation_invalidation_on_reregister(data):
    s = make_session(data)
    cache = cache_for(s)                  # no IVM: stale entries drop
    sql = Q.format(a=5, b=30)
    cache.run(sql)
    s.register_arrow("fact", data["fact"])    # same data, new generation
    before = METRICS.snapshot()
    r = cache.run(sql)
    d = METRICS.delta(before)
    assert d.get("result_cache_misses") == 1
    assert d.get("result_cache_invalidations") == 1
    assert r.to_pylist() == make_session(data).sql(sql).to_pylist()


def test_per_table_generation_scopes_invalidation(data):
    """Satellite pin: re-registering an UNRELATED table must not evict a
    cached result over fact (the old single global counter did)."""
    s = make_session(data)
    cache = cache_for(s)
    sql = Q.format(a=5, b=30)
    cache.run(sql)
    gen_before = s.table_generation("fact")
    s.register_arrow("other", data["other"])
    assert s.table_generation("fact") == gen_before
    assert s.table_generation("other") == gen_before + 1
    before = METRICS.snapshot()
    cache.run(sql)
    d = METRICS.delta(before)
    assert d.get("result_cache_hits") == 1
    assert not d.get("result_cache_invalidations")


def test_capacity_lru_eviction(data):
    s = make_session(data)
    cache = cache_for(s, entries=2)
    texts = [Q.format(a=1 + i, b=38) for i in range(3)]
    for t in texts:
        cache.run(t)
    assert len(cache) == 2
    before = METRICS.snapshot()
    cache.run(texts[0])                   # oldest: evicted, re-executes
    cache.run(texts[2])                   # newest: still cached
    d = METRICS.delta(before)
    assert d.get("result_cache_misses") == 1
    assert d.get("result_cache_hits") == 1


def test_backend_keying_separates_jax_and_numpy(data):
    """A numpy-oracle result must never serve a jax query (hashes may
    differ across backends): entries key on the backend tag."""
    s = make_session(data)
    cache = cache_for(s)
    sql = Q.format(a=4, b=33)
    r_np = cache.run(sql, backend="numpy")
    before = METRICS.snapshot()
    r_jax = cache.run(sql, backend="jax")
    d = METRICS.delta(before)
    assert d.get("result_cache_misses") == 1      # no cross-backend hit
    assert len(cache) == 2
    assert r_np.to_pylist() == r_jax.to_pylist()  # same logical answer


# -- subsumption tier ---------------------------------------------------------

def subs_cache(data):
    s = make_session(data)
    return s, cache_for(s, subsumption=True)


def test_subsume_narrower_between_window(data):
    s, cache = subs_cache(data)
    cache.run(Q.format(a=2, b=38))            # the coarse entry
    narrow = Q.format(a=10, b=25)
    before = METRICS.snapshot()
    r = cache.run(narrow)
    d = METRICS.delta(before)
    assert d.get("result_cache_subsumption_hits") == 1
    assert not d.get("queries_run")           # no execution at all
    assert r.to_pylist() == make_session(data).sql(narrow).to_pylist()
    # the narrowed answer became its own exact entry
    before = METRICS.snapshot()
    cache.run(narrow)
    assert METRICS.delta(before).get("result_cache_hits") == 1


def test_subsume_inlist_subset(data):
    s, cache = subs_cache(data)
    tpl = ("SELECT g, COUNT(*) AS n, SUM(v) AS tv FROM fact "
           "WHERE g IN ({vals}) GROUP BY g ORDER BY g")
    cache.run(tpl.format(vals="3, 7, 11, 19, 23"))
    narrow = tpl.format(vals="7, 19")
    before = METRICS.snapshot()
    r = cache.run(narrow)
    assert METRICS.delta(before).get("result_cache_subsumption_hits") == 1
    assert r.to_pylist() == make_session(data).sql(narrow).to_pylist()


def _assert_no_subsume(data, cache, wide_sql, narrow_sql):
    cache.run(wide_sql)
    before = METRICS.snapshot()
    r = cache.run(narrow_sql)
    d = METRICS.delta(before)
    assert not d.get("result_cache_subsumption_hits"), (wide_sql,
                                                        narrow_sql)
    assert d.get("result_cache_misses") == 1
    assert r.to_pylist() == make_session(data).sql(narrow_sql).to_pylist()


def test_reject_filter_not_over_group_key(data):
    """WHERE over a non-group column: per-group inputs differ, so the
    cached aggregate rows cannot be re-filtered into the answer."""
    s, cache = subs_cache(data)
    tpl = ("SELECT g, COUNT(*) AS n, SUM(v) AS tv FROM fact "
           "WHERE v BETWEEN {a} AND {b} GROUP BY g ORDER BY g")
    _assert_no_subsume(data, cache, tpl.format(a=1, b=90),
                       tpl.format(a=10, b=50))


def test_reject_non_mergeable_aggregate(data):
    s, cache = subs_cache(data)
    tpl = ("SELECT g, STDDEV_SAMP(f) AS sd FROM fact "
           "WHERE g BETWEEN {a} AND {b} GROUP BY g ORDER BY g")
    _assert_no_subsume(data, cache, tpl.format(a=2, b=38),
                       tpl.format(a=10, b=25))


def test_reject_or_widened_predicate(data):
    """A parameter under OR is opaque: the conjunct decomposition only
    splits AND, so the slot never gets a containment direction."""
    s, cache = subs_cache(data)
    tpl = ("SELECT g, COUNT(*) AS n FROM fact "
           "WHERE g <= {b} OR v > 95 GROUP BY g ORDER BY g")
    _assert_no_subsume(data, cache, tpl.format(b=38), tpl.format(b=20))


def test_reject_widened_window(data):
    s, cache = subs_cache(data)
    _assert_no_subsume(data, cache, Q.format(a=10, b=25),
                       Q.format(a=2, b=38))


def test_reject_limit_above_aggregate(data):
    """LIMIT truncated the cached groups; the narrower query may need a
    group the cached result dropped."""
    s, cache = subs_cache(data)
    tpl = ("SELECT g, COUNT(*) AS n FROM fact WHERE g >= {a} "
           "GROUP BY g ORDER BY g LIMIT 5")
    _assert_no_subsume(data, cache, tpl.format(a=2), tpl.format(a=10))


def test_reject_moved_point_equality(data):
    s, cache = subs_cache(data)
    tpl = ("SELECT g, COUNT(*) AS n FROM fact WHERE g = {a} "
           "GROUP BY g ORDER BY g")
    _assert_no_subsume(data, cache, tpl.format(a=5), tpl.format(a=6))


# -- incremental view maintenance (synthetic) ---------------------------------

def _warehouse_session(tmp_path, data, **cache_kw):
    from nds_tpu.warehouse import Warehouse

    wh = Warehouse(str(tmp_path / "wh"))
    wh.table("fact").create(data["fact"], partition=False)
    s = Session(EngineConfig())
    s.attach_warehouse(wh)
    s.register_arrow("stage", pa.table({
        "sg": pa.array(np.arange(30, dtype=np.int64) % 40),
        "sv": pa.array((np.arange(30, dtype=np.int64) * 7) % 90 + 1),
        "sf": pa.array(np.round(np.linspace(0, 5, 30), 3)),
    }))
    return s, cache_for(s, **cache_kw), wh


AGG = ("SELECT g, COUNT(*) AS n, SUM(v) AS tv, MIN(v) AS mv FROM fact "
       "GROUP BY g ORDER BY g")


def _cold(wh):
    s = Session(EngineConfig())
    s.attach_warehouse(wh)
    return s


def test_ivm_insert_merges_partials(tmp_path, data):
    s, cache, wh = _warehouse_session(tmp_path, data, ivm=True)
    cache.run(AGG)
    before = METRICS.snapshot()
    s.execute("INSERT INTO fact SELECT sg, sv, sf FROM stage")
    d = METRICS.delta(before)
    assert d.get("result_cache_ivm_updates") == 1
    assert not d.get("result_cache_invalidations")
    before = METRICS.snapshot()
    served = cache.run(AGG)
    assert METRICS.delta(before).get("result_cache_hits") == 1
    assert served.to_pylist() == _cold(wh).sql(AGG).to_pylist()


def test_ivm_delete_recomputes_touched_groups(tmp_path, data):
    s, cache, wh = _warehouse_session(tmp_path, data, ivm=True)
    cache.run(AGG)
    before = METRICS.snapshot()
    s.execute("DELETE FROM fact WHERE v < 40 AND g IN (3, 9, 17)")
    d = METRICS.delta(before)
    assert d.get("result_cache_ivm_updates") == 1
    served = cache.run(AGG)
    assert served.to_pylist() == _cold(wh).sql(AGG).to_pylist()


def test_float_sum_entry_invalidates_instead_of_merging(tmp_path, data):
    """f64 sums do not re-associate bit-stably, so a float-sum aggregate
    is IVM-ineligible: the delta invalidates it and the next load
    recomputes (still correct, just cold)."""
    s, cache, wh = _warehouse_session(tmp_path, data, ivm=True)
    sql = ("SELECT g, SUM(f) AS tf FROM fact GROUP BY g ORDER BY g")
    cache.run(sql)
    before = METRICS.snapshot()
    s.execute("INSERT INTO fact SELECT sg, sv, sf FROM stage")
    d = METRICS.delta(before)
    assert not d.get("result_cache_ivm_updates")
    assert d.get("result_cache_invalidations") == 1
    before = METRICS.snapshot()
    served = cache.run(sql)
    assert METRICS.delta(before).get("result_cache_misses") == 1
    assert served.to_pylist() == _cold(wh).sql(sql).to_pylist()


# -- query-service wiring -----------------------------------------------------

def test_service_admission_hit_does_zero_planner_device_work(data):
    from nds_tpu.service import QueryService, ServiceConfig

    s = make_session(data)
    sql = Q.format(a=5, b=30)
    want = make_session(data).sql(sql).to_pylist()
    cfg = ServiceConfig(result_cache=ResultCacheConfig())
    with QueryService(s, cfg) as svc:
        t1 = svc.submit(sql, label="cold")
        assert t1.result(60).to_pylist() == want
        before = METRICS.snapshot()
        h_before = METRICS.histograms()
        t2 = svc.submit(sql, label="warm")
        r2 = t2.result(60)
        d = METRICS.delta(before)
        h_after = METRICS.histograms()
    assert t2.stats.mode == "cached"
    assert r2.to_pylist() == want
    assert d.get("result_cache_hits") == 1
    # ZERO planner/device work, pinned by counters (not wall time):
    # no session execution, no compile, no batch, no plan-stage sample
    assert not d.get("queries_run")
    assert not d.get("compiles")
    assert not d.get("service_batches")
    plan_n = h_after.get("service_plan_ms", {}).get("count", 0) - \
        h_before.get("service_plan_ms", {}).get("count", 0)
    assert plan_n == 0


def test_service_subsumption_and_engine_flag_wiring(data):
    """EngineConfig.result_cache arms the service cache when the
    ServiceConfig leaves it unset; narrower windows serve subsumed."""
    from nds_tpu.service import QueryService, ServiceConfig

    s = make_session(data, result_cache=True,
                     result_cache_subsumption=True)
    narrow = Q.format(a=12, b=22)
    want = make_session(data).sql(narrow).to_pylist()
    with QueryService(s, ServiceConfig()) as svc:
        assert svc.result_cache is not None
        svc.sql(Q.format(a=2, b=38), label="coarse")
        before = METRICS.snapshot()
        t = svc.submit(narrow, label="narrow")
        r = t.result(60)
        d = METRICS.delta(before)
    assert t.stats.mode == "cached_subsumed"
    assert d.get("result_cache_subsumption_hits") == 1
    assert not d.get("queries_run")
    assert r.to_pylist() == want


def test_service_batched_members_store_and_rehit(data):
    from nds_tpu.service import QueryService, ServiceConfig

    s = make_session(data)
    cfg = ServiceConfig(result_cache=ResultCacheConfig(), max_batch=8)
    texts = [Q.format(a=6 + i, b=31 + i) for i in range(3)]
    with QueryService(s, cfg) as svc:
        svc.sql(texts[0], label="w")      # record + publish the program
        svc.sql(texts[0], label="w2")     # (second run compiles)
        with svc.hold_dispatch():
            tickets = [svc.submit(t, label=f"b{i}")
                       for i, t in enumerate(texts[1:])]
            t0 = time.time()
            while time.time() - t0 < 30:
                with svc._cv:
                    if len(svc._ready) >= len(tickets):
                        break
                time.sleep(0.01)
        for t in tickets:
            t.result(60)
        # repeats of the batched members hit at admission
        before = METRICS.snapshot()
        for i, text in enumerate(texts[1:]):
            t = svc.submit(text, label=f"r{i}")
            assert t.result(60) is not None
            assert t.stats.mode == "cached"
        d = METRICS.delta(before)
    assert d.get("result_cache_hits") == len(texts) - 1
    assert not d.get("queries_run")


# -- LF_*/DF_* differential suite (SF0.001 warehouse) -------------------------

#: int-only aggregate probes (order-safe partials: IVM-eligible even on
#: a float-decimal warehouse) — one per maintenance-touched fact table
PROBES = {
    "store_sales": ("SELECT ss_store_sk, COUNT(*) AS n, "
                    "SUM(ss_quantity) AS q FROM store_sales "
                    "GROUP BY ss_store_sk ORDER BY ss_store_sk"),
    "store_returns": ("SELECT sr_store_sk, COUNT(*) AS n, "
                      "SUM(sr_return_quantity) AS q FROM store_returns "
                      "GROUP BY sr_store_sk ORDER BY sr_store_sk"),
    "catalog_sales": ("SELECT cs_call_center_sk, COUNT(*) AS n, "
                      "SUM(cs_quantity) AS q FROM catalog_sales "
                      "GROUP BY cs_call_center_sk "
                      "ORDER BY cs_call_center_sk"),
    "catalog_returns": ("SELECT cr_call_center_sk, COUNT(*) AS n, "
                        "SUM(cr_return_quantity) AS q "
                        "FROM catalog_returns GROUP BY cr_call_center_sk "
                        "ORDER BY cr_call_center_sk"),
    "web_sales": ("SELECT ws_web_site_sk, COUNT(*) AS n, "
                  "SUM(ws_quantity) AS q FROM web_sales "
                  "GROUP BY ws_web_site_sk ORDER BY ws_web_site_sk"),
    "web_returns": ("SELECT wr_web_page_sk, COUNT(*) AS n, "
                    "SUM(wr_return_quantity) AS q FROM web_returns "
                    "GROUP BY wr_web_page_sk ORDER BY wr_web_page_sk"),
    "inventory": ("SELECT inv_warehouse_sk, COUNT(*) AS n, "
                  "SUM(inv_quantity_on_hand) AS q FROM inventory "
                  "GROUP BY inv_warehouse_sk ORDER BY inv_warehouse_sk"),
}


@pytest.fixture(scope="module")
def maint_env(tmp_path_factory):
    """SF0.001 base data + the smallest update set that carries staging
    rows (SF0.01), transcoded once into a pristine warehouse template —
    each test copies it so maintenance rounds stay isolated."""
    from nds_tpu.transcode import transcode

    root = tmp_path_factory.mktemp("rcache_maint")
    base = str(root / "base")
    upd = str(root / "upd")
    subprocess.run([sys.executable, "-m", "nds_tpu.datagen", "local", base,
                    "--scale", "0.001", "--parallel", "1"], check=True,
                   timeout=600)
    subprocess.run([sys.executable, "-m", "nds_tpu.datagen", "local", upd,
                    "--scale", "0.01", "--parallel", "1", "--update", "1"],
                   check=True, timeout=600)
    pristine = str(root / "wh_pristine")
    transcode(base, pristine)
    return {"upd": upd, "pristine": pristine}


def _run_ivm_differential(maint_env, tmp_path, funcs, probe_tables):
    """Prime cached probe entries, run the maintenance functions through
    the SAME session (deltas publish into the cache), then assert every
    probe serves from cache AND hashes identical to a cold session over
    the post-maintenance warehouse."""
    from nds_tpu.maintenance import run_maintenance
    from nds_tpu.warehouse import Warehouse

    wh_dir = str(tmp_path / "wh")
    shutil.copytree(maint_env["pristine"], wh_dir)
    s = Session(EngineConfig())
    s.attach_warehouse(Warehouse(wh_dir))
    cache = cache_for(s, ivm=True)
    for t in probe_tables:
        cache.run(PROBES[t])
    before = METRICS.snapshot()
    run_maintenance(wh_dir, maint_env["upd"], str(tmp_path / "maint.csv"),
                    maintenance_queries=list(funcs), session=s)
    delta = METRICS.delta(before)
    assert delta.get("result_cache_ivm_updates", 0) > 0, delta
    for t in probe_tables:
        before = METRICS.snapshot()
        served = cache.run(PROBES[t])
        d = METRICS.delta(before)
        assert d.get("result_cache_hits") == 1, (t, d)
        cold = Session(EngineConfig())
        cold.attach_warehouse(Warehouse(wh_dir))
        want = cold.sql(PROBES[t]).to_pylist()
        assert served.to_pylist() == want, \
            f"{t}: cached-updated != cold recompute after {funcs}"
    return delta


def test_ivm_differential_fast_slice(maint_env, tmp_path):
    """Tier-1 slice: one fact insert (LF_SS), one paired delete (DF_SS),
    one inventory insert (LF_I). catalog_sales rides along UNTOUCHED to
    pin per-table generation scope at warehouse grain: three maintenance
    functions over other tables must leave its entry hot."""
    delta = _run_ivm_differential(
        maint_env, tmp_path, ["LF_SS", "DF_SS", "LF_I"],
        ["store_sales", "store_returns", "inventory", "catalog_sales"])
    # LF_SS:1 + DF_SS: 3 date tuples x (returns, sales) + LF_I:1
    assert delta.get("result_cache_ivm_updates", 0) >= 3
    assert not delta.get("result_cache_invalidations")


@pytest.mark.slow
def test_ivm_differential_full_sweep(maint_env, tmp_path):
    from nds_tpu.maintenance import MAINTENANCE_FUNCS

    _run_ivm_differential(maint_env, tmp_path, MAINTENANCE_FUNCS,
                          list(PROBES))
