"""Distributed primitive tests on a virtual 8-device CPU mesh.

The conftest forces xla_force_host_platform_device_count=8 so these SPMD
programs compile and execute the same collectives they would use across a
real TPU slice (SURVEY.md §4 notes the reference cannot test multi-node
without a cluster; we can).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nds_tpu.parallel import (broadcast_join_aggregate, distributed_aggregate,
                              make_mesh, repartition_by_key, shard_rows)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def test_shard_rows_pads_and_shards(mesh):
    vals = jnp.arange(10, dtype=jnp.int32)
    alive = jnp.ones(10, bool)
    (svals,), salive = shard_rows([vals], alive, mesh)
    assert svals.shape[0] % 8 == 0
    assert int(jnp.sum(salive)) == 10
    assert svals.sharding.spec == jax.sharding.PartitionSpec("shards")


def test_repartition_by_key(mesh):
    rng = np.random.default_rng(0)
    n = 512
    key = jnp.asarray(rng.integers(0, 1000, n).astype(np.int32))
    val = jnp.asarray(rng.normal(size=n).astype(np.float32))
    alive = jnp.asarray(rng.random(n) < 0.9)
    (skey, sval), salive = shard_rows([key, val], alive, mesh)
    # reuse skey as both column and routing key
    fn = jax.jit(repartition_by_key(mesh, per_pair_capacity=64))
    (out_key_col, out_val), out_alive, out_key, overflow = fn(
        [skey, sval], salive, skey)
    assert int(overflow) == 0
    # no rows lost, values travel with their keys
    in_rows = sorted(zip(np.asarray(skey)[np.asarray(salive)].tolist(),
                         np.round(np.asarray(sval)[np.asarray(salive)], 5).tolist()))
    out_mask = np.asarray(out_alive)
    out_rows = sorted(zip(np.asarray(out_key_col)[out_mask].tolist(),
                          np.round(np.asarray(out_val)[out_mask], 5).tolist()))
    assert in_rows == out_rows
    # every key now lives on exactly one shard
    ok = np.asarray(out_key)
    keys_per_shard = ok.reshape(8, -1)
    mask_per_shard = out_mask.reshape(8, -1)
    seen = {}
    for s in range(8):
        for k in np.unique(keys_per_shard[s][mask_per_shard[s]]):
            assert seen.setdefault(int(k), s) == s


def test_distributed_aggregate_matches_host(mesh):
    rng = np.random.default_rng(1)
    n = 1024
    key = rng.integers(0, 37, n).astype(np.int32)
    val = rng.integers(1, 100, n).astype(np.float32)
    alive_h = rng.random(n) < 0.95
    (skey, sval), salive = shard_rows(
        [jnp.asarray(key), jnp.asarray(val)], jnp.asarray(alive_h), mesh)
    fn = jax.jit(distributed_aggregate(mesh, n_partial=64,
                                       specs=["sum", "count"]))
    out_keys, out_valid, (sums, counts), out_alive, overflow = fn(
        skey, jnp.ones_like(salive), salive, [sval, sval])
    assert int(overflow) == 0
    mask = np.asarray(out_alive)
    got = {int(k): (float(s), int(c))
           for k, s, c in zip(np.asarray(out_keys)[mask],
                              np.asarray(sums)[mask],
                              np.asarray(counts)[mask])}
    want = {}
    for k, v, a in zip(key, val, alive_h):
        if a:
            s, c = want.get(int(k), (0.0, 0))
            want[int(k)] = (s + float(v), c + 1)
    assert set(got) == set(want)
    for k in want:
        assert got[k][1] == want[k][1]
        assert got[k][0] == pytest.approx(want[k][0], rel=1e-5)


def test_broadcast_join_aggregate_matches_host(mesh):
    rng = np.random.default_rng(2)
    n, nd = 2048, 50
    fact_key = rng.integers(0, nd + 10, n).astype(np.int32)   # some dangling
    fact_val = rng.integers(1, 10, n).astype(np.float32)
    fmask_h = rng.random(n) < 0.7
    dim_key = np.arange(nd, dtype=np.int32)
    dim_group = (dim_key % 5).astype(np.int32)
    (sfk, sfv, sfm), salive = shard_rows(
        [jnp.asarray(fact_key), jnp.asarray(fact_val),
         jnp.asarray(fmask_h)], jnp.ones(n, bool), mesh)
    fn = jax.jit(broadcast_join_aggregate(mesh, n_partial=32,
                                          specs=["sum", "count"]))
    out_keys, (sums, counts), out_alive, overflow = fn(
        sfk, sfm.astype(bool), salive, [sfv, sfv],
        jnp.asarray(dim_key), jnp.asarray(dim_group), jnp.ones(nd, bool))
    assert int(overflow) == 0
    mask = np.asarray(out_alive)
    got = {int(k): (float(s), int(c))
           for k, s, c in zip(np.asarray(out_keys)[mask],
                              np.asarray(sums)[mask], np.asarray(counts)[mask])}
    want = {}
    dim_lookup = {int(k): int(g) for k, g in zip(dim_key, dim_group)}
    for k, v, m in zip(fact_key, fact_val, fmask_h):
        if m and int(k) in dim_lookup:
            g = dim_lookup[int(k)]
            s, c = want.get(g, (0.0, 0))
            want[g] = (s + float(v), c + 1)
    assert got.keys() == want.keys()
    for g in want:
        assert got[g][1] == want[g][1]
        assert got[g][0] == pytest.approx(want[g][0], rel=1e-5)


def test_distributed_aggregate_multi_key_minmax(mesh):
    """Composite GROUP BY + min/max through the collective aggregate."""
    rng = np.random.default_rng(7)
    n = 1024
    k1 = rng.integers(0, 7, n).astype(np.int32)
    k2 = rng.integers(0, 5, n).astype(np.int32)
    val = rng.integers(1, 1000, n).astype(np.int32)
    (sk1, sk2, sval), salive = shard_rows(
        [jnp.asarray(k1), jnp.asarray(k2), jnp.asarray(val)],
        jnp.ones(n, bool), mesh)
    ones = jnp.ones_like(salive)
    fn = jax.jit(distributed_aggregate(mesh, n_partial=64,
                                       specs=["min", "max", "sum"]))
    out_keys, out_valids, (mins, maxs, sums), out_alive, overflow = fn(
        [sk1, sk2], [ones, ones], salive, [sval, sval, sval])
    assert int(overflow) == 0
    mask = np.asarray(out_alive)
    got = {(int(a), int(b)): (int(m), int(x), int(s))
           for a, b, m, x, s in zip(np.asarray(out_keys[0])[mask],
                                    np.asarray(out_keys[1])[mask],
                                    np.asarray(mins)[mask],
                                    np.asarray(maxs)[mask],
                                    np.asarray(sums)[mask])}
    want = {}
    for a, b, v in zip(k1, k2, val):
        m, x, s = want.get((int(a), int(b)), (10**9, -10**9, 0))
        want[(int(a), int(b))] = (min(m, int(v)), max(x, int(v)), s + int(v))
    assert got == want


def test_distributed_aggregate_null_first_key(mesh):
    """Round-2 advisor (dist_ops): a group whose FIRST GROUP BY key is NULL
    must survive — slot occupancy comes from alive rows, not from the first
    key's validity."""
    n = 64
    k1 = np.arange(n, dtype=np.int32) % 3
    k1_valid = (np.arange(n) % 3) != 0           # k1 NULL for group 0
    k2 = np.full(n, 9, np.int32)
    val = np.ones(n, np.float32)
    (sk1, sk2, sv1, sval), salive = shard_rows(
        [jnp.asarray(k1), jnp.asarray(k2), jnp.asarray(k1_valid),
         jnp.asarray(val)], jnp.ones(n, bool), mesh)
    fn = jax.jit(distributed_aggregate(mesh, n_partial=32, specs=["sum"]))
    out_keys, out_valids, (sums,), out_alive, overflow = fn(
        [sk1, sk2], [sv1, jnp.ones_like(salive)], salive, [sval])
    assert int(overflow) == 0
    mask = np.asarray(out_alive)
    # three groups: (NULL,9), (1,9), (2,9) — the NULL-first-key group has
    # ceil(64/3) rows and must not be dropped
    assert int(mask.sum()) == 3
    v1 = np.asarray(out_valids[0])[mask]
    k1o = np.asarray(out_keys[0])[mask]
    got = {None if not v else int(k): float(s)
           for v, k, s in zip(v1, k1o, np.asarray(sums)[mask])}
    assert got == {None: 22.0, 1: 21.0, 2: 21.0}


def test_repartition_composite_key(mesh):
    rng = np.random.default_rng(9)
    n = 512
    k1 = jnp.asarray(rng.integers(0, 50, n).astype(np.int32))
    k2 = jnp.asarray(rng.integers(0, 3, n).astype(np.int32))
    val = jnp.asarray(rng.normal(size=n).astype(np.float32))
    (sk1, sk2, sval), salive = shard_rows([k1, k2, val],
                                          jnp.ones(n, bool), mesh)
    fn = jax.jit(repartition_by_key(mesh, per_pair_capacity=96))
    (ok1, ok2, oval), out_alive, out_key, overflow = fn(
        [sk1, sk2, sval], salive, [sk1, sk2])
    assert int(overflow) == 0
    mask = np.asarray(out_alive)
    # no rows lost; every composite key lands on exactly one shard
    in_rows = sorted(zip(np.asarray(sk1)[np.asarray(salive)].tolist(),
                         np.asarray(sk2)[np.asarray(salive)].tolist()))
    out_rows = sorted(zip(np.asarray(ok1)[mask].tolist(),
                          np.asarray(ok2)[mask].tolist()))
    assert in_rows == out_rows
    pair_shard = {}
    k1s = np.asarray(ok1).reshape(8, -1)
    k2s = np.asarray(ok2).reshape(8, -1)
    ms = mask.reshape(8, -1)
    for s in range(8):
        for a, b in zip(k1s[s][ms[s]], k2s[s][ms[s]]):
            assert pair_shard.setdefault((int(a), int(b)), s) == s


# -- power-run subset over the mesh ------------------------------------------
# Real NDS templates executed through Session.sql with mesh_shape=(8,):
# GSPMD row-shards the fact scans and inserts the collectives, and the result
# must pass the validator against the single-device numpy oracle (the role
# Spark's executor-distributed execution plays in the reference,
# nds/base.template executor topology + nds/nds_validate.py).

# star join+agg shapes with fact-table scans, plus the fact-fact join
# spread (q64/q78/q95 class) the round-2 verdict flagged as never mesh-run
MESH_POWER_SUBSET = (3, 52, 55, 78, 95)


@pytest.fixture(scope="module")
def mesh_session(tmp_path_factory):
    from nds_tpu import datagen
    from nds_tpu.config import EngineConfig
    from nds_tpu.engine import Session
    from nds_tpu.power import setup_tables

    data = str(tmp_path_factory.mktemp("mesh_data") / "d")
    datagen.generate_data_local(data, 0.001, parallel=2, overwrite=True)
    # shard_min_rows lowered so toy-SF fact tables exercise real sharding
    spmd = Session(EngineConfig(mesh_shape=(8,), shard_min_rows=1024))
    setup_tables(spmd, data, "csv")
    oracle = Session(EngineConfig())
    setup_tables(oracle, data, "csv")
    return spmd, oracle


@pytest.mark.slow  # minutes of 8-virtual-device GSPMD compiles on CPU
@pytest.mark.parametrize("number", MESH_POWER_SUBSET)
def test_power_subset_on_mesh_passes_validator(mesh_session, number):
    from nds_tpu import streams, validate

    from test_templates import _rows   # shared row-normalization policy

    spmd, oracle_s = mesh_session
    name = f"query{number}"
    sql = streams.instantiate(number, stream=0, rngseed=31415)
    expected = oracle_s.sql(sql, backend="numpy")
    spmd.sql(sql, backend="jax")            # record pass
    actual = spmd.sql(sql, backend="jax")   # compiled SPMD replay
    assert spmd.last_fallbacks == [], spmd.last_fallbacks
    assert spmd.last_exec_stats.get("mode") in ("compiled", "compile+run")

    rows_e, names = _rows(expected)
    rows_a, _ = _rows(actual)
    assert len(rows_e) == len(rows_a)
    for re_, ra_ in zip(rows_e, rows_a):
        assert validate.row_equal(re_, ra_, name, names), f"{re_} != {ra_}"

    # the fact scan must actually be sharded over the mesh axis
    ex = spmd._jax_exec
    sharded = [k for k, dt in ex._scan_cache.items()
               if getattr(dt.cols[0].data.sharding, "spec", None)
               and dt.cols[0].data.sharding.spec[0] == "shards"]
    assert sharded, "no scan was row-sharded over the mesh"
