"""Resilience layer tests: retry policies, deadlines, the fault-injection
registry, supervised throughput (restart + kill + partial elapsed), power
--resume, and the per-query deadline killing a hung device call.

These are the ISSUE-1 acceptance demos: a stream configured to crash via
the fault registry completes after a restart; an interrupted power run
resumes without re-running completed queries; a hung ``jax.execute``
fault point is killed by the per-query deadline and recorded as Failed.
All fast and CPU-only (tiny hand-built parquet inputs, no datagen).
"""
import csv
import glob
import json
import os
import subprocess
import sys
import time

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from nds_tpu.config import EngineConfig
from nds_tpu.power import _write_time_log, run_query_stream
from nds_tpu.report import BenchReport
from nds_tpu.resilience import (Deadline, DeadlineExceeded, FAULTS,
                                FaultError, FaultSpec, RetryPolicy,
                                TransientError, run_with_deadline)
from nds_tpu.throughput import (IncompleteStreamLog, ThroughputError,
                                run_throughput, scrape_log,
                                status_csv_path, supervise_processes,
                                throughput_elapsed)


@pytest.fixture(autouse=True)
def _clean_registry():
    FAULTS.clear()
    yield
    FAULTS.clear()


# -- retry policy -------------------------------------------------------------

def test_backoff_schedule_deterministic():
    p = RetryPolicy(backoff_s=0.1, backoff_factor=2.0, max_backoff_s=0.35)
    assert p.backoff(1) == pytest.approx(0.1)
    assert p.backoff(2) == pytest.approx(0.2)
    assert p.backoff(3) == pytest.approx(0.35)   # capped


def test_retry_transient_then_succeeds():
    calls, sleeps = [], []
    p = RetryPolicy(max_attempts=3, backoff_s=0.1)

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TransientError("hiccup")
        return "ok"

    assert p.call(flaky, sleep=sleeps.append) == "ok"
    assert len(calls) == 3
    assert sleeps == [pytest.approx(0.1), pytest.approx(0.2)]


def test_retry_fatal_not_retried():
    p = RetryPolicy(max_attempts=5, backoff_s=0.001)
    calls = []

    def doomed():
        calls.append(1)
        raise DeadlineExceeded("budget blown")

    with pytest.raises(DeadlineExceeded):
        p.call(doomed, sleep=lambda s: None)
    assert len(calls) == 1
    assert p.classify(DeadlineExceeded("x")) == "fatal"
    assert p.classify(TransientError("x")) == "transient"
    assert p.classify(FaultError("x")) == "transient"


def test_retry_exhausts_attempts():
    p = RetryPolicy(max_attempts=2, backoff_s=0.001)
    calls = []

    def always():
        calls.append(1)
        raise TransientError("nope")

    with pytest.raises(TransientError):
        p.call(always, sleep=lambda s: None)
    assert len(calls) == 2


# -- deadlines ----------------------------------------------------------------

def test_deadline_expiry():
    now = [0.0]
    d = Deadline(1.0, clock=lambda: now[0])
    assert not d.expired() and d.remaining() == pytest.approx(1.0)
    now[0] = 1.5
    assert d.expired()
    with pytest.raises(DeadlineExceeded):
        d.check("query42")
    assert Deadline(None).remaining() is None
    assert not Deadline(0).expired()     # 0 = unbounded


def test_run_with_deadline_passthrough_and_timeout():
    assert run_with_deadline(lambda x: x + 1, None, 41) == 42
    assert run_with_deadline(lambda: "fast", 5.0) == "fast"
    with pytest.raises(ValueError):      # worker errors re-raise in caller
        run_with_deadline(lambda: (_ for _ in ()).throw(ValueError("x")), 5.0)
    t0 = time.monotonic()
    with pytest.raises(DeadlineExceeded):
        run_with_deadline(time.sleep, 0.2, 5.0, label="hung query")
    assert time.monotonic() - t0 < 3.0   # did not wait the full sleep


# -- fault registry -----------------------------------------------------------

def test_fault_spec_grammar():
    s = FaultSpec.parse("jax.execute:hang:5#1")
    assert (s.point, s.action, s.seconds, s.times) == \
        ("jax.execute", "hang", 5.0, 1)
    s = FaultSpec.parse("device.put:delay:0.2@0.5")
    assert (s.action, s.seconds, s.probability) == ("delay", 0.2, 0.5)
    s = FaultSpec.parse("query.run:raise/query1")
    assert (s.action, s.match) == ("raise", "query1")
    assert FaultSpec.parse("arrow.read").action == "raise"
    with pytest.raises(ValueError):
        FaultSpec.parse("warp.core:raise")
    with pytest.raises(ValueError):
        FaultSpec.parse("arrow.read:explode")


def test_registry_fire_semantics():
    FAULTS.arm("arrow.read:raise#1")
    with pytest.raises(FaultError):
        FAULTS.fire("arrow.read")
    FAULTS.fire("arrow.read")                    # times=1: exhausted
    FAULTS.fire("device.put")                    # other points unaffected

    spec = FAULTS.arm("query.run:raise/query5")
    FAULTS.fire("query.run", "query7")           # match gates on detail
    with pytest.raises(FaultError):
        FAULTS.fire("query.run", "query5_part2", aliases=("query5",))
    assert FAULTS.would_raise("query.run", "query5")
    assert not FAULTS.would_raise("query.run", "query7")
    FAULTS.disarm(spec)
    FAULTS.fire("query.run", "query5")           # disarmed

    FAULTS.arm("stream.spawn:raise@0.0")         # p=0 never fires
    FAULTS.fire("stream.spawn")

    t0 = time.monotonic()
    FAULTS.arm("jax.compile:delay:0.05")
    FAULTS.fire("jax.compile")
    assert time.monotonic() - t0 >= 0.05


def test_registry_configure_replaces_config_batch():
    manual = FAULTS.arm("arrow.read:raise")
    FAULTS.configure(["device.put:raise"])
    FAULTS.configure(["jax.execute:raise"])      # replaces the config batch
    points = sorted(s.point for s in FAULTS.specs())
    assert points == ["arrow.read", "jax.execute"]
    FAULTS.disarm(manual)


def test_config_fault_points_via_property_file(tmp_path):
    prop = tmp_path / "engine.properties"
    prop.write_text(
        "nds.tpu.fault_points=arrow.read:raise#1\n"
        "nds.tpu.query_timeout_s=1.5\n"
        "nds.tpu.query_attempts=2\n"
        "nds.tpu.stream_attempts=3\n"
        "nds.tpu.use_jax=false\n")
    cfg = EngineConfig.from_property_file(str(prop))
    assert cfg.fault_points == ("arrow.read:raise#1",)
    assert cfg.query_timeout_s == pytest.approx(1.5)
    assert cfg.query_attempts == 2
    assert cfg.stream_attempts == 3

    from nds_tpu.engine import Session
    session = Session(cfg)                       # arms the registry
    session.register_arrow("t", pa.table({"a": [1, 2, 3]}))
    with pytest.raises(FaultError, match="arrow.read"):
        session.sql("SELECT COUNT(*) AS c FROM t")
    out = session.sql("SELECT COUNT(*) AS c FROM t")   # spec exhausted
    assert out.num_rows == 1


# -- per-attempt report records ----------------------------------------------

def test_report_records_attempts_and_retried_status():
    r = BenchReport({}, app_name="t")
    r.report_on(lambda: 42)
    assert r.summary["attempts"] == [1]
    assert r.summary["retriedStatus"] == [["Completed"]]

    r2 = BenchReport({}, app_name="t")
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] == 1:
            raise TransientError("transient wobble")
        return "ok"

    out = r2.report_on(flaky, retry=RetryPolicy(max_attempts=3,
                                                backoff_s=0.001))
    assert out == "ok"
    assert r2.summary["attempts"] == [2]
    assert r2.summary["retriedStatus"] == [["Failed", "Completed"]]
    # a retried success is not a clean Completed
    assert r2.finalize_status() == "CompletedWithTaskFailures"
    assert any("transient wobble" in e for e in r2.summary["exceptions"])

    def always_fails():
        raise TransientError("always")

    r3 = BenchReport({}, app_name="t")
    r3.report_on(always_fails, retry=RetryPolicy(max_attempts=2,
                                                 backoff_s=0.001))
    assert r3.summary["queryStatus"] == ["Failed"]
    assert r3.summary["retriedStatus"] == [["Failed", "Failed"]]


# -- tiny power/throughput environment ---------------------------------------

@pytest.fixture(scope="module")
def tiny_env(tmp_path_factory):
    """A minimal power/throughput input: one parquet table and two stream
    files of trivial queries — no datagen, sub-second streams."""
    root = tmp_path_factory.mktemp("resilience")
    ddim = root / "input" / "date_dim"
    ddim.mkdir(parents=True)
    pq.write_table(pa.table({
        "d_date_sk": pa.array(range(40), type=pa.int64()),
        "d_year": pa.array([1998 + i % 3 for i in range(40)],
                           type=pa.int64()),
    }), str(ddim / "part-0.parquet"))
    streams = root / "streams"
    streams.mkdir()
    body = (
        "-- start query 1 using template query1.tpl\n"
        "SELECT COUNT(*) AS cnt FROM date_dim;\n"
        "-- start query 2 using template query3.tpl\n"
        "SELECT d_year, COUNT(*) AS c FROM date_dim "
        "GROUP BY d_year ORDER BY d_year;\n")
    for sid in (0, 1, 2):
        (streams / f"query_{sid}.sql").write_text(body)
    return str(root / "input"), str(streams), root


def test_power_fault_inject_writes_failed_and_keeps_going(tiny_env, tmp_path):
    """The registry-backed --fault_inject keeps the reference contract: the
    injected query records Failed with the exception in its JSON summary
    and the stream keeps going."""
    inp, streams, _ = tiny_env
    json_dir = str(tmp_path / "json")
    rows = run_query_stream(inp, os.path.join(streams, "query_0.sql"),
                            str(tmp_path / "t.csv"), backend="numpy",
                            json_summary_folder=json_dir,
                            fault_inject=["query1"])
    assert [r[0] for r in rows] == ["query1", "query3"]
    summaries = {}
    for path in glob.glob(os.path.join(json_dir, "*.json")):
        with open(path) as f:
            summaries[os.path.basename(path).split("-")[1]] = json.load(f)
    assert summaries["query1"]["queryStatus"] == ["Failed"]
    assert any("injected fault" in e
               for e in summaries["query1"]["exceptions"])
    assert summaries["query3"]["queryStatus"] == ["Completed"]
    # the sugar disarms its specs on the way out
    assert not any(s.point == "query.run" for s in FAULTS.specs())


def test_power_resume_skips_completed_queries(tiny_env, tmp_path):
    """A power run interrupted mid-stream resumes from the flushed partial
    log without re-running completed queries."""
    inp, streams, _ = tiny_env
    log = str(tmp_path / "time.csv")
    # simulate an interrupted run: query1 recorded, no sentinel end rows,
    # and query1's JSON summary already on disk — a resumed run re-enters
    # its OWN summary folder (the non-empty-folder refusal only applies
    # to fresh runs; stale-run poisoning is what it guards against)
    _write_time_log(log, 111, [("query1", 111, 222, 111)], None)
    json_dir = str(tmp_path / "json")
    os.makedirs(os.path.join(json_dir, "power"))
    with open(os.path.join(json_dir, "power", "power-query1-0.json"),
              "w") as f:
        f.write('{"queryStatus": ["Completed"]}')
    rows = run_query_stream(inp, os.path.join(streams, "query_0.sql"),
                            log, backend="numpy",
                            json_summary_folder=json_dir, resume=True)
    assert rows[0] == ("query1", 111, 222, 111)   # preserved, not re-run
    assert [r[0] for r in rows] == ["query1", "query3"]
    # the pre-kill summary is preserved and only the remaining query
    # produced a new one
    ran = {os.path.basename(p).split("-")[1]
           for p in glob.glob(os.path.join(json_dir, "**", "*.json"),
                              recursive=True)}
    assert ran == {"query1", "query3"}
    with open(log) as f:
        rows_csv = list(csv.reader(f))
    labels = [r[0] for r in rows_csv]
    assert labels.count("query1") == 1
    assert "Power End Time" in labels
    start_row = rows_csv[labels.index("Power Start Time")]
    assert start_row[1] == "111"                  # original start kept

    # resuming a COMPLETE log is a no-op that preserves the sentinels
    before = open(log).read()
    rows2 = run_query_stream(inp, os.path.join(streams, "query_0.sql"),
                             log, backend="numpy", resume=True)
    assert [r[0] for r in rows2] == ["query1", "query3"]
    assert open(log).read() == before


def test_power_deadline_kills_hung_execute(tiny_env, tmp_path):
    """A hung jax.execute fault point is killed by the per-query deadline
    and recorded as Failed; the stream keeps going (the abandoned worker
    cannot block it — power swaps the session's statement lock via
    abandon_inflight). Budget 2 s: well under the 3 s hang so the kill
    still fires, well over query3's ~0.4 s cold record pass so the
    neighbor's completion is not a timing race on a loaded 1-core host."""
    inp, streams, _ = tiny_env
    FAULTS.arm("jax.execute:hang:3#1")
    json_dir = str(tmp_path / "json")
    t0 = time.monotonic()
    rows = run_query_stream(inp, os.path.join(streams, "query_0.sql"),
                            str(tmp_path / "t.csv"), backend="jax",
                            json_summary_folder=json_dir, query_timeout=2.0)
    assert [r[0] for r in rows] == ["query1", "query3"]
    assert time.monotonic() - t0 < 60
    summaries = {}
    for path in glob.glob(os.path.join(json_dir, "*.json")):
        with open(path) as f:
            summaries[os.path.basename(path).split("-")[1]] = json.load(f)
    assert summaries["query1"]["queryStatus"] == ["Failed"]
    assert any("exceeded" in e and "budget" in e
               for e in summaries["query1"]["exceptions"])
    assert summaries["query3"]["queryStatus"][0] in (
        "Completed", "CompletedWithTaskFailures")


def test_deadline_abandoned_worker_does_not_block_stream(tiny_env,
                                                         tmp_path):
    """The abandoned worker cannot be killed and sits INSIDE sql() —
    holding the session's statement serialization lock — for its whole
    12 s hang. power swaps in fresh locks after the deadline fires
    (Session.abandon_inflight), so the next query must run immediately
    and COMPLETE instead of queueing behind the zombie until its own
    budget expires."""
    inp, streams, _ = tiny_env
    FAULTS.arm("jax.execute:hang:12#1")
    json_dir = str(tmp_path / "json")
    t0 = time.monotonic()
    rows = run_query_stream(inp, os.path.join(streams, "query_0.sql"),
                            str(tmp_path / "t.csv"), backend="jax",
                            json_summary_folder=json_dir, query_timeout=2.0)
    assert [r[0] for r in rows] == ["query1", "query3"]
    assert time.monotonic() - t0 < 10   # nobody waited out the 12 s hang
    summaries = {}
    for path in glob.glob(os.path.join(json_dir, "*.json")):
        with open(path) as f:
            summaries[os.path.basename(path).split("-")[1]] = json.load(f)
    assert summaries["query1"]["queryStatus"] == ["Failed"]
    assert summaries["query3"]["queryStatus"][0] in (
        "Completed", "CompletedWithTaskFailures")


def test_power_query_retry_records_attempts(tiny_env, tmp_path):
    """A transiently failing query retries and completes; the summary
    carries the per-attempt trail."""
    inp, streams, _ = tiny_env
    FAULTS.arm("query.run:raise#1/query1")
    json_dir = str(tmp_path / "json")
    rows = run_query_stream(inp, os.path.join(streams, "query_0.sql"),
                            str(tmp_path / "t.csv"), backend="numpy",
                            json_summary_folder=json_dir, query_attempts=2)
    assert [r[0] for r in rows] == ["query1", "query3"]
    for path in glob.glob(os.path.join(json_dir, "*.json")):
        with open(path) as f:
            d = json.load(f)
        if os.path.basename(path).split("-")[1] == "query1":
            assert d["attempts"] == [2]
            assert d["retriedStatus"] == [["Failed", "Completed"]]
            assert d["queryStatus"] == ["CompletedWithTaskFailures"]


# -- supervised throughput ----------------------------------------------------

def test_throughput_stream_crash_restarts_and_completes(tiny_env, tmp_path):
    """A stream configured to crash via the fault registry completes after
    a restart; per-stream status lands in the CSV and elapsed is real."""
    inp, streams, _ = tiny_env
    log_dir = str(tmp_path / "logs")
    FAULTS.arm("stream.spawn:raise#1")
    elapsed = run_throughput(inp, streams, [1, 2], log_dir,
                             backend="numpy", mode="thread",
                             max_attempts=2, retry_backoff_s=0.01)
    assert elapsed > 0
    with open(status_csv_path(log_dir)) as f:
        status = {int(r["stream"]): r for r in csv.DictReader(f)}
    assert {s["status"] for s in status.values()} == {"Completed"}
    # exactly one stream burned the injected crash and restarted
    assert sorted(int(s["attempts"]) for s in status.values()) == [1, 2]


def test_throughput_permanent_failure_reports_partial_elapsed(tiny_env,
                                                              tmp_path):
    inp, streams, _ = tiny_env
    log_dir = str(tmp_path / "logs")
    with pytest.raises(ThroughputError) as ei:
        # stream 7 has no stream file: every attempt fails
        run_throughput(inp, streams, [1, 7], log_dir, backend="numpy",
                       mode="thread", max_attempts=2, retry_backoff_s=0.01)
    err = ei.value
    assert err.failed == [7]
    assert err.partial_elapsed is not None and err.partial_elapsed > 0
    assert "partial elapsed" in str(err)
    with open(status_csv_path(log_dir)) as f:
        status = {int(r["stream"]): r for r in csv.DictReader(f)}
    assert status[1]["status"] == "Completed"
    assert status[7]["status"] == "Failed"
    assert int(status[7]["attempts"]) == 2


def test_supervise_processes_retry_and_timeout(tmp_path):
    """Process-mode supervision: a crashing child restarts with backoff and
    completes; a hung child is killed at its budget and marked TimedOut."""
    marker = str(tmp_path / "marker")
    crash_once = [sys.executable, "-c",
                  "import os, sys\n"
                  f"p = {marker!r}\n"
                  "if not os.path.exists(p):\n"
                  "    open(p, 'w').close(); sys.exit(3)\n"]
    hang = [sys.executable, "-c", "import time; time.sleep(30)"]
    t0 = time.monotonic()
    statuses = {s.stream: s for s in supervise_processes(
        [(1, crash_once), (2, hang)], max_attempts=2, stream_timeout=1.0,
        backoff_s=0.01, poll_s=0.02)}
    assert statuses[1].status == "Completed" and statuses[1].attempts == 2
    assert statuses[2].status == "TimedOut"
    assert "budget" in statuses[2].error
    assert time.monotonic() - t0 < 20      # both hangs killed, not waited


def test_supervise_processes_kills_children_on_abandon(tmp_path):
    """An abandoned round (interrupt mid-supervision) never leaks sibling
    processes."""
    procs = []

    def spawn(cmd):
        p = subprocess.Popen(cmd)
        procs.append(p)
        return p

    calls = [0]

    def clock():
        calls[0] += 1
        if calls[0] > 8:
            raise KeyboardInterrupt
        return time.monotonic()

    hang = [sys.executable, "-c", "import time; time.sleep(30)"]
    with pytest.raises(KeyboardInterrupt):
        # stream_timeout keeps the supervisor consulting the clock each
        # poll round so the simulated interrupt lands mid-supervision
        supervise_processes([(1, hang), (2, hang)], max_attempts=1,
                            stream_timeout=50.0, poll_s=0.02,
                            spawn=spawn, clock=clock)
    assert procs, "supervisor never spawned"
    for p in procs:
        assert p.poll() is not None        # killed, not leaked


def test_throughput_process_mode_tiny(tiny_env, tmp_path):
    """One real process-mode round over the tiny input: both streams
    complete supervised, the status CSV and elapsed are written."""
    inp, streams, _ = tiny_env
    log_dir = str(tmp_path / "logs")
    elapsed = run_throughput(inp, streams, [1, 2], log_dir,
                             backend="numpy", mode="process")
    assert elapsed > 0
    with open(status_csv_path(log_dir)) as f:
        status = {int(r["stream"]): r for r in csv.DictReader(f)}
    assert {s["status"] for s in status.values()} == {"Completed"}


# -- degraded scraping / bench satellites ------------------------------------

def test_scrape_log_names_incomplete_streams(tmp_path):
    good = str(tmp_path / "throughput_1.csv")
    _write_time_log(good, 1000, [("query1", 1000, 1500, 500)], 2000)
    interrupted = str(tmp_path / "throughput_2.csv")
    _write_time_log(interrupted, 1000, [("query1", 1000, 1500, 500)], None)

    assert scrape_log(good) == (1000, 2000)
    with pytest.raises(IncompleteStreamLog, match="throughput_2"):
        scrape_log(interrupted)
    assert scrape_log(interrupted, strict=False) is None

    missing = str(tmp_path / "throughput_3.csv")
    with pytest.raises(IncompleteStreamLog) as ei:
        throughput_elapsed([good, interrupted, missing])
    msg = str(ei.value)
    assert "throughput_2" in msg and "throughput_3" in msg
    assert "throughput_1" not in msg
    # partial elapsed over the complete logs only
    assert throughput_elapsed([good, interrupted, missing],
                              allow_partial=True) == pytest.approx(1.0)
    with pytest.raises(IncompleteStreamLog):
        throughput_elapsed([interrupted], allow_partial=True)


def test_get_load_end_timestamp_missing_report_explains(tmp_path):
    from nds_tpu import bench
    missing = str(tmp_path / "load_report.txt")
    with pytest.raises(FileNotFoundError, match="skipped but"):
        bench.get_load_end_timestamp(missing)


def test_bench_phase_retry_config():
    """Phase-level retry wiring: the policy built from the YAML resilience
    section retries a transiently failing phase."""
    calls = []

    def phase():
        calls.append(1)
        if len(calls) == 1:
            raise ThroughputError("streams failed", partial_elapsed=1.0,
                                  failed=[3])
        return 7.5

    policy = RetryPolicy(max_attempts=2, backoff_s=0.001)
    assert policy.call(phase) == 7.5
    assert len(calls) == 2
