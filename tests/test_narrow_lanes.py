"""Narrow-lane packed uploads + encoded execution (round 9).

The packed-table layout carries every column at its minimal physical width
(device.plan_lanes/pack_table: u8/u16/u32/i32 lanes from dtype + value-range
stats, bit-packed validity, one contiguous byte buffer) and execution keeps
32-bit-range columns on i32 device arrays. Exactness is pinned by a
property-style pack/unpack round trip over dtypes x lanes x validity
patterns, a --no_narrow_lanes bit-identity differential on a streamed bench
shape, and verifier checks that a lane too narrow for its column's recorded
range is caught statically (verify.check_scan_lanes / ScanNode.lanes)."""
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from nds_tpu.config import EngineConfig
from nds_tpu.engine import Session
from nds_tpu.engine import arrow_bridge
from nds_tpu.engine.column import Column, Table
from nds_tpu.engine.jax_backend.device import (
    LaneOverflowError, device_bytes, lane_bytes, lane_legal, pack_table,
    plan_lanes, to_device, to_host, unpack_table)

N_FACT, N_DIM = 50_000, 300
CHUNK = 4_096


# ---------------------------------------------------------------------------
# pack/unpack round trip: dtypes x lane widths x validity patterns
# ---------------------------------------------------------------------------

def _col(dtype, data, valid=None, dictionary=None):
    return Column.from_values(dtype, np.asarray(data), valid, dictionary)


def _validity(pattern, n, rng):
    if pattern == "none_null":
        return None
    if pattern == "all_null":
        return np.zeros(n, dtype=bool)
    return rng.random(n) < 0.7


_CASES = [
    # (name, dtype, generator(lo..hi ints), stats, expected lane)
    ("int_u8", "int", (0, 255), (0, 255), "u8"),
    ("int_u16", "int", (0, 60_000), (0, 65_535), "u16"),
    ("int_u32", "int", (0, 2 ** 30), (0, 2 ** 31 - 1), "u32"),
    ("int_i32_neg", "int", (-1000, 1000), (-1000, 1000), "i32"),
    ("int_i64", "int", (-2 ** 40, 2 ** 40), (-2 ** 40, 2 ** 40), "i64"),
    ("dec2_u16", "dec2", (0, 50_000), (0, 65_535), "u16"),
    ("dec2_i64", "dec2", (-10 ** 12, 10 ** 12), (-10 ** 12, 10 ** 12),
     "i64"),
    ("date_u16", "date", (0, 40_000), (0, 40_000), "u16"),
    ("date_i32", "date", (0, 80_000), (0, 80_000), "i32"),
]


@pytest.mark.parametrize("pattern", ["none_null", "mixed", "all_null"])
@pytest.mark.parametrize("name,dtype,rng_range,stats,want_lane",
                         _CASES, ids=[c[0] for c in _CASES])
def test_roundtrip_int_family(name, dtype, rng_range, stats, want_lane,
                              pattern):
    rng = np.random.default_rng(hash((name, pattern)) % 2 ** 31)
    n = 700
    data = rng.integers(rng_range[0], rng_range[1] + 1, n)
    valid = _validity(pattern, n, rng)
    t = Table([name], [_col(dtype, data, valid)])
    lanes = plan_lanes([dtype], [stats])
    assert lanes == (want_lane,)
    packed = pack_table(t, capacity=1024, lanes=lanes)
    assert packed is not None
    got = to_host(unpack_table(packed))
    want = to_host(to_device(t, capacity=1024))
    np.testing.assert_array_equal(np.asarray(got.columns[0].data),
                                  np.asarray(want.columns[0].data))
    np.testing.assert_array_equal(got.columns[0].validity,
                                  want.columns[0].validity)


@pytest.mark.parametrize("pattern", ["none_null", "mixed", "all_null"])
def test_roundtrip_float_bool_str(pattern):
    rng = np.random.default_rng(hash(pattern) % 2 ** 31)
    n = 700
    fvals = rng.normal(size=n)
    bvals = rng.integers(0, 2, n).astype(bool)
    # max-code strings: every code of a full u8-sized dictionary occurs
    dict256 = np.asarray([f"v{i}" for i in range(256)], dtype=object)
    codes = rng.integers(0, 256, n).astype(np.int32)
    codes[:256] = np.arange(256)
    valid = _validity(pattern, n, rng)
    t = Table(["f", "b", "s"], [
        _col("float", fvals, valid),
        _col("bool", bvals, valid),
        Column("str", codes, valid, dict256),
    ])
    lanes = plan_lanes(["float", "bool", "str"], [None] * 3,
                       dict_sizes=[None, None, 256])
    assert lanes == ("f64", "b1", "u8")
    packed = pack_table(t, capacity=1024, lanes=lanes)
    got = to_host(unpack_table(packed))
    want = to_host(to_device(t, capacity=1024))
    for g, w in zip(got.columns, want.columns):
        np.testing.assert_array_equal(np.asarray(g.data), np.asarray(w.data))
        np.testing.assert_array_equal(g.validity, w.validity)
    # the str dictionary must survive the packed round trip
    assert list(got.columns[2].decode()) == list(want.columns[2].decode())


def test_narrow_lanes_reject_out_of_range_values():
    """Negative / oversized values must REJECT the narrow lane loudly:
    silent wraparound would alias unrelated rows."""
    neg = Table(["x"], [_col("int", np.asarray([-3, 1, 2]))])
    with pytest.raises(LaneOverflowError):
        pack_table(neg, capacity=8, lanes=("u8",))
    big = Table(["x"], [_col("int", np.asarray([0, 70_000]))])
    with pytest.raises(LaneOverflowError):
        pack_table(big, capacity=8, lanes=("u16",))
    f = Table(["x"], [_col("float", np.asarray([0.5]))])
    with pytest.raises(LaneOverflowError):
        pack_table(f, capacity=8, lanes=("u8",))


def test_lane_planning_rules():
    # stats-driven narrowing never picks an unsigned lane for negatives
    assert plan_lanes(["int"], [(-5, 5)]) == ("i32",)
    assert plan_lanes(["int"], [(0, 200)]) == ("u8",)
    assert plan_lanes(["int"], [(0, 2 ** 31 - 1)]) == ("u32",)
    assert plan_lanes(["int"], [(0, 2 ** 31)]) == ("i64",)
    assert plan_lanes(["int"], [None]) == ("i64",)   # unknown -> widest
    assert plan_lanes(["date"], [None]) == ("i32",)
    # legacy wide layout (--no_narrow_lanes): ints ride int64, bools and
    # strings fall back to the per-column path exactly like the old packer
    assert plan_lanes(["int", "date", "float"], narrow=False) == \
        ("i64", "i32", "f64")
    assert plan_lanes(["bool"], narrow=False) is None
    assert plan_lanes(["str"], narrow=False) is None
    assert not lane_legal("u8", "float")
    assert not lane_legal("b1", "int")
    assert lane_legal("b1", "bool")


def test_packed_bytes_accounting():
    t = Table(["a", "b"], [_col("int", np.arange(100)),
                           _col("bool", np.zeros(100, dtype=bool))])
    lanes = plan_lanes(["int", "bool"], [(0, 99), None])
    packed = pack_table(t, capacity=128, lanes=lanes)
    # u8 data (128) + b1 data (16) + 3 bit-packed masks (16 each)
    assert device_bytes(packed) == lane_bytes(lanes, 128) == 128 + 16 + 48


# ---------------------------------------------------------------------------
# streamed differential: narrow on vs off bit-identical, >= 2x fewer bytes
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def bench_shape(tmp_path_factory):
    """An NDS-fact-shaped table: int64 surrogate keys with small ranges,
    a small-int quantity, an f64 price, a date-like key, an 8-bit flag."""
    tmp = tmp_path_factory.mktemp("narrow_lanes")
    rng = np.random.default_rng(23)
    qty = rng.integers(1, 100, N_FACT).astype(object)
    qty[rng.random(N_FACT) < 0.05] = None
    fact = pa.table({
        "fk": pa.array(rng.integers(0, N_DIM + 5, N_FACT), type=pa.int64()),
        "qty": pa.array(list(qty), type=pa.int32()),
        "price": pa.array(np.round(rng.uniform(1, 100, N_FACT), 2)),
        "day": pa.array(rng.integers(2_450_000, 2_453_000, N_FACT),
                        type=pa.int64()),
        "flag": pa.array(rng.integers(0, 2, N_FACT).astype(bool)),
    })
    path = os.path.join(str(tmp), "fact.parquet")
    pq.write_table(fact, path, row_group_size=8192)
    dim = pa.table({"dk": pa.array(np.arange(N_DIM), type=pa.int32()),
                    "grp": pa.array((np.arange(N_DIM) % 13)
                                    .astype(np.int32))})
    return {"fact_path": path, "dim": dim}


Q_BENCH = """
SELECT d.grp, SUM(f.qty) AS s, COUNT(*) AS c, MIN(f.day) AS md,
       SUM(f.price) AS sp
FROM fact f JOIN dim d ON f.fk = d.dk
WHERE f.day < 2452500 AND f.flag
GROUP BY d.grp ORDER BY d.grp
"""


def _session(data, narrow, **kw):
    # encoded_exec off: this suite pins the pure narrow-LANE layout (the
    # encoding axis on top of it is pinned by tests/test_encoded_exec.py —
    # with encodings on, low-cardinality columns ride dict code lanes and
    # these width expectations would legitimately shift)
    kw.setdefault("encoded_exec", False)
    cfg = EngineConfig(out_of_core=True, chunk_rows=CHUNK,
                       out_of_core_min_rows=10_000, narrow_lanes=narrow,
                       **kw)
    s = Session(cfg)
    s.register_parquet("fact", data["fact_path"])
    s.register_arrow("dim", data["dim"])
    return s


def rows_of(t):
    return [tuple(r) for r in t.to_pylist()]


def test_narrow_off_bit_identical_and_2x_bytes(bench_shape):
    """Acceptance: default (narrow) vs --no_narrow_lanes results are
    BIT-IDENTICAL while bytes_uploaded drops >= 2x, with per-pass plan
    verification (incl. lane/stats legality) green in both modes."""
    s_on = _session(bench_shape, True, verify_plans="per-pass")
    on = rows_of(s_on.sql(Q_BENCH, backend="jax"))
    st_on = dict(s_on.last_exec_stats)
    s_off = _session(bench_shape, False, verify_plans="per-pass")
    off = rows_of(s_off.sql(Q_BENCH, backend="jax"))
    st_off = dict(s_off.last_exec_stats)
    assert st_on["mode"] == st_off["mode"] == "streaming"
    assert on == off
    assert st_on["narrow_lanes"] and not st_off["narrow_lanes"]
    assert st_on["bytes_uploaded"] * 2 <= st_off["bytes_uploaded"]
    lanes = st_on["lane_spec"]["fact"]
    assert lanes["fk"] == "u16" and lanes["qty"] == "u8"
    assert lanes["day"] == "u32" and lanes["flag"] == "b1"
    assert lanes["price"] == "f64"
    assert st_off.get("lane_spec") == {}
    # numpy oracle (float tolerance on the f64 sum only)
    oracle = rows_of(_session(bench_shape, True)
                     .sql(Q_BENCH, backend="numpy"))
    assert len(on) == len(oracle)
    for a, b in zip(on, oracle):
        assert a[:4] == b[:4]
        assert abs(a[4] - b[4]) <= 1e-6 * max(1.0, abs(b[4]))


def test_live_toggle_invalidates_stream_cache(bench_shape):
    """narrow_lanes is part of the stream-cache config fingerprint: a live
    toggle must re-derive groups/lanes/programs, not replay stale ones."""
    s = _session(bench_shape, True)
    a = rows_of(s.sql(Q_BENCH, backend="jax"))
    assert s.last_exec_stats["narrow_lanes"]
    s.config.narrow_lanes = False
    b = rows_of(s.sql(Q_BENCH, backend="jax"))
    assert not s.last_exec_stats["narrow_lanes"]
    assert s.last_exec_stats.get("lane_spec") == {}
    assert a == b


def test_lanes_static_across_skewed_morsels(bench_shape, tmp_path):
    """Morsel widths are decided ONCE per schedule from table-wide stats:
    a first morsel whose local range would fit a narrower lane must still
    ride the table-wide lane (no mid-stream width change, no re-record)."""
    n = 40_000
    vals = np.concatenate([np.zeros(n - 100, dtype=np.int64),
                           np.full(100, 60_000, dtype=np.int64)])
    t = pa.table({"k": pa.array(np.arange(n) % N_DIM, type=pa.int64()),
                  "v": pa.array(vals)})
    path = os.path.join(str(tmp_path), "skew.parquet")
    pq.write_table(t, path, row_group_size=8192)
    s = Session(EngineConfig(out_of_core=True, chunk_rows=CHUNK,
                             out_of_core_min_rows=10_000))
    s.register_parquet("skew", path)
    got = rows_of(s.sql(
        "SELECT SUM(v) s, MAX(v) m, COUNT(*) c FROM skew",
        backend="jax"))
    st = s.last_exec_stats
    assert st["mode"] == "streaming"
    assert st["lane_spec"]["skew"]["v"] == "u16"   # table-wide, not u8
    assert st["re_records"] == 0
    assert got == [(100 * 60_000, 60_000, n)]


# ---------------------------------------------------------------------------
# verifier: width metadata legality
# ---------------------------------------------------------------------------

def test_verifier_catches_too_narrow_lane():
    from nds_tpu.engine.plan import ScanNode
    from nds_tpu.engine.verify import check_scan_lanes, verify_plan

    scan = ScanNode("__morsel__", ["a", "b"], lanes=("u8", "u16"),
                    out_names=["a", "b"], out_dtypes=["int", "int"])
    ok = check_scan_lanes(scan, {"a": (0, 255), "b": (0, 65_535)})
    assert ok == []
    bad = check_scan_lanes(scan, {"a": (0, 999), "b": (-1, 10)})
    assert len(bad) == 2 and all(f.kind == "lane" for f in bad)
    # a narrow lane with NO stats proving it fits is itself a finding
    unproven = check_scan_lanes(scan, {"a": None, "b": (0, 10)})
    assert len(unproven) == 1 and "no value-range stats" in \
        unproven[0].message
    # dtype-level legality is independent of stats (verify_plan path)
    illegal = ScanNode("__morsel__", ["f"], lanes=("u8",),
                       out_names=["f"], out_dtypes=["float"])
    findings = verify_plan(illegal)
    assert any(f.kind == "lane" and "cannot carry" in f.message
               for f in findings)


def test_verify_groups_rejects_lying_stats(bench_shape):
    """Session-level: per-pass verification proves each group's lane spec
    against the SAME stats source the planner used — a lane too narrow for
    the recorded range aborts before any morsel ships on it."""
    from nds_tpu.engine import streaming
    from nds_tpu.engine.verify import PlanVerifyError

    s = _session(bench_shape, True, verify_plans="per-pass")
    sent_q = Q_BENCH
    # first, an honest run primes nothing stale and passes
    s.sql(sent_q, backend="jax")
    ent = s._stream_cache[sent_q]
    g = ent["groups"][0]
    narrowed = tuple("u8" if ln in ("u16", "u32") else ln
                     for ln in g.lanes)
    streaming.set_group_lanes(g, narrowed)
    with pytest.raises(PlanVerifyError) as exc:
        streaming.verify_groups(ent["groups"], col_stats=s.column_stats)
    assert "narrow_lanes" in str(exc.value)


# ---------------------------------------------------------------------------
# column stats sources: arrow tables, parquet metadata, warehouse manifests
# ---------------------------------------------------------------------------

def test_stats_sources_arrow_and_parquet(tmp_path):
    import decimal
    t = pa.table({
        "i": pa.array([3, None, 999_999], type=pa.int64()),
        "d": pa.array([10_957, 11_000, 10_958], type=pa.date32()),
        "dec": pa.array([decimal.Decimal("1.25"), None,
                         decimal.Decimal("-3.50")],
                        type=pa.decimal128(10, 2)),
        "s": pa.array(["x", "y", "z"]),
    })
    path = os.path.join(str(tmp_path), "t.parquet")
    pq.write_table(t, path)
    s = Session(EngineConfig(decimal_physical="i64"))
    s.register_arrow("mem", t)
    s.register_parquet("disk", path)
    for name in ("mem", "disk"):
        st = s.column_stats(name)
        assert st["i"] == (3, 999_999)
        assert st["d"] == (10_957, 11_000)
        assert st["dec"] == (-350, 125)      # engine units: scaled ints
        assert "s" not in st
    # re-registration invalidates the cache
    s.register_arrow("mem", t.slice(0, 1))
    assert s.column_stats("mem")["i"] == (3, 3)


def test_warehouse_manifest_stats_every_column(tmp_path):
    import decimal
    from nds_tpu.warehouse import Warehouse

    wh = Warehouse(str(tmp_path))
    t = pa.table({
        "ss_ticket_number": pa.array([7, 3, 11], type=pa.int64()),
        "ss_sold_date_sk": pa.array([2_450_816, 2_450_820, 2_450_818],
                                    type=pa.int64()),
        "ss_sales_price": pa.array([decimal.Decimal("9.99"),
                                    decimal.Decimal("0.50"), None],
                                   type=pa.decimal128(7, 2)),
        "ss_date": pa.array([10_957, 10_958, 10_959], type=pa.date32()),
    })
    wt = wh.table("demo")
    wt.create(t, partition=False)
    stats = wt.file_stats()
    assert len(stats) == 1
    (per_file,) = stats.values()
    # every integer/date/decimal column lands in the manifest (engine
    # units), not just the *_number delete-prune columns
    assert per_file["ss_ticket_number"] == [3, 11]
    assert per_file["ss_sold_date_sk"] == [2_450_816, 2_450_820]
    assert per_file["ss_sales_price"] == [50, 999]
    assert per_file["ss_date"] == [10_957, 10_959]
    agg = wt.column_stats(wt.current_files(), dec_as_int=True)
    assert agg["ss_ticket_number"] == (3, 11)
    s = Session(EngineConfig(decimal_physical="i64"))
    wh.register_all(s)
    assert s.column_stats("demo")["ss_sales_price"] == (50, 999)


# ---------------------------------------------------------------------------
# satellite: dictionary arrays load without the to_pylist Python loop
# ---------------------------------------------------------------------------

def test_dictionary_column_fast_path():
    vals = [f"cat{i}" for i in range(1000)]
    arr = pa.array(vals + [None, "cat0"]).dictionary_encode()
    chunked = pa.chunked_array([arr.slice(0, 500), arr.slice(500)])
    for a in (arr, chunked):
        col = arrow_bridge.from_arrow_column(a)
        assert col.dtype == "str"
        decoded = list(col.decode())
        assert decoded == vals + [None, "cat0"]
        assert col.data.dtype == np.int32
    # plain strings still encode exactly once and round-trip
    plain = pa.array(["b", None, "a", "b"])
    col = arrow_bridge.from_arrow_column(plain)
    assert list(col.decode()) == ["b", None, "a", "b"]
