"""Finer capacity ladder for big buffers (device.bucket): 3*2^(k-1) steps
between powers of two above CAP_LADDER_MIN rows (PERF.md r5 headroom #2 —
expansion caps averaged 1.5x the actual row count, and gather cost scales
with CAP). Below the threshold the ladder stays pure powers of two, so small
shape buckets — the compile-cache-friendly regime — are untouched."""
import pytest

from nds_tpu.engine.jax_backend.device import CAP_LADDER_MIN, bucket
from nds_tpu.engine.jax_backend.executor import (ReplayMismatch,
                                                 _verify_schedule)

M = 1 << 20


def test_small_counts_stay_powers_of_two():
    assert bucket(0) == 8 and bucket(1) == 8          # minimum
    assert bucket(9) == 16
    assert bucket(1000) == 1024
    assert bucket(CAP_LADDER_MIN) == CAP_LADDER_MIN   # 4M: last pure-pow2 cap


def test_midpoints_above_threshold():
    assert bucket(4 * M + 1) == 6 * M
    assert bucket(5 * M) == 6 * M
    assert bucket(6 * M) == 6 * M                     # idempotent on-cap
    assert bucket(6 * M + 1) == 8 * M
    assert bucket(8 * M) == 8 * M
    assert bucket(9 * M) == 12 * M
    assert bucket(12 * M + 1) == 16 * M
    assert bucket(17 * M) == 24 * M


def test_ladder_is_monotone_and_idempotent():
    prev = 0
    for n in range(1, 30 * M, 997 * 131):             # coarse sweep
        c = bucket(n)
        assert c >= n and c >= prev
        assert bucket(c) == c
        prev = c


def test_overshoot_bounded():
    # above the threshold the cap overshoots by at most 1.5x (was 2x);
    # gather cost scales with CAP, so this bounds the wasted traffic
    for n in range(CAP_LADDER_MIN + 1, 64 * M, 999 * 1009):
        assert bucket(n) / n <= 1.5


def test_mesh_divisibility_preserved():
    # midpoint caps keep every power-of-two shard count up to 2^(k-1)
    for shards in (2, 4, 8, 16):
        assert (6 * M) % shards == 0
        assert (12 * M) % shards == 0


def test_schedule_check_accepts_growth_within_ladder_cap():
    """Recompile-count bound: row counts drifting within one ladder step
    replay against the recorded program — only crossing the (now 1.5x-max)
    cap forces a re-record."""
    decisions = [("cap", 5 * M)]                       # planned: caps at 6M
    _verify_schedule(decisions, [5 * M + 100_000])     # growth inside cap
    _verify_schedule(decisions, [6 * M])               # exactly at cap
    with pytest.raises(ReplayMismatch):
        _verify_schedule(decisions, [6 * M + 1])       # crossed: re-record


def test_one_program_shape_per_ladder_band():
    caps = {bucket(n) for n in range(4 * M + 1, 6 * M, 65_536)}
    assert caps == {6 * M}
    caps = {bucket(n) for n in range(6 * M + 1, 8 * M, 65_536)}
    assert caps == {8 * M}
