"""q95-class self-join distinctness rewrite (planner._selfjoin_distinct_
rewrite): `SELECT key FROM t a, t b WHERE a.key = b.key AND a.x <> b.x`
consumed as a key set becomes `GROUP BY key HAVING MIN(x) < MAX(x)` —
the pair expansion (the hottest buffer class on the chip, q95's 16M-row
gathers spilling to host memory) disappears. Guard rails: the rewrite
must NOT fire for multiplicity- or value-sensitive consumers."""
import os

import numpy as np
import pyarrow as pa
import pytest

import nds_tpu.engine.plan as P
from nds_tpu.engine import Session
from nds_tpu.engine.planner import Planner
from nds_tpu.sql import parse_sql


def _session():
    rng = np.random.default_rng(11)
    n = 5000
    s = Session()
    s.register_arrow("sales", pa.table({
        "order_no": pa.array(rng.integers(0, 800, n), type=pa.int64()),
        "wh": pa.array(rng.integers(0, 5, n), type=pa.int64()),
        "amt": pa.array(rng.integers(1, 100, n), type=pa.int64()),
    }))
    s.register_arrow("probe", pa.table({
        "o": pa.array(np.arange(800), type=pa.int64()),
        "v": pa.array(np.arange(800) % 17, type=pa.int64()),
    }))
    return s


MULTI_WH = """
SELECT COUNT(*) FROM probe
WHERE o IN (SELECT a.order_no FROM sales a, sales b
            WHERE a.order_no = b.order_no AND a.wh <> b.wh)
"""


def _selfjoins(plan):
    return [n for n in P.iter_plan_nodes(plan)
            if isinstance(n, P.JoinNode) and isinstance(n.left, P.ScanNode)
            and isinstance(n.right, P.ScanNode)
            and n.left.table == n.right.table]


def test_rewrite_fires_and_matches_literal():
    s = _session()
    plan = Planner(s._catalog()).plan_query(parse_sql(MULTI_WH))
    assert not _selfjoins(plan), "self-join must be rewritten away"
    got = s.sql(MULTI_WH, backend="numpy").to_pylist()
    os.environ["NDS_TPU_NO_SELFJOIN_REWRITE"] = "1"
    try:
        s2 = _session()
        plan2 = Planner(s2._catalog()).plan_query(parse_sql(MULTI_WH))
        assert _selfjoins(plan2), "env toggle must disable the rewrite"
        want = s2.sql(MULTI_WH, backend="numpy").to_pylist()
    finally:
        del os.environ["NDS_TPU_NO_SELFJOIN_REWRITE"]
    assert got == want


def test_rewrite_matches_on_device():
    s = _session()
    got = s.sql(MULTI_WH, backend="jax").to_pylist()
    want = s.sql(MULTI_WH, backend="numpy").to_pylist()
    assert got == want


def test_no_rewrite_for_count_consumer():
    """COUNT over the self-join sees pair multiplicities: must not fire."""
    q = ("SELECT COUNT(*) FROM sales a, sales b "
         "WHERE a.order_no = b.order_no AND a.wh <> b.wh")
    s = _session()
    plan = Planner(s._catalog()).plan_query(parse_sql(q))
    assert _selfjoins(plan), "aggregate consumer observes multiplicity"
    # and the answer is the true pair count on both backends
    got = s.sql(q, backend="numpy").to_pylist()
    got_j = s.sql(q, backend="jax").to_pylist()
    assert got == got_j


def test_no_rewrite_when_x_column_consumed():
    """A consumer reading the wh column must keep the literal join."""
    q = ("SELECT COUNT(*) FROM probe WHERE v IN "
         "(SELECT a.wh FROM sales a, sales b "
         " WHERE a.order_no = b.order_no AND a.wh <> b.wh)")
    s = _session()
    plan = Planner(s._catalog()).plan_query(parse_sql(q))
    assert _selfjoins(plan), "wh is consumed: values matter, no rewrite"
    got = s.sql(q, backend="numpy").to_pylist()
    got_j = s.sql(q, backend="jax").to_pylist()
    assert got == got_j


def test_rewrite_handles_all_null_groups():
    """Groups whose x is entirely NULL must not qualify (SQL <> is
    null-rejecting), and single-row groups must not qualify."""
    s = Session()
    s.register_arrow("sales", pa.table({
        "order_no": pa.array([1, 1, 2, 2, 3, 4, 4], type=pa.int64()),
        "wh": pa.array([7, 8, None, None, 5, 6, 6], type=pa.int64()),
        "amt": pa.array([1] * 7, type=pa.int64()),
    }))
    s.register_arrow("probe", pa.table({
        "o": pa.array([1, 2, 3, 4], type=pa.int64()),
        "v": pa.array([0, 0, 0, 0], type=pa.int64()),
    }))
    got = s.sql(MULTI_WH, backend="numpy").to_pylist()
    # only order 1 has two distinct non-null wh values
    assert list(map(tuple, got)) == [(1,)]
