"""Chaos-hardened serving: the circuit breaker, retry budget, program
quarantine, and device-lane watchdog under REAL injected faults, plus the
seeded campaign driver (nds_tpu/chaos) at CI scale.

The contract under test is the ISSUE's acceptance bar: every failure a
client sees is typed, every completed response is hash-identical to the
fault-free baseline, flight artifacts exist per firing/trip, and a
quarantined program re-records instead of poisoning every adopter."""
import os
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.chaos import (CampaignSpec, ChaosCampaign, build_demo_session,
                           build_plan, build_workload, demo_pool)
from nds_tpu.config import EngineConfig
from nds_tpu.engine import Session
from nds_tpu.engine.jax_backend import executor as jexec_mod
from nds_tpu.obs.flight import FLIGHT
from nds_tpu.obs.metrics import METRICS
from nds_tpu.resilience import (FAULTS, AdmissionRejected, CircuitBreaker,
                                CircuitBreakerConfig, CircuitOpen,
                                DeadlineExceeded, FaultError, FaultSpec,
                                RetryPolicy)
from nds_tpu.service import QueryService, ServiceConfig

N_FACT, N_DIM = 20_000, 50
TPL = ("SELECT grp, COUNT(*) AS n, SUM(qty) AS tq FROM fact "
       "JOIN dim ON fk = dk WHERE qty BETWEEN {a} AND {b} "
       "GROUP BY grp ORDER BY grp")


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(11)
    fact = pa.table({
        "fk": pa.array(rng.integers(0, N_DIM, N_FACT), type=pa.int64()),
        "qty": pa.array(rng.integers(1, 100, N_FACT), type=pa.int64()),
    })
    dim = pa.table({"dk": pa.array(np.arange(N_DIM), type=pa.int64()),
                    "grp": pa.array((np.arange(N_DIM) % 7)
                                    .astype(np.int64))})
    return {"fact": fact, "dim": dim}


def make_session(data):
    s = Session(EngineConfig())
    s.register_arrow("fact", data["fact"])
    s.register_arrow("dim", data["dim"])
    return s


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.clear()
    yield
    FAULTS.clear()


def warm(svc, sql):
    svc.sql(sql, label="warm")
    svc.sql(sql, label="warm")


# -- circuit breaker (unit, injected clock) -----------------------------------

def test_breaker_trips_and_half_open_probe_closes():
    now = [0.0]
    b = CircuitBreaker(CircuitBreakerConfig(window=8, min_failures=3,
                                            failure_rate=0.5, open_s=5.0,
                                            probes=1),
                       clock=lambda: now[0])
    for _ in range(3):
        b.record("FaultError")
    st = b.state()["FaultError"]
    assert st["state"] == "open" and st["trips"] == 1
    with pytest.raises(CircuitOpen) as ei:
        b.admit()
    assert ei.value.error_class == "FaultError"
    assert ei.value.retry_after_s == pytest.approx(5.0)
    # cooldown elapses: exactly one probe slot opens
    now[0] = 6.0
    probe = b.admit()
    assert probe == "FaultError"
    with pytest.raises(CircuitOpen):    # second concurrent admission
        b.admit()
    b.record(None, probe=probe)         # probe succeeds -> closed
    assert b.state()["FaultError"]["state"] == "closed"
    assert b.admit() is None            # traffic flows again


def test_breaker_probe_failure_reopens():
    now = [0.0]
    b = CircuitBreaker(CircuitBreakerConfig(window=8, min_failures=2,
                                            failure_rate=0.5, open_s=1.0),
                       clock=lambda: now[0])
    b.record("FaultError")
    b.record("FaultError")
    now[0] = 2.0
    probe = b.admit()
    assert probe == "FaultError"
    b.record("FaultError", probe=probe)     # probe fails -> re-open
    st = b.state()["FaultError"]
    assert st["state"] == "open" and st["trips"] == 2
    with pytest.raises(CircuitOpen):
        b.admit()


def test_breaker_excluded_class_never_trips():
    b = CircuitBreaker(CircuitBreakerConfig(min_failures=1,
                                            failure_rate=0.1))
    for _ in range(10):
        b.record("DeadlineExceeded")
    assert b.admit() is None
    assert "DeadlineExceeded" not in b.state()


def test_breaker_successes_dilute_failure_rate():
    b = CircuitBreaker(CircuitBreakerConfig(window=8, min_failures=4,
                                            failure_rate=0.5))
    for _ in range(3):
        b.record("FaultError")
    for _ in range(6):
        b.record(None)
    # window now holds [T,T,F,F,F,F,F,F]; one more failure -> 2 fails in
    # the window, below min_failures: successes genuinely healed it
    b.record("FaultError")
    assert b.state()["FaultError"]["state"] == "closed"
    b2 = CircuitBreaker(CircuitBreakerConfig(window=8, min_failures=4,
                                             failure_rate=0.9))
    for _ in range(4):
        b2.record("FaultError")
        b2.record(None)
    assert b2.state()["FaultError"]["state"] == "closed"


def test_retry_policy_classification_table():
    p = RetryPolicy()
    assert p.classify(AdmissionRejected("q full")) == "transient"
    assert p.classify(CircuitOpen("open", error_class="X")) == "fatal"
    assert p.classify(DeadlineExceeded("late")) == "fatal"
    assert p.classify(FaultError("boom")) == "transient"
    # jittered backoff is deterministic and capped
    j = RetryPolicy(backoff_s=1.0, jitter=0.5, max_backoff_s=3.0,
                    backoff_factor=2.0)
    seq1 = [j.backoff(a) for a in (1, 2, 3, 4)]
    seq2 = [j.backoff(a) for a in (1, 2, 3, 4)]
    assert seq1 == seq2
    assert all(b <= 3.0 for b in seq1)
    assert seq1[0] > 1.0        # jitter stretched attempt 1


# -- service integration: breaker at admission --------------------------------

def test_service_circuit_open_typed_rejection(data):
    session = make_session(data)
    cfg = ServiceConfig(
        batching=False, quarantine=False,
        breaker=CircuitBreakerConfig(window=8, min_failures=3,
                                     failure_rate=0.5, open_s=60.0))
    before = METRICS.snapshot()
    with QueryService(session, cfg) as svc:
        warm(svc, TPL.format(a=5, b=60))
        spec = FAULTS.arm(FaultSpec(point="jax.execute", times=3))
        for i in range(3):
            with pytest.raises(FaultError):
                svc.sql(TPL.format(a=5, b=60), label=f"f{i}")
        FAULTS.disarm(spec)
        # breaker tripped: the NEXT submit is refused at the door, typed,
        # fatal under RetryPolicy (permanent-until-probe)
        with pytest.raises(CircuitOpen) as ei:
            svc.submit(TPL.format(a=5, b=60), label="refused")
        assert ei.value.error_class == "FaultError"
        assert RetryPolicy().classify(ei.value) == "fatal"
        assert isinstance(ei.value, AdmissionRejected)
    delta = METRICS.delta(before)
    assert delta.get("circuit_trips", 0) == 1
    assert delta.get("service_rejected", 0) >= 1


def test_service_breaker_probe_recovers(data):
    session = make_session(data)
    cfg = ServiceConfig(
        batching=False, quarantine=False,
        breaker=CircuitBreakerConfig(window=8, min_failures=2,
                                     failure_rate=0.5, open_s=0.2))
    with QueryService(session, cfg) as svc:
        warm(svc, TPL.format(a=5, b=60))
        ref = svc.sql(TPL.format(a=5, b=60), label="ref").to_pylist()
        spec = FAULTS.arm(FaultSpec(point="jax.execute", times=2))
        for i in range(2):
            with pytest.raises(FaultError):
                svc.sql(TPL.format(a=5, b=60), label=f"f{i}")
        FAULTS.disarm(spec)
        with pytest.raises(CircuitOpen):
            svc.submit(TPL.format(a=5, b=60), label="refused")
        time.sleep(0.4)     # cooldown passes: the next submit is the probe
        out = svc.sql(TPL.format(a=5, b=60), label="probe")
        assert out.to_pylist() == ref
        # closed again: normal traffic, bit-identical
        assert svc.sql(TPL.format(a=5, b=60),
                       label="after").to_pylist() == ref


# -- retry budget -------------------------------------------------------------

def test_retry_budget_requeues_transient_failure(data):
    session = make_session(data)
    cfg = ServiceConfig(batching=False, retry_budget=4, ticket_attempts=2)
    before = METRICS.snapshot()
    with QueryService(session, cfg) as svc:
        warm(svc, TPL.format(a=5, b=60))
        ref = svc.sql(TPL.format(a=5, b=60), label="ref").to_pylist()
        FAULTS.arm(FaultSpec(point="jax.execute", times=1))
        # first dispatch eats the fault, the requeued dispatch completes:
        # the client never sees the transient failure
        out = svc.sql(TPL.format(a=5, b=60), label="retried")
        assert out.to_pylist() == ref
    delta = METRICS.delta(before)
    assert delta.get("retry_budget_spent", 0) == 1
    assert delta.get("fault_point_firings", 0) == 1


def test_retry_budget_exhausted_fails_typed(data):
    session = make_session(data)
    cfg = ServiceConfig(batching=False, retry_budget=1, ticket_attempts=3)
    with QueryService(session, cfg) as svc:
        warm(svc, TPL.format(a=5, b=60))
        FAULTS.arm(FaultSpec(point="jax.execute", times=5))
        with pytest.raises(FaultError):
            svc.sql(TPL.format(a=5, b=60), label="doomed")


# -- program quarantine -------------------------------------------------------

def test_quarantine_evicts_and_rerecords(data):
    session = make_session(data)
    cfg = ServiceConfig(batching=False, breaker=None)
    sql = TPL.format(a=7, b=55)
    before = METRICS.snapshot()
    FLIGHT.configure(enabled=True, clear=True)
    FLIGHT.dump_dir = None
    try:
        with QueryService(session, cfg) as svc:
            warm(svc, sql)
            ref = svc.sql(sql, label="ref").to_pylist()
            fps = [fp for fp in jexec_mod._SHARED_PROGRAMS]
            assert len(fps) == 1
            fp = fps[0]
            FAULTS.arm(FaultSpec(point="jax.execute",
                                 times=jexec_mod.QUARANTINE_STRIKES))
            for i in range(jexec_mod.QUARANTINE_STRIKES):
                with pytest.raises(FaultError):
                    svc.sql(sql, label=f"strike{i}")
            # third strike quarantined the entry: shared cache evicted
            assert fp not in jexec_mod._SHARED_PROGRAMS
            # ... and the next use re-records fresh and re-publishes,
            # bit-identical (fault spec exhausted)
            out = svc.sql(sql, label="after")
            assert out.to_pylist() == ref
            assert fp in jexec_mod._SHARED_PROGRAMS
    finally:
        FLIGHT.configure(enabled=False, clear=False)
    delta = METRICS.delta(before)
    assert delta.get("quarantined_programs", 0) == 1
    quar = [e for e in FLIGHT.events() if e["event"] == "quarantine"]
    assert len(quar) == 1
    assert quar[0]["fp"] == fp[:12]
    assert delta.get("program_cache_misses", 0) >= 2  # warm + re-record


def test_quarantine_strikes_reset_on_success(data):
    session = make_session(data)
    sql = TPL.format(a=9, b=52)
    with QueryService(session, ServiceConfig(batching=False)) as svc:
        warm(svc, sql)
        fp = next(iter(jexec_mod._SHARED_PROGRAMS))
        for _ in range(jexec_mod.QUARANTINE_STRIKES - 1):
            FAULTS.arm(FaultSpec(point="jax.execute", times=1))
            with pytest.raises(FaultError):
                svc.sql(sql, label="strike")
        # a healthy run absolves the accumulated strikes...
        svc.sql(sql, label="healthy")
        # ...so one more failure does NOT quarantine
        FAULTS.arm(FaultSpec(point="jax.execute", times=1))
        with pytest.raises(FaultError):
            svc.sql(sql, label="late_strike")
        assert fp in jexec_mod._SHARED_PROGRAMS


# -- device-lane watchdog -----------------------------------------------------

def test_watchdog_abandons_wedged_lane_neighbors_complete(data):
    from nds_tpu.resilience import _drain_abandoned

    session = make_session(data)
    # warm OUTSIDE the watchdog service: the first sighting compiles, and
    # a compile must never be mistaken for a wedge on a loaded host
    session.sql(TPL.format(a=5, b=60), label="warm")
    session.sql(TPL.format(a=5, b=60), label="warm")
    ref = session.sql(TPL.format(a=6, b=61), label="ref").to_pylist()
    session.sql(TPL.format(a=6, b=61), label="warm")
    cfg = ServiceConfig(batching=False, dispatch_timeout_s=1.5)
    FLIGHT.configure(enabled=True, clear=True)
    FLIGHT.dump_dir = None
    try:
        with QueryService(session, cfg) as svc:
            # the wedge: a hang only the watchdog can end (it raises on
            # wake, so the abandoned zombie dies cleanly at drain below)
            FAULTS.arm(FaultSpec(point="jax.execute", action="hang",
                                 seconds=4.0, times=1))
            with svc.hold_dispatch():
                t_hang = svc.submit(TPL.format(a=5, b=60), label="hang")
                neighbors = [svc.submit(TPL.format(a=6, b=61),
                                        label=f"n{i}") for i in range(2)]
                deadline = time.time() + 10
                while time.time() < deadline:
                    with svc._cv:
                        if len(svc._ready) >= 3:
                            break
                    time.sleep(0.01)
            with pytest.raises(DeadlineExceeded):
                t_hang.result(timeout=30)
            # the lane was NOT wedged behind the zombie: neighbors
            # complete promptly and bit-identical
            for t in neighbors:
                assert t.result(timeout=30).to_pylist() == ref
    finally:
        FLIGHT.configure(enabled=False, clear=False)
    trips = [e for e in FLIGHT.events()
             if e["event"] == "trip" and e.get("reason") == "lane_watchdog"]
    assert len(trips) == 1
    _drain_abandoned(10.0)      # join the woken zombie deterministically


# -- fault registry: thread-safety + determinism ------------------------------

def test_fault_registry_hammering():
    """Arm/disarm/configure/fire from many threads at once: no internal
    corruption, every raise is FaultError, fired counts stay consistent
    with the times caps."""
    stop = threading.Event()
    errors: list = []

    def arm_disarm():
        while not stop.is_set():
            s = FAULTS.arm(FaultSpec(point="query.run", times=2))
            FAULTS.would_raise("query.run", "x")
            FAULTS.disarm(s)

    def reconfigure():
        while not stop.is_set():
            FAULTS.configure(["device.put:delay:0.0@0.5"])
            FAULTS.configure([])

    def fire():
        while not stop.is_set():
            try:
                FAULTS.fire("query.run", "x")
                FAULTS.fire("device.put")
            except FaultError:
                pass
            except BaseException as e:   # anything else = corruption
                errors.append(e)
                return

    threads = [threading.Thread(target=f) for f in
               (arm_disarm, arm_disarm, reconfigure, fire, fire, fire)]
    for t in threads:
        t.start()
    time.sleep(1.0)
    stop.set()
    for t in threads:
        t.join(timeout=10)
        assert not t.is_alive()
    assert not errors, errors
    FAULTS.clear()
    assert FAULTS.specs() == []


def test_unknown_fault_point_rejected_everywhere():
    """A typo'd point must fail loudly at arm/spec time — a campaign
    arming a point no engine layer fires would otherwise 'pass' as a
    silent no-op (found by a verify probe)."""
    with pytest.raises(ValueError, match="unknown fault point"):
        FAULTS.arm(FaultSpec(point="bogus.point"))
    with pytest.raises(ValueError, match="unknown fault point"):
        CampaignSpec(points=("jax.execute", "bogus.point"))


def test_fault_spec_rng_deterministic_per_arm_order():
    FAULTS.clear()
    s1 = FAULTS.arm(FaultSpec(point="query.run", probability=0.5))
    draws1 = [s1.rng.random() for _ in range(8)]
    FAULTS.clear()
    s2 = FAULTS.arm(FaultSpec(point="query.run", probability=0.5))
    draws2 = [s2.rng.random() for _ in range(8)]
    assert draws1 == draws2     # same seed + arm order -> same stream


# -- flight-dump format pins (trace_report) -----------------------------------

def _trace_report(path, capsys):
    sys_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts")
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "trace_report", os.path.join(sys_path, "trace_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.main([path])
    return capsys.readouterr().out


def test_trace_report_old_flight_format_pinned(tmp_path, capsys):
    """A PR 11-era dump (no self-healing events) summarizes exactly as
    before: per-event counts, tenant rollup, slowest tickets — and no
    self-healing section appears."""
    import json as _json
    path = str(tmp_path / "old.jsonl")
    events = [
        {"seq": 1, "t_ms": 0.1, "event": "admit", "label": "q1",
         "tenant": "t0", "depth": 1},
        {"seq": 2, "t_ms": 0.9, "event": "complete", "label": "q1",
         "tenant": "t0", "latency_ms": 12.5},
        {"seq": 3, "t_ms": 1.2, "event": "reject", "label": "q2",
         "tenant": "t0", "reason": "queue_full"},
        {"seq": 4, "t_ms": 1.5, "event": "fault", "point": "device.put",
         "actions": ["raise"]},
    ]
    with open(path, "w") as f:
        for e in events:
            f.write(_json.dumps(e) + "\n")
    out = _trace_report(path, capsys)
    assert "flight recorder: 4 events" in out
    assert "complete" in out and "reject" in out and "fault" in out
    assert "t0" in out and "12.5" in out
    assert "self-healing" not in out and "lifecycle phases" not in out


def test_trace_report_new_flight_vocabulary(tmp_path, capsys):
    import json as _json
    path = str(tmp_path / "new.jsonl")
    events = [
        {"seq": 1, "t_ms": 0.1, "event": "trip", "reason": "circuit:FaultError",
         "error_class": "FaultError", "dumped": True},
        {"seq": 2, "t_ms": 0.5, "event": "probe", "error_class": "FaultError"},
        {"seq": 3, "t_ms": 0.9, "event": "probe", "error_class": "FaultError",
         "outcome": "closed"},
        {"seq": 4, "t_ms": 1.1, "event": "quarantine", "fp": "abcdef123456",
         "strikes": 3, "reason": "ReplayMismatch"},
        {"seq": 5, "t_ms": 1.8, "event": "lifecycle_phase",
         "phase": "power", "status": "done", "elapsed_s": 4.2},
    ]
    with open(path, "w") as f:
        for e in events:
            f.write(_json.dumps(e) + "\n")
    out = _trace_report(path, capsys)
    assert "self-healing:" in out
    assert "circuit:FaultError" in out
    assert "FaultError/closed" in out
    assert "quarantine fp=abcdef123456" in out
    assert "lifecycle phases:" in out and "power" in out


# -- seeded campaigns ---------------------------------------------------------

def test_campaign_plan_pure_function_of_seed():
    spec = CampaignSpec(seed=1234, clients=4, queries_per_client=5)
    p1 = build_plan(spec)
    p2 = build_plan(spec)
    assert [(w.at_fraction, w.specs) for w in p1] == \
        [(w.at_fraction, w.specs) for w in p2]
    w1 = build_workload(spec, [("a", "A"), ("b", "B"), ("c", "C")])
    w2 = build_workload(spec, [("a", "A"), ("b", "B"), ("c", "C")])
    assert w1 == w2
    other = build_plan(CampaignSpec(seed=4321))
    assert [(w.at_fraction, w.specs) for w in p1] != \
        [(w.at_fraction, w.specs) for w in other]


def test_campaign_deterministic_firing_and_flight_sequence(tmp_path):
    """Same seed -> same firing schedule -> same flight fault-event
    sequence. One client + in-core-only pool keeps the event ORDER
    deterministic (every fire site runs on the lane/client threads in
    submission order)."""
    pool = [(f"q#{i}", TPL.format(a=5 + i, b=60 + i)) for i in range(3)]
    spec = CampaignSpec(seed=99, clients=1, queries_per_client=4,
                        points=("jax.execute", "query.run",
                                "stream.spawn"),
                        times_per_point=2, dump_dir=None, breaker=False,
                        retry_budget=0)

    def one_run():
        jexec_mod.clear_shared_programs()
        rng = np.random.default_rng(3)
        s = Session(EngineConfig())
        s.register_arrow("fact", pa.table({
            "fk": pa.array(rng.integers(0, N_DIM, N_FACT),
                           type=pa.int64()),
            "qty": pa.array(rng.integers(1, 100, N_FACT),
                            type=pa.int64())}))
        s.register_arrow("dim", pa.table({
            "dk": pa.array(np.arange(N_DIM), type=pa.int64()),
            "grp": pa.array((np.arange(N_DIM) % 7).astype(np.int64))}))
        return ChaosCampaign(spec, pool).run(s)

    r1 = one_run()
    r2 = one_run()
    assert r1["fired"] == r2["fired"]
    assert r1["fault_events"] == r2["fault_events"]
    assert r1["firings"] == r2["firings"] > 0
    assert r1["invariants"]["all_failures_typed"]
    assert r1["invariants"]["completed_hash_identical"]


def test_campaign_small_all_points(tmp_path):
    """The CI-sized campaign: ~8 concurrent clients, all six fault points
    armed with the self-healing service machinery on — 0 untyped
    failures, 0 hash mismatches, a flight dump per firing."""
    dump_dir = str(tmp_path / "flight")
    spec = CampaignSpec(seed=0xD1CE, clients=8, queries_per_client=4,
                        times_per_point=1, dump_dir=dump_dir,
                        retry_budget=32)
    session = build_demo_session(str(tmp_path))
    rec = ChaosCampaign(spec, demo_pool()).run(session)
    inv = rec["invariants"]
    assert inv["all_failures_typed"], rec["phases"]["armed"]
    assert inv["completed_hash_identical"], rec["phases"]["armed"]
    assert inv["flight_dump_per_firing"]
    assert rec["firings"] > 0
    assert rec["flight_dumps"] >= rec["firings"]
    assert os.path.isdir(dump_dir) and os.listdir(dump_dir)
    # recovery happened (the exact ratio is a quiet-host artifact claim,
    # not a 1-core CI assertion: completion is the functional bar)
    assert rec["phases"]["recovery"]["completed"] == \
        rec["phases"]["recovery"]["queries"]


def test_campaign_with_result_cache_armed(tmp_path):
    """The semantic result cache under fire: a campaign with the cache
    armed must stay clean — fault firings never serve a stale or torn
    cached result (every completed response, cached tier included, is
    hash-identical to the fault-free baseline in ALL THREE phases), and
    the cache actually served traffic (hits > 0, so the invariant was
    exercised, not vacuous)."""
    from nds_tpu.engine.result_cache import ResultCacheConfig

    spec = CampaignSpec(seed=0xCAC4E, clients=6, queries_per_client=4,
                        times_per_point=1, dump_dir=None,
                        retry_budget=32)
    session = build_demo_session(str(tmp_path))
    cfg = ServiceConfig(
        max_pending=max(256, 4 * spec.clients),
        breaker=CircuitBreakerConfig(open_s=spec.breaker_open_s,
                                     min_failures=spec.breaker_min_failures),
        retry_budget=spec.retry_budget,
        ticket_attempts=spec.ticket_attempts,
        result_cache=ResultCacheConfig(subsumption=True))
    before = METRICS.snapshot()
    rec = ChaosCampaign(spec, demo_pool()).run(session,
                                               service_config=cfg)
    delta = METRICS.delta(before)
    inv = rec["invariants"]
    assert inv["all_failures_typed"], rec["phases"]["armed"]
    assert inv["completed_hash_identical"], rec["phases"]["armed"]
    assert delta.get("result_cache_hits", 0) > 0, delta
    # a cached response can never be torn by a fault mid-serve: entries
    # are stored only from COMPLETED executions, so with zero untyped
    # escapes the armed phase's completions all hashed clean above
    assert rec["phases"]["recovery"]["completed"] == \
        rec["phases"]["recovery"]["queries"]
