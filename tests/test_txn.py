"""Transactional warehouse (nds_tpu/warehouse.py snapshot log):
crash-consistent manifest writes, atomic multi-table commits, snapshot-
pinned reads, recovery, and the chaos-mid-DML campaign.

The contract under test is the headline invariant of the PR: a reader
NEVER observes a torn manifest or a cross-table blend of two warehouse
versions, and a kill at any point of a commit recovers — on the next
warehouse open — to exactly the pre-commit or post-commit snapshot,
never anything in between.
"""
import hashlib
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.config import EngineConfig
from nds_tpu.engine import ResultCache, ResultCacheConfig, Session
from nds_tpu.engine.arrow_bridge import to_arrow
from nds_tpu.obs.metrics import METRICS
from nds_tpu.resilience import FAULTS, FaultError, FaultSpec
from nds_tpu.warehouse import Warehouse, _atomic_write_json

N_DIM = 20

JOIN_Q = ("SELECT grp, COUNT(*) AS n, SUM(qty) AS tq FROM fact "
          "JOIN dim ON fk = dk GROUP BY grp ORDER BY grp")


def _fact(n, seed):
    rng = np.random.default_rng(seed)
    return pa.table({
        "fk": pa.array(rng.integers(0, N_DIM, n), type=pa.int64()),
        "qty": pa.array(rng.integers(1, 100, n), type=pa.int64()),
    })


def _dim(extra_groups=0):
    n = N_DIM
    return pa.table({
        "dk": pa.array(np.arange(n), type=pa.int64()),
        "grp": pa.array((np.arange(n) % (3 + extra_groups))
                        .astype(np.int64)),
    })


def _hash(table) -> str:
    return hashlib.sha1(repr(table.to_pylist()).encode()).hexdigest()


def _rows(table) -> list[dict]:
    return to_arrow(table).to_pylist()


def _seeded(tmp_path, committer="seed"):
    """A two-table warehouse at published version 1."""
    wh = Warehouse(str(tmp_path / "wh"))
    with wh.transaction(committer=committer):
        wh.table("fact").create(_fact(800, 1), partition=False)
        wh.table("dim").create(_dim(), partition=False)
    return wh


def _stage(session):
    session.register_arrow("stage", _fact(120, 7))


# -- satellite 1: crash-consistent manifest writes ----------------------------

def test_manifest_torn_read_hunt(tmp_path):
    """Rapid commits vs 8 concurrent readers: under the atomic-rename
    protocol no reader ever parses a half-written manifest (the PR 12
    bounded re-read workaround is GONE — a decode failure now raises)."""
    wh = Warehouse(str(tmp_path / "wh"))
    wt = wh.table("t")
    wt.create(_fact(50, 2), partition=False)
    stop = threading.Event()
    errors: list = []
    versions: list = []

    def reader():
        last = 0
        while not stop.is_set():
            try:
                doc = wt._load_doc()
                n = len(doc["snapshots"])
            except Exception as e:       # torn read => fails the hunt
                errors.append(f"{type(e).__name__}: {e}")
                return
            if n < last:
                errors.append(f"snapshot count went backwards "
                              f"{last}->{n}")
                return
            last = n
            versions.append(n)

    threads = [threading.Thread(target=reader, daemon=True)
               for _ in range(8)]
    for t in threads:
        t.start()
    for i in range(30):
        wt.insert(_fact(10, 10 + i))
    stop.set()
    for t in threads:
        t.join()
    assert not errors
    assert versions and max(versions) <= 31
    assert wt.manifest_version() == 31  # create + 30 inserts all landed


def test_stray_tmp_files_invisible_to_readers(tmp_path):
    """A half-written temp file (a crash between write and rename) is
    never part of the manifest contract: readers ignore it, the next
    atomic write leaves no temp files behind."""
    wh = Warehouse(str(tmp_path / "wh"))
    wt = wh.table("t")
    wt.create(_fact(50, 3), partition=False)
    files = wt.current_files()
    # a crashed writer's leftovers: garbage JSON under the tmp pattern
    junk = wt.manifest_path + ".deadbeef.tmp"
    with open(junk, "w") as f:
        f.write('{"snapshots": [{"version"')     # torn mid-key
    assert wt.current_files() == files           # readers never look
    assert wt.manifest_version() == 1
    wt.insert(_fact(10, 4))
    assert wt.manifest_version() == 2
    leftover = [p for p in os.listdir(os.path.dirname(wt.manifest_path))
                if p.endswith(".tmp") and p != os.path.basename(junk)]
    assert leftover == []                        # rename consumed ours


def test_corrupt_manifest_fails_loudly(tmp_path):
    """Real corruption (not a torn in-flight write) names the file."""
    wh = Warehouse(str(tmp_path / "wh"))
    wt = wh.table("t")
    wt.create(_fact(20, 5), partition=False)
    with open(wt.manifest_path, "w") as f:
        f.write('{"snapshots": [{')
    with pytest.raises(RuntimeError, match="corrupt warehouse manifest"):
        wt.current_files()


# -- the snapshot log: atomic cross-table commits -----------------------------

def test_transaction_publishes_one_version(tmp_path):
    before = METRICS.snapshot()
    wh = _seeded(tmp_path)
    assert wh.current_version() == 1
    assert wh.versions() == [1]
    rec = wh.version_record(1)
    assert rec["committer"] == "seed"
    assert rec["tables"] == {"fact": 1, "dim": 1}
    with wh.transaction(committer="round2"):
        wh.table("fact").insert(_fact(100, 9))
    assert wh.current_version() == 2
    assert wh.version_record(2)["tables"] == {"fact": 2, "dim": 1}
    d = METRICS.delta(before)
    assert d.get("txn_commits") == 2
    assert not d.get("txn_rollbacks") and not d.get("txn_recoveries")


def test_transaction_rolls_back_on_error(tmp_path):
    wh = _seeded(tmp_path)
    before = METRICS.snapshot()
    with pytest.raises(ValueError, match="boom"):
        with wh.transaction(committer="bad"):
            wh.table("fact").insert(_fact(100, 9))
            wh.table("dim").insert(_dim(2))
            raise ValueError("boom")
    # both manifests truncated to base; nothing published; intent gone
    assert wh.current_version() == 1
    assert wh.table("fact").manifest_version() == 1
    assert wh.table("dim").manifest_version() == 1
    assert not [p for p in os.listdir(wh.snapshots_dir)
                if p.endswith(".inprogress.json")]
    d = METRICS.delta(before)
    assert d.get("txn_rollbacks") == 1 and not d.get("txn_commits")


def test_mid_commit_fault_aborts_atomically(tmp_path):
    """txn.between_tables fires as the SECOND table's write begins —
    the first table's already-landed manifest append rolls back."""
    wh = _seeded(tmp_path)
    spec = FAULTS.arm(FaultSpec(point="txn.between_tables",
                                action="raise", times=1))
    try:
        with pytest.raises(FaultError):
            with wh.transaction(committer="killed"):
                wh.table("fact").insert(_fact(100, 11))
                wh.table("dim").insert(_dim(2))
    finally:
        FAULTS.disarm(spec)
    assert spec.fired == 1
    assert wh.current_version() == 1
    assert wh.table("fact").manifest_version() == 1
    assert wh.table("dim").manifest_version() == 1


def test_recovery_discards_dead_writers_partial_commit(tmp_path):
    """A crash mid-transaction (intent record present, writer pid dead):
    the next Warehouse open truncates every table to max(base,
    published) and retires the record."""
    wh = _seeded(tmp_path)
    # simulate the crash: a manifest append past the base with a dead
    # writer's intent record (sleep 0 has exited; its pid is free)
    wh.table("fact").insert(_fact(60, 13))
    proc = subprocess.Popen(["sleep", "0"])
    proc.wait()
    _atomic_write_json(
        os.path.join(wh.snapshots_dir, "txn-deadbeef.inprogress.json"),
        {"txn": "deadbeef", "committer": "crashed", "pid": proc.pid,
         "started_ms": 0, "base": {"fact": 1, "dim": 1}})
    # an orphaned version record past CURRENT (kill between the record
    # write and the CURRENT swing) is also swept
    _atomic_write_json(os.path.join(wh.snapshots_dir, "v9.json"),
                       {"version": 9, "timestamp_ms": 0, "committer": "x",
                        "tables": {"fact": 2, "dim": 1}})
    before = METRICS.snapshot()
    wh2 = Warehouse(wh.root)
    assert METRICS.delta(before).get("txn_recoveries") == 1
    assert wh2.current_version() == 1
    assert wh2.table("fact").manifest_version() == 1
    assert wh2.versions() == [1]
    assert not os.path.exists(
        os.path.join(wh2.snapshots_dir, "v9.json"))
    assert not [p for p in os.listdir(wh2.snapshots_dir)
                if p.endswith(".inprogress.json")]


def test_recovery_skips_live_writer(tmp_path):
    """A verifier opening the warehouse MID-transaction (same process,
    writer alive) must not roll back the open transaction's work."""
    wh = _seeded(tmp_path)
    with wh.transaction(committer="open"):
        wh.table("fact").insert(_fact(60, 17))
        wh2 = Warehouse(wh.root)        # concurrent open: recovery runs
        assert wh2.table("fact").manifest_version() == 2   # untouched
    assert wh.current_version() == 2    # commit landed normally


# -- snapshot-pinned reads ----------------------------------------------------

def test_reader_pins_published_version_writer_reads_own_writes(tmp_path):
    wh = _seeded(tmp_path)
    writer = Session(EngineConfig())
    writer.attach_warehouse(wh)
    _stage(writer)
    reader = Session(EngineConfig())
    reader.attach_warehouse(Warehouse(wh.root))
    assert reader.warehouse_version() == 1
    assert reader.table_snapshot_version("fact") == 1
    h1 = _hash(reader.sql(JOIN_Q))
    with wh.transaction(committer="dml"):
        writer.execute("INSERT INTO fact SELECT fk, qty FROM stage")
        # read-your-writes: the writer sees its uncommitted insert...
        n = writer.sql("SELECT COUNT(*) AS n FROM fact")
        assert _rows(n)[0]["n"] == 920
        # ...while a refreshed reader still resolves the published v1
        reader.refresh_warehouse()
        assert reader.warehouse_version() == 1
        assert _hash(reader.sql(JOIN_Q)) == h1
    writer.refresh_warehouse()
    assert writer.warehouse_version() == 2
    reader.refresh_warehouse()
    assert reader.warehouse_version() == 2
    assert _hash(reader.sql(JOIN_Q)) != h1
    assert _hash(reader.sql(JOIN_Q)) == _hash(writer.sql(JOIN_Q))


def test_as_of_time_travel_and_version_rollback(tmp_path):
    wh = _seeded(tmp_path)
    s1 = Session(EngineConfig())
    s1.attach_warehouse(wh)
    _stage(s1)
    h_v1 = _hash(s1.sql(JOIN_Q))
    with wh.transaction(committer="dml"):
        s1.execute("INSERT INTO fact SELECT fk, qty FROM stage")
    # AS OF: a fresh session pinned to the OLD version reproduces it
    old = Session(EngineConfig())
    old.attach_warehouse(Warehouse(wh.root), at_version=1)
    assert old.warehouse_version() == 1
    assert _hash(old.sql(JOIN_Q)) == h_v1
    # warehouse-level rollback: history grows, state returns
    new_version = wh.rollback_to_version(1)
    assert new_version == 3
    back = Session(EngineConfig())
    back.attach_warehouse(Warehouse(wh.root))
    assert _hash(back.sql(JOIN_Q)) == h_v1


def test_rollback_cli_list_and_version(tmp_path, capsys):
    from nds_tpu import rollback as rb
    wh = _seeded(tmp_path)
    s = Session(EngineConfig())
    s.attach_warehouse(wh)
    _stage(s)
    with wh.transaction(committer="dml0"):
        s.execute("INSERT INTO fact SELECT fk, qty FROM stage")
    assert rb.main([wh.root, "--list"]) == 0
    out = capsys.readouterr().out
    assert "* v2" in out and "committer=dml0" in out
    assert "fact@2" in out and "dim@1" in out
    assert rb.main([wh.root, "--version", "1"]) == 0
    out = capsys.readouterr().out
    assert "rolled back to version 1" in out
    assert Warehouse(wh.root).current_version() == 3
    with pytest.raises(SystemExit):     # neither timestamp nor mode flag
        rb.main([wh.root])


def test_result_cache_entry_provably_from_pinned_snapshot(tmp_path):
    """A cached result stays valid exactly while the session stays on
    the snapshot it was computed against: the published head moving does
    NOT invalidate it (the pin is the proof), refreshing onto the new
    version does."""
    wh = _seeded(tmp_path)
    reader = Session(EngineConfig())
    reader.attach_warehouse(Warehouse(wh.root))
    cache = ResultCache(reader, ResultCacheConfig())
    reader.attach_result_cache(cache)
    before = METRICS.snapshot()
    r1 = cache.run(JOIN_Q)
    r2 = cache.run(JOIN_Q)
    assert r2 is r1
    writer = Session(EngineConfig())
    writer.attach_warehouse(wh)
    _stage(writer)
    with wh.transaction(committer="dml"):
        writer.execute("INSERT INTO fact SELECT fk, qty FROM stage")
    # head moved; the reader is still pinned to v1 -> still a hit
    r3 = cache.run(JOIN_Q)
    assert r3 is r1
    reader.refresh_warehouse()          # now on v2: entry must not serve
    r4 = cache.run(JOIN_Q)
    assert r4 is not r1
    assert r4.to_pylist() != r1.to_pylist()   # the insert changed the join
    d = METRICS.delta(before)
    assert d.get("result_cache_hits") == 2
    assert d.get("result_cache_misses") == 2


def test_transactions_disabled_is_bit_identical_legacy(tmp_path):
    """warehouse_transactions=False: no _snapshots directory is ever
    created, no pinning, no counter moves — the legacy non-transactional
    warehouse byte-for-byte."""
    before = METRICS.snapshot()
    wh = Warehouse(str(tmp_path / "wh"))
    wh.table("fact").create(_fact(800, 1), partition=False)
    wh.table("dim").create(_dim(), partition=False)
    s = Session(EngineConfig(warehouse_transactions=False))
    s.attach_warehouse(wh)
    _stage(s)
    h0 = _hash(s.sql(JOIN_Q))
    s.execute("INSERT INTO fact SELECT fk, qty FROM stage")
    assert _hash(s.sql(JOIN_Q)) != h0
    assert s.warehouse_version() is None
    assert s.table_snapshot_version("fact") is None
    assert not os.path.isdir(wh.snapshots_dir)
    d = METRICS.delta(before)
    for k in ("txn_commits", "txn_rollbacks", "txn_recoveries"):
        assert not d.get(k), k
    # and with the flag ON but no snapshot log: same legacy behavior
    s2 = Session(EngineConfig())
    s2.attach_warehouse(Warehouse(wh.root))
    assert s2.warehouse_version() is None
    assert not os.path.isdir(wh.snapshots_dir)


# -- system.snapshots + glossary ----------------------------------------------

def test_system_snapshots_table_and_glossary(tmp_path):
    wh = _seeded(tmp_path)
    s = Session(EngineConfig())
    s.attach_warehouse(wh)
    _stage(s)
    with wh.transaction(committer="dml0"):
        s.execute("INSERT INTO fact SELECT fk, qty FROM stage")
    s.refresh_warehouse()
    rows = _rows(s.sql("SELECT version, committer, table_count, current, "
                       "pinned FROM system.snapshots ORDER BY version"))
    assert rows == [
        {"version": 1, "committer": "seed", "table_count": 2,
         "current": False, "pinned": False},
        {"version": 2, "committer": "dml0", "table_count": 2,
         "current": True, "pinned": True},
    ]
    # AS OF session: pinned marks the time-traveled version
    old = Session(EngineConfig())
    old.attach_warehouse(Warehouse(wh.root), at_version=1)
    rows = _rows(old.sql("SELECT version, pinned FROM system.snapshots "
                         "ORDER BY version"))
    assert rows == [{"version": 1, "pinned": True},
                    {"version": 2, "pinned": False}]
    glossary = METRICS.describe()
    for k in ("txn_commits", "txn_rollbacks", "txn_recoveries"):
        assert k in glossary and glossary[k]


# -- concurrency hunts --------------------------------------------------------

def test_eight_thread_snapshot_consistency_direct(tmp_path):
    """8 reader threads through one pinned Session while a writer
    commits two-table transactions: every observed hash equals SOME
    published version replayed whole — never a cross-table blend."""
    wh = _seeded(tmp_path)
    writer = Session(EngineConfig())
    writer.attach_warehouse(wh)
    _stage(writer)
    reader = Session(EngineConfig())
    reader.attach_warehouse(Warehouse(wh.root))
    stop = threading.Event()
    seen: set = set()
    errors: list = []

    def read_loop():
        while not stop.is_set():
            try:
                h = _hash(reader.sql(JOIN_Q))
            except Exception as e:
                errors.append(f"{type(e).__name__}: {e}")
                return
            with lock:
                seen.add(h)

    lock = threading.Lock()
    threads = [threading.Thread(target=read_loop, daemon=True)
               for _ in range(8)]
    for t in threads:
        t.start()
    for i in range(4):
        with wh.transaction(committer=f"dml{i}"):
            writer.execute("INSERT INTO fact SELECT fk, qty FROM stage"
                           f" WHERE qty <= {30 + 15 * i}")
            writer.execute("INSERT INTO fact SELECT fk, qty FROM stage"
                           f" WHERE qty > {92 - i}")
        writer.refresh_warehouse()
        reader.refresh_warehouse()
        time.sleep(0.05)
    stop.set()
    for t in threads:
        t.join()
    assert not errors
    allowed = set()
    for v in Warehouse(wh.root).versions():
        s = Session(EngineConfig())
        s.attach_warehouse(Warehouse(wh.root), at_version=v)
        allowed.add(_hash(s.sql(JOIN_Q)))
    assert seen and seen <= allowed


def test_txn_chaos_campaign_live_service(tmp_path):
    """The seeded transactional campaign through a LIVE QueryService:
    commit-path faults kill transactions mid-flight under concurrent
    client traffic; all campaign invariants must hold."""
    from nds_tpu.chaos import TXN_POINTS, CampaignSpec, run_txn_campaign

    spec = CampaignSpec(seed=11, clients=2, queries_per_client=2,
                        points=TXN_POINTS, actions=("raise",),
                        times_per_point=1, pulse_at=0.0)
    rec = run_txn_campaign(spec, str(tmp_path), dml_rounds=4)
    assert rec["invariants"] == {
        "all_failures_typed": True,
        "snapshot_consistent_reads": True,
        "no_torn_manifest_reads": True,
        "dml_progress": True,
    }
    assert rec["dml"]["commits"] >= 1
    assert rec["dml"]["aborts"] >= 1        # the armed points did abort
    assert rec["txn_metrics"]["txn_rollbacks"] >= 1
    assert rec["current_version"] == rec["warehouse_versions"][-1]
    # determinism: the armed plan is a pure function of the spec
    from nds_tpu.chaos import build_plan
    assert build_plan(spec) == build_plan(spec)


# -- SIGKILL mid-commit (the real crash) --------------------------------------

_CHILD = r"""
import os, sys, time
import numpy as np, pyarrow as pa
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from nds_tpu.warehouse import Warehouse
wh = Warehouse({root!r})
rows = pa.table({{
    "fk": pa.array(np.arange(50) % 20, type=pa.int64()),
    "qty": pa.array(np.arange(50) + 1, type=pa.int64()),
}})
txn = wh.transaction(committer="victim")
txn.__enter__()
wh.table("fact").insert(rows)       # table A landed, B untouched
with open({marker!r}, "w") as f:
    f.write("mid-commit")
time.sleep(120)                     # parent SIGKILLs us here
"""


@pytest.mark.slow
def test_sigkill_between_table_commits_recovers_exactly(tmp_path):
    """SIGKILL between table A's manifest append and the rest of the
    transaction: reopening the warehouse recovers to the EXACT
    pre-commit snapshot — file lists and query hashes equal."""
    wh = _seeded(tmp_path)
    pre_files = {n: wh.table(n).current_files()
                 for n in wh.table_names()}
    s = Session(EngineConfig())
    s.attach_warehouse(Warehouse(wh.root))
    pre_hash = _hash(s.sql(JOIN_Q))
    marker = str(tmp_path / "mid-commit")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child = subprocess.Popen(
        [sys.executable, "-c",
         _CHILD.format(repo=repo, root=wh.root, marker=marker)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    try:
        deadline = time.time() + 120
        while not os.path.exists(marker):
            assert child.poll() is None, child.stderr.read().decode()
            assert time.time() < deadline, "child never reached commit"
            time.sleep(0.05)
        child.send_signal(signal.SIGKILL)
        child.wait(timeout=30)
    finally:
        if child.poll() is None:
            child.kill()
    # the orphaned append is on disk (raw read — no recovery yet)...
    with open(os.path.join(wh.root, "fact", "manifest.json")) as f:
        assert len(json.load(f)["snapshots"]) == 2
    wh2 = Warehouse(wh.root)            # ...and recovery discards it
    assert wh2.current_version() == 1
    assert {n: wh2.table(n).current_files()
            for n in wh2.table_names()} == pre_files
    s2 = Session(EngineConfig())
    s2.attach_warehouse(wh2)
    assert _hash(s2.sql(JOIN_Q)) == pre_hash
    assert not [p for p in os.listdir(wh2.snapshots_dir)
                if p.endswith(".inprogress.json")]
