import pyarrow as pa
import pytest

from nds_tpu.schema import Kind, all_schemas, get_maintenance_schemas, get_schemas

EXPECTED_SOURCE_COLUMNS = {
    "customer_address": 13, "customer_demographics": 9, "date_dim": 28,
    "warehouse": 14, "ship_mode": 6, "time_dim": 10, "reason": 3,
    "income_band": 3, "item": 22, "store": 29, "call_center": 31,
    "customer": 18, "web_site": 26, "store_returns": 20,
    "household_demographics": 5, "web_page": 14, "promotion": 19,
    "catalog_page": 9, "inventory": 4, "catalog_returns": 27,
    "web_returns": 24, "web_sales": 34, "catalog_sales": 34, "store_sales": 23,
}

EXPECTED_MAINT_COLUMNS = {
    "s_purchase_lineitem": 8, "s_purchase": 8, "s_catalog_order": 8,
    "s_web_order": 8, "s_catalog_order_lineitem": 12, "s_web_order_lineitem": 11,
    "s_store_returns": 17, "s_catalog_returns": 20, "s_web_returns": 17,
    "s_inventory": 4, "delete": 2, "inventory_delete": 2,
}


def test_source_table_count():
    assert set(get_schemas().keys()) == set(EXPECTED_SOURCE_COLUMNS)


def test_maintenance_table_count():
    assert set(get_maintenance_schemas().keys()) == set(EXPECTED_MAINT_COLUMNS)


@pytest.mark.parametrize("table,ncols", sorted(EXPECTED_SOURCE_COLUMNS.items()))
def test_source_column_counts(table, ncols):
    assert len(get_schemas()[table].columns) == ncols


@pytest.mark.parametrize("table,ncols", sorted(EXPECTED_MAINT_COLUMNS.items()))
def test_maintenance_column_counts(table, ncols):
    assert len(get_maintenance_schemas()[table].columns) == ncols


def test_identifier_width_policy():
    """ss_ticket_number / sr_ticket_number are 64-bit; other SKs are 32-bit."""
    s = get_schemas()
    assert s["store_sales"].column("ss_ticket_number").ctype.kind == Kind.ID64
    assert s["store_returns"].column("sr_ticket_number").ctype.kind == Kind.ID64
    assert s["store_sales"].column("ss_item_sk").ctype.kind == Kind.ID
    arrow = s["store_sales"].arrow_schema()
    assert arrow.field("ss_ticket_number").type == pa.int64()
    assert arrow.field("ss_item_sk").type == pa.int32()


def test_decimal_toggle():
    s = get_schemas()["store_sales"]
    assert s.arrow_schema(True).field("ss_list_price").type == pa.decimal128(7, 2)
    assert s.arrow_schema(False).field("ss_list_price").type == pa.float64()


def test_not_null_flags():
    s = get_schemas()["customer_address"]
    assert not s.arrow_schema().field("ca_address_sk").nullable
    assert s.arrow_schema().field("ca_street_number").nullable


def test_all_schemas_merged():
    assert len(all_schemas()) == 36
