"""Datagen statistical fidelity (VERDICT r4 #10): quantify this
generator's output against the published TPC-DS scaling targets the
reference's dsdgen produces (spec v3.2 table sizes; the reference builds
the genuine toolkit, nds/tpcds-gen/Makefile).

Checked: (a) SF1 dimension row counts EXACTLY; (b) SF0.01 fact row counts
within tolerance of the spec's per-order line model; (c) NULL densities of
nullable FKs; (d) join-key referential selectivities; (e) the round-5
chronological-ticket contract. Known divergences stay documented in
native/datagen/gen.cpp's header."""
import os
import subprocess
import sys

import numpy as np
import pytest

# spec SF1 dimension targets (TPC-DS v3.2 scaling table)
DIM_SF1 = {
    "call_center": 6, "catalog_page": 11718, "customer": 100000,
    "customer_address": 50000, "customer_demographics": 1920800,
    "date_dim": 73049, "household_demographics": 7200, "income_band": 20,
    "item": 18000, "promotion": 300, "reason": 35, "ship_mode": 20,
    "store": 12, "time_dim": 86400, "warehouse": 5, "web_page": 60,
    "web_site": 30,
}
# spec SF1 fact targets; this generator's order model approximates them.
# inventory is excluded from the linear-scaling check: it is STEP-scaled
# (261 weeks x items/2 x warehouses — exactly the spec's 11,745,000 at
# SF1) and covered by test_inventory_model below.
FACT_SF1 = {"store_sales": 2880404, "catalog_sales": 1441548,
            "web_sales": 719384, "store_returns": 287514,
            "catalog_returns": 144067, "web_returns": 71763}


def _count_rows(d):
    if os.path.isfile(d + ".dat"):          # flat ndsdgen -table output
        with open(d + ".dat") as fh:
            return sum(1 for _ in fh)
    n = 0
    for f in os.listdir(d):
        with open(os.path.join(d, f)) as fh:
            n += sum(1 for _ in fh)
    return n


@pytest.fixture(scope="module")
def sf1_dims(tmp_path_factory):
    from nds_tpu.datagen import check_build
    binary = check_build()
    root = str(tmp_path_factory.mktemp("dims"))
    for t in DIM_SF1:
        subprocess.run([binary, "-scale", "1", "-dir", root, "-table", t],
                       check=True, timeout=600)
    return root


@pytest.fixture(scope="module")
def sf001(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("facts"))
    subprocess.run([sys.executable, "-m", "nds_tpu.datagen", "local", root,
                    "--scale", "0.01", "--parallel", "1"], check=True,
                   timeout=600)
    return root


@pytest.mark.parametrize("table,expected", sorted(DIM_SF1.items()))
def test_sf1_dimension_counts_exact(sf1_dims, table, expected):
    assert _count_rows(os.path.join(sf1_dims, table)) == expected


@pytest.mark.parametrize("table,sf1_rows", sorted(FACT_SF1.items()))
def test_fact_counts_track_spec(sf001, table, sf1_rows):
    """Fact rows at SF0.01 within 10% of spec_SF1/100 (the per-order line
    count is random with the spec's mean; returns are a 1-in-10 draw)."""
    got = _count_rows(os.path.join(sf001, table))
    want = sf1_rows * 0.01
    assert abs(got - want) / want < 0.10, f"{table}: {got} vs ~{want:.0f}"


def _col(root, table, idx, conv=int):
    out = []
    d = os.path.join(root, table)
    for f in os.listdir(d):
        for line in open(os.path.join(d, f)):
            p = line.rstrip("\n").split("|")[idx]
            out.append(None if p == "" else conv(p))
    return out


def test_null_density_of_nullable_fks(sf001):
    """Nullable FK columns carry ~4% NULLs (1/25), the generator's stated
    density — dsdgen's is column-specific but the same order of magnitude."""
    cust = _col(sf001, "store_sales", 3)          # ss_customer_sk
    frac = sum(v is None for v in cust) / len(cust)
    assert 0.02 < frac < 0.07, frac


def test_fk_referential_selectivity(sf001):
    """Every non-null ss_item_sk resolves to a real item row (selectivity
    1.0 — dsdgen's property for this key), and ss->sr ticket join
    selectivity is the 1-in-10 return draw."""
    items = _count_rows(os.path.join(sf001, "item"))
    ss_items = [v for v in _col(sf001, "store_sales", 2) if v is not None]
    assert min(ss_items) >= 1 and max(ss_items) <= items
    ss_t = _col(sf001, "store_sales", 9)
    sr_t = _col(sf001, "store_returns", 9)
    assert set(sr_t) <= set(ss_t), "every return references a sale ticket"
    # ROW-level return rate: the spec's ~10% (returns drawn per lineitem)
    ratio = len(sr_t) / len(ss_t)
    assert 0.05 < ratio < 0.15, ratio


def test_inventory_model(sf001):
    """inventory = 261 weeks x items/2 x warehouses (the spec's SF1 count
    11,745,000 = 261 x 9000 x 5 exactly; step-scaled below SF1)."""
    items = _count_rows(os.path.join(sf001, "item"))
    whs = _count_rows(os.path.join(sf001, "warehouse"))
    got = _count_rows(os.path.join(sf001, "inventory"))
    assert got == 261 * (items // 2) * whs


def test_chronological_tickets(sf001):
    """Round-5 contract: sold date is monotone in ticket number up to the
    +-3-day jitter (what makes file [min,max] stats prune ticket deletes)."""
    date = _col(sf001, "store_sales", 0)
    tick = _col(sf001, "store_sales", 9)
    pairs = sorted((t, d) for t, d in zip(tick, date)
                   if t is not None and d is not None)
    d = np.array([p[1] for p in pairs])
    run_max = np.maximum.accumulate(d)
    assert int((run_max - d).max()) <= 6          # jitter-bounded
