"""Projection pushdown (engine/colprune): structure and equivalence.

The reference relies on Spark Catalyst's ColumnPruning for this (its scans
read only referenced parquet columns); here the rewrite is explicit, so we
pin (a) scans narrow to referenced columns, (b) the root schema is exactly
preserved, (c) results are identical with the pass disabled, including on
shared-CTE and set-op plans, (d) shared CTE subtrees stay shared."""
import os

import pytest

from nds_tpu import datagen, streams
from nds_tpu.config import EngineConfig
from nds_tpu.engine import Session, arrow_bridge
from nds_tpu.engine.plan import JoinNode, ScanNode, iter_plan_nodes, walk
from nds_tpu.power import setup_tables


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("cp_data") / "d")
    datagen.generate_data_local(d, 0.001, parallel=2, overwrite=True)
    return d


def _session(data_dir):
    s = Session(EngineConfig())
    setup_tables(s, data_dir, "csv")
    return s


def _plan(session, sql):
    from nds_tpu.engine.planner import Planner
    from nds_tpu.sql import parse_sql
    return Planner(session._catalog()).plan_query(parse_sql(sql))


def test_scan_narrowed_and_root_schema_preserved(data_dir):
    s = _session(data_dir)
    sql = ("SELECT ss_store_sk, SUM(ss_ext_sales_price) AS total "
           "FROM store_sales WHERE ss_quantity > 10 "
           "GROUP BY ss_store_sk ORDER BY total DESC")
    plan = _plan(s, sql)
    scans = [n for n in iter_plan_nodes(plan) if isinstance(n, ScanNode)]
    assert scans and all(len(sc.columns) <= 3 for sc in scans), \
        [(sc.table, sc.columns) for sc in scans]
    assert plan.out_names == ["ss_store_sk", "total"]
    os.environ["NDS_TPU_NO_COLPRUNE"] = "1"
    try:
        full = _plan(s, sql)
    finally:
        del os.environ["NDS_TPU_NO_COLPRUNE"]
    assert plan.out_names == full.out_names
    assert plan.out_dtypes == full.out_dtypes


def test_join_width_shrinks(data_dir):
    s = _session(data_dir)
    sql = ("SELECT d_year, COUNT(*) AS c FROM store_sales, date_dim "
           "WHERE ss_sold_date_sk = d_date_sk GROUP BY d_year")
    plan = _plan(s, sql)
    joins = [n for n in iter_plan_nodes(plan) if isinstance(n, JoinNode)]
    assert joins and all(len(j.out_names) <= 4 for j in joins), \
        [(j.kind, len(j.out_names)) for j in joins]


def test_shared_cte_stays_shared(data_dir):
    s = _session(data_dir)
    sql = ("WITH x AS (SELECT ss_store_sk AS sk, ss_quantity AS q "
           "FROM store_sales) "
           "SELECT a.sk, COUNT(*) AS c FROM x a, x b "
           "WHERE a.sk = b.sk GROUP BY a.sk")
    plan = _plan(s, sql)
    # both consumers reference the SAME pruned CTE node (one materialization)
    segs = getattr(plan, "cte_segments", [])
    assert len(segs) == 1
    seg_node = segs[0][1]
    # walk() is identity-memoized (shared nodes yield once), so count
    # PARENT references instead of traversal visits
    count = sum(1 for n in iter_plan_nodes(plan)
                for f in ("child", "left", "right")
                if getattr(n, f, None) is seg_node)
    assert count >= 2
    assert sum(1 for n in walk(plan) if n is seg_node) == 1


# a spread of plan shapes: correlated scalar subquery (1), multi-channel CTE
# union (5), rollup+window (36), semi/anti (16), set op (38), fact-fact CTE
# self-join (95), wide 10-table join (72)
EQUIV_TEMPLATES = (1, 5, 16, 36, 38, 72, 95)


@pytest.mark.parametrize("number", EQUIV_TEMPLATES)
def test_pruned_equals_unpruned(data_dir, number):
    sql = streams.instantiate(number, stream=0, rngseed=2718)
    parts = (streams.split_special_query(f"query{number}", sql)
             if number in streams.SPECIAL_TEMPLATES
             else [(f"query{number}", sql)])
    pruned = _session(data_dir)
    os.environ["NDS_TPU_NO_COLPRUNE"] = "1"
    try:
        full = _session(data_dir)
        for name, part_sql in parts:
            del os.environ["NDS_TPU_NO_COLPRUNE"]
            try:
                a = arrow_bridge.to_arrow(pruned.sql(part_sql,
                                                     backend="numpy"))
            finally:
                os.environ["NDS_TPU_NO_COLPRUNE"] = "1"
            b = arrow_bridge.to_arrow(full.sql(part_sql, backend="numpy"))
            assert a.num_rows == b.num_rows, name
            assert a.equals(b), name
    finally:
        os.environ.pop("NDS_TPU_NO_COLPRUNE", None)


def test_union_all_under_countstar(data_dir):
    """A set-op whose output is entirely unneeded (COUNT(*) above) must
    normalize like _keep does — regression for a KeyError during rebuild
    when a branch pruned away column 0."""
    s = _session(data_dir)
    out = s.sql(
        "SELECT COUNT(*) AS n FROM ("
        " (SELECT i_item_sk AS a, i_manufact_id AS b FROM item"
        "  UNION ALL SELECT i_item_sk, i_manufact_id FROM item"
        "  ORDER BY 2 LIMIT 3)"
        " UNION ALL SELECT i_item_sk, i_manufact_id FROM item) x",
        backend="numpy")
    assert out.num_rows == 1


def test_empty_build_side_outer_join(data_dir):
    """take_with_null against a zero-row build side (q41 at tiny SF)."""
    s = _session(data_dir)
    out = s.sql(
        "SELECT i_item_sk, x.c FROM item LEFT JOIN "
        "(SELECT i_manufact_id AS m, COUNT(*) AS c FROM item "
        " WHERE i_item_sk < -5 GROUP BY i_manufact_id) x "
        "ON i_manufact_id = x.m WHERE i_item_sk <= 3", backend="numpy")
    assert out.num_rows > 0
    assert not out.columns[1].validity.any()
