"""Orchestrator tests: metric arithmetic, stream ranges, scraping, and a
tiny full-process bench run (reference nds/nds_bench.py behaviors)."""
import csv
import math
import os

import pytest

from nds_tpu import bench


def test_round_up_tenth():
    assert bench.round_up_tenth(1.01) == 1.1
    assert bench.round_up_tenth(1.10) == 1.1
    assert bench.round_up_tenth(0.001) == 0.1


def test_stream_ranges():
    assert bench.get_stream_range(9, 1) == [1, 2, 3, 4]
    assert bench.get_stream_range(9, 2) == [5, 6, 7, 8]
    assert bench.get_stream_range(3, 1) == [1]
    assert bench.get_stream_range(3, 2) == [2]
    with pytest.raises(ValueError):
        bench.get_stream_range(4, 1)


def test_perf_metric_formula():
    # SF=100, 9 streams (Sq=4), all phase times 1 hour in seconds
    got = bench.get_perf_metric(100, 9, 3600, 3600, 1800, 1800, 1800, 1800)
    t_ld = 0.01 * 4 * 1.0
    t_pt = 4.0
    t_tt = 1.0
    t_dm = 1.0
    want = math.floor(100 * (4 * 99) / (t_pt * t_tt * t_dm * t_ld) ** 0.25)
    assert got == want


# ~48 s — the single largest tier-1 item, and every phase it chains
# (datagen, transcode, streams, power, throughput, maintenance) has its
# own tier-1 coverage; the end-to-end chain runs in the full `test`
# CI stage. Keeps the tier-1 wall inside its 870 s budget.
@pytest.mark.slow
def test_full_bench_tiny(tmp_path):
    cfg = {
        "backend": "numpy",
        "report_dir": str(tmp_path / "report"),
        "sub_queries": ["query1", "query3", "query42"],
        "data_gen": {"scale_factor": 0.001, "parallel": 2,
                     "data_path": str(tmp_path / "data")},
        "load_test": {"warehouse_path": str(tmp_path / "wh"),
                      "format": "parquet"},
        "generate_query_stream": {"num_streams": 3,
                                  "stream_path": str(tmp_path / "streams")},
        "power_test": {},
        "throughput_test": {"mode": "thread"},
        "maintenance_test": {},
    }
    result = bench.run_full_bench(cfg)
    assert result["metric"] > 0
    for k in ("load", "power", "throughput1", "throughput2",
              "maintenance1", "maintenance2"):
        assert result[k] >= 0.1  # rounded up to 0.1s resolution

    metrics = tmp_path / "report" / "metrics.csv"
    assert metrics.exists()
    rows = {r[0]: r[1] for r in csv.reader(open(metrics))}
    assert rows["Sq"] == "1"
    assert float(rows["perf_metric"]) == result["metric"]

    # skip-flag resume: rerun with every phase skipped, scraping only
    for section in ("data_gen", "load_test", "generate_query_stream",
                    "power_test", "throughput_test", "maintenance_test"):
        cfg[section]["skip"] = True
    again = bench.run_full_bench(cfg)
    assert again["metric"] == result["metric"]
