"""Test configuration: force an 8-device virtual CPU mesh before jax import.

Mirrors the reference's multi-node-less testing gap (SURVEY.md §4): the engine's
multi-chip sharding logic is exercised on a virtual device mesh
(``xla_force_host_platform_device_count``) so no TPU pod is needed for CI.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# jax may already be imported (the image's sitecustomize registers a TPU
# plugin at interpreter start and captures JAX_PLATFORMS before we run), so
# force the platform through the config system too.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# Persist XLA compilations across test sessions: the engine jit-compiles its
# kernels per shape bucket, and tiny-SF tests revisit the same buckets.
from nds_tpu.config import enable_compile_cache  # noqa: E402

enable_compile_cache()

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _isolate_shared_programs():
    """Tests register different data under identical table names/schemas;
    cross-session program adoption would couple their capacity schedules.
    Correctness would survive (schedule checks re-record on drift) but test
    expectations about compile modes would not — keep cases independent."""
    from nds_tpu.engine.jax_backend.executor import clear_shared_programs
    clear_shared_programs()
    yield
    clear_shared_programs()
