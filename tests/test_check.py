"""Preflight checks + multi-host datagen fanout + report finalization."""
import json
import os
import stat
import subprocess

import pytest

from nds_tpu import check
from nds_tpu.report import BenchReport


def test_version_gate():
    check.check_version((3, 0))
    with pytest.raises(RuntimeError):
        check.check_version((99, 0))


def test_dir_size(tmp_path):
    (tmp_path / "a").write_bytes(b"x" * 100)
    sub = tmp_path / "sub"
    sub.mkdir()
    (sub / "b").write_bytes(b"y" * 50)
    assert check.get_dir_size(str(tmp_path)) == 150


def test_json_summary_folder(tmp_path):
    check.check_json_summary_folder(None)
    check.check_json_summary_folder(str(tmp_path / "new"))  # missing: fine
    full = tmp_path / "full"
    full.mkdir()
    (full / "old.json").write_text("{}")
    with pytest.raises(RuntimeError):
        check.check_json_summary_folder(str(full))


def test_query_subset_exists():
    qd = {"query1": "", "query14_part1": "", "query14_part2": ""}
    assert check.check_query_subset_exists(qd, ["query1", "query14"])
    with pytest.raises(RuntimeError):
        check.check_query_subset_exists(qd, ["query99"])


def test_generate_data_hosts_fanout(tmp_path, monkeypatch):
    """ssh fanout (the reference's Hadoop MR role, GenTable.java): exercised
    with a stub `ssh` that runs the remote command locally."""
    from nds_tpu.datagen import generate_data_hosts

    bindir = tmp_path / "bin"
    bindir.mkdir()
    log = tmp_path / "ssh.log"
    ssh = bindir / "ssh"
    ssh.write_text(
        "#!/bin/sh\n"
        f"echo \"$1\" >> {log}\n"
        "shift\n"
        "exec sh -c \"$*\"\n")
    ssh.chmod(ssh.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")

    data_dir = tmp_path / "out"
    generate_data_hosts(str(data_dir), scale=0.001, parallel=2,
                        hosts=["hostA", "hostB"])
    hosts_used = log.read_text().split()
    assert sorted(hosts_used) == ["hostA", "hostB"]
    # both chunk ranges produced output for a chunked table
    assert (data_dir / "store_sales").exists()
    assert len(os.listdir(data_dir / "store_sales")) >= 1
    # every source table non-empty (merge/verify behavior)
    assert (data_dir / "date_dim").exists()


def test_generate_data_hosts_failure(tmp_path, monkeypatch):
    bindir = tmp_path / "bin"
    bindir.mkdir()
    ssh = bindir / "ssh"
    ssh.write_text("#!/bin/sh\nexit 7\n")
    ssh.chmod(ssh.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")

    from nds_tpu.datagen import generate_data_hosts
    with pytest.raises(RuntimeError, match="host generation failed"):
        generate_data_hosts(str(tmp_path / "o"), 0.001, 2, ["h1"])


def test_report_finalize_and_stats(tmp_path):
    r = BenchReport({}, app_name="t")
    r.report_on(lambda: 42)
    assert r.summary["queryStatus"][-1] == "Completed"
    r.record_task_failure("device fallback: WindowNode")
    assert r.finalize_status() == "CompletedWithTaskFailures"
    r.record_exec_stats({"mode": "compiled", "device_ms": 1.5})
    path = r.write_summary("query1", prefix=str(tmp_path / "power"))
    data = json.load(open(path))
    assert data["queryStatus"] == ["CompletedWithTaskFailures"]
    assert data["execStats"][0]["mode"] == "compiled"


def test_ci_pipeline_script_runs():
    """cicd/ci.yml must be backed by an EXECUTABLE pipeline (round-2
    verdict #6): the native stage builds the generator and self-checks a
    fixed-size table, and the workflow delegates every job to the script."""
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "cicd", "run_ci.sh")
    out = subprocess.run(["bash", script, "--list"], capture_output=True,
                         text=True, check=True)
    assert out.stdout.split() == ["native", "test", "bench", "all"]
    subprocess.run(["bash", script, "native"], check=True, timeout=600)
    import yaml
    with open(os.path.join(repo, "cicd", "ci.yml")) as f:
        wf = yaml.safe_load(f)
    assert set(wf["jobs"]) == {"native", "test", "bench"}
    for job in wf["jobs"].values():
        assert any("run_ci.sh" in str(step.get("run", ""))
                   for step in job["steps"])
