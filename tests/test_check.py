"""Preflight checks + multi-host datagen fanout + report finalization."""
import json
import os
import stat
import subprocess

import pytest

from nds_tpu import check
from nds_tpu.report import BenchReport


def test_version_gate():
    check.check_version((3, 0))
    with pytest.raises(RuntimeError):
        check.check_version((99, 0))


def test_dir_size(tmp_path):
    (tmp_path / "a").write_bytes(b"x" * 100)
    sub = tmp_path / "sub"
    sub.mkdir()
    (sub / "b").write_bytes(b"y" * 50)
    assert check.get_dir_size(str(tmp_path)) == 150


def test_json_summary_folder(tmp_path):
    check.check_json_summary_folder(None)
    check.check_json_summary_folder(str(tmp_path / "new"))  # missing: fine
    full = tmp_path / "full"
    full.mkdir()
    (full / "old.json").write_text("{}")
    with pytest.raises(RuntimeError):
        check.check_json_summary_folder(str(full))


def test_query_subset_exists():
    qd = {"query1": "", "query14_part1": "", "query14_part2": ""}
    assert check.check_query_subset_exists(qd, ["query1", "query14"])
    with pytest.raises(RuntimeError):
        check.check_query_subset_exists(qd, ["query99"])


def test_generate_data_hosts_fanout(tmp_path, monkeypatch):
    """ssh fanout (the reference's Hadoop MR role, GenTable.java): exercised
    with a stub `ssh` that runs the remote command locally."""
    from nds_tpu.datagen import generate_data_hosts

    bindir = tmp_path / "bin"
    bindir.mkdir()
    log = tmp_path / "ssh.log"
    ssh = bindir / "ssh"
    ssh.write_text(
        "#!/bin/sh\n"
        f"echo \"$1\" >> {log}\n"
        "shift\n"
        "exec sh -c \"$*\"\n")
    ssh.chmod(ssh.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")

    data_dir = tmp_path / "out"
    generate_data_hosts(str(data_dir), scale=0.001, parallel=2,
                        hosts=["hostA", "hostB"])
    hosts_used = log.read_text().split()
    assert sorted(hosts_used) == ["hostA", "hostB"]
    # both chunk ranges produced output for a chunked table
    assert (data_dir / "store_sales").exists()
    assert len(os.listdir(data_dir / "store_sales")) >= 1
    # every source table non-empty (merge/verify behavior)
    assert (data_dir / "date_dim").exists()


def test_generate_data_hosts_failure(tmp_path, monkeypatch):
    bindir = tmp_path / "bin"
    bindir.mkdir()
    ssh = bindir / "ssh"
    ssh.write_text("#!/bin/sh\nexit 7\n")
    ssh.chmod(ssh.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")

    from nds_tpu.datagen import generate_data_hosts
    with pytest.raises(RuntimeError, match="host generation failed"):
        generate_data_hosts(str(tmp_path / "o"), 0.001, 2, ["h1"])


def test_report_finalize_and_stats(tmp_path):
    r = BenchReport({}, app_name="t")
    r.report_on(lambda: 42)
    assert r.summary["queryStatus"][-1] == "Completed"
    r.record_task_failure("device fallback: WindowNode")
    assert r.finalize_status() == "CompletedWithTaskFailures"
    r.record_exec_stats({"mode": "compiled", "device_ms": 1.5})
    path = r.write_summary("query1", prefix=str(tmp_path / "power"))
    data = json.load(open(path))
    assert data["queryStatus"] == ["CompletedWithTaskFailures"]
    assert data["execStats"][0]["mode"] == "compiled"


def test_ci_pipeline_script_runs():
    """cicd/ci.yml must be backed by an EXECUTABLE pipeline (round-2
    verdict #6): the native stage builds the generator and self-checks a
    fixed-size table, and the workflow delegates every job to the script."""
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "cicd", "run_ci.sh")
    out = subprocess.run(["bash", script, "--list"], capture_output=True,
                         text=True, check=True)
    assert out.stdout.split() == ["native", "resilience", "static",
                                  "planner", "encoded", "kernels", "mesh",
                                  "service", "cache", "chaos", "frontdoor",
                                  "adaptive", "txn", "metrics_gate", "test",
                                  "bench", "all"]
    subprocess.run(["bash", script, "native"], check=True, timeout=600)
    import yaml
    with open(os.path.join(repo, "cicd", "ci.yml")) as f:
        wf = yaml.safe_load(f)
    assert set(wf["jobs"]) == {"native", "resilience", "static", "planner",
                               "encoded", "kernels", "mesh", "service",
                               "cache", "chaos", "frontdoor", "adaptive",
                               "txn", "metrics_gate", "test", "bench"}
    for job in wf["jobs"].values():
        assert any("run_ci.sh" in str(step.get("run", ""))
                   for step in job["steps"])
    # the static stage gates on the six-family engine lint through its
    # package entry point (scripts/lint_engine.py stays a thin shim)
    with open(script) as f:
        assert "python -m nds_tpu.analysis" in f.read()


def test_validator_streams_with_external_sort(tmp_path):
    """compare_results must stream (bounded batches, external merge sort
    under --ignore_ordering) and agree with an in-memory sorted compare."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    from nds_tpu import validate as V

    rng = np.random.default_rng(2)
    n = 5000
    k = rng.integers(0, 500, n)
    # float payload functionally determined by the sort key (like real
    # query outputs: sorting only non-float cols leaves ties otherwise)
    v = np.round(k * 0.517, 3)
    for side, order in (("e", np.argsort(k, kind="stable")),
                        ("a", np.random.default_rng(3).permutation(n))):
        d = tmp_path / side / "query1"
        d.mkdir(parents=True)
        # spread over several files to exercise multi-run merge
        for i in range(4):
            sl = slice(i * n // 4, (i + 1) * n // 4)
            pq.write_table(pa.table({
                "k": pa.array(k[order][sl], type=pa.int64()),
                "v": pa.array(v[order][sl]),
            }), d / f"part-{i}.parquet")
    # tiny batches force many spill runs through the merge path
    rows = list(V.iter_output_rows(
        V._output_files(str(tmp_path / "a" / "query1")), True,
        batch_rows=128, merge_batch=16))
    keys = [r[0] for r in rows]
    assert keys == sorted(keys, key=lambda x: (x is None, str(x)))
    assert len(rows) == n
    assert V.compare_results(str(tmp_path / "e"), str(tmp_path / "a"),
                             "query1", ignore_ordering=True)
    # ordering-sensitive compare must fail on the permuted side
    assert not V.compare_results(str(tmp_path / "e"), str(tmp_path / "a"),
                                 "query1", ignore_ordering=False)
