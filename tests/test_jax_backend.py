"""Differential tests: JAX device backend vs numpy oracle backend.

This is the framework's analog of the reference's CPU-vs-GPU differential
validation (reference nds/nds_validate.py compares CPU-Spark and GPU-Spark
outputs row by row): the numpy engine is the oracle, the JAX engine is the
product path, and both must agree on randomized inputs.
"""
import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.engine import Session


def _random_session(seed: int = 7, n_fact: int = 500, n_dim: int = 40):
    rng = np.random.default_rng(seed)
    k = rng.integers(0, n_dim + 5, n_fact)          # some keys miss the dim
    qty = rng.integers(1, 100, n_fact).astype(float)
    price = np.round(rng.uniform(0.5, 99.9, n_fact), 2)
    null_mask = rng.random(n_fact) < 0.1
    price_col = pa.array([None if m else p for m, p in zip(null_mask, price)])
    cat = rng.choice(["alpha", "beta", "gamma", "delta"], n_fact)
    day = rng.integers(0, 30, n_fact)
    s = Session()
    s.register_arrow("fact", pa.table({
        "fk": pa.array(k, type=pa.int64()),
        "qty": qty, "price": price_col,
        "cat": cat, "day": pa.array(day, type=pa.int64()),
    }))
    s.register_arrow("dim", pa.table({
        "dk": pa.array(np.arange(n_dim), type=pa.int64()),
        "dname": pa.array([f"name_{i % 7}" for i in range(n_dim)]),
        "dclass": pa.array(["even" if i % 2 == 0 else "odd"
                            for i in range(n_dim)]),
    }))
    return s


CORPUS = [
    # scans / filters / projections
    "SELECT fk, qty * 2, price FROM fact WHERE qty > 50 AND cat = 'alpha'",
    "SELECT * FROM fact WHERE price IS NULL OR qty < 5",
    "SELECT fk FROM fact WHERE cat IN ('beta', 'gamma') AND day BETWEEN 5 AND 25",
    "SELECT fk, CASE WHEN qty > 50 THEN 'hi' WHEN qty > 20 THEN 'mid' ELSE 'lo' END FROM fact",
    "SELECT COALESCE(price, 0.0), NULLIF(cat, 'alpha') FROM fact",
    "SELECT fk FROM fact WHERE cat LIKE 'a%a'",
    "SELECT CAST(qty AS INT), ROUND(price, 1) FROM fact WHERE price IS NOT NULL",
    "SELECT SUBSTR(cat, 1, 2), fk FROM fact",
    # aggregation
    "SELECT cat, COUNT(*), SUM(qty), AVG(price), MIN(day), MAX(day) FROM fact GROUP BY cat",
    "SELECT cat, COUNT(DISTINCT fk) FROM fact GROUP BY cat",
    "SELECT COUNT(*), SUM(price) FROM fact WHERE qty > 1000000",
    "SELECT day, STDDEV_SAMP(qty) FROM fact GROUP BY day",
    "SELECT cat, day, SUM(qty) FROM fact GROUP BY ROLLUP(cat, day)",
    "SELECT cat, SUM(qty) FROM fact GROUP BY cat HAVING SUM(qty) > 500",
    "SELECT MIN(cat), MAX(cat) FROM fact",
    "SELECT MIN(dname), MAX(dname) FROM dim GROUP BY dclass",
    # joins
    "SELECT f.fk, d.dname FROM fact f JOIN dim d ON f.fk = d.dk WHERE f.qty > 80",
    "SELECT f.fk, d.dname FROM fact f LEFT JOIN dim d ON f.fk = d.dk",
    "SELECT d.dclass, SUM(f.qty) FROM fact f, dim d WHERE f.fk = d.dk GROUP BY d.dclass",
    "SELECT f.fk FROM fact f WHERE f.fk IN (SELECT dk FROM dim WHERE dclass = 'even')",
    "SELECT f.fk FROM fact f WHERE NOT EXISTS (SELECT 1 FROM dim d WHERE d.dk = f.fk)",
    "SELECT f.fk FROM fact f WHERE f.fk NOT IN (SELECT dk FROM dim)",
    "SELECT a.fk, b.fk FROM fact a JOIN fact b ON a.fk = b.fk AND a.day < b.day WHERE a.qty > 95",
    "SELECT f.fk FROM fact f JOIN dim d ON f.fk = d.dk AND f.qty > 50",
    "SELECT d.dname, COUNT(*) FROM dim d RIGHT JOIN fact f ON d.dk = f.fk GROUP BY d.dname",
    # scalar subqueries
    "SELECT fk FROM fact WHERE qty > (SELECT AVG(qty) FROM fact)",
    "SELECT cat, SUM(qty) FROM fact GROUP BY cat HAVING SUM(qty) > (SELECT AVG(qty) FROM fact)",
    # sort / limit / distinct / set ops
    "SELECT DISTINCT cat, day FROM fact WHERE day < 4",
    "SELECT fk, price FROM fact ORDER BY price DESC, fk LIMIT 17",
    "SELECT fk, price FROM fact ORDER BY price ASC LIMIT 9",
    "SELECT cat FROM fact WHERE day = 1 UNION SELECT dclass FROM dim",
    "SELECT cat FROM fact UNION ALL SELECT dname FROM dim",
    "SELECT fk FROM fact WHERE day < 10 INTERSECT SELECT dk FROM dim",
    "SELECT dk FROM dim EXCEPT SELECT fk FROM fact WHERE day = 2",
    # CTEs
    "WITH big AS (SELECT * FROM fact WHERE qty > 50) "
    "SELECT b.cat, COUNT(*) FROM big b GROUP BY b.cat",
    # strings
    "SELECT cat, dname FROM fact JOIN dim ON cat < dname WHERE fk = 3",
    "SELECT fk FROM fact WHERE cat = 'beta' ORDER BY fk LIMIT 5",
]


@pytest.fixture(scope="module")
def sess():
    return _random_session()


def _canon(table):
    rows = table.to_pylist()
    def key(row):
        return tuple((x is None, str(type(x)), str(x)) for x in row)
    return sorted(rows, key=key)


def _approx_equal(rows_a, rows_b):
    assert len(rows_a) == len(rows_b)
    for ra, rb in zip(rows_a, rows_b):
        assert len(ra) == len(rb)
        for va, vb in zip(ra, rb):
            if va is None or vb is None:
                assert va is None and vb is None
            elif isinstance(va, float) or isinstance(vb, float):
                assert va == pytest.approx(vb, rel=1e-9, abs=1e-9)
            else:
                assert va == vb, (va, vb)


@pytest.mark.parametrize("query", CORPUS, ids=range(len(CORPUS)))
def test_backend_agreement(sess, query):
    oracle = sess.sql(query, backend="numpy")
    device = sess.sql(query, backend="jax")
    _approx_equal(_canon(device), _canon(oracle))


def test_ordered_results_preserve_order(sess):
    q = "SELECT fk, qty FROM fact ORDER BY qty DESC, fk LIMIT 25"
    oracle = sess.sql(q, backend="numpy").to_pylist()
    device = sess.sql(q, backend="jax").to_pylist()
    _approx_equal(device, oracle)


def test_no_unexpected_fallbacks(sess):
    """The core relational surface must run on device, not via fallback."""
    sess.sql("SELECT cat, SUM(qty) FROM fact JOIN dim ON fk = dk "
             "GROUP BY cat ORDER BY cat", backend="jax")
    assert sess.last_fallbacks == []
