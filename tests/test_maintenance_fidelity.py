"""Maintenance-insert fidelity (VERDICT r4 #5): each LF_* view SELECT must
produce the same rows through this engine as through SQLite executing the
same SQL on the same staging data — LEFT OUTER lookup semantics (failed
dimension lookups insert with NULL surrogate keys) and SCD currentness
filters (*_rec_end_date IS NULL) included, mirroring the reference's
nds/data_maintenance join kinds."""
import os
import re
import sqlite3
import subprocess
import sys

import pytest

from nds_tpu.engine.session import Session
from nds_tpu.schema import get_maintenance_schemas, get_schemas
from tests.sqlite_oracle import (_AFFINITY, _convert, load_database,
                                 normalize_rows, sort_rows, to_sqlite_sql)

MAINT_DIR = os.path.join(os.path.dirname(__file__), "..", "nds_tpu",
                         "data_maintenance")
LF_FILES = ["LF_SS", "LF_WS", "LF_CS", "LF_SR", "LF_CR", "LF_WR", "LF_I"]


@pytest.fixture(scope="module")
def staged(tmp_path_factory):
    root = tmp_path_factory.mktemp("maint")
    data = str(root / "data")
    upd = str(root / "upd")
    subprocess.run([sys.executable, "-m", "nds_tpu.datagen", "local", data,
                    "--scale", "0.01", "--parallel", "1"], check=True,
                   timeout=600)
    subprocess.run([sys.executable, "-m", "nds_tpu.datagen", "local", upd,
                    "--scale", "0.01", "--parallel", "1", "--update", "1"],
                   check=True, timeout=600)
    # sqlite side: base + staging tables
    conn = load_database(data)
    for name, schema in get_maintenance_schemas().items():
        tdir = os.path.join(upd, name)
        if not os.path.isdir(tdir):
            continue
        from nds_tpu.engine.arrow_bridge import engine_dtype
        fields = [(f.name, engine_dtype(f.type))
                  for f in schema.arrow_schema(use_decimal=False)]
        cols = ", ".join(f'"{n}" {_AFFINITY[d]}' for n, d in fields)
        conn.execute(f'CREATE TABLE "{name}" ({cols})')
        ph = ", ".join("?" * len(fields))
        rows = []
        for fname in sorted(os.listdir(tdir)):
            with open(os.path.join(tdir, fname)) as f:
                for line in f:
                    parts = line.rstrip("\n").split("|")
                    if len(parts) < len(fields):
                        continue
                    rows.append(tuple(None if p == "" else _convert(p, d)
                                      for p, (_n, d) in zip(parts, fields)))
        if rows:
            conn.executemany(f'INSERT INTO "{name}" VALUES ({ph})', rows)
    conn.commit()
    # engine side
    s = Session()
    for name, schema in get_schemas(False).items():
        tdir = os.path.join(data, name)
        if os.path.isdir(tdir):
            s.register_csv(name, tdir,
                           schema.arrow_schema(use_decimal=False))
    for name, schema in get_maintenance_schemas(False).items():
        tdir = os.path.join(upd, name)
        if os.path.isdir(tdir):
            s.register_csv(name, tdir,
                           schema.arrow_schema(use_decimal=False))
    return conn, s


def _view_select(path: str) -> str:
    text = open(path).read()
    m = re.search(r"CREATE TEMP VIEW \w+ AS\s*(SELECT.*?);\s*INSERT",
                  text, re.S | re.I)
    assert m, f"no view select in {path}"
    return m.group(1)


@pytest.mark.parametrize("lf", LF_FILES)
def test_lf_view_matches_sqlite(staged, lf):
    conn, s = staged
    sel = _view_select(os.path.join(MAINT_DIR, f"{lf}.sql"))
    mine = s.sql(sel, backend="numpy")
    import pyarrow as pa
    from nds_tpu.engine import arrow_bridge
    mine_rows = [tuple(r.values()) if isinstance(r, dict) else tuple(r)
                 for r in arrow_bridge.to_arrow(mine).to_pylist()]
    theirs = conn.execute(to_sqlite_sql(sel)).fetchall()
    assert len(mine_rows) == len(theirs), \
        f"{lf}: row count {len(mine_rows)} vs sqlite {len(theirs)}"
    a = sort_rows(normalize_rows(mine_rows))
    b = sort_rows(normalize_rows(theirs))
    mismatch = 0
    for ra, rb in zip(a, b):
        for va, vb in zip(ra, rb):
            if isinstance(va, float) or isinstance(vb, float):
                fa = float(va) if va is not None else None
                fb = float(vb) if vb is not None else None
                if (fa is None) != (fb is None) or \
                        (fa is not None and abs(fa - fb) >
                         1e-6 * max(1.0, abs(fa))):
                    mismatch += 1
                    break
            elif va != vb:
                mismatch += 1
                break
    assert mismatch == 0, f"{lf}: {mismatch} differing rows of {len(a)}"
