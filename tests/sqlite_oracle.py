"""Independent correctness oracle: SQLite (stdlib) as a second SQL engine.

The numpy-vs-jax differential suite shares one parser/planner, so it cannot
catch planner bugs. This oracle re-runs the same instantiated templates on
SQLite (its own parser, planner and executor — the independent-engine role
CPU-Spark plays in the reference, nds/nds_validate.py:48-114) over the same
generated data and compares rows under the validator's epsilon policy.

Dialect translation (our templates use exactly these non-SQLite forms —
verified over nds_tpu/templates/*.tpl):
- ``CAST('lit' AS DATE)``      -> ``'lit'``     (dates are ISO TEXT)
- ``expr + INTERVAL N DAYS``   -> ``date(expr, '+N days')``
- ``CAST(x AS DOUBLE)``        -> ``CAST(x AS REAL)``
- ``a / b``                    -> ``a * 1.0 / b``  (Spark divides in double;
  SQLite would truncate int/int)

Templates using ROLLUP/GROUPING are skipped: SQLite has no grouping sets.
"""
from __future__ import annotations

import datetime
import os
import re
import sqlite3

from nds_tpu.schema import get_schemas

# SQLite column affinity per engine dtype
_AFFINITY = {"int": "INTEGER", "float": "REAL", "bool": "INTEGER",
             "date": "TEXT", "str": "TEXT"}

_CAST_DATE = re.compile(r"CAST\s*\(\s*('([^']*)')\s+AS\s+DATE\s*\)",
                        re.IGNORECASE)
_CAST_DOUBLE = re.compile(r"AS\s+DOUBLE\s*\)", re.IGNORECASE)
_INTERVAL = re.compile(
    r"('[^']*'|[A-Za-z_][A-Za-z0-9_.]*)\s*([+-])\s*INTERVAL\s+(\d+)\s+DAYS?",
    re.IGNORECASE)
_DIV = re.compile(r"(?<![*/])/(?![*/])")


def to_sqlite_sql(sql: str) -> str:
    sql = _CAST_DATE.sub(lambda m: m.group(1), sql)
    sql = _CAST_DOUBLE.sub("AS REAL)", sql)
    sql = _INTERVAL.sub(
        lambda m: f"date({m.group(1)}, '{m.group(2)}{m.group(3)} days')",
        sql)
    # integer division differs (Spark: double, SQLite: truncating int)
    sql = _DIV.sub(" * 1.0 / ", sql)
    return sql


def load_database(data_dir: str, use_decimal: bool = False) -> sqlite3.Connection:
    """Load the generated pipe-delimited CSVs into an in-memory SQLite DB."""
    conn = sqlite3.connect(":memory:")
    for name, schema in get_schemas(use_decimal).items():
        tdir = os.path.join(data_dir, name)
        if not os.path.isdir(tdir):
            continue
        from nds_tpu.engine.arrow_bridge import engine_dtype
        fields = [(f.name, engine_dtype(f.type))
                  for f in schema.arrow_schema(use_decimal=False)]
        cols = ", ".join(f'"{n}" {_AFFINITY[d]}' for n, d in fields)
        conn.execute(f'CREATE TABLE "{name}" ({cols})')
        placeholders = ", ".join("?" * len(fields))
        rows = []
        for fname in sorted(os.listdir(tdir)):
            with open(os.path.join(tdir, fname)) as f:
                for line in f:
                    parts = line.rstrip("\n").split("|")
                    if len(parts) < len(fields):
                        continue
                    rows.append(tuple(
                        None if p == "" else _convert(p, d)
                        for p, (_n, d) in zip(parts, fields)))
        if rows:
            conn.executemany(
                f'INSERT INTO "{name}" VALUES ({placeholders})', rows)
        # join keys: without indexes SQLite nested-loops the star joins
        for n, _d in fields:
            if n.endswith("_sk"):
                conn.execute(f'CREATE INDEX IF NOT EXISTS '
                             f'"ix_{name}_{n}" ON "{name}"("{n}")')
    conn.commit()
    conn.execute("ANALYZE")
    return conn


def _convert(text: str, dtype: str):
    if dtype == "int":
        return int(text)
    if dtype == "float":
        return float(text)
    if dtype == "bool":
        return 1 if text.lower() in ("true", "1", "y") else 0
    return text  # str and date (ISO text)


def normalize_rows(rows) -> list[tuple]:
    """Canonical form for comparison: dates to ISO text, Decimal to float."""
    out = []
    for row in rows:
        out.append(tuple(
            v.isoformat() if isinstance(v, (datetime.date, datetime.datetime))
            else float(v) if type(v).__name__ == "Decimal"
            else v
            for v in row))
    return out


def sort_rows(rows: list[tuple]) -> list[tuple]:
    def key(row):
        return tuple((v is None, "" if v is None else str(v))
                     for v in row if not isinstance(v, float))
    return sorted(rows, key=key)
