"""Independent correctness oracle: SQLite (stdlib) as a second SQL engine.

The numpy-vs-jax differential suite shares one parser/planner, so it cannot
catch planner bugs. This oracle re-runs the same instantiated templates on
SQLite (its own parser, planner and executor — the independent-engine role
CPU-Spark plays in the reference, nds/nds_validate.py:48-114) over the same
generated data and compares rows under the validator's epsilon policy.

Dialect translation (our templates use exactly these non-SQLite forms —
verified over nds_tpu/templates/*.tpl):
- ``CAST('lit' AS DATE)``      -> ``'lit'``     (dates are ISO TEXT)
- ``expr + INTERVAL N DAYS``   -> ``date(expr, '+N days')``
- ``CAST(x AS DOUBLE)``        -> ``CAST(x AS REAL)``
- ``a / b``                    -> ``a * 1.0 / b``  (Spark divides in double;
  SQLite would truncate int/int)
- ``GROUP BY ROLLUP (c1..ck)`` -> UNION ALL of the k+1 plain GROUP BY
  prefixes, with grouped-out columns projected as NULL and ``GROUPING(ci)``
  folded to 0/1 per variant (SQLite has no grouping sets; every rollup in
  the 99 templates is a plain column-list rollup, so prefix expansion is
  exact). The grand-total () set is a plain ungrouped aggregate — one row
  even over empty input, per grouping-sets semantics.
"""
from __future__ import annotations

import datetime
import os
import re
import sqlite3

from nds_tpu.schema import get_schemas

# SQLite column affinity per engine dtype
_AFFINITY = {"int": "INTEGER", "float": "REAL", "bool": "INTEGER",
             "date": "TEXT", "str": "TEXT"}

# strip CAST(... AS DATE) for literals AND identifiers: dates are ISO text
# in the sqlite DB, and sqlite's CAST to the unknown DATE type applies
# NUMERIC affinity ('1999-09-30' -> 1999), breaking date joins
_CAST_DATE = re.compile(
    r"CAST\s*\(\s*('[^']*'|[A-Za-z_][A-Za-z0-9_.]*)\s+AS\s+DATE\s*\)",
    re.IGNORECASE)
_CAST_DOUBLE = re.compile(r"AS\s+DOUBLE\s*\)", re.IGNORECASE)
_INTERVAL = re.compile(
    r"('[^']*'|[A-Za-z_][A-Za-z0-9_.]*)\s*([+-])\s*INTERVAL\s+(\d+)\s+DAYS?",
    re.IGNORECASE)
_DIV = re.compile(r"(?<![*/])/(?![*/])")


_ROLLUP = re.compile(r"GROUP\s+BY\s+ROLLUP\s*\(", re.IGNORECASE)
_SELECT = re.compile(r"\bSELECT\b", re.IGNORECASE)
_FROM = re.compile(r"\bFROM\b", re.IGNORECASE)


def _depth_at(sql: str, pos: int) -> int:
    return sql.count("(", 0, pos) - sql.count(")", 0, pos)


def _match_paren(sql: str, open_pos: int) -> int:
    """Index of the ')' matching the '(' at open_pos."""
    depth = 0
    for i in range(open_pos, len(sql)):
        if sql[i] == "(":
            depth += 1
        elif sql[i] == ")":
            depth -= 1
            if depth == 0:
                return i
    raise ValueError("unbalanced parens")


_AGG_CALL = re.compile(r"\b(sum|min|max|avg|count|stddev_samp)\s*\(",
                       re.IGNORECASE)


def _null_outside_aggs(text: str, rolled: set[str]) -> str:
    """Substitute NULL for rolled-up columns in every context EXCEPT inside
    aggregate-call arguments (which see underlying row values, per
    grouping-sets semantics) and string literals."""
    strings = [m.span() for m in re.finditer(r"'[^']*'", text)]
    protected: list[tuple[int, int]] = list(strings)
    for m in _AGG_CALL.finditer(text):
        if any(s <= m.start() < e for s, e in strings):
            continue    # agg-looking text inside a string literal
        open_pos = text.index("(", m.end() - 1)
        protected.append((m.start(), _match_paren(text, open_pos) + 1))

    def shielded(i: int, j: int) -> bool:
        return any(s <= i and j <= e for s, e in protected)

    pattern = re.compile(
        "|".join(rf"\b{re.escape(c)}\b" for c in sorted(rolled)),
        re.IGNORECASE)
    out, last = [], 0
    for m in pattern.finditer(text):
        if shielded(m.start(), m.end()):
            continue
        out.append(text[last:m.start()])
        out.append("NULL")
        last = m.end()
    out.append(text[last:])
    return "".join(out)


def _rollup_variant(select_list: str, cols: list[str], p: int) -> str:
    """Rewrite a select list for the rollup prefix of length p: GROUPING(c)
    folds to 0 (grouped) / 1 (rolled up); rolled-up columns become NULL
    outside aggregate args and string literals (inside them, grouping-sets
    semantics keep the underlying value)."""
    for i, c in enumerate(cols):
        select_list = re.sub(
            rf"GROUPING\s*\(\s*{re.escape(c)}\s*\)",
            "0" if i < p else "1", select_list, flags=re.IGNORECASE)
    rolled = {c.strip() for c in cols[p:]}
    if rolled:
        select_list = _null_outside_aggs(select_list, rolled)
    return select_list


def expand_rollup(sql: str) -> str:
    """Expand every GROUP BY ROLLUP into a UNION ALL of plain GROUP BYs."""
    m = _ROLLUP.search(sql)
    if m is None:
        return sql
    open_pos = sql.index("(", m.end() - 1)
    close_pos = _match_paren(sql, open_pos)
    cols = [c.strip()
            for c in _split_top_commas(sql[open_pos + 1:close_pos])]
    block_depth = _depth_at(sql, m.start())

    # the SELECT that owns this GROUP BY: last same-depth SELECT before it
    sel_starts = [s.start() for s in _SELECT.finditer(sql, 0, m.start())
                  if _depth_at(sql, s.start()) == block_depth]
    block_start = sel_starts[-1]
    # its select list ends at the first same-depth FROM
    from_pos = next(f.start() for f in _FROM.finditer(sql, block_start)
                    if _depth_at(sql, f.start()) == block_depth)
    select_list = sql[block_start + len("SELECT"):from_pos]
    body = sql[from_pos:m.start()]          # FROM ... WHERE ... (untouched)

    # block tail (HAVING/ORDER BY/LIMIT) runs to the paren closing the block
    tail_end = len(sql)
    depth = 0
    for i in range(close_pos + 1, len(sql)):
        if sql[i] == "(":
            depth += 1
        elif sql[i] == ")":
            depth -= 1
            if depth < 0:
                tail_end = i
                break
    tail = sql[close_pos + 1:tail_end].strip()

    variants = []
    for p in range(len(cols), -1, -1):      # leftmost variant names columns
        group = f" GROUP BY {', '.join(cols[:p])}" if p else ""
        variants.append(f"SELECT {_rollup_variant(select_list, cols, p)} "
                        f"{body}{group}")
    union = " UNION ALL ".join(variants)
    new_block = f"SELECT * FROM ({union})" + (f" {tail}" if tail else "")
    return expand_rollup(sql[:block_start] + new_block + sql[tail_end:])


_CONCAT = re.compile(r"\bCONCAT\s*\(", re.IGNORECASE)
_COMPOUND_PARENS = re.compile(
    r"\)\s*(EXCEPT|INTERSECT|UNION(?:\s+ALL)?)\s*\(", re.IGNORECASE)
_COMPOUND_BARE_LEFT = re.compile(
    r"[^)\s]\s*\b(EXCEPT|INTERSECT|UNION(?:\s+ALL)?)\s*\(", re.IGNORECASE)


def _split_top_commas(text: str) -> list[str]:
    parts, depth, start = [], 0, 0
    for i, ch in enumerate(text):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append(text[start:i])
            start = i + 1
    parts.append(text[start:])
    return parts


def _rewrite_concat(sql: str) -> str:
    """CONCAT(a, b, ...) -> (a || b || ...): SQLite has no CONCAT, and ||
    NULL-propagates exactly like Spark's concat."""
    m = _CONCAT.search(sql)
    if m is None:
        return sql
    open_pos = sql.index("(", m.end() - 1)
    close_pos = _match_paren(sql, open_pos)
    args = _split_top_commas(sql[open_pos + 1:close_pos])
    joined = "(" + " || ".join(a.strip() for a in args) + ")"
    return _rewrite_concat(sql[:m.start()] + joined + sql[close_pos + 1:])


def _strip_compound_parens(sql: str) -> str:
    """((SELECT ...) EXCEPT (SELECT ...)) -> (SELECT ... EXCEPT SELECT ...):
    SQLite rejects parenthesized compound-select members."""
    m = _COMPOUND_PARENS.search(sql)
    while m is not None:
        close_pos = sql.index(")", m.start())
        # matching '(' of the left member
        depth = 0
        open_pos = -1
        for i in range(close_pos, -1, -1):
            if sql[i] == ")":
                depth += 1
            elif sql[i] == "(":
                depth -= 1
                if depth == 0:
                    open_pos = i
                    break
        r_open = sql.index("(", m.end() - 1)
        r_close = _match_paren(sql, r_open)
        left_is_select = sql[open_pos + 1:close_pos].lstrip()[:6].upper() == "SELECT"
        right_is_select = sql[r_open + 1:r_close].lstrip()[:6].upper() == "SELECT"
        if not (left_is_select and right_is_select):
            break
        chars = list(sql)
        for pos in (open_pos, close_pos, r_open, r_close):
            chars[pos] = " "
        sql = "".join(chars)
        m = _COMPOUND_PARENS.search(sql)
    # left member already bare (chained compounds): strip the right wrap only
    m = _COMPOUND_BARE_LEFT.search(sql)
    while m is not None:
        r_open = sql.index("(", m.end() - 1)
        r_close = _match_paren(sql, r_open)
        if sql[r_open + 1:r_close].lstrip()[:6].upper() != "SELECT":
            break
        chars = list(sql)
        chars[r_open] = " "
        chars[r_close] = " "
        sql = "".join(chars)
        m = _COMPOUND_BARE_LEFT.search(sql)
    return sql


class _StddevSamp:
    """Sample standard deviation for SQLite (no built-in stddev)."""

    def __init__(self):
        self.n = 0
        self.mean = 0.0
        self.m2 = 0.0     # Welford: numerically stable

    def step(self, value):
        if value is None:
            return
        self.n += 1
        d = float(value) - self.mean
        self.mean += d / self.n
        self.m2 += d * (float(value) - self.mean)

    def finalize(self):
        if self.n < 2:
            return None
        return (self.m2 / (self.n - 1)) ** 0.5


def to_sqlite_sql(sql: str) -> str:
    sql = expand_rollup(sql)
    sql = _rewrite_concat(sql)
    sql = _strip_compound_parens(sql)
    sql = _CAST_DATE.sub(lambda m: m.group(1), sql)
    sql = _CAST_DOUBLE.sub("AS REAL)", sql)
    sql = _INTERVAL.sub(
        lambda m: f"date({m.group(1)}, '{m.group(2)}{m.group(3)} days')",
        sql)
    # integer division differs (Spark: double, SQLite: truncating int)
    sql = _DIV.sub(" * 1.0 / ", sql)
    return sql


def load_database(data_dir: str, use_decimal: bool = False) -> sqlite3.Connection:
    """Load the generated pipe-delimited CSVs into an in-memory SQLite DB."""
    conn = sqlite3.connect(":memory:")
    conn.create_aggregate("STDDEV_SAMP", 1, _StddevSamp)
    for name, schema in get_schemas(use_decimal).items():
        tdir = os.path.join(data_dir, name)
        if not os.path.isdir(tdir):
            continue
        from nds_tpu.engine.arrow_bridge import engine_dtype
        fields = [(f.name, engine_dtype(f.type))
                  for f in schema.arrow_schema(use_decimal=False)]
        cols = ", ".join(f'"{n}" {_AFFINITY[d]}' for n, d in fields)
        conn.execute(f'CREATE TABLE "{name}" ({cols})')
        placeholders = ", ".join("?" * len(fields))
        rows = []
        for fname in sorted(os.listdir(tdir)):
            with open(os.path.join(tdir, fname)) as f:
                for line in f:
                    parts = line.rstrip("\n").split("|")
                    if len(parts) < len(fields):
                        continue
                    rows.append(tuple(
                        None if p == "" else _convert(p, d)
                        for p, (_n, d) in zip(parts, fields)))
        if rows:
            conn.executemany(
                f'INSERT INTO "{name}" VALUES ({placeholders})', rows)
        # join keys: without indexes SQLite nested-loops the star joins
        for n, _d in fields:
            if n.endswith("_sk"):
                conn.execute(f'CREATE INDEX IF NOT EXISTS '
                             f'"ix_{name}_{n}" ON "{name}"("{n}")')
    conn.commit()
    conn.execute("ANALYZE")
    return conn


def _convert(text: str, dtype: str):
    if dtype == "int":
        return int(text)
    if dtype == "float":
        return float(text)
    if dtype == "bool":
        return 1 if text.lower() in ("true", "1", "y") else 0
    return text  # str and date (ISO text)


def normalize_rows(rows) -> list[tuple]:
    """Canonical form for comparison: dates to ISO text, Decimal to float."""
    out = []
    for row in rows:
        out.append(tuple(
            v.isoformat() if isinstance(v, (datetime.date, datetime.datetime))
            else float(v) if type(v).__name__ == "Decimal"
            else v
            for v in row))
    return out


def sort_rows(rows: list[tuple]) -> list[tuple]:
    def key(row):
        return tuple((v is None, "" if v is None else str(v))
                     for v in row if not isinstance(v, float))
    return sorted(rows, key=key)
