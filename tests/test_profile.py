"""EXPLAIN ANALYZE (obs/profile.py + Session.explain_analyze).

Contracts pinned here:

- BIT-IDENTITY: profiled execution returns exactly what normal execution
  returns — in-core (eager walk vs the compiled steady state), streamed
  (the unchanged morsel path), encoded, sharded (mesh_shards=2 on the
  conftest's virtual mesh), and the numpy backend;
- EXACT per-node actual row counts (cross-checked against pyarrow
  recomputation) under stable TypeName#k labels shared with the plan
  verifier, and per-node walls summing to ~the profiled total;
- the normal (unprofiled) paths record ExecStats.node_stats for FREE:
  schedule-check actuals on the compiled path, morsel/final counts on
  the streamed path — and they AGREE with the profiled exact counts;
- the cardinality audit flags static-estimate misestimates (with
  capacity-ladder bucket drift) and stays silent when estimates hold;
- device-memory watermark accounting (DEVICE_MEM / ExecStats.mem_*);
- DISABLED-MODE ZERO COST: profiling off adds no profile counters
  (count-shaped asserts only — this host's wall-clock flakes);
- metrics hygiene: every registered metric has a describe() entry, and
  histogram label-cardinality overflow counts + folds visibly;
- renderer round trips: PlanProfile to_dict/from_dict/render,
  scripts/explain_report.py, scripts/obs_report.py --compare.
"""
import json
import os

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq
import pytest

from nds_tpu.config import EngineConfig
from nds_tpu.engine import Session
from nds_tpu.engine.arrow_bridge import to_arrow
from nds_tpu.obs import metrics as M
from nds_tpu.obs import profile as P

N_FACT, N_DIM = 40_000, 200
CHUNK = 4_096

AGG = ("SELECT d.grp, COUNT(*) AS c, SUM(f.qty) AS sq, MAX(f.qty) AS hi "
       "FROM fact f JOIN dim d ON f.fk = d.dk "
       "WHERE f.day BETWEEN 10 AND 300 GROUP BY d.grp ORDER BY d.grp")


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("profile")
    rng = np.random.default_rng(7)
    fact = pa.table({
        "fk": pa.array(rng.integers(0, N_DIM, N_FACT), type=pa.int64()),
        "qty": pa.array(rng.integers(1, 50, N_FACT), type=pa.int64()),
        # low-cardinality + clustered: the encoded path participates
        "day": pa.array(np.sort(rng.integers(0, 365, N_FACT))
                        .astype(np.int64)),
    })
    path = os.path.join(str(tmp), "fact.parquet")
    pq.write_table(fact, path, row_group_size=8192)
    dim = pa.table({"dk": pa.array(np.arange(N_DIM), type=pa.int64()),
                    "grp": pa.array((np.arange(N_DIM) % 13)
                                    .astype(np.int64))})
    return {"fact": fact, "fact_path": path, "dim": dim,
            "dir": str(tmp)}


def make_session(data, streamed=False, **cfg) -> Session:
    kw = dict(cfg)
    if streamed:
        kw.setdefault("chunk_rows", CHUNK)
        kw.setdefault("out_of_core_min_rows", 10_000)
    s = Session(EngineConfig(**kw))
    if streamed:
        s.register_parquet("fact", data["fact_path"])
    else:
        s.register_arrow("fact", data["fact"])
    s.register_arrow("dim", data["dim"])
    return s


def assert_identical(a, b):
    assert to_arrow(a).equals(to_arrow(b))


# -- bit-identity: profiled vs normal, every execution shape ----------------

def test_profiled_incore_bit_identity_and_exact_rows(data):
    s = make_session(data)
    normal = s.sql(AGG, label="q_incore")          # record
    s.sql(AGG, label="q_incore")                   # compile+run
    prof = s.explain_analyze(AGG, label="q_incore")
    assert_identical(prof.table, normal)
    assert prof.mode == "in-core" and prof.backend == "jax"
    # exact actual rows, cross-checked against pyarrow recomputation
    by_label = {ns.op: ns for ns in prof.nodes.values()}
    fact, dim = data["fact"], data["dim"]
    n_filter = pc.sum(pc.and_(
        pc.greater_equal(fact.column("day"), pa.scalar(10)),
        pc.less_equal(fact.column("day"), pa.scalar(300)))).as_py()
    scans = {ns.detail: ns for ns in prof.nodes.values()
             if ns.op == "ScanNode"}
    assert scans["fact"].rows == N_FACT
    assert scans["dim"].rows == N_DIM
    assert by_label["FilterNode"].rows == n_filter
    n_groups = len(set(
        (np.asarray(dim.column("grp")) % 13).tolist()))
    assert by_label["AggregateNode"].rows == n_groups
    assert prof.nodes[prof.root].rows == normal.num_rows
    # every executed node carries a wall + bytes; walls sum to ~total
    assert all(ns.wall_ms is not None and ns.bytes
               for ns in prof.nodes.values())
    assert prof.profiled_ms() <= prof.total_ms * 1.001
    # tree shape: root reaches every node through children
    seen, stack = set(), [prof.root]
    while stack:
        lbl = stack.pop()
        if lbl in seen:
            continue
        seen.add(lbl)
        stack.extend(prof.nodes[lbl].children)
    assert seen == set(prof.nodes)
    # labels are the verifier's TypeName#k identities
    assert prof.root.startswith(("ProjectNode", "SortNode"))


def test_profiled_wall_attribution_fraction(data):
    """Per-node walls must explain ~all of the profiled wall (the >=90%
    acceptance): the gap is pure python glue between nodes."""
    s = make_session(data)
    s.sql(AGG, label="q_frac")
    prof = s.explain_analyze(AGG, label="q_frac")
    assert prof.profiled_ms() >= 0.9 * prof.total_ms


def test_profiled_streamed_bit_identity(data):
    s = make_session(data, streamed=True)
    normal = s.sql(AGG, label="q_stream")
    assert s.last_exec_stats["mode"] == "streaming"
    prof = s.explain_analyze(AGG, label="q_stream")
    assert prof.mode == "streaming"
    assert_identical(prof.table, normal)
    # streamed profile: exact scan rows + group walls on the scan node
    scan = next(ns for ns in prof.nodes.values()
                if ns.op == "ScanNode" and ns.detail == "fact")
    assert scan.rows == N_FACT
    assert scan.wall_ms is not None and scan.wall_ms > 0
    agg = next(ns for ns in prof.nodes.values()
               if ns.op == "AggregateNode")
    assert agg.rows == normal.num_rows


def test_profiled_encoded_bit_identity(data):
    s = make_session(data, streamed=True)      # encoded_exec default on
    normal = s.sql(AGG, label="q_enc")
    assert s.last_exec_stats.get("enc_spec"), "encoded path must engage"
    prof = s.explain_analyze(AGG, label="q_enc")
    assert_identical(prof.table, normal)
    plain = make_session(data, streamed=True, encoded_exec=False)
    assert_identical(prof.table, plain.sql(AGG, label="q_enc"))


def test_profiled_sharded_bit_identity(data):
    single = make_session(data, streamed=True)
    normal = single.sql(AGG, label="q_mesh")
    s = make_session(data, streamed=True, mesh_shards=2)
    prof = s.explain_analyze(AGG, label="q_mesh")
    assert s.last_exec_stats.get("mesh_shards") == 2
    assert_identical(prof.table, normal)


def test_profiled_numpy_backend(data):
    s = make_session(data)
    normal = s.sql(AGG, backend="numpy", label="q_np")
    prof = s.explain_analyze(AGG, backend="numpy", label="q_np")
    assert prof.backend == "numpy"
    assert_identical(prof.table, normal)
    assert prof.nodes[prof.root].rows == normal.num_rows


def test_profile_plans_config_flag(data):
    """EngineConfig.profile_plans: sql() itself runs profiled (the power
    --explain wiring) and installs last_profile."""
    s = make_session(data, profile_plans=True)
    before = M.METRICS.snapshot()
    out = s.sql(AGG, label="q_flag")
    assert s.last_profile is not None
    assert s.last_profile.query == "q_flag"
    assert_identical(s.last_profile.table, out)
    assert s.last_exec_stats["mode"] == "profiled"
    delta = M.METRICS.delta(before)
    assert delta.get("profiled_queries") == 1


# -- node_stats on the NORMAL (unprofiled) paths ----------------------------

def test_compiled_node_stats_agree_with_profiled(data):
    s = make_session(data)
    s.sql(AGG, label="q_ns")                     # record
    rec_stats = s.last_exec_stats.get("node_stats")
    assert rec_stats, "record pass must attribute schedule decisions"
    s.sql(AGG, label="q_ns")                     # compile+run
    s.sql(AGG, label="q_ns")                     # compiled replay
    assert s.last_exec_stats["mode"] == "compiled"
    replay_stats = s.last_exec_stats.get("node_stats")
    assert replay_stats
    prof = s.explain_analyze(AGG, label="q_ns")
    exact = {lbl: ns.rows for lbl, ns in prof.nodes.items()}
    # every attributed label is a real node and its actual count is exact
    for lbl, rows in replay_stats.items():
        assert exact.get(lbl) == rows, (lbl, rows, exact.get(lbl))
    assert replay_stats == rec_stats


def test_streamed_node_stats_free_actuals(data):
    s = make_session(data, streamed=True)
    out = s.sql(AGG, label="q_sns")
    ns = s.last_exec_stats.get("node_stats")
    assert ns
    scan_rows = [v for k, v in ns.items() if k.startswith("ScanNode")]
    assert N_FACT in scan_rows
    root = [v for k, v in ns.items()
            if k.startswith(("ProjectNode", "SortNode"))]
    assert out.num_rows in root


# -- cardinality audit ------------------------------------------------------

def test_cardinality_audit_flags_stats_lie(data):
    s = Session(EngineConfig())
    # lie by 250x: the catalog thinks fact has 10M rows
    s.register_arrow("fact", data["fact"], est_rows=10_000_000)
    s.register_arrow("dim", data["dim"])
    before = M.METRICS.snapshot()
    prof = s.explain_analyze(AGG, label="q_lie")
    assert prof.findings, "a 250x stats lie must be flagged"
    f = next(f for f in prof.findings if f["op"] == "ScanNode")
    assert f["direction"] == "over" and f["bucket_drift"]
    assert f["est_rows"] == 10_000_000 and f["rows"] == N_FACT
    assert M.METRICS.delta(before).get("cardinality_misestimates", 0) \
        >= len(prof.findings)
    # honest estimates on the same shape stay quiet at the scan
    s2 = make_session(data)
    prof2 = s2.explain_analyze(AGG, label="q_honest")
    assert not any(f["op"] == "ScanNode" for f in prof2.findings)


# -- device-memory watermarks ----------------------------------------------

def test_device_memory_watermarks(data):
    s = make_session(data, streamed=True)
    out = s.sql(AGG, label="q_mem")
    assert out.num_rows
    st = s.last_exec_stats_typed
    assert st.mem_peak_bytes and st.mem_peak_bytes > 0
    assert st.mem_live_bytes is not None
    # streamed morsel buffers free as the loop advances: the live set at
    # finish sits below the in-flight peak
    assert st.mem_live_bytes <= st.mem_peak_bytes
    assert st.mem_headroom_bytes == \
        int(s.config.scan_budget_gb * (1 << 30)) - st.mem_peak_bytes
    assert P.DEVICE_MEM.peak >= st.mem_peak_bytes
    assert M.DEVICE_PEAK_BYTES.value == P.DEVICE_MEM.peak
    # the profile carries the same block
    prof = s.explain_analyze(AGG, label="q_mem")
    assert prof.memory["query_peak_bytes"] > 0
    assert prof.memory["headroom_bytes"] == \
        prof.memory["budget_bytes"] - P.DEVICE_MEM.peak


def test_mem_tracker_balance():
    t = P.DeviceMemTracker()
    t.add([(1, 100), (2, 50)])
    t.add([(1, 100)])                 # double add: ignored
    assert t.live == 150 and t.peak == 150
    t.mark_window()
    t.free([(2, 50), (3, 999)])       # untracked id: ignored
    assert t.live == 100
    t.add([(4, 500)])
    assert t.window_peak() == 600 and t.peak == 600


# -- disabled-mode zero cost ------------------------------------------------

def test_disabled_mode_adds_no_profile_counters(data):
    s = make_session(data)
    before = M.METRICS.snapshot()
    s.sql(AGG, label="q_off")
    s.sql(AGG, label="q_off")
    delta = M.METRICS.delta(before)
    assert "profiled_queries" not in delta
    assert "cardinality_misestimates" not in delta
    assert "histogram_series_overflow" not in delta
    assert s.last_profile is None


# -- metrics hygiene (satellite) --------------------------------------------

def test_every_metric_has_glossary_entry():
    """describe() completeness: every registered counter/gauge/histogram
    family must carry a non-empty help string."""
    missing = [name for name, help_ in M.METRICS.describe().items()
               if not help_]
    assert not missing, f"metrics without describe() help: {missing}"


def test_histogram_series_overflow_counts(monkeypatch):
    monkeypatch.setattr(M, "HISTOGRAM_MAX_SERIES",
                        len(M.METRICS._hists) + 1)
    base = M.METRICS.histogram("overflow_test_ms", "overflow probe")
    M.METRICS.histogram("overflow_test_ms", tenant="t0").observe(1.0)
    before = M.HISTOGRAM_SERIES_OVERFLOW.value
    folded = M.METRICS.histogram("overflow_test_ms", tenant="t1")
    assert folded is base            # folded into the base series
    assert M.HISTOGRAM_SERIES_OVERFLOW.value == before + 1
    M.METRICS.reset()


# -- serialization + renderers ---------------------------------------------

def test_profile_roundtrip_and_render(data, tmp_path):
    s = make_session(data)
    prof = s.explain_analyze(AGG, label="q_render")
    text = prof.render()
    assert "total" in text and "rows" in text and "memory:" in text
    d = prof.to_dict()
    back = P.PlanProfile.from_dict(json.loads(json.dumps(d)))
    assert back.render() == text
    assert back.to_dict() == d


def test_explain_report_cli(data, tmp_path, capsys):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import explain_report
    s = make_session(data)
    prof = s.explain_analyze(AGG, label="q_cli")
    pdir = tmp_path / "explain"
    pdir.mkdir()
    with open(pdir / "q_cli.json", "w") as f:
        json.dump(prof.to_dict(), f)
    assert explain_report.main([str(pdir)]) == 0
    out = capsys.readouterr().out
    assert "q_cli" in out and "rows" in out
    # power-summary mode: node_stats table from a normal run's stats
    s.sql(AGG, label="q_cli")
    summary = {"appName": "NDS-TPU q_cli",
               "execStats": [s.last_exec_stats]}
    with open(tmp_path / "power_q.json", "w") as f:
        json.dump(summary, f)
    assert explain_report.main([str(tmp_path / "power_q.json")]) == 0
    out = capsys.readouterr().out
    assert "rows" in out
    assert explain_report.main([str(tmp_path / "nope.json")]) == 2


def test_obs_report_compare(tmp_path, capsys):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import obs_report

    def round_doc(wall, q9, compiles):
        return {"schema_version": 3, "value": wall, "upload_gb": 0.5,
                "rows_per_s": 1000,
                "metrics": {"compiles": compiles, "morsels": 16},
                "histograms": {
                    "query_latency_ms{template=query9}": {
                        "name": "query_latency_ms",
                        "labels": {"template": "query9"},
                        "count": 3, "sum": q9 * 3, "min": q9, "max": q9,
                        "buckets": [[q9, 3]]}}}
    p1, p2 = tmp_path / "BENCH_r01.json", tmp_path / "BENCH_r02.json"
    p1.write_text(json.dumps(round_doc(1000.0, 300.0, 3)))
    p2.write_text(json.dumps(round_doc(1500.0, 450.0, 9)))
    assert obs_report.main(["--compare", str(p1), str(p2)]) == 0
    out = capsys.readouterr().out
    assert "wall_ms" in out and "query9" in out
    # regression highlighting: round 2 is >20% slower and tripled compiles
    assert "1500.0!" in out and "9!" in out and "450.0!" in out


# -- live service surface ---------------------------------------------------

def test_service_explain_analyze(data):
    from nds_tpu.service import QueryService, ServiceConfig
    s = make_session(data)
    with QueryService(s, ServiceConfig()) as svc:
        served = svc.sql(AGG, label="q_svc")
        prof = svc.explain_analyze(AGG, label="q_svc")
        assert_identical(prof.table, served)
        assert prof.nodes[prof.root].rows == served.num_rows
    from nds_tpu.resilience import AdmissionRejected
    with pytest.raises(AdmissionRejected):
        svc.explain_analyze(AGG)
