"""Late materialization (planner._late_materialization): aggregate-over-join
plans whose dimension columns are consumed only as group keys regroup by the
dimension's surrogate join key and gather the attributes AFTER aggregation
(the q72-class fix: 16M-row random-access gathers materializing joined
dimension columns before the group-by, PERF.md r5 headroom #1).

Exactness is pinned three ways: against an independent SQLite oracle over
the same rows, against the engine's own un-rewritten plan (the
NDS_TPU_NO_LATE_MAT A/B toggle), and numpy-vs-jax. Guard rails: ineligible
shapes — attributes consumed pre-aggregation, non-unique keys, computed
group expressions — must provably keep their original plans."""
import math
import os
import sqlite3

import numpy as np
import pyarrow as pa
import pytest

import nds_tpu.engine.plan as P
from nds_tpu.config import EngineConfig
from nds_tpu.engine import Session
from nds_tpu.engine.planner import Planner
from nds_tpu.sql import parse_sql

FACT_EST = 5_000_000     # claimed estimate: clears the late_mat_min_rows gate


def _tables(seed=3, n=3000, nd=48):
    rng = np.random.default_rng(seed)
    amt = rng.integers(1, 100, n).astype(object)
    amt[rng.random(n) < 0.1] = None          # NULLs exercise sum_guarded
    key = rng.integers(0, nd + 4, n)         # keys 48..51 miss the dimension
    fact = pa.table({
        "f_key": pa.array(key, type=pa.int64()),
        "f_cat": pa.array(rng.integers(0, 4, n), type=pa.int64()),
        "f_amt": pa.array(amt, type=pa.int64()),
        "f_price": pa.array(np.round(rng.random(n) * 10, 2),
                            type=pa.float64()),
    })
    attr = (np.arange(nd) % 7).astype(object)
    attr[5] = None                           # a NULL attribute value
    dim = pa.table({
        "d_key": pa.array(np.arange(nd), type=pa.int64()),
        "d_attr": pa.array(attr, type=pa.int64()),
        "d_name": pa.array([f"name{i % 5}" for i in range(nd)]),
    })
    return {"fact": fact, "dim": dim}


def _session(tables, declare_unique=True, config=None):
    s = Session(config)
    s.register_arrow("fact", tables["fact"], est_rows=FACT_EST)
    s.register_arrow("dim", tables["dim"],
                     unique_cols=("d_key",) if declare_unique else ())
    return s


def _sqlite(tables):
    conn = sqlite3.connect(":memory:")
    for name, t in tables.items():
        cols = ", ".join(f'"{c}"' for c in t.column_names)
        conn.execute(f"CREATE TABLE {name} ({cols})")
        rows = list(zip(*[t.column(c).to_pylist() for c in t.column_names]))
        conn.executemany(
            f"INSERT INTO {name} VALUES ({','.join('?' * len(t.column_names))})",
            rows)
    conn.commit()
    return conn


def _rows_equal(got, want):
    if len(got) != len(want):
        return False
    for g, w in zip(got, want):
        if len(g) != len(w):
            return False
        for a, b in zip(g, w):
            if isinstance(a, float) or isinstance(b, float):
                if a is None or b is None:
                    if a is not b:
                        return False
                elif not math.isclose(float(a), float(b), rel_tol=1e-6,
                                      abs_tol=1e-9):
                    return False
            elif a != b:
                return False
    return True


def _late_joins(plan):
    return [x for x in P.iter_plan_nodes(plan)
            if isinstance(x, P.JoinNode) and getattr(x, "late_mat", False)]


def _plan(session, q):
    return Planner(session._catalog()).plan_query(parse_sql(q))


def _check(q, tables=None, fires=True, declare_unique=True, config=None):
    """Plan-inspect + three-way differential (sqlite / numpy / jax)."""
    tables = tables or _tables()
    s = _session(tables, declare_unique, config)
    plan = _plan(s, q)
    if fires:
        assert _late_joins(plan), "late-materialization must fire"
    else:
        assert not _late_joins(plan), "plan must stay original"
    want = _sqlite(tables).execute(q).fetchall()
    got_np = s.sql(q, backend="numpy").to_pylist()
    assert _rows_equal(got_np, want), (got_np[:5], want[:5])
    got_jx = s.sql(q, backend="jax").to_pylist()
    assert _rows_equal(got_jx, want), (got_jx[:5], want[:5])
    return plan


# -- eligible shapes ---------------------------------------------------------

def test_group_key_only_counts():
    _check("SELECT d_attr, COUNT(*) AS cnt FROM fact, dim "
           "WHERE f_key = d_key GROUP BY d_attr ORDER BY d_attr")


def test_sum_min_max_avg_merge_exactly():
    _check("SELECT d_attr, SUM(f_amt) AS s, MIN(f_amt) AS mn, "
           "MAX(f_amt) AS mx, AVG(f_price) AS a, COUNT(f_amt) AS c "
           "FROM fact, dim WHERE f_key = d_key "
           "GROUP BY d_attr ORDER BY d_attr")


def test_string_attribute_group_key():
    _check("SELECT d_name, SUM(f_amt) AS s FROM fact, dim "
           "WHERE f_key = d_key GROUP BY d_name ORDER BY d_name")


def test_post_agg_projection_and_having():
    _check("SELECT d_name, COUNT(*) AS cnt FROM fact, dim "
           "WHERE f_key = d_key GROUP BY d_name "
           "HAVING COUNT(*) > 100 ORDER BY d_name")


def test_mixed_fact_and_dim_group_keys():
    _check("SELECT d_attr, f_cat, COUNT(*) AS cnt FROM fact, dim "
           "WHERE f_key = d_key GROUP BY d_attr, f_cat "
           "ORDER BY d_attr, f_cat")


def test_group_by_key_and_attr():
    # the surrogate key itself in the group list rides along exactly
    _check("SELECT d_key, d_attr, COUNT(*) AS cnt FROM fact, dim "
           "WHERE f_key = d_key GROUP BY d_key, d_attr "
           "ORDER BY d_key")


def test_duplicate_attr_values_re_merge():
    """Distinct surrogate keys sharing one attribute value must merge into
    ONE output group — the merge aggregate, not key-grouping alone, is what
    keeps the rewrite exact (48 keys fold to 7 d_attr groups)."""
    q = ("SELECT d_attr, COUNT(*) AS cnt, SUM(f_amt) AS s FROM fact, dim "
         "WHERE f_key = d_key GROUP BY d_attr ORDER BY d_attr")
    plan = _check(q)
    aggs = [x for x in P.iter_plan_nodes(plan)
            if isinstance(x, P.AggregateNode)]
    assert len(aggs) == 2, "partial (by key) + merge (by attribute)"


def test_empty_result_through_rewrite():
    _check("SELECT d_attr, COUNT(*) AS cnt, SUM(f_amt) AS s "
           "FROM fact, dim WHERE f_key = d_key AND f_cat = 99 "
           "GROUP BY d_attr ORDER BY d_attr")


def test_fact_filter_still_eligible():
    # a pre-agg filter on FACT columns does not pin the dimension
    _check("SELECT d_attr, COUNT(*) AS cnt FROM fact, dim "
           "WHERE f_key = d_key AND f_cat = 2 "
           "GROUP BY d_attr ORDER BY d_attr")


def test_q72_shape_two_dims_deferred():
    """The query72 shape: fact joins several dimensions; attribute group
    keys defer per-dimension, a dimension consumed by a pre-agg filter
    stays pinned."""
    tables = _tables()
    rng = np.random.default_rng(9)
    nd2 = 12
    tables = dict(tables)
    tables["wh"] = pa.table({
        "w_key": pa.array(np.arange(nd2), type=pa.int64()),
        "w_name": pa.array([f"wh{i % 3}" for i in range(nd2)]),
    })
    tables["dd"] = pa.table({
        "dd_key": pa.array(np.arange(30), type=pa.int64()),
        "dd_week": pa.array(np.arange(30) // 7, type=pa.int64()),
    })
    n = tables["fact"].num_rows
    tables["fact"] = tables["fact"].append_column(
        "f_wh", pa.array(rng.integers(0, nd2, n), type=pa.int64()))
    tables["fact"] = tables["fact"].append_column(
        "f_date", pa.array(rng.integers(0, 30, n), type=pa.int64()))
    q = ("SELECT d_attr, w_name, COUNT(*) AS cnt FROM fact, dim, wh, dd "
         "WHERE f_key = d_key AND f_wh = w_key AND f_date = dd_key "
         "AND dd_week >= 1 "
         "GROUP BY d_attr, w_name ORDER BY d_attr, w_name")
    s = Session()
    s.register_arrow("fact", tables["fact"], est_rows=FACT_EST)
    s.register_arrow("dim", tables["dim"], unique_cols=("d_key",))
    s.register_arrow("wh", tables["wh"], unique_cols=("w_key",))
    s.register_arrow("dd", tables["dd"], unique_cols=("dd_key",))
    plan = _plan(s, q)
    assert len(_late_joins(plan)) == 2, \
        "dim and wh defer; dd contributes no attribute group key"
    want = _sqlite(tables).execute(q).fetchall()
    assert _rows_equal(s.sql(q, backend="numpy").to_pylist(), want)
    assert _rows_equal(s.sql(q, backend="jax").to_pylist(), want)


def test_compiled_replay_matches():
    """Second jax execution replays the compiled program over the rewritten
    plan; results must be identical both times."""
    tables = _tables()
    s = _session(tables)
    q = ("SELECT d_attr, COUNT(*) AS cnt, SUM(f_amt) AS s FROM fact, dim "
         "WHERE f_key = d_key GROUP BY d_attr ORDER BY d_attr")
    first = s.sql(q, backend="jax").to_pylist()
    second = s.sql(q, backend="jax").to_pylist()
    assert first == second
    assert s.last_exec_stats.get("mode") in ("compiled", "compile+run")


# -- ineligible shapes keep their original plans ------------------------------

def test_pushed_down_dim_filter_still_eligible():
    """A dim-only predicate is pushed INTO the dimension unit by the
    planner: it clones with the dimension, so deferral stays exact (the
    attribute never materializes at fact scale either way)."""
    _check("SELECT d_attr, COUNT(*) AS cnt FROM fact, dim "
           "WHERE f_key = d_key AND d_attr > 2 "
           "GROUP BY d_attr ORDER BY d_attr")


def test_attr_in_pre_agg_filter_ineligible():
    """A mixed fact/dim predicate cannot push into either unit: it consumes
    the attribute ABOVE the join, pre-aggregation, and pins the dimension."""
    _check("SELECT d_attr, COUNT(*) AS cnt FROM fact, dim "
           "WHERE f_key = d_key AND d_attr > f_cat "
           "GROUP BY d_attr ORDER BY d_attr", fires=False)


def test_attr_in_agg_arg_ineligible():
    _check("SELECT d_attr, SUM(f_amt + d_attr) AS s FROM fact, dim "
           "WHERE f_key = d_key GROUP BY d_attr ORDER BY d_attr",
           fires=False)


def test_computed_group_expr_ineligible():
    _check("SELECT d_attr + 1 AS a1, COUNT(*) AS cnt FROM fact, dim "
           "WHERE f_key = d_key GROUP BY d_attr + 1 ORDER BY a1",
           fires=False)


def test_undeclared_key_uniqueness_ineligible():
    # without catalog uniqueness the post-agg join could double-count:
    # the legality analysis must refuse
    _check("SELECT d_attr, COUNT(*) AS cnt FROM fact, dim "
           "WHERE f_key = d_key GROUP BY d_attr ORDER BY d_attr",
           fires=False, declare_unique=False)


def test_distinct_agg_ineligible():
    _check("SELECT d_attr, COUNT(DISTINCT f_cat) AS c FROM fact, dim "
           "WHERE f_key = d_key GROUP BY d_attr ORDER BY d_attr",
           fires=False)


def test_small_plans_keep_original_shape():
    # default est_rows (actual tiny row counts) sits under the size gate
    tables = _tables()
    s = Session()
    s.register_arrow("fact", tables["fact"])
    s.register_arrow("dim", tables["dim"], unique_cols=("d_key",))
    q = ("SELECT d_attr, COUNT(*) AS cnt FROM fact, dim "
         "WHERE f_key = d_key GROUP BY d_attr")
    assert not _late_joins(_plan(s, q))


# -- opt-outs -----------------------------------------------------------------

def test_env_toggle_disables():
    tables = _tables()
    os.environ["NDS_TPU_NO_LATE_MAT"] = "1"
    try:
        s = _session(tables)
        q = ("SELECT d_attr, COUNT(*) AS cnt FROM fact, dim "
             "WHERE f_key = d_key GROUP BY d_attr")
        assert not _late_joins(_plan(s, q))
    finally:
        del os.environ["NDS_TPU_NO_LATE_MAT"]


def test_config_toggle_disables_and_matches():
    tables = _tables()
    cfg = EngineConfig(late_materialization=False)
    q = ("SELECT d_attr, SUM(f_amt) AS s FROM fact, dim "
         "WHERE f_key = d_key GROUP BY d_attr ORDER BY d_attr")
    s_off = _session(tables, config=cfg)
    assert not _late_joins(_plan(s_off, q))
    s_on = _session(tables)
    assert _late_joins(_plan(s_on, q))
    assert s_on.sql(q, backend="numpy").to_pylist() == \
        s_off.sql(q, backend="numpy").to_pylist()


def test_nds_dimension_keys_auto_declared():
    """NDS table names pick up schema.UNIQUE_KEYS without any declaration."""
    s = Session()
    item = pa.table({
        "i_item_sk": pa.array(np.arange(10), type=pa.int64()),
        "i_item_desc": pa.array([f"d{i % 3}" for i in range(10)]),
    })
    s.register_arrow("item", item)
    assert s._unique_cols["item"] == frozenset({"i_item_sk"})
    s.register_arrow("store_sales", pa.table({
        "ss_item_sk": pa.array([1, 2], type=pa.int64())}))
    assert s._unique_cols["store_sales"] == frozenset()
