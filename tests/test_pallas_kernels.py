"""Pallas kernel property suite (ISSUE 7).

Under JAX_PLATFORMS=cpu (conftest) the kernels run in Pallas INTERPRET
mode — the real kernel bodies execute, so tier-1 CI proves the code paths
the TPU will compile. Three layers:

- kernel-level: each pallas_kernels entry point vs the XLA lowering it
  replaces, bit-identical over randomized (values, validity, alive,
  capacity-pad) inputs including all-NULL, all-dead, single-group and
  max-capacity edges;
- engine-level: kernels.py dispatch seams with the op flags on vs off,
  and full Session SQL against the numpy oracle backend (ops.py);
- workload-level (slow marks): the on/off bit-identity differential
  through the independent SQLite oracle for q9/q22/q67/q95 at SF0.01 —
  the attribution-table target queries.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from nds_tpu.config import EngineConfig
from nds_tpu.engine import Session, arrow_bridge
from nds_tpu.engine.jax_backend import kernels
from nds_tpu.engine.jax_backend import pallas_kernels as pk

ALL_OPS = frozenset({"sort", "groupby", "gather"})


@pytest.fixture(autouse=True)
def _ops_off_after():
    """Every test leaves the thread-local op set empty: other suites in
    the same process must keep measuring the pure XLA lowering."""
    yield
    pk.set_active(frozenset())


def test_probe_interpret_under_cpu():
    mode, reason = pk.probe()
    assert mode == "interpret"
    assert pk.fallback_reason() is None


def test_parse_ops_validates():
    assert pk.parse_ops("sort,gather") == frozenset({"sort", "gather"})
    assert pk.parse_ops(("groupby",)) == frozenset({"groupby"})
    assert pk.parse_ops("sort, bogus") == frozenset({"sort"})   # dropped
    assert pk.parse_ops(None) == frozenset()
    assert pk.parse_ops("") == frozenset()


# ---------------------------------------------------------------------------
# kernel level
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n,dtype", [
    (1, jnp.int64), (2, jnp.int64), (5, jnp.int32), (64, jnp.int64),
    (1000, jnp.int32), (4096, jnp.int64), (6144, jnp.int32)])
def test_sort_pairs_matches_stable_sort(n, dtype):
    rng = np.random.default_rng(n)
    key = jnp.asarray(rng.integers(-9, 9, n), dtype)     # heavy ties
    # sentinel block: dead rows ride iinfo.max exactly like the engine
    key = key.at[: n // 3].set(jnp.iinfo(dtype).max)
    idx = jnp.arange(n, dtype=jnp.int32)
    pk.set_active(ALL_OPS)
    got_k, got_i = pk.sort_pairs(key, idx)
    want_k, want_i = lax.sort((key, idx), num_keys=1, is_stable=True)
    assert jnp.array_equal(got_k, want_k)
    assert jnp.array_equal(got_i, want_i)


def test_sort_pairs_all_equal_and_sorted_inputs():
    pk.set_active(ALL_OPS)
    n = 1000
    idx = jnp.arange(n, dtype=jnp.int32)
    for key in (jnp.zeros(n, jnp.int64),
                jnp.arange(n, dtype=jnp.int64),
                jnp.arange(n, 0, -1).astype(jnp.int64)):
        got = pk.sort_pairs(key, idx)
        want = lax.sort((key, idx), num_keys=1, is_stable=True)
        assert jnp.array_equal(got[0], want[0])
        assert jnp.array_equal(got[1], want[1])


def test_sort_pairs_under_jit():
    pk.set_active(ALL_OPS)
    rng = np.random.default_rng(3)
    key = jnp.asarray(rng.integers(0, 5, 4096), jnp.int64)
    idx = jnp.arange(4096, dtype=jnp.int32)
    got = jax.jit(pk.sort_pairs)(key, idx)
    want = lax.sort((key, idx), num_keys=1, is_stable=True)
    assert jnp.array_equal(got[0], want[0])
    assert jnp.array_equal(got[1], want[1])


@pytest.mark.parametrize("cap", [1, 2, 64, 1000, pk.GROUPBY_MAX_SEGMENTS])
def test_seg_reduce_matches_segment_ops(cap):
    rng = np.random.default_rng(cap)
    n = 4096
    # gid includes the dead-row sentinel (== cap): contributes nothing
    gid = jnp.asarray(rng.integers(0, cap + 1, n), jnp.int32)
    d_int = jnp.asarray(rng.integers(-1000, 1000, n), jnp.int64)
    d_f = jnp.asarray(rng.uniform(-5, 5, n), jnp.float64)
    pk.set_active(ALL_OPS)
    s, mn, mx, fmn = pk.seg_reduce_multi(
        [(d_int, "sum"), (d_int, "min"), (d_int, "max"), (d_f, "min")],
        gid, cap)
    sg = jnp.where(gid < cap, gid, cap)
    assert jnp.array_equal(s, jax.ops.segment_sum(d_int, sg,
                                                  num_segments=cap))
    assert jnp.array_equal(mn, jax.ops.segment_min(d_int, sg,
                                                   num_segments=cap))
    assert jnp.array_equal(mx, jax.ops.segment_max(d_int, sg,
                                                   num_segments=cap))
    assert jnp.array_equal(fmn, jax.ops.segment_min(d_f, sg,
                                                    num_segments=cap))


def test_seg_reduce_all_dead_and_single_group():
    pk.set_active(ALL_OPS)
    n, cap = 300, 8
    d = jnp.arange(n, dtype=jnp.int64)
    # all dead: every gid at the sentinel -> sum 0, min/max at identity
    dead = jnp.full(n, cap, jnp.int32)
    s = pk.seg_reduce(d, dead, cap, "sum")
    mn = pk.seg_reduce(d, dead, cap, "min")
    assert jnp.array_equal(s, jnp.zeros(cap, jnp.int64))
    assert jnp.array_equal(mn, jax.ops.segment_min(
        d, jnp.where(dead < cap, dead, cap), num_segments=cap))
    # single group
    one = jnp.zeros(n, jnp.int32)
    assert int(pk.seg_reduce(d, one, 1, "sum")[0]) == int(d.sum())


def test_seg_supported_gates():
    d_int = jnp.zeros(10, jnp.int64)
    d_f = jnp.zeros(10, jnp.float64)
    assert pk.seg_supported(d_int, 16, "sum")
    assert not pk.seg_supported(d_f, 16, "sum")          # float sum order
    assert pk.seg_supported(d_f, 16, "min")
    assert not pk.seg_supported(d_int, pk.GROUPBY_MAX_SEGMENTS + 1, "sum")
    assert not pk.seg_supported(d_int, 0, "sum")
    assert not pk.seg_supported(jnp.zeros(10, bool), 16, "max")


def test_take_many_dtypes_and_fallback():
    rng = np.random.default_rng(11)
    pk.set_active(ALL_OPS)
    srcs = [jnp.asarray(rng.integers(0, 1 << 30, 1000), jnp.int64),
            jnp.asarray(rng.random(1000) < 0.5),             # bool
            jnp.asarray(rng.random(1000), jnp.float64),
            jnp.asarray(rng.integers(0, 100, 1000), jnp.int32)]
    # over-budget source: falls back to the XLA gather inside take_many
    big = jnp.asarray(rng.integers(0, 9, (pk.GATHER_SRC_BYTES // 8) + 1),
                      jnp.int64)
    for n_idx in (1, 7, 777, 5000):                      # non-block-multiple
        idx = jnp.asarray(rng.integers(0, 1000, n_idx), jnp.int32)
        out = pk.take_many(srcs + [big[:1000]], idx)
        for got, s in zip(out, srcs + [big[:1000]]):
            assert got.dtype == s.dtype
            assert jnp.array_equal(got, s[idx])
    bidx = jnp.asarray(rng.integers(0, big.shape[0], 64), jnp.int32)
    assert jnp.array_equal(pk.take(big, bidx), big[bidx])
    assert not pk.gather_supported(big)


# ---------------------------------------------------------------------------
# engine dispatch seams: flag on vs off, bit-identical
# ---------------------------------------------------------------------------

def _rand_col(rng, n, null_frac=0.1, dtype=jnp.int64, lo=-50, hi=50):
    data = jnp.asarray(rng.integers(lo, hi, n), dtype)
    valid = jnp.asarray(rng.random(n) >= null_frac)
    return jnp.where(valid, data, jnp.zeros((), dtype)), valid


@pytest.mark.parametrize("case", ["random", "all_null", "all_dead",
                                  "single_group", "cap_edge"])
def test_dense_rank_packsort_on_off(case):
    rng = np.random.default_rng(17)
    n = 12288 if case == "cap_edge" else 9000     # >= 1<<13 packsort gate
    data, valid = _rand_col(rng, n)
    alive = jnp.asarray(rng.random(n) < 0.8)
    if case == "all_null":
        valid = jnp.zeros(n, bool)
    elif case == "all_dead":
        alive = jnp.zeros(n, bool)
    elif case == "single_group":
        data, valid = jnp.zeros(n, jnp.int64), jnp.ones(n, bool)
    outs = []
    for ops in (frozenset(), ALL_OPS):
        pk.set_active(ops)
        gid, ng = kernels.dense_rank_packsort([data], [valid], alive)
        outs.append((np.asarray(gid), int(ng)))
    pk.set_active(frozenset())
    assert outs[0][1] == outs[1][1]
    assert np.array_equal(outs[0][0], outs[1][0])


def test_compaction_build_side_unscatter_on_off():
    rng = np.random.default_rng(23)
    n = 9000                                  # above SORT_MIN_ROWS
    alive = jnp.asarray(rng.random(n) < 0.6)
    gid = jnp.asarray(rng.integers(0, 64, n), jnp.int32)
    vals = jnp.asarray(rng.uniform(-1, 1, n), jnp.float64)
    bval = jnp.asarray(rng.random(n) < 0.5)
    res = []
    for ops in (frozenset(), ALL_OPS):
        pk.set_active(ops)
        perm, cnt = kernels.compaction_perm(alive)
        sg, bperm = kernels.build_side(gid, alive)
        un = kernels.unscatter(perm, (vals, bval))
        res.append((np.asarray(perm), int(cnt), np.asarray(sg),
                    np.asarray(bperm), np.asarray(un[0]), np.asarray(un[1])))
    pk.set_active(frozenset())
    for a, b in zip(*res):
        assert np.array_equal(a, b)


@pytest.mark.parametrize("func", ["count_star", "count", "sum", "min",
                                  "max", "avg", "stddev_samp"])
def test_agg_apply_on_off(func):
    rng = np.random.default_rng(abs(hash(func)) % 1000)
    n, cap = 6000, 37                         # above GROUPBY_MIN_ROWS
    data, valid = _rand_col(rng, n)
    alive = jnp.asarray(rng.random(n) < 0.7)
    gid = jnp.where(alive, jnp.asarray(rng.integers(0, cap, n), jnp.int32),
                    cap)
    arg = None if func == "count_star" else (data, valid)
    res = []
    for ops in (frozenset(), ALL_OPS):
        pk.set_active(ops)
        vals, v = kernels.agg_apply(gid, alive, func, arg, cap)
        res.append((np.asarray(vals), np.asarray(v)))
    pk.set_active(frozenset())
    assert np.array_equal(res[0][0], res[1][0]), func   # bit-identical
    assert np.array_equal(res[0][1], res[1][1]), func


# ---------------------------------------------------------------------------
# session level: SQL on/off vs the numpy oracle (ops.py)
# ---------------------------------------------------------------------------

def _mk_tables(rng, n_fact=9_100, n_dim=300):
    # n_fact sits above the 1<<13 packsort gate but buckets to a small
    # capacity: the session tests exercise every pallas seam while keeping
    # first-compile of the sort network cheap for the tier-1 budget
    import pyarrow as pa
    qty = rng.integers(1, 50, n_fact).astype(object)
    qty[rng.random(n_fact) < 0.07] = None
    fact = pa.table({
        "fk": pa.array(rng.integers(0, n_dim + 9, n_fact),
                       type=pa.int32()),
        "qty": pa.array(list(qty), type=pa.int32()),
        "price": pa.array(np.round(rng.uniform(1, 100, n_fact), 2)),
        "day": pa.array(rng.integers(0, 365, n_fact), type=pa.int32()),
    })
    dim = pa.table({"dk": pa.array(np.arange(n_dim), type=pa.int32()),
                    "grp": pa.array((np.arange(n_dim) % 13)
                                    .astype(np.int32))})
    return fact, dim


Q_AGG = ("SELECT d.grp, COUNT(*) c, SUM(f.qty) s, MIN(f.day) mn, "
         "MAX(f.price) mx, AVG(f.qty) a FROM fact f JOIN dim d "
         "ON f.fk = d.dk WHERE f.day < 300 GROUP BY d.grp ORDER BY d.grp")
Q_WINDOW = ("SELECT dk, grp, RANK() OVER (PARTITION BY grp ORDER BY dk) r "
            "FROM dim ORDER BY grp, dk")
Q_TOPK = ("SELECT fk, qty FROM fact WHERE qty IS NOT NULL "
          "ORDER BY qty DESC, fk LIMIT 50")


def _rows(t):
    return [tuple(r) for r in arrow_bridge.to_arrow(t).to_pylist()]


@pytest.fixture(scope="module")
def tables():
    return _mk_tables(np.random.default_rng(7))


def _session(tables, ops):
    fact, dim = tables
    s = Session(EngineConfig(pallas_ops=tuple(sorted(ops))))
    s.register_arrow("fact", fact)
    s.register_arrow("dim", dim)
    return s


@pytest.mark.parametrize("q", [Q_AGG, Q_WINDOW, Q_TOPK])
def test_sql_on_off_bit_identity_and_oracle(tables, q):
    """Flag on/off bit-identity across record AND compiled replay, plus
    the ops.py numpy-oracle differential."""
    got = {}
    for name, ops in (("off", ()), ("on", ("sort", "groupby", "gather"))):
        s = _session(tables, ops)
        # run 1 records eagerly, run 2 replays the compiled program: the
        # pair pins record-vs-compiled bit-identity per mode
        runs = [_rows(s.sql(q, backend="jax")) for _ in range(2)]
        assert runs[0] == runs[1], (name, "replay drift")
        if ops:
            assert s.last_exec_stats.get("pallas_ops") == \
                ["gather", "groupby", "sort"]
            assert "pallas_fallback_reason" not in s.last_exec_stats
        got[name] = runs[0]
    assert got["on"] == got["off"], "pallas on/off differ"
    s = _session(tables, ())
    assert got["on"] == _rows(s.sql(q, backend="numpy"))


def test_live_toggle_invalidates_programs(tables):
    """Flipping pallas_ops on a LIVE session must re-record (the cached
    programs embed the kernel choice), still bit-identically."""
    s = _session(tables, ())
    a = _rows(s.sql(Q_AGG, backend="jax"))
    s.config.pallas_ops = ("sort", "gather")
    b = _rows(s.sql(Q_AGG, backend="jax"))
    assert s.last_exec_stats.get("mode") in ("record", "adopted")
    assert s.last_exec_stats.get("pallas_ops") == ["gather", "sort"]
    s.config.pallas_ops = ()
    c = _rows(s.sql(Q_AGG, backend="jax"))
    assert a == b == c


def test_graceful_degradation_when_platform_off(tables, monkeypatch):
    """Unusable platform: one warning, XLA fallback, reason recorded in
    last_exec_stats — never a crash, results unchanged."""
    s_ref = _session(tables, ())
    want = _rows(s_ref.sql(Q_AGG, backend="jax"))
    monkeypatch.setattr(pk, "_PROBE", ("off", "no TPU pallas on backend "
                                       "'fake'"))
    monkeypatch.setattr(pk, "_WARNED", False)
    s = _session(tables, ("sort", "groupby", "gather"))
    got = _rows(s.sql(Q_AGG, backend="jax"))
    assert got == want
    st = s.last_exec_stats
    assert "no TPU pallas" in st.get("pallas_fallback_reason", "")
    typed = s.last_exec_stats_typed
    assert typed.pallas_fallback_reason == st["pallas_fallback_reason"]


def test_streaming_path_on_off(tables):
    """The out-of-core morsel path executes through its own executor: the
    flag must reach it (stream-config key) and stay bit-identical."""
    fact, dim = tables
    got = {}
    for name, ops in (("off", ()), ("on", ("sort", "groupby", "gather"))):
        cfg = EngineConfig(pallas_ops=ops, out_of_core=True,
                           chunk_rows=4096, out_of_core_min_rows=5_000)
        s = Session(cfg)
        s.register_arrow("fact", fact)
        s.register_arrow("dim", dim)
        q = ("SELECT d.grp, SUM(f.qty) s FROM fact f JOIN dim d "
             "ON f.fk = d.dk GROUP BY d.grp ORDER BY d.grp")
        got[name] = _rows(s.sql(q, backend="jax"))
        assert s.last_exec_stats["mode"] == "streaming"
        if ops:
            assert s.last_exec_stats.get("pallas_ops")
            # the cached morsel programs must CARRY the op set: their
            # compiled replay otherwise silently traces with kernels off
            sent = s._stream_cache[q]
            for st in sent["gstates"]:
                assert st["cqs"], "no morsel programs recorded"
                for cq in st["cqs"]:
                    assert cq.pallas_ops == frozenset(ops)
    assert got["on"] == got["off"]


def test_pallas_metrics_move(tables):
    from nds_tpu.obs.metrics import METRICS
    before = {k: v for k, v in METRICS.snapshot().items()
              if k.startswith("pallas_")}
    s = _session(tables, ("sort", "gather"))
    s.sql(Q_AGG, backend="jax")
    after = {k: v for k, v in METRICS.snapshot().items()
             if k.startswith("pallas_")}
    assert after["pallas_sort_calls"] > before.get("pallas_sort_calls", 0)
    assert after["pallas_gather_calls"] > before.get("pallas_gather_calls", 0)


# ---------------------------------------------------------------------------
# workload level: SQLite-oracle on/off differential, attribution targets
# (SF0.01; slow — the full-suite CI test stage runs them, tier-1 does not)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def nds_env(tmp_path_factory):
    from nds_tpu import datagen
    from nds_tpu.power import setup_tables
    from sqlite_oracle import load_database
    data = str(tmp_path_factory.mktemp("pallas_nds") / "d")
    datagen.generate_data_local(data, 0.01, parallel=4, overwrite=True)
    conn = load_database(data)

    def mk(ops):
        s = Session(EngineConfig(pallas_ops=ops))
        setup_tables(s, data, "csv")
        return s
    return mk, conn


@pytest.mark.slow
@pytest.mark.parametrize("number", [9, 22, 67, 95])
def test_nds_query_on_off_sqlite_differential(nds_env, number):
    from nds_tpu import streams, validate
    from sqlite_oracle import normalize_rows, sort_rows, to_sqlite_sql
    mk, conn = nds_env
    sql = streams.instantiate(number, stream=0, rngseed=778)
    name = f"query{number}"
    expected = conn.execute(to_sqlite_sql(sql)).fetchall()
    rows = {}
    for label, ops in (("off", ()), ("on", ("sort", "groupby", "gather"))):
        s = mk(ops)
        t = s.sql(sql, backend="jax", label=name)
        at = arrow_bridge.to_arrow(t)
        rows[label] = [tuple(r[c] for c in at.column_names)
                       for r in at.to_pylist()]
        if ops:
            assert "pallas_fallback_reason" not in s.last_exec_stats
        names = list(t.names)
    assert rows["on"] == rows["off"], f"{name}: pallas on/off differ"
    rows_e = sort_rows(normalize_rows(expected))
    rows_a = sort_rows(normalize_rows(rows["on"]))
    assert len(rows_e) == len(rows_a), name
    for re_, ra_ in zip(rows_e, rows_a):
        assert validate.row_equal(re_, ra_, name, names), \
            f"{name}: sqlite {re_} != engine {ra_}"
