"""Adaptive execution (ISSUE 17): the feedback stats store closes the
loop from observed actuals back into plans.

Acceptance-backed properties — all COUNT-shaped or bit-identity (no wall
budgets: this host is 1-core and timing tests flake):

- **q9-class right-sizing**: the first sighting of a streamed query
  provisions every capacity decision at the morsel bucket; the second
  sighting re-records from observed actuals and provisions the minimal
  ladder bucket instead — with the response hash-identical across every
  sighting (right-sizing is provisioning, never results);
- **ceiling hint, never a correctness input**: a profile observed on
  small data replayed against grown data overflows the adapted schedule,
  raises ReplayMismatch internally, re-records eagerly, and still
  answers exactly (oracle differential) — counting adaptive_replans;
- **drift sentinel**: when observed actuals collapse below the stored
  profile by the drift ratio, the store refreshes and bumps the
  template generation so cached streamed state re-plans;
- **log<->store equivalence**: replaying a saved query-log JSONL through
  FeedbackStore.replay_log yields the same per-node actuals the live
  session observed (the PR 15 ring<->JSONL property, one layer up);
- **off is off**: adaptive_plans=False (the default) builds no store
  and moves feedback_hits / feedback_refreshes / adaptive_replans by
  exactly zero on a streamed workload;
- **crash-consistent persistence**: the store round-trips through its
  atomic JSON document at session attach, and an unreadable document
  degrades to an empty store instead of refusing to start;
- **system.plan_feedback** serves the store's facts over plain SQL.
"""
import json
import os

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.config import EngineConfig
from nds_tpu.engine import Session
from nds_tpu.engine.arrow_bridge import to_arrow
from nds_tpu.engine.feedback import FeedbackStore
from nds_tpu.engine.streaming import adapt_schedule, inflate_schedule
from nds_tpu.obs.metrics import (ADAPTIVE_REPLANS, FEEDBACK_HITS,
                                 FEEDBACK_REFRESHES)
from nds_tpu.obs.query_log import QUERY_LOG, read_jsonl

Q = "SELECT k, SUM(v) AS sv FROM big GROUP BY k ORDER BY k"


@pytest.fixture(autouse=True)
def _log_off():
    QUERY_LOG.configure(enabled=False, capacity=4096, path="", clear=True)
    yield
    QUERY_LOG.configure(enabled=False, capacity=4096, path="", clear=True)


def counters():
    return (FEEDBACK_HITS.value, FEEDBACK_REFRESHES.value,
            ADAPTIVE_REPLANS.value)


def make_session(**over) -> Session:
    cfg = dict(use_jax=True, out_of_core=True, out_of_core_min_rows=1000,
               chunk_rows=4096)
    cfg.update(over)
    return Session(EngineConfig(**cfg))


def low_card(n=20000, lo=0, hi=5, seed=0) -> pa.Table:
    rng = np.random.default_rng(seed)
    return pa.table({"k": rng.integers(lo, hi, n),
                     "v": rng.integers(0, 100, n)})


def arrow_rows(table):
    return to_arrow(table).to_pylist()


def cap_cells(store: FeedbackStore, template: str, table: str) -> list:
    """Observed cap values of the stored group profile."""
    with store._lock:
        g = store._templates[template]["groups"][table]
        return [[c for c, k in zip(cs, ks) if k == "cap"]
                for cs, ks in zip(g["caps"], g["kinds"])]


# -- schedule adaptation unit ------------------------------------------------

def test_adapt_schedule_falls_back_and_clamps():
    dec = [("exact", 3), ("cap", 7), ("cap", 2)]
    # no observations / structural drift -> plain morsel inflation
    assert adapt_schedule(dec, 4096, None) == inflate_schedule(dec, 4096)
    assert adapt_schedule(dec, 4096, [3, 7]) == inflate_schedule(dec, 4096)
    # observed maxima replace the morsel bound, record actual still floors
    adapted = adapt_schedule(dec, 4096, [3, 100, 1])
    assert adapted == [("exact", 3), ("cap", 100), ("cap", 2)]


def test_member_caps_requires_structural_match():
    fb = FeedbackStore()
    fb.observe_group("t", "big", bound=4096, fused=False, shards=0,
                     kinds=[["exact", "cap"]], caps=[[3, 9]])
    ok = fb.member_caps("t", "big", 0, ["exact", "cap"], 4096, False, 0)
    assert ok == [3, 9]
    assert fb.member_caps("t", "big", 0, ["cap", "cap"], 4096,
                          False, 0) is None          # kinds drift
    assert fb.member_caps("t", "big", 0, ["exact", "cap"], 8192,
                          False, 0) is None          # bound drift
    assert fb.member_caps("t", "big", 0, ["exact", "cap"], 4096,
                          True, 0) is None           # fusion drift
    assert fb.member_caps("t", "big", 1, ["exact", "cap"], 4096,
                          False, 0) is None          # no such member


# -- the q9-class right-size -------------------------------------------------

def test_second_sighting_rightsizes_caps_bit_identically():
    """First sighting provisions the morsel bucket; the second re-records
    from observed actuals and drops every group-by capacity to the
    minimal ladder bucket — responses hash-identical throughout."""
    s = make_session(adaptive_plans=True)
    s.register_arrow("big", low_card())
    h0, _r0, a0 = counters()
    ref = arrow_rows(s.sql(Q, label="q9ish"))
    assert FEEDBACK_HITS.value == h0          # nothing to consume yet
    assert s._feedback.stamp("q9ish") > 0     # ...but it observed
    out2 = arrow_rows(s.sql(Q, label="q9ish"))
    assert FEEDBACK_HITS.value == h0 + 1      # profile consumed
    assert ADAPTIVE_REPLANS.value == a0 + 1   # stamp-driven re-plan
    out3 = arrow_rows(s.sql(Q, label="q9ish"))  # steady state: replay
    assert FEEDBACK_HITS.value == h0 + 1
    assert out2 == ref and out3 == ref
    # the observed profile needs the MINIMAL bucket, not the morsel one
    cells = cap_cells(s._feedback, "q9ish", "big")
    assert all(c <= 8 for row in cells for c in row)
    applied = s._feedback.applied["q9ish"]
    assert applied["cap_cells_after"] * 100 <= applied["cap_cells_before"]


def test_observed_estimates_override_catalog(tmp_path):
    """The catalog prefers the store's observed table rows over the
    registered static estimate on the next sighting of the template."""
    s = make_session(adaptive_plans=True)
    s.register_arrow("big", low_card())
    assert s._est_rows_for("big", 0, "t") == 20000   # registered estimate
    s.sql(Q, label="t")
    # the streamed pass observed the exact row count; same answer here,
    # but through the feedback store now
    assert s._feedback.table_rows("t")["big"] == 20000
    assert s._est_rows_for("big", 0, "t") == 20000
    # a label that never streamed keeps the static estimate
    assert s._est_rows_for("big", 0, "other") == 20000


# -- ceiling hint: under-observation re-records, never mis-answers -----------

def test_underobserved_hint_rerecords_and_stays_exact():
    """A profile observed on low-cardinality data replayed against grown
    data overflows the adapted schedule mid-stream; the engine re-records
    eagerly (adaptive_replans moves) and the answer stays exact."""
    s = make_session(adaptive_plans=True)
    s.register_arrow("big", low_card())
    for _ in range(2):
        s.sql(Q, label="grow")        # observe + adapt on low-card data
    assert all(c <= 8 for row in cap_cells(s._feedback, "grow", "big")
               for c in row)
    # grown data: morsel 1 keeps the low cardinality (so the record pass
    # cannot see what is coming), morsel 2+ explodes the group count past
    # the adapted ceiling
    rng = np.random.default_rng(1)
    k = np.concatenate([rng.integers(0, 5, 4096),
                        rng.integers(0, 3000, 8192)])
    v = rng.integers(0, 100, k.size)
    grown = pa.table({"k": k, "v": v})
    s.register_arrow("big", grown)    # generation bump clears stream cache
    a0 = ADAPTIVE_REPLANS.value
    out = arrow_rows(s.sql(Q, label="grow"))
    assert ADAPTIVE_REPLANS.value > a0         # overflow -> eager re-record
    oracle = make_session()
    oracle.register_arrow("big", grown)
    assert out == arrow_rows(oracle.sql(Q, backend="numpy", label="grow"))
    # ...and the store now provisions for what was actually seen
    assert any(c > 8 for row in cap_cells(s._feedback, "grow", "big")
               for c in row)


def test_drift_sentinel_refreshes_stale_profile():
    """Observed actuals collapsing below the stored profile by the drift
    ratio refresh the profile (feedback_refreshes) and bump the template
    generation, so the next sighting re-plans down."""
    s = make_session(adaptive_plans=True, feedback_drift_ratio=4.0)
    rng = np.random.default_rng(2)
    s.register_arrow("big", pa.table({
        "k": rng.integers(0, 3000, 12288),
        "v": rng.integers(0, 100, 12288)}))
    for _ in range(2):
        s.sql(Q, label="shrink")      # profile at high cardinality
    assert any(c > 1000 for row in cap_cells(s._feedback, "shrink", "big")
               for c in row)
    r0 = FEEDBACK_REFRESHES.value
    s.register_arrow("big", low_card(n=12288))
    gen_before = s._feedback.stamp("shrink")
    ref = arrow_rows(s.sql(Q, label="shrink"))
    assert FEEDBACK_REFRESHES.value > r0       # sentinel fired
    assert s._feedback.stamp("shrink") > gen_before
    out = arrow_rows(s.sql(Q, label="shrink"))  # re-plans from fresh profile
    assert out == ref
    assert all(c <= 8 for row in cap_cells(s._feedback, "shrink", "big")
               for c in row)


# -- off is off ---------------------------------------------------------------

def test_disabled_mode_builds_no_store_and_moves_no_counters():
    before = counters()
    s = make_session()                # adaptive_plans defaults False
    s.register_arrow("big", low_card())
    ref = arrow_rows(s.sql(Q, label="off"))
    assert arrow_rows(s.sql(Q, label="off")) == ref
    assert s._feedback is None
    assert counters() == before
    assert "decision_rows" not in s.last_exec_stats.get("extra", {})


# -- log <-> store equivalence ------------------------------------------------

def test_query_log_replay_reconstructs_live_observations(tmp_path):
    """The query log's node_stats column replayed through replay_log
    yields the SAME per-node actuals the live session observed."""
    ql = str(tmp_path / "qlog.jsonl")
    s = make_session(adaptive_plans=True, query_log=True,
                     query_log_path=ql)
    s.register_arrow("big", low_card())
    for label in ("qa", "qb"):
        for _ in range(3):
            s.sql(Q, label=label)
    QUERY_LOG.flush()
    rows = read_jsonl(ql)
    assert any(r.get("node_stats") for r in rows)
    offline = FeedbackStore()
    assert offline.replay_log(rows) > 0
    for label in ("qa", "qb"):
        live = s._feedback.node_rows(label)
        assert live and offline.node_rows(label) == live
    # ring rows replay identically to file rows (they are the same rows)
    ring = FeedbackStore()
    ring.replay_log(QUERY_LOG.rows())
    assert ring.node_rows("qa") == offline.node_rows("qa")


# -- persistence --------------------------------------------------------------

def test_store_roundtrips_at_attach_and_fails_soft(tmp_path):
    fbp = str(tmp_path / "plan_feedback.json")
    s = make_session(adaptive_plans=True, feedback_path=fbp)
    s.register_arrow("big", low_card())
    for _ in range(2):
        s.sql(Q, label="persist")
    s._feedback.flush()
    doc = json.load(open(fbp))
    assert doc["version"] == 1 and "persist" in doc["templates"]
    # a fresh session warm-starts: the FIRST sighting already adapts
    h0 = FEEDBACK_HITS.value
    s2 = make_session(adaptive_plans=True, feedback_path=fbp)
    s2.register_arrow("big", low_card())
    ref = arrow_rows(s.sql(Q, label="persist"))
    assert arrow_rows(s2.sql(Q, label="persist")) == ref
    assert FEEDBACK_HITS.value > h0
    # derived placement: beside the query log when only that is set
    ql = str(tmp_path / "logs" / "q.jsonl")
    s3 = make_session(adaptive_plans=True, query_log=True,
                      query_log_path=ql)
    assert s3._feedback.path == str(tmp_path / "logs" /
                                    "plan_feedback.json")
    # unreadable document: advisory store starts empty, engine still runs
    with open(fbp, "w") as f:
        f.write("{corrupt")
    s4 = make_session(adaptive_plans=True, feedback_path=fbp)
    s4.register_arrow("big", low_card())
    assert s4._feedback.stamp("persist") == 0
    assert arrow_rows(s4.sql(Q, label="persist")) == ref


# -- system.plan_feedback -----------------------------------------------------

def test_plan_feedback_table_serves_store_facts():
    s = make_session(adaptive_plans=True)
    s.register_arrow("big", low_card())
    for _ in range(2):
        s.sql(Q, label="sysq")
    rows = arrow_rows(s.sql(
        "SELECT template, kind, node, rows FROM system.plan_feedback "
        "ORDER BY kind, node"))
    kinds = {r["kind"] for r in rows}
    assert {"node", "table", "cap"} <= kinds
    by_kind = {k: [r for r in rows if r["kind"] == k] for k in kinds}
    assert any(r["rows"] == 20000 for r in by_kind["table"])
    assert all(r["template"] == "sysq" for r in rows)
    # adaptive off: the table exists and is empty
    s2 = make_session()
    s2.register_arrow("big", low_card())
    assert arrow_rows(s2.sql(
        "SELECT template FROM system.plan_feedback")) == []
