"""Arrow-IPC front door + weighted-fair scheduling + cross-process cache.

The contract under test is the distributed-serving acceptance bar:
every result a REMOTE client receives must be bit-identical to running
the same SQL alone on a fresh single-caller Session — through the wire
frame codec, across a real OS process boundary, under the weighted-fair
scheduler, mid-stream at morsel-boundary preemption points, and through
the snapshot-warmed client cache; every failure that crosses the wire
must reconstruct as its real typed resilience class; and with every new
knob off, the in-process service is bit-identical to before this layer
existed with all six new counters pinned STRICT-ZERO.
"""
import io
import json
import os
import struct
import subprocess
import sys
import time

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.chaos import build_demo_session, demo_pool
from nds_tpu.obs.metrics import METRICS
from nds_tpu.resilience import (AdmissionRejected, CircuitOpen,
                                DeadlineExceeded, FaultError,
                                TransientError)
from nds_tpu.service import (ConnectionDropped, FlightClient,
                             FrontDoorServer, QueryService, RemoteQueryError,
                             ServiceConfig)
from nds_tpu.service.frontdoor import (_error_doc, read_frame,
                                       reconstruct_error, result_hash,
                                       write_frame)
from nds_tpu.service.service import ServiceClosed, _FairReadyQueue

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the six counters PR 18 adds — all must stay zero on any workload that
#: does not opt into the front door / fair queue / dedup
NEW_COUNTERS = ("frontdoor_requests", "frontdoor_errors",
                "service_preemptions", "service_inflight_dedup",
                "result_cache_snapshots", "frontdoor_client_cache_hits")


# -- frame codec --------------------------------------------------------------

def _pipe():
    return io.BytesIO()


def test_frame_roundtrip():
    buf = _pipe()
    write_frame(buf, {"op": "ping", "x": [1, 2]}, b"payload")
    buf.seek(0)
    header, body = read_frame(buf)
    assert header == {"op": "ping", "x": [1, 2]}
    assert body == b"payload"


def test_frame_empty_body():
    buf = _pipe()
    write_frame(buf, {"ok": True})
    buf.seek(0)
    _, body = read_frame(buf)
    assert body == b""


def test_frame_header_bound_refused():
    buf = _pipe()
    buf.write(struct.pack(">I", (1 << 20) + 1))
    buf.seek(0)
    with pytest.raises(ValueError, match="header"):
        read_frame(buf)


def test_frame_body_bound_refused():
    h = json.dumps({"op": "q"}).encode()
    buf = _pipe()
    buf.write(struct.pack(">I", len(h)) + h
              + struct.pack(">Q", (1 << 28) + 1))
    buf.seek(0)
    with pytest.raises(ValueError, match="body"):
        read_frame(buf)


def test_frame_eof_is_connection_dropped():
    buf = _pipe()
    write_frame(buf, {"op": "ping"}, b"full body here")
    trunc = io.BytesIO(buf.getvalue()[:-5])
    with pytest.raises(ConnectionDropped):
        read_frame(trunc)


# -- typed errors across the wire (unit) --------------------------------------

@pytest.mark.parametrize("err", [
    AdmissionRejected("queue full", depth=9, limit=8),
    ServiceClosed("closing", depth=1, limit=2),
    CircuitOpen("tripped", error_class="FaultError", retry_after_s=0.5),
    DeadlineExceeded("budget spent"),
    FaultError("injected"),
    TransientError("flaky"),
    TimeoutError("no answer"),
])
def test_error_reconstruction_roundtrip(err):
    doc = json.loads(json.dumps(_error_doc(err)))   # through the wire
    back = reconstruct_error(doc)
    assert type(back) is type(err)
    assert str(back) == str(err)
    for field in ("depth", "limit", "error_class", "retry_after_s"):
        assert getattr(back, field, None) == getattr(err, field, None)


def test_unknown_error_class_lands_typed():
    back = reconstruct_error({"cls": "ExoticServerError", "msg": "boom"})
    assert isinstance(back, RemoteQueryError)
    assert back.cls == "ExoticServerError"
    assert "boom" in str(back)


# -- weighted-fair ready queue (injected clock: charge() IS the clock) --------

class _T:
    """Minimal ticket stand-in."""

    def __init__(self, tenant, label, streams=False):
        self.tenant = tenant
        self.label = label
        self.streams = streams

    def __repr__(self):
        return self.label


def test_fair_queue_serves_least_served_tenant():
    q = _FairReadyQueue({"a": 1.0, "b": 1.0})
    for i in range(2):
        q.append(_T("a", f"a{i}"))
        q.append(_T("b", f"b{i}"))
    order = []
    # charge each pop 1s: equal weights alternate a/b
    while q:
        t = q.popleft()
        order.append(t.label)
        q.charge(t.tenant, 1.0)
    assert order == ["a0", "b0", "a1", "b1"]


def test_fair_queue_weights_split_the_lane():
    # weight 2 vs 1: over 6 equal-cost serves, "big" gets 4 and "small" 2
    q = _FairReadyQueue({"big": 2.0, "small": 1.0})
    for i in range(6):
        q.append(_T("big", f"big{i}"))
    for i in range(3):
        q.append(_T("small", f"small{i}"))
    served = []
    for _ in range(6):
        t = q.popleft()
        served.append(t.tenant)
        q.charge(t.tenant, 1.0)
    assert served.count("big") == 4
    assert served.count("small") == 2


def test_fair_queue_reactivation_joins_at_floor_no_burst():
    q = _FairReadyQueue({})
    q.append(_T("busy", "busy0"))
    for i in range(5):      # busy runs alone and accrues vtime
        q.popleft()
        q.charge("busy", 1.0)
        q.append(_T("busy", f"busy{i + 1}"))
    # idle tenant arrives: it must NOT owe the busy tenant's history
    # (starvation) and must NOT get unlimited credit (burst) — it joins
    # at the floor, then alternates fairly
    q.append(_T("idle", "idle0"))
    q.append(_T("idle", "idle1"))
    first = q.popleft()
    assert first.tenant == "idle"
    q.charge("idle", 1.0)
    second = q.popleft()
    assert second.tenant == "busy"


def test_fair_queue_pop_preemptable_skips_streamed():
    q = _FairReadyQueue({})
    q.append(_T("a", "stream0", streams=True))
    q.append(_T("a", "incore0"))
    t = q.pop_preemptable()
    assert t.label == "incore0"
    assert len(q) == 1          # the streamed ticket stayed queued
    assert q.pop_preemptable() is None
    assert q.popleft().label == "stream0"


def test_fair_queue_deque_surface():
    q = _FairReadyQueue({})
    assert not q
    with pytest.raises(IndexError):
        q.popleft()
    q.append(_T("a", "x"))
    q.append(_T("b", "y"))
    assert len(q) == 2 and bool(q)
    assert {t.label for t in q} == {"x", "y"}
    q.clear()
    assert len(q) == 0


# -- in-process wire round trip -----------------------------------------------

@pytest.fixture(scope="module")
def demo(tmp_path_factory):
    work = str(tmp_path_factory.mktemp("fd_demo"))
    session = build_demo_session(work)
    pool = demo_pool()
    baseline, hashes = {}, {}
    # the tiny dim group-by: the cheapest real query a FRESH engine
    # process can compile (~1.5s vs ~11s for the streamed group-by) —
    # the subprocess round-trip tests use it to keep tier-1 wall down
    tiny = "SELECT grp, COUNT(*) AS n FROM dim GROUP BY grp ORDER BY grp"
    for _label, sql in pool + [("tiny#0", tiny)]:
        table = session.sql(sql, label="base")
        baseline[sql] = table.to_pylist()
        hashes[sql] = result_hash(table)
    return {"work": work, "pool": pool, "tiny": tiny,
            "baseline": baseline, "hashes": hashes}


def fresh_service(work_dir, **svc_kw):
    session = build_demo_session(os.path.join(work_dir, "live"))
    return QueryService(session, ServiceConfig(**svc_kw))


@pytest.mark.slow  # demo-warehouse compile; CI frontdoor stage runs these
def test_wire_round_trip_bit_identical(demo, tmp_path):
    with fresh_service(str(tmp_path)) as svc, \
            FrontDoorServer(svc) as door, \
            FlightClient("127.0.0.1", door.port) as c:
        assert c.ping()["ok"]
        for label, sql in demo["pool"]:
            table, hdr = c.query(sql, label=label, want_hash=True)
            # Arrow row dicts vs engine tuples: compare values in order
            got = [tuple(r.values()) for r in table.to_pylist()]
            assert got == demo["baseline"][sql], label
            assert hdr["stats"]["queue_wait_ms"] is not None


@pytest.mark.slow
def test_wire_typed_errors(demo, tmp_path):
    with fresh_service(str(tmp_path)) as svc, \
            FrontDoorServer(svc) as door, \
            FlightClient("127.0.0.1", door.port) as c:
        # a queued deadline of ~0 expires before the lane: the client
        # must receive the REAL DeadlineExceeded class
        with pytest.raises(DeadlineExceeded):
            c.query(demo["pool"][0][1], deadline_s=1e-6)
        # an engine-level failure with no resilience class still lands
        # typed, carrying the server-side class name
        with pytest.raises(RemoteQueryError) as ei:
            c.query("SELECT nope FROM no_such_table")
        assert ei.value.cls
        # an unknown op is a protocol error, not a hangup
        with pytest.raises(RemoteQueryError):
            c._rpc({"op": "warp_drive"})
        # the connection survived all three errors
        assert c.ping()["ok"]


@pytest.mark.slow
def test_wire_closed_service_is_typed(demo, tmp_path):
    svc = fresh_service(str(tmp_path))
    svc.start()
    door = FrontDoorServer(svc).start()
    c = FlightClient("127.0.0.1", door.port)
    try:
        svc.close()
        with pytest.raises(ServiceClosed):
            c.query(demo["pool"][0][1])
    finally:
        c.close()
        door.stop()


@pytest.mark.slow
def test_chaos_op_refused_without_allow(demo, tmp_path):
    with fresh_service(str(tmp_path)) as svc, \
            FrontDoorServer(svc) as door, \
            FlightClient("127.0.0.1", door.port) as c:
        with pytest.raises(PermissionError):
            c.chaos(["frontdoor.drop:raise#1"])


# -- multi-process round trip -------------------------------------------------

def _spawn_server(extra, timeout_s=180.0):
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "frontdoor_server.py")] + extra,
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
    line = proc.stdout.readline()
    assert line.startswith("FRONTDOOR "), f"server never came up: {line!r}"
    return proc, json.loads(line.split(" ", 1)[1])


@pytest.mark.slow  # spawns a real server process (fresh XLA compile)
def test_multiprocess_round_trip(demo):
    """Two real OS client processes against one engine process: results
    hash-identical to the in-process serial baseline (the server ships
    its canonical engine-table hash per response)."""
    # the join + streamed templates cross the same wire in the
    # in-process suite above; the fresh server process gets the cheap
    # query so this test measures the PROCESS BOUNDARY, not XLA compile
    sql = demo["tiny"]
    base_hash = {sql: demo["hashes"][sql]}
    proc, info = _spawn_server(["--demo"])
    try:
        assert info["pid"] != os.getpid()
        client_src = (
            "import json,sys\n"
            "sys.path.insert(0, %r)\n"
            "from nds_tpu.service import FlightClient\n"
            "c = FlightClient('127.0.0.1', %d)\n"
            "out = {}\n"
            "for sql in json.loads(sys.argv[1]):\n"
            "    _t, hdr = c.query(sql, want_hash=True)\n"
            "    out[sql] = hdr['result_hash']\n"
            "print(json.dumps(out))\n" % (REPO, info["port"]))
        sqls = json.dumps(list(base_hash))
        clients = [subprocess.Popen(
            [sys.executable, "-c", client_src, sqls],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
            for _ in range(2)]          # CONCURRENT, like real clients
        for r in clients:
            out, err = r.communicate(timeout=180)
            assert r.returncode == 0, err[-800:]
            got = json.loads(out.strip().splitlines()[-1])
            assert got == base_hash
    finally:
        proc.stdin.close()
        assert proc.wait(timeout=60) == 0


# -- preemption: bit-identity at morsel boundaries ----------------------------

@pytest.mark.slow
def test_preemption_bit_identity(tmp_path):
    """A streamed query preempted at morsel boundaries returns exactly
    the bytes the unpreempted run returns, and the interactive tickets
    served inside its yield points are exact too."""
    stream_sql = demo_pool()[-1][1]
    incore_sql = demo_pool()[0][1]
    # tiny morsels: ~60 yield points across the streamed sfact scan —
    # but the 20k-row fact table must stay IN-CORE (min_rows above it),
    # because streamed tickets are never preemptors
    demo_kw = dict(chunk_rows=1024, out_of_core_min_rows=30_000)
    ref = build_demo_session(str(tmp_path / "ref"), **demo_kw)
    want_stream = ref.sql(stream_sql, label="ref").to_pylist()
    want_incore = ref.sql(incore_sql, label="ref").to_pylist()

    session = build_demo_session(str(tmp_path / "live"), **demo_kw)
    cfg = ServiceConfig(fair_queue=True, preemption=True,
                        tenant_weights={"interactive": 4, "batch": 1})
    before = METRICS.snapshot()
    with QueryService(session, cfg) as svc:
        # warm the in-core template so preempted dispatches adopt the
        # shared program instead of compiling inside the yield point
        svc.sql(incore_sql, label="warm", tenant="interactive")
        t_stream = svc.submit(stream_sql, label="long-scan",
                              tenant="batch")
        # wait until the scan OWNS the lane (mark_started fired), then
        # inject interactive arrivals: they are planned off-lane and can
        # only complete mid-stream through its morsel-boundary yields
        t0 = time.time()
        while t_stream.queue_wait_ms is None and time.time() - t0 < 60:
            time.sleep(0.001)
        assert t_stream.queue_wait_ms is not None
        t_int = [svc.submit(incore_sql, label=f"int{i}",
                            tenant="interactive") for i in range(4)]
        got_stream = t_stream.result(timeout=300).to_pylist()
        for t in t_int:
            assert t.result(timeout=300).to_pylist() == want_incore
    after = METRICS.snapshot()
    assert got_stream == want_stream
    preempts = after.get("service_preemptions", 0) \
        - before.get("service_preemptions", 0)
    assert preempts >= 1, "no interactive ticket was served mid-stream"
    assert t_stream.preempted == preempts


@pytest.mark.slow
def test_preempted_count_lands_in_query_log(tmp_path):
    from nds_tpu.obs.query_log import COLUMNS
    assert ("preempted", "int") in tuple(COLUMNS)


# -- in-flight dedup ----------------------------------------------------------

@pytest.mark.slow
def test_inflight_dedup_leader_and_follower_share(tmp_path):
    # dedup keys on the parameterized-plan fingerprint, which only
    # non-streamed tickets carry — keep the 20k-row fact in-core
    session = build_demo_session(str(tmp_path / "live"),
                                 out_of_core_min_rows=30_000)
    sql = demo_pool()[0][1]
    with QueryService(session,
                      ServiceConfig(inflight_dedup=True)) as svc:
        svc.sql(sql, label="warm")
        before = METRICS.snapshot()
        with svc.hold_dispatch():
            leader = svc.submit(sql, label="leader")
            # wait for the leader to reach the ready queue, then the
            # follower's identical (fp, params, gens, snap) key parks it
            t0 = time.time()
            while time.time() - t0 < 10:
                with svc._cv:
                    if len(svc._ready) >= 1:
                        break
                time.sleep(0.01)
            follower = svc.submit(sql, label="follower")
            t0 = time.time()
            while time.time() - t0 < 10:
                if METRICS.snapshot().get("service_inflight_dedup", 0) \
                        > before.get("service_inflight_dedup", 0):
                    break
                time.sleep(0.01)
        a = leader.result(timeout=120)
        b = follower.result(timeout=120)
        after = METRICS.snapshot()
        assert a.to_pylist() == b.to_pylist()
        assert follower.stats.mode == "deduped"
        assert after["service_inflight_dedup"] \
            - before.get("service_inflight_dedup", 0) == 1
        # exactly one execution: the ready queue saw one ticket
        assert leader.stats.mode != "deduped"


@pytest.mark.slow
def test_dedup_distinct_params_do_not_share(tmp_path):
    session = build_demo_session(str(tmp_path / "live"),
                                 out_of_core_min_rows=30_000)
    pool = demo_pool()
    with QueryService(session,
                      ServiceConfig(inflight_dedup=True)) as svc:
        before = METRICS.snapshot()
        with svc.hold_dispatch():
            t1 = svc.submit(pool[0][1], label="p0")
            t2 = svc.submit(pool[1][1], label="p1")
        t1.result(timeout=120)
        t2.result(timeout=120)
        after = METRICS.snapshot()
        assert after.get("service_inflight_dedup", 0) \
            == before.get("service_inflight_dedup", 0)


# -- cross-process cache sharing ----------------------------------------------

@pytest.mark.slow
def test_cache_snapshot_warm_and_invalidate_on_commit(tmp_path):
    from nds_tpu.engine.result_cache import ResultCacheConfig
    session = build_demo_session(str(tmp_path / "live"))
    sql = demo_pool()[0][1]
    cfg = ServiceConfig(result_cache=ResultCacheConfig())
    with QueryService(session, cfg) as svc, \
            FrontDoorServer(svc) as door:
        with FlightClient("127.0.0.1", door.port, use_cache=True) as c:
            want = [tuple(r.values())
                    for r in c.sql(sql, label="seed").to_pylist()]
            before = METRICS.snapshot()
            n = c.warm_cache()
            assert n >= 1
            # warmed entry revalidates True -> answered from client memory
            table, hdr = c.query(sql, label="hit")
            assert hdr.get("cache") == "client"
            assert [tuple(r.values()) for r in table.to_pylist()] == want
            after = METRICS.snapshot()
            assert after["frontdoor_client_cache_hits"] \
                - before.get("frontdoor_client_cache_hits", 0) == 1
            assert after["result_cache_snapshots"] \
                - before.get("result_cache_snapshots", 0) == 1

            # a catalog commit on the engine: the warmed entry must
            # validate FALSE on its next use and the refetched result
            # must reflect the NEW data — never a stale serve
            rng = np.random.default_rng(99)
            fact2 = pa.table({
                "fk": pa.array(rng.integers(0, 40, 5_000),
                               type=pa.int64()),
                "qty": pa.array(rng.integers(1, 100, 5_000),
                                type=pa.int64()),
            })
            session.register_arrow("fact", fact2)
            hits0 = METRICS.snapshot()["frontdoor_client_cache_hits"]
            table2, hdr2 = c.query(sql, label="post-commit")
            assert hdr2.get("cache") != "client"
            assert METRICS.snapshot()["frontdoor_client_cache_hits"] \
                == hits0, "stale client entry served after a commit"
            fresh = build_demo_session(str(tmp_path / "ref"))
            fresh.register_arrow("fact", fact2)
            want2 = fresh.sql(sql, label="ref").to_pylist()
            assert [tuple(r.values())
                    for r in table2.to_pylist()] == want2


@pytest.mark.slow
def test_cache_epoch_mismatch_invalidates_everything(tmp_path):
    from nds_tpu.engine.result_cache import ResultCacheConfig
    session = build_demo_session(str(tmp_path / "live"))
    sql = demo_pool()[0][1]
    cfg = ServiceConfig(result_cache=ResultCacheConfig())
    with QueryService(session, cfg) as svc:
        with FrontDoorServer(svc) as door:
            with FlightClient("127.0.0.1", door.port,
                              use_cache=True) as c:
                c.sql(sql, label="seed")
                assert c.warm_cache() >= 1
        # server restart: a FRESH FrontDoorServer (new epoch) over the
        # same service — the surviving client entry must not hit
        with FrontDoorServer(svc) as door2:
            with FlightClient("127.0.0.1", door2.port,
                              use_cache=True) as c2:
                c2._cache = c._cache       # inherit the warmed set
                hits0 = METRICS.snapshot().get(
                    "frontdoor_client_cache_hits", 0)
                _t, hdr = c2.query(sql, label="post-restart")
                assert hdr.get("cache") != "client"
                assert METRICS.snapshot().get(
                    "frontdoor_client_cache_hits", 0) == hits0


# -- engine-kill chaos round --------------------------------------------------

@pytest.mark.slow
def test_engine_kill_mid_query_typed(demo):
    """frontdoor.kill hard-exits the engine process before a dispatch:
    the client's failure is TYPED (ConnectionDropped IS-A
    TransientError) and the exit signature proves the injected kill,
    not a crash."""
    proc, info = _spawn_server(["--demo", "--allow_chaos"])
    c = FlightClient("127.0.0.1", info["port"], retries=0)
    try:
        c.chaos(["frontdoor.kill:raise#1"])
        with pytest.raises(ConnectionDropped):
            c.query(demo["pool"][0][1], label="doomed")
        assert proc.wait(timeout=60) == 86
    finally:
        c.close()
        if proc.poll() is None:
            proc.kill()


@pytest.mark.slow
def test_connection_drop_retry_recovers(demo, tmp_path):
    """frontdoor.drop severs the socket instead of replying; the client
    reconnect-retry loop re-submits and the final answer is exact."""
    from nds_tpu.resilience import FAULTS
    with fresh_service(str(tmp_path)) as svc, \
            FrontDoorServer(svc, allow_chaos=True) as door:
        with FlightClient("127.0.0.1", door.port, retries=3) as c:
            try:
                # two firings: the armed drop severs the ARM reply
                # itself first (arming still took), then the query reply
                c.chaos(["frontdoor.drop:raise#2"])
            except ConnectionDropped:
                pass
            sql = demo["pool"][0][1]
            try:
                table, _ = c.query(sql, label="survivor")
            finally:
                FAULTS.configure([])
            got = [tuple(r.values()) for r in table.to_pylist()]
            assert got == demo["baseline"][sql]


# -- off-mode: bit-identical, counters STRICT-ZERO ----------------------------

@pytest.mark.slow
def test_off_mode_bit_identical_and_counters_zero(demo, tmp_path):
    """The plain in-process service (every PR-18 knob at its default)
    must behave exactly as before this layer existed: same results, and
    all six new counters pinned at zero."""
    session = build_demo_session(str(tmp_path / "live"))
    before = METRICS.snapshot()
    with QueryService(session, ServiceConfig()) as svc:
        for label, sql in demo["pool"]:
            got = svc.sql(sql, label=label).to_pylist()
            assert got == demo["baseline"][sql], label
    after = METRICS.snapshot()
    for name in NEW_COUNTERS:
        assert after.get(name, 0) == before.get(name, 0), \
            f"{name} moved on an off-mode workload"


@pytest.mark.slow
def test_fair_queue_on_results_still_bit_identical(demo, tmp_path):
    """fair_queue changes ORDER, never CONTENT."""
    session = build_demo_session(str(tmp_path / "live"))
    cfg = ServiceConfig(fair_queue=True,
                        tenant_weights={"t0": 3, "t1": 1})
    with QueryService(session, cfg) as svc:
        tickets = [(svc.submit(sql, label=label, tenant=f"t{i % 2}"),
                    sql)
                   for i, (label, sql) in enumerate(demo["pool"])]
        for t, sql in tickets:
            assert t.result(timeout=300).to_pylist() \
                == demo["baseline"][sql]
