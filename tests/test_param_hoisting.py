"""Stream-parameter hoisting: statements differing only in numeric/date
literals must compile to IDENTICAL XLA programs (reference frame: dsqgen
re-instantiates templates per stream, nds/nds_gen_query_stream.py:42-89,
and Spark re-plans in milliseconds — here the persistent compile cache
serves every stream after the first because the programs are the same)."""
import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.engine import Session
from nds_tpu.engine.plan import (BLit, BParam, deparameterize_plan,
                                 parameterize_plan)


def _session():
    rng = np.random.default_rng(21)
    n = 4000
    s = Session()
    s.register_arrow("fact", pa.table({
        "fk": pa.array(rng.integers(0, 40, n), type=pa.int64()),
        "qty": pa.array(rng.integers(1, 100, n), type=pa.int64()),
        "price": pa.array(np.round(rng.uniform(1, 99, n), 2)),
        "cat": pa.array(rng.choice(["alpha", "beta", "gamma"], n)),
        "day": pa.array(rng.integers(0, 30, n), type=pa.int64()),
    }))
    s.register_arrow("dim", pa.table({
        "dk": pa.array(np.arange(40), type=pa.int64()),
        "nm": pa.array([f"n{i % 5}" for i in range(40)]),
    }))
    return s


def _lowered(s, sql):
    """Record + compile, then return the lowered program text."""
    expected = sorted(map(tuple, s.sql(sql, backend="numpy").to_pylist()),
                      key=repr)
    for _ in range(3):
        got = sorted(map(tuple, s.sql(sql, backend="jax").to_pylist()),
                     key=repr)
        assert got == expected
    jexec = s._jax_executor()
    ent = jexec._plans.get(("sql", sql)) or \
        jexec._plans.get((("sql", sql), "root"))
    assert ent and ent.get("cq") is not None
    cq = ent["cq"]
    return cq._fn.lower(*cq._args(jexec._scans_for(ent),
                                  ent["params"])).as_text(), ent


STREAM_PAIRS = [
    # numeric filter + join + agg: the q3-class shape
    ("SELECT d.nm, SUM(f.qty) FROM fact f JOIN dim d ON f.fk = d.dk "
     "WHERE f.day > {p0} AND f.qty < {p1} GROUP BY d.nm",
     {"p0": (3, 11), "p1": (80, 55)}),
    # arithmetic + IN-list + CASE
    ("SELECT fk, CASE WHEN qty > {p0} THEN qty * {p1} ELSE 0 END FROM fact "
     "WHERE day IN ({p2}, {p3})",
     {"p0": (50, 70), "p1": (2, 5), "p2": (1, 9), "p3": (4, 22)}),
]


@pytest.mark.parametrize("tpl,subs", STREAM_PAIRS, ids=range(len(STREAM_PAIRS)))
def test_streams_share_compiled_program(tpl, subs):
    s = _session()
    texts = []
    for stream in (0, 1):
        sql = tpl.format(**{k: v[stream] for k, v in subs.items()})
        text, ent = _lowered(s, sql)
        assert len(ent["params"]) >= 2     # literals actually hoisted
        texts.append(text)
    assert texts[0] == texts[1], "streams must lower to identical programs"


def test_param_values_recorded_in_entry():
    s = _session()
    sql = "SELECT COUNT(*) FROM fact WHERE qty > 42 AND day = 7"
    _, ent = _lowered(s, sql)
    assert 42 in ent["params"] and 7 in ent["params"]


def test_parameterize_roundtrip():
    """deparameterize(parameterize(plan)) restores the original literals."""
    from nds_tpu.sql import parse_sql
    from nds_tpu.engine.planner import Planner

    s = _session()
    plan = Planner(s._catalog()).plan_query(
        parse_sql("SELECT fk FROM fact WHERE qty > 10 AND day < 20"))
    pplan, values, dtypes = parameterize_plan(plan)
    assert values == [10, 20] and dtypes == ["int", "int"]
    restored = deparameterize_plan(pplan, values)
    from nds_tpu.engine.plan import iter_plan_nodes
    import dataclasses

    def lits(p):
        out = []
        stack = [p]
        while stack:
            x = stack.pop()
            if isinstance(x, BLit):
                out.append((x.dtype, x.value))
            if isinstance(x, BParam):
                out.append(("PARAM", x.index))
            if dataclasses.is_dataclass(x) and not isinstance(x, type):
                stack.extend(getattr(x, f.name)
                             for f in dataclasses.fields(x))
            elif isinstance(x, (list, tuple)):
                stack.extend(x)
    # no BParam survives deparameterize; literal multiset matches original
    assert sorted(map(repr, lits(restored) or [])) == \
        sorted(map(repr, lits(plan) or []))


def test_string_literals_stay_baked():
    """String params can't hoist (trace-time dictionary work): correctness
    must survive, with the literal baked into the program."""
    s = _session()
    for cat in ("alpha", "beta"):
        sql = f"SELECT COUNT(*) FROM fact WHERE cat = '{cat}' AND qty > 10"
        expected = s.sql(sql, backend="numpy").to_pylist()
        for _ in range(3):
            assert s.sql(sql, backend="jax").to_pylist() == expected


def test_cross_stream_program_adoption():
    """The second stream variant of a template must ADOPT the first's
    recorded schedule + compiled program (no re-record): VERDICT r4 #4 —
    bucket-coincident streams previously still re-recorded and re-traced
    per sql text; the shared-program registry keys on the parameterized
    plan fingerprint instead."""
    s = _session()
    sql_a = ("SELECT d.nm, SUM(f.qty) FROM fact f JOIN dim d ON f.fk = d.dk "
             "WHERE f.day > 3 GROUP BY d.nm")
    sql_b = ("SELECT d.nm, SUM(f.qty) FROM fact f JOIN dim d ON f.fk = d.dk "
             "WHERE f.day > 11 GROUP BY d.nm")
    _lowered(s, sql_a)          # records + compiles stream A
    jexec = s._jax_executor()
    expected = sorted(map(tuple, s.sql(sql_b, backend="numpy").to_pylist()),
                      key=repr)
    got = sorted(map(tuple, s.sql(sql_b, backend="jax").to_pylist()),
                 key=repr)
    assert got == expected
    ent_b = jexec._plans.get(("sql", sql_b))
    assert ent_b is not None and ent_b.get("cq") is not None, \
        "stream B must run compiled on FIRST sighting (adopted program)"
    ent_a = jexec._plans.get(("sql", sql_a))
    assert ent_b["cq"] is ent_a["cq"], "B must reuse A's program object"


def test_cross_session_program_adoption():
    """Adoption crosses Session boundaries (the throughput harness runs one
    session per concurrent stream)."""
    s1 = _session()
    sql = ("SELECT d.nm, SUM(f.qty) FROM fact f JOIN dim d ON f.fk = d.dk "
           "WHERE f.day > 3 GROUP BY d.nm")
    _lowered(s1, sql)
    s2 = _session()
    sql2 = sql.replace("> 3", "> 9")
    expected = sorted(map(tuple, s2.sql(sql2, backend="numpy").to_pylist()),
                      key=repr)
    got = sorted(map(tuple, s2.sql(sql2, backend="jax").to_pylist()),
                 key=repr)
    assert got == expected
    ent = s2._jax_executor()._plans.get(("sql", sql2))
    assert ent is not None and ent.get("cq") is not None, \
        "second session must adopt the compiled program"


def test_adoption_capacity_overflow_re_records():
    """A stream whose data exceeds the adopted capacity schedule must
    re-record (ReplayMismatch path) and still produce correct results,
    then publish max-merged capacities for later streams."""
    import pyarrow as pa
    rng = np.random.default_rng(5)
    s = Session()
    small = 500
    big = 3000
    s.register_arrow("t", pa.table({
        "k": pa.array(rng.integers(0, 8, small), type=pa.int64()),
        "v": pa.array(rng.integers(1, 50, small), type=pa.int64())}))
    sql_a = "SELECT k, SUM(v) FROM t WHERE v > 2 GROUP BY k"
    _lowered(s, sql_a)
    # second session: same schema/plan, 6x the rows -> adopted caps overflow
    s2 = Session()
    s2.register_arrow("t", pa.table({
        "k": pa.array(rng.integers(0, 8, big), type=pa.int64()),
        "v": pa.array(rng.integers(1, 50, big), type=pa.int64())}))
    sql_b = "SELECT k, SUM(v) FROM t WHERE v > 7 GROUP BY k"
    expected = sorted(map(tuple, s2.sql(sql_b, backend="numpy").to_pylist()),
                      key=repr)
    for _ in range(3):
        got = sorted(map(tuple, s2.sql(sql_b, backend="jax").to_pylist()),
                     key=repr)
        assert got == expected
