"""End-to-end slice: datagen -> stream gen -> power run -> validation.

This is the framework's minimum end-to-end test (SURVEY.md §7: "datagen SF
small -> schema load -> engine executes -> power-runner times it -> report
CSV"), run on both backends with the validator as the oracle check —
the reference could only do this against a live Spark cluster.
"""
import csv
import os

import pytest

from nds_tpu import datagen, streams, validate
from nds_tpu.power import gen_sql_from_stream, run_query_stream

SUBSET = ["query1", "query3", "query42", "query96"]


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    root = tmp_path_factory.mktemp("e2e")
    data = root / "data"
    datagen.generate_data_local(str(data), 0.001, parallel=2, overwrite=True)
    stream_dir = root / "streams"
    streams.generate_query_streams(str(stream_dir), streams=1, rngseed=777)
    return root, str(data), str(stream_dir / "query_0.sql")


def test_stream_file_parses(env):
    _, _, stream = env
    with open(stream) as f:
        queries = gen_sql_from_stream(f.read())
    nums = streams.available_templates()
    assert len(queries) >= len(nums)
    assert all(q.startswith("query") for q in queries)


def test_power_run_and_validate(env):
    root, data, stream = env
    out_np = str(root / "out_np")
    out_jax = str(root / "out_jax")
    rows = run_query_stream(data, stream, str(root / "time_np.csv"),
                            input_format="csv", backend="numpy",
                            output_prefix=out_np,
                            json_summary_folder=str(root / "json"),
                            sub_queries=SUBSET)
    assert [r[0] for r in rows] == SUBSET
    run_query_stream(data, stream, str(root / "time_jax.csv"),
                     input_format="csv", backend="jax",
                     output_prefix=out_jax, sub_queries=SUBSET)
    status = validate.iterate_queries(out_np, out_jax, SUBSET,
                                      ignore_ordering=True)
    assert all(s == "Pass" for s in status.values()), status

    # CSV time log sentinel rows (reference nds_power.py:281-299 format)
    with open(root / "time_np.csv") as f:
        log = list(csv.reader(f))
    labels = [r[0] for r in log]
    assert labels[0] == "query"
    assert "Power Start Time" in labels and "Power End Time" in labels
    assert "Power Test Time" in labels

    # JSON summaries exist with the prefix-query-startTime naming
    summaries = os.listdir(root / "json")
    assert any(s.startswith("power-query1-") for s in summaries)

    # validation status written back into summaries
    import json
    validate.update_summary(str(root / "json"), status)
    with open(root / "json" / sorted(summaries)[0]) as f:
        assert json.load(f)["queryValidationStatus"] in (["Pass"],)


def test_fault_injection_surfaces_failed_status(env):
    """Harness self-test hook (SURVEY.md §5 failure-detection item): an
    injected fault must record Failed with the exception in the JSON
    summary and the stream must keep running."""
    import glob
    import json

    root, data, stream = env
    json_dir = str(root / "json_fault")
    rows = run_query_stream(data, stream, str(root / "time_fault.csv"),
                            input_format="csv", backend="numpy",
                            json_summary_folder=json_dir,
                            sub_queries=["query1", "query3"],
                            fault_inject=["query1"])
    assert [r[0] for r in rows] == ["query1", "query3"]
    summaries = {}
    for path in glob.glob(os.path.join(json_dir, "*.json")):
        with open(path) as f:
            d = json.load(f)
        # filename contract: {prefix}-{query}-{startTime}.json
        summaries[os.path.basename(path).split("-")[1]] = d
    assert summaries["query1"]["queryStatus"] == ["Failed"]
    assert any("injected fault" in e
               for e in summaries["query1"]["exceptions"])
    assert summaries["query3"]["queryStatus"] == ["Completed"]
