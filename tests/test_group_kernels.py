"""Fast dense_rank tiers (direct-address / packed sort) vs the sort-based
kernel: gids must be BIT-IDENTICAL (both tiers are order-preserving), and the
executor must pick the tiers through the recorded schedule on big inputs.

The reference gets grouped aggregation from RAPIDS hash-groupby kernels
(reference nds/power_run_gpu.template); here the differential oracle is the
generic multi-operand sort kernel.
"""
import numpy as np
import pyarrow as pa
import pytest

import jax.numpy as jnp

from nds_tpu.engine import Session
from nds_tpu.engine.jax_backend import kernels


def _random_keys(rng, n, spec):
    """spec: list of (lo, hi, null_frac)."""
    key_data, key_valid = [], []
    for lo, hi, nf in spec:
        d = rng.integers(lo, hi, n)
        v = rng.random(n) >= nf
        key_data.append(jnp.asarray(np.where(v, d, 0)))
        key_valid.append(jnp.asarray(v))
    return key_data, key_valid


CASES = [
    # single small-domain key
    ([(0, 50, 0.0)], 1),
    # two keys with nulls
    ([(10, 200, 0.1), (-5, 40, 0.2)], 1),
    # offset-heavy key (big values, small span)
    ([(10**9, 10**9 + 1000, 0.05)], 1),
    # wide multi-key (q67-class): wide domain but packs into 63 bits
    ([(0, 20000, 0.0), (0, 1000, 0.1), (0, 100, 0.0), (0, 12, 0.0),
      (0, 2000, 0.0)], 1),
]


@pytest.mark.parametrize("spec,want_tier", CASES, ids=range(len(CASES)))
def test_packsort_matches_sort_based(spec, want_tier):
    rng = np.random.default_rng(11)
    n = 1 << 14
    key_data, key_valid = _random_keys(rng, n, spec)
    alive = jnp.asarray(rng.random(n) < 0.9)
    tier = int(kernels.group_tier(key_data, key_valid, alive))
    assert tier == want_tier
    gid0, ng0 = kernels.dense_rank(key_data, key_valid, alive)
    gid1, ng1 = kernels.dense_rank_packsort(key_data, key_valid, alive)
    assert int(ng0) == int(ng1)
    np.testing.assert_array_equal(np.asarray(gid0), np.asarray(gid1))


def test_tier0_when_domain_unpackable():
    """Keys spanning nearly the full int64 range can't pack: tier 0."""
    rng = np.random.default_rng(3)
    n = 1 << 13
    d = rng.integers(-(1 << 62), 1 << 62, n, dtype=np.int64)
    key_data = [jnp.asarray(d), jnp.asarray(rng.integers(0, 10**9, n))]
    key_valid = [jnp.ones(n, bool), jnp.ones(n, bool)]
    alive = jnp.ones(n, bool)
    assert int(kernels.group_tier(key_data, key_valid, alive)) == 0


def test_all_dead_and_all_null():
    n = 1 << 13
    key_data = [jnp.zeros(n, jnp.int64)]
    for valid, alive in [
        (jnp.zeros(n, bool), jnp.ones(n, bool)),    # all null
        (jnp.ones(n, bool), jnp.zeros(n, bool)),    # all dead
    ]:
        gid0, ng0 = kernels.dense_rank(key_data, [valid], alive)
        assert int(kernels.group_tier(key_data, [valid], alive)) == 1
        gid1, ng1 = kernels.dense_rank_packsort(key_data, [valid], alive)
        assert int(ng0) == int(ng1)
        np.testing.assert_array_equal(np.asarray(gid0), np.asarray(gid1))


def _big_session(n=20000):
    """Above the executor's fast-tier row gate (1<<13)."""
    rng = np.random.default_rng(5)
    s = Session()
    s.register_arrow("fact", pa.table({
        "fk": pa.array(rng.integers(0, 60, n), type=pa.int64()),
        "qty": pa.array(rng.integers(1, 100, n), type=pa.int64()),
        "price": pa.array(
            [None if m else round(p, 2) for m, p in
             zip(rng.random(n) < 0.1, rng.uniform(0.5, 99.9, n))]),
        "cat": pa.array(rng.choice(["alpha", "beta", "gamma"], n)),
        "wide": pa.array(rng.integers(0, 10**7, n), type=pa.int64()),
        "day": pa.array(rng.integers(0, 30, n), type=pa.int64()),
    }))
    s.register_arrow("dim", pa.table({
        "dk": pa.array(np.arange(60), type=pa.int64()),
        "dname": pa.array([f"n_{i % 9}" for i in range(60)]),
    }))
    return s


BIG_CORPUS = [
    # grouped agg (tier 1), incl. strings as keys (rank-LUT codes)
    "SELECT cat, day, COUNT(*), SUM(qty) FROM fact GROUP BY cat, day",
    # FLOAT group key: legal SQL with no iinfo range — must take the
    # generic sort tier, not crash the pack probe (sorted-agg gate)
    "SELECT price, COUNT(*) FROM fact GROUP BY price "
    "ORDER BY 2 DESC, 1 LIMIT 5",
    # wide key domain -> packed sort tier
    "SELECT wide, COUNT(*) FROM fact GROUP BY wide ORDER BY 2 DESC LIMIT 10",
    # rollup: per-grouping-set tiers
    "SELECT cat, day, SUM(qty) FROM fact GROUP BY ROLLUP(cat, day)",
    # distinct
    "SELECT DISTINCT cat, day FROM fact",
    # join through the generic (non-unique build) path: self-join
    "SELECT a.day, COUNT(*) FROM fact a JOIN fact b "
    "ON a.fk = b.fk AND a.day = b.day WHERE a.qty > 90 AND b.qty > 90 "
    "GROUP BY a.day",
    # window partition gid
    "SELECT fk, SUM(qty) OVER (PARTITION BY cat, day) FROM fact "
    "WHERE qty > 95",
]


@pytest.fixture(scope="module")
def big_sess():
    return _big_session()


@pytest.mark.parametrize("query", BIG_CORPUS, ids=range(len(BIG_CORPUS)))
def test_big_backend_agreement(big_sess, query):
    oracle = big_sess.sql(query, backend="numpy")
    device = big_sess.sql(query, backend="jax")
    # second run exercises compiled replay of the recorded tier decisions
    device2 = big_sess.sql(query, backend="jax")
    a = sorted(map(tuple, oracle.to_pylist()), key=repr)
    b = sorted(map(tuple, device.to_pylist()), key=repr)
    c = sorted(map(tuple, device2.to_pylist()), key=repr)
    assert b == c
    assert len(a) == len(b)
    for ra, rb in zip(a, b):
        for va, vb in zip(ra, rb):
            if isinstance(va, float) or isinstance(vb, float):
                assert va == pytest.approx(vb, rel=1e-9, abs=1e-9)
            else:
                assert va == vb
