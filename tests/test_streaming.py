"""Out-of-core morsel streaming (engine/streaming): bounded-memory
aggregation over a large scan, one compiled program for every morsel,
host-merged partials — vs the in-core oracle."""
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from nds_tpu.config import EngineConfig
from nds_tpu.engine import Session
from nds_tpu.engine.streaming import try_streaming_plan

N_FACT, N_DIM = 50_000, 300
CHUNK = 4_096  # forces ~13 morsels


def make_session(tmp_path, out_of_core=True):
    rng = np.random.default_rng(5)
    fact = pa.table({
        "fk": pa.array(rng.integers(0, N_DIM + 9, N_FACT), type=pa.int32()),
        "qty": pa.array(rng.integers(1, 50, N_FACT), type=pa.int32()),
        "price": pa.array(np.round(rng.uniform(1, 100, N_FACT), 2)),
        "day": pa.array(rng.integers(0, 365, N_FACT), type=pa.int32()),
    })
    # inject some nulls into qty
    mask = rng.random(N_FACT) < 0.05
    qty = fact.column("qty").to_numpy(zero_copy_only=False).astype(object)
    qty[mask] = None
    fact = fact.set_column(1, "qty", pa.array(list(qty), type=pa.int32()))
    dim = pa.table({"dk": pa.array(np.arange(N_DIM), type=pa.int32()),
                    "grp": pa.array((np.arange(N_DIM) % 13).astype(np.int32))})
    path = os.path.join(str(tmp_path), "fact.parquet")
    pq.write_table(fact, path, row_group_size=8192)
    cfg = EngineConfig(out_of_core=out_of_core, chunk_rows=CHUNK,
                       out_of_core_min_rows=10_000)
    s = Session(cfg)
    s.register_parquet("fact", path)
    s.register_arrow("dim", dim)
    return s


QUERY = """
SELECT d.grp, COUNT(*) AS cnt, COUNT(f.qty) AS cq, SUM(f.qty) AS sq,
       AVG(f.price) AS ap, MIN(f.price) AS lo, MAX(f.price) AS hi
FROM fact f JOIN dim d ON f.fk = d.dk
WHERE f.day < 200
GROUP BY d.grp
ORDER BY d.grp
"""


def rows_of(t):
    return [tuple(round(v, 6) if isinstance(v, float) else v for v in r)
            for r in t.to_pylist()]


def test_streaming_matches_incore(tmp_path):
    s = make_session(tmp_path)
    oracle = s.sql(QUERY, backend="numpy")
    streamed = s.sql(QUERY, backend="jax")
    assert s.last_exec_stats["mode"] == "streaming"
    assert s.last_exec_stats["morsels"] == -(-N_FACT // CHUNK)
    assert rows_of(oracle) == rows_of(streamed)


def test_streaming_global_aggregate(tmp_path):
    s = make_session(tmp_path)
    q = "SELECT COUNT(*), SUM(qty), AVG(price) FROM fact WHERE day >= 100"
    oracle = s.sql(q, backend="numpy")
    streamed = s.sql(q, backend="jax")
    assert s.last_exec_stats["mode"] == "streaming"
    assert rows_of(oracle) == rows_of(streamed)


def test_ineligible_plans_run_incore(tmp_path):
    s = make_session(tmp_path)
    # distinct agg is not streamable
    q = "SELECT COUNT(DISTINCT fk) FROM fact"
    oracle = s.sql(q, backend="numpy")
    got = s.sql(q, backend="jax")
    assert s.last_exec_stats["mode"] != "streaming"
    assert rows_of(oracle) == rows_of(got)


def test_eligibility_rules():
    from nds_tpu.engine.planner import Catalog, Planner
    from nds_tpu.sql import parse_sql

    catalog = Catalog({
        "big": (["k", "v"], ["int", "float"], 10_000_000),
        "small": (["k", "g"], ["int", "int"], 100),
    })
    est = {"big": 10_000_000, "small": 100}.get

    def plan(sql):
        return Planner(catalog).plan_query(parse_sql(sql))

    ok = try_streaming_plan(
        plan("SELECT g, SUM(v) FROM big JOIN small ON big.k = small.k "
             "GROUP BY g"), est, 1 << 20)
    assert ok is not None and ok.big_table == "big"
    # rollup IS streamable (round-3: per-prefix partials merged on
    # (group cols..., __grouping_id))
    rp = try_streaming_plan(
        plan("SELECT k, SUM(v) FROM big GROUP BY ROLLUP(k)"),
        est, 1 << 20)
    assert rp is not None and rp.partial_plan.rollup
    # windows ABOVE the aggregate are streamable (they run over merged
    # partials in the final phase); windows BELOW it are not
    assert try_streaming_plan(
        plan("SELECT g, s, rank() OVER (ORDER BY s DESC) FROM "
             "(SELECT g, SUM(v) s FROM big JOIN small ON big.k = small.k "
             "GROUP BY g) t"), est, 1 << 20) is not None
    # big table on the build side of a right join: not streamable
    assert try_streaming_plan(
        plan("SELECT g, SUM(v) FROM big RIGHT JOIN small ON big.k = small.k "
             "GROUP BY g"), est, 1 << 20) is None
    # two big tables: not streamable
    catalog2 = Catalog({"a": (["k"], ["int"], 10_000_000),
                        "b": (["k"], ["int"], 10_000_000)})
    assert try_streaming_plan(
        Planner(catalog2).plan_query(
            parse_sql("SELECT COUNT(*) FROM a JOIN b ON a.k = b.k")),
        {"a": 10_000_000, "b": 10_000_000}.get, 1 << 20) is None


def test_streaming_rollup_matches_incore(tmp_path):
    s = make_session(tmp_path)
    q = ("SELECT d.grp, f.day % 2 AS parity, SUM(f.qty) AS sq, "
         "COUNT(*) AS cnt FROM fact f JOIN dim d ON f.fk = d.dk "
         "WHERE f.day < 120 GROUP BY ROLLUP(d.grp, f.day % 2) "
         "ORDER BY d.grp, parity")
    oracle = s.sql(q, backend="numpy")
    streamed = s.sql(q, backend="jax")
    assert s.last_exec_stats["mode"] == "streaming"
    assert s.last_exec_stats.get("re_records", 0) == 0
    assert sorted(rows_of(oracle), key=repr) == \
        sorted(rows_of(streamed), key=repr)


def test_streaming_window_above_agg(tmp_path):
    s = make_session(tmp_path)
    q = ("SELECT grp, sq, RANK() OVER (ORDER BY sq DESC) rk FROM "
         "(SELECT d.grp AS grp, SUM(f.qty) AS sq FROM fact f "
         "JOIN dim d ON f.fk = d.dk GROUP BY d.grp) t ORDER BY rk, grp")
    oracle = s.sql(q, backend="numpy")
    streamed = s.sql(q, backend="jax")
    assert s.last_exec_stats["mode"] == "streaming"
    assert rows_of(oracle) == rows_of(streamed)


def test_pack_table_roundtrip():
    """Packed morsel upload (one data matrix + one mask matrix) must be
    value-identical to the per-column path, including f64 bitcasts, i32
    widening, nulls, and the alive mask."""
    import numpy as np
    import pyarrow as pa
    from nds_tpu.engine import arrow_bridge
    from nds_tpu.engine.jax_backend.device import (pack_table, to_device,
                                                   to_host, unpack_table)

    rng = np.random.default_rng(4)
    n = 1000
    t = arrow_bridge.from_arrow(pa.table({
        "i": pa.array([None if k % 13 == 0 else int(v) for k, v in
                       enumerate(rng.integers(-5, 5, n))], type=pa.int64()),
        "f": pa.array(rng.normal(size=n)),
        "d": pa.array(rng.integers(0, 30, n), type=pa.int32()),
        "dt": pa.array([None if k % 17 == 0 else int(v) for k, v in
                        enumerate(rng.integers(10000, 11000, n))],
                       type=pa.date32()),
    }), dec_as_int=True)
    packed = pack_table(t, capacity=2048)
    assert packed is not None
    got = to_host(unpack_table(packed))
    want = to_host(to_device(t, capacity=2048))
    for a, b in zip(got.columns, want.columns):
        np.testing.assert_array_equal(np.asarray(a.data), np.asarray(b.data))
        np.testing.assert_array_equal(a.validity, b.validity)


def make_union_session(tmp_path):
    """Two big fact channels + a small one (q2/q5-class UNION ALL shape)."""
    rng = np.random.default_rng(9)
    cfg = EngineConfig(out_of_core=True, chunk_rows=CHUNK,
                       out_of_core_min_rows=10_000)
    s = Session(cfg)
    for name, n in (("ch_a", 30_000), ("ch_b", 25_000)):
        t = pa.table({
            "fk": pa.array(rng.integers(0, N_DIM, n), type=pa.int32()),
            "amt": pa.array(rng.integers(1, 500, n), type=pa.int64()),
        })
        path = os.path.join(str(tmp_path), f"{name}.parquet")
        pq.write_table(t, path, row_group_size=8192)
        s.register_parquet(name, path)
    small = pa.table({
        "fk": pa.array(rng.integers(0, N_DIM, 400), type=pa.int32()),
        "amt": pa.array(rng.integers(1, 500, 400), type=pa.int64()),
    })
    s.register_arrow("ch_small", small)
    dim = pa.table({"dk": pa.array(np.arange(N_DIM), type=pa.int32()),
                    "grp": pa.array((np.arange(N_DIM) % 7).astype(np.int32))})
    s.register_arrow("dim", dim)
    return s


UNION_AGG = """
SELECT d.grp, COUNT(*) AS cnt, SUM(u.amt) AS total
FROM (SELECT fk, amt FROM ch_a
      UNION ALL SELECT fk, amt FROM ch_b
      UNION ALL SELECT fk, amt FROM ch_small) u
JOIN dim d ON u.fk = d.dk
GROUP BY d.grp
ORDER BY d.grp
"""


def test_union_branch_streaming(tmp_path):
    """q2/q4/q5-class multi-fact-channel aggregate: each UNION ALL branch
    streams independently (VERDICT r4 #1)."""
    s = make_union_session(tmp_path)
    oracle = s.sql(UNION_AGG, backend="numpy")
    streamed = s.sql(UNION_AGG, backend="jax")
    assert s.last_exec_stats["mode"] == "streaming"
    assert s.last_exec_stats["morsels"] >= \
        -(-30_000 // CHUNK) + -(-25_000 // CHUNK)
    assert rows_of(oracle) == rows_of(streamed)


def test_aggregate_below_join_streams(tmp_path):
    """q2-class: the streamable aggregate sits BELOW a join — the old
    top-path rule rejected it; find_streaming_jobs materializes the
    subtree and the remaining join runs in-core."""
    s = make_session(tmp_path)
    q = """
    SELECT a.grp, a.sq, b.sq
    FROM (SELECT d.grp, SUM(f.qty) sq FROM fact f JOIN dim d ON f.fk = d.dk
          WHERE f.day < 180 GROUP BY d.grp) a
    JOIN (SELECT d.grp, SUM(f.qty) sq FROM fact f JOIN dim d ON f.fk = d.dk
          WHERE f.day >= 180 GROUP BY d.grp) b
    ON a.grp = b.grp
    ORDER BY a.grp
    """
    oracle = s.sql(q, backend="numpy")
    streamed = s.sql(q, backend="jax")
    assert s.last_exec_stats["mode"] == "streaming"
    assert s.last_exec_stats["jobs"] == 2
    assert rows_of(oracle) == rows_of(streamed)


def test_small_side_subquery_streams(tmp_path):
    """q6/q8-class: an aggregate subquery over a SMALL table must not block
    streaming of the big scan (the unsupported-node gate is scoped to
    subtrees containing the big scan)."""
    s = make_session(tmp_path)
    q = """
    SELECT d.grp, COUNT(*) FROM fact f JOIN dim d ON f.fk = d.dk
    WHERE f.price > (SELECT AVG(price) FROM fact WHERE day < 0) + 0
      AND f.fk IN (SELECT dk FROM dim WHERE grp < 20)
    GROUP BY d.grp ORDER BY d.grp
    """
    oracle = s.sql(q, backend="numpy")
    streamed = s.sql(q, backend="jax")
    # the outer aggregate itself cannot claim the big scan (the big-table
    # scalar subquery would embed a full scan per morsel), but the
    # SUBQUERY aggregates stream as their own jobs
    assert s.last_exec_stats["mode"] == "streaming"
    assert rows_of(oracle) == rows_of(streamed)


def test_partial_compaction_bounds_memory(tmp_path):
    """High-cardinality groups with a tiny compaction bound: results stay
    exact through repeated combine passes."""
    s = make_session(tmp_path)
    s.config.stream_compact_rows = 2_000
    q = ("SELECT fk, day, COUNT(*) c, SUM(qty) sq, AVG(price) ap "
         "FROM fact GROUP BY fk, day ORDER BY fk, day LIMIT 500")
    oracle = s.sql(q, backend="numpy")
    streamed = s.sql(q, backend="jax")
    assert s.last_exec_stats["mode"] == "streaming"
    assert rows_of(oracle) == rows_of(streamed)


def test_scalar_subquery_streaming(tmp_path):
    """q9-class: scalar-subquery aggregates over the big table stream as
    independent jobs; the outer (tiny) plan runs in-core."""
    s = make_session(tmp_path)
    q = """
    SELECT d.grp,
           CASE WHEN (SELECT COUNT(*) FROM fact WHERE day < 100) > 10
                THEN (SELECT AVG(price) FROM fact WHERE day < 100)
                ELSE (SELECT AVG(price) FROM fact WHERE day >= 100) END AS v
    FROM dim d WHERE d.dk < 3
    """
    oracle = s.sql(q, backend="numpy")
    streamed = s.sql(q, backend="jax")
    assert s.last_exec_stats["mode"] == "streaming"
    assert s.last_exec_stats["jobs"] == 3
    assert rows_of(oracle) == rows_of(streamed)


def test_semi_join_big_build_streaming(tmp_path):
    """q10/q16-class: EXISTS over the big table = semi join with a big
    BUILD side; the right side streams as a distinct-key set."""
    s = make_session(tmp_path)
    q = """
    SELECT d.grp, COUNT(*) FROM dim d
    WHERE EXISTS (SELECT 1 FROM fact f WHERE f.fk = d.dk AND f.day < 50)
    GROUP BY d.grp ORDER BY d.grp
    """
    oracle = s.sql(q, backend="numpy")
    streamed = s.sql(q, backend="jax")
    assert s.last_exec_stats["mode"] == "streaming"
    assert s.last_exec_stats["jobs"] == 1
    assert rows_of(oracle) == rows_of(streamed)


def test_not_in_big_build_streaming(tmp_path):
    """Null-aware anti join (NOT IN) with a big build side: the NULL group
    must survive the streamed dedup."""
    s = make_session(tmp_path)
    q = ("SELECT COUNT(*) FROM dim "
         "WHERE dk NOT IN (SELECT fk FROM fact WHERE day < 30)")
    oracle = s.sql(q, backend="numpy")
    streamed = s.sql(q, backend="jax")
    assert s.last_exec_stats["mode"] == "streaming"
    assert rows_of(oracle) == rows_of(streamed)
