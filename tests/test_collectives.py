"""Collective-volume assertions over the compiled SPMD program (round-2
verdict #5: prove fact tables are processed shard-local — dimension tables
broadcast, fact tables must never be rebuilt with cap-sized all-gathers).

The star shape below compiles to: replicated dim LUT join (no collectives),
shard-local dense-rank group-by (bounded-partials all_gather), skipped
compaction (no global permutes) — so every collective in the optimized HLO
must be orders of magnitude below the fact capacity."""
import re

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.config import EngineConfig
from nds_tpu.engine import Session

N_FACT, N_DIM = 1 << 16, 512
_SHAPE = re.compile(r"=\s*\(?\w+\[([\d,]*)\]")


def _collective_volumes(hlo: str) -> list[tuple[int, str]]:
    out = []
    for line in hlo.splitlines():
        ls = line.strip()
        if re.search(r"\b(all-gather|all-reduce|all-to-all)\(", ls):
            m = _SHAPE.search(ls)
            if not m:
                continue
            dims = [int(d) for d in m.group(1).split(",") if d]
            n = int(np.prod(dims)) if dims else 1
            out.append((n, ls[:120]))
    return sorted(out, reverse=True)


@pytest.fixture(scope="module")
def star_session():
    rng = np.random.default_rng(11)
    s = Session(EngineConfig(mesh_shape=(8,), shard_min_rows=8192))
    s.register_arrow("fact", pa.table({
        "fk": rng.integers(0, N_DIM, N_FACT).astype(np.int64),
        "v": rng.normal(50, 10, N_FACT),
        "m": rng.integers(0, 12, N_FACT).astype(np.int64),
    }))
    s.register_arrow("dim", pa.table({
        "dk": np.arange(N_DIM, dtype=np.int64),
        "grp": (np.arange(N_DIM) % 29).astype(np.int64),
    }))
    return s


@pytest.mark.slow  # whole-plan GSPMD compile + HLO inspection
def test_star_query_collectives_bounded(star_session):
    s = star_session
    sql = ("SELECT d.grp, sum(f.v), count(*) FROM fact f, dim d "
           "WHERE f.fk = d.dk AND f.m < 9 GROUP BY d.grp")
    expected = sorted(s.sql(sql, backend="numpy").to_pylist(), key=repr)
    s.sql(sql, backend="jax")
    got = sorted(s.sql(sql, backend="jax").to_pylist(), key=repr)
    assert s.last_exec_stats.get("mode") in ("compiled", "compile+run")
    assert len(got) == len(expected)
    for e, g in zip(expected, got):
        assert e[0] == g[0] and e[2] == g[2]
        assert g[1] == pytest.approx(e[1], rel=1e-9)

    jexec = s._jax_executor()
    # layout: the fact scan is sharded, the dimension scan replicated
    fact_sharded = dim_replicated = False
    for k, dt in jexec._scan_cache.items():
        spec = getattr(dt.cols[0].data.sharding, "spec", None)
        if k.startswith("fact//"):
            fact_sharded = bool(spec) and spec[0] == "shards"
        if k.startswith("dim//"):
            dim_replicated = not spec or spec[0] is None
    assert fact_sharded, "fact scan must be row-sharded"
    assert dim_replicated, "dimension scan must replicate (broadcast join)"

    hlo = jexec.compiled_hlo(("sql", sql))
    assert hlo is not None
    vols = _collective_volumes(hlo)
    # the fact table must NEVER be rebuilt: cap-sized (or larger) gathers
    # mean GSPMD fell back to single-device semantics somewhere
    too_big = [(n, l) for n, l in vols if n >= N_FACT // 2]
    assert not too_big, \
        "fact-capacity collectives found:\n" + "\n".join(
            f"  {n}: {l}" for n, l in too_big)


@pytest.fixture(scope="module")
def factfact_session():
    rng = np.random.default_rng(23)
    s = Session(EngineConfig(mesh_shape=(8,), shard_min_rows=8192))
    n = N_FACT
    s.register_arrow("orders", pa.table({
        "ok": rng.integers(0, n // 4, n).astype(np.int64),
        "site": rng.integers(0, 7, n).astype(np.int64),
        "amt": rng.integers(1, 100, n).astype(np.int64),
    }))
    s.register_arrow("returns_", pa.table({
        "rk": rng.integers(0, n // 4, n).astype(np.int64),
        "rsite": rng.integers(0, 7, n).astype(np.int64),
    }))
    return s


@pytest.mark.slow  # whole-plan GSPMD compile + HLO inspection
def test_fact_fact_join_shuffles_not_gathers(factfact_session):
    """q64/q78/q95-class fact-fact joins on the mesh must repartition via
    all_to_all (Spark shuffle join), never rebuild a fact side with a
    capacity-sized all-gather (round-3 verdict #5)."""
    s = factfact_session
    sql = ("SELECT o.site, count(*), sum(o.amt) FROM orders o, returns_ r "
           "WHERE o.ok = r.rk AND o.site <> r.rsite GROUP BY o.site")
    expected = sorted(s.sql(sql, backend="numpy").to_pylist(), key=repr)
    s.sql(sql, backend="jax")
    got = sorted(s.sql(sql, backend="jax").to_pylist(), key=repr)
    assert s.last_exec_stats.get("mode") in ("compiled", "compile+run")
    assert got == expected
    assert s.last_fallbacks == []

    hlo = s._jax_executor().compiled_hlo(("sql", sql))
    assert hlo is not None
    gathers = [(nelem, line) for nelem, line in _collective_volumes(hlo)
               if "all-gather" in line and nelem >= N_FACT // 2]
    assert not gathers, \
        "fact-capacity all-gathers found:\n" + "\n".join(
            f"  {n}: {l}" for n, l in gathers)
