"""CTE-boundary compile segmentation (round-2 verdict #1).

Large multi-CTE plans split into one XLA program per CTE plus a root
program; CTE outputs stay device-resident and are shared across statements
with an identical WITH clause (the q4 compile-pathology fix and the
q14/q23 cross-part sharing fix). Reference analog: Spark compiles every
query bounded via its own planner (nds/nds_power.py:124-134)."""
import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.config import EngineConfig
from nds_tpu.engine import Session

CTE_SQL = ("WITH totals AS (SELECT g, sum(v) s, count(*) c FROM t "
           "GROUP BY g), big AS (SELECT g, s FROM totals WHERE s > 10) ")


@pytest.fixture()
def seg_session():
    # thresholds forced low so the tiny test plans segment
    s = Session(EngineConfig(segment_plan_nodes=2, segment_min_cte_nodes=2))
    rng = np.random.default_rng(5)
    s.register_arrow("t", pa.table({
        "g": rng.integers(0, 9, 200).astype(np.int64),
        "v": rng.normal(10, 3, 200),
        "k": rng.integers(0, 4, 200).astype(np.int64),
    }))
    s.register_arrow("d", pa.table({"k": [0, 1, 2, 3],
                                    "nm": ["a", "b", "c", "d"]}))
    return s


def _rows(t):
    return sorted(t.to_pylist(), key=repr)


def _approx(rows_a, rows_b, rel=1e-9):
    """Row compare with float tolerance: sorted-order summation moves the
    last ulp vs the oracle's row-order summation (the validator's epsilon
    policy exists for exactly this)."""
    assert len(rows_a) == len(rows_b)
    for ra, rb in zip(rows_a, rows_b):
        assert len(ra) == len(rb)
        for va, vb in zip(ra, rb):
            if isinstance(va, float) or isinstance(vb, float):
                assert va == pytest.approx(vb, rel=rel, abs=1e-9)
            else:
                assert va == vb, (va, vb)
    return True


def test_segmented_query_matches_oracle(seg_session):
    s = seg_session
    sql = CTE_SQL + ("SELECT b.g, b.s, tt.c FROM big b, totals tt "
                     "WHERE b.g = tt.g ORDER BY b.g")
    expected = _rows(s.sql(sql, backend="numpy"))
    for i in range(3):           # record -> compile -> steady state
        got = _rows(s.sql(sql, backend="jax"))
        assert _approx(got, expected), f"run {i}"
        assert s.last_fallbacks == []
    st = s.last_exec_stats
    assert st["mode"] == "compiled"
    assert st["segments"] == 2
    assert st["segments_run"] == 0    # device-resident, never re-run


def test_segments_shared_across_statements(seg_session):
    """Two DIFFERENT statements with an identical WITH clause (the q14/q23
    multi-part shape) reuse the materialized segments."""
    s = seg_session
    q1 = CTE_SQL + "SELECT g, s FROM big ORDER BY g"
    q2 = CTE_SQL + "SELECT count(*) FROM totals"
    r1 = _rows(s.sql(q1, backend="jax"))
    assert s.last_exec_stats.get("segments") == 2
    jexec = s._jax_executor()
    seg_keys = [k for k in list(jexec._scan_cache) +
                list(jexec._scan_cache_rec) if k.startswith("seg:")]
    assert len(set(seg_keys)) == 2
    _ = s.sql(q2, backend="jax")
    # q2's units must SKIP the shared segments (already materialized)
    assert s.last_exec_stats.get("segments_run") == 0
    assert _approx(_rows(s.sql(q2, backend="jax")),
                   _rows(s.sql(q2, backend="numpy")))
    assert _approx(r1, _rows(s.sql(q1, backend="numpy")))


def test_segment_eviction_recovers(seg_session):
    s = seg_session
    sql = CTE_SQL + "SELECT g, s FROM big ORDER BY g"
    expected = _rows(s.sql(sql, backend="numpy"))
    assert _approx(_rows(s.sql(sql, backend="jax")), expected)
    assert _approx(_rows(s.sql(sql, backend="jax")), expected)
    jexec = s._jax_executor()
    # evict every segment output (LRU pressure analog)
    for k in [k for k in list(jexec._scan_cache) if k.startswith("seg:")]:
        jexec._scan_cache.pop(k, None)
    for k in [k for k in list(jexec._scan_cache_rec) if k.startswith("seg:")]:
        jexec._scan_cache_rec.pop(k, None)
    jexec._segment_lru.clear()
    got = _rows(s.sql(sql, backend="jax"))
    assert _approx(got, expected)
    assert s.last_exec_stats.get("segments_run", 0) >= 1   # re-materialized


def test_lru_pins_in_flight_segments():
    """A cache cap smaller than one query's segment count must not evict a
    segment the same query still needs (review regression)."""
    s = Session(EngineConfig(segment_plan_nodes=2, segment_min_cte_nodes=2,
                             segment_cache_entries=1))
    rng = np.random.default_rng(6)
    s.register_arrow("t", pa.table({
        "g": rng.integers(0, 5, 100).astype(np.int64),
        "v": rng.normal(10, 3, 100)}))
    sql = CTE_SQL + ("SELECT b.g, b.s, tt.c FROM big b, totals tt "
                     "WHERE b.g = tt.g ORDER BY b.g")
    expected = _rows(s.sql(sql, backend="numpy"))
    for _ in range(3):
        assert _approx(_rows(s.sql(sql, backend="jax")), expected)


def test_small_plans_not_segmented():
    s = Session()     # default thresholds
    s.register_arrow("t", pa.table({"a": [1, 2, 3]}))
    sql = "WITH c AS (SELECT a FROM t WHERE a > 1) SELECT sum(a) FROM c"
    assert s.sql(sql, backend="jax").to_pylist() == [(5,)]
    assert "segments" not in s.last_exec_stats


def test_chained_ctes_segment_in_order(seg_session):
    """A CTE referencing an earlier CTE compiles against its virtual scan."""
    s = seg_session
    sql = ("WITH t1 AS (SELECT g, sum(v) s FROM t GROUP BY g), "
           "t2 AS (SELECT g, s FROM t1 WHERE s > 5), "
           "t3 AS (SELECT count(*) n, min(s) m FROM t2) "
           "SELECT n, m FROM t3")
    expected = _rows(s.sql(sql, backend="numpy"))
    for _ in range(3):
        assert _approx(_rows(s.sql(sql, backend="jax")), expected)
        assert s.last_fallbacks == []
    assert s.last_exec_stats["segments"] == 3


def test_scan_budget_evicts_lru():
    """HBM budget: least-recently-used resident scans evict past the cap,
    and an evicted scan transparently re-uploads on next use."""
    s = Session(EngineConfig(scan_budget_gb=2e-6))   # ~2 KB cap
    rng = np.random.default_rng(8)
    for name in ("a", "b", "c"):
        s.register_arrow(name, pa.table({
            "k": rng.integers(0, 50, 64).astype(np.int64),
            "v": rng.normal(size=64)}))
    sums = {}
    for name in ("a", "b", "c"):
        sql = f"SELECT sum(v) FROM {name} WHERE k > 10"
        s.sql(sql, backend="jax")
        sums[name] = s.sql(sql, backend="jax").to_pylist()  # compiled
    jexec = s._jax_executor()
    assert sum(jexec._resident.values()) > 0
    # budget is far below 3 tables' footprint: older entries must evict
    # (the pinned current query's own scans may exceed the cap alone)
    assert len(jexec._resident) < 3
    # evicted tables still answer correctly (re-upload path)
    for name in ("a", "b", "c"):
        sql = f"SELECT sum(v) FROM {name} WHERE k > 10"
        assert s.sql(sql, backend="jax").to_pylist() == sums[name]
        assert s.last_exec_stats.get("mode") in ("compiled", "compile+run")


ROLLUP_SQL = ("SELECT g, t.k, count(*) c, sum(v) s FROM t JOIN d "
              "ON t.k = d.k WHERE v > 2 GROUP BY ROLLUP(g, t.k)")


def test_rollup_splits_into_per_level_units(seg_session):
    """A big rollup over a CTE-less plan segments at grouping-set
    boundaries (the q67 compile-pathology fix): child materializes once,
    each level compiles separately, and the union of levels matches the
    in-program rollup exactly."""
    s = seg_session
    expected = _rows(s.sql(ROLLUP_SQL, backend="numpy"))
    for i in range(3):
        assert _approx(_rows(s.sql(ROLLUP_SQL, backend="jax")), expected), f"run {i}"
        assert s.last_fallbacks == []
    st = s.last_exec_stats
    assert st["mode"] == "compiled"
    # 1 child unit + 3 level units (g,k / g / ()) + root
    assert st["segments"] == 4
    assert st["segments_run"] == 0


def test_rollup_split_grouping_id(seg_session):
    """GROUPING() semantics survive the split: per-level units emit the
    right grouping-id bitmask (regression for the single-level path)."""
    s = seg_session
    sql = ("SELECT g, t.k, GROUPING(g), GROUPING(t.k), sum(v) FROM t "
           "JOIN d ON t.k = d.k GROUP BY ROLLUP(g, t.k)")
    expected = _rows(s.sql(sql, backend="numpy"))
    for _ in range(2):
        assert _approx(_rows(s.sql(sql, backend="jax")), expected)
