import pytest

from nds_tpu.sql import parse_sql, parse_statements
from nds_tpu.sql.ast_nodes import (
    Between, BinOp, Case, Cast, CreateView, Delete, DropView, FuncCall, InList,
    Insert, InSubquery, Interval, Join, Literal, Query, ScalarSubquery, Select,
    SetOp, Star, SubqueryRef, TableRef,
)
from nds_tpu.sql.parser import SqlParseError


def test_simple_select():
    q = parse_sql("select a, b as bb from t where a > 1 limit 10")
    assert isinstance(q.body, Select)
    assert q.limit == 10
    assert q.body.items[1].alias == "bb"


def test_cte_and_correlated_subquery():
    q = parse_sql(
        """
        with ctr as (select sr_store_sk k, sum(sr_return_amt) v
                     from store_returns group by sr_store_sk)
        select * from ctr c1
        where c1.v > (select avg(v) * 1.2 from ctr c2 where c1.k = c2.k)
        """
    )
    assert len(q.ctes) == 1
    pred = q.body.where
    assert isinstance(pred, BinOp) and isinstance(pred.right, ScalarSubquery)


def test_joins():
    q = parse_sql(
        "select * from a join b on a.x = b.x left outer join c on b.y = c.y, d"
    )
    j = q.body.from_
    assert isinstance(j, Join) and j.kind == "cross"
    assert j.left.kind == "left"


def test_interval_arithmetic():
    q = parse_sql("select * from t where d between cast('2000-01-01' as date) "
                  "and cast('2000-01-01' as date) + interval 30 days")
    between = q.body.where
    assert isinstance(between, Between)
    assert isinstance(between.high, BinOp) and isinstance(between.high.right, Interval)
    assert between.high.right.unit == "day"


def test_date_literal():
    q = parse_sql("select * from t where d >= date '2002-01-01'")
    assert q.body.where.right == Literal("2002-01-01", type_hint="date")


def test_in_list_and_subquery():
    q = parse_sql("select * from t where a in (1,2,3) and b not in (select x from s)")
    land = q.body.where
    assert isinstance(land.left, InList)
    assert isinstance(land.right, InSubquery) and land.right.negated


def test_case_when():
    q = parse_sql("select case when a > 0 then 'pos' else 'neg' end from t")
    assert isinstance(q.body.items[0].expr, Case)


def test_window_function():
    q = parse_sql(
        "select rank() over (partition by g order by sum(v) desc) rk from t group by g"
    )
    fc = q.body.items[0].expr
    assert isinstance(fc, FuncCall) and fc.over is not None
    assert len(fc.over.partition_by) == 1
    assert not fc.over.order_by[0].asc


def test_window_frame_is_tolerated():
    q = parse_sql(
        "select sum(v) over (partition by g order by d rows between "
        "unbounded preceding and current row) from t"
    )
    assert "unbounded" in q.body.items[0].expr.over.frame


def test_rollup_and_grouping():
    q = parse_sql(
        "select grouping(a), sum(v) from t group by rollup(a, b)"
    )
    assert q.body.group_by.rollup


def test_set_ops_precedence():
    q = parse_sql("select a from x union all select a from y intersect select a from z")
    assert isinstance(q.body, SetOp) and q.body.op == "union"
    assert isinstance(q.body.right, SetOp) and q.body.right.op == "intersect"


def test_count_distinct_star():
    q = parse_sql("select count(*), count(distinct a) from t")
    c0, c1 = (it.expr for it in q.body.items)
    assert isinstance(c0.args[0], Star)
    assert c1.distinct


def test_backtick_identifiers():
    q = parse_sql("select `sum sales`, sumsales from t order by `sum sales`")
    assert q.body.items[0].expr.parts == ("sum sales",)


def test_string_escape():
    q = parse_sql("select * from t where s = 'Doesn''t'")
    assert q.body.where.right == Literal("Doesn't")


def test_maintenance_statements():
    stmts = parse_statements(
        """
        create temp view v as (select * from s_store_returns);
        insert into store_returns (select * from v);
        delete from store_sales where ss_sold_date_sk >= (select min(d_date_sk)
          from date_dim where d_date between 'DATE1' and 'DATE2');
        drop view v;
        """
    )
    assert [type(s) for s in stmts] == [CreateView, Insert, Delete, DropView]


def test_parse_error_reports_context():
    with pytest.raises(SqlParseError):
        parse_sql("select from where")


def test_exists_and_not_exists():
    q = parse_sql(
        "select * from t where exists (select 1 from s where s.k = t.k) "
        "and not exists (select 1 from u where u.k = t.k)"
    )
    assert q.body.where is not None


def test_order_by_nulls():
    q = parse_sql("select a from t order by a desc nulls last, b nulls first")
    assert q.order_by[0].nulls_first is False
    assert q.order_by[1].nulls_first is True


def test_subquery_in_from():
    q = parse_sql("select * from (select a from t) sub where sub.a > 0")
    assert isinstance(q.body.from_, SubqueryRef)


def test_concat_operator():
    q = parse_sql("select c_last_name || ', ' || c_first_name from customer")
    assert isinstance(q.body.items[0].expr, BinOp)
