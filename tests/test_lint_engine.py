"""Engine-discipline lint (nds_tpu.analysis, shim scripts/lint_engine.py).

Two behaviors matter: the real tree lints CLEAN (the CI gate), and
reintroducing any hazard class is flagged with the right rule ID — an
in-place mutation of a frozen PlanNode field (ENG001), an unlocked
cross-thread write (ENG002), a lock-order inversion or cycle (ENG003),
a blocking call on the device lane (ENG004), an untyped raise in the
serving layer or a wire-table hole (ENG005), and a metrics/gate drift
(ENG006), plus pragma hygiene (ENG007). Fixture trees exercise each
family through the same ``lint_paths`` entry point CI uses.
"""
import importlib.util
import json
import os
import textwrap

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint():
    import sys
    spec = importlib.util.spec_from_file_location(
        "lint_engine", os.path.join(_REPO, "scripts", "lint_engine.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules["lint_engine"] = mod     # dataclasses resolves __module__
    spec.loader.exec_module(mod)
    return mod


LINT = _lint()


def _findings(src: str):
    return LINT.lint_source("snippet.py", textwrap.dedent(src))


# -- ENG001: frozen plan IR -------------------------------------------------

def test_flags_reintroduced_plannode_mutation():
    out = _findings("""
        def widen(node, col):
            node.out_names = node.out_names + [col]
    """)
    assert [f.rule for f in out] == ["ENG001"]
    assert "out_names" in out[0].message


def test_flags_subscript_and_mutating_calls():
    out = _findings("""
        def corrupt(join, proj, e):
            join.left_keys[0] = e
            proj.exprs.append(e)
    """)
    assert [f.rule for f in out] == ["ENG001", "ENG001"]


def test_allows_locally_constructed_builders():
    # builder-style initialization of a node the function provably owns
    out = _findings("""
        def build(child, exprs):
            p = ProjectNode(child, [])
            p.exprs = exprs
            return p
    """)
    assert out == []


def test_allows_unrelated_self_attributes():
    # Planner-style classes own attributes that share plan-field names
    out = _findings("""
        class Planner:
            def __init__(self):
                self.cte_segments = []
                self.keys = {}
    """)
    assert out == []


def test_flags_self_writes_inside_ir_classes():
    out = _findings("""
        class ProjectNode:
            def grow(self, e):
                self.exprs = self.exprs + [e]
    """)
    assert [f.rule for f in out] == ["ENG001"]


def test_frozen_pragma_exempts():
    out = _findings("""
        def annotate(root, segs):
            root.cte_segments = segs  # lint: frozen-exempt (root annotation)
    """)
    assert out == []


# -- ENG002: unlocked cross-thread writes -----------------------------------

def test_flags_unlocked_cross_thread_write():
    out = _findings("""
        import threading

        class Streamer:
            def start(self):
                t = threading.Thread(target=self._work)
                t.start()

            def _work(self):
                self.progress = 1
    """)
    assert [f.rule for f in out] == ["ENG002"]
    assert "progress" in out[0].message


def test_lock_protected_write_allowed():
    out = _findings("""
        import threading

        class Streamer:
            def start(self):
                t = threading.Thread(target=self._work)
                t.start()

            def _work(self):
                with self._lock:
                    self.progress = 1
    """)
    assert out == []


def test_thread_local_objects_allowed():
    out = _findings("""
        from concurrent.futures import ThreadPoolExecutor

        def launch(pool, items):
            pool.map(worker, items)

        def worker(item):
            acc = Accumulator()
            acc.total = 0       # thread-local, not shared state
            return acc
    """)
    assert out == []


def test_pool_submit_target_detected():
    out = _findings("""
        def launch(pool, shared):
            pool.submit(worker, shared)

        def worker(shared):
            shared.count = 1
    """)
    assert [f.rule for f in out] == ["ENG002"]


def test_lock_exempt_pragma():
    out = _findings("""
        import threading

        def launch(state):
            threading.Thread(target=work).start()

        def work(state):
            state.flag = True  # lint: lock-exempt (write-once sentinel)
    """)
    assert out == []


def test_thread_entry_pragma_applies_eng002():
    """Functions entered concurrently WITHOUT being a literal thread
    target (Session.sql / column_stats under the query service) opt into
    ENG002 with the def-line thread-entry pragma: an unlocked cache write
    inside is flagged, the same write under the lock is not."""
    out = _findings("""
        class Session:
            def column_stats(self, name):  # lint: thread-entry (service)
                self._col_stats[name] = {}
                return self._col_stats[name]
    """)
    assert [f.rule for f in out] == ["ENG002"]
    assert "_col_stats" in out[0].message

    out = _findings("""
        class Session:
            def column_stats(self, name):  # lint: thread-entry (service)
                with self._lock:
                    self._col_stats[name] = {}
                return self._col_stats[name]
    """)
    assert out == []


def test_thread_entry_pragma_on_multiline_def():
    out = _findings("""
        class Session:
            def sql(self, query,
                    backend=None):  # lint: thread-entry (service clients)
                self.last = query
    """)
    assert [f.rule for f in out] == ["ENG002"]


def _tree(tmp_path, files):
    """Write a fixture tree and lint its pkg/ dir through the same
    whole-program entry point CI uses; returns (findings, exit_code)."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    pkg = str(tmp_path / "pkg")
    return LINT.lint_paths([pkg]), LINT.main([pkg])


# -- ENG003: lock-order deadlock detection ----------------------------------

def test_flags_lock_acquisition_cycle_through_calls(tmp_path):
    """Two classes taking each other's lock while holding their own — the
    cycle closes through the summary pass's call propagation, not any
    single lexical nesting."""
    findings, code = _tree(tmp_path, {"pkg/ab.py": """
        class Alpha:
            def touch_alpha(self):
                with self._lock:
                    pass

            def cross(self, beta):
                with self._lock:
                    beta.touch_beta()

        class Beta:
            def touch_beta(self):
                with self._lock:
                    pass

            def cross_back(self, alpha):
                with self._lock:
                    alpha.touch_alpha()
    """})
    assert code == 1
    assert {f.rule for f in findings} == {"ENG003"}
    assert any("cycle" in f.message for f in findings)


def test_consistent_lock_order_is_clean(tmp_path):
    findings, code = _tree(tmp_path, {"pkg/ab.py": """
        class Alpha:
            def touch_alpha(self):
                with self._lock:
                    pass

        class Beta:
            def cross_back(self, alpha):
                with self._lock:
                    alpha.touch_alpha()
    """})
    assert (findings, code) == ([], 0)


def test_flags_declared_hierarchy_inversion(tmp_path):
    """Session._lock (inner) held while taking Session._sql_lock (outer)
    inverts the declared table — flagged even without a closing cycle."""
    findings, code = _tree(tmp_path, {"pkg/m.py": """
        def bad(session):
            with session._lock:
                with session._sql_lock:
                    pass
    """})
    assert code == 1
    assert [f.rule for f in findings] == ["ENG003"]
    assert "inverted" in findings[0].message


def test_declared_hierarchy_order_is_clean(tmp_path):
    findings, code = _tree(tmp_path, {"pkg/m.py": """
        def good(session):
            with session._sql_lock:
                with session._lock:
                    pass
    """})
    assert (findings, code) == ([], 0)


def test_lock_order_exempt_pragma(tmp_path):
    findings, code = _tree(tmp_path, {"pkg/m.py": """
        def audited(session):
            with session._lock:
                with session._sql_lock:  # lint: lock-order-exempt (startup only: single-threaded bootstrap)
                    pass
    """})
    assert (findings, code) == ([], 0)


# -- ENG004: device-lane purity ---------------------------------------------

def test_flags_blocking_call_in_lane_function(tmp_path):
    findings, code = _tree(tmp_path, {"pkg/svc.py": """
        import time

        class Service:
            def _loop(self):  # lint: device-lane (dispatch thread)
                time.sleep(0.1)
    """})
    assert code == 1
    assert [f.rule for f in findings] == ["ENG004"]
    assert "time.sleep" in findings[0].message


def test_flags_fsync_commit_under_sql_lock(tmp_path):
    findings, code = _tree(tmp_path, {"pkg/txn.py": """
        import os

        def commit(session, a, b):
            with session._sql_lock:
                os.replace(a, b)
    """})
    assert code == 1
    assert [f.rule for f in findings] == ["ENG004"]
    assert "_sql_lock" in findings[0].message


def test_lane_reads_and_offlane_blocking_are_clean(tmp_path):
    findings, code = _tree(tmp_path, {"pkg/svc.py": """
        import os
        import time

        class Service:
            def _loop(self, path):  # lint: device-lane (dispatch thread)
                with open(path) as f:
                    return f.read()

            def maintenance(self, a, b):
                time.sleep(0.1)
                os.replace(a, b)
    """})
    assert (findings, code) == ([], 0)


# -- ENG005: typed-error discipline -----------------------------------------

def test_flags_untyped_raise_in_serving_layer(tmp_path):
    findings, code = _tree(tmp_path, {"pkg/service/handlers.py": """
        def handle(req):
            raise RuntimeError("boom")
    """})
    assert code == 1
    assert [f.rule for f in findings] == ["ENG005"]
    assert "RuntimeError" in findings[0].message


def test_typed_subclass_raise_is_clean(tmp_path):
    """Typedness resolves through the program-wide hierarchy: a subclass
    of TransientError defined in another module is typed."""
    findings, code = _tree(tmp_path, {
        "pkg/errors.py": """
            class TransientError(Exception):
                pass

            class Flaky(TransientError):
                pass
        """,
        "pkg/service/handlers.py": """
            from ..errors import Flaky

            def handle(req):
                raise Flaky("retry me")
        """})
    assert (findings, code) == ([], 0)


def test_flags_wire_table_holes_both_directions(tmp_path):
    """A TYPED_ERRORS class without a reconstruct_error branch AND a
    branch naming a vanished class are both flagged."""
    findings, code = _tree(tmp_path, {"pkg/wire.py": """
        TYPED_ERRORS = frozenset({"FaultError", "TimeoutError"})

        class FaultError(Exception):
            pass

        def reconstruct_error(doc):
            cls = doc.get("cls")
            if cls == "FaultError":
                return FaultError(doc.get("msg"))
            if cls == "GoneError":
                return RuntimeError(doc.get("msg"))
            return RuntimeError(doc.get("msg"))
    """})
    assert code == 1
    assert [f.rule for f in findings] == ["ENG005", "ENG005"]
    msgs = " | ".join(f.message for f in findings)
    assert "TimeoutError" in msgs and "GoneError" in msgs


def test_wire_table_exhaustive_over_real_typed_errors():
    """Pin: reconstruct_error covers every TYPED_ERRORS class plus the
    tree-defined typed subclasses that cross the wire."""
    from nds_tpu.analysis.summary import summarize_paths
    prog = summarize_paths([
        os.path.join(_REPO, "nds_tpu", "chaos.py"),
        os.path.join(_REPO, "nds_tpu", "service", "frontdoor.py")])
    wire = next(m for m in prog.modules if m.wire_branches is not None)
    assert prog.typed_errors and \
        prog.typed_errors <= set(wire.wire_branches)
    assert "ConnectionDropped" in wire.wire_branches


# -- ENG006: counter discipline ---------------------------------------------

def test_flags_metric_drift_against_gate_and_glossary(tmp_path):
    """Help-less family, unresolvable write site, orphan STRICT_ZERO row,
    orphan baseline row, and an unbaselined gate-shaped counter — all in
    one fixture tree shaped like the real repo layout."""
    findings, code = _tree(tmp_path, {
        "pkg/metrics.py": """
            FOO_TOTAL = METRICS.counter("foo_total", "good help")
            BAR_TOTAL = METRICS.counter("bar_total")

            def bump():
                FOO_TOTAL.inc()
                GHOST_TOTAL.inc()
        """,
        "scripts/metrics_gate.py": """
            STRICT_ZERO = ("foo_total", "vanished_total")
        """,
        "cicd/metrics_baseline.json": """
            {"gated": {"foo_total": 0, "orphan_total": 0}}
        """})
    assert code == 1
    assert {f.rule for f in findings} == {"ENG006"}
    msgs = " | ".join(f.message for f in findings)
    assert "bar_total" in msgs          # help-less + unbaselined
    assert "GHOST_TOTAL" in msgs        # write site resolves to nothing
    assert "vanished_total" in msgs     # orphan STRICT_ZERO row
    assert "orphan_total" in msgs       # orphan baseline row


def test_consistent_metrics_are_clean(tmp_path):
    findings, code = _tree(tmp_path, {
        "pkg/metrics.py": """
            FOO_TOTAL = METRICS.counter("foo_total", "good help")
            LAT_MS = METRICS.histogram("lat_ms", "latency")

            def bump(v):
                FOO_TOTAL.inc()
                LAT_MS.observe(v)
        """,
        "scripts/metrics_gate.py": """
            STRICT_ZERO = ("foo_total",)
        """,
        "cicd/metrics_baseline.json": """
            {"gated": {"foo_total": 0}}
        """})
    assert (findings, code) == ([], 0)


# -- ENG007: pragma hygiene --------------------------------------------------

def test_flags_stale_unknown_and_unexplained_pragmas(tmp_path):
    findings, code = _tree(tmp_path, {"pkg/m.py": """
        def f(node, other):
            x = 1  # lint: frozen-exempt (nothing fires here)
            node.out_names = []  # lint: frozen-exempt
            other.extra = 2  # lint: frozen-exemptt (typo)
    """})
    assert code == 1
    by_msg = sorted((f.rule, f.message.split(":")[0]) for f in findings)
    assert [r for r, _ in by_msg] == ["ENG007", "ENG007", "ENG007"]
    msgs = " | ".join(f.message for f in findings)
    assert "stale pragma" in msgs       # line 1: rule never fires there
    assert "missing its (<reason>)" in msgs   # line 2: no reason given
    assert "unknown pragma" in msgs     # line 3: typo'd name


def test_docstring_pragma_mentions_are_not_pragmas(tmp_path):
    findings, code = _tree(tmp_path, {"pkg/m.py": '''
        def f():
            """Docs may quote '# lint: frozen-exempt (<reason>)' freely."""
            return 1
    '''})
    assert (findings, code) == ([], 0)


# -- summary pass -------------------------------------------------------------

def test_summary_records_locks_calls_and_markers():
    from nds_tpu.analysis.summary import summarize_source
    mod = summarize_source("m.py", textwrap.dedent("""
        class S:
            def work(self):  # lint: device-lane (lane)
                with self._sql_lock:
                    with self._lock:
                        self.flush()
    """))
    fn = mod.functions[0]
    assert (fn.cls, fn.name, fn.lane) == ("S", "work", True)
    assert [(la.raw, la.held) for la in fn.locks] == [
        ("self._sql_lock", ()), ("self._lock", ("self._sql_lock",))]
    call = [c for c in fn.calls if c.name == "flush"][0]
    assert call.is_self and call.in_lane
    assert call.held == ("self._sql_lock", "self._lock")


def test_summary_resolves_transitive_acquires():
    from nds_tpu.analysis import lock_order
    from nds_tpu.analysis.summary import summarize_source, ProgramSummary
    prog = ProgramSummary([summarize_source("m.py", textwrap.dedent("""
        class S:
            def outer(self):
                self.inner()

            def inner(self):
                with self._lock:
                    pass
    """))])
    acq = lock_order._transitive_acquires(prog)
    outer = [f for f in prog.functions if f.name == "outer"][0]
    assert acq[id(outer)] == {"S._lock"}


# -- the CI gate: the real tree is clean, and fast ---------------------------

def test_nds_tpu_tree_is_clean_within_budget():
    import time
    t0 = time.perf_counter()
    findings = LINT.lint_paths([os.path.join(_REPO, "nds_tpu")])
    wall = time.perf_counter() - t0
    assert findings == [], "\n".join(str(f) for f in findings)
    assert wall < 10.0, f"lint took {wall:.1f}s, budget is 10s"


def test_cli_exit_codes(tmp_path):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    dirty = tmp_path / "dirty.py"
    dirty.write_text("def f(n):\n    n.out_dtypes = []\n")
    assert LINT.main([str(clean)]) == 0
    assert LINT.main([str(dirty)]) == 1
    assert LINT.main([]) == 2


def test_json_output_is_machine_readable(tmp_path, capsys):
    dirty = tmp_path / "dirty.py"
    dirty.write_text("def f(n):\n    n.out_dtypes = []\n")
    assert LINT.main(["--json", str(dirty)]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["ok"] is False
    assert doc["counts"] == {"ENG001": 1}
    (f,) = doc["findings"]
    assert f["rule"] == "ENG001" and f["line"] == 2
    assert "frozen-exempt" in f["pragma_suggestion"]

    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    assert LINT.main(["--json", str(clean)]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc == {"ok": True, "counts": {}, "findings": []}
