"""Engine-discipline lint (scripts/lint_engine.py): regression pins.

Two behaviors matter: the real tree lints CLEAN (the CI gate), and
reintroducing either hazard class — an in-place mutation of a frozen
PlanNode field, or an unlocked cross-thread attribute write — is flagged.
"""
import importlib.util
import os
import textwrap

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint():
    import sys
    spec = importlib.util.spec_from_file_location(
        "lint_engine", os.path.join(_REPO, "scripts", "lint_engine.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules["lint_engine"] = mod     # dataclasses resolves __module__
    spec.loader.exec_module(mod)
    return mod


LINT = _lint()


def _findings(src: str):
    return LINT.lint_source("snippet.py", textwrap.dedent(src))


# -- ENG001: frozen plan IR -------------------------------------------------

def test_flags_reintroduced_plannode_mutation():
    out = _findings("""
        def widen(node, col):
            node.out_names = node.out_names + [col]
    """)
    assert [f.rule for f in out] == ["ENG001"]
    assert "out_names" in out[0].message


def test_flags_subscript_and_mutating_calls():
    out = _findings("""
        def corrupt(join, proj, e):
            join.left_keys[0] = e
            proj.exprs.append(e)
    """)
    assert [f.rule for f in out] == ["ENG001", "ENG001"]


def test_allows_locally_constructed_builders():
    # builder-style initialization of a node the function provably owns
    out = _findings("""
        def build(child, exprs):
            p = ProjectNode(child, [])
            p.exprs = exprs
            return p
    """)
    assert out == []


def test_allows_unrelated_self_attributes():
    # Planner-style classes own attributes that share plan-field names
    out = _findings("""
        class Planner:
            def __init__(self):
                self.cte_segments = []
                self.keys = {}
    """)
    assert out == []


def test_flags_self_writes_inside_ir_classes():
    out = _findings("""
        class ProjectNode:
            def grow(self, e):
                self.exprs = self.exprs + [e]
    """)
    assert [f.rule for f in out] == ["ENG001"]


def test_frozen_pragma_exempts():
    out = _findings("""
        def annotate(root, segs):
            root.cte_segments = segs  # lint: frozen-exempt (root annotation)
    """)
    assert out == []


# -- ENG002: unlocked cross-thread writes -----------------------------------

def test_flags_unlocked_cross_thread_write():
    out = _findings("""
        import threading

        class Streamer:
            def start(self):
                t = threading.Thread(target=self._work)
                t.start()

            def _work(self):
                self.progress = 1
    """)
    assert [f.rule for f in out] == ["ENG002"]
    assert "progress" in out[0].message


def test_lock_protected_write_allowed():
    out = _findings("""
        import threading

        class Streamer:
            def start(self):
                t = threading.Thread(target=self._work)
                t.start()

            def _work(self):
                with self._lock:
                    self.progress = 1
    """)
    assert out == []


def test_thread_local_objects_allowed():
    out = _findings("""
        from concurrent.futures import ThreadPoolExecutor

        def launch(pool, items):
            pool.map(worker, items)

        def worker(item):
            acc = Accumulator()
            acc.total = 0       # thread-local, not shared state
            return acc
    """)
    assert out == []


def test_pool_submit_target_detected():
    out = _findings("""
        def launch(pool, shared):
            pool.submit(worker, shared)

        def worker(shared):
            shared.count = 1
    """)
    assert [f.rule for f in out] == ["ENG002"]


def test_lock_exempt_pragma():
    out = _findings("""
        import threading

        def launch(state):
            threading.Thread(target=work).start()

        def work(state):
            state.flag = True  # lint: lock-exempt (write-once sentinel)
    """)
    assert out == []


def test_thread_entry_pragma_applies_eng002():
    """Functions entered concurrently WITHOUT being a literal thread
    target (Session.sql / column_stats under the query service) opt into
    ENG002 with the def-line thread-entry pragma: an unlocked cache write
    inside is flagged, the same write under the lock is not."""
    out = _findings("""
        class Session:
            def column_stats(self, name):  # lint: thread-entry (service)
                self._col_stats[name] = {}
                return self._col_stats[name]
    """)
    assert [f.rule for f in out] == ["ENG002"]
    assert "_col_stats" in out[0].message

    out = _findings("""
        class Session:
            def column_stats(self, name):  # lint: thread-entry (service)
                with self._lock:
                    self._col_stats[name] = {}
                return self._col_stats[name]
    """)
    assert out == []


def test_thread_entry_pragma_on_multiline_def():
    out = _findings("""
        class Session:
            def sql(self, query,
                    backend=None):  # lint: thread-entry (service clients)
                self.last = query
    """)
    assert [f.rule for f in out] == ["ENG002"]


# -- the CI gate: the real tree is clean ------------------------------------

def test_nds_tpu_tree_is_clean():
    findings = LINT.lint_paths([os.path.join(_REPO, "nds_tpu")])
    assert findings == [], "\n".join(str(f) for f in findings)


def test_cli_exit_codes(tmp_path):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    dirty = tmp_path / "dirty.py"
    dirty.write_text("def f(n):\n    n.out_dtypes = []\n")
    assert LINT.main([str(clean)]) == 0
    assert LINT.main([str(dirty)]) == 1
    assert LINT.main([]) == 2
