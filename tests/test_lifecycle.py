"""Crash-resumable scored lifecycle (nds_tpu/lifecycle).

Fast tests drive the checkpoint/resume/score machinery through a stub
runner writing deterministic phase logs (phase bodies are the ONLY thing
stubbed — state transitions, retries, scraping, and scoring are real);
the slow tests run the real thing end to end at SF0.001, including a
mid-power SIGKILL + --resume and the chaos round."""
import csv
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from nds_tpu.bench import get_perf_metric
from nds_tpu.lifecycle import (PHASES, LifecycleConfig, LifecycleRunner,
                               LifecycleStateError)
from nds_tpu.obs.metrics import METRICS
from nds_tpu.power import _write_time_log
from nds_tpu.throughput import stream_log_path

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the stub phases' deterministic timing-log contents (ms epochs)
POWER_ROWS = [("query1", 1000, 3000, 2000), ("query3", 3000, 4000, 1000)]
POWER_SPAN = (1000, 5000)           # -> Power Test Time 4.0 s
STREAM_SPAN = (1000, 11000)         # -> throughput elapsed 10.0 s
DM_ROWS = [("LF_CR", 0, 500, 500), ("DF_I", 500, 750, 250)]  # 0.75 s
LOAD_SECONDS = 12.345               # -> 12.4 after round-up


class StubRunner(LifecycleRunner):
    """Real checkpoint/score machinery over deterministic phase bodies.
    ``fail_phases`` maps phase name -> number of times it raises before
    succeeding (the injected mid-lifecycle crash)."""

    def __init__(self, config, fail_phases=None):
        super().__init__(config)
        self.calls = []
        self.fail_phases = dict(fail_phases or {})

    def _mark(self, name):
        self.calls.append(name)
        left = self.fail_phases.get(name, 0)
        if left > 0:
            self.fail_phases[name] = left - 1
            raise RuntimeError(f"injected failure in {name}")

    def _phase_datagen(self):
        self._mark("datagen")

    def _phase_load(self):
        self._mark("load")
        with open(self._load_report(), "w") as f:
            f.write(f"Load Test Time: {LOAD_SECONDS} seconds\n"
                    "RNGSEED used: 123\n")

    def _phase_streams(self):
        self._mark("streams")

    def _phase_power(self):
        self._mark("power")
        _write_time_log(self._power_log(), POWER_SPAN[0], POWER_ROWS,
                        POWER_SPAN[1])

    def _phase_throughput(self, rnd):
        self._mark(f"throughput{rnd}")
        from nds_tpu.bench import get_stream_range
        for s in get_stream_range(self.cfg.num_streams, rnd):
            _write_time_log(stream_log_path(self.cfg.report_dir, s),
                            STREAM_SPAN[0], POWER_ROWS, STREAM_SPAN[1])

    def _phase_maintenance(self, rnd):
        self._mark(f"maintenance{rnd}")
        from nds_tpu.bench import get_stream_range
        for s in get_stream_range(self.cfg.num_streams, rnd):
            with open(self._dm_log(s), "w", newline="") as f:
                w = csv.writer(f)
                w.writerow(["query", "start_time", "end_time", "time"])
                w.writerow(["Maintenance Start Time", 0, "", ""])
                for r in DM_ROWS:
                    w.writerow(r)
                w.writerow(["Maintenance End Time", 750, "", ""])


def cfg_for(tmp_path, name="run", **kw):
    kw.setdefault("scale_factor", 100.0)    # big SF: a nonzero stub metric
    kw.setdefault("num_streams", 3)
    return LifecycleConfig(report_dir=str(tmp_path / name), **kw)


EXPECTED_TIMES = {"load": 12.4, "power": 4.0, "throughput1": 10.0,
                  "throughput2": 10.0, "maintenance1": 0.8,
                  "maintenance2": 0.8}


def test_stub_run_scores_and_checkpoints(tmp_path):
    r = StubRunner(cfg_for(tmp_path))
    out = r.run()
    assert r.calls == list(PHASES)
    assert out["times"] == EXPECTED_TIMES
    assert out["metric"] == get_perf_metric(
        100.0, 3, 12.4, 4.0, 10.0, 10.0, 0.8, 0.8) > 0
    state = json.load(open(r.state_path))
    assert all(state["phases"][p]["status"] == "done" for p in PHASES)
    assert state["score"]["perf_metric"] == out["metric"]
    assert os.path.exists(os.path.join(r.cfg.report_dir, "metrics.csv"))


def test_crash_then_resume_identical_score_inputs(tmp_path):
    # uninterrupted reference
    ref = StubRunner(cfg_for(tmp_path, "ref")).run()
    # crashed run: throughput1 raises once, phase_attempts=1 -> the run
    # dies mid-lifecycle exactly like a SIGKILL after the power phase
    cfg = cfg_for(tmp_path, "crash")
    r1 = StubRunner(cfg, fail_phases={"throughput1": 1})
    with pytest.raises(RuntimeError, match="injected failure"):
        r1.run()
    state = json.load(open(r1.state_path))
    for p in ("datagen", "load", "streams", "power"):
        assert state["phases"][p]["status"] == "done"
    assert state["phases"]["throughput1"]["status"] == "failed"
    # resume with a fresh runner (new process, no memory of the first)
    r2 = StubRunner(cfg_for(tmp_path, "crash"))
    out = r2.run(resume=True)
    # completed phases did NOT re-run; the interrupted one did
    assert r2.calls == ["throughput1", "maintenance1", "throughput2",
                        "maintenance2"]
    # the acceptance bar: identical per-phase timing-log inputs to the
    # score, and therefore the identical score
    assert out["times"] == ref["times"]
    assert out["metric"] == ref["metric"]


def test_existing_state_requires_resume(tmp_path):
    cfg = cfg_for(tmp_path)
    StubRunner(cfg).run()
    with pytest.raises(LifecycleStateError, match="--resume"):
        StubRunner(cfg_for(tmp_path)).run()


def test_incompatible_config_refused_on_resume(tmp_path):
    StubRunner(cfg_for(tmp_path)).run()
    other = cfg_for(tmp_path, sub_queries=["query1"])
    with pytest.raises(LifecycleStateError, match="incompatible"):
        StubRunner(other).run(resume=True)


def test_phase_retry_counts_metric(tmp_path):
    before = METRICS.snapshot()
    cfg = cfg_for(tmp_path, phase_attempts=2)
    r = StubRunner(cfg, fail_phases={"power": 1})
    out = r.run()
    assert out["times"] == EXPECTED_TIMES
    assert METRICS.delta(before).get("lifecycle_phase_retries", 0) == 1
    state = json.load(open(r.state_path))
    assert state["phases"]["power"]["attempts"] == 2


# -- the real thing (slow) ----------------------------------------------------

LIFECYCLE_CLI = os.path.join(REPO, "scripts", "run_lifecycle.py")
SUBSET = "query1,query3"


def _cli(report_dir, *extra):
    return [sys.executable, LIFECYCLE_CLI, "--sf", "0.001",
            "--report_dir", report_dir, "--streams", "3",
            "--sub_queries", SUBSET, "--throughput_mode", "thread",
            "--rngseed", "777", "--datagen_parallel", "2", *extra]


@pytest.mark.slow
def test_real_lifecycle_kill_mid_power_then_resume(tmp_path):
    """SIGKILL the run once the power phase has flushed at least one
    query, then --resume: the run completes, the pre-kill power rows are
    preserved verbatim, every query is timed exactly once, and the score
    comes out of the combined logs."""
    rd = str(tmp_path / "life")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(_cli(rd), env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    power_log = os.path.join(rd, "power.csv")
    deadline = time.time() + 900
    killed = False
    while time.time() < deadline:
        if proc.poll() is not None:
            break       # finished before we could kill: still a pass
        if os.path.exists(power_log):
            try:
                rows = [r for r in csv.reader(open(power_log))
                        if r and r[0].startswith("query")
                        and r[0] != "query"]
            except OSError:
                rows = []
            if rows:
                proc.send_signal(signal.SIGKILL)
                proc.wait()
                killed = True
                break
        time.sleep(0.5)
    assert killed or proc.poll() == 0
    pre_kill = []
    if killed:
        pre_kill = [r for r in csv.reader(open(power_log))
                    if r and r[0].startswith("query") and r[0] != "query"]
    out = subprocess.run(_cli(rd, "--resume"), env=env,
                         capture_output=True, text=True, timeout=1800)
    assert out.returncode == 0, out.stderr[-2000:]
    final = [r for r in csv.reader(open(power_log))
             if r and r[0].startswith("query") and r[0] != "query"]
    # pre-kill measurements preserved verbatim; every query exactly once
    assert final[:len(pre_kill)] == pre_kill
    assert sorted(r[0] for r in final) == sorted(SUBSET.split(","))
    state = json.load(open(os.path.join(rd, "lifecycle_state.json")))
    assert all(state["phases"][p]["status"] == "done" for p in PHASES)
    assert "perf_metric" in state["score"]
    assert os.path.exists(os.path.join(rd, "metrics.csv"))


@pytest.mark.slow
def test_real_lifecycle_chaos_round(tmp_path):
    """Chaos mode for real: maintenance concurrently with service-mode
    streams under an armed campaign, flight dumps per firing, and the
    run still scores."""
    from nds_tpu.lifecycle import run_lifecycle

    cfg = LifecycleConfig(
        scale_factor=0.001, num_streams=3,
        report_dir=str(tmp_path / "chaos"),
        sub_queries=SUBSET.split(","), rngseed=777,
        chaos=True, chaos_times_per_point=1, phase_attempts=2)
    out = run_lifecycle(cfg)
    assert set(out["times"]) == {"load", "power", "throughput1",
                                 "throughput2", "maintenance1",
                                 "maintenance2"}
    state = json.load(open(os.path.join(cfg.report_dir,
                                        "lifecycle_state.json")))
    assert all(state["phases"][p]["status"] == "done" for p in PHASES)
    fired = state["phases"]["throughput1"].get("chaos_fired", [])
    assert {f["point"] for f in fired} == set(cfg.chaos_points)
