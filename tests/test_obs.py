"""Observability layer (nds_tpu/obs): span tracer, metrics registry,
device-time attribution, typed ExecStats, logging channel.

Acceptance-backed properties:
- disabled tracer hooks are near-free (the <2% bench-slice overhead bound
  rests on the disabled path doing no allocation/locking);
- a traced query produces a WELL-FORMED span tree (every span closed,
  every parent id resolvable) that exports to valid Chrome trace-event
  JSON (Perfetto-loadable);
- metrics counters move correctly under the fault-injection smoke run;
- ExecStats is built in one place with a dict view identical to the
  legacy untyped ``last_exec_stats`` keys, and records EVERY prefetch
  error.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.config import EngineConfig
from nds_tpu.engine import Session
from nds_tpu.obs import device_time as dt
from nds_tpu.obs import log as obs_log
from nds_tpu.obs import metrics as om
from nds_tpu.obs.stats import ExecStats
from nds_tpu.obs.trace import (NULL_SPAN, TRACER, span_tree,
                               validate_chrome_trace)
from nds_tpu.resilience import FAULTS, FaultError, FaultSpec, RetryPolicy

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _tracer_off():
    """Every test starts from a disabled, empty tracer."""
    TRACER.configure(enabled=False)
    yield
    TRACER.configure(enabled=False)


def make_session(**cfg_kwargs) -> Session:
    s = Session(EngineConfig(**cfg_kwargs))
    rng = np.random.default_rng(11)
    t = pa.table({
        "k": pa.array(rng.integers(0, 7, 5000), type=pa.int32()),
        "v": pa.array(rng.integers(0, 1000, 5000), type=pa.int64()),
    })
    s.register_arrow("t", t)
    return s


QUERY = "SELECT k, COUNT(*) AS c, SUM(v) AS sv FROM t GROUP BY k ORDER BY k"


# -- tracer: disabled path ----------------------------------------------------

def test_disabled_span_is_shared_noop():
    assert not TRACER.enabled
    sp = TRACER.span("anything", rows=1)
    assert sp is NULL_SPAN
    with sp as inner:
        inner.set(bytes=2)
    assert TRACER.events() == []


def test_disabled_span_overhead_is_negligible():
    """The <2% bench bound rests on this: a disabled hook must cost
    ~an attribute read. 200k calls in well under a second leaves orders
    of magnitude of headroom against ms-scale engine operations."""
    t0 = time.perf_counter()
    for _ in range(200_000):
        with TRACER.span("x", table="t", rows=5):
            pass
    elapsed = time.perf_counter() - t0
    assert elapsed < 2.0, f"disabled spans too slow: {elapsed:.2f}s/200k"


def test_disabled_run_records_nothing():
    s = make_session()
    s.sql(QUERY, backend="jax")
    assert TRACER.events() == []
    assert TRACER.open_spans() == []


# -- tracer: enabled lifecycle ------------------------------------------------

def test_span_tree_well_formed_for_real_query():
    TRACER.configure(enabled=True)
    s = make_session(verify_plans="per-pass")
    for _ in range(3):   # record -> compile+run -> compiled
        s.sql(QUERY, backend="jax", label="obs_q")
    assert TRACER.open_spans() == [], "unclosed spans"
    events = TRACER.events()
    names = {e["name"] for e in events}
    # the lifecycle phases the tentpole promises all appear
    for expected in ("query", "parse", "plan", "plan.pass", "plan.verify",
                     "record", "exec", "upload"):
        assert expected in names, f"missing {expected!r} span in {names}"
    tree = span_tree(events)      # raises on a dangling parent id
    roots = tree.get(0, [])
    assert len(roots) >= 3        # one "query" root per sql() call
    for e in events:
        if e.get("ph") == "X":
            assert e["dur"] >= 0
            assert e["ts"] >= 0
    # parse/plan nest under a query root
    by_sid = {e["sid"]: e for e in events if e.get("ph") == "X"}
    parse = next(e for e in events if e["name"] == "parse")
    chain = []
    cur = parse
    while cur.get("parent"):
        cur = by_sid[cur["parent"]]
        chain.append(cur["name"])
    assert "query" in chain


def test_span_attrs_and_error_marking():
    TRACER.configure(enabled=True)
    with pytest.raises(RuntimeError):
        with TRACER.span("boom", table="t") as sp:
            sp.set(rows=4)
            raise RuntimeError("x")
    (event,) = TRACER.events()
    assert event["args"]["table"] == "t"
    assert event["args"]["rows"] == 4
    assert event["args"]["error"] == "RuntimeError"
    assert TRACER.open_spans() == []


def test_spans_from_worker_threads_are_recorded():
    import threading
    TRACER.configure(enabled=True)
    barrier = threading.Barrier(4)   # all spans open concurrently, so the
    #                                  OS cannot recycle thread identities

    def work():
        barrier.wait()
        with TRACER.span("worker.span"):
            barrier.wait()

    ts = [threading.Thread(target=work) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    events = [e for e in TRACER.events() if e["name"] == "worker.span"]
    assert len(events) == 4
    assert len({e["tid"] for e in events}) == 4
    span_tree(TRACER.events())


# -- tracer: exporters --------------------------------------------------------

def test_chrome_trace_export_is_valid(tmp_path):
    TRACER.configure(enabled=True)
    s = make_session()
    s.sql(QUERY, backend="jax", label="chrome_q")
    path = TRACER.write_chrome_trace(str(tmp_path / "trace.json"))
    n = validate_chrome_trace(path)
    assert n >= 4
    with open(path) as f:
        doc = json.load(f)
    assert isinstance(doc["traceEvents"], list)
    assert doc["displayTimeUnit"] == "ms"
    # every complete event Perfetto needs: name/ph/ts/dur/pid/tid
    for e in doc["traceEvents"]:
        if e["ph"] == "X":
            assert isinstance(e["ts"], (int, float))
            assert isinstance(e["dur"], (int, float))


def test_jsonl_export_and_aggregate(tmp_path):
    TRACER.configure(enabled=True)
    with TRACER.span("a"):
        with TRACER.span("b"):
            pass
    with TRACER.span("a"):
        pass
    path = TRACER.write_jsonl(str(tmp_path / "events.jsonl"))
    lines = [json.loads(ln) for ln in open(path)]
    assert len(lines) == 3
    agg = TRACER.aggregate()
    assert agg["a"]["count"] == 2
    assert agg["b"]["count"] == 1
    assert agg["a"]["total_ms"] >= agg["a"]["max_ms"]


def test_trace_report_cli_on_trace_and_bench_json(tmp_path):
    TRACER.configure(enabled=True)
    with TRACER.span("cli.span", table="t"):
        pass
    trace = TRACER.write_chrome_trace(str(tmp_path / "t.json"))
    script = os.path.join(REPO, "scripts", "trace_report.py")
    out = subprocess.run([sys.executable, script, trace],
                         capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert "cli.span" in out.stdout
    bench = {"metric": "m", "value": 1.0, "unit": "ms", "vs_baseline": 1.0,
             "device_time_programs": [
                 {"program": "q1/root", "runs": 3, "device_ms": 30.0,
                  "mean_ms": 10.0, "max_ms": 12.0, "roofline_frac": 0.01}],
             "attribution_frac": {"q1": 0.97},
             "metrics": {"queries_run": 3}}
    bpath = tmp_path / "bench.json"
    bpath.write_text(json.dumps(bench))
    out = subprocess.run([sys.executable, script, str(bpath)],
                         capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert "q1/root" in out.stdout
    assert "queries_run" in out.stdout


# -- metrics registry ---------------------------------------------------------

def test_counter_and_gauge_basics():
    reg = om.MetricsRegistry()
    c = reg.counter("c", "help text")
    c.inc()
    c.inc(4)
    assert c.value == 5
    g = reg.gauge("g")
    g.set(7)
    g.add(-2)
    assert g.value == 5
    assert reg.snapshot() == {"c": 5, "g": 5}
    assert reg.describe()["c"] == "help text"
    with pytest.raises(TypeError):
        reg.gauge("c")
    assert reg.delta({"c": 2}) == {"c": 3, "g": 5}


def test_counters_are_thread_safe():
    import threading
    reg = om.MetricsRegistry()
    c = reg.counter("n")

    def work():
        for _ in range(10_000):
            c.inc()

    ts = [threading.Thread(target=work) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert c.value == 80_000


def test_query_metrics_move_through_session():
    before = om.METRICS.snapshot()
    s = make_session()
    for _ in range(3):
        s.sql(QUERY, backend="jax")
    d = om.METRICS.delta(before)
    assert d.get("queries_run") == 3
    assert d.get("program_cache_misses", 0) >= 1   # first sighting records
    assert d.get("program_cache_hits", 0) >= 2     # replays hit the cache
    assert d.get("compiles", 0) >= 1


def test_fault_injection_smoke_moves_counters():
    """The resilience smoke path: an armed fault fires (counted), the
    retry policy retries over it (counted), and the run completes."""
    before = om.METRICS.snapshot()
    spec = FAULTS.arm(FaultSpec(point="query.run", match="obs_smoke",
                                times=2))
    try:
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            FAULTS.fire("query.run", "obs_smoke")
            return "ok"

        policy = RetryPolicy(max_attempts=5, backoff_s=0.0)
        assert policy.call(flaky, sleep=lambda _s: None) == "ok"
    finally:
        FAULTS.disarm(spec)
    d = om.METRICS.delta(before)
    assert d.get("fault_point_firings") == 2
    assert d.get("retries") == 2
    assert calls["n"] == 3


def test_exhausted_retries_still_counted():
    before = om.METRICS.snapshot()

    def always_fails():
        raise FaultError("nope")

    policy = RetryPolicy(max_attempts=3, backoff_s=0.0)
    with pytest.raises(FaultError):
        policy.call(always_fails, sleep=lambda _s: None)
    # 3 attempts = 2 retries (the first try is not a retry)
    assert om.METRICS.delta(before).get("retries") == 2


# -- device-time attribution --------------------------------------------------

def test_program_registry_table_and_roofline():
    reg = dt.ProgramRegistry()
    reg.record_run("q9/root", 10.0)
    reg.record_run("q9/root", 30.0)
    reg.record_run("q1/root", 5.0)
    reg.record_cost("q9/root", {"flops": 1e6, "bytes accessed": 4e6})
    reg.record_cost("q1/root", [{"flops": 2e3, "bytes accessed": 1e3}])
    rows = reg.table(bw_gbps=100.0)
    assert [r["program"] for r in rows] == ["q9/root", "q1/root"]
    top = rows[0]
    assert top["runs"] == 2
    assert top["device_ms"] == 40.0
    assert top["mean_ms"] == 20.0
    assert top["max_ms"] == 30.0
    # roofline = (bytes / bw) / mean_run_s = (4e6/1e11) / 0.020 = 0.002
    assert abs(top["roofline_frac"] - 0.002) < 1e-6
    assert dt.coverage(rows, 50.0) == pytest.approx(0.9)
    text = dt.format_table(rows)
    assert "q9/root" in text and "roofline" in text


def test_compiled_runs_attribute_device_time():
    before = dt.PROGRAMS.snapshot()
    s = make_session()
    for _ in range(3):
        s.sql(QUERY, backend="jax", label="attr_q")
    after = dt.PROGRAMS.snapshot()
    new = {k: v for k, v in after.items() if k not in before}
    assert any(k.startswith("attr_q") for k in new), new
    st = next(v for k, v in new.items() if k.startswith("attr_q"))
    assert st.runs >= 2          # compile+run + compiled replay
    assert st.device_ms > 0


# -- ExecStats ----------------------------------------------------------------

def test_exec_stats_executor_dict_view_matches_legacy():
    st = ExecStats.from_executor(
        {"mode": "compiled", "device_ms": 1.5, "custom_key": 7},
        fallbacks=["ScanNode: no"])
    d = st.to_dict()
    assert d["mode"] == "compiled"
    assert d["device_ms"] == 1.5
    assert d["custom_key"] == 7            # unknown keys pass through
    assert d["fallback_reasons"] == ["ScanNode: no"]
    assert "jobs" not in d                 # unset streaming fields dropped
    assert "segments" not in d


def test_exec_stats_streaming_records_all_prefetch_errors():
    st = ExecStats.streaming(
        jobs=1, morsels=4, morsel_rows=1024, re_records=0, shared_scan=True,
        scan_passes=1, tables_streamed=1, branches_served=2, fused_groups=1,
        bytes_uploaded=100, morsels_per_table={"fact": 4}, narrow_lanes=True,
        lane_spec={"fact": {"fk": "u16"}},
        prefetch_error_details=["OSError: a", "OSError: b", "OSError: c"])
    d = st.to_dict()
    assert d["mode"] == "streaming"
    assert d["prefetch_errors"] == 3               # legacy count key
    assert d["prefetch_error"] == "OSError: a"     # legacy first-error key
    assert d["prefetch_error_details"] == ["OSError: a", "OSError: b",
                                           "OSError: c"]
    assert d["lane_spec"] == {"fact": {"fk": "u16"}}


def test_session_installs_typed_stats_both_paths(tmp_path):
    import pyarrow.parquet as pq
    # streaming path
    rng = np.random.default_rng(3)
    fact = pa.table({
        "fk": pa.array(rng.integers(0, 50, 30_000), type=pa.int32()),
        "v": pa.array(rng.integers(0, 100, 30_000), type=pa.int64())})
    path = os.path.join(str(tmp_path), "fact.parquet")
    pq.write_table(fact, path, row_group_size=4096)
    s = Session(EngineConfig(chunk_rows=4096, out_of_core_min_rows=10_000))
    s.register_parquet("fact", path)
    s.sql("SELECT fk, SUM(v) FROM fact GROUP BY fk", backend="jax")
    assert s.last_exec_stats_typed is not None
    assert s.last_exec_stats_typed.mode == "streaming"
    assert s.last_exec_stats == s.last_exec_stats_typed.to_dict()
    assert s.last_exec_stats["morsels"] == s.last_exec_stats_typed.morsels
    # in-core path on the same session
    s2 = make_session()
    s2.sql(QUERY, backend="jax")
    assert s2.last_exec_stats_typed.mode in ("record", "compile+run",
                                             "compiled", "adopted")
    assert s2.last_exec_stats == s2.last_exec_stats_typed.to_dict()


# -- logging ------------------------------------------------------------------

def test_log_verbosity_gates_info(capsys):
    import logging
    logger = obs_log.configure(verbosity=0, force=True)
    assert logger.level == logging.WARNING
    logger = obs_log.configure(verbosity=2, force=True)
    assert logger.level == logging.DEBUG
    child = obs_log.get_logger("bench")
    assert child.name == "nds_tpu.bench"
    # restore the env-driven default for other tests
    obs_log.configure(force=True)


# -- report schema ------------------------------------------------------------

def test_bench_report_schema_version_and_host_capture():
    from nds_tpu.report import SCHEMA_VERSION, BenchReport
    os.environ["NDS_TPU_TEST_SECRET"] = "hunter2"
    try:
        rep = BenchReport(EngineConfig(), app_name="obs-test")
    finally:
        del os.environ["NDS_TPU_TEST_SECRET"]
    assert rep.summary["schemaVersion"] == SCHEMA_VERSION
    host = rep.summary["env"]["host"]
    import socket
    assert host["host_id"] != socket.gethostname()   # never the raw name
    assert len(host["host_id"]) == 10
    assert host["python"]
    assert rep.summary["env"]["envVars"]["NDS_TPU_TEST_SECRET"] == \
        "*********(redacted)"
    rep.record_metrics({"queries_run": 2})
    assert rep.summary["metrics"] == {"queries_run": 2}
