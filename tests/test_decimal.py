"""Scaled-int64 decimal ("decN") tests.

decimal_physical="i64" stores DECIMAL(p,s) columns as value*10^s int64 —
exact sums/compares on integers, float only at division points (SURVEY.md §7
scaled-int64 decimal plan; the reference keeps DecimalType end-to-end,
nds/nds_schema.py:43-47). Covers both backends plus the use_decimal=True
end-to-end run the round-1 verdict asked for.
"""
import decimal

import pyarrow as pa
import pytest

from nds_tpu.config import EngineConfig
from nds_tpu.engine import Session
from nds_tpu.engine.column import dec_dtype, dec_scale, is_dec

D = decimal.Decimal


def dec_table() -> pa.Table:
    return pa.table({
        "k": pa.array([1, 1, 2, 2, 2, 3]),
        "p": pa.array([D("1.10"), D("2.25"), None, D("0.05"), D("-3.33"),
                       D("7.00")], type=pa.decimal128(7, 2)),
        "q": pa.array([2, 3, 1, 4, 2, 5]),
        "f": pa.array([0.5, 1.5, 2.5, 3.5, 4.5, 5.5]),
    })


@pytest.fixture(scope="module", params=["numpy", "jax"])
def backend(request):
    return request.param


@pytest.fixture(scope="module")
def dec_session():
    s = Session(EngineConfig(decimal_physical="i64"))
    s.register_arrow("t", dec_table())
    return s


def rows(t):
    return t.to_pylist()


def test_dec_dtype_helpers():
    assert is_dec("dec2") and is_dec("dec0")
    assert not is_dec("decimal") and not is_dec("int") and not is_dec("dec")
    assert dec_scale("dec2") == 2
    assert dec_dtype(4) == "dec4"


def test_scan_is_scaled_int(dec_session):
    t = dec_session.load_table("t")
    col = t.columns[t.names.index("p")]
    assert col.dtype == "dec2"
    assert col.data.dtype.kind == "i"
    assert col.data[0] == 110  # 1.10 -> 110


def test_exact_aggregates(dec_session, backend):
    r = dec_session.sql(
        "SELECT k, SUM(p) AS sp, AVG(p) AS ap, MIN(p) AS mn, MAX(p) AS mx, "
        "COUNT(p) AS c FROM t GROUP BY k ORDER BY k", backend=backend)
    assert dec_session.last_fallbacks == []
    got = rows(r)
    assert got[0] == (1, D("3.35"), 1.675, D("1.10"), D("2.25"), 2)
    assert got[1] == (2, D("-3.28"), pytest.approx(-1.64), D("-3.33"),
                      D("0.05"), 2)
    assert got[2] == (3, D("7.00"), 7.0, D("7.00"), D("7.00"), 1)


def test_exactness_beyond_float32(backend):
    """Sums that would round in f32 (2^24 cutoff) stay exact as scaled ints."""
    n = 60000
    vals = [D("167772.16")] * n          # scaled: 16777216 = 2^24 each
    s = Session(EngineConfig(decimal_physical="i64"))
    s.register_arrow("big", pa.table({
        "v": pa.array(vals, type=pa.decimal128(18, 2))}))
    r = s.sql("SELECT SUM(v) AS sv FROM big", backend=backend)
    assert rows(r)[0][0] == D("167772.16") * n


def test_mixed_arithmetic(dec_session, backend):
    r = dec_session.sql(
        "SELECT SUM(p * q) AS spq, SUM(p + 1) AS sp1, SUM(p - p) AS zero, "
        "SUM(p * p) AS spp, SUM(p / q) AS ratio FROM t", backend=backend)
    got = rows(r)[0]
    assert got[0] == D("37.49")      # exact dec2 * int
    assert got[1] == D("12.07")      # 7.07 + 5x1 (int literal scaled)
    assert got[2] == D("0.00")
    assert got[3] == D("66.3639")    # dec2*dec2 -> dec4, exact
    assert got[4] == pytest.approx(1.10 / 2 + 2.25 / 3 + 0.05 / 4
                                   - 3.33 / 2 + 7.0 / 5)


def test_compare_and_in_list(dec_session, backend):
    r = dec_session.sql(
        "SELECT COUNT(*) AS c FROM t WHERE p > 0.04 AND p <= 2.25",
        backend=backend)
    assert rows(r)[0][0] == 3        # 1.10, 2.25, 0.05
    r = dec_session.sql(
        "SELECT COUNT(*) AS c FROM t WHERE p IN (1.10, 7.00, 9.99)",
        backend=backend)
    assert rows(r)[0][0] == 2
    r = dec_session.sql(          # non-representable literal can never match
        "SELECT COUNT(*) AS c FROM t WHERE p IN (1.105)", backend=backend)
    assert rows(r)[0][0] == 0


def test_dec_float_interplay(dec_session, backend):
    r = dec_session.sql(
        "SELECT SUM(p * f) AS pf, COUNT(CASE WHEN p > f THEN 1 END) AS c "
        "FROM t", backend=backend)
    got = rows(r)[0]
    assert got[0] == pytest.approx(1.10 * 0.5 + 2.25 * 1.5 + 0.05 * 3.5
                                   - 3.33 * 4.5 + 7.0 * 5.5)
    assert got[1] == 3               # 1.10>0.5, 2.25>1.5, 7.00>5.5


def test_casts(dec_session, backend):
    r = dec_session.sql(
        "SELECT CAST(p AS INT) AS pi, CAST(p AS DOUBLE) AS pf, "
        "CAST(q AS DECIMAL(7,2)) AS qd, ROUND(p, 1) AS p1, "
        "CAST(p AS DECIMAL(7,1)) AS pr FROM t WHERE p IS NOT NULL "
        "ORDER BY p", backend=backend)
    got = rows(r)
    # ordered by p: -3.33, 0.05, 1.10, 2.25, 7.00
    assert [g[0] for g in got] == [-3, 0, 1, 2, 7]      # truncate toward 0
    assert got[2][1] == pytest.approx(1.10)
    assert got[0][2] == D("2.00")                        # q=2 -> 2.00
    assert [g[3] for g in got] == [D("-3.3"), D("0.1"), D("1.1"),
                                   D("2.3"), D("7.0")]   # half-up
    assert [g[4] for g in got] == [D("-3.3"), D("0.1"), D("1.1"),
                                   D("2.3"), D("7.0")]


def test_windows_over_dec(dec_session, backend):
    r = dec_session.sql(
        "SELECT k, p, SUM(p) OVER (PARTITION BY k ORDER BY p) AS rs, "
        "RANK() OVER (ORDER BY p) AS rk FROM t WHERE p IS NOT NULL "
        "ORDER BY k, p", backend=backend)
    got = rows(r)
    assert got[0][2] == D("1.10") and got[1][2] == D("3.35")
    assert got[2][2] == D("-3.33") and got[3][2] == D("-3.28")


def test_dec_group_key_and_join(dec_session, backend):
    r = dec_session.sql(
        "SELECT p, COUNT(*) AS c FROM t WHERE p IS NOT NULL GROUP BY p "
        "ORDER BY p", backend=backend)
    assert len(rows(r)) == 5
    r = dec_session.sql(
        "SELECT a.k, b.p FROM t a JOIN t b ON a.p = b.p WHERE a.k = 3",
        backend=backend)
    assert rows(r) == [(3, D("7.00"))]


def test_round_negative_digits(dec_session, backend):
    s = Session(EngineConfig(decimal_physical="i64"))
    s.register_arrow("h", pa.table({
        "v": pa.array([D("12345.78"), D("-250.00")],
                      type=pa.decimal128(9, 2))}))
    r = s.sql("SELECT ROUND(v, -2) AS r FROM h", backend=backend)
    assert [v for (v,) in rows(r)] == [D("12300"), D("-300")]


def test_out_of_core_decimal_streaming():
    """Out-of-core morsels must load decimals as scaled ints too (the
    compiled morsel plan expects decN columns)."""
    n = 5000
    t = pa.table({
        "k": pa.array([i % 3 for i in range(n)]),
        "p": pa.array([D("1.25")] * n, type=pa.decimal128(7, 2)),
    })
    s = Session(EngineConfig(decimal_physical="i64", out_of_core=True,
                             chunk_rows=512, out_of_core_min_rows=1000))
    s.register_arrow("t", t, est_rows=n)
    s._est_rows["t"] = n
    r = s.sql("SELECT k, SUM(p) AS sp, COUNT(*) AS c FROM t GROUP BY k "
              "ORDER BY k")
    got = rows(r)
    assert s.last_exec_stats.get("mode") == "streaming"
    for k, sp, c in got:
        assert sp == D("1.25") * c


def test_setop_aligns_decimal_scales(dec_session, backend):
    # p is dec2; p*p is dec4 — the union must rescale, never concat raw ints
    r = dec_session.sql(
        "SELECT p FROM t WHERE k = 3 UNION ALL "
        "SELECT p * p FROM t WHERE k = 3", backend=backend)
    vals = sorted(v for (v,) in rows(r))
    assert vals == [D("7.00"), D("49.00")] or vals == [D("7.0000"),
                                                       D("49.0000")]


def test_use_decimal_end_to_end(tmp_path):
    """VERDICT item 7 done-criterion: use_decimal=True datagen -> transcode
    -> power-style queries on the i64 session validate against the f64
    oracle session under the validator epsilon."""
    from nds_tpu import datagen, streams, transcode, validate
    from nds_tpu.power import setup_tables
    data = str(tmp_path / "data")
    wh = str(tmp_path / "wh")
    datagen.generate_data_local(data, 0.001, parallel=2, overwrite=True)
    transcode.transcode(data, wh, str(tmp_path / "rep.txt"),
                        use_decimal=True, partition=False)

    s_dec = Session(EngineConfig(decimal_physical="i64"))
    setup_tables(s_dec, wh, "parquet")
    s_f64 = Session(EngineConfig())
    setup_tables(s_f64, wh, "parquet")

    def norm_rows(table):
        # Decimal -> float so the sort key matches across physical types
        out = []
        for row in table.to_pylist():
            out.append(tuple(float(v) if isinstance(v, D) else v
                             for v in row))
        key = lambda row: tuple((v is None, str(v)) for v in row
                                if not isinstance(v, float))
        return sorted(out, key=key)

    for number in (3, 7, 42, 52, 55):
        sql = streams.instantiate(number, stream=0, rngseed=31415)
        expected = s_f64.sql(sql, backend="numpy")
        actual = s_dec.sql(sql, backend="jax")
        assert s_dec.last_fallbacks == [], \
            f"query{number}: {s_dec.last_fallbacks}"
        rows_e = norm_rows(expected)
        rows_a = norm_rows(actual)
        assert len(rows_e) == len(rows_a), f"query{number}"
        for re_, ra_ in zip(rows_e, rows_a):
            assert validate.row_equal(re_, ra_, f"query{number}",
                                      list(expected.names)), \
                f"query{number}: {re_} != {ra_}"


def test_const_fold_dec_literal_with_float(dec_session):
    """Round-2 advisor (planner.py _const_fold): a dec literal in a
    float-typed fold must descale first — CAST(1.00 AS DECIMAL(7,2)) * 0.5
    is 0.5, not the raw scaled int 100 * 0.5 = 50."""
    rows_ = rows(dec_session.sql(
        "SELECT k FROM t WHERE f IN (CAST(1.00 AS DECIMAL(7,2)) * 0.5)"))
    assert rows_ == [(1,)]
    # division folds too (previously left unfolded -> PlanError in IN lists)
    rows_ = rows(dec_session.sql(
        "SELECT k FROM t WHERE f IN (CAST(1.00 AS DECIMAL(7,2)) / 2)"))
    assert rows_ == [(1,)]
    # dec * int stays exact on scaled ints
    rows_ = rows(dec_session.sql(
        "SELECT k FROM t WHERE p IN (CAST(1.10 AS DECIMAL(7,2)) * 2, "
        "CAST(7.00 AS DECIMAL(7,2)))"))
    assert sorted(rows_) == [(3,)]
    # folded mod uses truncated (fmod) semantics: (0-7) % 2 = -1, not +1
    rows_ = rows(dec_session.sql(
        "SELECT k FROM t WHERE q IN (9, (0 - 7) % 2 + 3)"))
    assert sorted(rows_) == [(1,), (2,)]


def test_wide_decimal_column_no_silent_wrap():
    """Round-2 advisor (arrow_bridge._decimal_to_scaled_i64): precision>18
    columns take the exact loop; in-range values convert exactly and
    out-of-int64 values raise instead of wrapping silently."""
    from nds_tpu.engine.arrow_bridge import from_arrow_column
    ok = pa.array([D("123.45"), None, D("-9999999999999999.99")],
                  type=pa.decimal128(20, 2))
    col = from_arrow_column(ok, dec_as_int=True)
    assert col.dtype == "dec2"
    assert col.data[0] == 12345
    assert col.data[2] == -999999999999999999
    bad = pa.array([D("9300000000000000000")], type=pa.decimal128(20, 0))
    with pytest.raises(OverflowError):
        from_arrow_column(bad, dec_as_int=True)
