import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.engine import Session


@pytest.fixture()
def s():
    s = Session()
    s.register_arrow("t", pa.table({
        "a": [1, 2, 3, 4, None],
        "b": [10.0, 20.0, 30.0, 40.0, 50.0],
        "g": ["x", "y", "x", "y", "x"],
        "d": pa.array([0, 1, 2, 3, 4], type=pa.int64()),
    }))
    s.register_arrow("u", pa.table({"k": [1, 2, 6], "v": ["one", "two", "six"]}))
    s.register_arrow("dates", pa.table({
        "dk": [1, 2, 3],
        "dt": pa.array(["2000-01-01", "2000-01-15", "2000-03-01"]).cast(pa.date32()),
    }))
    return s


def test_filter_and_order(s):
    assert s.sql("select a from t where a > 1 order by a desc").to_pylist() == \
        [(4,), (3,), (2,)]


def test_null_comparison_excluded(s):
    # NULL > 1 is unknown -> filtered out
    assert len(s.sql("select a from t where a > 0").to_pylist()) == 4


def test_three_valued_or(s):
    # a > 3 OR a IS NULL keeps the null row via IS NULL
    rows = s.sql("select a from t where a > 3 or a is null").to_pylist()
    assert rows == [(4,), (None,)]


def test_group_by(s):
    rows = s.sql("select g, sum(b), count(*), count(a), avg(a) "
                 "from t group by g order by g").to_pylist()
    assert rows == [("x", 90.0, 3, 2, 2.0), ("y", 60.0, 2, 2, 3.0)]


def test_count_distinct(s):
    rows = s.sql("select count(distinct g), count(distinct a) from t "
                 "group by 1=1" if False else
                 "select g, count(distinct g) from t group by g").to_pylist()
    assert rows == [("x", 1), ("y", 1)]


def test_global_aggregate(s):
    rows = s.sql("select sum(b), min(a), max(a), count(*) from t").to_pylist()
    assert rows == [(150.0, 1, 4, 5)]


def test_having(s):
    rows = s.sql("select g, sum(b) from t group by g "
                 "having sum(b) > 70 order by g").to_pylist()
    assert rows == [("x", 90.0)]


def test_inner_join(s):
    rows = s.sql("select a, v from t join u on t.a = u.k order by a").to_pylist()
    assert rows == [(1, "one"), (2, "two")]


def test_left_join_nulls(s):
    rows = s.sql("select a, v from t left join u on t.a = u.k "
                 "order by a nulls last").to_pylist()
    assert rows == [(1, "one"), (2, "two"), (3, None), (4, None), (None, None)]


def test_comma_join_with_where(s):
    rows = s.sql("select a, v from t, u where t.a = u.k order by a").to_pylist()
    assert rows == [(1, "one"), (2, "two")]


def test_semi_join_in_subquery(s):
    rows = s.sql("select a from t where a in (select k from u)").to_pylist()
    assert sorted(rows) == [(1,), (2,)]


def test_anti_join_not_exists(s):
    rows = s.sql("select a from t where not exists "
                 "(select 1 from u where u.k = t.a) and a is not null "
                 "order by a").to_pylist()
    assert rows == [(3,), (4,)]


def test_uncorrelated_scalar_subquery(s):
    # Spark default ordering: ASC => NULLS FIRST
    rows = s.sql("select a from t where b > (select avg(b) from t) "
                 "order by a").to_pylist()
    assert rows == [(None,), (4,)]


def test_correlated_scalar_subquery(s):
    rows = s.sql(
        "select g, b from t t1 where b > (select avg(b) from t t2 "
        "where t1.g = t2.g) order by g").to_pylist()
    assert rows == [("x", 50.0), ("y", 40.0)]


def test_window_rank(s):
    rows = s.sql("select g, b, rank() over (partition by g order by b desc) rk "
                 "from t order by g, rk").to_pylist()
    assert rows == [("x", 50.0, 1), ("x", 30.0, 2), ("x", 10.0, 3),
                    ("y", 40.0, 1), ("y", 20.0, 2)]


def test_window_running_sum(s):
    rows = s.sql("select d, sum(b) over (order by d) rs from t order by d").to_pylist()
    assert [r[1] for r in rows] == [10.0, 30.0, 60.0, 100.0, 150.0]


def test_window_whole_partition_avg(s):
    rows = s.sql("select g, avg(b) over (partition by g) ab from t "
                 "order by g, ab").to_pylist()
    assert rows[0] == ("x", 30.0) and rows[-1] == ("y", 30.0)


def test_distinct(s):
    assert s.sql("select distinct g from t order by g").to_pylist() == \
        [("x",), ("y",)]


def test_union_and_intersect(s):
    rows = s.sql("select k from u union select a from t where a is not null "
                 "order by k").to_pylist()
    assert rows == [(1,), (2,), (3,), (4,), (6,)]
    rows = s.sql("select k from u intersect select a from t").to_pylist()
    assert sorted(rows) == [(1,), (2,)]
    rows = s.sql("select k from u except select a from t").to_pylist()
    assert rows == [(6,)]


def test_rollup_grouping(s):
    rows = s.sql("select g, grouping(g) gg, sum(b) from t group by rollup(g) "
                 "order by gg, g").to_pylist()
    assert rows == [("x", 0, 90.0), ("y", 0, 60.0), (None, 1, 150.0)]


def test_case_when(s):
    rows = s.sql("select case when a > 2 then 'big' when a is null then 'nul' "
                 "else 'small' end c, b from t order by b").to_pylist()
    assert [r[0] for r in rows] == ["small", "small", "big", "big", "nul"]


def test_like_and_substr(s):
    rows = s.sql("select v from u where v like 'o%'").to_pylist()
    assert rows == [("one",)]
    rows = s.sql("select substr(v, 1, 2) from u order by v").to_pylist()
    assert rows == [("on",), ("si",), ("tw",)]


def test_concat(s):
    rows = s.sql("select g || '-' || v from t join u on t.a = u.k "
                 "order by a").to_pylist()
    assert rows == [("x-one",), ("y-two",)]


def test_cast_and_arith(s):
    rows = s.sql("select cast(b as int), a * 2 + 1, b / 4 from t "
                 "where a = 2").to_pylist()
    assert rows == [(20, 5, 5.0)]


def test_div_by_zero_is_null(s):
    rows = s.sql("select b / (a - 2) from t where a = 2").to_pylist()
    assert rows == [(None,)]


def test_date_literals_and_interval(s):
    rows = s.sql("select dk from dates where dt between '2000-01-01' and "
                 "cast('2000-01-01' as date) + interval 20 days "
                 "order by dk").to_pylist()
    assert rows == [(1,), (2,)]
    rows = s.sql("select dk from dates where dt >= date '2000-02-01'").to_pylist()
    assert rows == [(3,)]


def test_in_list(s):
    rows = s.sql("select a from t where g in ('y') order by a").to_pylist()
    assert rows == [(2,), (4,)]


def test_limit(s):
    assert len(s.sql("select a from t order by b limit 2").to_pylist()) == 2


def test_order_by_alias_and_ordinal(s):
    rows = s.sql("select g, sum(b) total from t group by g order by total desc")
    assert rows.to_pylist()[0][0] == "x"
    rows = s.sql("select g, sum(b) from t group by g order by 2")
    assert rows.to_pylist()[0][0] == "y"


def test_select_star(s):
    rows = s.sql("select * from u order by k").to_pylist()
    assert rows[0] == (1, "one")


def test_subquery_in_from(s):
    rows = s.sql("select gg, tot from (select g gg, sum(b) tot from t group by g) "
                 "sub where tot > 70").to_pylist()
    assert rows == [("x", 90.0)]


def test_self_join(s):
    rows = s.sql("select t1.a, t2.a from t t1, t t2 "
                 "where t1.a = t2.a and t1.a < 3 order by t1.a").to_pylist()
    assert rows == [(1, 1), (2, 2)]


def test_cte_reuse(s):
    rows = s.sql(
        "with c as (select g, sum(b) tot from t group by g) "
        "select c1.g from c c1, c c2 where c1.tot > c2.tot").to_pylist()
    assert rows == [("x",)]


def test_stddev(s):
    rows = s.sql("select stddev_samp(b) from t").to_pylist()
    assert abs(rows[0][0] - np.std([10, 20, 30, 40, 50], ddof=1)) < 1e-9


def test_sum_over_empty_group_is_absent(s):
    rows = s.sql("select g, sum(b) from t where a > 100 group by g").to_pylist()
    assert rows == []


def test_global_agg_on_empty_input(s):
    rows = s.sql("select count(*), sum(b) from t where a > 100").to_pylist()
    assert rows == [(0, None)]


def test_windowed_count_star_running(s):
    rows = s.sql("select a, count(*) over (order by a) c from t "
                 "where a is not null order by a").to_pylist()
    assert [r[1] for r in rows] == [1, 2, 3, 4]


def test_rank_over_window_only_aggregate(s):
    rows = s.sql("select g, rank() over (order by sum(b) desc) r "
                 "from t group by g order by r").to_pylist()
    assert rows == [("x", 1), ("y", 2)]


def test_not_in_null_semantics(s):
    s.register_arrow("nn", __import__("pyarrow").table(
        {"k": [1, None]}))
    assert s.sql("select a from t where a not in (select k from nn)"
                 ).to_pylist() == []
    assert s.sql("select a from t where a not in (1, null)").to_pylist() == []
    assert s.sql("select a from t where a in (1, null)").to_pylist() == [(1,)]
