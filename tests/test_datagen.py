"""Native generator tests: build, determinism under chunking, schema fit.

The reference had no tests for its datagen layer (SURVEY.md §4); these cover
the properties the framework depends on: (a) -parallel/-child splits change
nothing but file boundaries, (b) every table parses under the registry
schema, (c) update sets produce the 12 maintenance tables.
"""
import os
import subprocess

import pyarrow as pa
import pyarrow.csv as pa_csv
import pytest

from nds_tpu import datagen
from nds_tpu.schema import all_schemas

SF = 0.002


@pytest.fixture(scope="module")
def binary():
    return datagen.check_build()


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory, binary):
    d = tmp_path_factory.mktemp("sf_tiny")
    datagen.generate_data_local(str(d), SF, parallel=2, overwrite=True)
    return str(d)


def _read_table(path: str, table: str) -> pa.Table:
    schema = all_schemas()[table].arrow_schema(use_decimal=True)
    convert = pa_csv.ConvertOptions(
        column_types={f.name: f.type for f in schema},
        null_values=[""], strings_can_be_null=True)
    read = pa_csv.ReadOptions(column_names=[f.name for f in schema])
    parse = pa_csv.ParseOptions(delimiter="|")
    return pa_csv.read_csv(path, read_options=read, parse_options=parse,
                           convert_options=convert)


def test_all_source_tables_parse_under_schema(data_dir):
    for table in datagen.SOURCE_TABLES:
        if table == "dbgen_version":
            continue
        tdir = os.path.join(data_dir, table)
        files = sorted(os.listdir(tdir))
        assert files, table
        total = 0
        for f in files:
            t = _read_table(os.path.join(tdir, f), table)
            total += t.num_rows
        assert total > 0, table


def test_not_null_columns_have_no_nulls(data_dir):
    for table in ("store_sales", "item", "customer"):
        tdir = os.path.join(data_dir, table)
        sch = all_schemas()[table]
        for f in os.listdir(tdir):
            t = _read_table(os.path.join(tdir, f), table)
            for col in sch.columns:
                if not col.nullable:
                    assert t.column(col.name).null_count == 0, \
                        f"{table}.{col.name}"


def test_chunking_determinism(binary, tmp_path):
    """parallel=1 vs parallel=3 must produce the same multiset of rows."""
    one = tmp_path / "p1"
    three = tmp_path / "p3"
    one.mkdir(), three.mkdir()
    subprocess.run([binary, "-scale", "0.001", "-dir", str(one),
                    "-table", "store_sales"], check=True)
    for child in (1, 2, 3):
        subprocess.run([binary, "-scale", "0.001", "-dir", str(three),
                        "-parallel", "3", "-child", str(child),
                        "-table", "store_sales"], check=True)
    rows_one = sorted((one / "store_sales.dat").read_text().splitlines())
    rows_three = []
    for child in (1, 2, 3):
        rows_three += (three / f"store_sales_{child}_3.dat"
                       ).read_text().splitlines()
    assert rows_one == sorted(rows_three)
    assert len(rows_one) > 100


def test_returns_reference_sales(data_dir):
    """Every store_returns row must match a store_sales (item, ticket) line."""
    sales_dir = os.path.join(data_dir, "store_sales")
    ret_dir = os.path.join(data_dir, "store_returns")
    sold = set()
    for f in os.listdir(sales_dir):
        t = _read_table(os.path.join(sales_dir, f), "store_sales")
        for item, ticket in zip(t.column("ss_item_sk").to_pylist(),
                                t.column("ss_ticket_number").to_pylist()):
            sold.add((item, ticket))
    checked = 0
    for f in os.listdir(ret_dir):
        t = _read_table(os.path.join(ret_dir, f), "store_returns")
        for item, ticket in zip(t.column("sr_item_sk").to_pylist(),
                                t.column("sr_ticket_number").to_pylist()):
            assert (item, ticket) in sold
            checked += 1
    assert checked > 10


def test_date_dim_calendar(data_dir):
    files = os.listdir(os.path.join(data_dir, "date_dim"))
    t = pa.concat_tables(
        _read_table(os.path.join(data_dir, "date_dim", f), "date_dim")
        for f in sorted(files))
    assert t.num_rows == 73049
    import datetime
    sks = t.column("d_date_sk").to_pylist()
    dates = t.column("d_date").to_pylist()
    years = t.column("d_year").to_pylist()
    dows = t.column("d_dow").to_pylist()
    names = t.column("d_day_name").to_pylist()
    assert sks[0] == 2415022 and dates[0] == datetime.date(1900, 1, 2)
    # spot-check a known date: 2000-03-01
    idx = dates.index(datetime.date(2000, 3, 1))
    assert years[idx] == 2000
    assert names[idx] == ["Sunday", "Monday", "Tuesday", "Wednesday",
                          "Thursday", "Friday", "Saturday"][dows[idx]]
    assert dows[idx] == 3  # 2000-03-01 was a Wednesday
    assert sks == list(range(2415022, 2415022 + 73049))


def test_update_set(tmp_path, binary):
    d = tmp_path / "upd"
    datagen.generate_data_local(str(d), 0.001, parallel=1, update=1,
                                overwrite=True)
    for table in datagen.MAINTENANCE_TABLES:
        files = os.listdir(d / table)
        assert files, table
        t = _read_table(str(d / table / files[0]), table)
        assert t.num_rows > 0
    # delete-date tables: 3 DATE1<DATE2 tuples (maintenance substitution)
    t = _read_table(str(d / "delete" / "delete.dat"), "delete")
    assert t.num_rows == 3
    for d1, d2 in zip(t.column("date1").to_pylist(),
                      t.column("date2").to_pylist()):
        assert d1 < d2


def test_scaling_monotonic(binary, tmp_path):
    import math
    out = {}
    for sf in (0.001, 0.01):
        d = tmp_path / f"sf{sf}"
        d.mkdir()
        subprocess.run([binary, "-scale", str(sf), "-dir", str(d),
                        "-table", "web_sales"], check=True)
        out[sf] = len((d / "web_sales.dat").read_text().splitlines())
    ratio = out[0.01] / out[0.001]
    assert 5 < ratio < 20 and not math.isnan(ratio)
