"""Service-grade observability (ISSUE 11): histogram metrics, trace
propagation across the query service's thread hops, and the flight
recorder.

Acceptance-backed properties:
- ``Histogram.quantile`` honors its DOCUMENTED error bound (within a
  factor sqrt(BUCKET_RATIO) of the exact sample quantile) on randomized
  samples; snapshots merge associatively and diff into window views;
- a batched service ticket's span tree is parent-linked from one
  ``service/ticket`` root through queue -> plan -> lane_wait -> dispatch
  -> materialize across three OS threads;
- ``MetricsRegistry.snapshot`` is one atomic cut (multi-metric updates
  under ``locked()`` can never tear);
- the flight-recorder ring drops oldest-first at capacity and auto-dumps
  on fault firings and rejection storms.
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.config import EngineConfig
from nds_tpu.engine import Session
from nds_tpu.obs import metrics as om
from nds_tpu.obs.flight import FLIGHT, FlightRecorder
from nds_tpu.obs.trace import TRACER, span_tree
from nds_tpu.resilience import FAULTS, FaultError, FaultSpec
from nds_tpu.service import QueryService, ServiceConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BOUND = om.BUCKET_RATIO ** 0.5


@pytest.fixture(autouse=True)
def _obs_off():
    """Every test starts from a disabled tracer and flight recorder."""
    TRACER.configure(enabled=False)
    FLIGHT.configure(enabled=False, clear=True)
    yield
    TRACER.configure(enabled=False)
    FLIGHT.configure(enabled=False, clear=True)


# -- histogram: quantile error bound ------------------------------------------

@pytest.mark.parametrize("dist", ["lognormal", "uniform", "bimodal"])
def test_histogram_quantile_within_documented_bound(dist):
    rng = np.random.default_rng(hash(dist) % 2**32)
    if dist == "lognormal":
        vals = np.exp(rng.normal(2.0, 1.5, 4000))
    elif dist == "uniform":
        vals = rng.uniform(0.05, 5000.0, 4000)
    else:
        vals = np.concatenate([rng.uniform(0.5, 2.0, 2000),
                               rng.uniform(800.0, 900.0, 2000)])
    h = om.Histogram("t")
    for v in vals:
        h.observe(float(v))
    sv = sorted(float(v) for v in vals)
    for p in (0.0, 0.01, 0.25, 0.5, 0.9, 0.95, 0.99, 1.0):
        q = h.quantile(p)
        exact = om.exact_quantile(sv, p)
        assert exact / BOUND - 1e-9 <= q <= exact * BOUND + 1e-9, \
            f"{dist} p{p}: hist {q} vs exact {exact} (bound x{BOUND:.3f})"
    # exact fields are exact, not bucketed
    assert h.count == len(vals)
    assert h.quantile(0.0) == pytest.approx(min(sv))
    assert h.quantile(1.0) == pytest.approx(max(sv))
    assert h.sum == pytest.approx(sum(sv), rel=1e-9)


def test_histogram_empty_and_single_sample_edges():
    assert om.Histogram("e").quantile(0.5) is None
    assert om.Histogram("e").snapshot()["count"] == 0
    one = om.Histogram("o")
    one.observe(3.7)
    # min/max clamp: a one-sample histogram is EXACT at every p
    for p in (0.0, 0.01, 0.5, 0.99, 1.0):
        assert one.quantile(p) == 3.7
    snap = one.snapshot()
    assert snap["min"] == snap["max"] == 3.7
    # values beyond the last bucket land in overflow and stay quantilable
    big = om.Histogram("b")
    big.observe(1e9)
    assert big.quantile(0.5) == 1e9
    assert big.snapshot()["buckets"][-1][0] is None


def test_histogram_merge_associative_and_equals_union():
    def mk(seed, n):
        h = om.Histogram("m")
        rng = np.random.default_rng(seed)
        for v in rng.uniform(0.001, 50_000.0, n):
            h.observe(float(v))
        return h.snapshot()

    a, b, c = mk(1, 300), mk(2, 217), mk(3, 55)
    m1 = om.merge_snapshots(om.merge_snapshots(a, b), c)
    m2 = om.merge_snapshots(a, om.merge_snapshots(b, c))
    assert m1 == m2                         # associativity
    assert om.merge_snapshots(a, b) == om.merge_snapshots(b, a)
    # merged == histogram of the concatenated samples
    h = om.Histogram("u")
    for seed, n in ((1, 300), (2, 217), (3, 55)):
        rng = np.random.default_rng(seed)
        for v in rng.uniform(0.001, 50_000.0, n):
            h.observe(float(v))
    union = h.snapshot()
    assert m1["count"] == union["count"]
    assert m1["buckets"] == union["buckets"]
    assert m1["min"] == union["min"] and m1["max"] == union["max"]
    assert m1["sum"] == pytest.approx(union["sum"], abs=1e-3)


def test_histogram_diff_is_window_view():
    h = om.Histogram("w")
    rng = np.random.default_rng(9)
    first = rng.uniform(1.0, 100.0, 500)
    second = rng.uniform(50.0, 5000.0, 300)
    for v in first:
        h.observe(float(v))
    before = h.snapshot()
    for v in second:
        h.observe(float(v))
    win = om.diff_snapshot(h.snapshot(), before)
    only = om.Histogram("w2")
    for v in second:
        only.observe(float(v))
    assert win["count"] == 300
    assert win["buckets"] == only.snapshot()["buckets"]
    # window quantiles honor the bound against the window's exact samples
    sv = sorted(float(v) for v in second)
    for p in (0.5, 0.99):
        q = om.quantile_from_snapshot(win, p)
        exact = om.exact_quantile(sv, p)
        assert exact / BOUND <= q <= exact * BOUND * (1 + 1e-9)


def test_histogram_thread_safety_under_hammering():
    h = om.Histogram("conc")

    def work(seed):
        rng = np.random.default_rng(seed)
        for v in rng.uniform(0.1, 1000.0, 10_000):
            h.observe(float(v))

    ts = [threading.Thread(target=work, args=(i,)) for i in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    snap = h.snapshot()
    assert snap["count"] == 80_000
    assert sum(n for _le, n in snap["buckets"]) == 80_000


# -- registry: labels, namespaces, atomic snapshots ---------------------------

def test_registry_labeled_series_and_percentiles_view():
    reg = om.MetricsRegistry()
    reg.histogram("lat_ms", "family help")
    for tenant, vals in (("a", [10, 20, 30]), ("b", [500, 600, 700])):
        for v in vals:
            reg.histogram("lat_ms", tenant=tenant, template="t1").observe(v)
            reg.histogram("lat_ms").observe(v)
    hists = reg.histograms()
    assert "lat_ms" in hists
    assert "lat_ms{template=t1,tenant=a}" in hists
    assert hists["lat_ms{template=t1,tenant=a}"]["labels"] == \
        {"tenant": "a", "template": "t1"}
    # children inherit the family help; describe lists the family once
    assert reg.histogram("lat_ms", tenant="a", template="t1").help == \
        "family help"
    assert reg.describe()["lat_ms"] == "family help"
    rows = reg.percentiles("lat_ms", ps=(0.5, 0.99))
    assert rows[0]["labels"] == {}                  # all-traffic row first
    assert rows[0]["count"] == 6
    assert rows[1]["labels"].get("tenant") == "b"   # slowest labeled first
    assert rows[1]["count"] == 3
    assert rows[1]["p99"] >= rows[1]["p50"] > 100


def test_registry_series_cap_overflows_to_base():
    reg = om.MetricsRegistry()
    orig = om.HISTOGRAM_MAX_SERIES
    om.HISTOGRAM_MAX_SERIES = 4
    try:
        for i in range(10):
            reg.histogram("h", tenant=f"t{i}").observe(1.0)
    finally:
        om.HISTOGRAM_MAX_SERIES = orig
    hists = reg.histograms()
    labeled = [k for k in hists if "{" in k]
    assert len(labeled) <= 4
    # the overflow observations landed in the base series, not the void
    assert hists["h"]["count"] == 10 - len(labeled)


def test_counter_and_histogram_namespaces_coexist():
    reg = om.MetricsRegistry()
    c = reg.counter("q_wait_ms", "total")
    c.inc(5)
    reg.histogram("q_wait_ms", "distribution").observe(5.0)
    assert reg.snapshot()["q_wait_ms"] == 5          # scalar view
    assert reg.histograms()["q_wait_ms"]["count"] == 1
    with pytest.raises(TypeError):
        reg.gauge("q_wait_ms")                       # scalar clash still typed


def test_snapshot_is_atomic_cut_across_metrics():
    """The satellite fix: a snapshot can never observe metric A's update
    from a logical event without metric B's when the writer holds the
    registry value lock."""
    reg = om.MetricsRegistry()
    a, b = reg.counter("a"), reg.counter("b")
    stop = threading.Event()
    torn = []

    def writer():
        while not stop.is_set():
            with reg.locked():
                a.inc()
                b.inc()

    def reader():
        for _ in range(2000):
            snap = reg.snapshot()
            if snap["a"] != snap["b"]:
                torn.append(snap)

    w = threading.Thread(target=writer)
    r = threading.Thread(target=reader)
    w.start()
    r.start()
    r.join()
    stop.set()
    w.join()
    assert torn == [], f"torn snapshots: {torn[:3]}"


def test_export_prometheus_structure():
    reg = om.MetricsRegistry()
    reg.counter("runs", "run counter").inc(3)
    reg.gauge("depth").set(7)
    h = reg.histogram("lat_ms", "latency", tenant="x")
    for v in (1.0, 2.0, 400.0):
        h.observe(v)
    text = reg.export_prometheus()
    assert "# TYPE runs_total counter" in text
    assert "runs_total 3" in text
    assert "depth 7" in text
    assert "# TYPE lat_ms histogram" in text
    assert 'lat_ms_count{tenant="x"} 3' in text
    assert 'lat_ms_sum{tenant="x"} 403.0' in text
    # bucket counts are CUMULATIVE and end at +Inf == count
    lines = [ln for ln in text.splitlines() if ln.startswith("lat_ms_bucket")]
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in lines]
    assert counts == sorted(counts)
    assert lines[-1].endswith(" 3") and 'le="+Inf"' in lines[-1]


def test_exact_quantile_nearest_rank():
    vals = sorted(float(i) for i in range(1, 101))
    assert om.exact_quantile(vals, 0.0) == 1.0
    assert om.exact_quantile(vals, 1.0) == 100.0
    assert om.exact_quantile(vals, 0.5) == 51.0   # round(0.5*99)=50 -> idx 50
    assert om.exact_quantile([], 0.5) == 0.0


# -- service integration: spans, histograms, stats ----------------------------

N_FACT, N_DIM = 20_000, 50
TPL = ("SELECT grp, COUNT(*) AS n, SUM(qty) AS tq FROM fact "
       "JOIN dim ON fk = dk WHERE qty BETWEEN {a} AND {b} "
       "GROUP BY grp ORDER BY grp")
#: no hoistable literals -> no shared fingerprint -> the serial lane
SERIAL_SQL = "SELECT grp, COUNT(*) AS n FROM dim GROUP BY grp ORDER BY grp"


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    fact = pa.table({
        "fk": pa.array(rng.integers(0, N_DIM, N_FACT), type=pa.int64()),
        "qty": pa.array(rng.integers(1, 100, N_FACT), type=pa.int64())})
    dim = pa.table({"dk": pa.array(np.arange(N_DIM), type=pa.int64()),
                    "grp": pa.array((np.arange(N_DIM) % 7)
                                    .astype(np.int64))})
    return {"fact": fact, "dim": dim}


def make_session(data):
    s = Session(EngineConfig())
    s.register_arrow("fact", data["fact"])
    s.register_arrow("dim", data["dim"])
    return s


def hold_batch(svc, texts, timeout=30.0):
    """Submit texts under a held lane; return tickets once all are ready."""
    with svc.hold_dispatch():
        tickets = [svc.submit(sql, label=f"t{i}", tenant="dash")
                   for i, sql in enumerate(texts)]
        t0 = time.time()
        while time.time() - t0 < timeout:
            with svc._cv:
                if len(svc._ready) >= len(tickets):
                    break
            time.sleep(0.005)
    return tickets


def test_service_span_tree_parent_linked_across_thread_hops(data):
    TRACER.configure(enabled=True)
    session = make_session(data)
    with QueryService(session, ServiceConfig(max_batch=8)) as svc:
        svc.sql(TPL.format(a=5, b=60), label="warm")
        svc.sql(TPL.format(a=5, b=60), label="warm")
        tickets = hold_batch(
            svc, [TPL.format(a=5 + i, b=60 + i) for i in range(4)])
        for t in tickets:
            t.result(timeout=120)
    assert TRACER.open_spans() == [], "unclosed spans"
    events = TRACER.events()
    tree = span_tree(events)            # raises on dangling parents
    by_sid = {e["sid"]: e for e in events}
    for t in tickets:
        assert t.trace_id > 0
        root = by_sid[t.trace_id]
        assert root["name"] == "service/ticket"
        assert root["args"]["tenant"] == "dash"
        assert root["args"]["latency_ms"] > 0
        kids = [by_sid[sid] for sid in tree.get(t.trace_id, [])]
        names = {k["name"] for k in kids}
        assert {"service/queue", "service/plan", "service/lane_wait",
                "service/dispatch", "service/materialize"} <= names, names
        # the three thread hops: client (root+queue), planner worker
        # (plan), device lane (dispatch) are distinct OS threads
        tids = {root["tid"]} | {k["tid"] for k in kids}
        assert len(tids) >= 3, f"expected >=3 threads, saw {tids}"
        dispatch = next(k for k in kids if k["name"] == "service/dispatch")
        assert dispatch["args"]["batched_with"] == 3
        assert dispatch["args"]["batch_rows"] == 4      # no duplicates
        assert dispatch["args"]["dedup"] == 0
        # ExecStats joins the stats record to this subtree
        assert t.stats.trace_id == t.trace_id
        assert t.stats.to_dict()["trace_id"] == t.trace_id


def test_service_serial_lane_nests_session_spans_under_ticket(data):
    TRACER.configure(enabled=True)
    session = make_session(data)
    with QueryService(session) as svc:
        ticket = svc.submit(SERIAL_SQL, label="serial", tenant="ten")
        ticket.result(timeout=120)
    events = TRACER.events()
    by_sid = {e["sid"]: e for e in events}
    query = next(e for e in events if e["name"] == "query"
                 and e.get("args", {}).get("label") == "serial")
    chain = []
    cur = query
    while cur.get("parent"):
        cur = by_sid[cur["parent"]]
        chain.append(cur["name"])
    assert chain[0] == "service/dispatch"
    assert chain[-1] == "service/ticket"
    assert ticket.stats.mode != "batched"


def test_service_records_histograms_per_tenant_and_template(data):
    session = make_session(data)
    before = {k: v["count"]
              for k, v in om.METRICS.histograms().items()}
    with QueryService(session, ServiceConfig(max_batch=8)) as svc:
        svc.sql(TPL.format(a=5, b=60), label="warm", tenant="t_a")
        svc.sql(TPL.format(a=5, b=60), label="warm", tenant="t_a")
        tickets = hold_batch(
            svc, [TPL.format(a=5 + i, b=60 + i) for i in range(3)])
        for t in tickets:
            t.result(timeout=120)
    hists = om.METRICS.histograms()

    def grew(name, labels=None):
        for key, snap in hists.items():
            if snap["name"] != name:
                continue
            if labels is not None and snap.get("labels") != labels:
                continue
            if snap["count"] > before.get(key, 0):
                return True
        return False

    template = tickets[0].template
    assert template and template == tickets[0].fp[:12]
    for fam in ("service_latency_ms", "service_queue_wait_ms",
                "service_plan_ms", "service_exec_ms",
                "service_materialize_ms"):
        assert grew(fam), f"{fam} base series did not move"
    assert grew("service_latency_ms",
                {"tenant": "dash", "template": template})
    # the live SLO view ranks the tenant rows
    rows = om.METRICS.percentiles("service_latency_ms")
    assert any(r["labels"].get("tenant") == "dash" for r in rows)


def test_tracing_disabled_service_records_no_spans(data):
    session = make_session(data)
    with QueryService(session) as svc:
        t = svc.submit(SERIAL_SQL, label="dark")
        t.result(timeout=120)
    assert TRACER.events() == []
    assert t.trace_id == 0
    assert t.stats.trace_id is None
    assert "trace_id" not in t.stats.to_dict()


def test_detached_span_cross_thread_begin_end():
    TRACER.configure(enabled=True)
    root = TRACER.span("root.detached", label="x").begin()
    out = {}

    def child():
        with TRACER.span("child", parent=root.sid):
            out["tid"] = threading.get_ident()

    th = threading.Thread(target=child)
    th.start()
    th.join()
    root.end()
    events = TRACER.events()
    child_e = next(e for e in events if e["name"] == "child")
    root_e = next(e for e in events if e["name"] == "root.detached")
    assert child_e["parent"] == root_e["sid"]
    assert child_e["tid"] == out["tid"] != root_e["tid"]
    span_tree(events)


# -- flight recorder ----------------------------------------------------------

def test_flight_ring_overflow_keeps_most_recent():
    fr = FlightRecorder(capacity=100)
    fr.configure(enabled=True, clear=True)
    for i in range(250):
        fr.record("admit", i=i)
    events = fr.events()
    assert len(events) == 100
    assert [e["i"] for e in events] == list(range(150, 250))
    assert events[0]["seq"] == 151 and events[-1]["seq"] == 250
    # monotonic timestamps
    ts = [e["t_ms"] for e in events]
    assert ts == sorted(ts)


def test_flight_disabled_records_nothing_and_is_cheap():
    fr = FlightRecorder()
    t0 = time.perf_counter()
    for _ in range(200_000):
        fr.record("admit", label="x")
    assert time.perf_counter() - t0 < 2.0
    assert fr.events() == []


def test_flight_fault_point_triggers_dump(tmp_path):
    FLIGHT.configure(enabled=True, dump_dir=str(tmp_path), clear=True)
    FLIGHT.record("admit", label="q1", tenant="a")
    spec = FAULTS.arm(FaultSpec(point="query.run", match="flight_q",
                                times=1))
    try:
        with pytest.raises(FaultError):
            FAULTS.fire("query.run", "flight_q")
    finally:
        FAULTS.disarm(spec)
    assert len(FLIGHT.dumps) == 1
    lines = [json.loads(ln) for ln in open(FLIGHT.dumps[0])]
    kinds = [e["event"] for e in lines]
    assert kinds == ["admit", "fault", "trip"]
    fault = lines[1]
    assert fault["point"] == "query.run"
    assert fault["detail"] == "flight_q"
    assert lines[2]["reason"] == "fault"
    # a second firing inside the cooldown records but does not re-dump
    spec = FAULTS.arm(FaultSpec(point="query.run", match="flight_q",
                                times=1))
    try:
        with pytest.raises(FaultError):
            FAULTS.fire("query.run", "flight_q")
    finally:
        FAULTS.disarm(spec)
    assert len(FLIGHT.dumps) == 1


def test_flight_reject_storm_triggers_dump(tmp_path, data):
    FLIGHT.configure(enabled=True, dump_dir=str(tmp_path),
                     reject_storm=5, reject_window_s=30.0, clear=True)
    session = make_session(data)
    svc = QueryService(session, ServiceConfig(max_pending=1)).start()
    try:
        with svc.hold_dispatch():
            svc.submit(SERIAL_SQL, label="occupier")
            from nds_tpu.resilience import AdmissionRejected
            for i in range(6):
                with pytest.raises(AdmissionRejected):
                    svc.submit(SERIAL_SQL, label=f"r{i}", tenant="storm")
    finally:
        svc.close()
    assert len(FLIGHT.dumps) == 1
    lines = [json.loads(ln) for ln in open(FLIGHT.dumps[0])]
    rejects = [e for e in lines if e["event"] == "reject"]
    assert len(rejects) >= 5
    assert rejects[0]["reason"] == "queue_full"
    assert rejects[0]["limit"] == 1
    trip = next(e for e in lines if e["event"] == "trip")
    assert trip["reason"] == "reject_storm"


def test_service_lifecycle_lands_in_flight_ring(data):
    FLIGHT.configure(enabled=True, clear=True)
    session = make_session(data)
    with QueryService(session, ServiceConfig(max_batch=8)) as svc:
        svc.sql(TPL.format(a=5, b=60), label="warm")
        svc.sql(TPL.format(a=5, b=60), label="warm")
        tickets = hold_batch(
            svc, [TPL.format(a=5 + i, b=60 + i) for i in range(3)])
        for t in tickets:
            t.result(timeout=120)
    kinds = [e["event"] for e in FLIGHT.events()]
    for k in ("admit", "plan", "batch", "complete"):
        assert k in kinds, f"missing {k} in {set(kinds)}"
    batch = next(e for e in FLIGHT.events() if e["event"] == "batch")
    assert batch["queries"] == 3 and batch["dedup"] == 0
    done = [e for e in FLIGHT.events() if e["event"] == "complete"]
    assert all(e["latency_ms"] > 0 for e in done)
    assert any(e.get("batched_with") == 2 for e in done)


# -- CLI summarizers ----------------------------------------------------------

def test_trace_report_on_flight_jsonl_and_service_trace(tmp_path, data):
    FLIGHT.configure(enabled=True, clear=True)
    TRACER.configure(enabled=True)
    session = make_session(data)
    with QueryService(session) as svc:
        svc.sql(SERIAL_SQL, label="cli_q", tenant="cli")
    fpath = FLIGHT.dump_jsonl(str(tmp_path / "flight.jsonl"))
    tpath = TRACER.write_chrome_trace(str(tmp_path / "trace.json"))
    script = os.path.join(REPO, "scripts", "trace_report.py")
    out = subprocess.run([sys.executable, script, fpath],
                         capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert "flight recorder" in out.stdout
    assert "cli" in out.stdout and "complete" in out.stdout
    out = subprocess.run([sys.executable, script, tpath],
                         capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert "service/ticket" in out.stdout
    assert "service tickets by tenant" in out.stdout
    assert "slowest" in out.stdout


def test_obs_report_on_histogram_artifact_and_flight(tmp_path):
    reg = om.MetricsRegistry()
    for tenant, base in (("a", 10.0), ("b", 900.0)):
        for i in range(20):
            reg.histogram("service_latency_ms", "lat", tenant=tenant,
                          template="tpl1").observe(base + i)
            reg.histogram("service_latency_ms").observe(base + i)
    artifact = tmp_path / "metrics.json"
    artifact.write_text(json.dumps(reg.export_json()))
    script = os.path.join(REPO, "scripts", "obs_report.py")
    out = subprocess.run([sys.executable, script, str(artifact)],
                         capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert "service_latency_ms" in out.stdout
    assert "tenant=b" in out.stdout          # slowest labeled row present
    out = subprocess.run([sys.executable, script, str(artifact),
                          "--prometheus"], capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert 'service_latency_ms_bucket{template="tpl1",tenant="a",le=' \
        in out.stdout
    fr = FlightRecorder()
    fr.configure(enabled=True, clear=True)
    fr.record("complete", label="x", tenant="t", latency_ms=12.0)
    fpath = fr.dump_jsonl(str(tmp_path / "fl.jsonl"))
    out = subprocess.run([sys.executable, script, fpath],
                         capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert "flight recorder" in out.stdout


# -- metrics gate -------------------------------------------------------------

def test_metrics_gate_compare_logic():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import metrics_gate as mg

    base = {"compiles": 4, "queries_run": 10, "morsels": 16}
    assert mg.compare(base, {"compiles": 4, "queries_run": 10,
                             "morsels": 16}) == []
    # generous bands: small absolute drift and <=2x ratio pass
    assert mg.compare(base, {"compiles": 6, "queries_run": 18,
                             "morsels": 30}) == []
    v = mg.compare(base, {"compiles": 40, "queries_run": 10,
                          "morsels": 16})
    assert len(v) == 1 and "compiles" in v[0]
    v = mg.compare(base, {"queries_run": 10, "morsels": 16})
    assert len(v) == 1 and "MISSING" in v[0]
    # strict-zero metrics fail on ANY movement
    v = mg.compare(base, {"compiles": 4, "queries_run": 10, "morsels": 16,
                          "replay_mismatches": 1})
    assert len(v) == 1 and "STRICT-ZERO" in v[0]
    gated, report = mg.gated_view({"compiles": 3, "host_decode_ms": 9.1,
                                   "bytes_uploaded": 100})
    assert "compiles" in gated
    assert "host_decode_ms" in report and "bytes_uploaded" in report


@pytest.mark.slow
def test_metrics_gate_end_to_end_passes_on_tree():
    script = os.path.join(REPO, "scripts", "metrics_gate.py")
    out = subprocess.run(
        [sys.executable, script], capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), timeout=600)
    assert out.returncode == 0, out.stderr
    assert "metrics_gate: OK" in out.stderr
