"""Shared-scan morsel fusion (streaming.plan_scan_groups / fuse_group +
session._stream_group): all streaming branches of one query that scan the
same big table share ONE morsel pass — the union of their pruned column
sets uploads once per morsel, each branch reads zero-copy views of the
staged buffer, and groups within the fusion budget run as a single
multi-output program per morsel.

Exactness is pinned three ways: against an independent SQLite oracle over
the same rows, against the engine's numpy oracle, and BIT-IDENTICAL across
the three streaming modes (shared+fused / shared-unfused / per-branch —
the --no_shared_scan A/B contract). The scan-pass economics are pinned by
last_exec_stats: q9-class queries stream each big table exactly once per
execution."""
import math
import os
import sqlite3

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from nds_tpu.config import EngineConfig
from nds_tpu.engine import Session
from nds_tpu.engine import streaming

N_FACT, N_DIM = 50_000, 300
CHUNK = 4_096
PER_PASS = -(-N_FACT // CHUNK)          # morsels in one full fact pass


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("shared_scan")
    rng = np.random.default_rng(11)
    qty = rng.integers(1, 50, N_FACT).astype(object)
    qty[rng.random(N_FACT) < 0.05] = None      # NULLs exercise sum_guarded
    fact = pa.table({
        "fk": pa.array(rng.integers(0, N_DIM + 9, N_FACT), type=pa.int32()),
        "qty": pa.array(list(qty), type=pa.int32()),
        "price": pa.array(np.round(rng.uniform(1, 100, N_FACT), 2)),
        "day": pa.array(rng.integers(0, 365, N_FACT), type=pa.int32()),
    })
    path = os.path.join(str(tmp), "fact.parquet")
    pq.write_table(fact, path, row_group_size=8192)
    dim = pa.table({"dk": pa.array(np.arange(N_DIM), type=pa.int32()),
                    "grp": pa.array((np.arange(N_DIM) % 13)
                                    .astype(np.int32))})
    return {"fact_path": path, "fact": fact, "dim": dim}


def make_session(data, shared_scan=True, fuse_max=16, chunk=CHUNK):
    cfg = EngineConfig(out_of_core=True, chunk_rows=chunk,
                       out_of_core_min_rows=10_000,
                       shared_scan=shared_scan,
                       stream_fusion_max_branches=fuse_max)
    s = Session(cfg)
    s.register_parquet("fact", data["fact_path"])
    s.register_arrow("dim", data["dim"])
    return s


def sqlite_conn(data):
    conn = sqlite3.connect(":memory:")
    for name, t in (("fact", data["fact"]), ("dim", data["dim"])):
        cols = ", ".join(f'"{c}"' for c in t.column_names)
        conn.execute(f"CREATE TABLE {name} ({cols})")
        rows = list(zip(*[t.column(c).to_pylist() for c in t.column_names]))
        conn.executemany(
            f"INSERT INTO {name} VALUES "
            f"({','.join('?' * len(t.column_names))})", rows)
    conn.commit()
    return conn


def rows_of(t):
    return [tuple(r) for r in t.to_pylist()]


def rows_close(got, want, rel=1e-5):
    """Row-wise equality with float tolerance (the device accumulates
    f32 without x64; sum order also differs from the oracles')."""
    if len(got) != len(want):
        return False
    for g, w in zip(got, want):
        if len(g) != len(w):
            return False
        for a, b in zip(g, w):
            if isinstance(a, float) or isinstance(b, float):
                if (a is None) != (b is None):
                    return False
                if a is not None and not math.isclose(
                        float(a), float(b), rel_tol=rel, abs_tol=1e-8):
                    return False
            elif a != b:
                return False
    return True


# q9-class: a battery of scalar-subquery aggregates over the big table,
# each pruning a DIFFERENT column subset (the union exercises fuse_group)
Q9 = """
SELECT d.grp,
       CASE WHEN (SELECT COUNT(*) FROM fact WHERE day < 100) > 10
            THEN (SELECT AVG(price) FROM fact WHERE day < 100)
            ELSE (SELECT AVG(qty) FROM fact WHERE day >= 100) END AS v,
       (SELECT SUM(qty) FROM fact WHERE day >= 200) AS s
FROM dim d WHERE d.dk < 3
"""
Q9_JOBS = 4

# q2/q5-class: two aggregate jobs, each a UNION ALL over the same two fact
# channels — per (job, channel) branches collapse to one pass per channel
UNION2 = """
SELECT a.grp, a.total, b.total
FROM (SELECT d.grp AS grp, SUM(u.amt) AS total
      FROM (SELECT fk, amt FROM ch_a UNION ALL SELECT fk, amt FROM ch_b) u
      JOIN dim d ON u.fk = d.dk WHERE u.amt < 400 GROUP BY d.grp) a
JOIN (SELECT d.grp AS grp, SUM(u.amt) AS total
      FROM (SELECT fk, amt FROM ch_a UNION ALL SELECT fk, amt FROM ch_b) u
      JOIN dim d ON u.fk = d.dk WHERE u.amt >= 400 GROUP BY d.grp) b
ON a.grp = b.grp ORDER BY a.grp
"""

# q10-class: a semi-join build side AND a scalar subquery over one table
SEMI = """
SELECT d.grp, COUNT(*) AS cnt FROM dim d
WHERE EXISTS (SELECT 1 FROM fact f WHERE f.fk = d.dk AND f.day < 50)
  AND d.dk < (SELECT AVG(fk) FROM fact) + 100
GROUP BY d.grp ORDER BY d.grp
"""


def run_modes(data, q):
    """The three streaming modes; returns (rows per mode, stats per mode)."""
    out, stats = [], []
    for shared, fuse_max in ((True, 16), (True, 1), (False, 16)):
        s = make_session(data, shared_scan=shared, fuse_max=fuse_max)
        got = rows_of(s.sql(q, backend="jax"))
        assert s.last_exec_stats["mode"] == "streaming"
        out.append(got)
        stats.append(dict(s.last_exec_stats))
    return out, stats


def test_q9_single_pass_pinned(data):
    """Acceptance: a q9-class query streams the big table EXACTLY once —
    one scan pass, one full-pass morsel count — while serving every job."""
    s = make_session(data)
    s.sql(Q9, backend="jax")
    st = s.last_exec_stats
    assert st["mode"] == "streaming"
    assert st["jobs"] == Q9_JOBS
    assert st["scan_passes"] == 1
    assert st["tables_streamed"] == 1
    assert st["branches_served"] == Q9_JOBS
    assert st["morsels"] == PER_PASS                 # not jobs * PER_PASS
    assert st["morsels_per_table"] == {"fact": PER_PASS}
    assert st["fused_groups"] == 1
    assert st["bytes_uploaded"] > 0
    assert st["re_records"] == 0


def test_q9_differential_sqlite_and_modes(data):
    """Fused, shared-unfused, and per-branch must be BIT-IDENTICAL to each
    other and match the SQLite + numpy oracles within float tolerance."""
    (fused, unfused, perbranch), (st_f, st_u, st_p) = run_modes(data, Q9)
    assert fused == unfused == perbranch
    assert st_f["scan_passes"] == 1 and st_u["scan_passes"] == 1
    assert st_u["fused_groups"] == 0                 # budget=1 opted out
    assert st_p["scan_passes"] == Q9_JOBS            # old per-branch passes
    assert st_p["morsels"] == Q9_JOBS * PER_PASS
    want = sqlite_conn(data).execute(Q9).fetchall()
    assert rows_close(fused, want), (fused[:3], want[:3])
    s = make_session(data)
    oracle = rows_of(s.sql(Q9, backend="numpy"))
    assert rows_close(fused, oracle)


def test_union_channels_share_per_table_pass(data):
    """Two union-channel jobs over the same two fact tables: shared scan
    collapses 4 streamed branches into one pass per channel table."""
    rng = np.random.default_rng(9)
    tmp = os.path.dirname(data["fact_path"])
    chans = {}
    for name, n in (("ch_a", 30_000), ("ch_b", 25_000)):
        t = pa.table({
            "fk": pa.array(rng.integers(0, N_DIM, n), type=pa.int32()),
            "amt": pa.array(rng.integers(1, 500, n), type=pa.int64()),
        })
        path = os.path.join(tmp, f"{name}.parquet")
        pq.write_table(t, path, row_group_size=8192)
        chans[name] = (t, path)
    results, stats = [], []
    for shared in (True, False):
        s = make_session(data, shared_scan=shared)
        for name, (_t, path) in chans.items():
            s.register_parquet(name, path)
        results.append(rows_of(s.sql(UNION2, backend="jax")))
        stats.append(dict(s.last_exec_stats))
    st_shared, st_per = stats
    assert results[0] == results[1]
    assert st_shared["mode"] == st_per["mode"] == "streaming"
    assert st_shared["jobs"] == 2
    assert st_shared["branches_served"] == 4         # 2 jobs x 2 channels
    assert st_shared["scan_passes"] == 2             # one per channel table
    assert st_shared["tables_streamed"] == 2
    per_pass = -(-30_000 // CHUNK) + -(-25_000 // CHUNK)
    assert st_shared["morsels"] == per_pass
    assert st_per["scan_passes"] == 4
    assert st_per["morsels"] == 2 * per_pass
    # independent oracle
    conn = sqlite3.connect(":memory:")
    for name, t in (("dim", data["dim"]), ("ch_a", chans["ch_a"][0]),
                    ("ch_b", chans["ch_b"][0])):
        cols = ", ".join(f'"{c}"' for c in t.column_names)
        conn.execute(f"CREATE TABLE {name} ({cols})")
        conn.executemany(
            f"INSERT INTO {name} VALUES "
            f"({','.join('?' * len(t.column_names))})",
            list(zip(*[t.column(c).to_pylist() for c in t.column_names])))
    want = conn.execute(UNION2).fetchall()
    assert rows_close(results[0], want)


def test_semi_join_build_side_shares_pass(data):
    """q10-class: the semi-join distinct-key job and a scalar-subquery job
    both scan the big table — one shared pass serves both."""
    (fused, unfused, perbranch), (st_f, _su, st_p) = run_modes(data, SEMI)
    assert fused == unfused == perbranch
    assert st_f["jobs"] == 2
    assert st_f["scan_passes"] == 1
    assert st_f["branches_served"] == 2
    assert st_f["morsels"] == PER_PASS
    assert st_p["morsels"] == 2 * PER_PASS
    want = sqlite_conn(data).execute(SEMI).fetchall()
    assert rows_close(fused, want)


def test_fuse_group_unions_columns(data):
    """Plan-level: one group per big table, union column set, and each
    member plan reading its subset through the shared morsel scan."""
    import nds_tpu.engine.plan as P
    from nds_tpu.engine.planner import Planner
    from nds_tpu.sql import parse_sql

    s = make_session(data)
    plan = Planner(s._catalog()).plan_query(parse_sql(Q9))
    jobs = streaming.find_streaming_jobs(
        plan, lambda t: s._est_rows.get(t, 0),
        s.config.out_of_core_min_rows)
    assert len(jobs) == Q9_JOBS
    groups = streaming.plan_scan_groups(jobs, shared=True)
    assert len(groups) == 1
    g = groups[0]
    assert g.table == "fact"
    want_union = {c for j in jobs for b in j.branches
                  for c in b.big_columns}
    assert set(g.columns) == want_union
    assert {"day", "price", "qty"} <= set(g.columns)
    assert g.morsel_key == \
        streaming.MORSEL_TABLE + "//" + ",".join(g.columns)
    assert len(g.plans) == Q9_JOBS
    for member_plan in g.plans:
        scans = [n for n in P.iter_plan_nodes(member_plan)
                 if isinstance(n, P.ScanNode)
                 and n.table == streaming.MORSEL_TABLE]
        assert len(scans) == 1
        assert list(scans[0].columns) == list(g.columns)
    # per-branch grouping (shared=False) keeps each branch's own columns
    per = streaming.plan_scan_groups(jobs, shared=False)
    assert len(per) == Q9_JOBS
    assert all(len(p.members) == 1 for p in per)


def test_upload_volume_shared_below_per_branch(data):
    """The union upload must cost less than the per-branch uploads it
    replaces (the whole point of the shared scan)."""
    s = make_session(data, shared_scan=True)
    s.sql(Q9, backend="jax")
    shared_bytes = s.last_exec_stats["bytes_uploaded"]
    s2 = make_session(data, shared_scan=False)
    s2.sql(Q9, backend="jax")
    per_branch_bytes = s2.last_exec_stats["bytes_uploaded"]
    assert 0 < shared_bytes < per_branch_bytes


def test_live_config_toggle_invalidates_stream_cache(data):
    """Satellite: _stream_cache keys on a config fingerprint — toggling
    shared_scan / chunk_rows / late_materialization on a LIVE session must
    not replay stale groups, programs, or not-streamable sentinels."""
    s = make_session(data)
    a = rows_of(s.sql(Q9, backend="jax"))
    assert s.last_exec_stats["scan_passes"] == 1
    s.config.shared_scan = False
    b = rows_of(s.sql(Q9, backend="jax"))
    assert s.last_exec_stats["scan_passes"] == Q9_JOBS
    assert a == b
    s.config.shared_scan = True
    s.config.chunk_rows = CHUNK * 2
    c = rows_of(s.sql(Q9, backend="jax"))
    assert s.last_exec_stats["morsels"] == -(-N_FACT // (CHUNK * 2))
    assert a == c
    # a threshold flip must drop the "streams" entry (and vice versa): the
    # sentinel for this query may not survive the config change
    s.config.out_of_core_min_rows = N_FACT * 10
    s.sql(Q9, backend="jax")
    assert s.last_exec_stats.get("mode") != "streaming"
    s.config.out_of_core_min_rows = 10_000
    s.sql(Q9, backend="jax")
    assert s.last_exec_stats["mode"] == "streaming"


def test_iter_morsels_single_slice_zero_copy(data, monkeypatch):
    """Satellite: a morsel assembled from ONE pending slice must pass
    through without pa.concat_tables (the aligned-batch common case)."""
    calls = {"n": 0}
    real = pa.concat_tables

    def counting(tables, *a, **k):
        calls["n"] += 1
        return real(tables, *a, **k)

    s = make_session(data)
    monkeypatch.setattr(pa, "concat_tables", counting)
    # parquet row groups are 8192 = 2 * CHUNK: every morsel is one slice
    morsels = list(s.iter_morsels("fact", ["fk", "day"], CHUNK))
    assert calls["n"] == 0
    assert sum(m.num_rows for m in morsels) == N_FACT
    assert max(m.num_rows for m in morsels) <= CHUNK
    # misaligned chunking still re-chunks correctly (concat engaged)
    morsels = list(s.iter_morsels("fact", ["fk"], 5_000))
    assert calls["n"] > 0
    assert sum(m.num_rows for m in morsels) == N_FACT
