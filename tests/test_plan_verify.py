"""Static plan-IR verification (engine/verify.py + planner.PassPipeline).

Three pillars, matching the reason the verifier exists (two of the last
three rounds shipped fixes for bugs rewrite passes introduced silently):

1. the full template sweep: every bundled query template plans under
   ``verify_plans="per-pass"`` — every rewrite pass output checked, shared
   nodes freeze-checked, parameter hoisting round-tripped — with ZERO
   findings, in both decimal modes;
2. mutation tests: seeded plan corruptions (dangling column index, dtype
   mismatch, in-place mutation of a node) are caught, naming the RIGHT
   node and the RIGHT pass;
3. the compiled-query argument contract: ArgSpecMismatch reports
   expected-vs-got dtype/shape PER ARGUMENT instead of a bare mismatch.
"""
import numpy as np
import pyarrow as pa
import pytest

from nds_tpu import streams
from nds_tpu.config import EngineConfig
from nds_tpu.engine import plan as P
from nds_tpu.engine.arrow_bridge import engine_schema
from nds_tpu.engine.planner import (Catalog, PassPipeline, PlanError,
                                    Planner)
from nds_tpu.engine.verify import (PlanVerifyError, check_frozen,
                                   node_labels, plan_fingerprint, snapshot,
                                   verify_plan)
from nds_tpu.power import strip_sql_comments
from nds_tpu.schema import UNIQUE_KEYS, get_schemas
from nds_tpu.sql import parse_sql

# SF100-ish row counts so size-gated rewrites (late materialization) fire
# during the sweep — the passes must be EXERCISED to be verified
_FACT_ROWS = {
    "store_sales": 288_000_000, "store_returns": 28_800_000,
    "catalog_sales": 144_000_000, "catalog_returns": 14_400_000,
    "web_sales": 72_000_000, "web_returns": 7_200_000,
    "inventory": 399_330_000, "customer": 2_000_000,
    "customer_demographics": 1_920_800, "item": 204_000,
}


def _catalog(dec_enabled: bool, verify: str = "per-pass") -> Catalog:
    tables = {}
    for name, sch in get_schemas(use_decimal=True).items():
        names, dtypes = engine_schema(sch.arrow_schema(use_decimal=True),
                                      dec_enabled)
        tables[name] = (names, dtypes, _FACT_ROWS.get(name, 10_000))
    uniq = {t: tuple(c for c in cols if c in tables[t][0])
            for t, cols in UNIQUE_KEYS.items() if t in tables}
    return Catalog(tables, dec_enabled=dec_enabled, unique_cols=uniq,
                   verify_plans=verify)


@pytest.fixture(scope="module")
def catalogs():
    return {dec: _catalog(dec) for dec in (False, True)}


def _statements(number: int):
    sql = streams.instantiate(number, stream=0, rngseed=31415)
    parts = (streams.split_special_query(f"query{number}", sql)
             if number in streams.SPECIAL_TEMPLATES
             else [(f"query{number}", sql)])
    for name, part_sql in parts:
        for stmt in strip_sql_comments(part_sql).split(";"):
            if stmt.strip():
                yield name, stmt


# -- 1. the sweep: every template, per-pass verification, zero findings ----

@pytest.mark.parametrize("number", streams.available_templates())
def test_template_sweep_per_pass(catalogs, number):
    for dec in (False, True):
        for name, stmt in _statements(number):
            # PassPipeline raises PlanVerifyError on any finding
            Planner(catalogs[dec]).plan_query(parse_sql(stmt))


def test_verification_is_pure():
    """per-pass verification must not alter the produced plan."""
    sql = ("SELECT ss_store_sk, SUM(ss_quantity) q FROM store_sales "
           "WHERE ss_quantity > 5 GROUP BY ss_store_sk ORDER BY q LIMIT 7")
    verified = Planner(_catalog(False)).plan_query(parse_sql(sql))
    plain = Planner(_catalog(False, verify="off")).plan_query(parse_sql(sql))
    assert plan_fingerprint(verified) == plan_fingerprint(plain)


def test_unknown_mode_rejected():
    with pytest.raises(PlanError, match="verify_plans"):
        PassPipeline("sometimes")


# -- 2. mutation tests: corruption caught with node + pass attribution ----

def _simple_plan(verify="off"):
    cat = _catalog(False, verify=verify)
    plan = Planner(cat).plan_query(parse_sql(
        "SELECT ss_store_sk, SUM(ss_quantity) q FROM store_sales "
        "WHERE ss_quantity > 5 GROUP BY ss_store_sk"))
    return cat, plan


def test_dangling_col_index_names_the_node():
    cat, plan = _simple_plan()
    labels = node_labels(plan)
    proj = next(n for n in P.iter_plan_nodes(plan)
                if isinstance(n, P.ProjectNode))
    old = proj.exprs[0]
    proj.exprs[0] = P.BCol(old.dtype, 999, old.name)
    findings = verify_plan(plan, cat)
    assert findings, "dangling index not caught"
    assert any(f.kind == "colref" and f.label == labels[id(proj)]
               and "999" in f.message for f in findings), \
        [str(f) for f in findings]


def test_dtype_mismatch_names_the_node():
    cat, plan = _simple_plan()
    labels = node_labels(plan)
    proj = next(n for n in P.iter_plan_nodes(plan)
                if isinstance(n, P.ProjectNode))
    old = proj.exprs[0]
    proj.exprs[0] = P.BCol("str", old.index, old.name)
    findings = verify_plan(plan, cat)
    assert any(f.kind == "dtype" and f.label == labels[id(proj)]
               for f in findings), [str(f) for f in findings]


def test_join_key_dtype_mismatch_caught():
    cat = _catalog(False, verify="off")
    plan = Planner(cat).plan_query(parse_sql(
        "SELECT s_store_name, COUNT(*) FROM store_sales, store "
        "WHERE ss_store_sk = s_store_sk GROUP BY s_store_name"))
    join = next(n for n in P.iter_plan_nodes(plan)
                if isinstance(n, P.JoinNode))
    k = join.right_keys[0]
    join.right_keys[0] = P.BCall("float", "cast", [k])
    findings = verify_plan(plan, cat)
    assert any(f.kind == "joinkey" and "int" in f.message
               and "float" in f.message for f in findings), \
        [str(f) for f in findings]


def test_shared_node_mutation_names_node_and_pass():
    """An in-place widening (the `_exact_rational_keys` hazard class) is
    caught by the freeze check and attributed to the mutating pass."""
    cat, plan = _simple_plan()
    pipe = PassPipeline("per-pass", cat)
    scan = next(n for n in P.iter_plan_nodes(plan)
                if isinstance(n, P.ScanNode))
    label = node_labels(plan)[id(scan)]

    def benign(p):
        return p

    def evil(p):
        scan.columns.append("ss_item_sk")
        scan.out_names.append("ss_item_sk")
        scan.out_dtypes.append("int")
        return p

    plan = pipe.run("benign_pass", benign, plan)
    with pytest.raises(PlanVerifyError) as exc:
        pipe.run("evil_widen", evil, plan)
    assert exc.value.pass_name == "evil_widen"
    assert any(f.kind == "frozen" and f.label == label
               for f in exc.value.findings), \
        [str(f) for f in exc.value.findings]
    # and the message names both the node and the pass
    assert "evil_widen" in str(exc.value) and label in str(exc.value)


def test_bind_pass_attribution():
    """A corruption present in the freshly bound plan is attributed to the
    'bind' pass, not to a later rewrite."""
    cat, plan = _simple_plan()
    flt = next(n for n in P.iter_plan_nodes(plan)
               if isinstance(n, P.FilterNode))
    flt.predicate.dtype = "int"     # break bool-typed predicate invariant
    pipe = PassPipeline("per-pass", cat)
    with pytest.raises(PlanVerifyError) as exc:
        pipe.check("bind", plan)
    assert exc.value.pass_name == "bind"


def test_check_frozen_reports_deepest_node():
    cat, plan = _simple_plan()
    before = snapshot(plan)
    scan = next(n for n in P.iter_plan_nodes(plan)
                if isinstance(n, P.ScanNode))
    scan.out_names[0] = "renamed"
    findings = check_frozen(plan, before)
    # the scan mutated; its ancestors' fingerprints changed too, but only
    # the deepest node is named
    assert len(findings) == 1 and findings[0].node is scan


def test_param_roundtrip_verified_deep():
    """deep verification parameterizes + deparameterizes the plan and
    proves structural identity (a literal-heavy template exercises it)."""
    cat = _catalog(False, verify="off")
    for name, stmt in _statements(3):
        plan = Planner(cat).plan_query(parse_sql(stmt))
        assert verify_plan(plan, cat, deep=True) == []


def test_mergeable_agg_decomposition_checked():
    cat, plan = _simple_plan()
    agg = next(n for n in P.iter_plan_nodes(plan)
               if isinstance(n, P.AggregateNode))
    # corrupt the aggregate's declared output dtype: the streaming
    # decomposition can no longer rebuild the declared schema
    agg.out_dtypes[-1] = "str"
    findings = verify_plan(plan, cat)
    assert any(f.kind in ("agg", "dtype") for f in findings), \
        [str(f) for f in findings]


def test_stream_fusion_groups_verified():
    """Fused shared-scan partial plans are plan-IR rewrites outside the
    PassPipeline; streaming.verify_groups covers them."""
    from nds_tpu.engine.streaming import MORSEL_TABLE, ScanGroup, \
        verify_groups
    scan = P.ScanNode(MORSEL_TABLE, ["a"], out_names=["a"],
                      out_dtypes=["int"])
    ok = P.ProjectNode(scan, [P.BCol("int", 0, "a")],
                       out_names=["a"], out_dtypes=["int"])
    verify_groups([ScanGroup("t", ["a"], ["int"], [(0, 0)], [ok])])
    bad = P.ProjectNode(scan, [P.BCol("int", 99, "a")],
                        out_names=["a"], out_dtypes=["int"])
    with pytest.raises(PlanVerifyError, match="stream_fusion"):
        verify_groups([ScanGroup("t", ["a"], ["int"], [(0, 0)], [bad])])


# -- 3. config / session plumbing -----------------------------------------

def test_property_file_and_config(tmp_path):
    p = tmp_path / "props.conf"
    p.write_text("nds.tpu.verify_plans=per-pass\n")
    cfg = EngineConfig.from_property_file(str(p))
    assert cfg.verify_plans == "per-pass"
    assert EngineConfig().verify_plans in ("off", "final", "per-pass")


def test_session_verifies_plans():
    from nds_tpu.engine import Session
    rng = np.random.default_rng(7)
    n = 500
    cfg = EngineConfig(verify_plans="per-pass", use_jax=False)
    s = Session(cfg)
    s.register_arrow("fact", pa.table({
        "fk": pa.array(rng.integers(0, 20, n), type=pa.int64()),
        "qty": pa.array(rng.integers(1, 50, n), type=pa.int64()),
    }))
    out = s.sql("SELECT fk, SUM(qty) FROM fact GROUP BY fk ORDER BY fk")
    assert out.num_rows == 20
    assert s._catalog().verify_plans == "per-pass"


def test_power_flag_wired():
    import nds_tpu.power as power
    # argparse rejects values outside the off/final/per-pass tri-state
    with pytest.raises(SystemExit):
        power.main(["d", "s", "t", "--verify_plans", "sometimes"])


# -- 4. compiled-query argument contract ----------------------------------

def _compiled_session():
    from nds_tpu.engine import Session
    rng = np.random.default_rng(11)
    n = 3000
    s = Session(EngineConfig())
    s.register_arrow("fact", pa.table({
        "fk": pa.array(rng.integers(0, 40, n), type=pa.int64()),
        "qty": pa.array(rng.integers(1, 100, n), type=pa.int64()),
    }))
    return s


def test_compiled_query_arg_validation_reports_per_argument():
    from nds_tpu.engine.jax_backend.executor import ArgSpecMismatch
    s = _compiled_session()
    sql = "SELECT fk, SUM(qty) FROM fact WHERE qty > 3 GROUP BY fk"
    expected = sorted(map(tuple, s.sql(sql, backend="numpy").to_pylist()))
    got = sorted(map(tuple, s.sql(sql, backend="jax").to_pylist()))
    assert got == expected
    jexec = s._jax_executor()
    res = jexec.precompile_parallel()
    key = ("sql", sql)
    ent = jexec._plans.get(key) or jexec._plans.get((key, "root"))
    assert ent is not None and ent.get("cq") is not None, res
    cq = ent["cq"]
    scans = jexec._scans_for(ent)
    values = ent.get("params", ())

    # well-formed args validate clean
    cq.validate_args(scans, values)

    # a missing scan names the absent key and the full contract
    with pytest.raises(ArgSpecMismatch, match="missing scan"):
        cq.validate_args({}, values)

    # a short parameter vector reports expected dtypes vs got count
    if cq.param_dtypes:
        with pytest.raises(ArgSpecMismatch,
                           match="parameter vector length"):
            cq.validate_args(scans, ())

    # a corrupted scan produces a per-argument expected-vs-got report
    import jax
    bad_key = cq.scan_keys[0]
    bad = dict(scans)
    bad[bad_key] = jax.tree_util.tree_map(
        lambda x: x[:1] if getattr(x, "ndim", 0) >= 1 else x,
        scans[bad_key])
    with pytest.raises(ArgSpecMismatch) as exc:
        cq.validate_args(bad, values)
    msg = str(exc.value)
    assert "expected" in msg and "got" in msg and repr(bad_key) in msg
