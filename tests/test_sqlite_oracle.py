"""Template differential vs SQLite — an INDEPENDENT engine (own parser,
planner, executor), catching shared-frontend bugs the numpy/jax comparison
cannot (SURVEY.md §4; reference independent-oracle role:
nds/nds_validate.py:48-114)."""
import sqlite3

import pytest

from nds_tpu import datagen, streams, validate
from nds_tpu.engine import Session
from nds_tpu.engine import arrow_bridge
from nds_tpu.power import setup_tables

from sqlite_oracle import (load_database, normalize_rows, sort_rows,
                           to_sqlite_sql)

def sqlite_supported_templates():
    # ROLLUP templates run through the oracle's grouping-set expansion
    # (sqlite_oracle.expand_rollup), so all 99 templates are covered
    return streams.available_templates()


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    data = str(tmp_path_factory.mktemp("sqlite_oracle") / "d")
    datagen.generate_data_local(data, 0.001, parallel=2, overwrite=True)
    session = Session()
    setup_tables(session, data, "csv")
    conn = load_database(data)
    return session, conn


def _engine_rows(table):
    at = arrow_bridge.to_arrow(table)
    cols = [c.to_pylist() for c in at.columns]
    return normalize_rows(list(zip(*cols)) if cols else [])


@pytest.mark.parametrize("number", sqlite_supported_templates())
def test_template_vs_sqlite(env, number):
    session, conn = env
    sql = streams.instantiate(number, stream=0, rngseed=31415)
    parts = (streams.split_special_query(f"query{number}", sql)
             if number in streams.SPECIAL_TEMPLATES
             else [(f"query{number}", sql)])
    for name, part_sql in parts:
        lite_sql = to_sqlite_sql(part_sql)
        try:
            expected = conn.execute(lite_sql).fetchall()
        except sqlite3.OperationalError as e:
            # skip budget is ZERO (round-2 verdict): every template is known
            # to translate, so a dialect regression must FAIL, not skip.
            # Sole carve-out: the ORACLE itself may be too old — FULL OUTER
            # JOIN needs sqlite >= 3.39 (q51/q97), a host-library capability,
            # not a translation regression
            if "FULL OUTER JOIN" in str(e) and \
                    sqlite3.sqlite_version_info < (3, 39):
                pytest.skip(f"host sqlite {sqlite3.sqlite_version} predates "
                            f"FULL OUTER JOIN (needs 3.39) for {name}")
            pytest.fail(f"sqlite dialect translation regressed for {name}: "
                        f"{e}\n{lite_sql}")
        actual = session.sql(part_sql, backend="numpy")
        rows_e = sort_rows(normalize_rows(expected))
        rows_a = sort_rows(_engine_rows(actual))
        assert len(rows_e) == len(rows_a), \
            f"{name}: sqlite {len(rows_e)} rows vs engine {len(rows_a)}"
        names = list(actual.names)
        for re_, ra_ in zip(rows_e, rows_a):
            assert validate.row_equal(re_, ra_, name, names), \
                f"{name}: sqlite {re_} != engine {ra_}"


def test_rollup_variant_scoped_to_plain_projections():
    """Round-2 advisor: NULL substitution must not touch occurrences of a
    rolled-up column inside aggregate args or string literals."""
    from sqlite_oracle import expand_rollup
    sql = ("SELECT a, b AS bb, sum(a) s, 'a b' tag, grouping(a) ga "
           "FROM t GROUP BY ROLLUP(a, b)")
    out = expand_rollup(sql)
    variants = out.split(" UNION ALL ")
    assert len(variants) == 3
    # grand-total variant: plain projections NULLed (alias kept), aggregate
    # arg and string literal untouched, GROUPING folded to 1
    total = variants[-1]
    assert "NULL" in total and "sum(a)" in total and "'a b'" in total
    assert "NULL AS bb" in total.replace("  ", " ") or "NULL bb" in total
    assert "1 ga" in total or "1  ga" in total
    # full-prefix variant unchanged apart from GROUPING -> 0
    assert "sum(a)" in variants[0] and "NULL" not in variants[0]
