"""Concurrent query service (nds_tpu/service): admission control, async
scheduling, the shared cross-client program cache, and compatible-plan
batching.

The contract under test is the acceptance bar of the service itself:
every result a client receives must be BIT-IDENTICAL to running the same
SQL alone on a fresh single-caller Session — through batched dispatches
(one compiled program over a stacked parameter matrix), through the
serial lane (record/adopt/replay, streaming), under concurrent clients,
racing live EngineConfig toggles, and beside deadline-expired neighbors
failing typed."""
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from nds_tpu.config import EngineConfig
from nds_tpu.engine import Session
from nds_tpu.obs.metrics import METRICS
from nds_tpu.resilience import AdmissionRejected, DeadlineExceeded
from nds_tpu.service import QueryService, ServiceConfig
from nds_tpu.service.service import ServiceClosed

N_FACT, N_DIM = 20_000, 50

#: one parameterized template (int + float aggregates: float sums prove
#: the batched lax.map dispatch is bit-identical even where order could
#: bite) instantiated with different literal values per "client"
TPL = ("SELECT grp, COUNT(*) AS n, SUM(qty) AS tq, SUM(price) AS tp "
       "FROM fact JOIN dim ON fk = dk WHERE qty BETWEEN {a} AND {b} "
       "GROUP BY grp ORDER BY grp")
#: a second, structurally different template (incompatible fingerprint)
TPL2 = ("SELECT fk, MAX(qty) AS mq FROM fact WHERE qty < {a} "
        "GROUP BY fk ORDER BY fk LIMIT 5")


def q1(a, b):
    return TPL.format(a=a, b=b)


def q2(a):
    return TPL2.format(a=a)


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    fact = pa.table({
        "fk": pa.array(rng.integers(0, N_DIM, N_FACT), type=pa.int64()),
        "qty": pa.array(rng.integers(1, 100, N_FACT), type=pa.int64()),
        "price": pa.array(np.round(rng.uniform(1, 50, N_FACT), 2)),
    })
    dim = pa.table({"dk": pa.array(np.arange(N_DIM), type=pa.int64()),
                    "grp": pa.array((np.arange(N_DIM) % 7)
                                    .astype(np.int64))})
    return {"fact": fact, "dim": dim}


def make_session(data, **cfg_kw):
    s = Session(EngineConfig(**cfg_kw))
    s.register_arrow("fact", data["fact"])
    s.register_arrow("dim", data["dim"])
    return s


@pytest.fixture()
def serial_ref(data):
    """Fresh single-caller session: the bit-identity oracle."""
    ref_session = make_session(data)
    cache = {}

    def ref(sql):
        if sql not in cache:
            cache[sql] = ref_session.sql(sql, label="ref").to_pylist()
        return cache[sql]
    return ref


def wait_ready(svc, n, timeout=10.0):
    """Block until the planner stage has n tickets parked at the (held)
    device lane — deterministic batch accumulation."""
    t0 = time.time()
    while time.time() - t0 < timeout:
        with svc._cv:
            if len(svc._ready) >= n:
                return
        time.sleep(0.01)
    raise AssertionError(f"planner stage never readied {n} tickets")


def warm(svc, sql):
    """Two executions: record, then compile + publish the shared program."""
    svc.sql(sql, label="warm")
    svc.sql(sql, label="warm")


# -- batching ----------------------------------------------------------------

def test_batched_dispatch_bit_identical(data, serial_ref):
    session = make_session(data)
    params = [(5 + i, 60 + i) for i in range(5)]
    with QueryService(session, ServiceConfig(max_batch=8)) as svc:
        warm(svc, q1(*params[0]))
        before = METRICS.snapshot()
        with svc.hold_dispatch():
            tickets = [svc.submit(q1(a, b), label=f"c{i}")
                       for i, (a, b) in enumerate(params)]
            wait_ready(svc, len(tickets))
        for t, (a, b) in zip(tickets, params):
            assert t.result(timeout=60).to_pylist() == serial_ref(q1(a, b))
            assert t.stats.mode == "batched"
            assert t.stats.batched_with == len(params) - 1
            assert t.stats.queue_wait_ms is not None
            assert t.stats.queue_wait_ms >= 0
            # the dict view carries the service keys too (bench JSON path)
            d = t.stats.to_dict()
            assert d["batched_with"] == len(params) - 1
            assert "queue_wait_ms" in d
        delta = METRICS.delta(before)
        assert delta.get("service_batches", 0) >= 1
        assert delta.get("service_batched_queries", 0) == len(params)
        # ONE batched dispatch compiled once; the per-row programs did not
        assert delta.get("compiles", 0) <= 1


def test_batch_dedups_identical_parameters(data, serial_ref):
    session = make_session(data)
    with QueryService(session, ServiceConfig()) as svc:
        warm(svc, q1(3, 77))
        with svc.hold_dispatch():
            tickets = [svc.submit(q1(3, 77), label=f"dup{i}")
                       for i in range(4)]
            wait_ready(svc, 4)
        want = serial_ref(q1(3, 77))
        for t in tickets:
            assert t.result(timeout=60).to_pylist() == want
            assert t.stats.mode == "batched"
            assert t.stats.batched_with == 3


def test_unwarmed_batch_falls_back_serial_and_correct(data, serial_ref):
    """No published shared program yet: the batched lookup misses, the
    group serves serially through record/replay, results stay exact."""
    session = make_session(data)
    params = [(2, 40), (3, 50), (4, 60)]
    with QueryService(session, ServiceConfig()) as svc:
        with svc.hold_dispatch():
            tickets = [svc.submit(q1(a, b)) for a, b in params]
            wait_ready(svc, len(tickets))
        for t, (a, b) in zip(tickets, params):
            assert t.result(timeout=60).to_pylist() == serial_ref(q1(a, b))
            assert t.stats.mode != "batched"


def test_incompatible_templates_do_not_cobatch(data, serial_ref):
    session = make_session(data)
    with QueryService(session, ServiceConfig()) as svc:
        warm(svc, q1(5, 60))
        warm(svc, q2(30))
        with svc.hold_dispatch():
            ta = [svc.submit(q1(5 + i, 60 + i)) for i in range(2)]
            tb = [svc.submit(q2(30 + i)) for i in range(2)]
            wait_ready(svc, 4)
        for i, t in enumerate(ta):
            assert t.result(60).to_pylist() == serial_ref(q1(5 + i, 60 + i))
        for i, t in enumerate(tb):
            assert t.result(60).to_pylist() == serial_ref(q2(30 + i))
        # each template batched only with its own kind
        assert all(t.stats.batched_with == 1 for t in ta + tb
                   if t.stats.mode == "batched")


# -- shared cross-client program cache ---------------------------------------

def test_cross_client_adoption_no_recompile(data):
    """The Nth client's NEW text of a warmed template re-traces and
    re-compiles nothing: the shared-fingerprint entry (schedule + program)
    is adopted, compile count stays flat."""
    session = make_session(data)
    with QueryService(session, ServiceConfig()) as svc:
        warm(svc, q1(7, 70))
        before = METRICS.snapshot()
        svc.sql(q1(8, 71), label="client2")   # new text, same template
        svc.sql(q1(9, 72), label="client3")
        delta = METRICS.delta(before)
        assert delta.get("compiles", 0) == 0
        assert delta.get("programs_adopted", 0) >= 2


# -- concurrent correctness ---------------------------------------------------

def test_concurrent_clients_bit_identical(data, serial_ref):
    session = make_session(data)
    texts = [q1(5 + i % 4, 60 + i % 4) for i in range(8)] + \
        [q2(25 + i % 3) for i in range(4)]
    want = {s: serial_ref(s) for s in texts}
    results: dict = {}
    errors: list = []
    with QueryService(session, ServiceConfig(plan_workers=2)) as svc:
        warm(svc, q1(5, 60))

        def client(i, sql):
            try:
                results[(i, sql)] = svc.sql(sql, label=f"cl{i}",
                                            timeout=120).to_pylist()
            except Exception as e:      # surfaced below
                errors.append((i, sql, e))

        threads = [threading.Thread(target=client, args=(i, s))
                   for i, s in enumerate(texts)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
    assert not errors, errors
    for (i, sql), got in results.items():
        assert got == want[sql], f"client {i} drifted on {sql!r}"


def test_live_config_toggle_races_inflight_queries(data, serial_ref):
    """EngineConfig.pallas_ops flipped while clients are in flight: the
    executor invalidates per generation key and every result stays exact
    (the kernels are bit-identical to XLA by contract)."""
    session = make_session(data)
    texts = [q1(5 + i % 3, 60 + i % 3) for i in range(6)]
    want = {s: serial_ref(s) for s in texts}
    errors: list = []
    with QueryService(session, ServiceConfig()) as svc:
        warm(svc, texts[0])

        def client(i, sql):
            try:
                got = svc.sql(sql, label=f"tog{i}", timeout=120).to_pylist()
                if got != want[sql]:
                    errors.append((i, sql, "drift"))
            except Exception as e:
                errors.append((i, sql, e))

        threads = [threading.Thread(target=client, args=(i, s))
                   for i, s in enumerate(texts)]
        for t in threads:
            t.start()
        for flip in (("gather",), (), ("gather", "groupby"), ()):
            session.config.pallas_ops = flip
            time.sleep(0.05)
        for t in threads:
            t.join(timeout=120)
    assert not errors, errors


def test_streamed_query_through_service(data, tmp_path):
    """Out-of-core queries take the serial lane (session streaming path)
    and stay exact vs a fresh single-caller session under the SAME
    streaming config (f64 partial-merge order is config-determined);
    the planner stage excludes them from batching."""
    path = str(tmp_path / "fact.parquet")
    pq.write_table(data["fact"], path, row_group_size=4096)
    cfg = dict(out_of_core=True, out_of_core_min_rows=10_000,
               chunk_rows=4096)

    def streaming_session():
        s = Session(EngineConfig(**cfg))
        s.register_parquet("fact", path)
        s.register_arrow("dim", data["dim"])
        return s

    sql = q1(10, 90)
    want = streaming_session().sql(sql, label="ref").to_pylist()
    session = streaming_session()
    with QueryService(session, ServiceConfig()) as svc:
        t = svc.submit(sql, label="streamed")
        got = t.result(timeout=120).to_pylist()
        assert t.stats.mode == "streaming"
        assert t.stats.queue_wait_ms is not None
        # live encoded_exec toggle racing a fresh submission: the stream
        # cache invalidates by config fingerprint and the encoded/plain
        # layouts are bit-identical by contract
        session.config.encoded_exec = False
        t2 = svc.submit(sql, label="streamed-plain")
        got_plain = t2.result(timeout=120).to_pylist()
        assert t2.stats.mode == "streaming"
        assert got_plain == got
    assert got == want


# -- admission control + deadlines -------------------------------------------

def test_queue_full_typed_rejection(data):
    session = make_session(data)
    with QueryService(session, ServiceConfig(max_pending=2)) as svc:
        with svc.hold_dispatch():
            t1 = svc.submit(q1(5, 60))
            t2 = svc.submit(q1(6, 61))
            before = METRICS.snapshot()
            with pytest.raises(AdmissionRejected) as ei:
                svc.submit(q1(7, 62))
            assert ei.value.depth == 2 and ei.value.limit == 2
            assert METRICS.delta(before).get("service_rejected") == 1
        assert t1.result(60) is not None
        assert t2.result(60) is not None


def test_deadline_expires_in_queue_neighbors_complete(data, serial_ref):
    session = make_session(data)
    with QueryService(session, ServiceConfig()) as svc:
        warm(svc, q1(5, 60))
        with svc.hold_dispatch():
            doomed = svc.submit(q1(6, 61), deadline_s=0.05, tenant="t-low")
            neighbors = [svc.submit(q1(7 + i, 62 + i)) for i in range(2)]
            wait_ready(svc, 1)
            time.sleep(0.2)        # the doomed ticket's budget expires
        before_err = None
        try:
            doomed.result(timeout=60)
        except DeadlineExceeded as e:
            before_err = e
        assert before_err is not None and "t-low" in str(before_err)
        for i, t in enumerate(neighbors):
            assert t.result(60).to_pylist() == serial_ref(q1(7 + i, 62 + i))


def test_tenant_deadline_mapping(data):
    session = make_session(data)
    cfg = ServiceConfig(tenant_deadlines={"impatient": 0.01},
                        default_deadline_s=0.0)
    with QueryService(session, cfg) as svc:
        with svc.hold_dispatch():
            doomed = svc.submit(q1(5, 60), tenant="impatient")
            ok = svc.submit(q1(5, 60), tenant="patient")
            time.sleep(0.1)
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=60)
        assert ok.result(60) is not None


def test_closed_service_rejects_typed(data):
    session = make_session(data)
    svc = QueryService(session, ServiceConfig())
    with pytest.raises(ServiceClosed):
        svc.submit(q1(5, 60))          # never started
    svc.start()
    svc.sql(q1(5, 60))
    svc.close()
    with pytest.raises(AdmissionRejected):
        svc.submit(q1(5, 60))


# -- service-backed throughput streams ---------------------------------------

def test_throughput_service_streams(data, serial_ref, tmp_path):
    """Two throughput streams through one shared service: per-stream time
    logs keep the power-run contract (scrape-able sentinels), elapsed
    computes, and the shared session served both."""
    from nds_tpu.throughput import (_run_stream_service, scrape_log,
                                    stream_log_path, throughput_elapsed)

    session = make_session(data)
    stream_text = "\n".join(
        f"-- start query {i + 1} using template query{i + 1}.tpl\n"
        + q1(5 + i, 60 + i) for i in range(2))
    sf = tmp_path / "stream.sql"
    sf.write_text(stream_text)
    logs = [stream_log_path(str(tmp_path), i) for i in range(2)]
    with QueryService(session, ServiceConfig()) as svc:
        threads = [threading.Thread(
            target=_run_stream_service, args=(svc, str(sf), log))
            for log in logs]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
    for log in logs:
        start, end = scrape_log(log)
        assert end >= start
    assert throughput_elapsed(logs) >= 0.0


# -- open loop at scale (slow: the 100-client run) ---------------------------

@pytest.mark.slow
def test_open_loop_100_clients(data, serial_ref):
    """100 concurrent clients, mixed templates, parameter pools shared
    across clients (dashboard shape): every response bit-identical to
    serial, no hangs, batching engaged."""
    session = make_session(data)
    pool = [q1(5 + i, 60 + i) for i in range(8)] + \
        [q2(20 + i) for i in range(4)]
    want = {s: serial_ref(s) for s in pool}
    errors: list = []
    done = [0]
    lock = threading.Lock()
    with QueryService(session, ServiceConfig(max_pending=512,
                                             max_batch=32)) as svc:
        warm(svc, pool[0])
        warm(svc, pool[8])

        def client(cid):
            rng = np.random.default_rng(cid)
            for _ in range(3):
                sql = pool[int(rng.integers(0, len(pool)))]
                try:
                    got = svc.sql(sql, label=f"open{cid}",
                                  timeout=300).to_pylist()
                    if got != want[sql]:
                        errors.append((cid, sql, "drift"))
                except Exception as e:
                    errors.append((cid, sql, e))
                with lock:
                    done[0] += 1

        before = METRICS.snapshot()
        threads = [threading.Thread(target=client, args=(cid,))
                   for cid in range(100)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
    assert not errors, errors[:5]
    assert done[0] == 300
    delta = METRICS.delta(before)
    assert delta.get("service_batches", 0) >= 1
    assert delta.get("service_batched_queries", 0) >= 10
