"""Whole-plan record/replay compilation (engine/jax_backend/executor).

The engine's steady-state contract: the second execution of a query (same
table registrations) runs as ONE jitted XLA program whose capacities come
from the recorded schedule, verified by device-computed check scalars.
"""
import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.config import EngineConfig
from nds_tpu.engine import Session


QUERY = """
SELECT d.grp, COUNT(*) AS cnt, SUM(f.qty) AS tq, AVG(f.price) AS ap,
       MAX(f.price) AS mp,
       RANK() OVER (ORDER BY SUM(f.qty) DESC) AS rk
FROM fact f JOIN dim d ON f.fk = d.dk
WHERE f.day BETWEEN 30 AND 120 AND f.qty > 5
GROUP BY d.grp ORDER BY d.grp
"""


def star_session(n_fact=20000, n_dim=500):
    rng = np.random.default_rng(7)
    fact = pa.table({
        "fk": pa.array(rng.integers(0, n_dim + 20, n_fact), type=pa.int32()),
        "qty": pa.array(rng.integers(1, 100, n_fact), type=pa.int32()),
        "price": pa.array(np.round(rng.uniform(0.5, 999.0, n_fact), 2)),
        "day": pa.array(rng.integers(0, 365, n_fact), type=pa.int32()),
    })
    dim = pa.table({"dk": pa.array(np.arange(n_dim), type=pa.int32()),
                    "grp": pa.array((np.arange(n_dim) % 23).astype(np.int32))})
    s = Session()
    s.register_arrow("fact", fact)
    s.register_arrow("dim", dim)
    return s


def assert_tables_equal(a, b, rtol=1e-9):
    assert a.num_rows == b.num_rows
    for name, ca, cb in zip(a.names, a.columns, b.columns):
        assert ca.validity.tolist() == cb.validity.tolist(), name
        va = np.asarray(ca.data, dtype=float)[ca.validity]
        vb = np.asarray(cb.data, dtype=float)[cb.validity]
        assert np.allclose(va, vb, rtol=rtol), name


def test_compiled_replay_matches_oracle_and_record():
    s = star_session()
    oracle = s.sql(QUERY, backend="numpy")
    first = s.sql(QUERY, backend="jax")       # record pass
    second = s.sql(QUERY, backend="jax")      # compile + run
    third = s.sql(QUERY, backend="jax")       # steady state
    ent = s._jax_exec._plans[("sql", QUERY)]
    assert ent["cq"] is not None and not ent["nojit"], ent.get("nojit_reason")
    assert s.last_exec_stats["mode"] == "compiled"
    assert s.last_exec_stats["device_ms"] > 0
    assert_tables_equal(oracle, first, rtol=1e-6)
    assert_tables_equal(first, second)
    assert_tables_equal(second, third)


def test_schedule_invalidation_on_data_change():
    s = star_session()
    s.sql(QUERY, backend="jax")
    s.sql(QUERY, backend="jax")
    assert s._jax_exec._plans[("sql", QUERY)]["cq"] is not None
    # re-registering a table bumps the generation: new executor, no stale plan
    rng = np.random.default_rng(8)
    s.register_arrow("fact", pa.table({
        "fk": pa.array(rng.integers(0, 520, 40000), type=pa.int32()),
        "qty": pa.array(rng.integers(1, 100, 40000), type=pa.int32()),
        "price": pa.array(rng.uniform(0.5, 999.0, 40000)),
        "day": pa.array(rng.integers(0, 365, 40000), type=pa.int32()),
    }))
    oracle = s.sql(QUERY, backend="numpy")
    result = s.sql(QUERY, backend="jax")
    assert_tables_equal(oracle, result, rtol=1e-6)


def test_replay_mismatch_detection():
    from nds_tpu.engine.jax_backend.executor import (ReplayMismatch,
                                                     _verify_schedule)
    _verify_schedule([("cap", 10), ("exact", 1)], [10, 1])
    _verify_schedule([("cap", 10)], [16])       # within bucket slack
    with pytest.raises(ReplayMismatch):
        _verify_schedule([("cap", 10)], [17])   # beyond bucket(10)=16
    with pytest.raises(ReplayMismatch):
        _verify_schedule([("exact", 0)], [1])


def test_jit_plans_off():
    cfg = EngineConfig(jit_plans=False)
    s = star_session()
    s.config = cfg
    s.sql(QUERY, backend="jax")
    s.sql(QUERY, backend="jax")
    assert s._jax_exec._plans == {}


@pytest.mark.slow  # 8-virtual-device whole-plan compile
def test_mesh_sharded_compiled_run():
    """8-virtual-device SPMD: fact scan row-sharded, plan GSPMD-partitioned."""
    import jax

    cfg = EngineConfig(mesh_shape=(8,), shard_min_rows=1024)
    s = star_session(n_fact=1 << 15)
    s.config = cfg
    s._jax_exec = None  # rebuild executor with the mesh
    oracle = s.sql(QUERY, backend="numpy")
    s.sql(QUERY, backend="jax")
    result = s.sql(QUERY, backend="jax")
    assert_tables_equal(oracle, result, rtol=1e-6)
    ex = s._jax_exec
    fact_keys = [k for k in ex._scan_cache if k.startswith("fact//")]
    assert fact_keys
    spec = ex._scan_cache[fact_keys[0]].cols[0].data.sharding.spec
    assert len(spec) == 1 and spec[0] == "shards"
