"""System tables + durable query log (ISSUE 15).

Acceptance-backed properties — all COUNT-shaped (no wall budgets: this
host is 1-core and timing tests flake):

- every ``system.*`` table's column names AND dtypes are FROZEN (schema
  pins) — operators script against them;
- the query-log ring and its JSONL sink hold the SAME rows (ring<->file
  equivalence), and the JSONL sink rotates size-capped with monotonic
  filenames and bounded file retention;
- snapshots are atomic cuts: readers racing 8 writer threads through the
  SQL path never observe a torn multi-counter row;
- the service serves ``system.*`` statements AROUND admission (works
  with the queue full / the service under pressure) with STRICT-ZERO
  device/planner counter movement;
- disabled mode adds zero counters (query_log_rows / query_log_rotations
  / system_queries all stay 0 on a plain workload);
- ``scripts/slo_report.py`` and ``scripts/metrics_server.py`` work as
  CLIs (the server on an OS-assigned ephemeral port).
"""
import json
import os
import subprocess
import sys
import threading
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.config import EngineConfig
from nds_tpu.engine import Session
from nds_tpu.engine.arrow_bridge import to_arrow
from nds_tpu.obs import system_tables as st
from nds_tpu.obs.metrics import METRICS
from nds_tpu.obs.query_log import COLUMNS, QUERY_LOG, read_jsonl
from nds_tpu.service import QueryService, ServiceConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _log_off():
    """Every test starts from a disabled, empty query log."""
    QUERY_LOG.configure(enabled=False, capacity=4096, path="", clear=True)
    yield
    QUERY_LOG.configure(enabled=False, capacity=4096, path="", clear=True)


def _rows(table) -> list[dict]:
    return to_arrow(table).to_pylist()


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    fact = pa.table({
        "k": pa.array(rng.integers(0, 5, 4000), type=pa.int64()),
        "v": pa.array(rng.integers(0, 100, 4000), type=pa.int64())})
    return fact


def make_session(data, **cfg) -> Session:
    s = Session(EngineConfig(**cfg))
    s.register_arrow("fact", data)
    return s


# -- schema pins --------------------------------------------------------------

def test_system_table_schemas_frozen():
    """The full column-name/dtype reference operators script against.
    Changing any of these is a deliberate, test-visible act."""
    expect = {
        "system.query_log": (
            ("ts", "seq", "source", "label", "tenant", "template",
             "trace_id", "status", "error", "wall_ms", "queue_ms",
             "plan_ms", "exec_ms", "materialize_ms", "rows",
             "bytes_uploaded", "mode", "cache_mode", "mesh_shards",
             "morsels", "mem_peak_bytes", "node_stats", "preempted"),
            ("float", "int", "str", "str", "str", "str", "int", "str",
             "str", "float", "float", "float", "float", "float", "int",
             "int", "str", "str", "int", "int", "int", "str", "int")),
        "system.metrics": (
            ("name", "kind", "value", "help"),
            ("str", "str", "float", "str")),
        "system.histograms": (
            ("name", "series", "tenant", "template", "le_ms", "count",
             "cum_count", "total_count", "sum_ms", "min_ms", "max_ms"),
            ("str", "str", "str", "str", "float", "int", "int", "int",
             "float", "float", "float")),
        "system.programs": (
            ("fingerprint", "hits", "compiles", "strikes", "volatile",
             "nojit", "decisions"),
            ("str", "int", "int", "int", "bool", "bool", "int")),
        "system.result_cache": (
            ("entry", "template", "backend", "rows", "hits", "stored_at",
             "tables", "ivm"),
            ("str", "str", "str", "int", "int", "float", "str", "bool")),
        "system.device_memory": (("metric", "bytes"), ("str", "int")),
        "system.flight": (
            ("seq", "t_ms", "event", "label", "tenant", "reason",
             "latency_ms", "detail"),
            ("int", "float", "str", "str", "str", "str", "float", "str")),
        "system.tables": (
            ("name", "generation", "est_rows", "columns", "unique_cols"),
            ("str", "int", "int", "int", "str")),
        "system.snapshots": (
            ("version", "timestamp_ms", "committer", "tables",
             "table_count", "current", "pinned"),
            ("int", "int", "str", "str", "int", "bool", "bool")),
        "system.plan_feedback": (
            ("template", "kind", "node", "table", "rows", "sightings",
             "refreshes", "gen"),
            ("str", "str", "str", "str", "int", "int", "int", "int")),
    }
    assert set(st.SYSTEM_SCHEMAS) == set(expect)
    for name, (cols, dts) in expect.items():
        assert st.SYSTEM_SCHEMAS[name] == (cols, dts), name
    # the query_log table IS the log's frozen row schema
    assert st.SYSTEM_SCHEMAS["system.query_log"][0] == \
        tuple(c for c, _ in COLUMNS)


def test_every_system_table_snapshots_with_its_schema(data):
    s = make_session(data)
    s.sql("SELECT k, COUNT(*) AS n FROM fact GROUP BY k ORDER BY k",
          label="seed")
    for name, (cols, _dts) in st.SYSTEM_SCHEMAS.items():
        arrow = st.snapshot_arrow(name, s)
        assert tuple(arrow.column_names) == cols, name


# -- session path: log rows + SQL over them -----------------------------------

def test_session_statement_logs_one_row_with_context(data):
    QUERY_LOG.configure(enabled=True, clear=True)
    s = make_session(data, query_log=True)
    res = s.sql("SELECT k, COUNT(*) AS n, SUM(v) AS sv FROM fact "
                "GROUP BY k ORDER BY k", label="inv1")
    rows = QUERY_LOG.rows()
    assert len(rows) == 1
    r = rows[0]
    assert r["source"] == "session" and r["label"] == "inv1"
    assert r["status"] == "ok" and r["rows"] == res.num_rows
    assert r["wall_ms"] is not None and r["wall_ms"] > 0
    assert r["mode"]            # record/compiled/... never empty
    assert r["mem_peak_bytes"] is not None


def test_sql_over_system_query_log_group_by_tenant(data):
    QUERY_LOG.configure(enabled=True, clear=True)
    s = make_session(data, query_log=True)
    for i in range(3):
        s.sql(f"SELECT k, COUNT(*) AS n FROM fact WHERE v > {i} "
              "GROUP BY k ORDER BY k", label=f"q{i}")
    got = _rows(s.sql("SELECT status, COUNT(*) AS n "
                      "FROM system.query_log GROUP BY status"))
    assert got == [{"status": "ok", "n": 3}]
    # filters + projection over the log
    labels = _rows(s.sql("SELECT label FROM system.query_log "
                         "WHERE label = 'q1'"))
    assert labels == [{"label": "q1"}]


def test_system_statement_not_logged_and_does_not_clobber_stats(data):
    QUERY_LOG.configure(enabled=True, clear=True)
    s = make_session(data, query_log=True)
    s.sql("SELECT k FROM fact WHERE v < 3", label="base")
    stats_before = s.last_exec_stats
    n0 = len(QUERY_LOG.rows())
    s.sql("SELECT name, value FROM system.metrics")
    assert len(QUERY_LOG.rows()) == n0     # polls never log themselves
    assert s.last_exec_stats is stats_before   # nor clobber stats views


def test_mixed_system_and_user_tables_rejected(data):
    s = make_session(data)
    with pytest.raises(ValueError, match="cannot join user tables"):
        s.sql("SELECT * FROM system.metrics m, fact f")
    with pytest.raises(ValueError, match="system.* tables only"):
        s.system_query("SELECT k FROM fact")


def test_dotted_name_in_literal_takes_normal_path(data):
    """A statement merely CONTAINING 'system.' routes normally."""
    s = make_session(data)
    res = s.sql("SELECT k FROM fact WHERE v < 5", label="plain")
    assert res.num_rows >= 0
    # string literal mentioning the prefix: still the normal path
    before = METRICS.snapshot().get("system_queries", 0)
    s.sql("SELECT k, COUNT(*) AS n FROM fact GROUP BY k ORDER BY k",
          label="system.decoy")      # label only, not SQL: no routing
    assert METRICS.snapshot().get("system_queries", 0) == before


# -- ring <-> JSONL equivalence + rotation ------------------------------------

def test_ring_and_jsonl_hold_identical_rows(tmp_path, data):
    path = str(tmp_path / "ql.jsonl")
    QUERY_LOG.configure(enabled=True, path=path, flush_every=2,
                        clear=True)
    s = make_session(data)
    for i in range(5):
        s.sql(f"SELECT k, COUNT(*) AS n FROM fact WHERE v >= {i} "
              "GROUP BY k ORDER BY k", label=f"eq{i}")
    QUERY_LOG.flush()
    assert read_jsonl(path) == QUERY_LOG.rows()


def test_jsonl_rotation_caps_and_monotonic_names(tmp_path, data):
    path = str(tmp_path / "rot.jsonl")
    # tiny cap: every flush rolls the file
    QUERY_LOG.configure(enabled=True, path=path, max_bytes=600,
                        max_files=2, flush_every=1, clear=True)
    before = METRICS.snapshot().get("query_log_rotations", 0)
    for i in range(12):
        QUERY_LOG.record(None, source="session", label=f"r{i}",
                         wall_ms=1.0)
    QUERY_LOG.flush()
    rotations = METRICS.snapshot()["query_log_rotations"] - before
    assert rotations >= 3
    kept = sorted(p for p in os.listdir(tmp_path)
                  if p.startswith("rot.jsonl."))
    # retention: at most max_files rotated files survive, and the
    # surviving suffixes are the HIGHEST (monotonic — newest kept)
    assert len(kept) <= 2
    suffixes = sorted(int(p.rsplit(".", 1)[1]) for p in kept)
    assert suffixes == sorted(suffixes) and suffixes[-1] == rotations
    # every surviving row parses and carries the frozen schema
    for p in kept + ["rot.jsonl"]:
        for row in read_jsonl(str(tmp_path / p)):
            assert set(row) == {c for c, _ in COLUMNS}


def test_flight_dump_retention_and_monotonic_filenames(tmp_path):
    from nds_tpu.obs.flight import FlightRecorder
    fr = FlightRecorder()
    fr.configure(enabled=True, dump_dir=str(tmp_path),
                 trip_cooldown_s=0.0, max_dumps=3)
    for i in range(8):
        fr.record("admit", label=f"q{i}")
        fr.trip(f"reason{i}")
    files = sorted(os.listdir(tmp_path))
    assert len(files) == 3                       # oldest-first eviction
    seqs = [int(f.split("_")[1]) for f in files]
    assert seqs == sorted(seqs) == [6, 7, 8]     # monotonic, newest kept
    # bytes cap: newest dump always survives
    fr2 = FlightRecorder()
    d2 = tmp_path / "b"
    fr2.configure(enabled=True, dump_dir=str(d2), trip_cooldown_s=0.0,
                  max_dump_bytes=300)
    for i in range(5):
        for j in range(8):
            fr2.record("admit", label=f"x{i}_{j}", pad="y" * 30)
        fr2.trip(f"r{i}")
    survivors = sorted(os.listdir(d2))
    assert survivors                              # newest kept
    assert len(survivors) < 5                     # older ones evicted
    assert survivors[-1].startswith("flight_00005_")


# -- atomic cut under concurrent writers --------------------------------------

def test_readers_never_see_torn_counter_rows_under_8_writers(data):
    """8 writer threads bump a counter PAIR atomically (under
    METRICS.locked()); SQL readers over system.metrics must always see
    a == b — the registry-lock snapshot contract, exercised through the
    full system-table path."""
    s = make_session(data)
    a = METRICS.counter("tw_pair_a", "torn-read probe (tests)")
    b = METRICS.counter("tw_pair_b", "torn-read probe (tests)")
    a._reset(), b._reset()
    stop = threading.Event()
    torn: list[tuple] = []

    def writer():
        while not stop.is_set():
            with METRICS.locked():
                a.inc()
                b.inc()

    def reader():
        for _ in range(25):
            got = {r["name"]: r["value"] for r in _rows(s.system_query(
                "SELECT name, value FROM system.metrics "
                "WHERE name = 'tw_pair_a' OR name = 'tw_pair_b'"))}
            if got["tw_pair_a"] != got["tw_pair_b"]:
                torn.append((got["tw_pair_a"], got["tw_pair_b"]))

    writers = [threading.Thread(target=writer) for _ in range(8)]
    readers = [threading.Thread(target=reader) for _ in range(2)]
    for t in writers + readers:
        t.start()
    for t in readers:
        t.join()
    stop.set()
    for t in writers:
        t.join()
    assert not torn, f"torn counter rows observed: {torn[:5]}"


# -- service path: admission bypass + strict-zero pins ------------------------

def test_service_system_bypass_strict_zero_counters(data):
    QUERY_LOG.configure(enabled=True, clear=True)
    s = make_session(data, query_log=True)
    with QueryService(s) as svc:
        for i in range(3):
            svc.sql(f"SELECT k, COUNT(*) AS n FROM fact WHERE v > {i} "
                    "GROUP BY k ORDER BY k", label=f"w{i}",
                    tenant="dash")
        before = METRICS.snapshot()
        got = _rows(svc.sql("SELECT tenant, COUNT(*) AS n "
                            "FROM system.query_log GROUP BY tenant"))
        hist = _rows(svc.sql(
            "SELECT series, total_count FROM system.histograms "
            "WHERE name = 'service_latency_ms' AND tenant = 'dash'"))
        delta = METRICS.delta(before)
    assert got == [{"tenant": "dash", "n": 3}]
    assert hist and all(r["total_count"] >= 1 for r in hist)
    # STRICT-ZERO: polls moved NOTHING but the system_queries counter —
    # no admission, no planner samples, no device dispatch, no compiles
    assert delta.pop("system_queries") == 2
    gated = {k: v for k, v in delta.items() if not k.endswith("_ms")}
    assert gated == {}, f"system polls perturbed counters: {gated}"


def test_service_system_bypass_works_when_queue_is_full(data):
    """Observability during overload: with max_pending saturated and
    normal submits REJECTED, system polls still answer."""
    from nds_tpu.resilience import AdmissionRejected
    s = make_session(data)
    with QueryService(s, ServiceConfig(max_pending=1)) as svc:
        with svc.hold_dispatch():
            t1 = svc.submit("SELECT k, COUNT(*) AS n FROM fact "
                            "GROUP BY k ORDER BY k", label="held")
            with pytest.raises(AdmissionRejected):
                svc.submit("SELECT COUNT(*) AS n FROM fact",
                           label="shed")
            poll = svc.submit("SELECT name, value FROM system.metrics "
                              "WHERE name = 'service_rejected'",
                              label="poll")
            assert poll.done()           # completed synchronously
            rows = _rows(poll.result(timeout=5))
            assert rows[0]["value"] >= 1
        t1.result(timeout=120)


def test_service_ticket_rows_carry_tenant_phases_and_errors(data):
    QUERY_LOG.configure(enabled=True, clear=True)
    s = make_session(data, query_log=True)
    with QueryService(s) as svc:
        svc.sql("SELECT k, COUNT(*) AS n FROM fact GROUP BY k "
                "ORDER BY k", label="ok1", tenant="dash")
        with pytest.raises(Exception):
            svc.sql("SELECT nope FROM fact", label="bad1",
                    tenant="dash")
    rows = {r["label"]: r for r in QUERY_LOG.rows()}
    ok = rows["ok1"]
    assert ok["source"] == "service" and ok["tenant"] == "dash"
    assert ok["status"] == "ok" and ok["wall_ms"] > 0
    assert ok["queue_ms"] is not None and ok["plan_ms"] is not None
    assert ok["exec_ms"] is not None and ok["rows"] is not None
    bad = rows["bad1"]
    assert bad["status"] != "ok" and bad["error"]
    # exactly one row per ticket: no session-side duplicates
    assert len(QUERY_LOG.rows()) == 2


def test_system_programs_and_tables_rows(data):
    s = make_session(data)
    tpl = ("SELECT k, COUNT(*) AS n FROM fact WHERE v BETWEEN {a} AND "
           "{b} GROUP BY k ORDER BY k")
    for i in range(3):                  # record -> compile -> replay
        s.sql(tpl.format(a=1, b=50), label="progs")
    progs = _rows(s.sql("SELECT fingerprint, compiles, strikes "
                        "FROM system.programs"))
    assert progs and all(len(r["fingerprint"]) > 8 for r in progs)
    assert any(r["compiles"] >= 1 for r in progs)
    assert all(r["strikes"] == 0 for r in progs)
    tabs = _rows(s.sql("SELECT name, generation, columns "
                       "FROM system.tables"))
    assert tabs == [{"name": "fact", "generation": 1, "columns": 2}]


def test_system_result_cache_rows(data):
    from nds_tpu.engine.result_cache import ResultCacheConfig
    s = make_session(data)
    with QueryService(s, ServiceConfig(
            result_cache=ResultCacheConfig())) as svc:
        sql = ("SELECT k, COUNT(*) AS n FROM fact GROUP BY k ORDER BY k")
        svc.sql(sql, label="c1")
        svc.sql(sql, label="c2")         # exact hit
        rows = _rows(svc.sql("SELECT entry, hits, backend "
                             "FROM system.result_cache"))
    assert len(rows) == 1
    assert rows[0]["hits"] >= 1 and rows[0]["backend"] == "jax"


def test_system_flight_rows(data):
    from nds_tpu.obs.flight import FLIGHT
    FLIGHT.configure(enabled=True, clear=True)
    try:
        s = make_session(data)
        with QueryService(s) as svc:
            svc.sql("SELECT COUNT(*) AS n FROM fact", label="fl1")
            got = _rows(svc.sql(
                "SELECT event, COUNT(*) AS n FROM system.flight "
                "GROUP BY event"))
        events = {r["event"]: r["n"] for r in got}
        assert events.get("admit", 0) >= 1
        assert events.get("complete", 0) >= 1
    finally:
        FLIGHT.configure(enabled=False, clear=True)


# -- disabled mode: zero counters ---------------------------------------------

def test_disabled_mode_moves_no_new_counters(data):
    before = METRICS.snapshot()
    s = make_session(data)             # query_log NOT enabled
    for i in range(3):
        s.sql(f"SELECT k, COUNT(*) AS n FROM fact WHERE v > {i} "
              "GROUP BY k ORDER BY k", label=f"d{i}")
    with QueryService(s) as svc:
        svc.sql("SELECT COUNT(*) AS n FROM fact", label="d3")
    delta = METRICS.delta(before)
    for name in ("query_log_rows", "query_log_rotations",
                 "system_queries"):
        assert delta.get(name, 0) == 0, name
    assert QUERY_LOG.rows() == []


# -- CLIs ---------------------------------------------------------------------

def _make_log_jsonl(path, data):
    QUERY_LOG.configure(enabled=True, path=str(path), flush_every=1,
                        clear=True)
    s = make_session(data, query_log=True)
    with QueryService(s) as svc:
        for i, tenant in enumerate(["dash", "dash", "batch"]):
            svc.sql(f"SELECT k, COUNT(*) AS n FROM fact WHERE v > {i} "
                    "GROUP BY k ORDER BY k", label=f"c{i}",
                    tenant=tenant)
    QUERY_LOG.flush()


def test_slo_report_cli(tmp_path, data):
    log = tmp_path / "ql.jsonl"
    _make_log_jsonl(log, data)
    out_json = tmp_path / "slo.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "slo_report.py"),
         str(log), "--slo_ms", "60000", "--target", "0.9",
         "--windows", "300,3600", "--json", str(out_json)],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr
    rep = json.loads(out_json.read_text())
    by_tenant = {r["tenant"]: r for r in rep["rows"]}
    assert by_tenant["dash"]["count"] == 2
    assert by_tenant["batch"]["count"] == 1
    assert by_tenant["(all)"]["count"] == 3
    # generous SLO: everything attains, burn 0
    assert all(r["met"] for r in rep["rows"])
    assert by_tenant["(all)"]["burn"]["5m"] == 0.0


def test_metrics_server_cli_ephemeral_port(tmp_path, data):
    log = tmp_path / "ql.jsonl"
    _make_log_jsonl(log, data)
    proc = subprocess.Popen(
        [sys.executable,
         os.path.join(REPO, "scripts", "metrics_server.py"),
         "--port", "0", "--query_log", str(log)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        line = proc.stdout.readline().strip()
        assert line.startswith("serving on http://"), line
        base = line.split("serving on ", 1)[1]
        with urllib.request.urlopen(f"{base}/healthz", timeout=30) as r:
            health = json.load(r)
        assert health["status"] == "ok"
        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
            prom = r.read().decode()
        assert "queries_run_total" in prom
        sql = urllib.parse.quote(
            "SELECT tenant, COUNT(*) AS n FROM system.query_log "
            "GROUP BY tenant")
        with urllib.request.urlopen(f"{base}/query?sql={sql}",
                                    timeout=30) as r:
            doc = json.load(r)
        assert doc["columns"] == ["tenant", "n"]
        assert sorted(doc["rows"]) == [["batch", 1], ["dash", 2]]
        # user tables refused over the wire
        bad = urllib.parse.quote("SELECT * FROM store_sales")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/query?sql={bad}", timeout=30)
        assert ei.value.code == 403
    finally:
        proc.terminate()
        proc.wait(timeout=30)


def test_service_metrics_port_scrape(data):
    """ServiceConfig.metrics_port=0: the service owns the endpoint's
    lifetime and the bound port reads back from the server object."""
    s = make_session(data)
    svc = QueryService(s, ServiceConfig(metrics_port=0))
    with svc:
        svc.sql("SELECT COUNT(*) AS n FROM fact", label="mp")
        port = svc.metrics_server.port
        assert port > 0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=30) as r:
            assert json.load(r)["status"] == "ok"
    assert svc.metrics_server is None      # stopped with the service


# -- obs_report --gate --------------------------------------------------------

def test_obs_report_compare_gate_and_allow(tmp_path):
    """--gate exits 1 on a >20% '!' regression; --allow waives it."""
    good = {"value": 100.0, "metrics": {"compiles": 10}}
    bad = {"value": 180.0, "metrics": {"compiles": 31}}
    a, b = tmp_path / "r1.json", tmp_path / "r2.json"
    a.write_text(json.dumps(good))
    b.write_text(json.dumps(bad))
    script = os.path.join(REPO, "scripts", "obs_report.py")

    def run(*extra):
        return subprocess.run(
            [sys.executable, script, "--compare", str(a), str(b),
             *extra], capture_output=True, text=True, timeout=120)

    flagged = run("--gate")
    assert flagged.returncode == 1
    assert "GATE FAIL" in flagged.stderr
    assert "wall_ms (slice total)@r2" in flagged.stderr
    waived = run("--gate", "--allow",
                 "wall_ms (slice total),compiles")
    assert waived.returncode == 0, waived.stderr
    assert "GATE OK" in waived.stderr
    clean = subprocess.run(
        [sys.executable, script, "--compare", str(a), str(a), "--gate"],
        capture_output=True, text=True, timeout=120)
    assert clean.returncode == 0
