"""Differential template coverage: every template in nds_tpu/templates runs
end-to-end on both backends at tiny SF, numpy-oracle vs JAX-device, compared
with the validator's epsilon/ordering policy (the reference's CPU-vs-GPU
differential oracle, nds/nds_validate.py, applied per template)."""
import numpy as np
import pytest

from nds_tpu import datagen, streams, validate
from nds_tpu.config import EngineConfig
from nds_tpu.engine import Session
from nds_tpu.engine import arrow_bridge
from nds_tpu.power import setup_tables


@pytest.fixture(scope="module")
def sessions(tmp_path_factory):
    data = str(tmp_path_factory.mktemp("tpl_data") / "d")
    datagen.generate_data_local(data, 0.001, parallel=2, overwrite=True)
    out = {}
    for backend in ("numpy", "jax"):
        s = Session(EngineConfig())
        setup_tables(s, data, "csv")
        out[backend] = s
    return out


def _rows(table, ignore_ordering=True):
    at = arrow_bridge.to_arrow(table)
    cols = [c.to_pylist() for c in at.columns]
    rows = list(zip(*cols)) if cols else []
    names = at.column_names

    def key(row):
        return tuple(
            (v is None, str(v)) for i, v in enumerate(row)
            if not isinstance(v, float))
    return sorted(rows, key=key), names


@pytest.mark.parametrize("number", streams.available_templates())
def test_template_differential(sessions, number):
    sql = streams.instantiate(number, stream=0, rngseed=31415)
    parts = (streams.split_special_query(f"query{number}", sql)
             if number in streams.SPECIAL_TEMPLATES
             else [(f"query{number}", sql)])
    for name, part_sql in parts:
        expected = sessions["numpy"].sql(part_sql, backend="numpy")
        actual = sessions["jax"].sql(part_sql, backend="jax")
        # reference runs every op on the accelerator (RAPIDS plugin,
        # nds/power_run_gpu.template); a host fallback is a coverage bug
        assert sessions["jax"].last_fallbacks == [], \
            f"{name}: device fallback {sessions['jax'].last_fallbacks}"
        rows_e, names = _rows(expected)
        rows_a, _ = _rows(actual)
        assert len(rows_e) == len(rows_a), \
            f"{name}: row count {len(rows_e)} vs {len(rows_a)}"
        for re_, ra_ in zip(rows_e, rows_a):
            assert validate.row_equal(re_, ra_, name, names), \
                f"{name}: {re_} != {ra_}"


# whole-plan XLA compile is 15-60s/template on the CPU test backend, so the
# compiled-replay differential runs on a representative spread of plan shapes
# (correlated subquery, star agg, rollup, window, set op, outer join, union
# CTE) rather than all 103 units; bench.py exercises the compiled path on the
# real chip and test_compiled_plans.py covers the machinery.
COMPILED_SUBSET = (1, 5, 12, 22, 51, 93)


@pytest.mark.parametrize("number", COMPILED_SUBSET)
def test_template_compiled_replay(sessions, number):
    sql = streams.instantiate(number, stream=0, rngseed=31415)
    parts = (streams.split_special_query(f"query{number}", sql)
             if number in streams.SPECIAL_TEMPLATES
             else [(f"query{number}", sql)])
    for name, part_sql in parts:
        expected = sessions["numpy"].sql(part_sql, backend="numpy")
        s = sessions["jax"]
        s.sql(part_sql, backend="jax")          # record pass (shared fixture
        actual = s.sql(part_sql, backend="jax")  # may already have recorded)
        assert s.last_exec_stats.get("mode") in ("compiled", "compile+run"), \
            f"{name}: not compiled ({s.last_exec_stats})"
        rows_e, names = _rows(expected)
        rows_a, _ = _rows(actual)
        assert len(rows_e) == len(rows_a)
        for re_, ra_ in zip(rows_e, rows_a):
            assert validate.row_equal(re_, ra_, name, names), \
                f"{name}: {re_} != {ra_}"
