"""Multi-chip sharded morsel execution (EngineConfig.mesh_shards).

Every streamed scan group's morsels partition across data-parallel
replicas of the device mesh: one row-sharded packed upload per morsel,
the same compiled per-morsel program replayed per replica via shard_map,
and ONE all_gather of the decomposed partials before the unchanged
host-side merge (engine/jax_backend/shard_exec.py). The conftest forces
an 8-virtual-device CPU mesh, so these tests exercise the real shard_map
programs + collectives without a TPU slice.

Contracts pinned here:
- BIT-IDENTICAL results at mesh_shards in {1, 2, 4, 8} vs the single-chip
  path (integer/decimal partials are order-independent — the exact-decimal
  measured configuration), including the skewed case where the last morsel
  holds fewer rows than the shard count (whole replicas all-dead);
- mesh_shards unset/1 leaves the single-chip path untouched (no mesh
  stats, no sharded programs);
- Pallas kernels dispatch INSIDE shard_map (the PR-7 "mesh executors
  force empty pallas_ops" restriction is lifted for the sharded morsel
  path); the GSPMD whole-plan mesh path still records
  pallas_fallback_reason="mesh";
- collective accounting (collective_bytes / collective_ms) and per-shard
  device-time attribution labels ("<q>/morsel:<t>@mesh<n>" /
  "<q>/gather:<t>@mesh<n>") are observable;
- independent SQLite oracle agreement for the sharded path.
"""
import sqlite3

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.config import EngineConfig
from nds_tpu.engine import Session

N_FACT, N_DIM = 30_000, 200
CHUNK = 4_096

STAR = ("SELECT d.grp, COUNT(*) AS c, SUM(f.qty) AS sq, MIN(f.amt) AS lo, "
        "MAX(f.amt) AS hi, AVG(f.qty) AS aq, MAX(f.price) AS mp "
        "FROM fact f JOIN dim d ON f.fk = d.dk "
        "WHERE f.day BETWEEN 10 AND 300 GROUP BY d.grp ORDER BY d.grp")

# q9-class: several scalar-subquery aggregates over the same big table —
# one shared-scan group, multiple members, fused multi-output program
SUBQ = ("SELECT (SELECT COUNT(*) FROM fact WHERE day < 100) AS a, "
        "(SELECT SUM(qty) FROM fact WHERE day >= 100) AS b, "
        "(SELECT MAX(amt) FROM fact WHERE day < 200) AS m "
        "FROM dim WHERE dk = 0")

# q10-class: semi join whose BUILD side holds the big scan (synthesized
# distinct-key aggregate streams, join patched to the materialized keys)
SEMI = ("SELECT d.grp, COUNT(*) AS c FROM dim d "
        "WHERE EXISTS (SELECT 1 FROM fact f WHERE f.fk = d.dk "
        "AND f.day < 50) GROUP BY d.grp ORDER BY d.grp")


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(11)
    qty = rng.integers(1, 50, N_FACT).astype(object)
    qty[rng.random(N_FACT) < 0.05] = None        # NULLs: sum_guarded merge
    fact = pa.table({
        "fk": pa.array(rng.integers(0, N_DIM + 9, N_FACT),
                       type=pa.int32()),
        "qty": pa.array(list(qty), type=pa.int32()),
        "amt": pa.array(rng.integers(100, 100000, N_FACT)
                        .astype(np.int64)),
        "price": pa.array(np.round(rng.uniform(1, 100, N_FACT), 2)),
        "day": pa.array(rng.integers(0, 365, N_FACT), type=pa.int32()),
    })
    dim = pa.table({"dk": pa.array(np.arange(N_DIM), type=pa.int32()),
                    "grp": pa.array((np.arange(N_DIM) % 13)
                                    .astype(np.int32))})
    return {"fact": fact, "dim": dim}


def make_session(data, mesh_shards=0, chunk=CHUNK, fact=None, **cfg):
    config = EngineConfig(out_of_core=True, chunk_rows=chunk,
                          out_of_core_min_rows=10_000,
                          mesh_shards=mesh_shards, **cfg)
    s = Session(config)
    s.register_arrow("fact", fact if fact is not None else data["fact"])
    s.register_arrow("dim", data["dim"])
    return s


def run(data, sql, mesh_shards=0, label=None, **kw):
    s = make_session(data, mesh_shards=mesh_shards, **kw)
    t = s.sql(sql, backend="jax",
              label=label or f"mesh{mesh_shards}")
    return t, dict(s.last_exec_stats)


def rows_of(t):
    return sorted(tuple(r) for r in t.to_pylist())


@pytest.fixture(scope="module")
def baseline(data):
    out = {}
    for key, sql in (("star", STAR), ("subq", SUBQ), ("semi", SEMI)):
        t, st = run(data, sql, mesh_shards=0, label=f"base_{key}")
        assert st.get("mode") == "streaming", (key, st.get("mode"))
        assert "mesh_shards" not in st
        out[key] = rows_of(t)
    return out


@pytest.mark.parametrize("n", [1, 2, 4, 8])
def test_star_bit_identity_across_shard_counts(data, baseline, n):
    t, st = run(data, STAR, mesh_shards=n, label=f"star{n}")
    assert rows_of(t) == baseline["star"]
    assert st["mode"] == "streaming"
    if n <= 1:
        # 1/unset = the single-chip path exactly: no mesh stats recorded
        assert "mesh_shards" not in st
        assert "collective_bytes" not in st
    else:
        assert st["mesh_shards"] == n
        assert st["sharded_groups"] == 1
        assert st["collective_bytes"] > 0
        assert st["collective_ms"] >= 0
        assert st.get("re_records", 0) == 0


def test_fused_multi_member_group_shards(data, baseline):
    """q9-class scalar-subquery battery: one shared-scan group, several
    member plans, ONE fused sharded multi-output program per morsel."""
    t, st = run(data, SUBQ, mesh_shards=8, label="subq8")
    assert rows_of(t) == baseline["subq"]
    assert st["mesh_shards"] == 8
    assert st["fused_groups"] == 1
    assert st["branches_served"] >= 2


def test_semi_join_build_side_shards(data, baseline):
    t, st = run(data, SEMI, mesh_shards=8, label="semi8")
    assert rows_of(t) == baseline["semi"]
    assert st["mesh_shards"] == 8


def test_skewed_last_morsel_smaller_than_shard_count(data):
    """Last morsel holds 3 rows < 8 shards: trailing replicas see
    all-dead blocks; results stay bit-identical."""
    n_rows = 3 * CHUNK + 3
    fact = data["fact"].slice(0, n_rows)
    base, st0 = run(data, STAR, mesh_shards=0, fact=fact, label="skew0")
    assert st0["mode"] == "streaming" and st0["morsels"] == 4
    t, st = run(data, STAR, mesh_shards=8, fact=fact, label="skew8")
    assert rows_of(t) == rows_of(base)
    assert st["mesh_shards"] == 8
    assert st.get("re_records", 0) == 0


def test_unfused_groups_shard(data, baseline):
    """Fusion budget exceeded: per-member sharded programs over the same
    row-sharded staged buffer."""
    t, st = run(data, SUBQ, mesh_shards=8,
                stream_fusion_max_branches=1, label="subq8uf")
    assert rows_of(t) == baseline["subq"]
    assert st["mesh_shards"] == 8
    assert st["fused_groups"] == 0


def test_wide_layout_shards(data, baseline):
    """--no_narrow_lanes: the wide packed layout also uploads row-sharded
    (or falls back to the per-leaf sharded DTable) bit-identically."""
    t, st = run(data, STAR, mesh_shards=4, narrow_lanes=False,
                label="star4wide")
    assert rows_of(t) == baseline["star"]
    assert st["mesh_shards"] == 4


def test_pallas_dispatches_inside_shard_map(data, baseline):
    """The PR-7 restriction is lifted for the sharded morsel path: with
    pallas_ops enabled the shard-local replay traces the kernels (cpu =
    interpret mode runs the real bodies), results stay bit-identical, and
    the flag is NOT silently dropped."""
    t, st = run(data, STAR, mesh_shards=8,
                pallas_ops=("sort", "groupby", "gather"), label="star8pk")
    assert rows_of(t) == baseline["star"]
    assert st["mesh_shards"] == 8
    assert st.get("pallas_ops") == ["gather", "groupby", "sort"]
    assert "pallas_fallback_reason" not in st


def test_gspmd_mesh_records_pallas_fallback_reason(data):
    """The GSPMD whole-plan mesh path (mesh_shape) still keeps the XLA
    lowering, but now records WHY: pallas_fallback_reason == "mesh"."""
    s = make_session(data, mesh_shape=(2,),
                     pallas_ops=("sort", "groupby", "gather"))
    s.config.out_of_core = False      # force the in-core GSPMD path
    s.sql(STAR, backend="jax", label="gspmd")
    st = s.last_exec_stats
    assert st.get("pallas_fallback_reason") == "mesh"
    assert "pallas_ops" not in st


def test_device_time_attribution_labels(data, baseline):
    from nds_tpu.obs.device_time import PROGRAMS
    run(data, STAR, mesh_shards=8, label="attr")
    labels = [row["program"] for row in PROGRAMS.table(top=200)]
    assert any(l.startswith("attr/morsel:fact") and l.endswith("@mesh8")
               for l in labels), labels
    assert any(l.startswith("attr/gather:fact") and l.endswith("@mesh8")
               for l in labels), labels


def test_sharded_vs_sqlite_oracle(data):
    """Independent-oracle agreement for the sharded path (own parser,
    planner, executor — catches shared-frontend bugs the single-vs-sharded
    differential cannot)."""
    conn = sqlite3.connect(":memory:")
    for name, t in (("fact", data["fact"]), ("dim", data["dim"])):
        cols = ", ".join(f'"{c}"' for c in t.column_names)
        conn.execute(f"CREATE TABLE {name} ({cols})")
        rows = list(zip(*[t.column(c).to_pylist()
                          for c in t.column_names]))
        conn.executemany(
            f"INSERT INTO {name} VALUES "
            f"({','.join('?' * len(t.column_names))})", rows)
    conn.commit()
    got, st = run(data, STAR, mesh_shards=8, label="oracle8")
    assert st["mesh_shards"] == 8
    want = sorted(tuple(r) for r in conn.execute(STAR).fetchall())
    got_rows = []
    for r in rows_of(got):
        got_rows.append(tuple(
            float(v) if hasattr(v, "as_tuple") else v for v in r))
    for g, w in zip(got_rows, want):
        assert len(g) == len(w)
        for gv, wv in zip(g, w):
            if isinstance(gv, float) or isinstance(wv, float):
                assert gv == pytest.approx(wv, rel=1e-9)
            else:
                assert gv == wv
    assert len(got_rows) == len(want)


def test_stream_cache_keys_on_shard_count(data):
    """Toggling mesh_shards on a live session must not replay cached
    single-chip streaming state (stream-cache key includes the count)."""
    s = make_session(data, mesh_shards=0)
    t0 = s.sql(STAR, backend="jax", label="toggle")
    assert "mesh_shards" not in s.last_exec_stats
    s.config.mesh_shards = 8
    t1 = s.sql(STAR, backend="jax", label="toggle")
    assert s.last_exec_stats.get("mesh_shards") == 8
    assert rows_of(t0) == rows_of(t1)
    s.config.mesh_shards = 0
    t2 = s.sql(STAR, backend="jax", label="toggle")
    assert "mesh_shards" not in s.last_exec_stats
    assert rows_of(t2) == rows_of(t0)


@pytest.mark.slow
def test_sf001_nds_queries_sharded_vs_single(tmp_path_factory):
    """Real NDS templates at SF0.01 on the 8-virtual-device mesh: the
    bench-slice queries must be bit-identical sharded vs single-chip in
    the measured EXACT-decimal configuration (integer partials merge
    order-independently; f64 decimals would reassociate sums), and agree
    with the independent SQLite oracle under the validator's epsilon
    policy. GSPMD-compile-heavy (slow marker: runs in the full CI test
    stage)."""
    import sys
    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent))
    from sqlite_oracle import load_database, normalize_rows, sort_rows, \
        to_sqlite_sql

    from nds_tpu import datagen, streams, validate
    from nds_tpu.engine import arrow_bridge
    from nds_tpu.power import setup_tables

    data_dir = str(tmp_path_factory.mktemp("mesh_sf001") / "d")
    datagen.generate_data_local(data_dir, 0.01, parallel=2, overwrite=True)
    conn = load_database(data_dir)

    def session_for(n):
        # csv registration estimates every table at 10k rows, so the
        # threshold goes under that: single-big-scan plans (query9's
        # store_sales-only scalar-subquery branches) then stream and
        # shard; multi-big-scan joins stay in-core — recorded per query
        cfg = EngineConfig(out_of_core=True, chunk_rows=8192,
                           out_of_core_min_rows=5_000, mesh_shards=n,
                           decimal_physical="i64")
        s = Session(cfg)
        setup_tables(s, data_dir, "csv")
        return s

    single, sharded = session_for(0), session_for(8)
    streamed_sharded = 0
    for number in (3, 7, 9):
        sql = streams.instantiate(number, stream=0, rngseed=31415)
        name = f"query{number}"
        t0 = single.sql(sql, backend="jax", label=name)
        t1 = sharded.sql(sql, backend="jax", label=name)
        st = dict(sharded.last_exec_stats)
        if st.get("mesh_shards"):
            streamed_sharded += 1
        # csv registration loads decimals as f64 (arrow_schema(use_decimal
        # =False)), so float sums reassociate across partial granularities
        # — compare floats at ULP-scale tolerance here; STRICT bit-identity
        # is pinned by the fast synthetic tests above and by the bench's
        # mesh scaling run over the exact-decimal parquet warehouse
        r0 = sort_rows(normalize_rows([tuple(r) for r in t0.to_pylist()]))
        r1 = sort_rows(normalize_rows([tuple(r) for r in t1.to_pylist()]))
        assert len(r0) == len(r1), f"{name}: sharded row count drifted"
        for a, b in zip(r0, r1):
            assert len(a) == len(b)
            for va, vb in zip(a, b):
                if isinstance(va, float) and isinstance(vb, float):
                    assert va == pytest.approx(vb, rel=1e-12, abs=1e-9), \
                        f"{name} drifted sharded: {a} != {b}"
                else:
                    assert va == vb, f"{name} drifted sharded: {a} != {b}"
        want = sort_rows(normalize_rows(
            conn.execute(to_sqlite_sql(sql)).fetchall()))
        at = arrow_bridge.to_arrow(t1)
        got = sort_rows(normalize_rows(list(zip(
            *[c.to_pylist() for c in at.columns])) if at.num_columns
            else []))
        assert len(got) == len(want), f"{name}: row count vs sqlite"
        for g, w in zip(got, want):
            assert validate.row_equal(w, g, name, list(t1.names)), \
                f"{name}: sqlite {w} != engine {g}"
    # at least one bench-slice query must have actually sharded (query9's
    # scalar-subquery battery streams store_sales at this threshold)
    assert streamed_sharded >= 1
