"""Partition-pruned DELETEs: a date-window delete over a date-partitioned
fact table must only read/rewrite the matching partitions (the reference
gets this from Iceberg metadata-pruned deletes, nds/nds_maintenance.py:
146-185; here the `<col>=<val>` file layout is the metadata)."""
import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.engine import Session
from nds_tpu.warehouse import Warehouse
import nds_tpu.warehouse as warehouse_mod


@pytest.fixture()
def wh_session(tmp_path):
    rng = np.random.default_rng(9)
    n = 3000
    dates = rng.integers(100, 130, n)          # 30 date partitions
    ss = pa.table({
        "ss_sold_date_sk": pa.array(
            [None if i % 97 == 0 else int(d) for i, d in enumerate(dates)],
            type=pa.int64()),
        "ss_ticket_number": pa.array(np.arange(n), type=pa.int64()),
        "ss_qty": pa.array(rng.integers(1, 50, n), type=pa.int64()),
    })
    dd = pa.table({
        "d_date_sk": pa.array(np.arange(100, 130), type=pa.int64()),
        "d_seq": pa.array(np.arange(30), type=pa.int64()),
    })
    wh = Warehouse(str(tmp_path / "wh"))
    wh.table("store_sales").create(ss)
    wh.table("date_dim").create(dd)
    s = Session()
    s.attach_warehouse(wh)
    return s, wh, ss


def _reads(monkeypatch):
    counted = []
    real = warehouse_mod.pq.read_table

    def spy(path, *a, **k):
        counted.append(path)
        return real(path, *a, **k)
    monkeypatch.setattr(warehouse_mod.pq, "read_table", spy)
    return counted


def test_in_subquery_delete_prunes_partitions(wh_session, monkeypatch):
    s, wh, ss = wh_session
    before = dict(zip(*np.unique(
        [v for v in ss.column("ss_sold_date_sk").to_pylist()
         if v is not None], return_counts=True)))
    counted = _reads(monkeypatch)
    s.execute("DELETE FROM store_sales WHERE ss_sold_date_sk IN "
          "(SELECT d_date_sk FROM date_dim WHERE d_seq < 5)")
    # only the 5 matching partitions were read during the rewrite
    assert 0 < len(counted) <= 5
    after = wh.table("store_sales").read()
    vals = [v for v in after.column("ss_sold_date_sk").to_pylist()
            if v is not None]
    assert all(v >= 105 for v in vals)
    kept_expected = sum(c for d, c in before.items() if d >= 105)
    assert len(vals) == kept_expected
    # null-key rows are NOT deleted by IN (NULL never matches)
    assert any(v is None
               for v in after.column("ss_sold_date_sk").to_pylist())


def test_between_delete_prunes(wh_session, monkeypatch):
    s, wh, _ = wh_session
    counted = _reads(monkeypatch)
    s.execute("DELETE FROM store_sales "
          "WHERE ss_sold_date_sk BETWEEN 110 AND 112 AND ss_qty > 0")
    assert 0 < len(counted) <= 3
    after = wh.table("store_sales").read()
    vals = [v for v in after.column("ss_sold_date_sk").to_pylist()
            if v is not None]
    assert not any(110 <= v <= 112 for v in vals)


def test_non_partition_delete_not_pruned(wh_session, monkeypatch):
    """A predicate that doesn't constrain the partition key reads every
    file (correctness over speed)."""
    s, wh, ss = wh_session
    counted = _reads(monkeypatch)
    s.execute("DELETE FROM store_sales WHERE ss_qty = 7")
    assert len(counted) >= 30
    after = wh.table("store_sales").read()
    assert 7 not in after.column("ss_qty").to_pylist()


def test_pruned_delete_matches_unpruned(tmp_path):
    """Differential: same delete with pruning disabled yields identical
    surviving rows."""
    rng = np.random.default_rng(3)
    n = 1000
    rows = pa.table({
        "inv_date_sk": pa.array(rng.integers(50, 60, n), type=pa.int64()),
        "inv_qty": pa.array(rng.integers(0, 9, n), type=pa.int64()),
    })
    dd = pa.table({"d_date_sk": pa.array([52, 53], type=pa.int64())})
    survivors = []
    for prune in (True, False):
        wh = Warehouse(str(tmp_path / f"wh_{prune}"))
        wh.table("inventory").create(rows)
        wh.table("date_dim").create(dd)
        s = Session()
        s.attach_warehouse(wh)
        if not prune:
            s._partition_prune = lambda *a, **k: None
        s.execute("DELETE FROM inventory WHERE inv_date_sk IN "
              "(SELECT d_date_sk FROM date_dim)")
        t = wh.table("inventory").read().sort_by(
            [("inv_date_sk", "ascending"), ("inv_qty", "ascending")])
        survivors.append(t.to_pylist())
    assert survivors[0] == survivors[1]


def chrono_session(tmp_path):
    """Chronological-ticket layout (the generator's contract since round 5):
    ticket numbers increase with sold date, so per-file ticket [min,max]
    manifest stats can prune ticket-keyed deletes on the RETURNS table,
    whose partition key (return date) the delete does not constrain."""
    n = 6000
    rng = np.random.default_rng(4)
    ticket = np.arange(n)
    sold = 100 + (ticket * 30) // n                       # 30 sold dates
    ret_date = sold + 1 + rng.integers(0, 20, n)          # returns lag
    sr = pa.table({
        "sr_returned_date_sk": pa.array(ret_date, type=pa.int64()),
        "sr_ticket_number": pa.array(ticket, type=pa.int64()),
        "sr_qty": pa.array(rng.integers(1, 9, n), type=pa.int64()),
    })
    ss = pa.table({
        "ss_sold_date_sk": pa.array(sold, type=pa.int64()),
        "ss_ticket_number": pa.array(ticket, type=pa.int64()),
    })
    dd = pa.table({"d_date_sk": pa.array(np.arange(100, 130),
                                         type=pa.int64()),
                   "d_seq": pa.array(np.arange(30), type=pa.int64())})
    wh = Warehouse(str(tmp_path / "whc"))
    wh.table("store_returns").create(sr)
    wh.table("store_sales").create(ss)
    wh.table("date_dim").create(dd)
    s = Session()
    s.attach_warehouse(wh)
    return s, wh, sr, ss


def test_ticket_in_subquery_delete_stats_pruned(tmp_path, monkeypatch):
    """DF_SS-class returns delete: sr_ticket_number IN (tickets sold in a
    3-day window) must only read the few files whose recorded ticket range
    intersects (VERDICT r4 #6: file min/max metadata, the half of Tdm the
    date partitions cannot prune)."""
    s, wh, sr, ss = chrono_session(tmp_path)
    nfiles = len(wh.table("store_returns").current_files())
    assert nfiles >= 20          # partitioned by return date
    counted = _reads(monkeypatch)
    s.execute(
        "DELETE FROM store_returns WHERE sr_ticket_number IN "
        "(SELECT ss_ticket_number FROM store_sales WHERE ss_sold_date_sk IN "
        " (SELECT d_date_sk FROM date_dim WHERE d_seq BETWEEN 10 AND 12))")
    sr_reads = [p for p in counted if "store_returns" in p]
    assert 0 < len(sr_reads) < nfiles * 0.6, \
        f"stats should prune: read {len(sr_reads)} of {nfiles}"
    # and the delete is exact
    after = wh.table("store_returns").read()
    doomed = set(np.asarray(ss.column("ss_ticket_number"))[
        (np.asarray(ss.column("ss_sold_date_sk")) >= 110)
        & (np.asarray(ss.column("ss_sold_date_sk")) <= 112)].tolist())
    left = set(after.column("sr_ticket_number").to_pylist())
    assert not (left & doomed)
    assert len(left) == 6000 - len(doomed | set())


def test_stats_survive_rollback(tmp_path):
    """Stats are never GC'd: a rollback-resurrected file still prunes."""
    s, wh, sr, ss = chrono_session(tmp_path)
    import time as _t
    ts = int(_t.time() * 1000)
    _t.sleep(0.005)   # the delete commit must land strictly after ts
    s.execute(
        "DELETE FROM store_returns WHERE sr_ticket_number IN "
        "(SELECT ss_ticket_number FROM store_sales WHERE ss_sold_date_sk IN "
        " (SELECT d_date_sk FROM date_dim WHERE d_seq BETWEEN 10 AND 12))")
    wh.table("store_returns").rollback_to_timestamp(ts)
    stats = wh.table("store_returns").file_stats()
    files = wh.table("store_returns").current_files()
    import os as _os
    rels = [_os.path.relpath(p, wh.table("store_returns").dir)
            for p in files]
    with_stats = [r for r in rels if r in stats]
    assert len(with_stats) == len(rels)
