"""Result validation: differential comparison of two query-output trees.

Capability parity with the reference validator (reference
nds/nds_validate.py): per-query compare of two output dirs with a row-count
gate then row-by-row comparison (compare_results :48-114), sorting on
non-float columns first when --ignore_ordering (collect_results :116-144),
epsilon comparison for floats/decimals with NaN == NaN (compare :194-215),
the query78 ratio-column carve-out of ±0.01001 (:146-192), the q65 skip and
q67-under-floats skip (iterate_queries :231-244), and writing
``queryValidationStatus`` Pass/Fail/NotAttempted back into the JSON
summaries (update_summary :262-296).

Here the two trees are typically the JAX device backend vs the numpy host
oracle (the reference compares GPU-Spark vs CPU-Spark).
"""
from __future__ import annotations

import argparse
import glob
import json
import math
import os
import sys

import pyarrow.parquet as pq

from .power import gen_sql_from_stream

DEFAULT_EPSILON = 0.0001
Q78_EPSILON = 0.01001

SKIP_ALWAYS = ("query65",)          # nondeterministic under ties (ref :231)
SKIP_WITH_FLOATS = ("query67",)     # rank over floats (ref :237)


def _is_float_type(t) -> bool:
    import pyarrow as pa
    return (pa.types.is_floating(t) or pa.types.is_decimal(t))


def _output_files(path: str):
    files = sorted(glob.glob(os.path.join(path, "*.parquet")))
    return files or None


def _output_rowcount(files: list[str]) -> int:
    """Row count from parquet metadata only (no data read)."""
    return sum(pq.ParquetFile(f).metadata.num_rows for f in files)


def _batch_rows(batch):
    cols = [batch.column(i).to_pylist() for i in range(batch.num_columns)]
    return list(zip(*cols)) if cols else []


def _sort_key_fn(schema):
    """Row sort key over non-float columns (reference collect_results
    :116-144 sorts on non-float columns before iterating)."""
    float_cols = {i for i, f in enumerate(schema)
                  if _is_float_type(f.type)}

    def key(row):
        return tuple((v is None, "" if v is None else str(v))
                     for i, v in enumerate(row) if i not in float_cols)
    return key


def iter_output_rows(files: list[str], ignore_ordering: bool,
                     batch_rows: int = 1 << 16, merge_batch: int = 4096):
    """Stream rows of an output tree with BOUNDED memory (the reference
    switches to toLocalIterator for large outputs, nds/nds_validate.py:
    116-144; here a no-LIMIT SF100 output must not materialize).

    ignore_ordering: external merge sort — each batch sorts in memory and
    spills as a run; runs k-way-merge (stable, so the total order matches
    the in-memory stable sort the small-output path used)."""
    import heapq
    import shutil
    import tempfile

    import pyarrow as pa

    if not ignore_ordering:
        for f in files:
            for batch in pq.ParquetFile(f).iter_batches(batch_rows):
                yield from _batch_rows(batch)
        return

    schema = pq.ParquetFile(files[0]).schema_arrow
    key = _sort_key_fn(schema)
    tmp = tempfile.mkdtemp(prefix="nds_validate_")
    try:
        runs: list[str] = []
        for f in files:
            for batch in pq.ParquetFile(f).iter_batches(batch_rows):
                rows = _batch_rows(batch)
                rows.sort(key=key)
                run = os.path.join(tmp, f"run-{len(runs)}.parquet")
                cols = list(zip(*rows)) if rows else [
                    [] for _ in schema.names]
                # from_arrays, not pa.table(dict): output column names can
                # legally repeat (two unaliased identical expressions) and a
                # dict would silently drop all but one
                pq.write_table(
                    pa.Table.from_arrays(
                        [pa.array(list(c), type=t.type)
                         for t, c in zip(schema, cols)],
                        schema=schema), run)
                runs.append(run)

        def run_iter(path):
            for batch in pq.ParquetFile(path).iter_batches(merge_batch):
                yield from _batch_rows(batch)

        yield from heapq.merge(*(run_iter(r) for r in runs), key=key)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def compare(expected, actual, epsilon: float = DEFAULT_EPSILON) -> bool:
    """Scalar compare with float epsilon and NaN == NaN (ref :194-215);
    Decimal (scaled-int64 decimal outputs) compares under the same epsilon
    as float, matching the reference's Decimal handling (ref :203-210)."""
    import decimal
    if expected is None or actual is None:
        return expected is None and actual is None
    if isinstance(expected, decimal.Decimal) or \
            isinstance(actual, decimal.Decimal):
        expected, actual = float(expected), float(actual)
    if isinstance(expected, float) or isinstance(actual, float):
        fe, fa = float(expected), float(actual)
        if math.isnan(fe) or math.isnan(fa):
            return math.isnan(fe) and math.isnan(fa)
        if fe == fa:
            return True
        denom = max(abs(fe), abs(fa), 1e-30)
        return abs(fe - fa) / denom < epsilon or abs(fe - fa) < epsilon
    return expected == actual


def _ratio_column_index(names: list[str]) -> int | None:
    for i, n in enumerate(names):
        if "ratio" in n.lower():
            return i
    return None


def row_equal(row_e, row_a, query_name: str, names: list[str],
              use_floats: bool = True) -> bool:
    # tolerance carve-outs match the reference validator exactly (q65 skip,
    # q67-float skip, q78 ratio +-0.01001; nds/nds_validate.py:146-164,
    # 231-244). In the exact-decimal configuration q49 needs none:
    # rank-feeding divisions order by exact rational keys on every backend
    # (planner._exact_rational_keys). The FLOAT configuration keeps a +-1
    # rank slack for q49 — there decimals bind as f64 and the rank keys
    # are emulated-f64 divisions whose exact ties can flip 1 ULP, the same
    # failure class the reference skips q67 floats for.
    ratio_idx = _ratio_column_index(names) if query_name.startswith("query78") \
        else None
    rank_cols = {i for i, n in enumerate(names) if n.lower().endswith("rank")} \
        if use_floats and query_name.startswith("query49") else set()
    for i, (e, a) in enumerate(zip(row_e, row_a)):
        if i in rank_cols and isinstance(e, int) and isinstance(a, int):
            if abs(e - a) > 1:
                return False
            continue
        eps = Q78_EPSILON if i == ratio_idx else DEFAULT_EPSILON
        if not compare(e, a, eps):
            return False
    return True


def compare_results(path_expected: str, path_actual: str, query_name: str,
                    ignore_ordering: bool = False,
                    epsilon: float = DEFAULT_EPSILON,
                    use_floats: bool = True) -> bool:
    fe = _output_files(os.path.join(path_expected, query_name))
    fa = _output_files(os.path.join(path_actual, query_name))
    if fe is None or fa is None:
        print(f"{query_name}: missing output "
              f"(expected={fe is not None}, actual={fa is not None})")
        return False
    ne, na = _output_rowcount(fe), _output_rowcount(fa)
    if ne != na:
        print(f"{query_name}: row count differs {ne} vs {na}")
        return False
    names = pq.ParquetFile(fe[0]).schema_arrow.names
    rows_e = iter_output_rows(fe, ignore_ordering)
    rows_a = iter_output_rows(fa, ignore_ordering)
    for i, (re_, ra) in enumerate(zip(rows_e, rows_a)):
        if not row_equal(re_, ra, query_name, names, use_floats):
            print(f"{query_name}: row {i} differs\n  e: {re_}\n  a: {ra}")
            return False
    return True


def iterate_queries(path_expected: str, path_actual: str,
                    query_names: list[str], ignore_ordering: bool = False,
                    use_floats: bool = True) -> dict[str, str]:
    """Compare every query; returns {name: Pass|Fail|NotAttempted}."""
    status: dict[str, str] = {}
    for name in query_names:
        base = name.split("_part")[0]
        if base in SKIP_ALWAYS or (use_floats and base in SKIP_WITH_FLOATS):
            status[name] = "NotAttempted"
            continue
        ok = compare_results(path_expected, path_actual, name,
                             ignore_ordering, use_floats=use_floats)
        status[name] = "Pass" if ok else "Fail"
    return status


def update_summary(json_summary_folder: str, status: dict[str, str]) -> None:
    """Write queryValidationStatus into the power-run JSON summaries
    (reference :262-296)."""
    for path in glob.glob(os.path.join(json_summary_folder, "power-*.json")):
        base = os.path.basename(path)
        parts = base.split("-")
        if len(parts) < 3:
            continue
        qname = "-".join(parts[1:-1])
        if qname not in status:
            continue
        with open(path) as f:
            summary = json.load(f)
        summary["queryValidationStatus"] = [status[qname]]
        with open(path, "w") as f:
            json.dump(summary, f, indent=2)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="nds_tpu.validate")
    p.add_argument("expected", help="output dir of the oracle run")
    p.add_argument("actual", help="output dir of the device run")
    p.add_argument("query_stream_file")
    p.add_argument("--ignore_ordering", action="store_true")
    p.add_argument("--json_summary_folder", default=None)
    p.add_argument("--use_decimal", action="store_true",
                   help="affects only the q67 skip policy")
    a = p.parse_args(argv)
    with open(a.query_stream_file) as f:
        names = list(gen_sql_from_stream(f.read()))
    status = iterate_queries(a.expected, a.actual, names, a.ignore_ordering,
                             use_floats=not a.use_decimal)
    if a.json_summary_folder:
        update_summary(a.json_summary_folder, status)
    failed = [n for n, s in status.items() if s == "Fail"]
    for n, s in status.items():
        print(f"{n}: {s}")
    print(f"{len([s for s in status.values() if s == 'Pass'])} passed, "
          f"{len(failed)} failed, "
          f"{len([s for s in status.values() if s == 'NotAttempted'])} skipped")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
