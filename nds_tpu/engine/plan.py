"""Bound logical plan and expression IR.

The planner resolves every name to a column *position* in its input relation,
so self-joins and alias shadowing are settled before execution. Plan nodes are
relational; bound expressions are positional trees the expression evaluator
turns into vectorized JAX/numpy compute.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


# --------------------------------------------------------------------------
# bound expressions
# --------------------------------------------------------------------------

@dataclass
class BExpr:
    dtype: str  # "int" | "float" | "bool" | "date" | "str"


@dataclass
class BCol(BExpr):
    index: int
    name: str = ""


@dataclass
class BLit(BExpr):
    value: object  # python int/float/str/bool/None; date as epoch-days int


@dataclass
class BCall(BExpr):
    op: str
    args: list[BExpr] = field(default_factory=list)
    extra: object = None  # op-specific payload (e.g. cast target, like pattern)


@dataclass
class BParam(BExpr):
    """A hoisted literal: slot `index` of the execution's parameter vector.

    Stream-generated statements differ only in template parameter literals
    (reference dsqgen substitution, nds/nds_gen_query_stream.py:42-89);
    hoisting them out of the plan makes the compiled XLA program identical
    across streams/seeds, so the persistent compile cache serves every
    stream after the first (the Spark analog: re-planning is milliseconds,
    nds/nds_power.py:124-134)."""
    index: int


@dataclass
class BScalarSubquery(BExpr):
    plan: "PlanNode"


@dataclass
class AggSpec:
    func: str                 # sum, count, count_star, avg, min, max, stddev_samp
    arg: Optional[BExpr]      # None for count(*)
    distinct: bool = False
    name: str = ""

    @property
    def dtype(self) -> str:
        if self.func in ("count", "count_star"):
            return "int"
        if self.func in ("avg", "stddev_samp"):
            return "float"
        return self.arg.dtype if self.arg is not None else "int"


@dataclass
class SortKey:
    expr: BExpr
    asc: bool = True
    nulls_first: Optional[bool] = None  # None => Spark default (asc: first, desc: last)


@dataclass
class WindowFunc:
    func: str                     # rank, dense_rank, row_number, sum, avg, min, max, count
    arg: Optional[BExpr]
    partition_by: list[BExpr]
    order_by: list[SortKey]
    name: str = ""

    @property
    def dtype(self) -> str:
        if self.func in ("rank", "dense_rank", "row_number", "count"):
            return "int"
        if self.func == "avg":
            return "float"
        return self.arg.dtype if self.arg is not None else "int"


# --------------------------------------------------------------------------
# plan nodes — every node exposes `out_names`/`out_dtypes` for its output
# --------------------------------------------------------------------------

@dataclass
class PlanNode:
    out_names: list[str] = field(default_factory=list, kw_only=True)
    out_dtypes: list[str] = field(default_factory=list, kw_only=True)


@dataclass
class ScanNode(PlanNode):
    table: str
    columns: list[str]  # physical columns to read, in output order
    # per-column physical upload lane (device.plan_lanes tags) for packed
    # morsel scans; None = layout decided by the executor (in-core scans).
    # Width metadata so the plan verifier can prove every lane wide enough
    # for its column's value range BEFORE a morsel ships on it.
    lanes: Optional[tuple] = None
    # per-column wire encoding tags ("plain" | ("dict", card) |
    # ("rle", runs_bound), device.plan_encodings) for packed morsel scans;
    # None = all plain. Encoding metadata so the verifier can prove each
    # spec legal against recorded cardinality/run stats (the "encoding"
    # findings), and so program fingerprints include the physical encoding.
    encodings: Optional[tuple] = None


@dataclass
class FilterNode(PlanNode):
    child: PlanNode
    predicate: BExpr


@dataclass
class ProjectNode(PlanNode):
    child: PlanNode
    exprs: list[BExpr]


@dataclass
class JoinNode(PlanNode):
    left: PlanNode
    right: PlanNode
    kind: str                 # inner, left, right, full, cross, semi, anti
    left_keys: list[BExpr] = field(default_factory=list)
    right_keys: list[BExpr] = field(default_factory=list)
    residual: Optional[BExpr] = None  # extra non-equi condition, over combined schema
    null_aware: bool = False  # NOT IN semantics for anti joins
    # late materialization (planner._late_materialization): this join gathers
    # dimension attributes AFTER aggregation against a unique-key build side.
    # The flag is an annotation (execution is a plain inner join); it blocks
    # re-application of the rewrite and makes rewritten plans inspectable.
    late_mat: bool = False


@dataclass
class AggregateNode(PlanNode):
    child: PlanNode
    group_exprs: list[BExpr] = field(default_factory=list)
    aggs: list[AggSpec] = field(default_factory=list)
    rollup: bool = False
    # compile segmentation may split a rollup into per-level units: an
    # explicit subset of rollup prefix lengths to emit (None = all levels
    # when rollup, else just the full grouping)
    rollup_levels: Optional[list[int]] = None
    # output: group cols, then agg cols, then (if rollup) int col "__grouping_id"


@dataclass
class WindowNode(PlanNode):
    child: PlanNode
    funcs: list[WindowFunc] = field(default_factory=list)
    # output: child cols, then one col per window func


@dataclass
class SortNode(PlanNode):
    child: PlanNode
    keys: list[SortKey] = field(default_factory=list)


@dataclass
class LimitNode(PlanNode):
    child: PlanNode
    n: int = 0


@dataclass
class DistinctNode(PlanNode):
    child: PlanNode


@dataclass
class SetOpNode(PlanNode):
    op: str    # union, intersect, except
    all: bool
    left: PlanNode
    right: PlanNode


@dataclass
class MaterializedNode(PlanNode):
    """An already-computed table injected into the plan (CTE results, views)."""
    table: object  # engine.column.Table
    label: str = ""


@dataclass
class VirtualScanNode(PlanNode):
    """A scan whose table is the output of another compile unit (a segmented
    CTE): the device executor resolves `key` against its segment cache, so a
    pathologically large plan splits into bounded XLA programs that hand
    device-resident tables to each other (reference analog: Spark reuses one
    compiled plan per query and materializes nothing, nds/nds_power.py:124-134
    — here bounded compile time requires the cut)."""
    key: str
    label: str = ""


def column_view(child: PlanNode, indices: list[int], out_names: list[str],
                out_dtypes: list[str]) -> "ProjectNode":
    """A pure column-selection projection over `child` (BCol references
    only): both executors evaluate it as column picking with no data
    movement — inside a compiled device program the selection fuses away
    entirely. Shared-scan morsel fusion builds these to hand each branch
    its pruned subset of the staged union-column buffer as zero-copy
    views."""
    return ProjectNode(
        child,
        [BCol(child.out_dtypes[i], i, n)
         for i, n in zip(indices, out_names)],
        out_names=list(out_names), out_dtypes=list(out_dtypes))


def walk(node: PlanNode):
    """Pre-order traversal of the child/left/right plan structure, memoized
    on node identity: a shared subtree (CTE DAG) yields ONCE, so traversal
    is linear in the number of distinct nodes instead of exponential in the
    sharing depth (a q14-class WITH clause consumed k times at d nesting
    levels would otherwise expand k^d visits)."""
    seen: set[int] = set()
    stack: list[PlanNode] = [node]
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        yield n
        # push right-to-left so pre-order (child first) is preserved
        for f in ("right", "left", "child"):
            sub = getattr(n, f, None)
            if isinstance(sub, PlanNode):
                stack.append(sub)


_FIELD_CACHE: dict[type, tuple] = {}


def type_fields(x) -> tuple:
    """Dataclass field names of x's type, cached per type (dataclasses.
    fields() re-resolves per call; plan traversal is hot enough to care)."""
    import dataclasses as _dc

    t = type(x)
    names = _FIELD_CACHE.get(t)
    if names is None:
        names = tuple(f.name for f in _dc.fields(t))
        _FIELD_CACHE[t] = names
    return names


def iter_plan_nodes(root: PlanNode):
    """Every distinct PlanNode reachable from `root`, INCLUDING plans embedded
    in expressions (BScalarSubquery) — shared nodes (CTE DAG) yield once.
    Traversal memoizes on object identity for EVERY dataclass (plan nodes
    and expression trees alike), so shared-DAG plans walk in linear time."""
    import dataclasses as _dc

    seen: set[int] = set()
    stack: list = [root]
    while stack:
        x = stack.pop()
        if isinstance(x, PlanNode):
            if id(x) in seen:
                continue
            seen.add(id(x))
            yield x
            if isinstance(x, MaterializedNode):
                continue      # its Table payload holds no plan nodes
        elif isinstance(x, (BCol, BLit, BParam)):
            continue          # leaf expressions hold no plan nodes
        if _dc.is_dataclass(x) and not isinstance(x, type):
            if not isinstance(x, PlanNode):
                if id(x) in seen:
                    continue
                seen.add(id(x))
            for name in type_fields(x):
                v = getattr(x, name)
                if v is not None and not isinstance(v, (str, int, float,
                                                        bool)):
                    stack.append(v)
        elif isinstance(x, (list, tuple)):
            stack.extend(v for v in x
                         if v is not None and
                         not isinstance(v, (str, int, float, bool)))


# ops whose handlers consume literal arguments as traced device scalars —
# a literal under any OTHER op (substr positions, LIKE patterns, cast
# payloads, string work) may be read on the host at trace time and must
# stay baked into the program
_PARAM_SAFE_OPS = frozenset({
    "add", "sub", "mul", "div", "mod", "neg", "eq", "ne", "lt", "le", "gt",
    "ge", "and", "or", "not", "case", "coalesce", "nullif", "in_list", "abs",
})


def _param_hoistable(lit: "BLit") -> bool:
    return lit.value is not None and (
        lit.dtype in ("int", "float", "date", "bool")
        or lit.dtype.startswith("dec"))


def parameterize_plan(root: PlanNode) -> tuple[PlanNode, list, list]:
    """Hoist numeric/date/decimal/bool literals into parameter slots.

    Returns (rewritten plan, values, dtypes): every hoisted BLit becomes a
    BParam(index) and its value/dtype land at that index. Only
    literals in _PARAM_SAFE_OPS argument positions hoist; traversal order
    is deterministic, so two stream-instantiations of one template yield
    THE SAME rewritten plan with different `values` — and therefore the
    same compiled program (see BParam). Node sharing (CTE DAGs) is
    preserved."""
    import dataclasses as _dc

    values: list = []
    dtypes: list = []
    memo: dict[int, object] = {}

    def rw_expr(e, safe_parent: bool):
        if isinstance(e, BLit):
            if safe_parent and _param_hoistable(e):
                values.append(e.value)
                dtypes.append(e.dtype)
                return BParam(e.dtype, index=len(values) - 1)
            return e
        if isinstance(e, BCall):
            safe = e.op in _PARAM_SAFE_OPS
            args = [rw_expr(a, safe) for a in e.args]
            extra = e.extra
            # IN-list values ride in `extra` as a host list; int/date items
            # hoist as params (the device handler resolves BParam entries)
            if e.op == "in_list" and isinstance(extra, list) and \
                    args and args[0].dtype in ("int", "date"):
                new_extra = []
                for v in extra:
                    # only EXACT ints hoist against an int/date probe: a
                    # non-integral item (1.5) matches nothing under float
                    # promotion, but an int-dtype param cast would truncate
                    # it into a spurious match
                    if isinstance(v, bool) or not isinstance(v, int):
                        new_extra.append(v)
                    else:
                        values.append(v)
                        dtypes.append(args[0].dtype)
                        new_extra.append(BParam(args[0].dtype,
                                                index=len(values) - 1))
                if any(isinstance(v, BParam) for v in new_extra):
                    extra = new_extra
            if extra is e.extra and all(
                    a is b for a, b in zip(args, e.args)):
                return e
            return _dc.replace(e, args=args, extra=extra)
        if isinstance(e, BScalarSubquery):
            p = rw_plan(e.plan)
            return e if p is e.plan else _dc.replace(e, plan=p)
        return e

    def rw_other(x):
        if isinstance(x, BExpr):
            return rw_expr(x, False)
        if isinstance(x, list):
            out = [rw_other(v) for v in x]
            return out if any(a is not b for a, b in zip(out, x)) else x
        if isinstance(x, tuple):
            out = tuple(rw_other(v) for v in x)
            return out if any(a is not b for a, b in zip(out, x)) else x
        if _dc.is_dataclass(x) and not isinstance(x, type) \
                and not isinstance(x, PlanNode):
            changes = {}
            for f in _dc.fields(x):
                v = getattr(x, f.name)
                nv = rw_other(v)
                if nv is not v:
                    changes[f.name] = nv
            return _dc.replace(x, **changes) if changes else x
        return x

    def rw_plan(node):
        if id(node) in memo:
            return memo[id(node)]
        if isinstance(node, MaterializedNode):
            memo[id(node)] = node
            return node
        changes = {}
        for f in _dc.fields(node):
            v = getattr(node, f.name)
            nv = rw_plan(v) if isinstance(v, PlanNode) else rw_other(v)
            if nv is not v:
                changes[f.name] = nv
        out = _dc.replace(node, **changes) if changes else node
        memo[id(node)] = out
        return out

    return rw_plan(root), values, dtypes


def deparameterize_plan(root: PlanNode, values: list) -> PlanNode:
    """Substitute parameter values back as literals (host-fallback plans:
    the numpy expression engine evaluates literals, not parameter slots)."""
    import dataclasses as _dc

    memo: dict[int, object] = {}

    def rw(x):
        if isinstance(x, BParam):
            return BLit(x.dtype, values[x.index])
        if isinstance(x, BCall):
            args = rw(x.args)
            extra = x.extra
            if isinstance(extra, list) and \
                    any(isinstance(v, BParam) for v in extra):
                # in_list extras hold RAW python values, not BLit nodes
                extra = [values[v.index] if isinstance(v, BParam) else v
                         for v in extra]
            if args is x.args and extra is x.extra:
                return x
            return _dc.replace(x, args=args, extra=extra)
        if isinstance(x, MaterializedNode):
            return x
        if _dc.is_dataclass(x) and not isinstance(x, type):
            if id(x) in memo:
                return memo[id(x)]
            changes = {}
            for f in _dc.fields(x):
                v = getattr(x, f.name)
                nv = rw(v)
                if nv is not v:
                    changes[f.name] = nv
            out = _dc.replace(x, **changes) if changes else x
            memo[id(x)] = out
            return out
        if isinstance(x, list):
            out = [rw(v) for v in x]
            return out if any(a is not b for a, b in zip(out, x)) else x
        if isinstance(x, tuple):
            out = tuple(rw(v) for v in x)
            return out if any(a is not b for a, b in zip(out, x)) else x
        return x

    return rw(root)


def replace_plan_nodes(root, mapping: dict):
    """Functionally rewrite a plan DAG, substituting nodes by identity:
    mapping[id(node)] -> replacement. Untouched shared subtrees keep their
    identity (executor memoization still dedupes them); expression-embedded
    plans (BScalarSubquery) are rewritten too."""
    import dataclasses as _dc

    memo: dict[int, object] = {}

    def rw(x):
        if isinstance(x, PlanNode) and id(x) in mapping:
            return mapping[id(x)]
        if isinstance(x, MaterializedNode):
            return x          # leaf: its Table payload holds no plan nodes
        if _dc.is_dataclass(x) and not isinstance(x, type):
            if id(x) in memo:
                return memo[id(x)]
            changes = {}
            for f in _dc.fields(x):
                v = getattr(x, f.name)
                nv = rw(v)
                if nv is not v:
                    changes[f.name] = nv
            out = _dc.replace(x, **changes) if changes else x
            memo[id(x)] = out
            return out
        if isinstance(x, list):
            nl = [rw(e) for e in x]
            return nl if any(a is not b for a, b in zip(nl, x)) else x
        if isinstance(x, tuple):
            nt = tuple(rw(e) for e in x)
            return nt if any(a is not b for a, b in zip(nt, x)) else x
        return x

    return rw(root)
