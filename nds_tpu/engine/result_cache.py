"""Semantic result cache: cross-client reuse, subsumption proofs, IVM.

PR 10's service deduplicates only parameter-IDENTICAL in-flight tickets
(the locked result cell); every repeat dashboard load still replans and
re-executes. This module adds the next three reuse tiers, each opt-in and
each bit-identical to recompute by construction:

- **Exact tier** — a capacity-bounded LRU of finished results keyed by
  parameterized-plan fingerprint (``executor.shared_fingerprint`` /
  ``executor._plan_fingerprint``) + parameter vector + backend. The
  service consults it at ADMISSION through a text alias map, so a repeat
  dashboard load touches neither a planner thread nor the device lane.
  Entries are invalidated by the per-table catalog generations of the
  base tables the plan scans (``Session.table_generation`` — registering
  table A never evicts results over table B) and an optional TTL.
- **Subsumption tier** — when a new ticket's plan differs from a cached
  entry only by a provably-narrower filter/date-window over the SAME
  group keys, the answer is computed by re-filtering the cached coarser
  aggregate on host: no scan, no upload. The proof machinery is the PR 4
  verifier's structural fingerprint (``verify.plan_fingerprint``): two
  texts of one template parameterize to the same plan, so containment
  reduces to per-slot value comparisons over comparison conjuncts whose
  column side is structurally one of the aggregate's group keys that
  survives to the output. Any failure of the proof falls back to normal
  execution.
- **Incremental view maintenance** — entries for decomposable aggregates
  store the mergeable partial state ``streaming._decompose`` /
  ``_final_builder`` already define. ``Session._insert``/``_delete``
  publish per-table row deltas after each LF_*/DF_* statement commits,
  and ``apply_delta`` UPDATES the partials (merge inserted-row partials
  through the partial-schema-preserving combine plan; recompute only the
  delta-touched groups for deletes) instead of invalidating — dashboards
  stay warm across maintenance rounds. Bit-identity discipline: only
  partials whose merged columns are order-insensitive (int/date/scaled-
  decimal sums, min/max, counts) are IVM-eligible; float sums (f64
  decimal mode) fall back to invalidation, because re-associated float
  addition cannot promise the recompute hash.

Every tier counts through the metrics registry (``result_cache_*``) and
records flight events, so cache behavior is observable in the same
artifacts as the rest of the service.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, replace
from typing import Optional

import pyarrow as pa

from ..obs import metrics as _metrics
from ..obs.flight import FLIGHT
from ..obs.trace import TRACER
from . import plan as P
from . import streaming
from .column import is_dec
from .executor import Executor
from .verify import plan_fingerprint


@dataclass
class ResultCacheConfig:
    """Knobs of one ResultCache (mirrored on EngineConfig for property-
    file parity; ServiceConfig.result_cache takes this object directly)."""
    #: cached entries before LRU eviction
    entries: int = 256
    #: seconds before an entry expires (0 = no TTL)
    ttl_s: float = 0.0
    #: prove narrower filters against cached coarser aggregates
    subsumption: bool = False
    #: keep mergeable partial state and absorb LF_*/DF_* deltas
    ivm: bool = False
    #: cached entries of one template tried per subsumption lookup
    subsumption_candidates: int = 8

    @classmethod
    def from_engine(cls, cfg) -> "ResultCacheConfig":
        return cls(entries=cfg.result_cache_entries,
                   ttl_s=cfg.result_cache_ttl_s,
                   subsumption=cfg.result_cache_subsumption,
                   ivm=cfg.result_cache_ivm)


@dataclass
class CacheHit:
    """One answered lookup: the materialized result + which tier served."""
    table: object            # engine.column.Table (read-only, shared)
    kind: str                # "exact" | "subsumed"


# ---------------------------------------------------------------------------
# template analysis (per parameterized-plan fingerprint, memoized)
# ---------------------------------------------------------------------------

@dataclass
class _Slot:
    """One subsumable parameter slot: a comparison conjunct whose column
    side is group key `out_col` of the final output."""
    kind: str                # "lower" (ge/gt) | "upper" (le/lt) | "point"
    op: str                  # canonicalized op with the column on the left
    out_col: int             # final-output column position of the group key
    col_dtype: str
    param_dtype: str


@dataclass
class _InSet:
    """One subsumable IN-list conjunct with hoisted parameter slots."""
    slots: tuple             # parameter slot indices inside the list
    literals: tuple          # non-hoisted list values
    out_col: int
    col_dtype: str


class _TemplateInfo:
    """Structure-only facts about one template (same for every parameter
    vector): subsumption slot map, the cross-length subsumption FAMILY
    key (recognized IN-list extras and parameter indices normalized, so
    ``IN (a, b, c)`` and ``IN (a, b)`` land in one family), and IVM
    eligibility."""
    __slots__ = ("subsumable", "slots", "insets", "family_key", "ivm_ok")

    def __init__(self):
        self.subsumable = False
        self.slots: dict[int, _Slot] = {}
        self.insets: list[_InSet] = []
        self.family_key: Optional[str] = None
        self.ivm_ok = False

    def reduce(self, pvalues: tuple):
        """Split one parameter vector into (non-inset values in slot
        order, per-inset value frozensets, non-inset slot order). Two
        plans of one family align POSITIONALLY on the reduced vector —
        outside the recognized IN lists their structures are identical,
        and parameterize_plan numbers slots in traversal order."""
        inset_idx = {i for s in self.insets for i in s.slots}
        order = [i for i in range(len(pvalues)) if i not in inset_idx]
        reduced = tuple(pvalues[i] for i in order)
        sets = tuple(frozenset(s.literals)
                     | {pvalues[j] for j in s.slots} for s in self.insets)
        return reduced, sets, order


def _conjuncts(e):
    if isinstance(e, P.BCall) and e.op == "and":
        for a in e.args:
            yield from _conjuncts(a)
    else:
        yield e


def _has_params(x) -> bool:
    stack = [x]
    while stack:
        v = stack.pop()
        if isinstance(v, P.BParam):
            return True
        if isinstance(v, P.BCall):
            stack.extend(v.args)
            if isinstance(v.extra, list):
                stack.extend(v.extra)
        elif isinstance(v, P.BScalarSubquery):
            return True       # conservatively opaque: subplan literals
    return False


def _param_counts(pplan) -> dict[int, int]:
    """How many places each parameter slot appears in — a slot consumed
    anywhere beyond its one recognized conjunct is opaque (re-filtering
    the output would not reproduce its other effect)."""
    counts: dict[int, int] = {}
    seen: set[int] = set()
    stack: list = [pplan]
    while stack:
        x = stack.pop()
        if isinstance(x, P.BParam):
            counts[x.index] = counts.get(x.index, 0) + 1
            continue
        if x is None or isinstance(x, (str, int, float, bool)):
            continue
        if dataclasses.is_dataclass(x) and not isinstance(x, type):
            if id(x) in seen:
                continue
            seen.add(id(x))
            if isinstance(x, P.MaterializedNode):
                continue
            for name in P.type_fields(x):
                stack.append(getattr(x, name))
        elif isinstance(x, (list, tuple)):
            stack.extend(x)
    return counts


def _parent_counts(root) -> dict[int, int]:
    counts: dict[int, int] = {}
    for n in P.iter_plan_nodes(root):
        for f in ("child", "left", "right"):
            sub = getattr(n, f, None)
            if isinstance(sub, P.PlanNode):
                counts[id(sub)] = counts.get(id(sub), 0) + 1
        for sub in streaming._expr_subplans(n):
            counts[id(sub)] = counts.get(id(sub), 0) + 1
    return counts


def _subst_cols(e, exprs):
    """Push an expression through a ProjectNode: every BCol reference is
    replaced by the projection's defining expression (composition), so a
    group key keeps one structural identity all the way down to the
    filter's schema."""
    if isinstance(e, P.BCol):
        return exprs[e.index]
    if isinstance(e, P.BCall):
        return replace(e, args=[_subst_cols(a, exprs) for a in e.args])
    return e


_FLIP = {"ge": "le", "gt": "lt", "le": "ge", "lt": "gt", "eq": "eq"}


def _order_safe_partials(recipes, p_dtypes) -> bool:
    """May these partials be re-associated (merged with a delta, or kept
    while sibling groups recompute) and still hash-match a cold
    recompute? min/max and exact-integer sums are order-insensitive;
    float sums are not (f64 addition does not re-associate bit-stably)."""
    for kind, idxs in recipes:
        if kind in ("min", "max"):
            continue
        for j in idxs:
            d = p_dtypes[j]
            if not (d in ("int", "date") or is_dec(d)):
                return False
    return True


def _analyze_template(pplan) -> _TemplateInfo:
    """Structure-only analysis of one parameterized plan: which parameter
    slots are subsumable (comparison conjuncts over output-surviving
    group keys) and whether the shape supports IVM partial state."""
    info = _TemplateInfo()
    path, agg = streaming._path_to_aggregate(pplan)
    if agg is None:
        return info
    mergeable = streaming._mergeable(agg)
    if mergeable and not agg.rollup:
        try:
            _specs, recipes, _pn, p_dtypes = streaming._decompose(agg)
        except Exception:
            recipes = None
        if recipes is not None and _order_safe_partials(recipes, p_dtypes):
            info.ivm_ok = True
    if not mergeable:
        return info
    # subsumption shape: only order/projection above the aggregate (a
    # LIMIT would have truncated groups the narrower query still needs; a
    # HAVING/window above could consume the differing parameters)
    if any(not isinstance(n, (P.SortNode, P.ProjectNode)) for n in path):
        return info
    # where does each group key land in the FINAL output?
    pos = {i: i for i in range(len(agg.group_exprs))}
    for node in reversed(path):          # nearest-to-aggregate first
        if isinstance(node, P.SortNode):
            continue
        new_pos: dict[int, int] = {}
        inv = {p: g for g, p in pos.items()}
        for j, e in enumerate(node.exprs):
            if isinstance(e, P.BCol) and e.index in inv:
                new_pos[inv[e.index]] = j
        pos = new_pos
    if not pos:
        return info
    # the filter chain under the aggregate must be exclusively owned by
    # it: a shared (CTE) subtree narrowed here would also narrow some
    # other consumer the re-filter cannot see
    parents = _parent_counts(pplan)
    counts = _param_counts(pplan)
    node = agg.child
    cur = list(agg.group_exprs)          # group exprs in `node`'s schema
    memo: dict[int, int] = {}
    recognized: set[int] = set()         # ids of recognized inset BCalls
    while True:
        if parents.get(id(node), 0) > 1:
            return info
        if isinstance(node, P.FilterNode):
            fps = [plan_fingerprint(e, memo) for e in cur]
            for conj in _conjuncts(node.predicate):
                _classify_conjunct(conj, fps, pos, counts, info, memo,
                                   recognized)
            node = node.child
        elif isinstance(node, P.ProjectNode):
            cur = [_subst_cols(e, node.exprs) for e in cur]
            node = node.child
        else:
            break
    info.subsumable = bool(info.slots or info.insets)
    if info.subsumable:
        info.family_key = _family_fingerprint(pplan, recognized)
    return info


def _family_fingerprint(pplan, recognized: set[int]) -> str:
    """The cross-length subsumption family: fingerprint of the plan with
    every parameter index normalized and every RECOGNIZED group-key
    IN-list's member list collapsed to one token. Templates differing
    only in how many values those IN lists carry then share one family,
    while any other structural difference (including the lengths of
    UNrecognized IN lists) keeps them apart — positional slot pairing
    inside a family stays sound."""
    from .jax_backend.executor import _plan_fingerprint

    memo: dict[int, object] = {}

    def rw(x):
        if isinstance(x, P.BParam):
            return replace(x, index=-1)
        if isinstance(x, P.BCall):
            args = [rw(a) for a in x.args]
            if id(x) in recognized:
                extra = "<inset>"
            elif isinstance(x.extra, list):
                extra = [rw(v) if isinstance(v, P.BParam) else v
                         for v in x.extra]
            else:
                extra = x.extra
            return replace(x, args=args, extra=extra)
        if isinstance(x, P.MaterializedNode):
            return x
        if dataclasses.is_dataclass(x) and not isinstance(x, type):
            got = memo.get(id(x))
            if got is not None:
                return got
            out = replace(x, **{f: rw(getattr(x, f))
                                for f in P.type_fields(x)})
            memo[id(x)] = out
            return out
        if isinstance(x, list):
            return [rw(v) for v in x]
        if isinstance(x, tuple):
            return tuple(rw(v) for v in x)
        return x

    return _plan_fingerprint(rw(pplan))


def _classify_conjunct(conj, group_fps, pos, counts, info, memo,
                       recognized: set) -> None:
    if not isinstance(conj, P.BCall):
        return
    if conj.op in ("ge", "gt", "le", "lt", "eq") and len(conj.args) == 2:
        a, b = conj.args
        if isinstance(b, P.BParam) and not _has_params(a):
            col, prm, op = a, b, conj.op
        elif isinstance(a, P.BParam) and not _has_params(b):
            col, prm, op = b, a, _FLIP[conj.op]
        else:
            return
        if counts.get(prm.index, 0) != 1:
            return               # slot consumed elsewhere too: opaque
        g = _group_of(col, group_fps, memo)
        if g is None or g not in pos:
            return
        kind = ("lower" if op in ("ge", "gt")
                else "upper" if op in ("le", "lt") else "point")
        info.slots[prm.index] = _Slot(kind, op, pos[g], col.dtype,
                                      prm.dtype)
    elif conj.op == "in_list" and len(conj.args) == 1 \
            and isinstance(conj.extra, list):
        col = conj.args[0]
        if _has_params(col):
            return
        pslots = tuple(v.index for v in conj.extra
                       if isinstance(v, P.BParam))
        if not pslots or any(counts.get(i, 0) != 1 for i in pslots):
            return
        g = _group_of(col, group_fps, memo)
        if g is None or g not in pos:
            return
        inset = _InSet(pslots,
                       tuple(v for v in conj.extra
                             if not isinstance(v, P.BParam)),
                       pos[g], col.dtype)
        info.insets.append(inset)
        recognized.add(id(conj))


def _group_of(col_expr, group_fps, memo) -> Optional[int]:
    fp = plan_fingerprint(col_expr, memo)
    for g, gfp in enumerate(group_fps):
        if gfp == fp:
            return g
    return None


def _prove_containment(new_info: _TemplateInfo, new_pv: tuple,
                       cand_info: _TemplateInfo,
                       cand_pv: tuple) -> Optional[list]:
    """The containment proof, positional across one family: every
    differing non-inset slot must sit in a recognized comparison AND move
    in the narrowing direction; every recognized IN set must be a subset
    of the cached one. Returns the re-filter predicate pieces
    [(slot_or_inset, value(s))], or None when the new plan is not
    provably contained in the cached entry's."""
    n_red, n_sets, n_order = new_info.reduce(new_pv)
    c_red, c_sets, _c_order = cand_info.reduce(cand_pv)
    if len(n_red) != len(c_red) or len(n_sets) != len(c_sets):
        return None
    preds: list = []
    for pos, (nv, cv) in enumerate(zip(n_red, c_red)):
        if nv == cv:
            continue
        slot = new_info.slots.get(n_order[pos])
        if slot is None:
            return None              # opaque slot differs: no proof
        if slot.kind == "point":
            return None              # different equality: disjoint groups
        try:
            if slot.kind == "lower" and not nv >= cv:
                return None
            if slot.kind == "upper" and not nv <= cv:
                return None
        except TypeError:
            return None
        preds.append((slot, nv))
    for k, (ns, cs) in enumerate(zip(n_sets, c_sets)):
        if ns == cs:
            continue
        if not ns <= cs:
            return None              # widened membership: not contained
        preds.append((new_info.insets[k], sorted(ns)))
    return preds if preds else None


def _refilter(entry: "_Entry", preds: list):
    """Answer the narrower query from the cached coarser aggregate: apply
    the NEW parameter values' conjuncts to the cached FINAL rows on the
    group-key output columns. Each surviving group's aggregate was
    computed from exactly the rows the narrower plan would have seen
    (the filter is a pure function of the group key), so the result is
    bit-identical to recompute; filtering preserves the sort order."""
    names, dtypes = list(entry.out_names), list(entry.out_dtypes)
    pred = None
    for spec, val in preds:
        if isinstance(spec, _Slot):
            c = P.BCall("bool", spec.op,
                        [P.BCol(spec.col_dtype, spec.out_col,
                                names[spec.out_col]),
                         P.BLit(spec.param_dtype, val)])
        else:
            c = P.BCall("bool", "in_list",
                        [P.BCol(spec.col_dtype, spec.out_col,
                                names[spec.out_col])],
                        extra=list(val))
        pred = c if pred is None else P.BCall("bool", "and", [pred, c])
    mat = P.MaterializedNode(table=entry.result, label="result-cache",
                             out_names=names, out_dtypes=dtypes)
    filt = P.FilterNode(mat, pred, out_names=names, out_dtypes=dtypes)
    return Executor(_no_load).execute(filt)


def _no_load(*_a, **_k):
    raise RuntimeError("result-cache plans never scan tables")


# ---------------------------------------------------------------------------
# IVM state: mergeable partials + per-table probe-side scans
# ---------------------------------------------------------------------------

class _IvmState:
    """Everything needed to absorb a table delta into one entry: the
    aggregate's decomposition, its partial table, and — per base table —
    the unique probe-side scan a delta substitutes into."""
    __slots__ = ("agg", "path", "recipes", "p_names", "p_dtypes",
                 "partial_specs", "partial", "partial_plan",
                 "scan_by_table")

    def __init__(self, agg, path, partial_specs, recipes, p_names,
                 p_dtypes, partial, partial_plan, scan_by_table):
        self.agg = agg
        self.path = path
        self.partial_specs = partial_specs
        self.recipes = recipes
        self.p_names = p_names
        self.p_dtypes = p_dtypes
        self.partial = partial
        self.partial_plan = partial_plan
        self.scan_by_table = scan_by_table


def _probe_scan(subtree, table: str):
    """The unique scan of `table` on the probe spine of `subtree`, or
    None. Linearity requirement for delta merging: the aggregate must
    distribute over a row-union of this table — true when its single
    scan flows through filters/projections and the LEFT side of
    inner/left/semi/anti joins (a build-side delta changes every probe
    row's matches instead)."""
    scans = [n for n in P.iter_plan_nodes(subtree)
             if isinstance(n, P.ScanNode) and n.table == table]
    if len(scans) != 1:
        return None
    target = scans[0]

    def on_spine(node) -> bool:
        if node is target:
            return True
        if isinstance(node, (P.FilterNode, P.ProjectNode)):
            return on_spine(node.child)
        if isinstance(node, P.JoinNode) and node.kind in (
                "inner", "left", "semi", "anti"):
            if any(n is target for n in P.iter_plan_nodes(node.right)):
                return False
            return on_spine(node.left)
        return False

    return target if on_spine(subtree) else None


def _execute_plan(session, plan, use_jax: bool):
    """One-shot plan execution through the session's backend (key=None:
    the eager record path — nothing lands in the program caches)."""
    if use_jax:
        from .jax_backend import to_host
        with session._sql_lock:
            jexec = session._jax_executor()
            return to_host(jexec.run_query(None, lambda: plan))
    return Executor(session.load_table).execute(plan)


def _col_values(col):
    """(sorted unique non-null python values, has_null) of one engine
    column — the touched-group key sets a delete recompute filters by."""
    import numpy as np

    valid = np.asarray(col.validity, dtype=bool)
    has_null = bool((~valid).any())
    if col.dtype == "str":
        dec = col.decode()
        vals = sorted({dec[i] for i in np.flatnonzero(valid)})
    else:
        data = np.asarray(col.data)[valid]
        vals = sorted({v.item() for v in np.unique(data)})
    return vals, has_null


def plan_for_cache(session, sql: str, backend: Optional[str] = None):
    """Parse/plan/parameterize one text the way the service's planner
    stage does — shared so direct ResultCache.run callers and tests key
    identically to service tickets."""
    from ..sql import parse_sql
    from .planner import Planner

    cfg = session.config
    use_jax = (backend == "jax") if backend else cfg.use_jax
    plan = Planner(session._catalog()).plan_query(parse_sql(sql))
    streams = False
    if use_jax and cfg.out_of_core:
        jobs = streaming.find_streaming_jobs(
            plan, lambda t: session._est_rows.get(t, 0),
            cfg.out_of_core_min_rows)
        streams = bool(jobs)
    fp = None
    pvalues: tuple = ()
    if use_jax and not streams and cfg.jit_plans and not cfg.mesh_shape:
        from .jax_backend import pallas_kernels as _pk
        from .jax_backend.executor import shared_fingerprint
        pplan, pvals, pdts = P.parameterize_plan(plan)
        if pdts:
            fp = shared_fingerprint(pplan, cfg.shard_min_rows,
                                    _pk.parse_ops(cfg.pallas_ops))
            pvalues = tuple(pvals)
    return plan, fp, pvalues, use_jax


# ---------------------------------------------------------------------------
# the cache
# ---------------------------------------------------------------------------

class _Entry:
    __slots__ = ("key", "template_key", "family", "pvalues", "backend",
                 "result", "out_names", "out_dtypes", "tables", "gens",
                 "snaps", "stored_at", "plan", "ivm", "hits")

    def __init__(self, key, template_key, family, pvalues, backend,
                 result, out_names, out_dtypes, tables, gens, snaps,
                 stored_at, plan, ivm):
        self.hits = 0          # lookups served (system.result_cache)
        self.key = key
        self.template_key = template_key
        self.family = family
        self.pvalues = pvalues
        self.backend = backend
        self.result = result
        self.out_names = out_names
        self.out_dtypes = out_dtypes
        self.tables = tables
        self.gens = gens
        # per-table warehouse MANIFEST versions at store time (snapshot-
        # pinned registrations only; {} when unpinned): the provable
        # snapshot identity — a reader pinned to a different warehouse
        # version never gets this entry, even within one session
        self.snaps = snaps
        self.stored_at = stored_at
        self.plan = plan
        self.ivm = ivm


class ResultCache:
    """The semantic result cache over one Session (cross-client: every
    service client shares it). Thread-safe; the internal lock is never
    held across plan execution, so lookups stay cheap beside IVM work."""

    def __init__(self, session, config: Optional[ResultCacheConfig] = None):
        self.session = session
        self.config = config or ResultCacheConfig()
        self._lock = threading.RLock()
        self._entries: "OrderedDict" = OrderedDict()   # key -> _Entry (LRU)
        self._aliases: dict[tuple, tuple] = {}   # (sql, backend) -> key
        self._by_family: dict = {}        # subsumption family -> [key]
        self._templates: dict = {}        # template_key -> _TemplateInfo

    # -- keying --------------------------------------------------------------
    def _template_key(self, plan, fp, pvalues):
        """(template key, full parameter vector). fp=None plans (streamed
        / jit-off) key on the executor's sha1 structural fingerprint of
        the parameterized plan, with the parameter vector recomputed —
        two texts of one template must not collide on an empty vector."""
        if fp is not None:
            return fp, tuple(pvalues)
        from .jax_backend.executor import _plan_fingerprint
        pplan, pvals, _pdts = P.parameterize_plan(plan)
        return ("pfp", _plan_fingerprint(pplan)), tuple(pvals)

    def _template_info(self, template_key, plan) -> _TemplateInfo:
        with self._lock:
            info = self._templates.get(template_key)
        if info is not None:
            return info
        pplan, _v, _d = P.parameterize_plan(plan)
        info = _analyze_template(pplan)
        with self._lock:
            self._templates.setdefault(template_key, info)
            while len(self._templates) > 4 * max(self.config.entries, 1):
                self._templates.pop(next(iter(self._templates)))
        return info

    @staticmethod
    def _backend_tag(use_jax: bool) -> str:
        return "jax" if use_jax else "numpy"

    # -- validity ------------------------------------------------------------
    def _valid(self, entry: _Entry) -> bool:
        ttl = self.config.ttl_s
        if ttl > 0 and time.time() - entry.stored_at > ttl:
            return False
        gen = self.session.table_generation
        if not all(gen(t) == g for t, g in entry.gens.items()):
            return False
        # snapshot-stamped entries additionally require the READER's
        # pinned warehouse versions to match the entry's: the cached
        # result is served only to the exact snapshot it came from
        snap = self.session.table_snapshot_version
        return all(snap(t) == s for t, s in entry.snaps.items())

    def _drop_locked(self, key, reason: str) -> None:
        entry = self._entries.pop(key, None)
        if entry is None:
            return
        _metrics.RESULT_CACHE_INVALIDATIONS.inc()
        FLIGHT.record("cache_invalidate", reason=reason,
                      template=str(entry.template_key)[:12])

    def _check_locked(self, key) -> Optional[_Entry]:
        """Entry for `key` if currently valid; stale entries that IVM can
        still absorb are KEPT (a maintenance delta is about to re-stamp
        them), everything else stale is dropped + counted."""
        entry = self._entries.get(key)
        if entry is None:
            return None
        if self._valid(entry):
            self._entries.move_to_end(key)
            return entry
        if not (self.config.ivm and entry.ivm is not None):
            self._drop_locked(key, "stale")
        return None

    # -- lookups -------------------------------------------------------------
    def lookup_text(self, sql: str,
                    backend: Optional[str] = None) -> Optional[CacheHit]:
        """Admission-time probe: a text seen before maps straight to its
        entry — no parsing, no planning, no device. Misses are silent
        (the plan-level lookup gives the final verdict). The alias is
        backend-scoped: a numpy-oracle result never serves a jax query."""
        use_jax = (backend == "jax") if backend \
            else self.session.config.use_jax
        alias = (sql, self._backend_tag(use_jax))
        with self._lock:
            key = self._aliases.get(alias)
            if key is None:
                return None
            entry = self._check_locked(key)
            if entry is None:
                if key not in self._entries:
                    del self._aliases[alias]
                return None
            entry.hits += 1
        _metrics.RESULT_CACHE_HITS.inc()
        FLIGHT.record("cache_hit", tier="exact", via="text")
        return CacheHit(entry.result, "exact")

    def lookup_plan(self, sql: str, plan, fp, pvalues,
                    use_jax: bool = True) -> Optional[CacheHit]:
        """Plan-level probe: exact by (template, parameters, backend),
        then the subsumption proof against cached siblings of the same
        template. Counts the definitive hit/miss."""
        tag = self._backend_tag(use_jax)
        tk, pv = self._template_key(plan, fp, pvalues)
        key = (tk, pv, tag)
        with self._lock:
            entry = self._check_locked(key)
            if entry is not None:
                self._aliases[(sql, tag)] = key
                entry.hits += 1
        if entry is not None:
            _metrics.RESULT_CACHE_HITS.inc()
            FLIGHT.record("cache_hit", tier="exact", via="plan")
            return CacheHit(entry.result, "exact")
        if self.config.subsumption:
            hit = self._try_subsume(sql, plan, tk, pv, tag, key)
            if hit is not None:
                return hit
        _metrics.RESULT_CACHE_MISSES.inc()
        return None

    def _try_subsume(self, sql, plan, tk, pv, tag,
                     key) -> Optional[CacheHit]:
        info = self._template_info(tk, plan)
        if not info.subsumable or info.family_key is None:
            return None
        with self._lock:
            keys = self._by_family.get(info.family_key, [])
            keys[:] = [k for k in keys if k in self._entries]
            cands = []
            for k in reversed(keys):
                entry = self._entries.get(k)
                if entry is None or entry.backend != tag:
                    continue
                if not self._valid(entry):
                    continue
                cands.append(entry)
                if len(cands) >= self.config.subsumption_candidates:
                    break
        for cand in cands:
            cand_info = self._cand_info(cand)
            if cand_info is None:
                continue
            preds = _prove_containment(info, pv, cand_info, cand.pvalues)
            if preds is None:
                continue
            cand.hits += 1
            with TRACER.span("cache.subsume",
                             rows=cand.result.num_rows):
                table = _refilter(cand, preds)
            _metrics.RESULT_CACHE_SUBSUMPTION_HITS.inc()
            FLIGHT.record("cache_hit", tier="subsumed",
                          from_rows=cand.result.num_rows,
                          to_rows=table.num_rows)
            # the narrowed answer becomes its own exact entry (repeat
            # narrow loads skip the proof); it inherits the parent's
            # generation stamps and data age
            derived = _Entry(key, tk, info.family_key, pv, tag, table,
                             list(cand.out_names), list(cand.out_dtypes),
                             cand.tables, dict(cand.gens),
                             dict(cand.snaps), cand.stored_at, None, None)
            self._insert_entry(sql, derived)
            return CacheHit(table, "subsumed")
        return None

    def _cand_info(self, cand: _Entry) -> Optional[_TemplateInfo]:
        """A candidate's own analysis (its slot ORDER can differ from the
        probe's when IN-list lengths differ): memoized by template key;
        derived entries (plan=None) rely on the memo their creation
        populated."""
        with self._lock:
            got = self._templates.get(cand.template_key)
        if got is not None:
            return got
        if cand.plan is None:
            return None
        return self._template_info(cand.template_key, cand.plan)

    # -- store ---------------------------------------------------------------
    def store(self, sql: str, plan, fp, pvalues, result,
              use_jax: bool = True, gens: Optional[dict] = None) -> None:
        """Cache one finished execution. `gens` should be the per-table
        generation snapshot taken at DISPATCH time (a registration racing
        the store then correctly invalidates the entry); defaults to
        now. Failures degrade to not-caching, never to failing the query."""
        try:
            self._store(sql, plan, fp, pvalues, result, use_jax, gens)
        except Exception as e:   # caching is an optimization, never fatal
            FLIGHT.record("cache_store", status="failed",
                          error=type(e).__name__)

    def _store(self, sql, plan, fp, pvalues, result, use_jax, gens):
        session = self.session
        tables = sorted({n.table for n in P.iter_plan_nodes(plan)
                         if isinstance(n, P.ScanNode)})
        if any(t not in session._schemas for t in tables):
            return
        if any(isinstance(n, P.MaterializedNode) and n.table is not None
               for n in P.iter_plan_nodes(plan)):
            return               # payload tables have no generation identity
        tag = self._backend_tag(use_jax)
        tk, pv = self._template_key(plan, fp, pvalues)
        key = (tk, pv, tag)
        if gens is None:
            gens = {t: session.table_generation(t) for t in tables}
        # any registration between dispatch and store moved the gens (a
        # snapshot change always re-registers), so capturing snaps here
        # is race-free: a mismatch coincides with a gens mismatch that
        # already invalidates the entry
        snaps = {}
        for t in tables:
            sv = session.table_snapshot_version(t)
            if sv is not None:
                snaps[t] = sv
        ivm = None
        family = None
        info = self._template_info(tk, plan)
        if self.config.ivm and info.ivm_ok:
            ivm = self._capture_ivm(plan)
        if self.config.subsumption:
            family = info.family_key
        entry = _Entry(key, tk, family, pv, tag, result,
                       list(plan.out_names), list(plan.out_dtypes),
                       tables, gens, snaps, time.time(), plan, ivm)
        self._insert_entry(sql, entry)
        FLIGHT.record("cache_store", template=str(tk)[:12],
                      tables=",".join(tables), ivm=ivm is not None)

    def snapshot_gens(self, plan) -> dict:
        """Per-table generation snapshot for a later deferred store()."""
        gen = self.session.table_generation
        return {n.table: gen(n.table) for n in P.iter_plan_nodes(plan)
                if isinstance(n, P.ScanNode)}

    def export_snapshot(self) -> list:
        """Exact-tier export for CROSS-PROCESS sharing (the front door's
        ``cache_snapshot`` op): one dict per currently-valid entry that
        has a text alias — a client process keys its local cache on SQL
        text, having no planner of its own. Each item carries the full
        consistency identity beside the result: per-table catalog
        generations and warehouse snapshot versions exactly as stored,
        so the client can re-validate per lookup (the ``cache_validate``
        handshake) before trusting a warmed entry. Cut under the cache
        lock; results are the shared read-only Tables."""
        with self._lock:
            out = []
            seen = set()
            for (sql, tag), key in self._aliases.items():
                entry = self._entries.get(key)
                if entry is None or entry.result is None \
                        or not self._valid(entry) or key in seen:
                    continue
                seen.add(key)
                out.append({"sql": sql, "backend": tag,
                            "gens": dict(entry.gens),
                            "snaps": dict(entry.snaps),
                            "result": entry.result})
            return out

    def validate_stamps(self, gens: dict, snaps: dict) -> bool:
        """The invalidation handshake's server side: do these per-table
        generation/snapshot stamps still match the live session? Exactly
        the ``_valid`` test minus TTL — a client-held entry whose base
        table re-registered or whose warehouse snapshot moved answers
        False (the client must drop it), so N front-end processes can
        never serve a result the engine already invalidated."""
        gen = self.session.table_generation
        if not all(gen(t) == g for t, g in (gens or {}).items()):
            return False
        snap = self.session.table_snapshot_version
        return all(snap(t) == s for t, s in (snaps or {}).items())

    def snapshot_rows(self) -> list:
        """``system.result_cache`` rows: one per live entry, cut under
        the cache lock (entry id is a short stable digest of the full
        key — operators correlate rows across polls, not decode keys)."""
        import hashlib
        with self._lock:
            out = []
            for key, e in self._entries.items():
                digest = hashlib.sha1(repr(key).encode()).hexdigest()[:12]
                out.append({
                    "entry": digest,
                    "template": str(e.template_key)[:16],
                    "backend": e.backend,
                    "rows": e.result.num_rows
                    if e.result is not None else None,
                    "hits": e.hits,
                    "stored_at": round(e.stored_at, 3),
                    "tables": ",".join(e.tables) or None,
                    "ivm": e.ivm is not None})
            return out

    def _insert_entry(self, sql: str, entry: _Entry) -> None:
        with self._lock:
            self._entries[entry.key] = entry
            self._entries.move_to_end(entry.key)
            self._aliases[(sql, entry.backend)] = entry.key
            if entry.family is not None:
                bucket = self._by_family.setdefault(entry.family, [])
                if entry.key not in bucket:
                    bucket.append(entry.key)
            while len(self._entries) > max(1, self.config.entries):
                old_key, old = self._entries.popitem(last=False)
                ob = self._by_family.get(old.family) \
                    if old.family is not None else None
                if ob and old_key in ob:
                    ob.remove(old_key)
            if len(self._aliases) > 8 * max(1, self.config.entries):
                self._aliases = {s: k for s, k in self._aliases.items()
                                 if k in self._entries}

    def _capture_ivm(self, plan) -> Optional[_IvmState]:
        """Execute the partial aggregate (host backend: IVM partials are
        order-safe exact dtypes, so host == device bit-for-bit) and
        resolve each base table's probe-side scan."""
        path, agg = streaming._path_to_aggregate(plan)
        if agg is None:
            return None
        if any(isinstance(n, P.MaterializedNode)
               for n in P.iter_plan_nodes(agg.child)):
            return None
        partial_specs, recipes, p_names, p_dtypes = streaming._decompose(agg)
        partial_plan = P.AggregateNode(
            child=agg.child, group_exprs=list(agg.group_exprs),
            aggs=list(partial_specs), out_names=list(p_names),
            out_dtypes=list(p_dtypes))
        with TRACER.span("cache.ivm_capture", groups=len(agg.group_exprs)):
            partial = _execute_plan(self.session, partial_plan,
                                    use_jax=False)
        scan_by_table = {}
        for t in {n.table for n in P.iter_plan_nodes(agg.child)
                  if isinstance(n, P.ScanNode)}:
            sn = _probe_scan(agg.child, t)
            if sn is not None:
                scan_by_table[t] = sn
        return _IvmState(agg, path, partial_specs, recipes, p_names,
                         p_dtypes, partial, partial_plan, scan_by_table)

    # -- incremental view maintenance ----------------------------------------
    def apply_delta(self, table: str, inserts=None, deletes=None) -> None:
        """Absorb one maintenance statement's row delta (Session
        ``_publish_table_delta``): entries over `table` either UPDATE in
        place (mergeable partials + probe-side scan) or invalidate.
        Called after the warehouse commit re-registered the table, so the
        expected generation pattern is `table` at current-1 and every
        other base table unmoved."""
        session = self.session
        with self._lock:
            items = [(k, e) for k, e in self._entries.items()
                     if table in e.tables]
        for key, entry in items:
            new_entry = None
            try:
                new_entry = self._updated_entry(entry, table, inserts,
                                                deletes)
            except Exception as e:   # degradation: invalidate, observable
                FLIGHT.record("cache_ivm", status="failed", table=table,
                              error=type(e).__name__)
                new_entry = None
            with self._lock:
                if self._entries.get(key) is not entry:
                    continue          # replaced/evicted mid-flight
                if new_entry is None:
                    self._drop_locked(key, f"delta:{table}")
                else:
                    self._entries[key] = new_entry
            if new_entry is not None:
                _metrics.RESULT_CACHE_IVM_UPDATES.inc()
                FLIGHT.record("cache_ivm", status="updated", table=table,
                              template=str(entry.template_key)[:12])

    def _updated_entry(self, entry: _Entry, table: str, inserts,
                       deletes) -> Optional[_Entry]:
        session = self.session
        if not (self.config.ivm and entry.ivm is not None):
            return None
        gen = session.table_generation
        # exactly one statement behind on the delta table, current on the
        # rest — anything else means a delta was missed: invalidate
        for t, g in entry.gens.items():
            want = gen(t) - 1 if t == table else gen(t)
            if g != want:
                return None
        st = entry.ivm
        partial = st.partial
        if deletes is not None and deletes.num_rows:
            partial = self._ivm_delete(st, partial, table, deletes)
            if partial is None:
                return None
        if inserts is not None and inserts.num_rows:
            partial = self._ivm_insert(st, partial, table, inserts)
            if partial is None:
                return None
        use_jax = entry.backend == "jax"
        mat = P.MaterializedNode(table=partial, label="ivm-partials",
                                 out_names=list(st.p_names),
                                 out_dtypes=list(st.p_dtypes))
        final_b = streaming._final_builder(st.agg, st.recipes, st.p_names,
                                           st.p_dtypes)
        with TRACER.span("cache.ivm_finalize", rows=partial.num_rows):
            result = _execute_plan(
                session, streaming.rebuild_above(st.path, final_b(mat)),
                use_jax)
        new_ivm = _IvmState(st.agg, st.path, st.partial_specs, st.recipes,
                            st.p_names, st.p_dtypes, partial,
                            st.partial_plan, st.scan_by_table)
        gens = {t: gen(t) for t in entry.gens}
        snaps = {}
        for t in entry.tables:
            sv = session.table_snapshot_version(t)
            if sv is not None:
                snaps[t] = sv
        return _Entry(entry.key, entry.template_key, entry.family,
                      entry.pvalues, entry.backend, result,
                      entry.out_names, entry.out_dtypes, entry.tables,
                      gens, snaps, time.time(), entry.plan, new_ivm)

    def _delta_table(self, scan, arrow_rows):
        """Arrow delta rows -> engine Table in the scan's projection; the
        engine dtypes must match the scan's declared dtypes exactly (a
        drifted staging schema invalidates instead of merging garbage)."""
        from . import arrow_bridge

        t = arrow_bridge.from_arrow(arrow_rows.select(list(scan.columns)),
                                    self.session._dec_as_int())
        got = [c.dtype for c in t.columns]
        if got != list(scan.out_dtypes):
            raise ValueError(f"delta dtypes {got} != scan "
                             f"{list(scan.out_dtypes)}")
        return t

    def _ivm_insert(self, st: _IvmState, partial, table, inserts):
        """Merge inserted-row partials: the aggregate distributes over a
        probe-side row union, so partial(old ∪ delta) = combine(
        partial(old) ∪ partial(delta)) — and every merged column is an
        order-insensitive dtype, so the combine is bit-stable."""
        scan = st.scan_by_table.get(table)
        if scan is None:
            return None
        mat = P.MaterializedNode(table=self._delta_table(scan, inserts),
                                 label="ivm-delta",
                                 out_names=list(scan.columns),
                                 out_dtypes=list(scan.out_dtypes))
        dplan = streaming.substitute_nodes(st.partial_plan,
                                           {id(scan): mat})
        with TRACER.span("cache.ivm_insert", rows=inserts.num_rows):
            delta_partial = _execute_plan(self.session, dplan,
                                          use_jax=False)
            if delta_partial.num_rows == 0:
                return partial
            merged = self._concat_partials(st, [partial, delta_partial])
            combine = streaming._combine_builder(
                st.agg, st.recipes, st.p_names, st.p_dtypes)
            mat2 = P.MaterializedNode(table=merged, label="ivm-merge",
                                      out_names=list(st.p_names),
                                      out_dtypes=list(st.p_dtypes))
            return Executor(_no_load).execute(combine(mat2))

    def _ivm_delete(self, st: _IvmState, partial, table, deletes):
        """Recompute only delta-touched groups: the deleted rows' group
        keys name the groups whose partials are stale; every other
        group's rows are untouched, so its partial row is kept verbatim."""
        scan = st.scan_by_table.get(table)
        if scan is None:
            return None
        mat = P.MaterializedNode(table=self._delta_table(scan, deletes),
                                 label="ivm-delta",
                                 out_names=list(scan.columns),
                                 out_dtypes=list(scan.out_dtypes))
        dplan = streaming.substitute_nodes(st.partial_plan,
                                           {id(scan): mat})
        with TRACER.span("cache.ivm_delete", rows=deletes.num_rows):
            touched = _execute_plan(self.session, dplan, use_jax=False)
            if touched.num_rows == 0:
                return partial       # deletes never reached the aggregate
            ngroups = len(st.agg.group_exprs)
            child = st.agg.child

            def key_pred(exprs):
                """Membership predicate over the touched group-key value
                sets (per-column: a cartesian superset — over-inclusive
                recomputation is correct, just wider)."""
                pred = None
                for i in range(ngroups):
                    vals, has_null = _col_values(touched.columns[i])
                    e = exprs[i]
                    c = None
                    if vals:
                        c = P.BCall("bool", "in_list", [e], extra=vals)
                    if has_null:
                        isn = P.BCall("bool", "isnull", [e])
                        c = isn if c is None else P.BCall("bool", "or",
                                                          [c, isn])
                    if c is None:
                        continue
                    pred = c if pred is None else P.BCall("bool", "and",
                                                          [pred, c])
                return pred

            child_pred = key_pred(st.agg.group_exprs)
            if child_pred is None:
                return None
            recompute = P.AggregateNode(
                child=P.FilterNode(child, child_pred,
                                   out_names=list(child.out_names),
                                   out_dtypes=list(child.out_dtypes)),
                group_exprs=list(st.agg.group_exprs),
                aggs=list(st.partial_specs),
                out_names=list(st.p_names), out_dtypes=list(st.p_dtypes))
            recomputed = _execute_plan(self.session, recompute,
                                       use_jax=False)
            # keep every partial row whose group the delta did NOT touch.
            # Three-valued logic: an untouched NULL-keyed group evaluates
            # `key IN (...)` to NULL, and NOT(NULL) would silently drop
            # it — coalesce the membership to FALSE first so "not
            # touched" keeps NULL verdicts
            part_pred = key_pred([P.BCol(st.p_dtypes[i], i, st.p_names[i])
                                  for i in range(ngroups)])
            keep = P.FilterNode(
                P.MaterializedNode(table=partial, label="ivm-partials",
                                   out_names=list(st.p_names),
                                   out_dtypes=list(st.p_dtypes)),
                P.BCall("bool", "not",
                        [P.BCall("bool", "coalesce",
                                 [part_pred, P.BLit("bool", False)])]),
                out_names=list(st.p_names), out_dtypes=list(st.p_dtypes))
            kept = Executor(_no_load).execute(keep)
            return self._concat_partials(st, [kept, recomputed])

    def _concat_partials(self, st: _IvmState, parts: list):
        from . import arrow_bridge

        arrow = pa.concat_tables(
            [arrow_bridge.to_arrow(p) for p in parts if p.num_rows]
            or [arrow_bridge.to_arrow(parts[0])],
            promote_options="permissive")
        return arrow_bridge.from_arrow(arrow, self.session._dec_as_int())

    # -- maintenance / introspection -----------------------------------------
    def invalidate_table(self, table: str) -> int:
        """Drop every entry over `table` (manual escape hatch)."""
        with self._lock:
            keys = [k for k, e in self._entries.items()
                    if table in e.tables]
            for k in keys:
                self._drop_locked(k, "manual")
        return len(keys)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # -- convenience ---------------------------------------------------------
    def run(self, sql: str, label: Optional[str] = None,
            backend: Optional[str] = None):
        """Lookup-or-execute one text through this cache (the service
        wires the same three steps across its stages; direct engine
        callers and tests use this)."""
        hit = self.lookup_text(sql)
        if hit is not None:
            return hit.table
        plan, fp, pvalues, use_jax = plan_for_cache(self.session, sql,
                                                    backend)
        hit = self.lookup_plan(sql, plan, fp, pvalues, use_jax)
        if hit is not None:
            return hit.table
        gens = self.snapshot_gens(plan)
        table, _stats = self.session.service_run(sql, backend=backend,
                                                 label=label, plan=plan)
        self.store(sql, plan, fp, pvalues, table, use_jax=use_jax,
                   gens=gens)
        return table
