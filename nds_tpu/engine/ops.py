"""Relational operators over columnar Tables.

Execution model: the host does *shape discovery* — group factorization, join
match counting — while bulk compute (segment reductions, gathers, sorts) is
vectorized array math. This is the TPU-first split: every kernel here is
expressible as fixed-shape XLA ops once sizes are known, which is how the
jitted fast-path (nds_tpu.engine.kernels) compiles the same operators; the
numpy forms below are the reference semantics and CPU fallback.

Capability parity targets: the scan/filter/project/join/agg/sort pipeline the
reference runs through Spark SQL + RAPIDS (reference nds_power.py:124-134 is
`spark.sql(query).collect()`; the plugin's columnar ops are the analog here).
"""
from __future__ import annotations

import numpy as np

from .column import (Column, Table, concat_columns, dec_scale, is_dec,
                     merge_dictionaries)
from .plan import AggSpec, SortKey, WindowFunc

_I64_NULL = np.int64(np.iinfo(np.int64).min + 1)


# --------------------------------------------------------------------------
# key normalization & factorization
# --------------------------------------------------------------------------

def key_array(col: Column) -> np.ndarray:
    """int64 representation of a column for grouping/joining; nulls -> sentinel."""
    data = np.asarray(col.data)
    if col.dtype == "float":
        # total order via IEEE bit flip (handles -0.0 == 0.0 by normalizing)
        d = data.astype(np.float64)
        d = np.where(d == 0.0, 0.0, d)
        bits = d.view(np.int64)
        out = np.where(bits < 0, np.int64(np.iinfo(np.int64).min) - bits, bits)
    else:
        out = data.astype(np.int64)
    if col.valid is not None:
        out = np.where(col.valid, out, _I64_NULL)
    return out


def _row_view(arrays: list[np.ndarray]) -> np.ndarray:
    """Pack parallel int64 arrays into one void array for row-wise unique."""
    stacked = np.ascontiguousarray(np.stack(arrays, axis=1))
    return stacked.view([("", np.int64)] * len(arrays)).ravel()


def factorize(key_cols: list[Column], pre_keys: list[np.ndarray] | None = None
              ) -> tuple[np.ndarray, np.ndarray, int]:
    """Assign dense group ids for the given key columns.

    Returns (group_ids[n], first_row_index[ngroups], ngroups); group ids are
    ordered by sorted key value, making output deterministic.
    """
    arrays = pre_keys if pre_keys is not None else [key_array(c) for c in key_cols]
    if not arrays:
        raise ValueError("factorize with no keys")
    n = len(arrays[0])
    if n == 0:
        return np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64), 0
    if len(arrays) == 1:
        uniq, first, inverse = np.unique(arrays[0], return_index=True,
                                         return_inverse=True)
    else:
        rows = _row_view(arrays)
        uniq, first, inverse = np.unique(rows, return_index=True,
                                         return_inverse=True)
    return inverse.astype(np.int64), first.astype(np.int64), len(uniq)


def take_with_null(col: Column, indices: np.ndarray) -> Column:
    """Gather; negative indices produce NULL (outer-join fill).

    A zero-row source is legal (outer join against an empty build side:
    every index is -1) — clip would index into nothing, so gather from a
    one-null-row extension instead."""
    if len(col.data) == 0 and len(indices):
        col = Column(col.dtype, np.zeros(1, dtype=np.asarray(col.data).dtype),
                     np.zeros(1, dtype=bool), col.dictionary)
    safe = np.where(indices >= 0, indices, 0)
    out = col.take(safe)
    miss = indices < 0
    if miss.any():
        return out.with_valid(out.validity & ~miss)
    return out


# --------------------------------------------------------------------------
# filter / project / limit / distinct
# --------------------------------------------------------------------------

def filter_table(table: Table, mask_col: Column) -> Table:
    mask = np.asarray(mask_col.data, dtype=bool) & mask_col.validity
    idx = np.nonzero(mask)[0]
    return table.take(idx)


def distinct(table: Table) -> Table:
    if table.num_rows == 0 or not table.columns:
        return table
    _, first, _ = factorize(list(table.columns))
    return table.take(np.sort(first))


# --------------------------------------------------------------------------
# sort
# --------------------------------------------------------------------------

def sort_indices(key_cols: list[Column], keys: list[SortKey]) -> np.ndarray:
    """Spark ordering: asc => NULLS FIRST, desc => NULLS LAST (overridable)."""
    columns = []
    for col, k in zip(key_cols, keys):
        if col.dtype == "str":
            arr = _string_rank_keys(col)
        else:
            arr = key_array(col)
        nulls_first = k.nulls_first if k.nulls_first is not None else k.asc
        null_key = np.iinfo(np.int64).min if nulls_first else np.iinfo(np.int64).max
        if not k.asc:
            arr = -arr  # flip order; null placement applied after
        if col.valid is not None:
            arr = np.where(col.valid, arr, null_key)
        columns.append(arr)
    # lexsort: last key is primary
    return np.lexsort(columns[::-1]) if columns else np.arange(0)


def _string_rank_keys(col: Column) -> np.ndarray:
    d = col.dictionary
    if d is None or len(d) == 0:
        return np.zeros(len(col), dtype=np.int64)
    order = np.argsort(d.astype(str), kind="stable")
    ranks = np.empty(len(d), dtype=np.int64)
    ranks[order] = np.arange(len(d))
    codes = np.asarray(col.data)
    return ranks[np.where(codes >= 0, codes, 0)]


def sort_table(table: Table, key_cols: list[Column], keys: list[SortKey]) -> Table:
    if table.num_rows <= 1:
        return table
    return table.take(sort_indices(key_cols, keys))


# --------------------------------------------------------------------------
# aggregation
# --------------------------------------------------------------------------

def _segment_sum(values: np.ndarray, valid: np.ndarray, gid: np.ndarray,
                 ngroups: int) -> tuple[np.ndarray, np.ndarray]:
    w = np.where(valid, values, 0)
    if np.issubdtype(values.dtype, np.floating):
        sums = np.bincount(gid, weights=w, minlength=ngroups)
    else:
        sums = np.zeros(ngroups, dtype=np.int64)
        np.add.at(sums, gid, w.astype(np.int64))
    counts = np.bincount(gid[valid], minlength=ngroups)
    return sums, counts


def _segment_minmax(values: np.ndarray, valid: np.ndarray, gid: np.ndarray,
                    ngroups: int, is_min: bool) -> tuple[np.ndarray, np.ndarray]:
    if np.issubdtype(values.dtype, np.floating):
        init = np.inf if is_min else -np.inf
        out = np.full(ngroups, init, dtype=np.float64)
        fn = np.minimum if is_min else np.maximum
        fn.at(out, gid[valid], values[valid].astype(np.float64))
    else:
        init = np.iinfo(np.int64).max if is_min else np.iinfo(np.int64).min
        out = np.full(ngroups, init, dtype=np.int64)
        fn = np.minimum if is_min else np.maximum
        fn.at(out, gid[valid], values[valid].astype(np.int64))
    counts = np.bincount(gid[valid], minlength=ngroups)
    return out, counts


def _distinct_pairs(gid: np.ndarray, col: Column) -> tuple[np.ndarray, np.ndarray]:
    """(group_id, first_row_idx) of distinct valid (group, value) pairs."""
    valid = col.validity
    rows = np.nonzero(valid)[0]
    keys = key_array(col)[rows]
    pair_view = _row_view([gid[rows], keys])
    _, first = np.unique(pair_view, return_index=True)
    return gid[rows[first]], rows[first]


def compute_agg(spec: AggSpec, arg: Column | None, gid: np.ndarray,
                ngroups: int, total_rows: int) -> Column:
    if spec.func == "count_star":
        return Column.from_values("int", np.bincount(gid, minlength=ngroups))
    assert arg is not None
    values = np.asarray(arg.data)
    valid = arg.validity
    if spec.distinct:
        if spec.func == "count":
            dgid, _ = _distinct_pairs(gid, arg)
            return Column.from_values("int", np.bincount(dgid, minlength=ngroups))
        dgid, rows = _distinct_pairs(gid, arg)
        gid, values, valid = dgid, values[rows], np.ones(len(rows), dtype=bool)
    if spec.func == "count":
        return Column.from_values("int", np.bincount(gid[valid], minlength=ngroups))
    if spec.func in ("sum", "avg"):
        sums, counts = _segment_sum(values, valid, gid, ngroups)
        if spec.func == "sum":
            # decimal sums stay exact scaled int64 (the TPU decimal story);
            # float stays float, everything else sums as int
            dtype = arg.dtype if arg.dtype == "float" or is_dec(arg.dtype) \
                else "int"
            return Column.from_values(dtype, sums, counts > 0)
        with np.errstate(invalid="ignore"):
            avg = sums / np.maximum(counts, 1)
        if is_dec(arg.dtype):
            avg = avg / 10.0 ** dec_scale(arg.dtype)
        return Column.from_values("float", avg, counts > 0)
    if spec.func in ("min", "max"):
        out, counts = _segment_minmax(values, valid, gid, ngroups,
                                      spec.func == "min")
        if arg.dtype == "str":
            # min/max over dictionary ranks, then map back to codes
            raise NotImplementedError("min/max over strings handled in aggregate()")
        return Column.from_values(arg.dtype, out.astype(values.dtype), counts > 0)
    if spec.func == "stddev_samp":
        v = values.astype(np.float64)
        if is_dec(arg.dtype):
            v = v / 10.0 ** dec_scale(arg.dtype)
        sums, counts = _segment_sum(v, valid, gid, ngroups)
        sq, _ = _segment_sum(v * v, valid, gid, ngroups)
        cnt = counts.astype(np.float64)
        with np.errstate(invalid="ignore", divide="ignore"):
            var = (sq - sums * sums / np.maximum(cnt, 1)) / np.maximum(cnt - 1, 1)
        return Column.from_values("float", np.sqrt(np.maximum(var, 0)), counts > 1)
    raise NotImplementedError(f"aggregate {spec.func}")


def _agg_string_minmax(spec: AggSpec, arg: Column, gid: np.ndarray,
                       ngroups: int) -> Column:
    ranks = _string_rank_keys(arg)
    valid = arg.validity
    init = np.iinfo(np.int64).max if spec.func == "min" else np.iinfo(np.int64).min
    out = np.full(ngroups, init, dtype=np.int64)
    fn = np.minimum if spec.func == "min" else np.maximum
    fn.at(out, gid[valid], ranks[valid])
    counts = np.bincount(gid[valid], minlength=ngroups)
    # rank -> code lookup
    d = arg.dictionary if arg.dictionary is not None else np.empty(0, dtype=object)
    order = np.argsort(d.astype(str), kind="stable") if len(d) else np.empty(0, np.int64)
    safe = np.where((out >= 0) & (out < len(order)), out, 0)
    codes = order[safe].astype(np.int32) if len(order) else np.zeros(ngroups, np.int32)
    return Column.from_values("str", codes, counts > 0, d)


def aggregate(table: Table, group_cols: list[Column], aggs: list[AggSpec],
              agg_args: list[Column | None], rollup: bool = False,
              levels: list[int] | None = None
              ) -> tuple[list[Column], list[Column], Column | None]:
    """Grouped aggregation.

    Returns (group_out_cols, agg_out_cols, grouping_id_col or None).
    With rollup=True, emits one block per rollup level, null-filling rolled-up
    keys, with a Spark-compatible grouping-id bitmask column. `levels` (an
    explicit subset of rollup prefix lengths) supports per-level compile
    segmentation of big rollups.
    """
    if levels is None:
        levels = [len(group_cols)]
        if rollup:
            levels = list(range(len(group_cols), -1, -1))
    blocks: list[tuple[list[Column], list[Column], int]] = []
    for lvl in levels:
        keys = group_cols[:lvl]
        if keys:
            gid, first, ngroups = factorize(keys)
        else:
            # global aggregate: one group even over zero rows (SQL semantics)
            gid = np.zeros(table.num_rows, dtype=np.int64)
            first = np.zeros(1, dtype=np.int64)
            ngroups = 1
        g_out = []
        for i, c in enumerate(group_cols):
            if i < lvl:
                g_out.append(c.take(first) if table.num_rows else _empty_like(c))
            else:
                nn = ngroups
                g_out.append(Column.constant(c.dtype, None, nn, c.dictionary))
        a_out = []
        for spec, arg in zip(aggs, agg_args):
            if table.num_rows == 0 and keys:
                a_out.append(Column.constant(spec.dtype, None, 0))
                continue
            if spec.func in ("min", "max") and arg is not None and arg.dtype == "str":
                a_out.append(_agg_string_minmax(spec, arg, gid, ngroups))
            else:
                a_out.append(compute_agg(spec, arg, gid, ngroups, table.num_rows))
        # grouping id bitmask: bit i set => group expr i rolled up
        gid_mask = sum(1 << (len(group_cols) - 1 - i)
                       for i in range(lvl, len(group_cols)))
        blocks.append((g_out, a_out, gid_mask))
    if len(blocks) == 1:
        g_out, a_out, mask = blocks[0]
        gidc = Column.from_values(
            "int", np.full(len(g_out[0]) if g_out else len(a_out[0]), mask,
                           np.int64)) \
            if rollup else None
        return g_out, a_out, gidc
    g_cat = [concat_columns([b[0][i] for b in blocks])
             for i in range(len(group_cols))]
    a_cat = [concat_columns([b[1][i] for b in blocks]) for i in range(len(aggs))]
    gid_vals = np.concatenate([
        np.full(len(b[0][0]) if b[0] else len(b[1][0]), b[2], dtype=np.int64)
        for b in blocks])
    return g_cat, a_cat, Column.from_values("int", gid_vals)


def _empty_like(c: Column) -> Column:
    return c.take(np.empty(0, dtype=np.int64))


# --------------------------------------------------------------------------
# join
# --------------------------------------------------------------------------

def _joint_keys(left_keys: list[Column], right_keys: list[Column]
                ) -> tuple[np.ndarray, np.ndarray]:
    """Factorize left+right composite keys into one comparable int64 space."""
    nl = len(left_keys[0]) if left_keys else 0
    arrays = []
    for lc, rc in zip(left_keys, right_keys):
        if lc.dtype == "str" or rc.dtype == "str":
            _, (lcodes, rcodes) = merge_dictionaries([lc, rc])
            la = lcodes.astype(np.int64)
            ra = rcodes.astype(np.int64)
            if lc.valid is not None:
                la = np.where(lc.valid, la, _I64_NULL)
            if rc.valid is not None:
                ra = np.where(rc.valid, ra, _I64_NULL)
        else:
            la, ra = key_array(lc), key_array(rc)
        arrays.append(np.concatenate([la, ra]))
    if len(arrays) == 1:
        joint = arrays[0]
    else:
        gid, _, _ = factorize([], pre_keys=arrays)
        joint = gid
    return joint[:nl], joint[nl:]


def _null_key_mask(cols: list[Column]) -> np.ndarray:
    n = len(cols[0]) if cols else 0
    mask = np.zeros(n, dtype=bool)
    for c in cols:
        if c.valid is not None:
            mask |= ~c.valid
    return mask


def join_match(left_keys: list[Column], right_keys: list[Column]
               ) -> tuple[np.ndarray, np.ndarray]:
    """All matching (left_idx, right_idx) pairs for an equi-join (null-safe:
    null keys match nothing). Sort-probe: build on right, probe from left."""
    lk, rk = _joint_keys(left_keys, right_keys)
    lnull = _null_key_mask(left_keys)
    rnull = _null_key_mask(right_keys)
    rvalid_idx = np.nonzero(~rnull)[0]
    rk_valid = rk[rvalid_idx]
    order = np.argsort(rk_valid, kind="stable")
    rk_sorted = rk_valid[order]
    probe_rows = np.nonzero(~lnull)[0]
    pk = lk[probe_rows]
    lo = np.searchsorted(rk_sorted, pk, side="left")
    hi = np.searchsorted(rk_sorted, pk, side="right")
    counts = hi - lo
    total = int(counts.sum())
    if total == 0:
        return np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64)
    # expand [lo, hi) ranges without a python loop
    offsets = np.concatenate([[0], np.cumsum(counts)[:-1]])
    flat = np.arange(total) - np.repeat(offsets, counts) + np.repeat(lo, counts)
    right_idx = rvalid_idx[order[flat]]
    left_idx = np.repeat(probe_rows, counts)
    return left_idx, right_idx


def join(left: Table, right: Table, kind: str,
         left_keys: list[Column], right_keys: list[Column],
         residual_eval=None, null_aware: bool = False
         ) -> tuple[Table, np.ndarray, np.ndarray]:
    """Execute a join; returns (combined_table, left_idx, right_idx).

    residual_eval: callable(combined Table) -> Column(bool) applied to matched
    pairs before outer-fill, so non-equi conditions see the matched rows only.
    null_aware: NOT-IN semantics for anti joins — a NULL probe key or any NULL
    build key disqualifies (predicate is NULL, never TRUE).
    """
    # The null-aware branch below tests build-side NULLs BEFORE the residual
    # filter, which is wrong when a residual could exclude the NULL-key build
    # rows; the planner guarantees the combination never reaches us
    # (planner.py _decorrelate raises PlanError for it). A real raise, not an
    # assert: python -O must not silently return wrong NOT IN results if a
    # future planner change re-enables the combination.
    if null_aware and residual_eval is not None:
        raise NotImplementedError(
            "null-aware anti join with residual is unsupported")
    if kind == "cross" or not left_keys:
        # keyless joins (pure theta: residual-only condition) are a filtered
        # cross product
        nl, nr = left.num_rows, right.num_rows
        left_idx = np.repeat(np.arange(nl, dtype=np.int64), nr)
        right_idx = np.tile(np.arange(nr, dtype=np.int64), nl)
    else:
        left_idx, right_idx = join_match(left_keys, right_keys)
    if residual_eval is not None and len(left_idx):
        matched = _combine(left, right, left_idx, right_idx)
        mask_col = residual_eval(matched)
        keep = np.asarray(mask_col.data, dtype=bool) & mask_col.validity
        left_idx, right_idx = left_idx[keep], right_idx[keep]
    if kind in ("inner", "cross"):
        combined = _combine(left, right, left_idx, right_idx)
        return combined, left_idx, right_idx
    if kind == "semi":
        keep = np.unique(left_idx)
        return left.take(keep), keep, np.empty(0, np.int64)
    if kind == "anti":
        matched_mask = np.zeros(left.num_rows, dtype=bool)
        matched_mask[left_idx] = True
        if null_aware:
            if right.num_rows and _null_key_mask(right_keys).any():
                # NOT IN over a set containing NULL: nothing qualifies
                empty = np.empty(0, np.int64)
                return left.take(empty), empty, empty
            matched_mask |= _null_key_mask(left_keys)  # NULL probe: excluded
        keep = np.nonzero(~matched_mask)[0]
        return left.take(keep), keep, np.empty(0, np.int64)
    if kind in ("left", "full"):
        matched = np.zeros(left.num_rows, dtype=bool)
        matched[left_idx] = True
        extra_l = np.nonzero(~matched)[0]
        left_idx = np.concatenate([left_idx, extra_l])
        right_idx = np.concatenate([right_idx,
                                    np.full(len(extra_l), -1, dtype=np.int64)])
    if kind in ("right", "full"):
        matched_r = np.zeros(right.num_rows, dtype=bool)
        matched_r[right_idx[right_idx >= 0]] = True
        extra_r = np.nonzero(~matched_r)[0]
        left_idx = np.concatenate([left_idx,
                                   np.full(len(extra_r), -1, dtype=np.int64)])
        right_idx = np.concatenate([right_idx, extra_r])
    combined = _combine(left, right, left_idx, right_idx)
    return combined, left_idx, right_idx


def _combine(left: Table, right: Table, left_idx: np.ndarray,
             right_idx: np.ndarray) -> Table:
    cols = [take_with_null(c, left_idx) for c in left.columns]
    cols += [take_with_null(c, right_idx) for c in right.columns]
    return Table(left.names + right.names, cols)


# --------------------------------------------------------------------------
# set operations
# --------------------------------------------------------------------------

def _align_set_tables(a: Table, b: Table) -> tuple[Table, Table]:
    """Position-wise align string dictionaries between two set-op inputs."""
    a_cols, b_cols = list(a.columns), list(b.columns)
    for i, (ca, cb) in enumerate(zip(a_cols, b_cols)):
        if ca.dtype == "str" or cb.dtype == "str":
            merged, (codes_a, codes_b) = merge_dictionaries([ca, cb])
            a_cols[i] = Column.from_values("str", codes_a, ca.valid, merged)
            b_cols[i] = Column.from_values("str", codes_b, cb.valid, merged)
    return Table(a.names, a_cols), Table(b.names, b_cols)


def set_op(op: str, all_: bool, left: Table, right: Table) -> Table:
    left, right = _align_set_tables(left, right)
    if op == "union":
        out = Table(left.names,
                    [concat_columns([lc, rc])
                     for lc, rc in zip(left.columns, right.columns)])
        return out if all_ else distinct(out)
    # intersect / except use distinct row semantics (ALL variants unsupported)
    nl = left.num_rows
    both = Table(left.names,
                 [concat_columns([lc, rc])
                  for lc, rc in zip(left.columns, right.columns)])
    gid, first, ngroups = factorize(list(both.columns))
    in_left = np.zeros(ngroups, dtype=bool)
    in_right = np.zeros(ngroups, dtype=bool)
    in_left[gid[:nl]] = True
    in_right[gid[nl:]] = True
    if op == "intersect":
        keep_groups = in_left & in_right
    elif op == "except":
        keep_groups = in_left & ~in_right
    else:
        raise ValueError(op)
    # first occurrence restricted to left rows
    first_left = np.full(ngroups, -1, dtype=np.int64)
    # reverse iterate trick: assign in reverse so first occurrence wins
    left_rows = np.arange(nl - 1, -1, -1, dtype=np.int64)
    first_left[gid[left_rows]] = left_rows
    rows = first_left[keep_groups & (first_left >= 0)]
    return both.take(np.sort(rows))


# --------------------------------------------------------------------------
# window functions
# --------------------------------------------------------------------------

def window(table: Table, funcs: list[WindowFunc],
           part_cols: list[list[Column]], order_cols: list[list[Column]],
           arg_cols: list[Column | None]) -> list[Column]:
    out: list[Column] = []
    n = table.num_rows
    for wf, pcols, ocols, arg in zip(funcs, part_cols, order_cols, arg_cols):
        if n == 0:
            out.append(Column.constant(wf.dtype, None, 0))
            continue
        if pcols:
            gid, _, ngroups = factorize(pcols)
        else:
            gid, ngroups = np.zeros(n, dtype=np.int64), 1
        if not ocols:
            col = _window_whole_partition(wf, arg, gid, ngroups, n)
        else:
            col = _window_ordered(wf, arg, gid, ngroups, ocols, wf.order_by, n)
        out.append(col)
    return out


def _window_whole_partition(wf: WindowFunc, arg: Column | None,
                            gid: np.ndarray, ngroups: int, n: int) -> Column:
    if wf.func in ("rank", "dense_rank", "row_number"):
        raise ValueError(f"{wf.func} requires ORDER BY")
    c = compute_agg(AggSpec(wf.func, None), arg, gid, ngroups, n)
    return c.take(gid)


def _window_ordered(wf: WindowFunc, arg: Column | None, gid: np.ndarray,
                    ngroups: int, ocols: list[Column], okeys: list[SortKey],
                    n: int) -> Column:
    # global order: partition id, then order keys
    part_key = SortKey(expr=None, asc=True)  # type: ignore[arg-type]
    gid_col = Column.from_values("int", gid)
    order = sort_indices([gid_col] + ocols, [part_key] + okeys)
    inv = np.empty(n, dtype=np.int64)
    inv[order] = np.arange(n)
    sgid = gid[order]
    new_part = np.concatenate([[True], sgid[1:] != sgid[:-1]])
    # tie detection among order keys
    tie_key_arrays = [key_array(c) if c.dtype != "str" else _string_rank_keys(c)
                      for c in ocols]
    skeys = [a[order] for a in tie_key_arrays]
    same_as_prev = np.ones(n, dtype=bool)
    for a in skeys:
        same_as_prev[1:] &= a[1:] == a[:-1]
    same_as_prev[0] = False
    same_as_prev &= ~new_part
    pos_in_part = np.arange(n) - np.maximum.accumulate(
        np.where(new_part, np.arange(n), 0))
    if wf.func == "row_number":
        vals = pos_in_part + 1
        return Column.from_values("int", vals[inv])
    if wf.func == "rank":
        # rank = 1 + offset of the tie-run's first row within its partition
        run_start = np.maximum.accumulate(np.where(~same_as_prev, np.arange(n), 0))
        part_start = np.maximum.accumulate(np.where(new_part, np.arange(n), 0))
        vals = run_start - part_start + 1
        return Column.from_values("int", vals[inv])
    if wf.func == "dense_rank":
        bump = (~same_as_prev) & ~new_part
        dens = np.cumsum(bump) - np.maximum.accumulate(
            np.where(new_part, np.cumsum(bump), 0)) + 1
        return Column.from_values("int", dens[inv])
    # cumulative aggregates with RANGE semantics (ties share the value)
    assert arg is not None or wf.func == "count_star"
    if wf.func == "count_star":
        vals = (pos_in_part + 1).astype(np.float64)
        run = _spread_ties_last(vals, same_as_prev)
        return Column.from_values("int", run[inv].astype(np.int64))
    data = np.asarray(arg.data, dtype=np.float64)[order]
    valid = arg.validity[order]
    w = np.where(valid, data, 0.0)
    csum = np.cumsum(w)
    # running sum within partition: subtract the cumsum just before the partition
    base = _segment_base(csum - w, new_part)
    run_sum = csum - base
    ccount = np.cumsum(valid.astype(np.int64)).astype(np.float64)
    run_count = ccount - _segment_base(ccount - valid, new_part)
    if wf.func in ("sum", "avg"):
        run_sum = _spread_ties_last(run_sum, same_as_prev)
        run_count = _spread_ties_last(run_count, same_as_prev)
        if wf.func == "sum":
            # dec window sums cumulate scaled ints in f64 (exact < 2^53)
            dtype = "float" if arg.dtype == "float" else \
                arg.dtype if is_dec(arg.dtype) else "int"
            vals = run_sum if dtype == "float" else run_sum.astype(np.int64)
            return Column.from_values(dtype, vals[inv], (run_count > 0)[inv])
        with np.errstate(invalid="ignore"):
            res = run_sum / np.maximum(run_count, 1)
        if is_dec(arg.dtype):
            res = res / 10.0 ** dec_scale(arg.dtype)
        return Column.from_values("float", res[inv], (run_count > 0)[inv])
    if wf.func in ("min", "max"):
        fn = np.minimum if wf.func == "min" else np.maximum
        init = np.inf if wf.func == "min" else -np.inf
        vals = np.where(valid, data, init)
        out = _segmented_accumulate(vals, new_part, fn)
        out = _spread_ties_last(out, same_as_prev)
        dtype = arg.dtype if arg.dtype in ("int", "float", "date") \
            or is_dec(arg.dtype) else "float"
        cast = out if dtype == "float" else out.astype(np.int64)
        return Column.from_values(dtype, cast[inv], (run_count > 0)[inv])
    raise NotImplementedError(f"window {wf.func}")


def _segment_base(cum_before: np.ndarray, new_part: np.ndarray) -> np.ndarray:
    """Per-row value of `cum_before` at the row's partition start."""
    n = len(cum_before)
    starts = np.nonzero(new_part)[0]
    seg_id = np.cumsum(new_part) - 1
    return cum_before[starts][seg_id]


def _segmented_accumulate(vals, new_part, fn):
    """Cumulative fn within each partition (loop over partitions, not rows)."""
    out = vals.copy()
    n = len(vals)
    starts = np.nonzero(new_part)[0]
    ends = np.append(starts[1:], n)
    for s, e in zip(starts, ends):
        out[s:e] = fn.accumulate(vals[s:e])
    return out


def _spread_ties_last(vals: np.ndarray, same_as_prev: np.ndarray) -> np.ndarray:
    """RANGE frames: every row of a tie run takes the run's last value."""
    n = len(vals)
    if n == 0:
        return vals
    run_id = np.cumsum(~same_as_prev) - 1
    nruns = run_id[-1] + 1
    last = np.zeros(nruns, dtype=vals.dtype)
    last[run_id] = vals  # later rows overwrite -> last of run
    return last[run_id]
