"""Arrow <-> engine Table conversion.

Arrow is the host-side interchange format (the `collect()` analog in the
reference pulls rows to the Spark driver, nds_power.py:131; here results
materialize as Arrow tables for reporting/validation/output writing).
"""
from __future__ import annotations

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from .column import Column, Table


def engine_dtype(t: pa.DataType) -> str:
    if pa.types.is_integer(t):
        return "int"
    if pa.types.is_decimal(t) or pa.types.is_floating(t):
        return "float"
    if pa.types.is_date(t):
        return "date"
    if pa.types.is_boolean(t):
        return "bool"
    if pa.types.is_string(t) or pa.types.is_large_string(t) or \
            pa.types.is_dictionary(t):
        return "str"
    raise TypeError(f"unsupported arrow type {t}")


def engine_schema(schema: pa.Schema) -> tuple[list[str], list[str]]:
    names = list(schema.names)
    dtypes = [engine_dtype(f.type) for f in schema]
    return names, dtypes


def _chunked_to_array(arr: pa.ChunkedArray | pa.Array) -> pa.Array:
    if isinstance(arr, pa.ChunkedArray):
        return arr.combine_chunks()
    return arr


def from_arrow_column(arr) -> Column:
    arr = _chunked_to_array(arr)
    t = arr.type
    dtype = engine_dtype(t)
    null_count = arr.null_count
    if dtype == "str":
        if not pa.types.is_dictionary(t):
            arr = arr.dictionary_encode()
        codes = arr.indices.to_numpy(zero_copy_only=False)
        codes = np.where(np.isnan(codes.astype(np.float64)), -1, codes) \
            if codes.dtype.kind == "f" else codes
        codes = codes.astype(np.int32)
        valid = None
        if null_count:
            valid = ~np.asarray(arr.is_null())
            codes = np.where(valid, codes, -1)
        dictionary = np.asarray(arr.dictionary.to_pylist(), dtype=object)
        return Column("str", codes, valid, dictionary)
    if dtype == "date":
        valid = ~np.asarray(arr.is_null()) if null_count else None
        ints = arr.cast(pa.int32())
        if null_count:  # fill BEFORE to_numpy: nulls otherwise round-trip
            import pyarrow.compute as pc  # through float NaN -> int garbage
            ints = pc.fill_null(ints, 0)
        days = ints.to_numpy(zero_copy_only=False)
        return Column("date", np.asarray(days, dtype=np.int32), valid)
    if dtype == "float":
        if pa.types.is_decimal(t):
            arr = arr.cast(pa.float64())
        vals = arr.to_numpy(zero_copy_only=False).astype(np.float64)
        valid = ~np.asarray(arr.is_null()) if null_count else None
        if valid is not None:
            vals = np.where(valid, vals, 0.0)
        return Column("float", vals, valid)
    if dtype == "bool":
        valid = ~np.asarray(arr.is_null()) if null_count else None
        vals = arr.to_numpy(zero_copy_only=False)
        vals = np.asarray(vals, dtype=bool)
        return Column("bool", vals, valid)
    # int
    valid = ~np.asarray(arr.is_null()) if null_count else None
    vals = arr.to_numpy(zero_copy_only=False)
    if valid is not None:
        vals = np.where(valid, vals, 0)
    return Column("int", np.asarray(vals, dtype=np.int64), valid)


def from_arrow(table: pa.Table) -> Table:
    return Table(list(table.schema.names),
                 [from_arrow_column(table.column(i))
                  for i in range(table.num_columns)])


def to_arrow_column(col: Column) -> pa.Array:
    v = col.validity
    mask = None if col.valid is None else ~col.valid
    if col.dtype == "str":
        codes = np.asarray(col.data)
        d = col.dictionary if col.dictionary is not None \
            else np.empty(0, dtype=object)
        null_mask = (codes < 0) | ~v
        safe = np.where(codes >= 0, codes, 0)
        values = pa.array(list(d), type=pa.string())
        indices = pa.array(safe.astype(np.int32),
                           mask=null_mask if null_mask.any() else None)
        return pa.DictionaryArray.from_arrays(indices, values).cast(pa.string())
    if col.dtype == "date":
        return pa.array(np.asarray(col.data, dtype=np.int32), type=pa.date32(),
                        mask=mask)
    if col.dtype == "float":
        return pa.array(np.asarray(col.data, dtype=np.float64), mask=mask)
    if col.dtype == "bool":
        return pa.array(np.asarray(col.data, dtype=bool), mask=mask)
    return pa.array(np.asarray(col.data, dtype=np.int64), mask=mask)


def to_arrow(table: Table) -> pa.Table:
    arrays = [to_arrow_column(c) for c in table.columns]
    return pa.table(dict(zip(_dedupe(table.names), arrays))) \
        if len(set(table.names)) != len(table.names) else \
        pa.Table.from_arrays(arrays, names=table.names)


def _dedupe(names: list[str]) -> list[str]:
    seen: dict[str, int] = {}
    out = []
    for n in names:
        if n in seen:
            seen[n] += 1
            out.append(f"{n}_{seen[n]}")
        else:
            seen[n] = 0
            out.append(n)
    return out
