"""Arrow <-> engine Table conversion.

Arrow is the host-side interchange format (the `collect()` analog in the
reference pulls rows to the Spark driver, nds_power.py:131; here results
materialize as Arrow tables for reporting/validation/output writing).
"""
from __future__ import annotations

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from .column import Column, Table, dec_dtype, dec_scale, is_dec


def engine_dtype(t: pa.DataType, dec_as_int: bool = False) -> str:
    if pa.types.is_integer(t):
        return "int"
    if pa.types.is_decimal(t):
        # dec_as_int: exact scaled-int64 decimals (decimal_physical="i64");
        # default keeps the f64 mapping (reference decimal toggle,
        # nds/nds_schema.py:43-47)
        return dec_dtype(t.scale) if dec_as_int else "float"
    if pa.types.is_floating(t):
        return "float"
    if pa.types.is_date(t):
        return "date"
    if pa.types.is_boolean(t):
        return "bool"
    if pa.types.is_string(t) or pa.types.is_large_string(t) or \
            pa.types.is_dictionary(t):
        return "str"
    raise TypeError(f"unsupported arrow type {t}")


def engine_schema(schema: pa.Schema,
                  dec_as_int: bool = False) -> tuple[list[str], list[str]]:
    names = list(schema.names)
    dtypes = [engine_dtype(f.type, dec_as_int) for f in schema]
    return names, dtypes


def _chunked_to_array(arr: pa.ChunkedArray | pa.Array) -> pa.Array:
    if isinstance(arr, pa.ChunkedArray):
        return arr.combine_chunks()
    return arr


def _decimal_to_scaled_i64(arr: pa.Array) -> np.ndarray:
    """Exact decimal128(p,s) -> value*10^s as int64 (no float round-trip)."""
    t = arr.type
    # fast path only when every scaled value provably fits int64
    # (10^18 < 2^63): the safe=False cast below would wrap silently
    if t.precision <= 18:
        mul = pa.scalar(10 ** t.scale, pa.decimal128(t.scale + 1, 0))
        ints = pc.cast(pc.multiply(arr, mul), pa.int64(), safe=False)
        ints = pc.fill_null(ints, 0)
        return ints.to_numpy(zero_copy_only=False)
    out = np.zeros(len(arr), dtype=np.int64)     # precision edge: exact loop
    for i, d in enumerate(arr.to_pylist()):
        if d is not None:
            out[i] = int(d.scaleb(t.scale))
    return out


def from_arrow_column(arr, dec_as_int: bool = False) -> Column:
    arr = _chunked_to_array(arr)
    t = arr.type
    dtype = engine_dtype(t, dec_as_int)
    null_count = arr.null_count
    if is_dec(dtype):
        valid = ~np.asarray(arr.is_null()) if null_count else None
        return Column(dtype, _decimal_to_scaled_i64(arr), valid)
    if dtype == "str":
        # encode at most ONCE (already-dictionary arrays pass through), and
        # null indices fill host-side — the old float-NaN round-trip turned
        # every null-bearing code array into a f64 copy
        if not pa.types.is_dictionary(t):
            arr = arr.dictionary_encode()
        codes = pc.fill_null(arr.indices, -1) \
            .to_numpy(zero_copy_only=False).astype(np.int32)
        valid = None
        if null_count:
            valid = ~np.asarray(arr.is_null())
            codes = np.where(valid, codes, -1)
        # to_numpy over the value buffer, NOT to_pylist: a wide dictionary
        # (100k+ distinct values) otherwise pays a Python-object loop per
        # morsel/load
        dictionary = arr.dictionary.to_numpy(zero_copy_only=False) \
            .astype(object)
        return Column("str", codes, valid, dictionary)
    if dtype == "date":
        valid = ~np.asarray(arr.is_null()) if null_count else None
        ints = arr.cast(pa.int32())
        if null_count:  # fill BEFORE to_numpy: nulls otherwise round-trip
            ints = pc.fill_null(ints, 0)  # through float NaN -> int garbage
        days = ints.to_numpy(zero_copy_only=False)
        return Column("date", np.asarray(days, dtype=np.int32), valid)
    if dtype == "float":
        if pa.types.is_decimal(t):
            arr = arr.cast(pa.float64())
        vals = arr.to_numpy(zero_copy_only=False).astype(np.float64)
        valid = ~np.asarray(arr.is_null()) if null_count else None
        if valid is not None:
            vals = np.where(valid, vals, 0.0)
        return Column("float", vals, valid)
    if dtype == "bool":
        valid = ~np.asarray(arr.is_null()) if null_count else None
        vals = arr.to_numpy(zero_copy_only=False)
        vals = np.asarray(vals, dtype=bool)
        return Column("bool", vals, valid)
    # int
    valid = ~np.asarray(arr.is_null()) if null_count else None
    vals = arr.to_numpy(zero_copy_only=False)
    if valid is not None:
        vals = np.where(valid, vals, 0)
    return Column("int", np.asarray(vals, dtype=np.int64), valid)


def from_arrow(table: pa.Table, dec_as_int: bool = False) -> Table:
    from ..resilience import FAULTS
    FAULTS.fire("arrow.read")
    return Table(list(table.schema.names),
                 [from_arrow_column(table.column(i), dec_as_int)
                  for i in range(table.num_columns)])


def to_arrow_column(col: Column) -> pa.Array:
    v = col.validity
    mask = None if col.valid is None else ~col.valid
    if is_dec(col.dtype):
        # output materialization is post-aggregation (small); exact loop.
        # precision 20 covers any scaled int64 (<= 19 digits) and keeps the
        # fast path available if the column round-trips back through
        # _decimal_to_scaled_i64 (streamed-partials merge)
        return pa.array(col.decode().tolist(),
                        type=pa.decimal128(min(38, 20 + dec_scale(col.dtype)),
                                           dec_scale(col.dtype)))
    if col.dtype == "str":
        codes = np.asarray(col.data)
        d = col.dictionary if col.dictionary is not None \
            else np.empty(0, dtype=object)
        null_mask = (codes < 0) | ~v
        safe = np.where(codes >= 0, codes, 0)
        values = pa.array(list(d), type=pa.string())
        indices = pa.array(safe.astype(np.int32),
                           mask=null_mask if null_mask.any() else None)
        return pa.DictionaryArray.from_arrays(indices, values).cast(pa.string())
    if col.dtype == "date":
        return pa.array(np.asarray(col.data, dtype=np.int32), type=pa.date32(),
                        mask=mask)
    if col.dtype == "float":
        return pa.array(np.asarray(col.data, dtype=np.float64), mask=mask)
    if col.dtype == "bool":
        return pa.array(np.asarray(col.data, dtype=bool), mask=mask)
    return pa.array(np.asarray(col.data, dtype=np.int64), mask=mask)


def to_arrow(table: Table) -> pa.Table:
    arrays = [to_arrow_column(c) for c in table.columns]
    return pa.table(dict(zip(_dedupe(table.names), arrays))) \
        if len(set(table.names)) != len(table.names) else \
        pa.Table.from_arrays(arrays, names=table.names)


# -- column value-range stats (narrow-lane planning) --------------------------
# (lo, hi) per column in ENGINE units: raw ints for "int", epoch days for
# "date", SCALED ints for decimals under decimal_physical="i64". Streaming
# chooses per-column upload lanes from these ONCE per scan group, so morsel
# widths are static per schedule (device.plan_lanes).

def _stat_pair(t: pa.DataType, mn, mx, dec_as_int: bool):
    """Convert an arrow min/max pair to engine units; None = no stats for
    this column (it then rides the widest legal lane)."""
    if mn is None or mx is None:
        return None
    if pa.types.is_integer(t):
        return int(mn), int(mx)
    if pa.types.is_date(t):
        import datetime
        epoch = datetime.date(1970, 1, 1)
        return (mn - epoch).days, (mx - epoch).days
    if pa.types.is_decimal(t) and dec_as_int:
        return int(mn.scaleb(t.scale)), int(mx.scaleb(t.scale))
    return None     # float/bool/str: lane is dtype-determined


def table_column_stats(table: pa.Table, dec_as_int: bool = False) -> dict:
    """{column: (lo, hi)} for the lane-relevant columns of an in-memory
    arrow table (one vectorized min_max pass per column)."""
    out: dict = {}
    for name in table.column_names:
        col = table.column(name)
        t = col.type
        if not (pa.types.is_integer(t) or pa.types.is_date(t)
                or (pa.types.is_decimal(t) and dec_as_int)):
            continue
        mm = pc.min_max(col)
        pair = _stat_pair(t, mm["min"].as_py(), mm["max"].as_py(),
                          dec_as_int)
        if pair is not None:
            out[name] = pair
    return out


def parquet_column_stats(paths, dec_as_int: bool = False) -> dict:
    """{column: (lo, hi)} aggregated over parquet files from row-group
    METADATA only (no data read). A column missing statistics in any row
    group of any file is omitted (unknown range -> widest lane)."""
    import pyarrow.parquet as pq

    agg: dict = {}
    bad: set = set()
    schema = None
    for path in paths:
        meta = pq.read_metadata(path)
        if schema is None:
            schema = pq.read_schema(path)
        names = meta.schema.names
        for rg in range(meta.num_row_groups):
            group = meta.row_group(rg)
            if group.num_rows == 0:
                continue
            for ci in range(group.num_columns):
                name = names[ci]
                if name in bad or name not in schema.names:
                    continue
                t = schema.field(name).type
                if not (pa.types.is_integer(t) or pa.types.is_date(t)
                        or (pa.types.is_decimal(t) and dec_as_int)):
                    bad.add(name)
                    continue
                st = group.column(ci).statistics
                pair = None if st is None or not st.has_min_max else \
                    _stat_pair(t, st.min, st.max, dec_as_int)
                if pair is None:
                    bad.add(name)
                    agg.pop(name, None)
                    continue
                old = agg.get(name)
                agg[name] = pair if old is None else \
                    (min(old[0], pair[0]), max(old[1], pair[1]))
    return agg


# -- column encoding stats (encoded-execution planning) -----------------------
# Cardinality (the sorted distinct-value set, capped) and total run count
# per column, in ENGINE units. device.plan_encodings chooses per-column
# dictionary/RLE wire encodings from these ONCE per scan group, exactly
# like plan_lanes does from the (lo, hi) range stats above. The run count
# is a BOUND for any contiguous morsel window of the same data in the same
# order, so the static per-morsel run capacity derived from it can never
# overflow while the stats hold.

#: distinct values above this are not collected (no dictionary encoding)
ENC_MAX_CARD = 1 << 16


def column_enc_stat(col, dec_as_int: bool = False,
                    max_card: int = ENC_MAX_CARD):
    """{"distinct": sorted int array or None, "runs": int, "rows": n} for
    one arrow column (int/date/decimal only; None otherwise). `distinct`
    covers VALID values (null slots ride canonical code 0); `runs` counts
    over null-filled-with-zero values — the exact canonicalization
    pack-time RLE runs over."""
    arr = _chunked_to_array(col)
    t = arr.type
    if not (pa.types.is_integer(t) or pa.types.is_date(t)
            or (pa.types.is_decimal(t) and dec_as_int)):
        return None
    c = from_arrow_column(arr, dec_as_int)   # engine units, nulls -> 0
    return column_enc_stat_values(np.asarray(c.data), c.validity, max_card)


def column_enc_stat_values(data: np.ndarray, valid: np.ndarray,
                           max_card: int = ENC_MAX_CARD) -> dict:
    """Encoding stats over an already-engine-unit value array."""
    filled = np.where(valid, data, np.zeros((), dtype=data.dtype))
    n = int(len(filled))
    runs = int(np.count_nonzero(filled[1:] != filled[:-1]) + 1) if n else 0
    distinct = None
    u = np.unique(data[valid])
    if len(u) <= max_card:
        distinct = u.astype(np.int64)
    return {"distinct": distinct, "runs": runs, "rows": n}


def merge_enc_stats(parts: list) -> "dict | None":
    """Combine per-source encoding stats (per warehouse file, per chunk):
    distinct = the union (None when any part lacks it), runs = the sum —
    a window spanning source boundaries holds at most the per-source run
    totals combined, under ANY source order."""
    if not parts or any(p is None for p in parts):
        return None
    distinct = None
    if all(p.get("distinct") is not None for p in parts):
        distinct = np.unique(np.concatenate(
            [np.asarray(p["distinct"], dtype=np.int64) for p in parts]))
        if len(distinct) > ENC_MAX_CARD:
            distinct = None
    return {"distinct": distinct,
            "runs": sum(int(p["runs"]) for p in parts),
            "rows": sum(int(p.get("rows", 0)) for p in parts)}


# -- parquet dictionary pass-through (staging-thread hot loop) ----------------

def parquet_dictionary_columns(paths) -> list[str]:
    """String columns dictionary-encoded in EVERY column chunk of every
    row group of the given parquet files (metadata only, no data read).
    Reading these with ParquetReadOptions(dictionary_columns=...) hands
    the staging thread codes + dictionary directly — from_arrow_column
    then skips its dictionary_encode() re-encoding pass, the hot loop of
    double-buffered morsel staging."""
    import pyarrow.parquet as pq

    cand = None
    for path in paths:
        try:
            meta = pq.read_metadata(path)
            schema = pq.read_schema(path)
        except Exception:
            return []
        strs = {f.name for f in schema
                if pa.types.is_string(f.type)
                or pa.types.is_large_string(f.type)}
        cand = strs if cand is None else (cand & strs)
        names = meta.schema.names
        for rg in range(meta.num_row_groups):
            group = meta.row_group(rg)
            for ci in range(group.num_columns):
                name = names[ci]
                if name not in cand:
                    continue
                encs = set(group.column(ci).encodings)
                if not (encs & {"PLAIN_DICTIONARY", "RLE_DICTIONARY"}):
                    cand.discard(name)
    return sorted(cand or ())


def parquet_dataset_format(paths):
    """A pyarrow dataset format that reads the (fully) dictionary-encoded
    string columns of `paths` as dictionary arrays — zero-copy code
    pass-through for the staging thread. None when nothing qualifies or
    the pyarrow version lacks the option."""
    import pyarrow.dataset as pa_dataset

    cols = parquet_dictionary_columns(paths)
    if not cols:
        return None
    try:
        return pa_dataset.ParquetFileFormat(
            read_options=pa_dataset.ParquetReadOptions(
                dictionary_columns=cols))
    except Exception:
        return None


def _dedupe(names: list[str]) -> list[str]:
    seen: dict[str, int] = {}
    out = []
    for n in names:
        if n in seen:
            seen[n] += 1
            out.append(f"{n}_{seen[n]}")
        else:
            seen[n] = 0
            out.append(n)
    return out
